"""Sharding rules: PartitionSpecs for model params, KV cache, and activations.

Megatron-style tensor parallelism expressed declaratively — XLA/GSPMD
derives the collectives (one all-reduce after attention out-proj, one after
MLP down-proj per layer; all-gather for the tp-sharded logits):

  wq/wk/wv  [L, D, H*hd]   split output heads on tp
  wo        [L, H*hd, D]   split contraction dim on tp  -> psum(x)
  w_gate/up [L, D, F]      split F on tp
  w_down    [L, F, D]      split F on tp                -> psum(x)
  embed     [V, D]         split vocab on tp (gather is cheap)
  lm_head   [D, V]         split vocab on tp            -> logits sharded, top-k local
  cache     [L, S, C, KV, hd] split slots on dp, kv heads on tp

This wholesale replaces the reference's tensor-split mechanisms
(per-GPU fractions backend.proto:136,176 and remote rpc-server dispatch
grpc-server.cpp:2264-2267).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def llama_param_specs(tie_word_embeddings: bool = False) -> dict:
    specs = {
        "embed": P("tp", None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, "tp"),
            "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None),
        },
        "final_norm": P(None),
    }
    if not tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def mamba_param_specs(tie_word_embeddings: bool = True) -> dict:
    """Megatron-style tp for the mamba mixer (VERDICT r4 #7): d_inner is
    the parallel axis — in_proj_x/z column-parallel, out_proj
    row-parallel (psum), conv/x_proj/dt/A/D sharded on their Di axis so
    the whole recurrence stays device-local per Di shard."""
    specs = {
        "embed": P("tp", None),
        "layers": {
            "norm": P(None, None),
            "in_proj_x": P(None, None, "tp"),
            "in_proj_z": P(None, None, "tp"),
            "conv_w": P(None, "tp", None),
            "conv_b": P(None, "tp"),
            "x_proj": P(None, "tp", None),
            "dt_proj_w": P(None, None, "tp"),
            "dt_proj_b": P(None, "tp"),
            "A_log": P(None, "tp", None),
            "D": P(None, "tp"),
            "out_proj": P(None, "tp", None),
        },
        "final_norm": P(None),
    }
    if not tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def mamba_state_spec() -> P:
    # conv [L, S, Di, K-1] / ssm [L, S, Di, N]: slots on dp, Di on tp —
    # matches the param sharding so each device's recurrence is local
    return P(None, "dp", "tp", None)


def cache_spec() -> P:
    # [L, S, C, KV, hd]: slots on dp, kv heads on tp
    return P(None, "dp", None, "tp", None)


def paged_cache_spec() -> P:
    # paged pool [L, n_pages, page_size, KV, hd]: kv heads on tp — the
    # page axis is REPLICATED (any slot's rows may land in any page, so
    # there is no slot/dp analogue); HBM still shrinks tp-fold per chip
    # through the head split, and the pool is sized to actual usage
    # rather than worst-case-per-slot (ops/kvcache.py paged layout)
    return P(None, None, None, "tp", None)


def page_table_spec() -> P:
    # [S, max_pages] int32: replicated — every shard resolves the same
    # logical-row -> physical-page mapping, and the table is tiny
    return P(None, None)


def batch_spec() -> P:
    return P("dp")


def ragged_pack_spec() -> P:
    # [N] packed-prefill token axis (tokens/positions/seg_of): REPLICATED
    # — segments are ragged, so no token range maps to a fixed slot/dp
    # shard; parallelism comes from the head/F splits of the params the
    # pack flows through (tp), exactly like the decode token vector
    return P(None)


def ragged_seg_spec() -> P:
    # [B] per-segment metadata (slots/start/offsets/lengths): replicated
    # — every shard resolves the same segment -> slot mapping, and the
    # tables are tiny (like page_table_spec)
    return P(None)


def overlap_halves(fn, x, axis: int = 1):
    """TokenWeave-style compute/communication overlap: apply ``fn`` to
    the two halves of ``x`` along ``axis`` independently and concatenate.

    A row-wise fn whose chain ends in a contraction-sharded matmul (wo,
    w_down — the psum producers above) becomes two INDEPENDENT
    matmul + all-reduce chains; XLA's latency-hiding scheduler overlaps
    half A's all-reduce with half B's matmul, recovering most of the
    collective time that a single full-batch chain serializes
    (TokenWeave, PAPERS.md). Bit-exact by construction: slicing the
    token axis changes neither any row's operands nor its reduction
    order, so greedy outputs are byte-identical with the overlap on or
    off. Token axes shorter than 2 rows fall through to one call."""
    import jax.numpy as jnp

    n = x.shape[axis]
    if n < 2:
        return fn(x)
    h = n // 2
    a = jax.lax.slice_in_dim(x, 0, h, axis=axis)
    b = jax.lax.slice_in_dim(x, h, n, axis=axis)
    return jnp.concatenate([fn(a), fn(b)], axis=axis)


def fit_spec(mesh: Mesh, shape, spec: P) -> P:
    """Drop (replicate) any spec axis whose dimension the mesh degree
    does not divide — e.g. a 258-row test vocab on tp=8. Every case the
    fallback fires would otherwise be a device_put error, so this only
    ever turns a crash into replication, never changes a working
    placement."""
    fitted = []
    for i, ax in enumerate(spec):
        if ax is not None and i < len(shape):
            names = ax if isinstance(ax, tuple) else (ax,)
            deg = 1
            for n in names:
                deg *= mesh.shape[n]
            if shape[i] % deg:
                ax = None
        fitted.append(ax)
    return P(*fitted)


def to_named(mesh: Mesh, tree):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(mesh: Mesh, params: dict, tie_word_embeddings: bool = False,
                 specs: dict = None) -> dict:
    """Device_put a param pytree onto the mesh (llama specs by default;
    pass specs=mamba_param_specs(...) for the mamba family).

    Quantized leaves ({"q": int8/int4 weight, "s": scales}) shard q with
    the weight's spec and s per ops.quant.scale_spec (flat int8 scales
    follow the output-channel partitioning; grouped int4 scales
    additionally follow the contraction axis on their group axis — the
    group count must divide that axis's mesh degree; load-time
    quantization picks such a group automatically, pick_int4_group)."""
    from localai_tpu.ops.quant import is_grouped, scale_spec

    specs = specs or llama_param_specs(tie_word_embeddings)

    def put(x, spec):
        if isinstance(x, dict) and "q" in x:
            if is_grouped(x) and spec[-2] is not None \
                    and x["s"].shape[-3] % mesh.shape[spec[-2]]:
                raise ValueError(
                    f"int4 group count {x['s'].shape[-3]} does not divide "
                    f"the {spec[-2]!r}-axis mesh degree "
                    f"{mesh.shape[spec[-2]]}; re-quantize with "
                    f"quantize_weight_int4(shard_divisor=...) or a "
                    f"compatible group size")
            q = jax.device_put(x["q"], NamedSharding(mesh, spec))
            s = jax.device_put(x["s"],
                               NamedSharding(mesh, scale_spec(x, spec)))
            return {"q": q, "s": s}
        return jax.device_put(x, NamedSharding(mesh, spec))

    def walk(node, spec):
        if isinstance(spec, dict):
            return {k: walk(node[k], spec[k]) for k in spec}
        return put(node, spec)

    return walk(params, specs)
