"""Structured serving errors: one failure vocabulary for every layer.

Raised by the loader (circuit breaker), produced from gRPC status codes
at the capabilities boundary (a backend abort becomes a typed error,
never a raw RpcError traceback in a client response), and rendered by
the HTTP layer as OpenAI-style envelopes with the right status code and
a ``Retry-After`` header (api/app.py error_response).

The engine communicates the error KIND over the wire as a gRPC status
code (backend/runner.py maps StreamEvent.error_kind) plus the crude
retry-after hint as trailing metadata — the hand-rolled stubs cannot
grow proto fields.
"""

from __future__ import annotations

from typing import Optional

# trailing-metadata key carrying the engine's retry-after hint (seconds)
META_RETRY_AFTER = "localai-retry-after"


class ServingError(RuntimeError):
    """Base: a request-level failure with an HTTP mapping."""

    status = 500
    etype = "server_error"
    retryable = False

    def __init__(self, message: str, retry_after_s: float = 0.0,
                 detail: Optional[dict] = None):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s or 0.0)
        self.detail = detail or {}

    def body_extra(self) -> dict:
        """Extra keys merged into the HTTP error object (breaker state,
        retryability) so clients can react without parsing messages."""
        out: dict = {}
        if self.retryable:
            out["retryable"] = True
        if self.retry_after_s:
            out["retry_after"] = round(self.retry_after_s, 1)
        out.update(self.detail)
        return out


class OverloadedError(ServingError):
    """Admission control shed the request (bounded queue / queue-wait)."""

    status = 429
    etype = "overloaded"
    retryable = True


class BackendUnavailableError(ServingError):
    """The backend died, is respawning, or aborted the stream."""

    status = 503
    etype = "backend_unavailable"
    retryable = True


class DeadlineExceededError(ServingError):
    """request_timeout_ms (or the RPC deadline) expired."""

    status = 504
    etype = "deadline_exceeded"
    retryable = False


class CircuitOpenError(BackendUnavailableError):
    """Fast-fail: consecutive spawn/LoadModel failures opened the
    breaker. ``detail["breaker"]`` carries the breaker state and ends up
    verbatim in the 503 body."""

    etype = "circuit_open"


def wrap_backend_error(e: BaseException, model: str = "") -> BaseException:
    """gRPC RpcError -> typed ServingError, RETURNED (for
    ``raise wrap_backend_error(e, name) from e``). Anything already
    structured — or not a gRPC error — passes through unchanged."""
    import grpc

    if isinstance(e, ServingError) or not isinstance(e, grpc.RpcError):
        return e
    code = e.code() if callable(getattr(e, "code", None)) else None
    details = e.details() if callable(getattr(e, "details", None)) else str(e)
    msg = f"model {model}: {details}" if model else str(details)
    ra = 0.0
    try:
        for k, v in (e.trailing_metadata() or ()):
            if k == META_RETRY_AFTER:
                ra = float(v)
    except Exception:
        pass
    if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
        return OverloadedError(msg, retry_after_s=ra or 1.0)
    if code == grpc.StatusCode.UNAVAILABLE:
        return BackendUnavailableError(msg, retry_after_s=ra or 2.0)
    if code == grpc.StatusCode.DEADLINE_EXCEEDED:
        return DeadlineExceededError(msg)
    if code == grpc.StatusCode.ABORTED:
        # engine stall abort: this request died but the backend survives
        return BackendUnavailableError(msg, retry_after_s=ra or 1.0)
    return ServingError(msg)
