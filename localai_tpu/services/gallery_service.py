"""Gallery job queue: serialized async install/delete worker.

Parity with the reference's gallery service (reference: core/services/
gallery.go:18-31 op struct + :65-100 serialized channel worker; status
polled at /models/jobs/:uuid).
"""

from __future__ import annotations

import logging
import math
import queue
import threading
import time
import uuid
from typing import Optional

log = logging.getLogger("localai_tpu.services.gallery")


class ModelRequestLog:
    """Recency/frequency log over model requests — the prediction feed
    for the ISSUE-19 weight prefetcher (PRESERVE-style).

    Every model-addressed request notes its model name here; the score
    of a model is a sum of exponentially-decayed request marks
    (``exp(-(age)/tau)``), so one burst ages out and a steadily-used
    model keeps a high score. ``predict_next(exclude=...)`` answers
    "while THIS model serves, which other model is most likely to be
    asked for next" — that one's weights are worth warming. The clock is
    injectable so decay arithmetic is unit-testable."""

    def __init__(self, tau_s: float = 600.0, maxlen: int = 512,
                 clock=time.monotonic):
        self.tau_s = float(tau_s)
        self.clock = clock
        self._marks: dict = {}     # name -> deque-ish list of times
        self._maxlen = int(maxlen)
        self._order: list = []     # (t, name) FIFO for global trim
        self._lock = threading.Lock()

    def note(self, name: str):
        if not name:
            return
        now = self.clock()
        with self._lock:
            self._marks.setdefault(name, []).append(now)
            self._order.append((now, name))
            while len(self._order) > self._maxlen:
                t, old = self._order.pop(0)
                marks = self._marks.get(old)
                if marks:
                    try:
                        marks.remove(t)
                    except ValueError:
                        pass
                    if not marks:
                        del self._marks[old]

    def scores(self) -> dict:
        now = self.clock()
        with self._lock:
            return {
                name: sum(math.exp(-max(0.0, now - t) / self.tau_s)
                          for t in marks)
                for name, marks in self._marks.items() if marks
            }

    def predict_next(self, exclude=()) -> str:
        """Highest-scoring model not in ``exclude`` ('' when the log
        knows nothing useful — prefetching on no evidence only burns
        host RAM)."""
        best, best_s = "", 0.0
        for name, s in self.scores().items():
            if name in exclude:
                continue
            if s > best_s:
                best, best_s = name, s
        return best

    def snapshot(self) -> dict:
        sc = self.scores()
        return {"models": {k: round(v, 4) for k, v in sc.items()},
                "tau_s": self.tau_s}


class GalleryService:
    def __init__(self, app_config, caps):
        self.app = app_config
        self.caps = caps
        # the prediction feed (ISSUE 19): Capabilities notes every
        # model-addressed request into its ModelRequestLog; exposed here
        # so gallery-layer consumers can read the same feed
        self.requests = getattr(caps, "model_requests", None)
        self._jobs: dict[str, dict] = {}
        self._queue: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, name="gallery", daemon=True)
        self._thread.start()

    def shutdown(self):
        self._stop = True
        self._queue.put(None)
        if self._thread:
            self._thread.join(timeout=5)

    # ---- API surface ----

    def submit_apply(self, spec: dict) -> str:
        job_id = str(uuid.uuid4())
        with self._lock:
            self._jobs[job_id] = {"processed": False, "progress": 0.0,
                                  "message": "queued", "error": None,
                                  "file_name": "", "gallery_model_name": spec.get("id", "")}
        self._queue.put((job_id, "apply", spec))
        return job_id

    def submit_delete(self, name: str) -> str:
        job_id = str(uuid.uuid4())
        with self._lock:
            self._jobs[job_id] = {"processed": False, "progress": 0.0,
                                  "message": "queued", "error": None,
                                  "gallery_model_name": name}
        self._queue.put((job_id, "delete", {"name": name}))
        return job_id

    def job_status(self, job_id: str) -> Optional[dict]:
        with self._lock:
            st = self._jobs.get(job_id)
            return dict(st) if st else None

    def all_jobs(self) -> dict:
        with self._lock:
            return {k: dict(v) for k, v in self._jobs.items()}

    def list_available(self) -> list:
        from localai_tpu.gallery.gallery import load_gallery_index

        index = load_gallery_index(self.app.galleries)
        return [
            {"name": e.get("name"), "gallery": e.get("_gallery"),
             "license": e.get("license", ""), "description": e.get("description", ""),
             "urls": e.get("urls", []), "tags": e.get("tags", []),
             "installed": e.get("name") in self.caps.configs}
            for e in index
        ]

    # ---- worker ----

    def _run(self):
        while not self._stop:
            item = self._queue.get()
            if item is None:
                continue
            job_id, op, spec = item
            try:
                self._update(job_id, message="processing")
                if op == "apply":
                    self._apply(job_id, spec)
                elif op == "delete":
                    self._delete(job_id, spec["name"])
                self._update(job_id, processed=True, progress=1.0, message="completed")
            except Exception as e:
                log.exception("gallery job %s failed", job_id)
                self._update(job_id, processed=True, error=str(e), message="error")

    def _update(self, job_id: str, **kw):
        with self._lock:
            if job_id in self._jobs:
                self._jobs[job_id].update(kw)

    def _apply(self, job_id: str, spec: dict):
        from localai_tpu.config.model_config import scan_models_dir
        from localai_tpu.gallery.gallery import find_model, install_model, load_gallery_index

        def progress(frac, msg):
            self._update(job_id, progress=float(frac), message=msg)

        name = spec.get("id") or spec.get("name") or ""
        overrides = spec.get("overrides") or {}
        if spec.get("url"):
            # direct config URL install
            import tempfile

            from localai_tpu.gallery import downloader as dl

            with tempfile.NamedTemporaryFile(suffix=".yaml", delete=False) as tmp:
                dl.download_file(spec["url"], tmp.name)
            import os

            import yaml

            with open(tmp.name) as f:
                config = yaml.safe_load(f) or {}
            os.unlink(tmp.name)
            entry = {"name": spec.get("name") or config.get("name", "model"),
                     "config_file": config, "files": spec.get("files", [])}
            install_model(entry, self.app.models_path, overrides, progress)
        else:
            index = load_gallery_index(self.app.galleries)
            entry = find_model(index, name)
            if entry is None:
                raise ValueError(f"model {name!r} not found in galleries")
            install_model(entry, self.app.models_path, overrides, progress,
                          name_override=spec.get("name", ""))
        self.caps.configs.update(scan_models_dir(self.app.models_path))

    def _delete(self, job_id: str, name: str):
        from localai_tpu.gallery.gallery import delete_model

        delete_model(name, self.app.models_path)
        self.caps.configs.pop(name, None)
        try:
            self.caps.loader.shutdown_model(name, force=True)
        except Exception:
            pass
