"""Cross-host KV wire protocol: a HostPageStore made network-addressable.

ISSUE 17's transport layer. DejaVu (arXiv:2403.01876) streams KV-cache
state between hosts so prefill/decode disaggregation, cross-host warm
restores, and crash recovery all ride one mechanism; this module is the
wire half of that design for the TPU serving stack. A ``KVWireServer``
fronts one host's (shared) ``HostPageStore`` over length-prefixed TCP
frames; peers fetch/push chain entries — target AND draft planes —
with exactly the integrity discipline the on-disk ``kv_host_store``
persistence enforces: a protocol version tag, the full page SCOPE
(model family + attention geometry + cache dtype + page size), and a
CRC per plane set that the RECEIVER recomputes before admitting a page
(bad bytes never enter a store; the requester re-prefills, which is
always correct).

Frame format (all integers big-endian)::

    +--------+-----+------------------+
    | len:u32| op:u8| payload[len]    |
    +--------+-----+------------------+

Control payloads (HELLO/HAS/DIGEST/STATS and every reply envelope) are
UTF-8 JSON; entry payloads (FETCH replies, PUSH requests) are the
store's own npz container format (``pack_entries``) extended with the
draft planes the on-disk format deliberately drops — on the wire a
draft plane is worth shipping (the peer's speculation warms instantly),
on disk it is not (staleness risk across restarts).

Sessions are stateful: a client MUST open with HELLO, which pins the
protocol version and the store scope for the connection — every later
frame on a mismatched session is refused. The server is a daemon
``ThreadingTCPServer``: one OS thread per peer connection, blocking
reads, no event loop — peers are few (a pod's worth of hosts), frames
are large, and the GIL releases during socket I/O and numpy copies.

Chaos hooks (services/faults.py): ``kv_stream_drop`` severs the
connection mid-FETCH instead of replying (the requester sees a dead
peer and degrades to local re-prefill); ``kv_stream_corrupt`` flips a
byte in the outgoing COPY of a fetched page so the receiver's CRC check
must reject it (the server's own store is never touched).

This module is the cluster's DATA plane. The CONTROL plane
(services/cluster_rpc.py, ISSUE 20) reuses the same framing helpers —
``send_frame``/``recv_frame`` and the HELLO-first session discipline —
on a DISJOINT op-number range (32+), so a client that dials the wrong
port gets a typed refusal instead of a silent mis-parse.
"""

from __future__ import annotations

import io
import json
import logging
import socket
import socketserver
import struct
import threading

import numpy as np

from localai_tpu.services.faults import FAULTS

log = logging.getLogger(__name__)

WIRE_VERSION = 1

# ops
OP_HELLO = 1
OP_OK = 2
OP_ERR = 3
OP_HAS = 4
OP_FETCH = 5
OP_PUSH = 6
OP_DIGEST = 7
OP_STATS = 8

_HDR = struct.Struct(">IB")
# one frame tops out at 1 GiB — far above any sane chain batch, low
# enough that a corrupted length prefix cannot OOM the receiver
MAX_FRAME = 1 << 30
# DIGEST caps the advertised key set: routing only needs the warm
# working set, not an unbounded dump of a 100 GB host tier
DIGEST_MAX_KEYS = 8192


class WireError(RuntimeError):
    """Protocol violation or peer-reported error."""


def send_frame(sock, op: int, payload: bytes = b"") -> None:
    sock.sendall(_HDR.pack(len(payload), op) + payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise WireError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def recv_frame(sock) -> tuple:
    """(op, payload) or raises WireError on a severed/garbled stream."""
    hdr = _recv_exact(sock, _HDR.size)
    n, op = _HDR.unpack(hdr)
    if n > MAX_FRAME:
        raise WireError(f"frame length {n} exceeds cap")
    return op, _recv_exact(sock, n) if n else b""


def _jdump(obj) -> bytes:
    return json.dumps(obj, separators=(",", ":")).encode()


def _jload(payload: bytes):
    return json.loads(payload.decode()) if payload else {}


# --------------- entry (de)serialization ---------------


def _plane_payload(prefix: str, rows, payload: dict):
    """Stage one K-or-V plane set into the npz payload dict; handles
    the {"q","s"} int8 page dicts exactly like HostPageStore.save."""
    from localai_tpu.engine.kv_offload import _to_savable

    if isinstance(rows[0], dict):
        payload[prefix + "q"] = np.stack([r["q"] for r in rows])
        payload[prefix + "s"] = np.stack([r["s"] for r in rows])
        return True
    arr, name = _to_savable(np.stack(rows))
    payload[prefix + "d"] = arr
    payload[prefix + "dtype"] = np.asarray(name)
    return False


def _plane_unpack(prefix: str, data, n: int, quant: bool) -> list:
    from localai_tpu.engine.kv_offload import _from_savable

    if quant:
        q, s = data[prefix + "q"], data[prefix + "s"]
        return [{"q": q[i], "s": s[i]} for i in range(n)]
    arr = _from_savable(data[prefix + "d"], str(data[prefix + "dtype"]))
    return [arr[i] for i in range(n)]


def pack_entries(scope: bytes, page_size: int, entries: list) -> bytes:
    """Serialize host-store entries (``_HostEntry`` or anything with the
    same attributes) for the wire. The carried CRCs are the SOURCE
    store's — the receiver recomputes over the received bytes and
    rejects on mismatch, so wire corruption can never be admitted."""
    payload = {
        "version": np.int32(WIRE_VERSION),
        "scope": np.frombuffer(scope, np.uint8),
        "page_size": np.int32(page_size),
        "keys": np.stack([np.frombuffer(e.key, np.uint8)
                          for e in entries]),
        "parents": np.stack([np.frombuffer(e.parent, np.uint8)
                             for e in entries]),
        "depths": np.asarray([e.depth for e in entries], np.int64),
        "crcs": np.asarray([e.crc for e in entries], np.uint32),
    }
    quant = _plane_payload("k", [e.k for e in entries], payload)
    _plane_payload("v", [e.v for e in entries], payload)
    payload["quant"] = np.int32(1 if quant else 0)
    # draft planes (ISSUE 13) ride the wire — unlike disk persistence —
    # as a masked sub-batch: only the entries that carry them
    didx = [i for i, e in enumerate(entries) if e.dk is not None]
    payload["didx"] = np.asarray(didx, np.int64)
    if didx:
        payload["dcrcs"] = np.asarray([entries[i].dcrc for i in didx],
                                      np.uint32)
        dq = _plane_payload("dk", [entries[i].dk for i in didx], payload)
        _plane_payload("dv", [entries[i].dv for i in didx], payload)
        payload["dquant"] = np.int32(1 if dq else 0)
    buf = io.BytesIO()
    np.savez(buf, **payload)
    return buf.getvalue()


def unpack_entries(data: bytes, scope: bytes, page_size: int) -> list:
    """Parse a pack_entries payload into per-entry dicts, enforcing the
    version/scope/page-size contract (same rules as HostPageStore.load:
    a mismatch means the bytes describe a DIFFERENT model or layout and
    must be refused, not coerced). CRC verification is left to the
    caller — the receiver recomputes over its OWN copy of the arrays so
    a flip anywhere on the path is caught. Raises WireError on any
    structural defect."""
    try:
        z = np.load(io.BytesIO(data), allow_pickle=False)
        if int(z["version"]) != WIRE_VERSION:
            raise WireError(f"wire version {int(z['version'])} != "
                            f"{WIRE_VERSION}")
        if (bytes(z["scope"].tobytes()) != scope
                or int(z["page_size"]) != page_size):
            raise WireError("scope/page-size mismatch (different model "
                            "or layout)")
        keys, parents, depths = z["keys"], z["parents"], z["depths"]
        crcs = z["crcs"]
        n = keys.shape[0]
        quant = bool(int(z["quant"]))
        ks = _plane_unpack("k", z, n, quant)
        vs = _plane_unpack("v", z, n, quant)
        didx = z["didx"].tolist()
        dks = dvs = dcrcs = None
        if didx:
            dquant = bool(int(z["dquant"]))
            dks = _plane_unpack("dk", z, len(didx), dquant)
            dvs = _plane_unpack("dv", z, len(didx), dquant)
            dcrcs = z["dcrcs"]
        out = []
        for i in range(n):
            ent = {"key": bytes(keys[i].tobytes()),
                   "parent": bytes(parents[i].tobytes()),
                   "depth": int(depths[i]), "crc": int(crcs[i]),
                   "k": ks[i], "v": vs[i],
                   "dk": None, "dv": None, "dcrc": 0}
            out.append(ent)
        for j, i in enumerate(didx):
            out[i]["dk"] = dks[j]
            out[i]["dv"] = dvs[j]
            out[i]["dcrc"] = int(dcrcs[j])
        return out
    except WireError:
        raise
    except Exception as e:
        raise WireError(f"malformed entry payload: "
                        f"{type(e).__name__}: {e}") from e


# --------------- server ---------------


class _PeerHandler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: KVWireServer = self.server.kv     # type: ignore[attr-defined]
        hello = False
        try:
            while True:
                op, payload = recv_frame(self.request)
                if op == OP_HELLO:
                    hello = srv._handle_hello(self.request, payload)
                    continue
                if not hello:
                    send_frame(self.request, OP_ERR,
                               _jdump({"error": "HELLO required first"}))
                    return
                if not srv._dispatch(self.request, op, payload):
                    return       # fault-severed connection
        except (WireError, OSError):
            pass                 # peer went away: the thread just ends


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class KVWireServer:
    """Serve one host's HostPageStore (and optionally its
    PoolPrefixIndex digest) to cluster peers. The server reads the
    store through its LOCAL accessors only — a served FETCH must never
    recurse into the store's own federated tier, or two cold hosts
    would chase each other's misses forever."""

    def __init__(self, store, index=None, host_id: int = 0,
                 bind: str = "127.0.0.1", port: int = 0):
        self.store = store
        self.index = index
        self.host_id = int(host_id)
        self._bind = (bind, int(port))
        self.address = ""
        self._srv = None
        self._thread = None
        self._lock = threading.Lock()
        # telemetry (monotonic totals; the serving half of the
        # localai_kv_stream_* family — the client half lives on
        # kv_stream.FederatedKV)
        self.serves = 0          # FETCH requests answered
        self.pages_out = 0       # entries shipped to peers
        self.bytes_out = 0       # payload bytes shipped
        self.pushes_in = 0       # PUSH requests accepted
        self.pages_in = 0        # entries accepted from peers

    # ---- lifecycle ----

    def start(self) -> str:
        self._srv = _Server(self._bind, _PeerHandler)
        self._srv.kv = self      # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="kv-wire", daemon=True)
        self._thread.start()
        host, port = self._srv.server_address[:2]
        self.address = f"{host}:{port}"
        log.info("kv wire server host=%d listening on %s",
                 self.host_id, self.address)
        return self.address

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None

    def stats(self) -> dict:
        """Local (in-process) view of the serving counters — the same
        numbers OP_STATS ships to peers."""
        with self._lock:
            return {"host": self.host_id, "serves": self.serves,
                    "pages_out": self.pages_out,
                    "bytes_out": self.bytes_out,
                    "pushes_in": self.pushes_in,
                    "pages_in": self.pages_in}

    # ---- op handlers (connection threads) ----

    def _handle_hello(self, sock, payload) -> bool:
        req = _jload(payload)
        store = self.store
        if store is None:
            send_frame(sock, OP_ERR, _jdump({"error": "no store"}))
            return False
        if (int(req.get("version", -1)) != WIRE_VERSION
                or req.get("scope") != store.scope.hex()
                or int(req.get("page_size", -1)) != store.page_size):
            send_frame(sock, OP_ERR, _jdump(
                {"error": "version/scope/page-size mismatch",
                 "version": WIRE_VERSION, "scope": store.scope.hex(),
                 "page_size": store.page_size}))
            return False
        send_frame(sock, OP_OK, _jdump(
            {"version": WIRE_VERSION, "host": self.host_id,
             "scope": store.scope.hex(), "page_size": store.page_size}))
        return True

    def _dispatch(self, sock, op: int, payload: bytes) -> bool:
        """Handle one post-HELLO frame; False = connection severed."""
        store = self.store
        if op == OP_HAS:
            keys = [bytes.fromhex(k) for k in _jload(payload)["keys"]]
            send_frame(sock, OP_OK, _jdump(
                {"has": [1 if store.contains(k) else 0 for k in keys]}))
            return True
        if op == OP_FETCH:
            return self._handle_fetch(sock, payload)
        if op == OP_PUSH:
            return self._handle_push(sock, payload)
        if op == OP_DIGEST:
            send_frame(sock, OP_OK, _jdump(self.digest()))
            return True
        if op == OP_STATS:
            send_frame(sock, OP_OK, _jdump(
                {"host": self.host_id, "stats": store.stats(),
                 "serves": self.serves, "pages_out": self.pages_out,
                 "bytes_out": self.bytes_out, "pushes_in": self.pushes_in,
                 "pages_in": self.pages_in}))
            return True
        send_frame(sock, OP_ERR, _jdump({"error": f"unknown op {op}"}))
        return True

    def _handle_fetch(self, sock, payload) -> bool:
        store = self.store
        keys = [bytes.fromhex(k) for k in _jload(payload)["keys"]]
        if FAULTS.active and FAULTS.take("kv_stream_drop") is not None:
            # chaos: sever the peer stream mid-chain — no reply, no
            # close handshake; the requester must degrade to local
            # re-prefill byte-identically
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return False
        ents = []
        for k in keys:
            # get_local: CRC-checked read, LRU touch, and — critically —
            # no federated recursion (see class docstring)
            e = store.get_local(k)
            if e is None:
                continue
            ents.append(e)
            if store.audit is not None:
                store.audit.ledger.record("stream_out", key=k)
        if not ents:
            send_frame(sock, OP_OK, b"")
            return True
        body = pack_entries(store.scope, store.page_size, ents)
        if FAULTS.active and FAULTS.take("kv_stream_corrupt") is not None:
            # chaos: flip one byte of the first entry's K plane in the
            # outgoing COPY (re-pack from corrupted clones) so the
            # receiver's CRC recompute MUST reject it; the local store
            # is untouched
            import copy

            bad = []
            for e in ents:
                c = copy.copy(e)
                bad.append(c)
            first = bad[0]
            k0 = first.k
            leaf = next(iter(k0.values())) if isinstance(k0, dict) else k0
            flat = np.array(leaf, copy=True).view(np.uint8).reshape(-1)
            flat[0] ^= 0xFF
            corrupted = flat.view(leaf.dtype).reshape(leaf.shape)
            if isinstance(k0, dict):
                nk = dict(k0)
                nk[next(iter(k0))] = corrupted
                first.k = nk
            else:
                first.k = corrupted
            body = pack_entries(store.scope, store.page_size, bad)
        with self._lock:
            self.serves += 1
            self.pages_out += len(ents)
            self.bytes_out += len(body)
        send_frame(sock, OP_OK, body)
        return True

    def _handle_push(self, sock, payload) -> bool:
        from localai_tpu.engine.kv_offload import _page_crc

        store = self.store
        try:
            ents = unpack_entries(payload, store.scope, store.page_size)
        except WireError as e:
            send_frame(sock, OP_ERR, _jdump({"error": str(e)}))
            return True
        accepted = rejected = 0
        for ent in ents:
            if _page_crc(ent["k"], ent["v"]) != ent["crc"]:
                rejected += 1
                continue
            dk, dv = ent["dk"], ent["dv"]
            if dk is not None and _page_crc(dk, dv) != ent["dcrc"]:
                dk = dv = None   # draft planes decay, target survives
            store.put(ent["key"], ent["parent"], ent["depth"],
                      ent["k"], ent["v"], dk=dk, dv=dv)
            if store.audit is not None:
                store.audit.ledger.record("stream_in", key=ent["key"])
            accepted += 1
        with self._lock:
            self.pushes_in += 1
            self.pages_in += accepted
        send_frame(sock, OP_OK, _jdump(
            {"accepted": accepted, "rejected": rejected}))
        return True

    # ---- digest (router affinity) ----

    def digest(self) -> dict:
        """The polled routing digest: which chain keys this host can
        serve warm — its replicas' device tiers (the pool index) plus
        the host tier itself — capped at DIGEST_MAX_KEYS. The router
        matches a request's chain keys root-down against this set."""
        keys = set()
        if self.index is not None:
            keys.update(self.index.keys())
        store = self.store
        if store is not None:
            with store._lock:
                keys.update(store._entries)
        out = [k.hex() for k in list(keys)[:DIGEST_MAX_KEYS]]
        return {"host": self.host_id, "keys": out,
                "truncated": len(keys) > len(out),
                "pages": store.pages if store is not None else 0}
