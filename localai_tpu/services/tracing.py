"""Request-lifecycle tracing: a low-overhead ring-buffer span tracer.

The engine records timestamped spans (queue_wait, admission, prefill
dispatches, decode bursts, detok, stream flush) keyed by the request's
correlation id into a fixed-size ring — bounded memory, no allocation
churn beyond one tuple per span, one lock. Aggregate totals per span
name survive ring wraparound, so the host-walltime vs device-time
decomposition (``summary()["decomp_ms"]``) reflects the whole engine
lifetime even when individual spans have been overwritten.

``chrome_trace()`` renders the ring as Chrome trace-event JSON
(https://ui.perfetto.dev loads it directly): one track per slot plus
one for the scheduler tick loop and one for engine-level dispatches.

The reference exposes per-slot timings as plain struct fields
(grpc-server.cpp:2465-2488 slot timing block); this module is that
layer rebuilt around the dispatch-first engine, where "where did the
wall-clock go" must distinguish host dispatch cost from device compute
observed at sync-worker completion.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

# Span names counted as HOST loop work in the decomposition: time the
# engine thread spends dispatching / detokenizing / flushing, measured
# as plain walltime deltas on the engine thread.
HOST_SPANS = frozenset({
    "admission",
    "prefill_chunk",
    "prefill_dispatch",
    "decode_dispatch",
    "emit",
    "stream_flush",
    "offload_dispatch",
    "restore_dispatch",
})

# Span names counted as DEVICE time: dispatch call → sync-worker
# ready-set (the only trustworthy device-completion observation point
# on this platform — block_until_ready/is_ready lie here, see
# engine._sync_worker).
DEVICE_SPANS = frozenset({
    "prefill_device",
    "decode_burst_device",
})

# Span names recorded by the EMITTER worker thread (ISSUE 9): detok,
# stop-scan and stream queue puts that used to run on the engine loop.
# They get their own decomposition bucket — this walltime overlaps both
# device compute and the host loop, so folding it into host_loop would
# double-count time the engine thread never spent.
EMITTER_SPANS = frozenset({
    "emit_bg",
    "stream_flush_bg",
})

# Sync-worker ready-set → engine loop picking the result up: the
# finish-detection latency called out in the r5 verdict.
FINISH_DETECT_SPAN = "finish_detect"


class RingTracer:
    """Fixed-size span ring with always-on per-name aggregates.

    ``record()`` is the only hot-path entry point; when ``enabled`` is
    False it returns immediately without taking the lock (trace=0 is a
    true no-op). Spans are (name, track, t0, t1, rid, args) tuples with
    t0/t1 from time.monotonic().
    """

    def __init__(self, size: int = 4096, enabled: bool = True):
        self.size = max(1, int(size))
        self.enabled = bool(enabled) and int(size) > 0
        self._buf: list = [None] * self.size
        self._n = 0  # total spans ever recorded (monotonic)
        self._agg: dict = {}  # name -> [total_s, count]
        self._lock = threading.Lock()
        # Trace epoch: chrome_trace timestamps are relative to this so
        # perfetto's timeline starts near zero.
        self.t0 = time.monotonic()
        self.t0_epoch = time.time()

    def record(self, name, track, t0, t1, rid="", args=None):
        if not self.enabled:
            return
        with self._lock:
            self._buf[self._n % self.size] = (name, track, t0, t1, rid, args)
            self._n += 1
            a = self._agg.get(name)
            if a is None:
                a = self._agg[name] = [0.0, 0]
            a[0] += t1 - t0
            a[1] += 1

    def spans(self) -> list:
        """Retained spans, oldest first, as dicts."""
        with self._lock:
            n = self._n
            if n <= self.size:
                raw = self._buf[:n]
            else:
                cut = n % self.size
                raw = self._buf[cut:] + self._buf[:cut]
        return [
            {"name": s[0], "track": s[1], "t0": s[2], "t1": s[3],
             "rid": s[4], "args": s[5]}
            for s in raw if s is not None
        ]

    def reset(self):
        with self._lock:
            self._buf = [None] * self.size
            self._n = 0
            self._agg = {}
            self.t0 = time.monotonic()
            self.t0_epoch = time.time()

    def summary(self) -> dict:
        """Aggregate totals + the host-vs-device decomposition."""
        if not self.enabled:
            return {"enabled": False}
        with self._lock:
            n = self._n
            agg = {k: (v[0], v[1]) for k, v in self._agg.items()}
        by_span = {
            name: {"total_ms": round(tot * 1e3, 3), "count": cnt,
                   "avg_ms": round(tot * 1e3 / cnt, 4) if cnt else 0.0}
            for name, (tot, cnt) in sorted(agg.items())
        }
        host = sum(t for name, (t, _) in agg.items() if name in HOST_SPANS)
        device = sum(t for name, (t, _) in agg.items() if name in DEVICE_SPANS)
        emitter = sum(t for name, (t, _) in agg.items()
                      if name in EMITTER_SPANS)
        fin = agg.get(FINISH_DETECT_SPAN, (0.0, 0))[0]
        return {
            "enabled": True,
            "ring_size": self.size,
            "spans_recorded": n,
            "spans_dropped": max(0, n - self.size),
            "by_span_ms": by_span,
            "decomp_ms": {
                "host_loop": round(host * 1e3, 3),
                "device": round(device * 1e3, 3),
                "emitter": round(emitter * 1e3, 3),
                "finish_detect": round(fin * 1e3, 3),
            },
        }


def _track_order_key(track: str):
    # scheduler first, engine dispatches second, slots in numeric order.
    if track == "sched":
        return (0, 0)
    if track == "engine":
        return (1, 0)
    if track.startswith("slot"):
        try:
            return (2, int(track[4:]))
        except ValueError:
            pass
    return (3, track)


def chrome_trace(tracer: RingTracer, pid: int = 1,
                 process_name: str = "localai-engine") -> dict:
    """Render the ring as a Chrome trace-event JSON object.

    One thread (track) per slot plus "sched" (the engine tick loop) and
    "engine" (dispatch/device spans). Load the serialized dict at
    https://ui.perfetto.dev or chrome://tracing.

    The top-level ``localai`` block carries this process's trace epoch
    (wall-clock t0 of the relative-µs timeline) and pid — the anchor the
    HTTP process uses to re-base backend timelines onto ONE merged
    cross-process trace (ISSUE 12), corrected by the LoadModel clock
    handshake offset.
    """
    spans = tracer.spans()
    tracks = sorted({s["track"] for s in spans}, key=_track_order_key)
    tid = {t: i for i, t in enumerate(tracks)}
    events: list = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    for t in tracks:
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid[t],
            "args": {"name": t},
        })
        events.append({
            "name": "thread_sort_index", "ph": "M", "pid": pid,
            "tid": tid[t], "args": {"sort_index": tid[t]},
        })
    base = tracer.t0
    for s in spans:
        args = dict(s["args"]) if s["args"] else {}
        if s["rid"]:
            args["request_id"] = s["rid"]
        events.append({
            "name": s["name"],
            "cat": "engine",
            "ph": "X",
            "pid": pid,
            "tid": tid[s["track"]],
            "ts": round((s["t0"] - base) * 1e6, 1),
            "dur": round(max(0.0, s["t1"] - s["t0"]) * 1e6, 1),
            "args": args,
        })
    return {"displayTimeUnit": "ms", "traceEvents": events,
            "localai": {"t0_epoch": tracer.t0_epoch,
                        "pid": os.getpid()}}


# --- frontend (HTTP/API process) tracer (ISSUE 12) -------------------------
# The core process gets its own RingTracer so the request timeline no
# longer fractures at the gRPC boundary: HTTP parse/route spans and the
# gRPC-hop span are recorded here under the same correlation id the
# backend keys its spans by, and /debug/trace merges both rings onto one
# clock-aligned timeline. LOCALAI_TRACE=0 disables it (record() is then
# the same first-line no-op the engine's trace=0 knob gives the backend).

_frontend_tracer = None
_frontend_lock = threading.Lock()


def frontend_tracer() -> RingTracer:
    """Per-process singleton tracer for the HTTP/API process."""
    global _frontend_tracer
    with _frontend_lock:
        if _frontend_tracer is None:
            enabled = os.environ.get("LOCALAI_TRACE", "1").strip().lower() \
                not in ("0", "false", "off", "no")
            size = int(os.environ.get("LOCALAI_TRACE_RING_SIZE", "2048")
                       or 2048)
            _frontend_tracer = RingTracer(size, enabled=enabled)
        return _frontend_tracer


def dump_ring(tracer: RingTracer, out_dir: str = "", tag: str = "stall") -> str:
    """Write the span ring to disk as perfetto-loadable JSON; return the path.

    The post-mortem half of the stall watchdog (ROADMAP PR-6 follow-up
    "stream the ring to disk for post-mortem of wedged runs"): when the
    engine aborts a wedged dispatch it calls this so the trace of the
    run-up to the stall survives the process.
    """
    out_dir = out_dir or tempfile.gettempdir()
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(
        out_dir,
        f"localai-{tag}-{os.getpid()}-{int(time.time() * 1e3)}.trace.json")
    with open(path, "w") as f:
        json.dump(chrome_trace(tracer), f)
    return path
