"""Structured event log (ISSUE 8): JSON-lines lifecycle events.

Complementary to the span tracer (services/tracing.py): spans answer
"where did this request's wall-time go", events answer "what state
transitions did the SYSTEM go through" — admissions, sheds, timeouts,
completions, backend respawns, circuit transitions, stall dumps,
compile-after-warmup storms, pool pressure. Every event is one JSON
object per line with a wall-clock timestamp, a monotonically increasing
per-process sequence number, and (where applicable) the request
correlation id (`rid`) that also keys the tracer spans — so an operator
can pivot from an event line to the matching span breakdown.

Sink knob (`event_log=path|stderr|off`, also `LOCALAI_EVENT_LOG` env for
the core API process, which has no `options:` wire of its own):

* ``off`` (default) — ring only, nothing written through
* ``stderr``        — write-through to stderr (survives crashes)
* any other value   — append to that file path (line-buffered)

Regardless of sink, the last `ring_size` events are retained in a
bounded in-memory ring surfaced at `/debug/events`. One EventLog per
PROCESS: the core API process and each backend subprocess hold their
own; backend rings ride the GetState RPC JSON and `/debug/events`
merges them (each event tagged with its origin process).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from collections import deque

log = logging.getLogger("localai_tpu.eventlog")

RING_SIZE_DEFAULT = 512


class EventLog:
    def __init__(self, sink: str = "", ring_size: int = RING_SIZE_DEFAULT):
        self._ring: deque = deque(maxlen=max(1, int(ring_size)))
        self._lock = threading.Lock()
        self._fh = None
        self._seq = 0
        self._max_bytes = 0
        self.rotations = 0
        self.sink = "off"
        self.configure(sink or os.environ.get("LOCALAI_EVENT_LOG", ""))

    def configure(self, sink: str, max_mb: int = 64):
        """(Re)arm the write-through sink: path | stderr | off/empty.

        ``max_mb`` bounds a FILE sink's size (ROADMAP PR-8 follow-up):
        once the file reaches the bound it rotates to ``<path>.1``, one
        generation kept — an always-on event log can never fill the
        disk. 0 disables rotation; stderr/ring sinks are unaffected."""
        sink = (sink or "").strip()
        with self._lock:
            if self._fh is not None and self._fh is not sys.stderr:
                try:
                    self._fh.close()
                except Exception:
                    pass
            self._fh = None
            self._max_bytes = max(0, int(max_mb)) * 1024 * 1024
            if not sink or sink == "off":
                self.sink = "off"
            elif sink == "stderr":
                self.sink = "stderr"
                self._fh = sys.stderr
            else:
                self.sink = sink
                try:
                    self._fh = open(sink, "a", buffering=1)
                except OSError as e:
                    log.warning("event_log sink %s unwritable (%s); "
                                "ring-only", sink, e)
                    self.sink = "off"

    def _maybe_rotate(self, fh):
        """Rotate the file sink once it crosses the size bound. Called
        outside the lock with the fh the writer just used; re-checks
        under the lock so concurrent writers rotate exactly once."""
        with self._lock:
            if fh is not self._fh or self._fh is sys.stderr:
                return   # someone else already rotated / reconfigured
            try:
                if self._fh.tell() < self._max_bytes:
                    return
                self._fh.close()
                os.replace(self.sink, self.sink + ".1")
                self._fh = open(self.sink, "a", buffering=1)
                self.rotations += 1
            except Exception as e:
                log.warning("event_log rotation of %s failed (%s); "
                            "ring-only", self.sink, e)
                self._fh = None

    def emit(self, event: str, rid: str = "", model: str = "", **fields):
        """Record one event. Never raises — telemetry must not take the
        serving path down with it."""
        rec = {"ts": round(time.time(), 6), "event": event}
        if rid:
            rec["rid"] = rid
        if model:
            rec["model"] = model
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            fh = self._fh
        if fh is not None:
            try:
                fh.write(json.dumps(rec, default=str) + "\n")
                if self._max_bytes and fh is not sys.stderr \
                        and fh.tell() >= self._max_bytes:
                    self._maybe_rotate(fh)
            except Exception:
                pass

    def events(self, last: int = 0) -> list:
        """Snapshot of the ring, oldest first; `last` > 0 trims to the
        most recent N."""
        with self._lock:
            evs = list(self._ring)
        if last > 0:
            evs = evs[-last:]
        return evs

    def clear(self):
        with self._lock:
            self._ring.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {"sink": self.sink, "seq": self._seq,
                    "ring": len(self._ring),
                    "ring_size": self._ring.maxlen,
                    "rotations": self.rotations}


# Per-process singleton. The engine's `event_log=` option and the core
# process's LOCALAI_EVENT_LOG env both land here via configure().
EVENTS = EventLog()
