"""Fault-injection registry: the chaos-testing backbone (ISSUE 7).

A process-wide table of ARMED faults that production code consults at
its injection points. With nothing armed, every hook is one boolean
attribute read (`FAULTS.active`) — the harness costs nothing in normal
serving.

Spec format (env ``LOCALAI_FAULTS`` or the ``faults=`` model option —
semicolon-separated, because the options wire splits on commas)::

    name[=value][*count] [; name2[=value2][*count2] ...]

``count`` is how many times the fault FIRES before disarming itself
(default 1 — one-shot faults keep chaos runs deterministic: the fault
hits exactly once and the survivors' behavior is comparable to a
fault-free run). ``*`` alone means unlimited.

Injection points (grep for ``FAULTS.take``):

==========================  =================================================
``kill_backend_after_tokens=N``  backend/service.py: ``os._exit`` the backend
                                 process after N streamed PredictStream tokens
``rpc_unavailable=Method``       backend/service.py: abort that RPC with
                                 UNAVAILABLE before the handler runs
``sync_delay_ms=N``              engine/engine.py sync worker: sleep N ms
                                 before syncing an item (stall injection)
``sync_fail``                    engine sync worker: fail an item's sync
``page_alloc_fail``              engine ``_ensure_pages``: raise PoolExhausted
``host_store_corrupt``           engine/kv_offload.py ``get``: flip a byte in
                                 the stored page (the checksum must catch it)
``emitter_wedge_ms=N``           engine/emitter.py worker loop: sleep N ms on
                                 one item (wedged-emitter watchdog coverage)
``kv_leak``                      engine/prefix_cache.py ``_remove_tree``:
                                 suppress one retention ``drop()`` at the
                                 eviction seam — a refcount leak the online
                                 KV auditor must detect (ISSUE 15)
``replicaN_die``                 engine loop tick top: raise, killing replica
                                 N's loop (pool crash recovery)
``clusterN_die``                 same hook, host-scoped name: kill every
                                 engine loop on cluster host N (router
                                 crash recovery, ISSUE 17)
``kv_stream_drop``               services/kv_wire.py FETCH handler: sever the
                                 peer stream mid-chain (no reply, socket
                                 shutdown) — the puller must degrade to a
                                 local re-prefill, byte-identical
``kv_stream_corrupt``            services/kv_wire.py FETCH handler: flip a
                                 byte in the shipped payload (the receiver's
                                 CRC recompute must reject the entry; the
                                 server's own store is untouched)
``weight_stream_slow_ms=N``      engine/weights.py ``stream_llama_params``
                                 pace hook: sleep N ms per streamed leaf — a
                                 slow checkpoint source must not stall
                                 serving siblings or flap the autoscaler
                                 (ISSUE 19; arm ``*`` for the whole load)
``cluster_rpc_delay_ms=N``       services/cluster_rpc.py dispatch: sleep N ms
                                 before answering each control frame — a SLOW
                                 peer. Heartbeats land, late: the failure
                                 detector must hold SUSPECT (routing
                                 de-preference), never walk to DEAD (arm
                                 ``*`` to keep the host slow; ISSUE 20)
``cluster_rpc_drop``             services/cluster_rpc.py dispatch: sever one
                                 control connection with no reply — the event
                                 stream must resume from the last ACKED
                                 sequence number after the client reconnects
                                 (no token delivered twice or dropped)
``clusterN_hang``                services/cluster_rpc.py heartbeat handler:
                                 host N swallows heartbeat frames while the
                                 process lives (arm ``*``) — it must be
                                 declared DEAD after ``cluster_dead_ms`` and
                                 its streams recovered byte-identically on
                                 siblings
==========================  =================================================
"""

from __future__ import annotations

import os
import threading
from typing import Optional

_UNLIMITED = -1


class FaultInjector:
    """Thread-safe armed-fault table. ``active`` is a plain attribute so
    hot paths skip the lock entirely when nothing is armed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._faults: dict[str, list] = {}   # name -> [value, remaining]
        self.fired: dict[str, int] = {}      # name -> times fired (telemetry)
        self.active = False

    # ---- arming ----

    def configure(self, spec: str) -> None:
        """Merge a ``name[=value][*count];...`` spec into the table."""
        for item in (spec or "").split(";"):
            item = item.strip()
            if not item:
                continue
            count = 1
            if "*" in item:
                item, _, c = item.rpartition("*")
                count = _UNLIMITED if c.strip() in ("", "inf") else int(c)
            name, _, value = item.partition("=")
            self.arm(name.strip(), value.strip() or "1", count)

    def arm(self, name: str, value: str = "1", count: int = 1) -> None:
        with self._lock:
            self._faults[name] = [value, count]
            self.active = True

    def disarm(self, name: str) -> None:
        with self._lock:
            self._faults.pop(name, None)
            self.active = bool(self._faults)

    def reset(self) -> None:
        with self._lock:
            self._faults.clear()
            self.fired.clear()
            self.active = False

    # ---- firing ----

    def value(self, name: str) -> Optional[str]:
        """Peek an armed fault's value WITHOUT consuming a firing."""
        with self._lock:
            f = self._faults.get(name)
            return f[0] if f else None

    def take(self, name: str, match: Optional[str] = None) -> Optional[str]:
        """Consume one firing of ``name``; returns its value or None.

        ``match`` gates value-addressed faults (``rpc_unavailable=Embedding``
        only fires for take("rpc_unavailable", match="Embedding"))."""
        with self._lock:
            f = self._faults.get(name)
            if f is None or (match is not None and f[0] != match):
                return None
            value, remaining = f
            if remaining != _UNLIMITED:
                if remaining <= 1:
                    del self._faults[name]
                    self.active = bool(self._faults)
                else:
                    f[1] = remaining - 1
            self.fired[name] = self.fired.get(name, 0) + 1
            return value

    def snapshot(self) -> dict:
        with self._lock:
            return {"armed": {k: {"value": v[0], "remaining": v[1]}
                              for k, v in self._faults.items()},
                    "fired": dict(self.fired)}


FAULTS = FaultInjector()
# env arming happens at import so spawned backends (BackendProcess copies
# os.environ) inherit the chaos configuration with zero plumbing
FAULTS.configure(os.environ.get("LOCALAI_FAULTS", ""))
