"""Metrics: api_call duration histogram, pull-updated engine gauges
(kv pool occupancy, prefix-cache counters), Prometheus text exposition.

Parity with the reference (reference: core/services/metrics.go:18-45 — an
OTel meter exporting one `api_call` histogram tagged method/path, served at
GET /metrics). Hand-rolled exposition keeps the dependency surface zero.

Engine-side series (localai_kv_pool_pages_{total,free,retained,active},
localai_kv_pool_oversubscription, localai_prefix_cache_*_total) live in
the backend subprocess; the /metrics handler (api/localai_routes.py)
refreshes them via each loaded model's GetMetrics RPC right before
rendering, labeled model="<name>".
"""

from __future__ import annotations

import threading
from collections import defaultdict

_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
            30.0, 60.0, 120.0, 300.0)

# Prometheus text exposition content type (version is part of the
# contract: scrapers negotiate the parser off it)
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def escape_label_value(v) -> str:
    """Prometheus exposition label-value escaping: backslash, newline,
    double-quote (in that order — escaping the escape char first)."""
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def label_str(**kv) -> str:
    """Build a label string with properly escaped values, sorted for a
    stable exposition ordering."""
    return ",".join(f'{k}="{escape_label_value(v)}"'
                    for k, v in sorted(kv.items()))


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        # (method, path) -> [bucket counts..., +inf], sum, count
        self._hist = defaultdict(lambda: [[0] * (len(_BUCKETS) + 1), 0.0, 0])
        self._counters = defaultdict(int)
        # pull-updated instruments (engine pool telemetry): the /metrics
        # handler refreshes these from each loaded backend's GetMetrics
        # before rendering. Gauges are point-in-time; "absolute counters"
        # are monotonic totals owned by the backend (the engine counts,
        # this process just re-exposes — so a backend restart resets
        # them, which Prometheus rate() handles as a counter reset).
        self._gauges: dict = {}
        self._abs_counters: dict = {}
        # named histograms: locally observed (observe_histogram) or
        # pull-updated from backend snapshots (set_histogram). Keyed
        # (name, labels) -> [buckets(tuple), counts(+Inf last), sum, n]
        self._named_hists: dict = {}
        # per-histogram exemplars (ISSUE 8 satellite): the worst recent
        # observation's correlation id, attached to the bucket line the
        # observation falls in (OpenMetrics `# {trace_id="..."}` syntax).
        # Keyed (name, labels) -> (value, trace_id, unix_ts)
        self._exemplars: dict = {}

    def observe_api_call(self, method: str, path: str, seconds: float):
        with self._lock:
            h = self._hist[(method, path)]
            for i, b in enumerate(_BUCKETS):
                if seconds <= b:
                    h[0][i] += 1
                    break
            else:
                h[0][-1] += 1
            h[1] += seconds
            h[2] += 1

    def inc(self, name: str, labels: str = ""):
        with self._lock:
            self._counters[(name, labels)] += 1

    def set_gauge(self, name: str, value, labels: str = ""):
        with self._lock:
            self._gauges[(name, labels)] = float(value)

    def set_counter(self, name: str, value, labels: str = ""):
        """Expose a backend-owned monotonic total at its current value."""
        with self._lock:
            self._abs_counters[(name, labels)] = int(value)

    def observe_histogram(self, name: str, seconds: float,
                          labels: str = "", buckets=None):
        """Observe one sample into a named histogram (cumulative
        exposition with _bucket/_sum/_count happens in render())."""
        buckets = tuple(buckets) if buckets else _BUCKETS
        with self._lock:
            h = self._named_hists.get((name, labels))
            if h is None or h[0] != buckets:
                h = self._named_hists[(name, labels)] = [
                    buckets, [0] * (len(buckets) + 1), 0.0, 0]
            for i, b in enumerate(buckets):
                if seconds <= b:
                    h[1][i] += 1
                    break
            else:
                h[1][-1] += 1
            h[2] += seconds
            h[3] += 1

    def set_histogram(self, name: str, labels: str, buckets, counts,
                      hsum: float, count: int):
        """Expose a backend-owned histogram snapshot (non-cumulative
        per-bucket counts, +Inf last) at its current state — same
        pull-updated contract as set_counter."""
        with self._lock:
            self._named_hists[(name, labels)] = [
                tuple(buckets), [int(c) for c in counts],
                float(hsum), int(count)]

    def set_exemplar(self, name: str, labels: str, value: float,
                     trace_id: str, ts: float = 0.0):
        """Attach an exemplar (worst recent observation + its trace id)
        to a named histogram — rendered on the matching bucket line."""
        with self._lock:
            self._exemplars[(name, labels)] = (float(value),
                                               str(trace_id), float(ts))

    def clear_instrument(self, name: str):
        """Drop every series of a pull-updated instrument (a model was
        unloaded; stale per-model series must not linger)."""
        with self._lock:
            for d in (self._gauges, self._abs_counters, self._named_hists,
                      self._exemplars):
                for k in [k for k in d if k[0] == name]:
                    del d[k]

    def render(self) -> str:
        lines = [
            "# HELP localai_api_call Duration of API calls",
            "# TYPE localai_api_call histogram",
        ]
        with self._lock:
            for (method, path), (buckets, total, count) in sorted(self._hist.items()):
                labels = (f'method="{escape_label_value(method)}",'
                          f'path="{escape_label_value(path)}"')
                cum = 0
                for i, b in enumerate(_BUCKETS):
                    cum += buckets[i]
                    lines.append(
                        f'localai_api_call_bucket{{{labels},le="{b}"}} {cum}')
                cum += buckets[-1]
                lines.append(f'localai_api_call_bucket{{{labels},le="+Inf"}} {cum}')
                lines.append(f'localai_api_call_sum{{{labels}}} {total:.6f}')
                lines.append(f'localai_api_call_count{{{labels}}} {count}')
            hseen = set()
            for (name, labels), (buckets, counts, hsum, count) in sorted(
                    self._named_hists.items()):
                if name not in hseen:
                    hseen.add(name)
                    lines.append(f"# TYPE localai_{name} histogram")
                sep = "," if labels else ""
                # exemplar: rendered on the bucket line whose range the
                # worst recent observation falls in
                ex = self._exemplars.get((name, labels))
                ex_i = None
                if ex is not None:
                    ex_i = len(buckets)   # +Inf by default
                    for i, b in enumerate(buckets):
                        if ex[0] <= b:
                            ex_i = i
                            break
                cum = 0
                for i, b in enumerate(buckets):
                    cum += counts[i]
                    line = (f'localai_{name}_bucket{{{labels}{sep}le="{b}"}} '
                            f'{cum}')
                    if ex_i == i:
                        line += (f' # {{trace_id="{ex[1]}"}} {ex[0]:g}'
                                 + (f' {ex[2]:.3f}' if ex[2] else ""))
                    lines.append(line)
                cum += counts[-1]
                line = f'localai_{name}_bucket{{{labels}{sep}le="+Inf"}} {cum}'
                if ex_i == len(buckets):
                    line += (f' # {{trace_id="{ex[1]}"}} {ex[0]:g}'
                             + (f' {ex[2]:.3f}' if ex[2] else ""))
                lines.append(line)
                label_part = f"{{{labels}}}" if labels else ""
                lines.append(f'localai_{name}_sum{label_part} {hsum:.6f}')
                lines.append(f'localai_{name}_count{label_part} {count}')
            for (name, labels), v in sorted(self._counters.items()):
                label_part = f"{{{labels}}}" if labels else ""
                lines.append(f"localai_{name}{label_part} {v}")
            seen = set()
            for (name, labels), v in sorted(self._gauges.items()):
                if name not in seen:
                    seen.add(name)
                    lines.append(f"# TYPE localai_{name} gauge")
                label_part = f"{{{labels}}}" if labels else ""
                lines.append(f"localai_{name}{label_part} {v:g}")
            for (name, labels), v in sorted(self._abs_counters.items()):
                if name not in seen:
                    seen.add(name)
                    lines.append(f"# TYPE localai_{name} counter")
                label_part = f"{{{labels}}}" if labels else ""
                lines.append(f"localai_{name}{label_part} {v}")
        return "\n".join(lines) + "\n"


METRICS = Metrics()
