"""KV lifecycle ledger + online invariant auditor (ISSUE 15).

The paged KV subsystem — COW page pool (engine/paging.py), cross-release
prefix cache (engine/prefix_cache.py), two-tier host offload
(engine/kv_offload.py), and the shared-store replica pool
(engine/pool.py) — encodes its lifecycle rules as refcount discipline.
Before this module those rules lived in bare ``assert``s (compiled away
under ``python -O``) and test-time checks; a silent refcount leak in
production was invisible until the pool wedged. This module makes the
lifecycle OBSERVABLE and ENFORCED:

* ``KVLedger`` — a bounded ring of compact per-page transition records
  (alloc/free/share/clone/hold/drop/splice/release/retain/evict/
  offload/restore/host_evict/adopt/migrate/demote/compress/prefetch),
  with per-transition
  counters and running live-page/live-hold balances. Fed by hooks in
  the four KV modules, each gated on a single ``audit is not None``
  check so ``kv_audit=off`` constructs nothing and allocates nothing on
  the hot path (same zero-cost-off discipline as ``trace=0``).

* ``KVAuditor`` — O(num_pages) numpy invariant scans, piggybacked on
  the engine housekeeping cadence (the 0.5 s watermark fold) and the
  pool housekeeping loop. Families: CONSERVATION (free + in-use ==
  num_pages, refs >= held, table-referenced pages all refs > 0, owned
  counts match the table), LEAK FREEDOM (no referenced page outside
  every slot table, the prefix cache, and caller-declared extras),
  LEDGER BALANCE (running balances match the pool's truth),
  CROSS-TIER / CROSS-REPLICA (host-store byte accounting matches the
  summed entry sizes, no dangling sibling-mapped key after an
  eviction — both scanned inside HostPageStore.audit_scan under its
  lock), sampled CRC spot-checks of retained host entries, and a
  POST-DRAIN check (everything free, all holds dropped, ledger balances
  to zero).

Modes: ``off`` (no auditor object, no hooks fire), ``on`` (report-only:
counters + ``kv_audit_violation`` events + flight dump — the default),
``strict`` (raises ``KVAuditError``, for tests and chaos rigs).

Violations are dicts ``{"check", "detail", ...}`` so they ride
structured events, ``/debug/kv``, and flight-recorder payloads as-is.
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np

#: every transition the ledger understands — keep in sync with the
#: hooks in paging.py / prefix_cache.py / kv_offload.py / pool.py
TRANSITIONS = ("alloc", "free", "share", "clone", "hold", "drop",
               "splice", "release", "retain", "evict", "offload",
               "restore", "host_evict", "adopt", "migrate", "reset",
               "demote", "compress", "prefetch",
               # ISSUE 17 cluster transport: entries crossing the wire
               # are declared extras, never leaks — stream_in/stream_out
               # bracket a FederatedKV fetch/push, disagg marks a
               # prefill-role chain retirement to the decode host
               "stream_in", "stream_out", "disagg")


class KVLifecycleError(RuntimeError):
    """A page lifecycle rule was broken (hold on a free page, splice of
    a freed page, share into a non-empty slot, ...).

    Replaces the load-bearing bare ``assert``s in engine/paging.py
    (ISSUE 15 satellite): raised unconditionally — it survives
    ``python -O`` — and carries the op/page/slot so the auditor can
    record the violation before the raise propagates."""

    def __init__(self, op: str, detail: str, page: int = -1, slot=None):
        super().__init__(
            f"kv lifecycle: {op}: {detail} (page={page}, slot={slot})")
        self.op = op
        self.detail = detail
        self.page = int(page)
        self.slot = slot


class KVAuditError(RuntimeError):
    """Strict mode: an invariant scan found violations."""


class KVLedger:
    """Bounded per-page lifecycle ledger: a ring of compact tuples
    ``(seq, op, page, slot, key8, rid)`` plus per-transition counters
    and running balances. record() is the hot-path hook target — one
    counter bump and one deque append, no allocation beyond the tuple;
    callers gate on ``audit is not None`` so off-mode pays nothing."""

    __slots__ = ("ring", "counts", "seq", "replica",
                 "live_pages", "live_holds")

    def __init__(self, size: int = 2048, replica: int = -1):
        self.ring = deque(maxlen=max(64, int(size)))
        self.counts: dict = {}
        self.seq = 0
        self.replica = replica
        self.live_pages = 0     # alloc minus free (== pages_in_use)
        self.live_holds = 0     # hold minus drop (== held.sum())

    def record(self, op: str, page: int = -1, slot=-1,
               key: bytes = b"", rid: str = ""):
        self.seq += 1
        self.counts[op] = self.counts.get(op, 0) + 1
        if op == "alloc":
            self.live_pages += 1
        elif op == "free":
            self.live_pages -= 1
        elif op == "hold":
            self.live_holds += 1
        elif op == "drop":
            self.live_holds -= 1
        self.ring.append((self.seq, op, int(page), slot,
                          key[:8].hex() if key else "", rid))

    def rebase(self):
        """Zero the running balances (device-state reset rebuilt the
        pool: every page is free again, every hold is gone). Totals and
        the ring survive — the reset itself is a ledger event."""
        self.live_pages = 0
        self.live_holds = 0
        self.record("reset")

    def tail(self, n: int = 64) -> list:
        items = list(self.ring)
        return [{"seq": s, "op": op, "page": p, "slot": str(sl),
                 "key": k, "rid": r}
                for (s, op, p, sl, k, r) in items[-int(n):]]

    def snapshot(self) -> dict:
        return {"events_total": self.seq, "live_pages": self.live_pages,
                "live_holds": self.live_holds, "counts": dict(self.counts)}


class KVAuditor:
    """Online invariant auditor over one replica's KV tiers. Constructed
    only when ``kv_audit != off``; the engine wires ``on_violation`` to
    emit the ``kv_audit_violation`` event and trigger the flight
    recorder with the ledger tail attached."""

    def __init__(self, mode: str = "on", replica: int = -1,
                 ledger_size: int = 2048, sample_crc: int = 4,
                 seed: int = 0):
        if mode not in ("on", "strict"):
            raise ValueError(f"kv_audit mode must be on|strict, got {mode!r}"
                             " (off never constructs an auditor)")
        self.mode = mode
        self.replica = replica
        self.ledger = KVLedger(size=ledger_size, replica=replica)
        self.checks = 0
        self.violations = 0
        self.leaked_pages = 0           # orphan count from the last scan
        self.sample_crc = int(sample_crc)
        self.on_violation = None
        self.last_violations: deque = deque(maxlen=16)
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()

    # ---------------- reporting ----------------

    def _report(self, violations: list):
        if not violations:
            return
        with self._lock:
            self.violations += len(violations)
            self.last_violations.extend(violations)
        cb = self.on_violation
        if cb is not None:
            for v in violations:
                try:
                    cb(v)
                except Exception:
                    pass        # telemetry, never a serving dependency
        if self.mode == "strict":
            raise KVAuditError("; ".join(
                f"[{v.get('check')}] {v.get('detail')}" for v in violations))

    def lifecycle_violation(self, err: KVLifecycleError):
        """paging.py reports a broken lifecycle rule here right before
        raising it — the raise (not strict mode) is the enforcement, the
        report is the observability."""
        self.ledger.record("violation", page=err.page, slot=err.slot)
        v = {"check": "lifecycle", "detail": str(err), "op": err.op,
             "page": err.page, "replica": self.replica}
        with self._lock:
            self.violations += 1
            self.last_violations.append(v)
        cb = self.on_violation
        if cb is not None:
            try:
                cb(v)
            except Exception:
                pass

    # ---------------- invariant families ----------------

    def check_pool(self, pool, pcache=None, extra_pages=None,
                   drained: bool = False) -> list:
        """Conservation + table consistency + leak freedom + ledger
        balance, O(num_pages) numpy over the pool's host mirrors. Run
        from the engine-loop thread (or with the engine quiesced) so the
        mirrors are not mid-mutation."""
        out = []
        refs, held = pool.refs, pool.held
        n = int(pool.num_pages)
        n_free = len(pool._free)
        in_use = int(np.count_nonzero(refs > 0))
        if n_free + in_use != n:
            out.append({"check": "conservation",
                        "detail": f"free({n_free}) + in_use({in_use}) "
                                  f"!= num_pages({n})"})
        if n_free:
            free = np.fromiter(pool._free, dtype=np.int64, count=n_free)
            bad = free[refs[free] != 0]
            if bad.size:
                out.append({"check": "conservation",
                            "detail": f"{bad.size} free-list pages still "
                                      f"referenced: {bad[:8].tolist()}"})
        over = np.flatnonzero(held > refs)
        if over.size:
            out.append({"check": "conservation",
                        "detail": f"held > refs on {over.size} pages: "
                                  f"{over[:8].tolist()}"})
        mask = pool.ptab != n
        pages = pool.ptab[mask]
        if pages.size:
            freed = pages[refs[pages] <= 0]
            if freed.size:
                out.append({"check": "table",
                            "detail": f"slot tables reference {freed.size} "
                                      f"freed pages: "
                                      f"{freed[:8].tolist()}"})
        owned_counts = mask.sum(axis=1)
        if np.any(owned_counts != pool.owned):
            bad_slots = np.flatnonzero(
                owned_counts != pool.owned)[:8].tolist()
            out.append({"check": "table",
                        "detail": f"owned[] disagrees with the table on "
                                  f"slots {bad_slots}"})
        # leak freedom: every referenced page must be reachable from a
        # slot table, a prefix-cache hold, or a caller-declared extra
        live = np.flatnonzero(refs > 0)
        accounted = set(pages.tolist())
        if pcache is not None:
            accounted.update(pcache.pages())
        if extra_pages:
            accounted.update(int(p) for p in extra_pages)
        orphans = [int(p) for p in live if int(p) not in accounted]
        self.leaked_pages = len(orphans)
        if orphans:
            out.append({"check": "leak",
                        "detail": f"{len(orphans)} referenced pages "
                                  f"reachable from no table/cache: "
                                  f"{orphans[:8]}",
                        "leaked_pages": len(orphans)})
        led = self.ledger
        if led.live_pages != in_use:
            out.append({"check": "ledger",
                        "detail": f"ledger live_pages({led.live_pages}) "
                                  f"!= pool in_use({in_use})"})
        held_sum = int(held.sum())
        if led.live_holds != held_sum:
            out.append({"check": "ledger",
                        "detail": f"ledger live_holds({led.live_holds}) "
                                  f"!= pool held({held_sum})"})
        if drained:
            if in_use or held_sum:
                out.append({"check": "drain",
                            "detail": f"post-drain leak: in_use={in_use} "
                                      f"held={held_sum}",
                            "leaked_pages": in_use})
                self.leaked_pages = max(self.leaked_pages, in_use)
            if pcache is not None and len(pcache) != 0:
                out.append({"check": "drain",
                            "detail": f"post-drain: prefix cache still "
                                      f"holds {len(pcache)} entries"})
        for v in out:
            v.setdefault("replica", self.replica)
        return out

    def check_host(self, store) -> list:
        """Cross-tier / cross-replica families + sampled CRC, delegated
        to HostPageStore.audit_scan (the scan needs the store lock)."""
        try:
            out = store.audit_scan(sample_crc=self.sample_crc,
                                   rng=self._rng)
        except Exception as e:   # never let telemetry kill the loop
            out = [{"check": "host",
                    "detail": f"audit_scan failed: "
                              f"{type(e).__name__}: {e}"}]
        for v in out:
            v.setdefault("replica", self.replica)
        return out

    def scan_shared(self, store) -> list:
        """Pool housekeeping entry point: scan the SHARED host store
        once, pool-wide (never per replica — violations would double
        count). Tagged replica=-1: a shared-tier fault has no single
        replica to blame."""
        with self._lock:
            self.checks += 1
        out = self.check_host(store)
        for v in out:
            v["replica"] = -1
        self._report(out)
        return out

    def run(self, pool, pcache=None, hstore=None, extra_pages=None,
            drained: bool = False) -> list:
        """One full audit pass; returns (and reports) the violations."""
        with self._lock:
            self.checks += 1
        out = self.check_pool(pool, pcache=pcache, extra_pages=extra_pages,
                              drained=drained)
        if hstore is not None:
            out.extend(self.check_host(hstore))
        self._report(out)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {"mode": self.mode, "checks": self.checks,
                    "violations": self.violations,
                    "leaked_pages": self.leaked_pages,
                    "ledger_events": self.ledger.seq,
                    "ledger": self.ledger.snapshot(),
                    "last_violations": list(self.last_violations)}
