"""System observability (ISSUE 8): XLA compile tracking, memory
watermarks, goodput/MFU accounting.

Complementary to services/tracing.py (per-request spans): this module
watches the SYSTEM — what the compiler and the memory pools are doing
underneath the request stream.

**Compile tracking.** jax emits a
``/jax/core/compile/backend_compile_duration`` monitoring event once
per real XLA compilation, synchronously on the compiling thread (cached
executions emit only cheap trace events). We register ONE module-level
listener and dispatch to the engine whose thread is compiling via a
thread-local registration: the engine loop thread registers its
CompileTracker at startup, and ``precompile()`` (which runs on the
loader/caller thread) wraps itself in :func:`activated`. Program
attribution rides the same thread-local — the engine's fn-getters call
``note_program(kind, key)`` on a jit-cache miss immediately before the
compiling call, so the listener can name the program that compiled.

The warm boundary is marked at the END of ``precompile()``: everything
before it (including incidental helper fills like ``jnp.ones``) is
warmup; any compile after it is a "compile storm" — a structured
WARNING + ``compile_storm`` event, because a post-warmup recompile is a
latency cliff the bucket tables were supposed to prevent.

**Watermarks.** High-water marks over gauge samples (peak active /
retained / offloaded pages, host bytes, …) — cheap max() folds sampled
from the engine loop so peaks between /metrics scrapes are not lost.

**Goodput / MFU.** Analytic FLOPs-per-token from the model config
(matmul params ×2 + attention term) and achieved tokens/s over a
rolling window → model FLOPs utilization against the device's peak
(``LOCALAI_PEAK_TFLOPS`` env or per-kind table; 0 ⇒ unknown ⇒ MFU
reported as 0.0, the honest answer on CPU rigs). Goodput counts ONLY
completed-request tokens — sheds, timeouts, stalls and errors produce
no goodput even though they burned FLOPs.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

log = logging.getLogger("localai_tpu.sysobs")

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_LAST_COMPILES = 32     # ring of recent compiles kept per tracker

_tl = threading.local()
_listener_lock = threading.Lock()
_listener_installed = False


def _on_event_duration(name: str, secs: float, **kw):
    if name != _COMPILE_EVENT:
        return
    tracker = getattr(_tl, "tracker", None)
    if tracker is not None:
        tracker.on_compile(secs)


def install_listener():
    """Register the module-level jax.monitoring listener (idempotent).
    Gated on import success so non-jax processes can still import the
    watermark/goodput halves of this module."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_event_duration)
            _listener_installed = True
        except Exception as e:  # pragma: no cover - jax always present in CI
            log.warning("compile-event listener unavailable: %s", e)


def register_thread(tracker: "CompileTracker"):
    """Bind `tracker` to THIS thread for compile attribution (engine
    loop threads call this once at startup)."""
    _tl.tracker = tracker


class activated:
    """Context manager binding a tracker to the current thread for the
    duration of a block — used by precompile(), which runs on the
    loader/caller thread, not the engine loop."""

    def __init__(self, tracker: "CompileTracker"):
        self.tracker = tracker

    def __enter__(self):
        self.prev = getattr(_tl, "tracker", None)
        _tl.tracker = self.tracker
        return self.tracker

    def __exit__(self, *exc):
        _tl.tracker = self.prev
        return False


class CompileTracker:
    """Per-engine XLA compilation counters + compile-storm detection."""

    def __init__(self, model: str = "", on_storm=None):
        self.model = model
        self.on_storm = on_storm    # callable(rec) — eventlog write-through
        self.compiles = 0
        self.compile_seconds = 0.0
        self.compiles_after_warmup = 0
        self.warm = False
        self._last: deque = deque(maxlen=_LAST_COMPILES)
        self._lock = threading.Lock()
        install_listener()

    def note_program(self, kind: str, key=None):
        """Name the program about to compile on THIS thread (called by
        the engine's fn-getters on a jit-cache miss)."""
        _tl.program = f"{kind}:{key}" if key is not None else kind

    def mark_warm(self):
        """precompile() finished: every compile from now on is a storm."""
        with self._lock:
            self.warm = True

    def on_compile(self, secs: float):
        program = getattr(_tl, "program", None) or "?"
        _tl.program = None   # consume: one note names one compile
        with self._lock:
            self.compiles += 1
            self.compile_seconds += secs
            storm = self.warm
            rec = {"t": round(time.time(), 3), "seconds": round(secs, 4),
                   "program": program, "after_warmup": storm}
            self._last.append(rec)
            if storm:
                self.compiles_after_warmup += 1
        if storm:
            # a recompile after warmup is a latency cliff: make it loud
            # (structured WARNING) and durable (eventlog write-through)
            log.warning(json.dumps({
                "event": "compile_after_warmup", "model": self.model,
                "program": program, "seconds": round(secs, 4),
                "compiles_after_warmup": self.compiles_after_warmup}))
            if self.on_storm is not None:
                try:
                    self.on_storm(rec)
                except Exception:
                    pass

    def last_compiles(self) -> list:
        with self._lock:
            return list(self._last)

    def snapshot(self) -> dict:
        with self._lock:
            return {"compiles_total": self.compiles,
                    "compile_seconds_total": round(self.compile_seconds, 4),
                    "compiles_after_warmup": self.compiles_after_warmup,
                    "warm": self.warm}


class Watermarks:
    """High-water (and a few low-water) marks over sampled gauges."""

    def __init__(self):
        self._peak: dict = {}
        self._lock = threading.Lock()

    def sample(self, **gauges):
        with self._lock:
            for name, val in gauges.items():
                if val is None:
                    continue
                cur = self._peak.get(name)
                if cur is None or val > cur:
                    self._peak[name] = val

    def peak(self, name: str, default=0):
        with self._lock:
            return self._peak.get(name, default)

    def snapshot(self) -> dict:
        with self._lock:
            return {f"peak_{k}": v for k, v in sorted(self._peak.items())}


def flops_per_token(cfg, ctx: int = 0) -> float:
    """Analytic forward-pass FLOPs per generated token for a llama-family
    config: 2 FLOPs per matmul weight parameter, plus the attention
    score/value term (~4*h FLOPs per layer per context row) at context
    depth `ctx`. Embedding lookup is free; the LM head counts (it is a
    matmul), tied or not."""
    h = cfg.hidden_size
    kv = cfg.num_kv_heads * cfg.head_dim_
    q = cfg.num_heads * cfg.head_dim_
    per_layer = (h * q          # q proj
                 + 2 * h * kv   # k,v proj
                 + q * h        # o proj
                 + 3 * h * cfg.intermediate_size)  # gate/up/down
    matmul_params = cfg.num_layers * per_layer + h * cfg.vocab_size
    attn = 4.0 * cfg.num_layers * ctx * h if ctx > 0 else 0.0
    return 2.0 * matmul_params + attn


# peak dense (bf16) FLOP/s per chip by device-kind substring. CPU rigs
# fall through to 0.0: "unknown" — README documents that MFU reads 0
# there rather than inventing a laptop-core number.
_PEAK_FLOPS_TABLE = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_device_flops() -> float:
    """Peak FLOP/s of one local device: LOCALAI_PEAK_TFLOPS env wins,
    else a TPU device-kind table, else 0.0 (unknown — e.g. CPU)."""
    env = os.environ.get("LOCALAI_PEAK_TFLOPS", "")
    if env:
        try:
            return float(env) * 1e12
        except ValueError:
            log.warning("bad LOCALAI_PEAK_TFLOPS=%r; ignoring", env)
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return 0.0
    for sub, flops in _PEAK_FLOPS_TABLE:
        if sub in kind:
            return flops
    return 0.0


class GoodputMeter:
    """Completed-request token accounting → goodput tok/s and MFU.

    `add(n)` is called ONLY from the clean-finish branch of the engine's
    emit path — sheds/timeouts/stalls never reach it, so `tokens_total`
    is useful-work throughput by construction."""

    def __init__(self, flops_per_tok: float = 0.0, peak_flops: float = 0.0,
                 window_s: float = 60.0):
        self.flops_per_tok = float(flops_per_tok)
        self.peak_flops = float(peak_flops)
        self.window_s = float(window_s)
        self.tokens_total = 0
        self.requests_total = 0
        self._window: deque = deque()   # (t_monotonic, n_tokens)
        self._lock = threading.Lock()

    def add(self, n_tokens: int):
        now = time.monotonic()
        with self._lock:
            self.tokens_total += int(n_tokens)
            self.requests_total += 1
            self._window.append((now, int(n_tokens)))
            self._trim(now)

    def _trim(self, now: float):
        horizon = now - self.window_s
        w = self._window
        while w and w[0][0] < horizon:
            w.popleft()

    def tok_s(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            if not self._window:
                return 0.0
            toks = sum(n for _, n in self._window)
            span = max(now - self._window[0][0], 1e-3)
        return toks / span

    def mfu(self, tok_s: float = None) -> float:
        if self.peak_flops <= 0 or self.flops_per_tok <= 0:
            return 0.0
        rate = self.tok_s() if tok_s is None else tok_s
        return rate * self.flops_per_tok / self.peak_flops

    def snapshot(self) -> dict:
        rate = self.tok_s()
        return {"goodput_tokens_total": self.tokens_total,
                "goodput_requests_total": self.requests_total,
                "goodput_tok_s": round(rate, 3),
                "mfu": round(self.mfu(rate), 6),
                "flops_per_token": self.flops_per_tok,
                "peak_flops": self.peak_flops}
