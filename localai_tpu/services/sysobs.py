"""System observability (ISSUE 8): XLA compile tracking, memory
watermarks, goodput/MFU accounting.

Complementary to services/tracing.py (per-request spans): this module
watches the SYSTEM — what the compiler and the memory pools are doing
underneath the request stream.

**Compile tracking.** jax emits a
``/jax/core/compile/backend_compile_duration`` monitoring event once
per real XLA compilation, synchronously on the compiling thread (cached
executions emit only cheap trace events). We register ONE module-level
listener and dispatch to the engine whose thread is compiling via a
thread-local registration: the engine loop thread registers its
CompileTracker at startup, and ``precompile()`` (which runs on the
loader/caller thread) wraps itself in :func:`activated`. Program
attribution rides the same thread-local — the engine's fn-getters call
``note_program(kind, key)`` on a jit-cache miss immediately before the
compiling call, so the listener can name the program that compiled.

The warm boundary is marked at the END of ``precompile()``: everything
before it (including incidental helper fills like ``jnp.ones``) is
warmup; any compile after it is a "compile storm" — a structured
WARNING + ``compile_storm`` event, because a post-warmup recompile is a
latency cliff the bucket tables were supposed to prevent.

**Watermarks.** High-water marks over gauge samples (peak active /
retained / offloaded pages, host bytes, …) — cheap max() folds sampled
from the engine loop so peaks between /metrics scrapes are not lost.

**Goodput / MFU.** Analytic FLOPs-per-token from the model config
(matmul params ×2 + attention term) and achieved tokens/s over a
rolling window → model FLOPs utilization against the device's peak
(``LOCALAI_PEAK_TFLOPS`` env or per-kind table; 0 ⇒ unknown ⇒ MFU
reported as 0.0, the honest answer on CPU rigs). Goodput counts ONLY
completed-request tokens — sheds, timeouts, stalls and errors produce
no goodput even though they burned FLOPs.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from collections import deque

log = logging.getLogger("localai_tpu.sysobs")

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_LAST_COMPILES = 32     # ring of recent compiles kept per tracker

_tl = threading.local()
_listener_lock = threading.Lock()
_listener_installed = False


def _on_event_duration(name: str, secs: float, **kw):
    if name != _COMPILE_EVENT:
        return
    tracker = getattr(_tl, "tracker", None)
    if tracker is not None:
        tracker.on_compile(secs)


def install_listener():
    """Register the module-level jax.monitoring listener (idempotent).
    Gated on import success so non-jax processes can still import the
    watermark/goodput halves of this module."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_event_duration)
            _listener_installed = True
        except Exception as e:  # pragma: no cover - jax always present in CI
            log.warning("compile-event listener unavailable: %s", e)


def register_thread(tracker: "CompileTracker"):
    """Bind `tracker` to THIS thread for compile attribution (engine
    loop threads call this once at startup)."""
    _tl.tracker = tracker


class activated:
    """Context manager binding a tracker to the current thread for the
    duration of a block — used by precompile(), which runs on the
    loader/caller thread, not the engine loop."""

    def __init__(self, tracker: "CompileTracker"):
        self.tracker = tracker

    def __enter__(self):
        self.prev = getattr(_tl, "tracker", None)
        _tl.tracker = self.tracker
        return self.tracker

    def __exit__(self, *exc):
        _tl.tracker = self.prev
        return False


class CompileTracker:
    """Per-engine XLA compilation counters + compile-storm detection."""

    def __init__(self, model: str = "", on_storm=None):
        self.model = model
        self.on_storm = on_storm    # callable(rec) — eventlog write-through
        self.compiles = 0
        self.compile_seconds = 0.0
        self.compiles_after_warmup = 0
        self.warm = False
        self._last: deque = deque(maxlen=_LAST_COMPILES)
        self._lock = threading.Lock()
        install_listener()

    def note_program(self, kind: str, key=None):
        """Name the program about to compile on THIS thread (called by
        the engine's fn-getters on a jit-cache miss)."""
        _tl.program = f"{kind}:{key}" if key is not None else kind

    def mark_warm(self):
        """precompile() finished: every compile from now on is a storm."""
        with self._lock:
            self.warm = True

    def on_compile(self, secs: float):
        program = getattr(_tl, "program", None) or "?"
        _tl.program = None   # consume: one note names one compile
        with self._lock:
            self.compiles += 1
            self.compile_seconds += secs
            storm = self.warm
            rec = {"t": round(time.time(), 3), "seconds": round(secs, 4),
                   "program": program, "after_warmup": storm}
            self._last.append(rec)
            if storm:
                self.compiles_after_warmup += 1
        if storm:
            # a recompile after warmup is a latency cliff: make it loud
            # (structured WARNING) and durable (eventlog write-through)
            log.warning(json.dumps({
                "event": "compile_after_warmup", "model": self.model,
                "program": program, "seconds": round(secs, 4),
                "compiles_after_warmup": self.compiles_after_warmup}))
            if self.on_storm is not None:
                try:
                    self.on_storm(rec)
                except Exception:
                    pass

    def last_compiles(self) -> list:
        with self._lock:
            return list(self._last)

    def snapshot(self) -> dict:
        with self._lock:
            return {"compiles_total": self.compiles,
                    "compile_seconds_total": round(self.compile_seconds, 4),
                    "compiles_after_warmup": self.compiles_after_warmup,
                    "warm": self.warm}


class Watermarks:
    """High-water (and a few low-water) marks over sampled gauges."""

    def __init__(self):
        self._peak: dict = {}
        self._lock = threading.Lock()

    def sample(self, **gauges):
        with self._lock:
            for name, val in gauges.items():
                if val is None:
                    continue
                cur = self._peak.get(name)
                if cur is None or val > cur:
                    self._peak[name] = val

    def peak(self, name: str, default=0):
        with self._lock:
            return self._peak.get(name, default)

    def snapshot(self) -> dict:
        with self._lock:
            return {f"peak_{k}": v for k, v in sorted(self._peak.items())}


def flops_per_token(cfg, ctx: int = 0) -> float:
    """Analytic forward-pass FLOPs per generated token for a llama-family
    config: 2 FLOPs per matmul weight parameter, plus the attention
    score/value term (~4*h FLOPs per layer per context row) at context
    depth `ctx`. Embedding lookup is free; the LM head counts (it is a
    matmul), tied or not."""
    h = cfg.hidden_size
    kv = cfg.num_kv_heads * cfg.head_dim_
    q = cfg.num_heads * cfg.head_dim_
    per_layer = (h * q          # q proj
                 + 2 * h * kv   # k,v proj
                 + q * h        # o proj
                 + 3 * h * cfg.intermediate_size)  # gate/up/down
    matmul_params = cfg.num_layers * per_layer + h * cfg.vocab_size
    attn = 4.0 * cfg.num_layers * ctx * h if ctx > 0 else 0.0
    return 2.0 * matmul_params + attn


# peak dense (bf16) FLOP/s per chip by device-kind substring. CPU rigs
# fall through to 0.0: "unknown" — README documents that MFU reads 0
# there rather than inventing a laptop-core number.
_PEAK_FLOPS_TABLE = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def peak_device_flops() -> float:
    """Peak FLOP/s of one local device: LOCALAI_PEAK_TFLOPS env wins,
    else a TPU device-kind table, else 0.0 (unknown — e.g. CPU)."""
    env = os.environ.get("LOCALAI_PEAK_TFLOPS", "")
    if env:
        try:
            return float(env) * 1e12
        except ValueError:
            log.warning("bad LOCALAI_PEAK_TFLOPS=%r; ignoring", env)
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return 0.0
    for sub, flops in _PEAK_FLOPS_TABLE:
        if sub in kind:
            return flops
    return 0.0


class GoodputMeter:
    """Completed-request token accounting → goodput tok/s and MFU.

    `add(n)` is called ONLY from the clean-finish branch of the engine's
    emit path — sheds/timeouts/stalls never reach it, so `tokens_total`
    is useful-work throughput by construction."""

    def __init__(self, flops_per_tok: float = 0.0, peak_flops: float = 0.0,
                 window_s: float = 60.0):
        self.flops_per_tok = float(flops_per_tok)
        self.peak_flops = float(peak_flops)
        self.window_s = float(window_s)
        self.tokens_total = 0
        self.requests_total = 0
        self._window: deque = deque()   # (t_monotonic, n_tokens)
        self._lock = threading.Lock()

    def add(self, n_tokens: int):
        now = time.monotonic()
        with self._lock:
            self.tokens_total += int(n_tokens)
            self.requests_total += 1
            self._window.append((now, int(n_tokens)))
            self._trim(now)

    def _trim(self, now: float):
        horizon = now - self.window_s
        w = self._window
        while w and w[0][0] < horizon:
            w.popleft()

    def tok_s(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            if not self._window:
                return 0.0
            toks = sum(n for _, n in self._window)
            span = max(now - self._window[0][0], 1e-3)
        return toks / span

    def mfu(self, tok_s: float = None) -> float:
        if self.peak_flops <= 0 or self.flops_per_tok <= 0:
            return 0.0
        rate = self.tok_s() if tok_s is None else tok_s
        return rate * self.flops_per_tok / self.peak_flops

    def snapshot(self) -> dict:
        rate = self.tok_s()
        return {"goodput_tokens_total": self.tokens_total,
                "goodput_requests_total": self.requests_total,
                "goodput_tok_s": round(rate, 3),
                "mfu": round(self.mfu(rate), 6),
                "flops_per_token": self.flops_per_tok,
                "peak_flops": self.peak_flops}


# ---------------------------------------------------------------------------
# SLO engine (ISSUE 12): per-priority-class latency objectives with
# multi-window burn-rate evaluation, plus the violation flight recorder.
# ---------------------------------------------------------------------------

# the priority classes the scheduler knows, in the same order the
# colon-separated option values use (matches priority_weights)
SLO_CLASSES = ("high", "normal", "low")

# metric -> EngineConfig/options knob suffix; all thresholds in ms
SLO_METRICS = ("ttft_ms", "itl_ms", "queue_wait_ms")

# burn-rate windows (name -> seconds). Multi-window per SRE practice:
# the short window catches fast burns, the long one sustained ones.
SLO_WINDOWS = (("5m", 300.0), ("1h", 3600.0))


def parse_slo_classes(spec: str) -> dict:
    """Parse a colon-separated per-class threshold spec into
    {class: threshold_ms}. Accepted shapes (option values ride a
    comma-joined wire, so colon is the list separator, as in
    priority_weights):

    * ``""``            -> {} (no objective declared)
    * ``"500"``         -> the one threshold applies to every class
    * ``"250:1000:5000"`` -> high:normal:low
    * ``"high=250:low=5000"`` -> named subset; unnamed classes have no
      objective

    Raises ValueError on anything else so config validation can reject
    typos at scan time instead of silently serving without SLOs."""
    spec = (spec or "").strip()
    if not spec:
        return {}
    parts = [p.strip() for p in spec.split(":") if p.strip()]
    if not parts:
        return {}

    def _ms(v: str) -> float:
        ms = float(v)
        if not ms > 0:
            raise ValueError(f"SLO threshold must be > 0 ms, got {v!r}")
        return ms

    if any("=" in p for p in parts):
        out = {}
        for p in parts:
            if "=" not in p:
                raise ValueError(
                    f"mixed named and positional SLO classes in {spec!r}")
            k, v = (x.strip() for x in p.split("=", 1))
            if k not in SLO_CLASSES:
                raise ValueError(
                    f"unknown SLO class {k!r} (want one of {SLO_CLASSES})")
            out[k] = _ms(v)
        return out
    if len(parts) == 1:
        ms = _ms(parts[0])
        return {c: ms for c in SLO_CLASSES}
    if len(parts) == len(SLO_CLASSES):
        return {c: _ms(p) for c, p in zip(SLO_CLASSES, parts)}
    raise ValueError(
        f"SLO spec {spec!r} must have 1 or {len(SLO_CLASSES)} "
        f"(high:normal:low) colon-separated values, got {len(parts)}")


@dataclasses.dataclass
class AutoscaleSignals:
    """One policy-input snapshot (ISSUE 19) — everything the autoscaler
    is allowed to see, gathered by the pool on the housekeeping cadence
    and handed to ``AutoscalePolicy.sample()``. Kept a plain dataclass
    so every scaling decision can flight-record ``asdict(signals)`` as
    the evidence that justified it."""
    replicas: int = 1            # routable (alive, non-draining) replicas
    queued: int = 0              # queued requests summed over replicas
    queue_frac: float = 0.0      # queued / (max_queued_requests * replicas)
    busy_frac: float = 0.0       # active slots / total slots
    burn_5m: float = 0.0         # worst short-window SLO burn, any class
    free_page_frac: float = 1.0  # min over replicas (shared pool pressure)
    preempt_rate_per_min: float = 0.0  # summed preemption EWMA

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


class SLOEngine:
    """Per-(metric, class) objective tracking with windowed burn rates.

    Samples are (timestamp, violated?) pairs in bounded deques; the burn
    rate of a window is ``(violations / samples) / error_budget`` — the
    standard "how many times faster than allowed are we spending the
    error budget" number: 1.0 means exactly on budget, >1 means the SLO
    will be missed if the rate holds. `clock` is injectable so the
    window arithmetic is unit-testable with hand-picked timestamps.

    Thread-safety: observe() is called from the engine loop (single
    writer); snapshot()/burn_events() from metrics pulls — a lock keeps
    the deques consistent."""

    def __init__(self, objectives: dict, error_budget: float = 0.01,
                 clock=time.monotonic, max_samples: int = 4096,
                 burn_event_interval_s: float = 30.0):
        # objectives: {metric: {class: threshold_ms}}
        self.objectives = {m: dict(c) for m, c in (objectives or {}).items()
                           if c}
        self.error_budget = max(1e-6, float(error_budget))
        self.clock = clock
        self._samples: dict = {}     # (metric, cls) -> deque[(t, bad)]
        self._violations: dict = {}  # (metric, cls) -> int
        self._last_burn_event: dict = {}  # (metric, cls) -> t
        self._burn_event_interval = float(burn_event_interval_s)
        self._max_samples = int(max_samples)
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(self.objectives)

    def observe(self, metric: str, cls: str, value_ms: float,
                rid: str = ""):
        """Record one sample; returns the violation record (dict) when
        the sample broke its objective, else None. No objective declared
        for (metric, class) -> cheap no-op."""
        threshold = self.objectives.get(metric, {}).get(cls)
        if threshold is None:
            return None
        bad = value_ms > threshold
        now = self.clock()
        with self._lock:
            dq = self._samples.get((metric, cls))
            if dq is None:
                dq = self._samples[(metric, cls)] = deque(
                    maxlen=self._max_samples)
            dq.append((now, bad))
            if bad:
                self._violations[(metric, cls)] = \
                    self._violations.get((metric, cls), 0) + 1
        if not bad:
            return None
        return {"metric": metric, "class": cls,
                "value_ms": round(float(value_ms), 3),
                "objective_ms": threshold, "rid": rid}

    def _burn(self, dq, now: float, window_s: float):
        total = bad = 0
        horizon = now - window_s
        for t, b in dq:
            if t >= horizon:
                total += 1
                bad += b
        if total == 0:
            return 0.0, 0
        return (bad / total) / self.error_budget, total

    def snapshot(self) -> dict:
        """{class: {metric: {objective_ms, burn_5m, burn_1h, n_5m,
        violations}}, violations_total, error_budget}."""
        now = self.clock()
        out = {"error_budget": self.error_budget, "classes": {}}
        total_viol = 0
        with self._lock:
            for metric, classes in self.objectives.items():
                for cls, threshold in classes.items():
                    dq = self._samples.get((metric, cls), ())
                    viol = self._violations.get((metric, cls), 0)
                    total_viol += viol
                    rec = {"objective_ms": threshold, "violations": viol}
                    for wname, wsec in SLO_WINDOWS:
                        burn, n = self._burn(dq, now, wsec)
                        rec[f"burn_{wname}"] = round(burn, 4)
                        rec[f"n_{wname}"] = n
                    out["classes"].setdefault(cls, {})[metric] = rec
        out["violations_total"] = total_viol
        return out

    def max_burn(self, window_s: Optional[float] = None) -> float:
        """Policy-input scalar (ISSUE 19): the WORST burn across every
        observed (metric, class) pair over the short window (default:
        the 5m window). This is the autoscaler's primary scale-out
        signal — any one class burning its budget is reason to add a
        replica, whichever metric is suffering. Pairs with no samples
        in the window contribute nothing (an idle class is not 'fine',
        it is silent)."""
        wsec = float(window_s) if window_s else SLO_WINDOWS[0][1]
        now = self.clock()
        worst = 0.0
        with self._lock:
            for dq in self._samples.values():
                burn, n = self._burn(dq, now, wsec)
                if n and burn > worst:
                    worst = burn
        return worst

    def burn_events(self) -> list:
        """(metric, class) pairs whose SHORT-window burn is > 1 right
        now, rate-limited to one record per pair per
        `burn_event_interval_s` — the caller turns these into `slo_burn`
        structured events."""
        now = self.clock()
        out = []
        wname, wsec = SLO_WINDOWS[0]
        with self._lock:
            for (metric, cls), dq in self._samples.items():
                burn, n = self._burn(dq, now, wsec)
                if burn <= 1.0 or n == 0:
                    continue
                last = self._last_burn_event.get((metric, cls), -1e18)
                if now - last < self._burn_event_interval:
                    continue
                self._last_burn_event[(metric, cls)] = now
                out.append({"metric": metric, "class": cls,
                            "window": wname, "burn": round(burn, 4),
                            "samples": n,
                            "objective_ms":
                                self.objectives[metric][cls]})
        return out


class FlightRecorder:
    """Atomic on-violation dumps: merged chrome trace + state snapshot +
    last-N events written as ONE json file to `out_dir` (tmp file +
    os.replace so a reader never sees a half-written dump).

    Rate-limited (`min_interval_s` between dumps) and disk-bounded
    (`max_dumps` newest kept; older flight dumps are pruned) so a
    sustained violation storm cannot fill the disk. `clock` injectable
    for deterministic tests. dump() never raises — the recorder is
    telemetry, not a serving dependency."""

    PREFIX = "localai-flight-"

    def __init__(self, out_dir: str = "", min_interval_s: float = 30.0,
                 max_dumps: int = 8, clock=time.monotonic):
        import tempfile

        self.out_dir = out_dir or tempfile.gettempdir()
        self.min_interval_s = float(min_interval_s)
        self.max_dumps = max(1, int(max_dumps))
        self.clock = clock
        self.dumps = 0          # written
        self.suppressed = 0     # rate-limited away
        self._last_t = None
        self._lock = threading.Lock()

    def dump(self, reason: str, payload: dict, tag: str = "slo") -> str:
        """Write one flight dump; returns its path, or "" when
        rate-limited or on write failure."""
        now = self.clock()
        with self._lock:
            if self._last_t is not None \
                    and now - self._last_t < self.min_interval_s:
                self.suppressed += 1
                return ""
            self._last_t = now
            self.dumps += 1
            seq = self.dumps
        rec = {"reason": reason, "tag": tag, "ts": round(time.time(), 6)}
        rec.update(payload or {})
        name = (f"{self.PREFIX}{tag}-{os.getpid()}-"
                f"{int(time.time() * 1000)}-{seq}.json")
        path = os.path.join(self.out_dir, name)
        tmp = path + ".tmp"
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(rec, f, default=str)
            os.replace(tmp, path)
        except Exception as e:
            log.warning("flight-recorder dump failed: %s", e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return ""
        self._prune()
        return path

    def _prune(self):
        """Keep only the newest `max_dumps` flight dumps in out_dir."""
        try:
            mine = sorted(
                f for f in os.listdir(self.out_dir)
                if f.startswith(self.PREFIX) and f.endswith(".json"))
            for f in mine[:-self.max_dumps]:
                try:
                    os.unlink(os.path.join(self.out_dir, f))
                except OSError:
                    pass
        except OSError:
            pass

    def snapshot(self) -> dict:
        with self._lock:
            return {"dumps": self.dumps, "suppressed": self.suppressed,
                    "dir": self.out_dir, "max_dumps": self.max_dumps,
                    "min_interval_s": self.min_interval_s}


def device_memory_stats() -> dict:
    """Real-device memory watermarks (closes the PR-8 follow-up):
    `jax.local_devices()[0].memory_stats()` where the platform provides
    it (TPU and GPU runtimes do; CPU returns None/raises -> {}). Keys
    normalized to bytes_in_use / peak_bytes_in_use / bytes_limit; {}
    means "no device counters here — analytic accounting is the
    fallback"."""
    try:
        import jax

        dev = jax.local_devices()[0]
        stats = dev.memory_stats()
    except Exception:
        return {}
    if not stats:
        return {}
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        v = stats.get(key)
        if v is not None:
            out[key] = int(v)
    if out:
        out["device_kind"] = getattr(dev, "device_kind", "")
    return out
