"""Cluster control plane: real-process hosts behind a framed RPC surface.

ISSUE 20. PR 17 made KV location-independent across hosts, but every
"host" was an in-process ``ClusterHost`` handle — the only failure mode
the cluster could exercise was a cooperative ``kill()``. This module
gives each host a CONTROL PLANE so it can run as its own OS process
(spawned via ``scripts/cluster_host.py``) and fail the way real hosts
fail: crash (kill -9), hang (alive but unresponsive), and run slow
(answering, late). The KV data plane (services/kv_wire.py) is untouched
— it was already process-agnostic; this is the half the router needed.

Same framing discipline as the KV wire (length-prefixed frames, a
versioned HELLO that pins protocol version + store scope + page size,
refusal on any mismatch), with typed control ops::

    SUBMIT     start a generation; the server owns a seq-numbered
               event buffer for the request
    EVENTS     long-poll the buffer from the last ACKED sequence
               number — after a severed connection the client simply
               reconnects and re-polls from its ack, so a mid-stream
               RPC disconnect costs latency, never tokens
    CANCEL     cancel by request id
    DIGEST     chain-key routing digest (same payload the KV wire
               serves; proxied here so the router needs ONE plane)
    METRICS    pool metrics + transport stats snapshot
    AUDIT      cluster-wide KV invariant sweep (ISSUE 15)
    HEARTBEAT  liveness + load + RTT sample for the failure detector
    DRAIN      graceful drain: stop admissions, checkpoint active
               chains, hand streams off with a ``handoff`` marker
    PEERS      attach the host's federated KV tier to peer addresses
    FAULT      arm a chaos fault in the host process (test rigs only)

Robustness is the point, not the transport:

* Every op carries a DEADLINE (socket timeout = remaining budget).
* Failed IDEMPOTENT ops (DIGEST / METRICS / HEARTBEAT / AUDIT) retry
  with full-jitter exponential backoff (``RetryPolicy``). SUBMIT is
  NEVER auto-retried — a retried submit could double-admit; the router
  re-adopts through the recovery path instead (resume ≡ fresh
  re-admission of prompt + delivered tokens, the PR-10 contract).
  EVENTS is its own retry loop by construction (resume-from-ack).
* A phi-accrual-style failure detector distinguishes SLOW from DEAD:
  heartbeats that succeed but arrive late (or a suspicion value past
  the phi threshold) move a host to SUSPECT — the router de-prefers it
  and stops placing KV-streaming work on it but keeps its streams
  alive; only ``cluster_dead_ms`` without ANY successful beat (or the
  process exiting) declares DEAD and triggers the byte-gated recovery.

Chaos hooks (services/faults.py): ``cluster_rpc_delay_ms`` stalls the
server before each frame (a slow peer — must reach SUSPECT, never
DEAD), ``cluster_rpc_drop`` severs one control connection mid-request
(the event stream must resume from the last acked seq), and
``cluster{N}_hang`` makes host N swallow heartbeats while the process
lives (must be declared DEAD and recovered byte-identically).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import random
import socket
import socketserver
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Optional

from localai_tpu.services.faults import FAULTS
from localai_tpu.services.kv_wire import (WireError, _jdump, _jload,
                                          recv_frame, send_frame)

log = logging.getLogger(__name__)

RPC_VERSION = 1

# control ops (disjoint numbering from kv_wire on purpose: a client
# that dials the wrong port gets a typed refusal, not silent nonsense)
OP_HELLO = 32
OP_OK = 33
OP_ERR = 34
OP_SUBMIT = 35
OP_CANCEL = 36
OP_EVENTS = 37
OP_DIGEST = 38
OP_METRICS = 39
OP_AUDIT = 40
OP_HEARTBEAT = 41
OP_DRAIN = 42
OP_PEERS = 43
OP_FAULT = 44

OP_NAMES = {OP_HELLO: "hello", OP_SUBMIT: "submit", OP_CANCEL: "cancel",
            OP_EVENTS: "events", OP_DIGEST: "digest",
            OP_METRICS: "metrics", OP_AUDIT: "audit",
            OP_HEARTBEAT: "heartbeat", OP_DRAIN: "drain",
            OP_PEERS: "peers", OP_FAULT: "fault"}

# the retry matrix: ONLY read-only, side-effect-free ops may auto-retry
# on a transport failure. SUBMIT must never be retried (double-admit);
# CANCEL/DRAIN/PEERS/FAULT are issued once and re-driven by their
# caller; EVENTS is a resume-from-ack loop — its retry is explicit.
RETRYABLE_OPS = frozenset({OP_DIGEST, OP_METRICS, OP_HEARTBEAT, OP_AUDIT})

# server-side event buffer bound: a client that stops acking cannot
# pin unbounded history (the stream is failed instead)
MAX_BUFFERED_EVENTS = 16384


# --------------- retry policy ---------------


@dataclasses.dataclass
class RetryPolicy:
    """Full-jitter exponential backoff (AWS-style): attempt ``a`` sleeps
    ``uniform(0, min(cap, base * 2**a))``. Deterministic under an
    injected ``rng``; the schedule is pure so tests assert it."""

    base_ms: float = 50.0
    cap_ms: float = 2000.0
    attempts: int = 4          # total tries (1 first call + retries)

    def backoff_s(self, attempt: int, rng: Callable[[], float]) -> float:
        """Sleep before retry number ``attempt`` (0-based)."""
        span = min(self.cap_ms, self.base_ms * (2 ** attempt))
        return rng() * span / 1e3


# --------------- failure detector ---------------


class FailureDetector:
    """Phi-accrual-style heartbeat failure detector with hard bounds.

    ALIVE -> SUSPECT when the suspicion level phi crosses
    ``phi_suspect``, when no successful beat lands within
    ``suspect_ms``, or when the beats that DO land are slower than
    ``suspect_ms`` (RTT EWMA) — the slow-peer rung: answering late is
    degraded, not dead. SUSPECT is recoverable; a healthy beat returns
    the host to ALIVE.

    SUSPECT -> DEAD only after ``dead_ms`` without ANY successful beat
    (or an explicit ``declare_dead()`` — e.g. the process exited).
    DEAD is sticky: recovery is byte-gated and fires exactly once.

    phi uses the exponential inter-arrival model of the phi-accrual
    paper: ``phi = log10(e) * elapsed / mean_interval`` — suspicion
    grows continuously with silence, scaled by the OBSERVED cadence, so
    a detector configured for a slow heartbeat period does not cry wolf.
    """

    ALIVE = "alive"
    SUSPECT = "suspect"
    DEAD = "dead"

    def __init__(self, suspect_ms: float = 1000.0, dead_ms: float = 3000.0,
                 phi_suspect: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.suspect_ms = float(suspect_ms)
        self.dead_ms = float(dead_ms)
        self.phi_suspect = float(phi_suspect)
        self._clock = clock
        self._lock = threading.Lock()
        now = clock()
        self._last_ok = now
        self._mean_interval_s = 0.0    # EWMA of inter-beat gaps
        self._rtt_ewma_ms = 0.0
        self._beats = 0
        self._failures = 0
        self._dead = False

    # ---- inputs ----

    def beat(self, rtt_ms: float) -> None:
        """A successful heartbeat round-trip."""
        with self._lock:
            now = self._clock()
            gap = now - self._last_ok
            self._last_ok = now
            a = 0.2
            if self._beats:
                self._mean_interval_s = ((1 - a) * self._mean_interval_s
                                         + a * gap)
            self._rtt_ewma_ms = (rtt_ms if not self._beats
                                 else (1 - a) * self._rtt_ewma_ms
                                 + a * float(rtt_ms))
            self._beats += 1

    def failure(self) -> None:
        """A failed/timed-out probe (telemetry; the timers decide)."""
        with self._lock:
            self._failures += 1

    def declare_dead(self) -> None:
        """External hard evidence (process exited)."""
        with self._lock:
            self._dead = True

    # ---- outputs ----

    def phi(self) -> float:
        with self._lock:
            elapsed = self._clock() - self._last_ok
            mean = self._mean_interval_s
        if mean <= 0:
            return 0.0
        return 0.4342944819 * elapsed / mean      # log10(e) * t / mean

    def state(self) -> str:
        with self._lock:
            if self._dead:
                return self.DEAD
            elapsed_ms = (self._clock() - self._last_ok) * 1e3
            slow = self._beats > 0 and self._rtt_ewma_ms > self.suspect_ms
        if elapsed_ms >= self.dead_ms:
            with self._lock:
                self._dead = True
            return self.DEAD
        if (elapsed_ms >= self.suspect_ms or slow
                or self.phi() >= self.phi_suspect):
            return self.SUSPECT
        return self.ALIVE

    def snapshot(self) -> dict:
        with self._lock:
            return {"beats": self._beats, "failures": self._failures,
                    "rtt_ewma_ms": round(self._rtt_ewma_ms, 3),
                    "mean_interval_ms":
                        round(self._mean_interval_s * 1e3, 3),
                    "dead": self._dead}


# --------------- request / event (de)serialization ---------------


def req_to_dict(req) -> dict:
    """GenRequest -> JSON-safe dict. The control plane carries the text
    serving surface (prompt ids, sampling, stops, priority); multimodal
    vectors and prompt-cache paths stay host-local concerns."""
    p = dataclasses.asdict(req.params)
    p["logit_bias"] = {str(k): float(v)
                       for k, v in (p.get("logit_bias") or {}).items()}
    return {"prompt_ids": [int(t) for t in req.prompt_ids],
            "max_new_tokens": int(req.max_new_tokens),
            "stop_sequences": list(req.stop_sequences or []),
            "ignore_eos": bool(req.ignore_eos),
            "grammar": req.grammar or "",
            "priority": req.priority or "",
            "request_id": req.request_id,
            "params": p}


def req_from_dict(d: dict):
    from localai_tpu.engine import engine as eng
    from localai_tpu.engine import sampling

    p = dict(d.get("params") or {})
    p["logit_bias"] = {int(k): float(v)
                       for k, v in (p.pop("logit_bias", None) or {}).items()}
    return eng.GenRequest(
        prompt_ids=[int(t) for t in d["prompt_ids"]],
        params=sampling.SamplingParamsHost(**p),
        max_new_tokens=int(d.get("max_new_tokens", 256)),
        stop_sequences=list(d.get("stop_sequences") or []),
        ignore_eos=bool(d.get("ignore_eos", False)),
        grammar=d.get("grammar", ""),
        priority=d.get("priority", ""),
        request_id=d.get("request_id", ""))


def event_to_dict(ev) -> dict:
    d = {"t": int(ev.token_id), "x": ev.text, "lp": float(ev.logprob)}
    if ev.finish_reason is not None:
        d["fin"] = ev.finish_reason
    if ev.prompt_tokens:
        d["pt"] = int(ev.prompt_tokens)
    if ev.completion_tokens:
        d["ct"] = int(ev.completion_tokens)
    if ev.error is not None:
        d["err"] = str(ev.error)
    if ev.error_kind is not None:
        d["ek"] = str(ev.error_kind)
    if ev.retry_after_s:
        d["ra"] = float(ev.retry_after_s)
    if ev.token_ids:
        d["ts"] = [int(t) for t in ev.token_ids]
    if ev.logprobs:
        d["lps"] = [float(v) for v in ev.logprobs]
    return d


def event_from_dict(d: dict):
    from localai_tpu.engine import engine as eng

    return eng.StreamEvent(
        token_id=int(d.get("t", -1)), text=d.get("x", ""),
        logprob=float(d.get("lp", 0.0)), finish_reason=d.get("fin"),
        prompt_tokens=int(d.get("pt", 0)),
        completion_tokens=int(d.get("ct", 0)),
        error=d.get("err"), error_kind=d.get("ek"),
        retry_after_s=float(d.get("ra", 0.0)),
        token_ids=d.get("ts"), logprobs=d.get("lps"))


# --------------- client ---------------


class RpcClient:
    """One framed, reconnecting control connection with per-op
    deadlines and the idempotent-only retry matrix.

    Deadlines: each call computes an absolute budget; the socket
    timeout is re-armed to the REMAINING budget before every blocking
    step, so a slow server cannot stretch one op past its deadline.
    Retries: only ``RETRYABLE_OPS`` re-dial after a transport failure,
    sleeping a full-jitter backoff between attempts; a server-reported
    OP_ERR never retries (the server answered — retrying cannot help).
    Clock/sleep/rng are injectable so the schedule is unit-testable."""

    def __init__(self, address: str, scope: Optional[bytes] = None,
                 timeout_s: float = 2.0, connect_timeout_s: float = 2.0,
                 retry: Optional[RetryPolicy] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Callable[[], float] = random.random):
        host, _, port = address.rpartition(":")
        self.address = address
        self._addr = (host or "127.0.0.1", int(port))
        self.scope = scope              # None = adopt the server's
        self.timeout_s = float(timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.retry = retry or RetryPolicy()
        self._clock = clock
        self._sleep = sleep
        self._rng = rng
        self._lock = threading.Lock()
        self._sock = None
        self.hello: dict = {}
        self._stats_lock = threading.Lock()
        self.retries: dict = {}         # op name -> count
        self.timeouts: dict = {}        # op name -> count
        self.reconnects = 0

    # ---- transport ----

    def _connect_locked(self, deadline: float):
        budget = max(0.05, deadline - self._clock())
        s = socket.create_connection(
            self._addr, timeout=min(self.connect_timeout_s, budget))
        try:
            s.settimeout(max(0.05, deadline - self._clock()))
            hello = {"version": RPC_VERSION}
            if self.scope is not None:
                hello["scope"] = self.scope.hex()
            send_frame(s, OP_HELLO, _jdump(hello))
            op, payload = recv_frame(s)
            info = _jload(payload)
            if op != OP_OK:
                raise WireError(f"HELLO refused: {info}")
            if self.scope is None and info.get("scope"):
                self.scope = bytes.fromhex(info["scope"])
            self.hello = info
        except Exception:
            s.close()
            raise
        self._sock = s
        with self._stats_lock:
            self.reconnects += 1

    def _close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self):
        with self._lock:
            self._close_locked()

    def _roundtrip(self, op: int, payload: bytes, deadline: float) -> dict:
        """One send/recv on the (re)connected socket. Raises
        OSError/WireError on transport failure; WireError (non-retried)
        on a server OP_ERR."""
        with self._lock:
            try:
                if self._sock is None:
                    self._connect_locked(deadline)
                self._sock.settimeout(max(0.05, deadline - self._clock()))
                send_frame(self._sock, op, payload)
                rop, rpayload = recv_frame(self._sock)
            except (OSError, WireError):
                self._close_locked()
                raise
        body = _jload(rpayload)
        if rop == OP_ERR:
            raise RpcRefused(str(body.get("error", "?")), body)
        return body

    def call(self, op: int, obj: Optional[dict] = None,
             deadline_s: Optional[float] = None) -> dict:
        """One RPC with deadline + (idempotent-only) retry."""
        payload = _jdump(obj or {})
        budget = self.timeout_s if deadline_s is None else float(deadline_s)
        name = OP_NAMES.get(op, str(op))
        attempts = self.retry.attempts if op in RETRYABLE_OPS else 1
        last = None
        for attempt in range(attempts):
            deadline = self._clock() + budget
            try:
                return self._roundtrip(op, payload, deadline)
            except RpcRefused:
                raise               # the server answered: never retry
            except (OSError, WireError) as e:
                last = e
                if isinstance(e, socket.timeout):
                    with self._stats_lock:
                        self.timeouts[name] = self.timeouts.get(name, 0) + 1
                if attempt + 1 >= attempts:
                    break
                with self._stats_lock:
                    self.retries[name] = self.retries.get(name, 0) + 1
                self._sleep(self.retry.backoff_s(attempt, self._rng))
        raise last

    def stats(self) -> dict:
        with self._stats_lock:
            return {"retries": dict(self.retries),
                    "timeouts": dict(self.timeouts),
                    "reconnects": self.reconnects}

    # ---- convenience ops ----

    def submit(self, reqdict: dict, deadline_s: float = 10.0) -> dict:
        return self.call(OP_SUBMIT, {"req": reqdict}, deadline_s)

    def events(self, rid: str, ack: int, wait_ms: int = 250,
               deadline_s: Optional[float] = None) -> dict:
        if deadline_s is None:
            deadline_s = self.timeout_s + wait_ms / 1e3
        return self.call(OP_EVENTS, {"rid": rid, "ack": int(ack),
                                     "wait_ms": int(wait_ms)}, deadline_s)

    def cancel(self, rid: str) -> dict:
        return self.call(OP_CANCEL, {"rid": rid})

    def digest(self) -> dict:
        return self.call(OP_DIGEST)

    def metrics(self) -> dict:
        return self.call(OP_METRICS)

    def audit(self, drained: bool = False) -> dict:
        return self.call(OP_AUDIT, {"drained": bool(drained)})

    def heartbeat(self, deadline_s: Optional[float] = None) -> dict:
        return self.call(OP_HEARTBEAT, {"t": self._clock()}, deadline_s)

    def drain(self, deadline_s: float = 30.0) -> dict:
        return self.call(OP_DRAIN, {"exit": True}, deadline_s)

    def peers(self, addrs: list) -> dict:
        return self.call(OP_PEERS, {"addrs": list(addrs)})

    def fault(self, spec: str) -> dict:
        return self.call(OP_FAULT, {"spec": spec})


class RpcRefused(WireError):
    """The server answered with a typed error (NOT a transport failure
    — never retried)."""

    def __init__(self, msg: str, body: Optional[dict] = None):
        super().__init__(msg)
        self.body = body or {}


# --------------- server ---------------


class _Stream:
    """Server-side seq-numbered event buffer for one request. Events
    are retained until the client ACKS them, so a reconnecting client
    resumes exactly where it left off — mid-stream delivery survives a
    severed control connection."""

    def __init__(self, rid: str):
        self.rid = rid
        self.cond = threading.Condition()
        self.buf: list = []            # [(seq, dict)]
        self.seq = 0
        self.acked = 0
        self.done = False
        self.handoff = False
        self.failed = ""

    def append(self, evdict: dict):
        with self.cond:
            self.seq += 1
            self.buf.append((self.seq, evdict))
            if len(self.buf) > MAX_BUFFERED_EVENTS:
                self.failed = "event buffer overflow (client not acking)"
            self.cond.notify_all()

    def finish(self):
        with self.cond:
            self.done = True
            self.cond.notify_all()

    def poll(self, ack: int, wait_s: float) -> dict:
        with self.cond:
            self.acked = max(self.acked, int(ack))
            self.buf = [(s, d) for s, d in self.buf if s > self.acked]
            if not self.buf and not self.done and not self.failed:
                self.cond.wait(wait_s)
            evs = [dict(d, seq=s) for s, d in self.buf if s > ack]
            out = {"events": evs, "last": self.seq, "eof": self.done,
                   "handoff": self.handoff}
            if self.failed:
                out["failed"] = self.failed
            return out

    def drained(self, ack_grace_s: float) -> bool:
        """True once every event was delivered AND acked."""
        deadline = time.monotonic() + ack_grace_s
        while time.monotonic() < deadline:
            with self.cond:
                if self.done and self.acked >= self.seq:
                    return True
            time.sleep(0.02)
        return False


class _RpcHandler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: ClusterHostServer = self.server.rpc  # type: ignore[attr-defined]
        hello = False
        try:
            while True:
                op, payload = recv_frame(self.request)
                if FAULTS.active:
                    v = FAULTS.take("cluster_rpc_delay_ms")
                    if v is not None:
                        # chaos: a slow peer — every frame stalls, but
                        # every frame is ANSWERED (SUSPECT, never DEAD)
                        time.sleep(int(v) / 1e3)
                    if FAULTS.take("cluster_rpc_drop") is not None:
                        # chaos: sever the control connection with no
                        # reply — the event stream must resume from the
                        # last acked seq on the client's reconnect
                        try:
                            self.request.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass
                        return
                if op == OP_HELLO:
                    hello = srv._handle_hello(self.request, payload)
                    continue
                if not hello:
                    send_frame(self.request, OP_ERR,
                               _jdump({"error": "HELLO required first"}))
                    return
                if not srv._dispatch(self.request, op, payload):
                    return
        except (WireError, OSError):
            pass


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class ClusterHostServer:
    """The control-plane server for ONE cluster host: wraps a
    ``ClusterHost`` (EnginePool + KV wire server) and serves the typed
    ops above. Runs wherever the host runs — its own process under
    ``scripts/cluster_host.py`` (cluster_mode=process) or in-process in
    unit tests (the protocol doesn't care)."""

    def __init__(self, host, bind: str = "127.0.0.1", port: int = 0):
        self.host = host
        self._bind = (bind, int(port))
        self.address = ""
        self._srv = None
        self._thread = None
        self._lock = threading.Lock()
        self._streams: dict = {}
        self.draining = False
        self.exit_event = threading.Event()
        self._hb_seq = 0
        self.submits = 0
        self.drains = 0

    # ---- lifecycle ----

    def start(self) -> str:
        self._srv = _Server(self._bind, _RpcHandler)
        self._srv.rpc = self        # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="cluster-rpc", daemon=True)
        self._thread.start()
        h, p = self._srv.server_address[:2]
        self.address = f"{h}:{p}"
        log.info("cluster rpc server host=%d (%s) listening on %s",
                 self.host.host_id, self.host.role, self.address)
        return self.address

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None

    # ---- HELLO ----

    def _scopes(self) -> tuple:
        store = self.host.pool._shared.store
        pc = self.host.pool._engines[0]._pcache
        return store, pc

    def _handle_hello(self, sock, payload) -> bool:
        req = _jload(payload)
        store, pc = self._scopes()
        if int(req.get("version", -1)) != RPC_VERSION:
            send_frame(sock, OP_ERR, _jdump(
                {"error": f"rpc version {req.get('version')} != "
                          f"{RPC_VERSION}", "version": RPC_VERSION}))
            return False
        if req.get("scope") is not None \
                and req["scope"] != store.scope.hex():
            send_frame(sock, OP_ERR, _jdump(
                {"error": "scope mismatch (different model or layout)",
                 "scope": store.scope.hex()}))
            return False
        send_frame(sock, OP_OK, _jdump(
            {"version": RPC_VERSION, "host": self.host.host_id,
             "role": self.host.role, "pid": os.getpid(),
             "scope": store.scope.hex(),
             "chain_scope": pc.scope.hex() if pc is not None else "",
             "page_size": store.page_size,
             "kv": self.host.address}))
        return True

    # ---- dispatch ----

    def _dispatch(self, sock, op: int, payload: bytes) -> bool:
        if op == OP_HEARTBEAT:
            if FAULTS.active and FAULTS.value(
                    f"cluster{self.host.host_id}_hang") is not None:
                # chaos: the host process LIVES but stops answering
                # heartbeats — the detector must walk SUSPECT -> DEAD
                # and the router must recover byte-identically
                return True
            return self._reply(sock, self._heartbeat(_jload(payload)))
        if op == OP_SUBMIT:
            return self._handle_submit(sock, _jload(payload))
        if op == OP_EVENTS:
            return self._handle_events(sock, _jload(payload))
        if op == OP_CANCEL:
            rid = _jload(payload).get("rid", "")
            self.host.cancel(rid)
            return self._reply(sock, {"cancelled": rid})
        if op == OP_DIGEST:
            d = (self.host.server.digest()
                 if self.host.server is not None else {"keys": []})
            return self._reply(sock, d)
        if op == OP_METRICS:
            return self._reply(sock, self.host.metrics_snapshot())
        if op == OP_AUDIT:
            drained = bool(_jload(payload).get("drained"))
            return self._reply(sock,
                               self.host.kv_audit_sweep(drained=drained))
        if op == OP_DRAIN:
            want_exit = bool(_jload(payload).get("exit", True))
            t = threading.Thread(target=self.drain,
                                 kwargs={"exit_after": want_exit},
                                 name="cluster-drain", daemon=True)
            t.start()
            return self._reply(sock, {"draining": True})
        if op == OP_PEERS:
            addrs = _jload(payload).get("addrs") or []
            self.host.connect_peers(addrs)
            return self._reply(sock, {"peers": len(addrs)})
        if op == OP_FAULT:
            # chaos control seam for test rigs: arm the HOST process's
            # fault table remotely (bench drives slow/hang phases here)
            spec = _jload(payload).get("spec", "")
            if spec == "reset":
                FAULTS.reset()
            else:
                FAULTS.configure(spec)
            return self._reply(sock, {"armed": spec})
        send_frame(sock, OP_ERR, _jdump({"error": f"unknown op {op}"}))
        return True

    def _reply(self, sock, obj: dict) -> bool:
        send_frame(sock, OP_OK, _jdump(obj))
        return True

    def _heartbeat(self, req: dict) -> dict:
        with self._lock:
            self._hb_seq += 1
            seq = self._hb_seq
        return {"t": req.get("t"), "seq": seq,
                "load": self.host.load(1),
                "active": self.host.pool.num_active,
                "draining": self.draining}

    # ---- streaming ----

    def _handle_submit(self, sock, body: dict) -> bool:
        if self.draining:
            send_frame(sock, OP_ERR, _jdump(
                {"error": "host draining", "draining": True}))
            return True
        try:
            req = req_from_dict(body["req"])
        except Exception as e:
            send_frame(sock, OP_ERR, _jdump(
                {"error": f"bad request: {type(e).__name__}: {e}"}))
            return True
        stream = _Stream(req.request_id)
        with self._lock:
            self._streams[req.request_id] = stream
            self.submits += 1
        out = self.host.submit(req)
        t = threading.Thread(target=self._pump, args=(out, stream),
                             name=f"rpc-pump-{req.request_id[:8]}",
                             daemon=True)
        t.start()
        return self._reply(sock, {"rid": req.request_id, "seq0": 0})

    def _pump(self, out: "queue.Queue", stream: _Stream):
        while True:
            ev = out.get()
            if ev is None:
                stream.finish()
                return
            stream.append(event_to_dict(ev))

    def _handle_events(self, sock, body: dict) -> bool:
        rid = body.get("rid", "")
        with self._lock:
            stream = self._streams.get(rid)
        if stream is None:
            send_frame(sock, OP_ERR, _jdump(
                {"error": f"unknown stream {rid!r}"}))
            return True
        wait_s = min(2.0, max(0.0, int(body.get("wait_ms", 250)) / 1e3))
        out = stream.poll(int(body.get("ack", 0)), wait_s)
        if out["eof"] and out["last"] <= stream.acked:
            with self._lock:            # fully delivered + acked: GC
                self._streams.pop(rid, None)
        return self._reply(sock, out)

    # ---- graceful drain (SIGTERM / OP_DRAIN) ----

    def drain(self, grace_s: float = 10.0, linger_s: float = 2.0,
              exit_after: bool = True) -> dict:
        """The clean half of the crash path: stop admissions, eject
        every active stream at a known point (its delivered tokens ARE
        the handoff state — resume ≡ fresh re-admission), checkpoint
        chains to the host tier where the KV wire serves them, and wait
        for clients to ack before signalling exit. The ``handoff``
        marker (instead of ``eof``) tells the router-side puller to
        re-adopt the continuation on a sibling."""
        with self._lock:
            if self.draining:
                return {"draining": True}
            self.draining = True
            self.drains += 1
            streams = [s for s in self._streams.values()
                       if not s.done]
        log.info("cluster host %d: draining (%d active streams)",
                 self.host.host_id, len(streams))
        for s in streams:
            s.handoff = True
            self.host.cancel(s.rid)
        handed = sum(1 for s in streams if s.drained(grace_s))
        # release-time checkpointing retains each ejected chain in the
        # host tier asynchronously; linger so the adopting sibling can
        # stream it off this process's KV wire before we exit
        if linger_s > 0:
            time.sleep(linger_s)
        out = {"streams": len(streams), "handed_off": handed}
        log.info("cluster host %d: drain done %s", self.host.host_id, out)
        if exit_after:
            self.exit_event.set()
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"address": self.address, "submits": self.submits,
                    "streams_open": len(self._streams),
                    "draining": self.draining, "drains": self.drains}


# --------------- remote host handle ---------------


class RemoteHostHandle:
    """A cluster host that lives behind the control plane — possibly in
    another PROCESS. Presents the same facade as the in-process
    ``ClusterHost`` (submit / cancel / metrics_snapshot / chain_keys /
    kv_audit_sweep / load / alive), so ``ClusterRouter`` is agnostic to
    whether a host is a thread or a PID.

    Liveness is the handle's own job: a heartbeat thread probes on
    ``heartbeat_ms`` cadence (idempotent — retries with backoff inside
    the deadline), feeds the phi-accrual detector, and on DEAD aborts
    every live stream so its pullers fail over through
    ``on_stream_lost(req, emitted_ids, reason)`` — the router installs
    that callback and re-adopts each continuation on a sibling.

    Token delivery: one puller thread per request long-polls EVENTS
    with the last ACKED seq; a transient disconnect (severed socket,
    chaos ``cluster_rpc_drop``) reconnects and resumes from the ack —
    no token is ever delivered twice or dropped. SUBMIT itself is never
    auto-retried."""

    remote = True

    def __init__(self, control_address: str, proc=None,
                 host_id: int = 0, role: str = "both",
                 scope: Optional[bytes] = None,
                 heartbeat_ms: int = 250, suspect_ms: int = 1000,
                 dead_ms: int = 3000, rpc_timeout_ms: int = 2000,
                 retry: Optional[RetryPolicy] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.control_address = control_address
        self.proc = proc
        self.host_id = int(host_id)
        self.role = role
        self.address = ""               # kv wire address (from HELLO)
        self.chain_scope = b""
        self.page_size = 0
        self.heartbeat_s = max(0.02, heartbeat_ms / 1e3)
        self.rpc_timeout_s = max(0.1, rpc_timeout_ms / 1e3)
        # a heartbeat must be allowed to finish SLOWLY without dying:
        # its deadline sits between the suspect and dead bounds so a
        # delayed-but-answering host lands beats (SUSPECT), while a
        # hung one times out every probe until dead_ms declares it
        self.heartbeat_deadline_s = max(self.rpc_timeout_s,
                                        1.6 * suspect_ms / 1e3)
        self.detector = FailureDetector(suspect_ms=suspect_ms,
                                        dead_ms=dead_ms, clock=clock)
        self._retry = retry or RetryPolicy()
        self._clock = clock
        self._ctl = RpcClient(control_address, scope=scope,
                              timeout_s=self.rpc_timeout_s,
                              retry=self._retry, clock=clock)
        self._hb = RpcClient(control_address, scope=scope,
                             timeout_s=self.heartbeat_deadline_s,
                             retry=RetryPolicy(attempts=1), clock=clock)
        self._lock = threading.Lock()
        self._pullers: dict = {}        # rid -> _RemoteStream
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._last_load = 0.0
        self._last_rtt_ms = 0.0
        self.on_stream_lost: Optional[Callable] = None
        self.on_state_change: Optional[Callable] = None
        self._reported_state = FailureDetector.ALIVE
        self.killed = False

    # ---- construction ----

    @classmethod
    def spawn(cls, spec: dict, script: str = "", timeout_s: float = 180.0,
              env: Optional[dict] = None, **kw) -> "RemoteHostHandle":
        """Spawn ``scripts/cluster_host.py`` with ``spec`` and attach to
        the control address it announces on stdout. The child inherits
        the environment (so LOCALAI_FAULTS / JAX_PLATFORMS propagate,
        same contract as BackendProcess)."""
        if not script:
            script = os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                "scripts", "cluster_host.py")
        f = tempfile.NamedTemporaryFile("w", suffix=".json", delete=False,
                                        prefix="cluster-host-")
        json.dump(spec, f)
        f.close()
        proc = subprocess.Popen(
            [sys.executable, script, "--spec", f.name],
            stdout=subprocess.PIPE, stderr=None,
            env=dict(env) if env is not None else None, text=True)
        ready = None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"cluster host process exited rc={proc.returncode} "
                        f"before READY")
                time.sleep(0.05)
                continue
            line = line.strip()
            if line.startswith("{") and '"ready"' in line:
                ready = json.loads(line)
                break
        if ready is None:
            proc.kill()
            raise RuntimeError("cluster host process never became ready")
        # keep draining child stdout so it can't block on a full pipe
        threading.Thread(target=_drain_pipe, args=(proc.stdout,),
                         daemon=True).start()
        h = cls(ready["control"], proc=proc,
                host_id=int(spec.get("host_id", 0)),
                role=spec.get("role", "both"), **kw)
        return h

    # ---- ClusterHost facade ----

    def start(self, precompile: bool = False) -> str:
        # the first real op performs HELLO lazily; force it now so the
        # kv address and scopes are known before routing begins. The
        # roundtrip is also the detector's FIRST beat: monitoring
        # starts here, not at construction, so a sibling's slow
        # build/precompile between spawn() and start() cannot count
        # as silence and walk a fresh host straight to sticky DEAD.
        t0 = self._clock()
        hb = self._ctl.heartbeat(deadline_s=self.rpc_timeout_s)
        del hb
        self.detector.beat((self._clock() - t0) * 1e3)
        hello = self._ctl.hello
        self.address = hello.get("kv", "")
        self.role = hello.get("role", self.role)
        self.chain_scope = bytes.fromhex(hello.get("chain_scope", "") or "")
        self.page_size = int(hello.get("page_size", 0))
        self.pid = hello.get("pid")
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"hb-host{self.host_id}", daemon=True)
        self._hb_thread.start()
        return self.address

    def connect_peers(self, addresses: list):
        addrs = [a for a in addresses if a and a != self.address]
        if addrs:
            self._ctl.peers(addrs)

    def submit(self, req) -> "queue.Queue":
        self._ctl.submit(req_to_dict(req))
        puller = _RemoteStream(self, req)
        with self._lock:
            self._pullers[req.request_id] = puller
        puller.start()
        return req.out

    def cancel(self, rid: str):
        try:
            self._ctl.cancel(rid)
        except (OSError, WireError):
            pass

    def metrics_snapshot(self) -> dict:
        snap = self._ctl.metrics()
        snap.setdefault("rpc", {})
        snap["rpc"]["client"] = self.rpc_stats()
        return snap

    def kv_debug(self) -> dict:
        try:
            return self.metrics_snapshot().get("kv_debug", {})
        except (OSError, WireError):
            return {}

    def kv_audit_sweep(self, drained: bool = False) -> dict:
        return self._ctl.audit(drained=drained)

    def chain_keys(self, ids) -> list:
        """Pure chain hashing (PR-2 block hashes are location-
        independent): the handle computes the same keys the remote
        host's prefix cache would, from the HELLO-pinned scope."""
        if not self.chain_scope or not self.page_size:
            return []
        from localai_tpu.ops import kvcache

        pg = self.page_size
        parent = kvcache.PAGE_HASH_ROOT
        out = []
        for i in range(len(ids) // pg):
            parent = kvcache.page_chain_hash(
                parent, ids[i * pg:(i + 1) * pg], self.chain_scope)
            out.append(parent)
        return out

    def load(self, rank: int = 1) -> float:
        return self._last_load

    def digest(self) -> dict:
        return self._ctl.digest()

    @property
    def state(self) -> str:
        if self.proc is not None and self.proc.poll() is not None:
            self.detector.declare_dead()
        return self.detector.state()

    @property
    def alive(self) -> bool:
        return self.state != FailureDetector.DEAD

    # ---- heartbeating ----

    def _heartbeat_loop(self):
        while not self._hb_stop.wait(self.heartbeat_s):
            t0 = self._clock()
            try:
                r = self._hb.heartbeat(deadline_s=self.heartbeat_deadline_s)
                rtt = (self._clock() - t0) * 1e3
                self.detector.beat(rtt)
                with self._lock:
                    self._last_load = float(r.get("load", 0.0))
                    self._last_rtt_ms = rtt
            except (OSError, WireError):
                self.detector.failure()
            st = self.state
            if st != self._reported_state:
                prev, self._reported_state = self._reported_state, st
                log.warning("cluster host %d: %s -> %s (phi=%.2f)",
                            self.host_id, prev, st, self.detector.phi())
                if self.on_state_change is not None:
                    try:
                        self.on_state_change(self, prev, st)
                    except Exception:
                        log.exception("on_state_change failed")
            if st == FailureDetector.DEAD:
                self.abort_streams("crash")
                return

    def heartbeat_telemetry(self) -> dict:
        with self._lock:
            return {"state": self._reported_state,
                    "rtt_ms": round(self._last_rtt_ms, 3),
                    "load": self._last_load,
                    **self.detector.snapshot()}

    def rpc_stats(self) -> dict:
        """Fold the control + heartbeat + per-stream clients' retry/
        timeout counters (-> localai_cluster_rpc_{retries,timeouts})."""
        out = {"retries": {}, "timeouts": {}, "reconnects": 0}
        with self._lock:
            clients = [self._ctl, self._hb] + \
                [p.rpc for p in self._pullers.values()]
        for c in clients:
            s = c.stats()
            for k in ("retries", "timeouts"):
                for op, n in s[k].items():
                    out[k][op] = out[k].get(op, 0) + n
            out["reconnects"] += s["reconnects"]
        return out

    # ---- failure / drain handling ----

    def abort_streams(self, reason: str):
        with self._lock:
            pullers = list(self._pullers.values())
        for p in pullers:
            p.abort(reason)

    def _stream_done(self, rid: str):
        with self._lock:
            self._pullers.pop(rid, None)

    def drain(self, deadline_s: float = 30.0) -> dict:
        return self._ctl.drain(deadline_s=deadline_s)

    def fault(self, spec: str) -> dict:
        """Arm (or ``"reset"``) the HOST process's chaos table over
        OP_FAULT — how bench drives slow/hang phases in a real child."""
        return self._ctl.fault(spec)

    def kill(self):
        """Chaos: SIGKILL the host process (the crash the control plane
        exists for). In-proc handles implement the PR-17 loop-death
        kill; a real process loses its KV wire too — recovery degrades
        to re-prefill of (prompt + delivered), still byte-identical."""
        self.killed = True
        if self.proc is not None:
            self.proc.kill()

    def terminate(self):
        if self.proc is not None:
            self.proc.terminate()

    def shutdown(self):
        self._hb_stop.set()
        self.abort_streams("shutdown")
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self._ctl.close()
        self._hb.close()


class _RemoteStream:
    """Client-side puller for one remote request: long-polls EVENTS
    with the last acked seq, feeds the request's own out queue, and
    tracks the delivered token ids (the handoff/recovery state)."""

    def __init__(self, handle: RemoteHostHandle, req):
        self.h = handle
        self.req = req
        self.rpc = RpcClient(handle.control_address,
                             scope=handle._ctl.scope,
                             timeout_s=handle.rpc_timeout_s,
                             retry=RetryPolicy(attempts=1),
                             clock=handle._clock)
        self.ack = 0
        self.emitted: list = []
        self._abort = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name=f"pull-{self.req.request_id[:8]}",
            daemon=True)
        self._thread.start()

    def abort(self, reason: str):
        self._abort = reason

    def _lost(self, reason: str):
        self.h._stream_done(self.req.request_id)
        cb = self.h.on_stream_lost
        if cb is not None:
            cb(self.h, self.req, list(self.emitted), reason)
        else:
            # no router to adopt us: fail the stream honestly
            from localai_tpu.engine import engine as eng

            self.req.out.put(eng.StreamEvent(
                token_id=-1, text="", logprob=0.0,
                error=f"cluster host {self.h.host_id} lost ({reason})",
                error_kind="stall"))
            self.req.out.put(None)

    def _run(self):
        backoff = 0
        while True:
            if self._abort:
                self._lost(self._abort)
                return
            try:
                r = self.rpc.events(self.req.request_id, self.ack,
                                    wait_ms=250)
                backoff = 0
            except RpcRefused as e:
                if self._abort:
                    self._lost(self._abort)
                else:
                    self._lost(f"refused: {e}")
                return
            except (OSError, WireError):
                # transient disconnect: reconnect + resume from ack —
                # unless the host is gone, in which case fail over
                if self.h.state == FailureDetector.DEAD or self._abort:
                    self._lost(self._abort or "crash")
                    return
                time.sleep(self.h._retry.backoff_s(
                    min(backoff, 5), random.random))
                backoff += 1
                continue
            for ed in r.get("events", ()):
                seq = int(ed.get("seq", 0))
                if seq <= self.ack:
                    continue            # duplicate after a resume
                self.ack = seq
                ev = event_from_dict(ed)
                if ev.token_ids:
                    self.emitted.extend(int(t) for t in ev.token_ids)
                elif ev.token_id >= 0:
                    self.emitted.append(int(ev.token_id))
                self.req.out.put(ev)
            if r.get("failed"):
                self._lost(str(r["failed"]))
                return
            if self.ack >= int(r.get("last", 0)):
                if r.get("handoff"):
                    # graceful drain: delivered tokens are the handoff
                    # state; one final ack releases the server buffer
                    try:
                        self.rpc.events(self.req.request_id, self.ack,
                                        wait_ms=0)
                    except (OSError, WireError):
                        pass
                    self._lost("drain")
                    return
                if r.get("eof"):
                    try:
                        self.rpc.events(self.req.request_id, self.ack,
                                        wait_ms=0)
                    except (OSError, WireError):
                        pass
                    self.h._stream_done(self.req.request_id)
                    self.req.out.put(None)
                    self.rpc.close()
                    return


def _drain_pipe(pipe):
    try:
        for _ in pipe:
            pass
    except Exception:
        pass
