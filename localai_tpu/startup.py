"""Application startup: wire configs, loader, watchdog, services, HTTP app.

Parity with the reference's startup sequence (reference: core/startup/
startup.go:20-183 — dir creation, model install, config load, watchdog
start, warmup loads, shutdown hook).
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

from localai_tpu.capabilities import Capabilities, build_model_options
from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.model_config import scan_models_dir
from localai_tpu.modelmgr.loader import ModelLoader
from localai_tpu.modelmgr.watchdog import WatchDog

log = logging.getLogger("localai_tpu.startup")


def startup(app_config: AppConfig):
    """Returns (Capabilities, ModelLoader, gallery_service)."""
    os.makedirs(app_config.models_path, exist_ok=True)

    if app_config.preload_models:
        from localai_tpu.gallery.preload import install_models

        install_models(app_config.preload_models, app_config.models_path,
                       app_config.galleries)

    configs = scan_models_dir(app_config.models_path)
    log.info("loaded %d model configs from %s", len(configs), app_config.models_path)

    loader = ModelLoader(single_active=app_config.single_active_backend)
    if app_config.enable_watchdog_idle or app_config.enable_watchdog_busy:
        wd = WatchDog(
            loader,
            busy_timeout_s=app_config.watchdog_busy_timeout_s,
            idle_timeout_s=app_config.watchdog_idle_timeout_s,
            check_busy=app_config.enable_watchdog_busy,
            check_idle=app_config.enable_watchdog_idle,
        )
        loader.watchdog = wd
        wd.start()

    caps = Capabilities(app_config, loader, configs)

    # warmup loads (reference: LoadToMemory, startup.go:148-176)
    for name in app_config.load_to_memory:
        mc = caps.resolve(name)
        try:
            caps._load(mc)
            log.info("warmed up model %s", name)
        except Exception:
            log.exception("warmup load failed for %s", name)

    from localai_tpu.services.gallery_service import GalleryService

    gallery_service = GalleryService(app_config, caps)
    gallery_service.start()

    # dynamic config hot-reload (reference: config_file_watcher.go:29-43)
    if app_config.dynamic_config_dir:
        from localai_tpu.config.watcher import ConfigWatcher

        ConfigWatcher(app_config, loader).start()
    return caps, loader, gallery_service


async def serve(app_config: AppConfig):
    from localai_tpu.api.app import build_app, run_app

    caps, loader, gallery_service = startup(app_config)
    app = build_app(caps, app_config, gallery_service)
    runner = await run_app(app, app_config.address)
    log.info("localai-tpu listening on %s", app_config.address)
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await runner.cleanup()
        gallery_service.shutdown()
        loader.stop_all()
