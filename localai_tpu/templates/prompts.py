"""Prompt templating.

Parity with the reference's template layer (reference: pkg/templates/
cache.go:40 Go text/template + sprig; multimodal placeholder injection
pkg/templates/multimodal.go; per-message evaluation + join
core/http/endpoints/openai/chat.go:296-441) — re-based on Jinja2, the
ecosystem standard for HF chat templates, so `use_tokenizer_template`
(vLLM-backend parity, backend.proto UseTokenizerTemplate) is the same
engine as explicit templates.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jinja2

_env = jinja2.Environment(
    loader=jinja2.BaseLoader(),
    undefined=jinja2.ChainableUndefined,  # missing fields render empty, like text/template
    trim_blocks=True,
    lstrip_blocks=True,
    keep_trailing_newline=True,
)
_cache: dict = {}


def render(template: str, **values) -> str:
    tpl = _cache.get(template)
    if tpl is None:
        tpl = _env.from_string(template)
        if len(_cache) < 512:
            _cache[template] = tpl
    return tpl.render(**values)


@dataclasses.dataclass
class ChatMessageData:
    """Per-message template inputs (reference: chat.go:311-397)."""
    system_prompt: str = ""
    role: str = ""
    role_name: str = ""
    content: str = ""
    function_call: Any = None
    function_name: str = ""
    last_message: bool = False
    index: int = 0


DEFAULT_CHAT_MESSAGE = "{% if Role %}{{ Role }}: {% endif %}{{ Content }}"


def render_chat_message(template: str, msg: ChatMessageData) -> str:
    return render(
        template,
        SystemPrompt=msg.system_prompt,
        Role=msg.role,
        RoleName=msg.role_name,
        Content=msg.content,
        FunctionCall=msg.function_call,
        FunctionName=msg.function_name,
        LastMessage=msg.last_message,
        MessageIndex=msg.index,
        # lowercase aliases
        role=msg.role, content=msg.content,
    )


def render_chat_prompt(template: str, joined_messages: str, system_prompt: str = "",
                       functions: Optional[list] = None, suppressed: bool = False) -> str:
    return render(
        template,
        Input=joined_messages,
        SystemPrompt=system_prompt,
        Functions=functions or [],
        SuppressSystemPrompt=suppressed,
        input=joined_messages,
    )


def render_completion(template: str, prompt: str, system_prompt: str = "") -> str:
    return render(template, Input=prompt, SystemPrompt=system_prompt, input=prompt)


def render_edit(template: str, instruction: str, prompt: str) -> str:
    return render(template, Instruction=instruction, Input=prompt,
                  instruction=instruction, input=prompt)


def multimodal_placeholders(template: str, text: str, n_images: int = 0,
                            n_audios: int = 0, n_videos: int = 0,
                            img_offset: int = 0, audio_offset: int = 0,
                            vid_offset: int = 0) -> str:
    """Inject [img-N]/[audio-N]/[vid-N] placeholders before the text
    (reference: pkg/templates/multimodal.go:24-26 default template).

    N is GLOBAL across the whole chat (offsets = media count in earlier
    messages): the backend resolves [vid-N] against one request-wide
    media list, so per-message numbering would alias every message's
    first video onto index 0."""
    imgs = "".join(f"[img-{i}]"
                   for i in range(img_offset, img_offset + n_images))
    auds = "".join(f"[audio-{i}]"
                   for i in range(audio_offset, audio_offset + n_audios))
    vids = "".join(f"[vid-{i}]"
                   for i in range(vid_offset, vid_offset + n_videos))
    if template:
        return render(template, Text=text, ImagesCount=n_images, AudiosCount=n_audios,
                      VideosCount=n_videos, Images=imgs, Audios=auds, Videos=vids)
    out = auds + imgs + vids
    if out and text:
        out += "\n"
    return out + text


def apply_tokenizer_template(tokenizer, messages: list, add_generation_prompt: bool = True,
                             tools: Optional[list] = None) -> str:
    """use_tokenizer_template path: delegate to the HF chat template."""
    kwargs = dict(tokenize=False, add_generation_prompt=add_generation_prompt)
    if tools:
        kwargs["tools"] = tools
    return tokenizer.apply_chat_template(messages, **kwargs)
