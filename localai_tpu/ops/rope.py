"""Rotary position embeddings with the scaling families users of the
reference expect (none/linear/yarn/llama3 — reference plumbs these knobs
end-to-end: backend.proto:226-231, grpc-server.cpp:2310-2330).

Uses the HF "rotate_half" convention (split head_dim in halves) so weights
converted from HF checkpoints work unmodified.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _base_inv_freq(cfg) -> np.ndarray:
    hd = cfg.head_dim_
    return 1.0 / (cfg.rope_theta ** (np.arange(0, hd, 2, dtype=np.float64) / hd))


def _scaled_inv_freq(cfg) -> np.ndarray:
    """Static (trace-time) inverse frequencies with scaling applied."""
    inv_freq = _base_inv_freq(cfg)
    t = cfg.rope_scaling_type
    if t in ("none", "default") or cfg.rope_scaling_factor == 1.0 and t != "llama3":
        return inv_freq
    if t == "linear":
        return inv_freq / cfg.rope_scaling_factor
    if t == "llama3":
        # Llama-3.1 frequency-dependent NTK scaling.
        low_wl = cfg.rope_original_max_position / cfg.rope_low_freq_factor
        high_wl = cfg.rope_original_max_position / cfg.rope_high_freq_factor
        wavelen = 2 * np.pi / inv_freq
        scaled = inv_freq / cfg.rope_scaling_factor
        smooth = (cfg.rope_original_max_position / wavelen - cfg.rope_low_freq_factor) / (
            cfg.rope_high_freq_factor - cfg.rope_low_freq_factor
        )
        smooth = np.clip(smooth, 0.0, 1.0)
        mid = (1 - smooth) * scaled + smooth * inv_freq
        out = np.where(wavelen < high_wl, inv_freq, np.where(wavelen > low_wl, scaled, mid))
        return out
    if t == "yarn":
        # YaRN: interpolate low-freq dims, keep high-freq dims (beta ramp).
        hd = cfg.head_dim_
        factor = cfg.rope_scaling_factor
        beta_fast, beta_slow = 32.0, 1.0
        orig = cfg.rope_original_max_position

        def correction_dim(num_rot):
            return hd * np.log(orig / (num_rot * 2 * np.pi)) / (2 * np.log(cfg.rope_theta))

        low = np.floor(correction_dim(beta_fast))
        high = np.ceil(correction_dim(beta_slow))
        low, high = max(low, 0), min(high, hd - 1)
        ramp = np.clip((np.arange(hd // 2, dtype=np.float64) - low) / max(high - low, 1e-3), 0, 1)
        mask = 1 - ramp
        return inv_freq / factor * (1 - mask) + inv_freq * mask
    raise ValueError(f"unknown rope scaling type: {t}")


def rope_frequencies(cfg, positions: jax.Array):
    """positions [B, T] -> (sin, cos) each [B, T, head_dim] (half-duplicated)."""
    inv_freq = jnp.asarray(_scaled_inv_freq(cfg), jnp.float32)
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq[None, None, :]  # [B,T,hd/2]
    # yarn attention temperature scaling
    mscale = 1.0
    if cfg.rope_scaling_type == "yarn" and cfg.rope_scaling_factor > 1.0:
        mscale = 0.1 * np.log(cfg.rope_scaling_factor) + 1.0
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.sin(emb) * mscale, jnp.cos(emb) * mscale


def rope_delta_terms(cfg, delta: jax.Array):
    """delta positions [...] -> (sin, cos) each [..., head_dim] for a PURE
    rotation by ``delta * inv_freq`` — no yarn attention-temperature
    mscale. RoPE rotations compose (angle is linear in position for every
    scaling family, which only modifies inv_freq), so cached keys written
    at position a become keys at position b when rotated by (b - a); the
    mscale magnitude factor is already baked into the cached keys and must
    not be applied twice. Used by the self-extend KV re-rotation
    (reference: grpc-server.cpp:1916-1927 llama_kv_cache_seq_div/add)."""
    inv_freq = jnp.asarray(_scaled_inv_freq(cfg), jnp.float32)
    freqs = delta.astype(jnp.float32)[..., None] * inv_freq
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.sin(emb), jnp.cos(emb)


def rotate_by_delta(x: jax.Array, sin: jax.Array, cos: jax.Array) -> jax.Array:
    """x [..., hd]; sin/cos broadcastable [..., hd]. rotate_half rotation."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    rotated = jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)
    return (x * cos + rotated * sin).astype(dtype)


def apply_rope(x: jax.Array, sin: jax.Array, cos: jax.Array) -> jax.Array:
    """x [B, T, H, hd]; sin/cos [B, T, hd]. HF rotate_half convention."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    out = x * cos[:, :, None, :] + rotated * sin[:, :, None, :]
    return out.astype(dtype)
