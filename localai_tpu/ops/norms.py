"""Normalization ops (fp32 accumulation, cast back to activation dtype)."""

import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-5):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jnp.reciprocal(jnp.sqrt(var + eps)) * weight.astype(jnp.float32)
    return out.astype(dtype)


def layer_norm(x, weight, bias, eps: float = 1e-5):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    out = out * weight.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(dtype)
