"""Attention ops: jnp implementations (XLA-fused; production path).

THE load-bearing design rule here (measured on the serving chip, r3):
attention NEVER reads cache rows written in the same step. Reading the
freshly-scattered rows forces XLA to materialize the scattered layer as
a fresh buffer before the read (+~8 ms/step on the 1B bench config —
2x the whole model's matmul time); attending over the PRE-update rows
plus the new keys/values held in registers makes the KV scatter fuse
into the in-place cache update (measured free) and cuts the decode step
from ~11.5 to ~5 ms. Hence the *_append variants below.

GQA is computed with grouped einsums — queries reshaped to
[.., KV, G, hd] against un-repeated keys — NOT by materializing
jnp.repeat(k, G) (which multiplies decode HBM traffic by G; measured 8x
slowdown on a 1B model at G=8).

Sequence-parallel long-context attention lives in
localai_tpu/parallel/ring_attention.py. Pure-jnp also means every test
runs hermetically on the 8-device CPU mesh.

Role parity: this is the attention inside the reference's hot loop
(llama.cpp's llama_decode, driven from grpc-server.cpp:1941).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _split_cache(cache):
    """A cache operand is either a plain float array or the int8 pytree
    {"q": int8[..., hd], "s": f32[...]} (ops/kvcache.py). Returns
    (rows, scales|None); the scales are folded OUTSIDE the contraction
    (scores for K, probs for V) so no dequantized cache materializes —
    HBM reads stay int8."""
    if isinstance(cache, dict):
        return cache["q"], cache["s"]
    return cache, None


def causal_attention(q, k, v, valid, q_per_kv: int):
    """Prefill attention.

    q: [B, T, H, hd]; k, v: [B, T, KV, hd]; valid: [B, T] bool.
    Returns [B, T, H, hd].
    """
    dtype = q.dtype
    B, T, H, hd = q.shape
    KV = k.shape[2]
    qg = q.reshape(B, T, KV, q_per_kv, hd)
    scale = jnp.float32(1.0) / jnp.sqrt(hd).astype(jnp.float32)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32) * scale
    causal = jnp.tril(jnp.ones((T, T), bool))
    mask = causal[None, :, :] & valid[:, None, :]                # [B, T, S]
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(B, T, H, hd)


def mixed_prefill_attention(q, chunk_k, chunk_v, k_rows, v_rows, start_pos,
                            seq_lens, q_per_kv: int):
    """Continued-prefill attention: queries for a chunk at absolute positions
    start_pos..start_pos+T attend over the PRE-update cache rows (the
    committed prefix) plus the chunk's own keys/values (see module doc —
    reading the same-step scattered rows costs a full-layer copy).

    q, chunk_k, chunk_v: [B, T, {H|KV|KV}, hd]; k_rows/v_rows: [B, C, KV, hd]
    (cache contents BEFORE this chunk's scatter — plain float or the int8
    {"q","s"} pytree); start_pos, seq_lens: [B].
    Cache position kp is visible iff kp < start_pos (committed prefix);
    chunk position t' is visible to query t iff t' <= t AND t' < seq_lens.
    """
    dtype = q.dtype
    B, T, H, hd = q.shape
    k_rows, sk = _split_cache(k_rows)
    v_rows, sv = _split_cache(v_rows)
    C = k_rows.shape[1]
    KV = k_rows.shape[2]
    qg = q.reshape(B, T, KV, q_per_kv, hd)
    scale = jnp.float32(1.0) / jnp.sqrt(hd).astype(jnp.float32)
    sc_cache = jnp.einsum("btkgd,bskd->bkgts", qg,
                          k_rows.astype(dtype)).astype(jnp.float32) * scale
    if sk is not None:
        # per-(row, kv-head) key scale folded into the logits: [B,C,KV] ->
        # [B,KV,1,1,C] against scores [B,KV,G,T,C]
        sc_cache = sc_cache * sk.transpose(0, 2, 1)[:, :, None, None, :]
    kp = jnp.arange(C, dtype=jnp.int32)                                       # [C]
    m_cache = kp[None, None, :] < start_pos[:, None, None]                    # [B, T, C]
    sc_cache = jnp.where(m_cache[:, None, None, :, :], sc_cache, _NEG_INF)
    sc_chunk = jnp.einsum("btkgd,bskd->bkgts", qg, chunk_k).astype(jnp.float32) * scale
    causal = jnp.tril(jnp.ones((T, T), bool))
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < seq_lens[:, None]       # [B, T]
    m_chunk = causal[None, :, :] & valid[:, None, :]                          # [B, T, T]
    sc_chunk = jnp.where(m_chunk[:, None, None, :, :], sc_chunk, _NEG_INF)
    scores = jnp.concatenate([sc_cache, sc_chunk], axis=-1)                   # [B,KV,G,T,C+T]
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    p_cache = probs[..., :C]
    if sv is not None:
        # value scale folded into the (small) probs tensor, not the cache
        p_cache = p_cache * sv.transpose(0, 2, 1)[:, :, None, None, :].astype(dtype)
    out = (jnp.einsum("bkgts,bskd->btkgd", p_cache, v_rows.astype(dtype))
           + jnp.einsum("bkgts,bskd->btkgd", probs[..., C:], chunk_v))
    return out.reshape(B, T, H, hd)


def decode_attention(q, cache_k, cache_v, lengths, q_per_kv: int):
    """Single-token decode attention over the cache for all slots.

    q: [S, H, hd]; cache_k/v: [S, C, KV, hd] (plain float or int8 {"q","s"});
    lengths: [S] (valid cache positions are [0, lengths[s))).
    Returns [S, H, hd].
    """
    dtype = q.dtype
    S, H, hd = q.shape
    cache_k, sk = _split_cache(cache_k)
    cache_v, sv = _split_cache(cache_v)
    C = cache_k.shape[1]
    KV = cache_k.shape[2]
    qg = q.reshape(S, KV, q_per_kv, hd)
    scale = jnp.float32(1.0) / jnp.sqrt(hd).astype(jnp.float32)
    scores = jnp.einsum("skgd,sckd->skgc", qg,
                        cache_k.astype(dtype)).astype(jnp.float32) * scale
    if sk is not None:
        scores = scores * sk.transpose(0, 2, 1)[:, :, None, :]  # [S,KV,1,C]
    mask = jnp.arange(C, dtype=jnp.int32)[None, :] < lengths[:, None]  # [S, C]
    scores = jnp.where(mask[:, None, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    if sv is not None:
        probs = probs * sv.transpose(0, 2, 1)[:, :, None, :].astype(dtype)
    out = jnp.einsum("skgc,sckd->skgd", probs, cache_v.astype(dtype))
    return out.reshape(S, H, hd)


def decode_attention_append(q, new_k, new_v, cache_k, cache_v, lengths,
                            q_per_kv: int):
    """Decode attention over the PRE-update cache plus the current token's
    own key/value (which the caller scatters into the cache separately —
    see module doc for why the read must not see the scatter).

    q, new_k, new_v: [S, {H|KV|KV}, hd]; cache_k/v: [S, C, KV, hd] (plain
    float or int8 {"q","s"}) holding rows [0, lengths[s]) — row lengths[s]
    is written this step but read from ``new_k``/``new_v`` instead.
    Returns [S, H, hd].
    """
    dtype = q.dtype
    S, H, hd = q.shape
    cache_k, sk = _split_cache(cache_k)
    cache_v, sv = _split_cache(cache_v)
    C = cache_k.shape[1]
    KV = cache_k.shape[2]
    qg = q.reshape(S, KV, q_per_kv, hd)
    scale = jnp.float32(1.0) / jnp.sqrt(hd).astype(jnp.float32)
    scores = jnp.einsum("skgd,sckd->skgc", qg,
                        cache_k.astype(dtype)).astype(jnp.float32) * scale
    if sk is not None:
        scores = scores * sk.transpose(0, 2, 1)[:, :, None, :]  # [S,KV,1,C]
    mask = jnp.arange(C, dtype=jnp.int32)[None, :] < lengths[:, None]  # [S, C]
    scores = jnp.where(mask[:, None, None, :], scores, _NEG_INF)
    sc_self = jnp.einsum("skgd,skd->skg", qg, new_k).astype(jnp.float32) * scale
    scores = jnp.concatenate([scores, sc_self[..., None]], axis=-1)    # [S,KV,G,C+1]
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    p_cache = probs[..., :C]
    if sv is not None:
        p_cache = p_cache * sv.transpose(0, 2, 1)[:, :, None, :].astype(dtype)
    out = (jnp.einsum("skgc,sckd->skgd", p_cache, cache_v.astype(dtype))
           + probs[..., C] [..., None] * new_v[:, :, None, :])
    return out.reshape(S, H, hd)
