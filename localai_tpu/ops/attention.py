"""Attention ops: jnp reference implementations.

These are the semantic reference; Pallas TPU kernels (flash prefill,
paged decode) in localai_tpu/ops/pallas/ replace them on TPU via the
dispatch switch in localai_tpu/ops/__init__.py. Keeping a pure-jnp path
means every test runs hermetically on the 8-device CPU mesh.

Role parity: this is the attention inside the reference's hot loop
(llama.cpp's llama_decode, driven from grpc-server.cpp:1941).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _repeat_kv(k: jax.Array, q_per_kv: int) -> jax.Array:
    """[.., KV, hd] -> [.., KV*q_per_kv, hd] for GQA."""
    if q_per_kv == 1:
        return k
    return jnp.repeat(k, q_per_kv, axis=-2)


def causal_attention(q, k, v, valid, q_per_kv: int):
    """Prefill attention.

    q: [B, T, H, hd]; k, v: [B, T, KV, hd]; valid: [B, T] bool.
    Returns [B, T, H, hd].
    """
    dtype = q.dtype
    hd = q.shape[-1]
    k = _repeat_kv(k, q_per_kv)
    v = _repeat_kv(v, q_per_kv)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / jnp.sqrt(hd).astype(jnp.float32)
    T = q.shape[1]
    causal = jnp.tril(jnp.ones((T, T), bool))
    mask = causal[None, None, :, :] & valid[:, None, None, :]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def mixed_prefill_attention(q, k_rows, v_rows, start_pos, seq_lens, q_per_kv: int):
    """Continued-prefill attention: queries for a chunk at absolute positions
    start_pos..start_pos+T attend over full cache rows (prefix + chunk).

    q: [B, T, H, hd]; k_rows/v_rows: [B, C, KV, hd]; start_pos, seq_lens: [B].
    Key position kp is visible to query qi iff kp <= start_pos + qi AND
    kp < start_pos + seq_lens (excludes garbage keys written by chunk padding).
    """
    dtype = q.dtype
    hd = q.shape[-1]
    k = _repeat_kv(k_rows, q_per_kv)
    v = _repeat_kv(v_rows, q_per_kv)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / jnp.sqrt(hd).astype(jnp.float32)
    B, T = q.shape[:2]
    C = k_rows.shape[1]
    abs_q = start_pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]      # [B, T]
    kp = jnp.arange(C, dtype=jnp.int32)                                        # [C]
    mask = kp[None, None, :] <= abs_q[:, :, None]                              # [B, T, C]
    mask &= kp[None, None, :] < (start_pos + seq_lens)[:, None, None]
    scores = jnp.where(mask[:, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def decode_attention(q, cache_k, cache_v, lengths, q_per_kv: int):
    """Single-token decode attention over the cache for all slots.

    q: [S, H, hd]; cache_k/v: [S, C, KV, hd]; lengths: [S] (valid cache
    positions are [0, lengths[s])). Returns [S, H, hd].
    """
    dtype = q.dtype
    hd = q.shape[-1]
    k = _repeat_kv(cache_k, q_per_kv)  # [S, C, H, hd]
    v = _repeat_kv(cache_v, q_per_kv)
    scores = jnp.einsum("shd,schd->shc", q, k).astype(jnp.float32) / jnp.sqrt(hd).astype(jnp.float32)
    C = cache_k.shape[1]
    mask = jnp.arange(C, dtype=jnp.int32)[None, :] < lengths[:, None]  # [S, C]
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    return jnp.einsum("shc,schd->shd", probs, v)
