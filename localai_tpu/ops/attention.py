"""Attention ops: jnp implementations (XLA-fused; production path).

Measured on the serving chip, these run at the device's HBM streaming
rate for the serving shapes (weights + KV reads dominate; see bench.py),
so hand-written Pallas kernels are kept as a future optimization rather
than a dispatch layer here. Sequence-parallel long-context attention
lives in localai_tpu/parallel/ring_attention.py. Pure-jnp also means
every test runs hermetically on the 8-device CPU mesh.

GQA is computed with grouped einsums — queries reshaped to
[.., KV, G, hd] against un-repeated keys — NOT by materializing
jnp.repeat(k, G) (which multiplies decode HBM traffic by G; measured 8x
slowdown on a 1B model at G=8).

Role parity: this is the attention inside the reference's hot loop
(llama.cpp's llama_decode, driven from grpc-server.cpp:1941).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def causal_attention(q, k, v, valid, q_per_kv: int):
    """Prefill attention.

    q: [B, T, H, hd]; k, v: [B, T, KV, hd]; valid: [B, T] bool.
    Returns [B, T, H, hd].
    """
    dtype = q.dtype
    B, T, H, hd = q.shape
    KV = k.shape[2]
    qg = q.reshape(B, T, KV, q_per_kv, hd)
    scale = jnp.float32(1.0) / jnp.sqrt(hd).astype(jnp.float32)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k).astype(jnp.float32) * scale
    causal = jnp.tril(jnp.ones((T, T), bool))
    mask = causal[None, :, :] & valid[:, None, :]                # [B, T, S]
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(B, T, H, hd)


def mixed_prefill_attention(q, k_rows, v_rows, start_pos, seq_lens, q_per_kv: int):
    """Continued-prefill attention: queries for a chunk at absolute positions
    start_pos..start_pos+T attend over full cache rows (prefix + chunk).

    q: [B, T, H, hd]; k_rows/v_rows: [B, C, KV, hd]; start_pos, seq_lens: [B].
    Key position kp is visible to query qi iff kp <= start_pos + qi AND
    kp < start_pos + seq_lens (excludes garbage keys written by chunk padding).
    """
    dtype = q.dtype
    B, T, H, hd = q.shape
    C = k_rows.shape[1]
    KV = k_rows.shape[2]
    qg = q.reshape(B, T, KV, q_per_kv, hd)
    scale = jnp.float32(1.0) / jnp.sqrt(hd).astype(jnp.float32)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k_rows).astype(jnp.float32) * scale
    abs_q = start_pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]      # [B, T]
    kp = jnp.arange(C, dtype=jnp.int32)                                        # [C]
    mask = kp[None, None, :] <= abs_q[:, :, None]                              # [B, T, C]
    mask &= kp[None, None, :] < (start_pos + seq_lens)[:, None, None]
    scores = jnp.where(mask[:, None, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v_rows)
    return out.reshape(B, T, H, hd)


def decode_attention(q, cache_k, cache_v, lengths, q_per_kv: int):
    """Single-token decode attention over the cache for all slots.

    q: [S, H, hd]; cache_k/v: [S, C, KV, hd]; lengths: [S] (valid cache
    positions are [0, lengths[s])). Returns [S, H, hd].
    """
    dtype = q.dtype
    S, H, hd = q.shape
    C = cache_k.shape[1]
    KV = cache_k.shape[2]
    qg = q.reshape(S, KV, q_per_kv, hd)
    scale = jnp.float32(1.0) / jnp.sqrt(hd).astype(jnp.float32)
    scores = jnp.einsum("skgd,sckd->skgc", qg, cache_k).astype(jnp.float32) * scale
    mask = jnp.arange(C, dtype=jnp.int32)[None, :] < lengths[:, None]  # [S, C]
    scores = jnp.where(mask[:, None, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    out = jnp.einsum("skgc,sckd->skgd", probs, cache_v)
    return out.reshape(S, H, hd)
