"""Pallas TPU kernel: RAGGED PAGED decode (append-)attention.

The paged KV layout (ops/kvcache.py) stores rows in a shared page pool
[n_pages, page_size, KV, hd] with a per-slot page table [S, max_pages];
a mixed-length batch is "ragged" — each slot touches only the pages its
table names (Ragged Paged Attention, PAPERS.md arxiv 2604.15464). A
naive XLA gather materializes a dense [S, C, KV, hd] copy of the pool
every layer of every step; this kernel reads pages IN PLACE:

  * Grid (S, max_pages): one program per (slot, page-table entry).
  * The page table and lengths are SCALAR-PREFETCH arguments, consumed
    by the K/V BlockSpec index maps — the grid pipeline therefore knows
    page p+1's physical address while page p computes, and its automatic
    double-buffering overlaps the next page's HBM read with the current
    page's FLOPs (the prefetch-ahead-of-decode idea of PRESERVE,
    arxiv 2501.08192, expressed through the Pallas pipeline).
  * Table entries past a slot's last valid page are remapped to the last
    valid page in the index map: consecutive grid steps then name the
    SAME block, and the pipeline skips the redundant DMA entirely —
    short slots cost ~their own length in HBM reads, not max_pages.
  * Softmax is accumulated online across pages (m/l/acc VMEM scratch);
    the current token's own k/v is appended from registers at the final
    page, matching ops/attention.py::decode_attention_append — the jnp
    fallback used on CPU (kvcache.gather_all_rows) and the parity
    reference in tests.

The int8 paged cache has its own kernel variant below
(paged_decode_attention_append_quant): pages stay int8 in HBM and the
per-(row, kv-head) scales are folded OUTSIDE the contraction — scores
for K, probs for V — exactly the fold ops/attention.py::_split_cache
does on the jnp path, so HBM reads stay 1 byte/element on the decode
hot path instead of falling back to the dense gather.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _kernel(ptab_ref, len_ref, q_ref, nk_ref, nv_ref, kp_ref, vp_ref,
            out_ref, m_ref, l_ref, acc_ref):
    """One (slot, page) program: q [1, KV, G, hd]; k/v page [1, Pg, KV, hd];
    online-softmax state in VMEM scratch, persistent across the page walk
    (the output block index is invariant in the page dimension)."""
    s = pl.program_id(0)
    p = pl.program_id(1)
    mp = pl.num_programs(1)
    length = len_ref[s]
    pg = kp_ref.shape[1]
    kv_heads = kp_ref.shape[2]

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    for h in range(kv_heads):
        q = q_ref[0, h]                               # [G, hd]
        k = kp_ref[0, :, h, :]                        # [Pg, hd]
        v = vp_ref[0, :, h, :]
        scale = jax.lax.rsqrt(jnp.float32(q.shape[-1]))
        qf = q.astype(jnp.float32) * scale
        scores = jax.lax.dot_general(                 # [G, Pg] NT matmul
            qf, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1) + p * pg
        scores = jnp.where(col < length, scores, _NEG_INF)

        m_prev = m_ref[h]                             # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(scores - m_new)               # [G, Pg]
        l_ref[h] = l_ref[h] * alpha + jnp.sum(probs, axis=-1, keepdims=True)
        acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
            probs, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[h] = m_new

    @pl.when(p == mp - 1)
    def _finish():
        for h in range(kv_heads):
            q = q_ref[0, h]
            nk = nk_ref[0, h]                         # [1, hd]
            nv = nv_ref[0, h]
            scale = jax.lax.rsqrt(jnp.float32(q.shape[-1]))
            qf = q.astype(jnp.float32) * scale
            # current token's own key/value (register append; visible)
            s_self = jnp.sum(qf * nk.astype(jnp.float32), axis=-1,
                             keepdims=True)           # [G, 1]
            m_fin = jnp.maximum(m_ref[h], s_self)
            alpha = jnp.exp(m_ref[h] - m_fin)
            p_self = jnp.exp(s_self - m_fin)
            denom = l_ref[h] * alpha + p_self
            out = (acc_ref[h] * alpha + p_self * nv.astype(jnp.float32))
            out_ref[0, h] = (out / denom).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("q_per_kv", "interpret"))
def paged_decode_attention_append(q, new_k, new_v, pages_k, pages_v, ptab,
                                  lengths, q_per_kv: int,
                                  interpret: bool = False):
    """q: [S, H, hd]; new_k/new_v: [S, KV, hd]; pages_k/v:
    [n_pages, page_size, KV, hd] (single-layer page pool); ptab:
    [S, max_pages] int32 (sentinel n_pages = unallocated); lengths: [S].
    Returns [S, H, hd] (q.dtype). Semantics match
    ops/attention.py::decode_attention_append over the slot's logical
    rows [0, lengths[s]) plus the register-appended current token."""
    S, H, hd = q.shape
    n_pages, pg, kv_heads, _ = pages_k.shape
    mp = ptab.shape[1]
    G = q_per_kv
    qg = q.reshape(S, kv_heads, G, hd)
    nk = new_k.reshape(S, kv_heads, 1, hd)
    nv = new_v.reshape(S, kv_heads, 1, hd)

    def page_map(s, p, ptab_ref, len_ref):
        # pages past the slot's last valid one revisit the last valid
        # block (no DMA); fully-empty slots clamp to physical page 0 —
        # their scores are all masked (col < 0 never holds)
        n_valid = (len_ref[s] + pg - 1) // pg
        last = jnp.maximum(n_valid - 1, 0)
        pid = ptab_ref[s, jnp.minimum(p, last)]
        return (jnp.clip(pid, 0, n_pages - 1), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # ptab, lengths
        grid=(S, mp),
        in_specs=[
            pl.BlockSpec((1, kv_heads, G, hd),
                         lambda s, p, pt, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, kv_heads, 1, hd),
                         lambda s, p, pt, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, kv_heads, 1, hd),
                         lambda s, p, pt, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, pg, kv_heads, hd), page_map),
            pl.BlockSpec((1, pg, kv_heads, hd), page_map),
        ],
        out_specs=pl.BlockSpec((1, kv_heads, G, hd),
                               lambda s, p, pt, ln: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kv_heads, G, 1), jnp.float32),    # running max
            pltpu.VMEM((kv_heads, G, 1), jnp.float32),    # running denom
            pltpu.VMEM((kv_heads, G, hd), jnp.float32),   # running out
        ],
    )
    out = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, kv_heads, G, hd), q.dtype),
        interpret=interpret,
    )(ptab, lengths, qg, nk, nv, pages_k, pages_v)
    return out.reshape(S, H, hd)


def _kernel_quant(ptab_ref, len_ref, q_ref, nk_ref, nv_ref, kp_ref, sk_ref,
                  vp_ref, sv_ref, out_ref, m_ref, l_ref, acc_ref):
    """_kernel with the int8 {q, scales} page representation: k/v pages
    arrive int8 and their per-(row, kv-head) scales ride as separate
    [1, Pg, KV] blocks of the same page walk. The scale fold matches
    ops/attention.py (scores * s_k per key column; probs * s_v before
    the value contraction) so no dequantized page ever materializes.
    The current token's own k/v (nk/nv) stays float — the engine holds
    it in registers; only cache rows are quantized."""
    s = pl.program_id(0)
    p = pl.program_id(1)
    mp = pl.num_programs(1)
    length = len_ref[s]
    pg = kp_ref.shape[1]
    kv_heads = kp_ref.shape[2]

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    for h in range(kv_heads):
        q = q_ref[0, h]                               # [G, hd]
        k = kp_ref[0, :, h, :]                        # [Pg, hd] int8
        v = vp_ref[0, :, h, :]
        sk = sk_ref[0, :, h]                          # [Pg] f32
        sv = sv_ref[0, :, h]
        scale = jax.lax.rsqrt(jnp.float32(q.shape[-1]))
        qf = q.astype(jnp.float32) * scale
        scores = jax.lax.dot_general(                 # [G, Pg] NT matmul
            qf, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        scores = scores * sk[None, :]                 # key scale fold
        col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1) + p * pg
        scores = jnp.where(col < length, scores, _NEG_INF)

        m_prev = m_ref[h]                             # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(scores - m_new)               # [G, Pg]
        l_ref[h] = l_ref[h] * alpha + jnp.sum(probs, axis=-1, keepdims=True)
        acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
            probs * sv[None, :], v.astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[h] = m_new

    @pl.when(p == mp - 1)
    def _finish():
        for h in range(kv_heads):
            q = q_ref[0, h]
            nk = nk_ref[0, h]                         # [1, hd] float
            nv = nv_ref[0, h]
            scale = jax.lax.rsqrt(jnp.float32(q.shape[-1]))
            qf = q.astype(jnp.float32) * scale
            s_self = jnp.sum(qf * nk.astype(jnp.float32), axis=-1,
                             keepdims=True)           # [G, 1]
            m_fin = jnp.maximum(m_ref[h], s_self)
            alpha = jnp.exp(m_ref[h] - m_fin)
            p_self = jnp.exp(s_self - m_fin)
            denom = l_ref[h] * alpha + p_self
            out = (acc_ref[h] * alpha + p_self * nv.astype(jnp.float32))
            out_ref[0, h] = (out / denom).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("q_per_kv", "interpret"))
def paged_decode_attention_append_quant(q, new_k, new_v, pages_k, scales_k,
                                        pages_v, scales_v, ptab, lengths,
                                        q_per_kv: int,
                                        interpret: bool = False):
    """Int8-KV variant of paged_decode_attention_append: pages_k/v are
    int8 [n_pages, page_size, KV, hd] and scales_k/v are their f32
    [n_pages, page_size, KV] companions (the {"pages","scales"} leaves
    of the quantized paged cache, ops/kvcache.py). new_k/new_v stay
    float. Semantics match decode_attention_append over the
    dense-gathered {"q","s"} rows (the jnp fallback / parity
    reference)."""
    S, H, hd = q.shape
    n_pages, pg, kv_heads, _ = pages_k.shape
    mp = ptab.shape[1]
    G = q_per_kv
    qg = q.reshape(S, kv_heads, G, hd)
    nk = new_k.reshape(S, kv_heads, 1, hd)
    nv = new_v.reshape(S, kv_heads, 1, hd)

    def page_map(s, p, ptab_ref, len_ref):
        n_valid = (len_ref[s] + pg - 1) // pg
        last = jnp.maximum(n_valid - 1, 0)
        pid = ptab_ref[s, jnp.minimum(p, last)]
        return (jnp.clip(pid, 0, n_pages - 1), 0, 0, 0)

    def scale_map(s, p, ptab_ref, len_ref):
        return page_map(s, p, ptab_ref, len_ref)[:3]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # ptab, lengths
        grid=(S, mp),
        in_specs=[
            pl.BlockSpec((1, kv_heads, G, hd),
                         lambda s, p, pt, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, kv_heads, 1, hd),
                         lambda s, p, pt, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, kv_heads, 1, hd),
                         lambda s, p, pt, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, pg, kv_heads, hd), page_map),
            pl.BlockSpec((1, pg, kv_heads), scale_map),
            pl.BlockSpec((1, pg, kv_heads, hd), page_map),
            pl.BlockSpec((1, pg, kv_heads), scale_map),
        ],
        out_specs=pl.BlockSpec((1, kv_heads, G, hd),
                               lambda s, p, pt, ln: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kv_heads, G, 1), jnp.float32),    # running max
            pltpu.VMEM((kv_heads, G, 1), jnp.float32),    # running denom
            pltpu.VMEM((kv_heads, G, hd), jnp.float32),   # running out
        ],
    )
    out = pl.pallas_call(
        _kernel_quant,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, kv_heads, G, hd), q.dtype),
        interpret=interpret,
    )(ptab, lengths, qg, nk, nv, pages_k, scales_k, pages_v, scales_v)
    return out.reshape(S, H, hd)
