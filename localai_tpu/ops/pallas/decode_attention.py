"""Pallas TPU kernel: batched decode (append-)attention over the KV cache.

WHY A KERNEL (r3 HLO evidence, scripts/inspect_hlo.py): with the jnp
einsum formulation, XLA's layout assignment gives the attention dot a
C-minor (transposed) cache operand layout while the scan carry holds the
cache hd-minor — so every layer of every decode step materializes TWO
full-layer layout-change copies for k and two for v (~5.8 GB/step of
copy traffic on the 1B bench config, ~2x the whole model's weight
reads). A Pallas kernel consumes the cache block in its NATIVE layout
(the dot is an NT matmul the MXU handles directly), so the copies
vanish. This is the kernel VERDICT r1/r2 asked for.

Semantics match ops/attention.py::decode_attention_append (the jnp
fallback, used on CPU and as the reference in tests): attention over
cache rows [0, lengths[s]) PLUS the current token's k/v from registers;
the cache itself is read-only here (the engine scatters the new row
separately — a write-only scatter XLA performs in place).

Grid: (S, KV) — one program per (slot, kv-head); q rows for the head's
G query groups ride along. Blocks stay modest (C*hd bf16, <= ~1 MB for
8k contexts) so the automatic grid pipeline double-buffers HBM reads.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _kernel(len_ref, q_ref, nk_ref, nv_ref, k_ref, v_ref, out_ref):
    """One slot: q [KV, G, hd]; new k/v [KV, 1, hd]; cache k/v [C, KV, hd].
    Static loop over the KV heads (TPU block tiling forbids blocking the
    small KV axis; slicing it in-kernel is free)."""
    length = len_ref[pl.program_id(0)]
    KV = k_ref.shape[2]
    for h in range(KV):
        q = q_ref[0, h]                       # [G, hd]
        k = k_ref[0, :, h, :]                 # [C, hd]
        v = v_ref[0, :, h, :]
        nk = nk_ref[0, h]                     # [1, hd]
        nv = nv_ref[0, h]

        scale = jax.lax.rsqrt(jnp.float32(q.shape[-1]))
        qf = q.astype(jnp.float32) * scale
        # [G, C] = [G, hd] @ [C, hd]^T — NT contraction, native layouts
        scores = jax.lax.dot_general(
            qf, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(col < length, scores, _NEG_INF)
        # current token's own key/value (register append; always visible)
        s_self = jnp.sum(qf * nk.astype(jnp.float32), axis=-1, keepdims=True)

        m = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), s_self)   # [G, 1]
        p = jnp.exp(scores - m)                                            # [G, C]
        p_self = jnp.exp(s_self - m)                                       # [G, 1]
        denom = jnp.sum(p, axis=-1, keepdims=True) + p_self
        out = jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                            # [G, hd]
        out = (out + p_self * nv.astype(jnp.float32)) / denom
        out_ref[0, h] = out.astype(out_ref.dtype)


def _kernel_full(li_ref, len_ref, q_ref, nk_ref, nv_ref, k_ref, v_ref,
                 out_ref):
    """Variant taking the FULL [L, S, C, KV, hd] cache: the layer index is a
    scalar-prefetch argument consumed by the BlockSpec index maps, so no
    XLA-side dynamic-slice of the cache exists (that slice materialized a
    full relayouted layer per step — the last copy this kernel removes)."""
    length = len_ref[pl.program_id(0)]
    KV = k_ref.shape[3]
    for h in range(KV):
        q = q_ref[0, h]                       # [G, hd]
        k = k_ref[0, 0, :, h, :]              # [C, hd]
        v = v_ref[0, 0, :, h, :]
        nk = nk_ref[0, h]                     # [1, hd]
        nv = nv_ref[0, h]

        scale = jax.lax.rsqrt(jnp.float32(q.shape[-1]))
        qf = q.astype(jnp.float32) * scale
        scores = jax.lax.dot_general(
            qf, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(col < length, scores, _NEG_INF)
        s_self = jnp.sum(qf * nk.astype(jnp.float32), axis=-1, keepdims=True)
        m = jnp.maximum(jnp.max(scores, axis=-1, keepdims=True), s_self)
        p = jnp.exp(scores - m)
        p_self = jnp.exp(s_self - m)
        denom = jnp.sum(p, axis=-1, keepdims=True) + p_self
        out = jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out = (out + p_self * nv.astype(jnp.float32)) / denom
        out_ref[0, h] = out.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("q_per_kv", "interpret"))
def decode_attention_append_pallas_full(q, new_k, new_v, cache_k, cache_v,
                                        lengths, layer_idx, q_per_kv: int,
                                        interpret: bool = False):
    """Full-cache variant: cache_k/v are [L, S, C, KV, hd]; layer_idx is a
    traced scalar (the scan's layer counter). See _kernel_full."""
    S, H, hd = q.shape
    C = cache_k.shape[2]
    KV = cache_k.shape[3]
    G = q_per_kv
    qg = q.reshape(S, KV, G, hd)
    nk = new_k.reshape(S, KV, 1, hd)
    nv = new_v.reshape(S, KV, 1, hd)
    li_arr = jnp.reshape(layer_idx, (1,)).astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,      # li_arr, lengths
        grid=(S,),
        in_specs=[
            pl.BlockSpec((1, KV, G, hd), lambda s, li, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, KV, 1, hd), lambda s, li, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, KV, 1, hd), lambda s, li, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, 1, C, KV, hd),
                         lambda s, li, ln: (li[0], s, 0, 0, 0)),
            pl.BlockSpec((1, 1, C, KV, hd),
                         lambda s, li, ln: (li[0], s, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, KV, G, hd), lambda s, li, ln: (s, 0, 0, 0)),
    )
    out = pl.pallas_call(
        _kernel_full,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, KV, G, hd), q.dtype),
        interpret=interpret,
    )(li_arr, lengths, qg, nk, nv, cache_k, cache_v)
    return out.reshape(S, H, hd)


@functools.partial(jax.jit, static_argnames=("q_per_kv", "interpret"))
def decode_attention_append_pallas(q, new_k, new_v, cache_k, cache_v,
                                   lengths, q_per_kv: int,
                                   interpret: bool = False):
    """q: [S, H, hd]; new_k/new_v: [S, KV, hd]; cache_k/v: [S, C, KV, hd];
    lengths: [S]. Returns [S, H, hd] (q.dtype)."""
    S, H, hd = q.shape
    C = cache_k.shape[1]
    KV = cache_k.shape[2]
    G = q_per_kv
    qg = q.reshape(S, KV, G, hd)
    nk = new_k.reshape(S, KV, 1, hd)
    nv = new_v.reshape(S, KV, 1, hd)

    out = pl.pallas_call(
        _kernel,
        grid=(S,),
        in_specs=[
            # full lengths vector in SMEM (rank-1 SMEM blocks must cover
            # the array); the kernel indexes it by program_id
            pl.BlockSpec((S,), lambda s: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, KV, G, hd), lambda s: (s, 0, 0, 0)),
            pl.BlockSpec((1, KV, 1, hd), lambda s: (s, 0, 0, 0)),
            pl.BlockSpec((1, KV, 1, hd), lambda s: (s, 0, 0, 0)),
            # cache block [1, C, KV, hd]: the slot's full rows in their
            # NATIVE hd-minor layout — no relayout copies (see module doc)
            pl.BlockSpec((1, C, KV, hd), lambda s: (s, 0, 0, 0)),
            pl.BlockSpec((1, C, KV, hd), lambda s: (s, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, KV, G, hd), lambda s: (s, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((S, KV, G, hd), q.dtype),
        interpret=interpret,
    )(lengths, qg, nk, nv, cache_k, cache_v)
    return out.reshape(S, H, hd)
