"""Pallas TPU kernel: RAGGED PACKED PREFILL attention, segment-blocked.

The packed prefill step (ops/ragged_prefill.py has the semantics and the
jnp fallback) feeds one [N]-token batch holding the prompt tails of up
to B slots; each token attends its slot's committed cache PAGES plus the
pack's own keys causally within its segment. A naive XLA lowering
gathers every segment's dense [C] row window per layer; this kernel
walks the pages IN PLACE, the same way ops/pallas/paged_attention.py
does for decode.

The grid blocks QUERIES PER SEGMENT (Ragged Paged Attention style)
instead of keeping the whole pack's query rows resident:

  * Grid (NQB, B, MP + NKB): for each QB-row query block, sweep every
    segment's MP committed page-table entries, then the NKB blocks of
    the pack's own keys.
  * The page table and the per-segment metadata (slot, start, offset,
    length) are SCALAR-PREFETCH arguments consumed by the K/V BlockSpec
    index maps — the pipeline knows page j+1's physical address while
    page j computes. Entries past a segment's last committed page, and
    every (q-block, segment) pair that does not overlap, clamp to a
    constant block so consecutive skipped steps revisit (no DMA), and
    their compute is predicated off entirely (``pl.when``).
  * Online softmax per q-block (m/l/acc VMEM scratch over QB*G query
    rows). Each query row belongs to exactly one segment and every
    other segment's scores are fully masked for it, so the accumulator
    runs across the whole (segment, kv-step) sweep without per-segment
    resets; the q-block's output is written once at the final step.

Scratch is therefore INDEPENDENT of the pack size N — the old
whole-pack layout hit a VMEM wall at ~1k packed tokens for 8B head
shapes (KV=8, G=4, hd=128) and fell back to the jnp scan exactly where
packing matters most. ``ragged_kernel_plan`` below is the single
source of truth for the blocking and for "does this pack stay on the
kernel path", shared by models/llama.py and the engine's fallback
counter.

Plain float paged caches only (the int8 paged prefill folds scales
through the jnp fallback).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30

# Per-q-block f32 scratch budget (m + l + acc over QB*G rows). QB tops
# out at 128, so this never binds for transformer shapes; it guards
# pathological configs rather than pack length.
_VMEM_SCRATCH_BUDGET = 8 * 1024 * 1024


def ragged_kernel_plan(N: int, kv_heads: int, q_per_kv: int,
                       head_dim: int) -> Optional[Tuple[int, int]]:
    """Blocking plan ``(qb, pkb)`` for an N-token pack, or None when the
    pack cannot run on the kernel path.

    ``qb`` (query block) and ``pkb`` (pack-key block) are the largest
    power of two <= 128 dividing N — gcd with 128, so power-of-two pack
    buckets get full 128-row MXU tiles and any other N still divides
    cleanly. Scratch is per-q-block (independent of N): the plan only
    fails for configs whose PER-BLOCK scratch exceeds VMEM, not for
    long packs — the ~1k-token cliff of the whole-pack layout is gone.
    """
    if N <= 0:
        return None
    qb = math.gcd(N, 128)
    scratch = kv_heads * qb * q_per_kv * (head_dim + 2) * 4
    if scratch > _VMEM_SCRATCH_BUDGET:
        return None
    return qb, qb


def _kernel(ptab_ref, slots_ref, start_ref, off_ref, len_ref,
            q_ref, ck_ref, cv_ref, kp_ref, vp_ref,
            out_ref, m_ref, l_ref, acc_ref, *, mp: int, pkb: int, qb: int):
    """One (q-block, segment, kv-step) program. q [QB, KV, G, hd];
    ck/cv pack keys [PKB, KV, hd]; kp/vp one page [1, Pg, KV, hd]."""
    i = pl.program_id(0)
    b = pl.program_id(1)
    j = pl.program_id(2)
    nb = pl.num_programs(1)
    nj = pl.num_programs(2)
    _, kv_heads, G, hd = q_ref.shape
    pg = kp_ref.shape[1]
    start = start_ref[b]
    off = off_ref[b]
    length = len_ref[b]
    q_lo = i * qb

    @pl.when((b == 0) & (j == 0))
    def _reset():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # global query index n for each of the QB*G flattened rows
    n_of_row = q_lo + \
        jax.lax.broadcasted_iota(jnp.int32, (qb * G, 1), 0) // G
    in_seg_row = (n_of_row >= off) & (n_of_row < off + length)

    # does this (q-block, segment, kv-step) contribute anything? A
    # skipped step is exact: all its scores would mask to -inf, so
    # m/l/acc are unchanged (alpha == 1, probs == 0).
    seg_hit = (length > 0) & (off < q_lo + qb) & (off + length > q_lo)
    if_page = j < mp
    pk_lo = (j - mp) * pkb
    need = seg_hit & jnp.where(
        if_page,
        j * pg < start,
        (pk_lo < off + length) & (pk_lo + pkb > off) & (pk_lo < q_lo + qb))

    @pl.when(need)
    def _compute():
        scale = jax.lax.rsqrt(jnp.float32(hd))
        for h in range(kv_heads):
            qf = q_ref[:, h].astype(jnp.float32).reshape(qb * G, hd) * scale
            # both regions compute with the SAME [QB*G, BLK] shape so
            # the online update below is region-agnostic; pkb == pg is
            # not required — the two score blocks mask independently
            k_page = kp_ref[0, :, h, :].astype(jnp.float32)       # [Pg, hd]
            s_page = jax.lax.dot_general(
                qf, k_page, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)               # [QB*G, Pg]
            col = jax.lax.broadcasted_iota(jnp.int32, s_page.shape, 1) \
                + j * pg
            mask_page = in_seg_row & (col < start) & if_page
            s_page = jnp.where(mask_page, s_page, _NEG_INF)

            k_pack = ck_ref[:, h, :].astype(jnp.float32)          # [PKB, hd]
            s_pack = jax.lax.dot_general(
                qf, k_pack, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)               # [QB*G, PKB]
            midx = jax.lax.broadcasted_iota(jnp.int32, s_pack.shape, 1) \
                + pk_lo
            mask_pack = in_seg_row & (midx >= off) & (midx < off + length) \
                & (midx <= n_of_row) & jnp.logical_not(if_page)
            s_pack = jnp.where(mask_pack, s_pack, _NEG_INF)

            scores = jnp.concatenate([s_page, s_pack], axis=1)
            masked = jnp.concatenate([mask_page, mask_pack], axis=1)
            m_prev = m_ref[h]                                     # [QB*G, 1]
            m_new = jnp.maximum(m_prev,
                                jnp.max(scores, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            # explicit zero where masked: an all-masked row has
            # m == _NEG_INF and exp(score - m) would be exp(0) == 1
            probs = jnp.where(masked, jnp.exp(scores - m_new), 0.0)
            l_ref[h] = l_ref[h] * alpha \
                + jnp.sum(probs, axis=-1, keepdims=True)
            v_page = vp_ref[0, :, h, :].astype(jnp.float32)
            v_pack = cv_ref[:, h, :].astype(jnp.float32)
            v_all = jnp.concatenate([v_page, v_pack], axis=0)
            acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
                probs, v_all, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[h] = m_new

    @pl.when((b == nb - 1) & (j == nj - 1))
    def _finish():
        # every row accumulated only from its own segment (other
        # segments masked it); rows in no segment have l == 0 -> 0
        for h in range(kv_heads):
            denom = l_ref[h] + (l_ref[h] == 0.0)                  # pad: 0/1
            out_ref[:, h] = (acc_ref[h] / denom).reshape(
                qb, G, hd).astype(out_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("q_per_kv", "pkb", "qb", "interpret"))
def ragged_prefill_attention_pallas(q, chunk_k, chunk_v, pages_k, pages_v,
                                    ptab, seg_slots, seg_start, seg_off,
                                    seg_len, q_per_kv: int, pkb: int = 128,
                                    qb: Optional[int] = None,
                                    interpret: bool = False):
    """q: [N, H, hd]; chunk_k/chunk_v: [N, KV, hd] (this pack's keys, not
    yet scattered); pages_k/v: [n_pages, page_size, KV, hd] single-layer
    page pool; ptab: [S, MP] int32 (sentinel n_pages = unallocated);
    seg_slots/seg_start/seg_off/seg_len: [B] int32 segment tables (pad
    segments: seg_len == 0). ``pkb`` (pack-key block) and ``qb`` (query
    block, default ``gcd(N, 128)``) must divide N; use
    ``ragged_kernel_plan`` to pick both. Returns [N, H, hd] (q.dtype);
    semantics match ops/ragged_prefill.py::ragged_prefill_attention over
    a paged cache."""
    N, H, hd = q.shape
    n_pages, pg, kv_heads, _ = pages_k.shape
    mp = ptab.shape[1]
    B = seg_slots.shape[0]
    G = q_per_kv
    if qb is None:
        qb = math.gcd(N, 128)
    assert N % pkb == 0 and N % qb == 0, (N, pkb, qb)
    nkb = N // pkb
    nqb = N // qb
    qg = q.reshape(N, kv_heads, G, hd)

    def _seg_hit(i, b, off_ref, len_ref):
        q_lo = i * qb
        return (len_ref[b] > 0) & (off_ref[b] < q_lo + qb) \
            & (off_ref[b] + len_ref[b] > q_lo)

    def q_map(i, b, j, *refs):
        return (i, 0, 0, 0)

    def page_map(i, b, j, ptab_ref, slots_ref, start_ref, off_ref, len_ref):
        # pages past the segment's last committed one — and every page
        # of a (q-block, segment) pair with no overlap — clamp to a
        # constant so consecutive skipped steps revisit (no DMA);
        # their compute is predicated off in the kernel
        n_valid = (start_ref[b] + pg - 1) // pg
        last = jnp.maximum(n_valid - 1, 0)
        pid = ptab_ref[slots_ref[b], jnp.minimum(jnp.minimum(j, mp - 1),
                                                 last)]
        hit = _seg_hit(i, b, off_ref, len_ref) & (j * pg < start_ref[b])
        return (jnp.where(hit, jnp.clip(pid, 0, n_pages - 1), 0), 0, 0, 0)

    def pack_map(i, b, j, ptab_ref, slots_ref, start_ref, off_ref, len_ref):
        blk = jnp.clip(j - mp, 0, nkb - 1)
        q_lo = i * qb
        lo, hi = off_ref[b], off_ref[b] + len_ref[b]
        pk_lo = blk * pkb
        hit = _seg_hit(i, b, off_ref, len_ref) & (j >= mp) \
            & (pk_lo < hi) & (pk_lo + pkb > lo) & (pk_lo < q_lo + qb)
        return (jnp.where(hit, blk, 0), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,      # ptab, seg_slots, seg_start/off/len
        grid=(nqb, B, mp + nkb),
        in_specs=[
            pl.BlockSpec((qb, kv_heads, G, hd), q_map),
            pl.BlockSpec((pkb, kv_heads, hd), pack_map),
            pl.BlockSpec((pkb, kv_heads, hd), pack_map),
            pl.BlockSpec((1, pg, kv_heads, hd), page_map),
            pl.BlockSpec((1, pg, kv_heads, hd), page_map),
        ],
        out_specs=pl.BlockSpec((qb, kv_heads, G, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((kv_heads, qb * G, 1), jnp.float32),    # running max
            pltpu.VMEM((kv_heads, qb * G, 1), jnp.float32),    # running denom
            pltpu.VMEM((kv_heads, qb * G, hd), jnp.float32),   # running out
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, mp=mp, pkb=pkb, qb=qb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, kv_heads, G, hd), q.dtype),
        interpret=interpret,
    )(ptab, seg_slots, seg_start, seg_off, seg_len,
      qg, chunk_k, chunk_v, pages_k, pages_v)
    return out.reshape(N, H, hd)
