"""Pallas TPU kernel: RAGGED PACKED PREFILL attention.

The packed prefill step (ops/ragged_prefill.py has the semantics and the
jnp fallback) feeds one [N]-token batch holding the prompt tails of up
to B slots; each token attends its slot's committed cache PAGES plus the
pack's own keys causally within its segment. A naive XLA lowering
gathers every segment's dense [C] row window per layer; this kernel
walks the pages IN PLACE, the same way ops/pallas/paged_attention.py
does for decode:

  * Grid (B, MP + NKB): for each segment, MP page-table entries of its
    slot's committed prefix, then NKB blocks of the pack's own keys.
  * The page table and the per-segment metadata (slot, start, offset,
    length) are SCALAR-PREFETCH arguments consumed by the K/V BlockSpec
    index maps — the pipeline knows page j+1's physical address while
    page j computes, and entries past the segment's last committed page
    revisit it (no DMA), so short prefixes cost ~their own length in
    HBM reads.
  * Online softmax across the whole walk (m/l/acc VMEM scratch over all
    N*G query rows, reset per segment); each segment's rows of the
    shared [N] output are masked-merged at its final grid step, so the
    output block stays VMEM-resident for the entire grid.

Plain float paged caches only (the int8 paged prefill folds scales
through the jnp fallback); VMEM bounds the pack bucket — the caller
(models/llama.py) falls back to the jnp path for packs whose per-head
scratch would not fit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _kernel(ptab_ref, slots_ref, start_ref, off_ref, len_ref,
            q_ref, ck_ref, cv_ref, kp_ref, vp_ref,
            out_ref, m_ref, l_ref, acc_ref, *, mp: int, pkb: int):
    """One (segment, key-block) program. q [N, KV, G, hd]; ck/cv pack
    keys [PKB, KV, hd]; kp/vp one page [1, Pg, KV, hd]."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    N, kv_heads, G, hd = q_ref.shape
    pg = kp_ref.shape[1]
    start = start_ref[b]
    off = off_ref[b]
    length = len_ref[b]

    @pl.when((b == 0) & (j == 0))
    def _zero_out():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(j == 0)
    def _reset():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    scale = jax.lax.rsqrt(jnp.float32(hd))
    # query-row index n for each of the N*G flattened rows
    n_of_row = jax.lax.broadcasted_iota(jnp.int32, (N * G, 1), 0) // G
    in_seg_row = (n_of_row >= off) & (n_of_row < off + length)

    for h in range(kv_heads):
        qf = q_ref[:, h].astype(jnp.float32).reshape(N * G, hd) * scale
        if_page = j < mp
        # both regions compute with the SAME [N*G, BLK] shape so the
        # online update below is region-agnostic; pkb == pg is not
        # required — the two score blocks are masked independently
        k_page = kp_ref[0, :, h, :].astype(jnp.float32)       # [Pg, hd]
        s_page = jax.lax.dot_general(
            qf, k_page, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [N*G, Pg]
        col = jax.lax.broadcasted_iota(jnp.int32, s_page.shape, 1) + j * pg
        mask_page = in_seg_row & (col < start) & if_page
        s_page = jnp.where(mask_page, s_page, _NEG_INF)

        k_pack = ck_ref[:, h, :].astype(jnp.float32)          # [PKB, hd]
        s_pack = jax.lax.dot_general(
            qf, k_pack, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [N*G, PKB]
        midx = jax.lax.broadcasted_iota(jnp.int32, s_pack.shape, 1) \
            + (j - mp) * pkb
        mask_pack = in_seg_row & (midx >= off) & (midx < off + length) \
            & (midx <= n_of_row) & jnp.logical_not(if_page)
        s_pack = jnp.where(mask_pack, s_pack, _NEG_INF)

        scores = jnp.concatenate([s_page, s_pack], axis=1)
        masked = jnp.concatenate([mask_page, mask_pack], axis=1)
        m_prev = m_ref[h]                                     # [N*G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # explicit zero where masked: an all-masked block has
        # m == _NEG_INF and exp(score - m) would be exp(0) == 1
        probs = jnp.where(masked, jnp.exp(scores - m_new), 0.0)
        l_ref[h] = l_ref[h] * alpha + jnp.sum(probs, axis=-1, keepdims=True)
        v_page = vp_ref[0, :, h, :].astype(jnp.float32)
        v_pack = cv_ref[:, h, :].astype(jnp.float32)
        v_all = jnp.concatenate([v_page, v_pack], axis=0)
        acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
            probs, v_all, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[h] = m_new

    @pl.when(j == nj - 1)
    def _finish():
        for h in range(kv_heads):
            denom = l_ref[h] + (l_ref[h] == 0.0)              # pad rows: 0/1
            res = (acc_ref[h] / denom).reshape(N, G, hd)
            out_ref[:, h] = jnp.where(in_seg_row.reshape(N, G, 1),
                                      res.astype(out_ref.dtype),
                                      out_ref[:, h])


@functools.partial(jax.jit, static_argnames=("q_per_kv", "pkb", "interpret"))
def ragged_prefill_attention_pallas(q, chunk_k, chunk_v, pages_k, pages_v,
                                    ptab, seg_slots, seg_start, seg_off,
                                    seg_len, q_per_kv: int, pkb: int = 128,
                                    interpret: bool = False):
    """q: [N, H, hd]; chunk_k/chunk_v: [N, KV, hd] (this pack's keys, not
    yet scattered); pages_k/v: [n_pages, page_size, KV, hd] single-layer
    page pool; ptab: [S, MP] int32 (sentinel n_pages = unallocated);
    seg_slots/seg_start/seg_off/seg_len: [B] int32 segment tables (pad
    segments: seg_len == 0). ``pkb`` (pack-key block, must divide N)
    trades grid steps against VMEM. Returns [N, H, hd] (q.dtype);
    semantics match ops/ragged_prefill.py::ragged_prefill_attention over
    a paged cache."""
    N, H, hd = q.shape
    n_pages, pg, kv_heads, _ = pages_k.shape
    mp = ptab.shape[1]
    B = seg_slots.shape[0]
    G = q_per_kv
    assert N % pkb == 0, (N, pkb)
    nkb = N // pkb
    qg = q.reshape(N, kv_heads, G, hd)

    def page_map(b, j, ptab_ref, slots_ref, start_ref, off_ref, len_ref):
        # pages past the segment's last committed one revisit it (no
        # DMA); segments with no committed prefix clamp to physical
        # page 0 — their scores are fully masked (col < 0 never holds)
        n_valid = (start_ref[b] + pg - 1) // pg
        last = jnp.maximum(n_valid - 1, 0)
        pid = ptab_ref[slots_ref[b], jnp.minimum(jnp.minimum(j, mp - 1),
                                                 last)]
        return (jnp.clip(pid, 0, n_pages - 1), 0, 0, 0)

    def pack_map(b, j, *refs):
        return (jnp.clip(j - mp, 0, nkb - 1), 0, 0)

    def whole(b, j, *refs):
        return (0, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,      # ptab, seg_slots, seg_start/off/len
        grid=(B, mp + nkb),
        in_specs=[
            pl.BlockSpec((N, kv_heads, G, hd), whole),
            pl.BlockSpec((pkb, kv_heads, hd), pack_map),
            pl.BlockSpec((pkb, kv_heads, hd), pack_map),
            pl.BlockSpec((1, pg, kv_heads, hd), page_map),
            pl.BlockSpec((1, pg, kv_heads, hd), page_map),
        ],
        out_specs=pl.BlockSpec((N, kv_heads, G, hd), whole),
        scratch_shapes=[
            pltpu.VMEM((kv_heads, N * G, 1), jnp.float32),    # running max
            pltpu.VMEM((kv_heads, N * G, 1), jnp.float32),    # running denom
            pltpu.VMEM((kv_heads, N * G, hd), jnp.float32),   # running out
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, mp=mp, pkb=pkb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, kv_heads, G, hd), q.dtype),
        interpret=interpret,
    )(ptab, seg_slots, seg_start, seg_off, seg_len,
      qg, chunk_k, chunk_v, pages_k, pages_v)
    return out.reshape(N, H, hd)
