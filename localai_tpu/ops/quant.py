"""Weight-only int8 quantization — the ONE {q, s} contract every LLM
family shares (llama, mamba, rwkv).

Capability parity: the reference serves quantized GGUF (Q4/Q8) by
default; per-out-channel symmetric int8 is the TPU-native analogue — XLA
fuses the int8->float cast + scale into the consuming matmul, so the MXU
consumes dequantized tiles while HBM reads stay int8 (measured ~2.2x
faster than bf16 matmuls on the serving chip). shard_params' scale-spec
handling and the XLA fusion pattern both depend on this exact layout, so
it lives in one place.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def quantize_weight(w) -> dict:
    """[..., in, out] float weight -> {"q": int8, "s": f32 per-out-channel
    scale}. The scale reduces ONLY the contraction (second-to-last) axis,
    so stacked [L, in, out] weights keep per-layer scales."""
    w32 = np.asarray(w, np.float32)
    s = np.max(np.abs(w32), axis=w32.ndim - 2, keepdims=True) / 127.0
    s = np.maximum(s, 1e-12)
    qv = np.clip(np.rint(w32 / s), -127, 127).astype(np.int8)
    return {"q": jnp.asarray(qv), "s": jnp.asarray(s, jnp.float32)}


def mat(w, dtype):
    """Dequantize a weight leaf if needed (pass-through for dense)."""
    if isinstance(w, dict):
        return (w["q"].astype(jnp.float32) * w["s"]).astype(dtype)
    return w
