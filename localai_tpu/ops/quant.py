"""Weight-only quantization — the ONE {q, s} contract every LLM family
shares (llama, mamba, rwkv).

Capability parity: the reference serves quantized GGUF (Q4/Q8) by
default; the TPU-native analogues are
  * per-out-channel symmetric int8 ({q: int8 [..., in, out],
    s: f32 [..., 1, out]}) — XLA fuses the cast + scale into the
    consuming matmul, so the MXU consumes dequantized tiles while HBM
    reads stay int8 (measured ~2.2x faster than bf16 matmuls on the
    serving chip);
  * group-wise symmetric int4 ({q: int4 [..., in, out],
    s: f32 [..., in/g, 1, out]}) — jnp.int4 packs two values/byte in
    HBM, halving weight traffic again where decode is bandwidth-bound;
    group scales along the contraction axis (GPTQ's layout) keep the
    4-bit rounding loss per-group instead of per-column.
The two forms are discriminated by scale rank (grouped scales carry one
extra axis), so ``mat`` is the single dequant point for every family.
shard_params' scale-spec handling and the XLA fusion pattern both depend
on these exact layouts, so they live in one place.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def quantize_weight(w) -> dict:
    """[..., in, out] float weight -> {"q": int8, "s": f32 per-out-channel
    scale}. The scale reduces ONLY the contraction (second-to-last) axis,
    so stacked [L, in, out] weights keep per-layer scales."""
    w32 = np.asarray(w, np.float32)
    s = np.max(np.abs(w32), axis=w32.ndim - 2, keepdims=True) / 127.0
    s = np.maximum(s, 1e-12)
    qv = np.clip(np.rint(w32 / s), -127, 127).astype(np.int8)
    return {"q": jnp.asarray(qv), "s": jnp.asarray(s, jnp.float32)}


def pick_int4_group(cin: int, group: int = 128, shard_divisor: int = 1):
    """Largest group size <= ``group`` whose count divides evenly into
    both the contraction axis and ``shard_divisor`` tp shards (so the
    grouped scale's group axis stays shardable alongside a row-parallel
    weight). None when no group >= 16 qualifies (caller falls back to
    int8). E.g. llama-2's 11008 FFN with tp=8: 128 gives 86 groups (not
    divisible by 8) -> picks 86 (128 groups)."""
    for g in range(min(group, cin), 15, -1):
        if cin % g == 0 and (cin // g) % shard_divisor == 0:
            return g
    return None


def quantize_weight_int4(w, group: int = 128, shard_divisor: int = 1) -> dict:
    """[..., in, out] float weight -> {"q": int4, "s": f32 group scale
    [..., in/g, 1, out]}. Symmetric round-to-nearest over [-8, 7] with
    max-abs group scales — the data layout (not the Hessian search) of
    GPTQ, so real GPTQ checkpoints can map onto it losslessly.

    The effective group size is pick_int4_group(...): at most ``group``,
    adjusted so the group count divides ``shard_divisor`` (the tp degree
    on the contraction axis, when known at load time). Falls back to
    per-channel int8 when no viable group exists (tiny test models)."""
    w32 = np.asarray(w, np.float32)
    cin = w32.shape[-2]
    g = pick_int4_group(cin, group, shard_divisor)
    if g is None:
        return quantize_weight(w32)
    lead, out = w32.shape[:-2], w32.shape[-1]
    wg = w32.reshape(*lead, cin // g, g, out)
    s = np.max(np.abs(wg), axis=-2, keepdims=True) / 7.0
    s = np.maximum(s, 1e-12)
    qv = np.clip(np.rint(wg / s), -8, 7)
    return {"q": jnp.asarray(qv.reshape(w32.shape), jnp.int4),
            "s": jnp.asarray(s, jnp.float32)}


def is_grouped(w) -> bool:
    """True for a group-scaled (int4) {q, s} leaf."""
    return isinstance(w, dict) and w["s"].ndim == w["q"].ndim + 1


def scale_spec(leaf: dict, weight_spec):
    """PartitionSpec for a {q, s} leaf's scale given its weight's spec.

    Flat (int8) scales [..., 1, out] follow only the output-channel
    partitioning. Grouped (int4) scales [..., in/g, 1, out] additionally
    follow the contraction-axis partitioning on their group axis, so
    row-parallel weights (wo, w_down) keep their scales device-local."""
    from jax.sharding import PartitionSpec as P

    if is_grouped(leaf):
        return P(*weight_spec[:-1], None, weight_spec[-1])
    return P(*([None] * (leaf["s"].ndim - 1) + [weight_spec[-1]]))


def mat(w, dtype):
    """Dequantize a weight leaf if needed (pass-through for dense)."""
    if isinstance(w, dict):
        q, s = w["q"], w["s"]
        if s.ndim == q.ndim + 1:            # grouped (int4) scales
            shape = q.shape
            G = s.shape[-3]
            wd = q.reshape(*shape[:-2], G, shape[-2] // G, shape[-1])
            wd = wd.astype(jnp.float32) * s
            return wd.reshape(shape).astype(dtype)
        return (q.astype(jnp.float32) * s).astype(dtype)
    return w
