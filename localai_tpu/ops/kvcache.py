"""KV-cache representations: quantized (int8) and PAGED layouts.

Two orthogonal axes of representation, both expressed as pytrees so the
engine's jitted bodies stay shape-stable and donation-friendly:

1. QUANTIZED (int8, per-row-per-head scales) — see below.
2. PAGED (Ragged Paged Attention, PAPERS.md arxiv 2604.15464): instead
   of one contiguous [L, S, C, KV, hd] reservation, KV rows live in a
   shared PAGE POOL

       {"pages": [L, n_pages, page_size, KV, hd],
        "ptab":  int32 [S, max_pages]}            (+ "scales" when int8)

   with a per-slot page table mapping logical row c of slot s to
   physical row ``ptab[s, c // page_size] * page_size + c % page_size``.
   Unallocated table entries hold the sentinel ``n_pages`` so gathers
   fill zeros and scatters drop (mode="drop") — the same OOB discipline
   the contiguous layout uses for inactive slots. The page table rides
   INSIDE the cache pytree: every jitted engine body (bursts, prefill,
   fused admission, restore) is layout-agnostic — the host allocator
   (engine/paging.py) mutates its numpy mirror and commits it as a new
   ``ptab`` leaf before dispatch. Logical shape() stays
   [L, S, max_pages*page_size, KV, hd], so capacity math is unchanged.

   Why: HBM is reserved for actual rows (lazily, page granularity)
   instead of worst-case per slot, and a shared prompt prefix is
   REF-COUNTED page sharing instead of a row copy (copy-on-write: the
   first divergent page is cloned, see clone_page / engine admission).

Quantized representation (int8, per-row-per-head scales).

`kv_cache_dtype: int8` in the model YAML (reference analogue: llama.cpp's
`cache-type-k q8_0`, plumbed via backend.proto ModelOptions and vLLM's
kv_cache_dtype knob, /root/reference/backend/python/vllm/backend.py:92-111)
switches the engine cache from a plain bf16 array to this pytree:

    {"q": int8 [L, S, C, KV, hd], "s": float32 [L, S, C, KV]}

i.e. symmetric int8 with one scale per (layer, slot, position, kv-head),
quantized over head_dim. At hd=128 the scale overhead is 4/128 = 3%, so
the cache shrinks ~1.94x vs bf16 — which is the whole point: decode on
one chip is HBM-bandwidth-bound and slot count is capped by KV size, so
halving the KV doubles the concurrent slots the weight read amortizes
over (VERDICT r4 headline math).

TPU-first numerics: the scales NEVER produce a dequantized cache tensor.
Attention folds them outside the contraction —
    scores[s,kv,g,c] = (q . k_q[c]) * s_k[s,c,kv]         (per-key logit scale)
    out = einsum(probs * s_v[s,c,kv], v_q)                 (scale into probs)
— so the MXU consumes the int8 rows cast in-register (the same fusion the
int8 weight path relies on, models/llama.py:_mat) and HBM reads stay 1
byte/element. See ops/attention.py for the score-side folding.
"""

from __future__ import annotations

import hashlib
from typing import Any, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Cache = Union[jax.Array, dict]

_EPS = 1e-8

# ---------- page identity hashing (cross-release prefix cache) ----------
#
# The engine's PrefixPageCache (engine/prefix_cache.py) indexes committed
# FULL pages by a chained block hash so a released slot's prompt-prefix
# pages stay findable after the slot is gone. The hash lives here, next
# to the layout it names, because it IS part of the page representation
# contract: a page's identity is (scope, parent chain, its token ids) —
# never its float content, which is not bit-stable across dtypes/meshes.

PAGE_HASH_BYTES = 16
PAGE_HASH_ROOT = b"\x00" * PAGE_HASH_BYTES


def page_scope(page_size: int, *parts) -> bytes:
    """Scope token for a page-hash chain: page size + any model-identity
    parts (family, layer/head geometry, cache dtype, tokenizer id...).
    Two engines whose scopes differ can NEVER alias each other's chains —
    the scope is folded into every link, so a different tokenization or
    page layout diverges at the first hash."""
    text = "|".join([f"pg={int(page_size)}"] + [str(p) for p in parts])
    return hashlib.blake2b(text.encode("utf-8"),
                           digest_size=PAGE_HASH_BYTES).digest()


def page_chain_hash(parent: bytes, token_ids, scope: bytes) -> bytes:
    """hash(scope, parent, page_token_ids) — one link of the chained
    block hash. parent is PAGE_HASH_ROOT for the first page. Token ids
    are hashed as int64 so the digest is independent of the caller's
    container (list / np array) and of numpy's default int width."""
    h = hashlib.blake2b(digest_size=PAGE_HASH_BYTES)
    h.update(scope)
    h.update(parent)
    h.update(np.asarray(token_ids, np.int64).tobytes())
    return h.digest()


def wants_quant(dtype) -> bool:
    """True when the configured cache dtype selects the int8 representation."""
    return dtype == jnp.int8


def is_paged(cache: Any) -> bool:
    """True for the page-pool layout (full cache or single-layer view)."""
    return isinstance(cache, dict) and "ptab" in cache


def is_quant(cache: Any) -> bool:
    """True when rows are stored int8 with folded scales — for BOTH the
    contiguous {"q","s"} pytree and the paged {"pages","scales","ptab"}."""
    return isinstance(cache, dict) and ("q" in cache or "scales" in cache)


def init(shape: Tuple[int, ...], dtype) -> Cache:
    """Zeros cache of the given logical shape; int8 -> quantized pytree."""
    if wants_quant(dtype):
        return {"q": jnp.zeros(shape, jnp.int8),
                "s": jnp.zeros(shape[:-1], jnp.float32)}
    return jnp.zeros(shape, dtype)


def init_paged(shape: Tuple[int, ...], dtype, page_size: int,
               num_pages: int = 0) -> Cache:
    """Page-pool cache for logical shape [L, S, C, KV, hd].

    C must be a page_size multiple; max_pages = C // page_size. num_pages
    defaults to S * max_pages — exactly the old contiguous reservation,
    never more (callers shrink it to realize HBM savings). The page table
    starts all-sentinel (nothing allocated)."""
    L, S, C, KV, hd = shape
    if C % page_size:
        raise ValueError(f"max_context {C} not a multiple of page_size "
                         f"{page_size}")
    mp = C // page_size
    np_ = num_pages or S * mp
    ptab = jnp.full((S, mp), np_, jnp.int32)
    if wants_quant(dtype):
        return {"pages": jnp.zeros((L, np_, page_size, KV, hd), jnp.int8),
                "scales": jnp.zeros((L, np_, page_size, KV), jnp.float32),
                "ptab": ptab}
    return {"pages": jnp.zeros((L, np_, page_size, KV, hd), dtype),
            "ptab": ptab}


def page_size(cache: Cache) -> int:
    return cache["pages"].shape[-3]


def num_phys_pages(cache: Cache) -> int:
    return cache["pages"].shape[-4]


def with_page_table(cache: Cache, ptab) -> Cache:
    """New cache dict with the (host-updated) page table committed."""
    out = dict(cache)
    out["ptab"] = ptab
    return out


def shape(cache: Cache) -> Tuple[int, ...]:
    """LOGICAL shape [L, S, C, KV, hd] — paged caches report
    C = max_pages * page_size so capacity math is layout-agnostic."""
    if is_paged(cache):
        pg = cache["pages"]
        s, mp = cache["ptab"].shape
        return (pg.shape[0], s, mp * pg.shape[-3]) + pg.shape[-2:]
    if is_quant(cache):
        return cache["q"].shape
    return cache.shape


def store_dtype(cache: Cache):
    """The dtype new rows must be cast to before a raw scatter (plain
    caches only; quantized caches go through quantize())."""
    if is_paged(cache):
        return cache["pages"].dtype
    if is_quant(cache):
        return jnp.int8
    return cache.dtype


def _row_index(ptab_rows: jax.Array, pg: int) -> jax.Array:
    """Expand page-table rows [..., MP] to physical row ids [..., MP*pg].
    Sentinel entries expand past the pool — gathers must use mode="fill"."""
    base = ptab_rows[..., :, None] * pg + jnp.arange(pg, dtype=jnp.int32)
    return base.reshape(*ptab_rows.shape[:-1], ptab_rows.shape[-1] * pg)


def _page_of(ptab_rows: jax.Array, cols: jax.Array, pg: int,
             n_pages: int) -> Tuple[jax.Array, jax.Array]:
    """(physical page, in-page offset) for logical columns, vectorized.

    ptab_rows [..., MP] are the owning slots' table rows aligned with
    cols [...]. Out-of-range columns (>= MP*pg, e.g. the drop sentinel
    used for inactive slots) map to page n_pages so scatters drop."""
    mp = ptab_rows.shape[-1]
    pidx = cols // pg
    page = jnp.take_along_axis(
        ptab_rows, jnp.minimum(pidx, mp - 1)[..., None], axis=-1)[..., 0]
    return jnp.where(pidx < mp, page, n_pages), cols % pg


def quantize(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 over the trailing (head_dim) axis.

    x: [..., hd] -> (q int8 [..., hd], s float32 [...]).
    """
    x32 = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(x32), axis=-1) / 127.0, _EPS)
    q = jnp.clip(jnp.round(x32 / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def dequantize(q: jax.Array, s: jax.Array, dtype) -> jax.Array:
    """Materialize float rows (slot-local ops only: prompt-cache export,
    self-extend re-rotation — never the attention hot path)."""
    return (q.astype(jnp.float32) * s[..., None]).astype(dtype)


def gather_slots(cache: Cache, slot_ids: jax.Array) -> Cache:
    """cache[:, slot_ids] per leaf (continued-prefill row read)."""
    if is_quant(cache):
        return {"q": cache["q"][:, slot_ids], "s": cache["s"][:, slot_ids]}
    return cache[:, slot_ids]


def layer(cache: Cache, li) -> Cache:
    """Select one layer (inside the lax.scan over layers)."""
    if is_paged(cache):
        out = {"pages": cache["pages"][li], "ptab": cache["ptab"]}
        if "scales" in cache:
            out["scales"] = cache["scales"][li]
        return out
    if is_quant(cache):
        return {"q": cache["q"][li], "s": cache["s"][li]}
    return cache[li]


def set_layer(cache: Cache, li, lcache: Cache) -> Cache:
    if is_paged(cache):
        out = {"pages": cache["pages"].at[li].set(lcache["pages"]),
               "ptab": cache["ptab"]}
        if "scales" in cache:
            out["scales"] = cache["scales"].at[li].set(lcache["scales"])
        return out
    if is_quant(cache):
        return {"q": cache["q"].at[li].set(lcache["q"]),
                "s": cache["s"].at[li].set(lcache["s"])}
    return cache.at[li].set(lcache)


def gather_layer_rows(lcache: Cache, slot_ids: jax.Array) -> Cache:
    """lcache[slot_ids] for a single-layer cache [S, C, KV, hd].

    Paged caches materialize the selected slots' logical rows densely
    (page gather with zero fill for unallocated pages) — prefill-path
    only; the decode hot path uses the paged kernel / gather_all_rows."""
    if is_paged(lcache):
        pg = lcache["pages"].shape[-3]
        idx = _row_index(lcache["ptab"][slot_ids], pg)          # [B, C]
        flat = lcache["pages"].reshape((-1,) + lcache["pages"].shape[-2:])
        rows = jnp.take(flat, idx, axis=0, mode="fill", fill_value=0)
        if "scales" in lcache:
            sflat = lcache["scales"].reshape(-1, lcache["scales"].shape[-1])
            return {"q": rows,
                    "s": jnp.take(sflat, idx, axis=0, mode="fill",
                                  fill_value=0)}
        return rows
    if is_quant(lcache):
        return {"q": lcache["q"][slot_ids], "s": lcache["s"][slot_ids]}
    return lcache[slot_ids]


def gather_all_rows(lcache: Cache) -> Cache:
    """Single-layer paged cache -> dense [S, C, KV, hd] rows for every
    slot (the pure-jnp decode fallback used where the Pallas ragged
    kernel is unavailable, e.g. JAX_PLATFORMS=cpu)."""
    if not is_paged(lcache):
        return lcache
    s = lcache["ptab"].shape[0]
    return gather_layer_rows(lcache, jnp.arange(s, dtype=jnp.int32))


def scatter_decode(lcache: Cache, slot_idx: jax.Array, lengths: jax.Array,
                   new_kv: jax.Array) -> Cache:
    """Write one token per slot at [slot, lengths[slot]] (mode=drop).

    lcache: single-layer [S, C, KV, hd]; new_kv: [S, KV, hd] float.
    """
    if is_paged(lcache):
        n_pages = lcache["pages"].shape[0]
        pg = lcache["pages"].shape[-3]
        page, off = _page_of(lcache["ptab"][slot_idx], lengths, pg, n_pages)
        out = dict(lcache)
        if "scales" in lcache:
            q, s = quantize(new_kv)
            out["pages"] = lcache["pages"].at[page, off].set(q, mode="drop")
            out["scales"] = lcache["scales"].at[page, off].set(s, mode="drop")
        else:
            out["pages"] = lcache["pages"].at[page, off].set(
                new_kv.astype(lcache["pages"].dtype), mode="drop")
        return out
    if is_quant(lcache):
        q, s = quantize(new_kv)
        return {"q": lcache["q"].at[slot_idx, lengths].set(q, mode="drop"),
                "s": lcache["s"].at[slot_idx, lengths].set(s, mode="drop")}
    return lcache.at[slot_idx, lengths].set(
        new_kv.astype(lcache.dtype), mode="drop")


def scatter_prefill(cache: Cache, li, rows: jax.Array, cols: jax.Array,
                    new_kv: jax.Array) -> Cache:
    """Batched prompt scatter: cache[li, rows[b,t], cols[b,t]] = new_kv[b,t].

    cache: full [L, S, C, KV, hd]; rows/cols: [B, T]; new_kv: [B, T, KV, hd].
    """
    if is_paged(cache):
        n_pages = cache["pages"].shape[1]
        pg = cache["pages"].shape[-3]
        page, off = _page_of(cache["ptab"][rows], cols, pg, n_pages)
        out = dict(cache)
        if "scales" in cache:
            q, s = quantize(new_kv)
            out["pages"] = cache["pages"].at[li, page, off].set(
                q, mode="drop")
            out["scales"] = cache["scales"].at[li, page, off].set(
                s, mode="drop")
        else:
            out["pages"] = cache["pages"].at[li, page, off].set(
                new_kv.astype(cache["pages"].dtype), mode="drop")
        return out
    if is_quant(cache):
        q, s = quantize(new_kv)
        return {"q": cache["q"].at[li, rows, cols].set(q, mode="drop"),
                "s": cache["s"].at[li, rows, cols].set(s, mode="drop")}
    return cache.at[li, rows, cols].set(
        new_kv.astype(cache.dtype), mode="drop")


def scatter_ragged(cache: Cache, li, slot_of: jax.Array, cols: jax.Array,
                   new_kv: jax.Array) -> Cache:
    """RAGGED packed-prefill scatter: cache[li, slot_of[n], cols[n]] =
    new_kv[n] for a [N]-token pack whose tokens belong to many slots.

    slot_of/cols: [N] int32; new_kv: [N, KV, hd] float. Pad tokens use
    the column sentinel C (paged: any col >= MP*page_size) so the write
    DROPS — the same OOB discipline every other scatter here uses. For
    the paged layout the write goes through each token's own slot's page
    table, i.e. this is the "ragged scatter into the page pool" of the
    packed prefill step (engine.py)."""
    return scatter_prefill(cache, li, slot_of[None], cols[None],
                           new_kv[None])


def tree_slot_update(cache: Cache, dst, new_rows: Cache) -> Cache:
    """cache[:, dst] = new_rows per leaf (fork / restore bodies).

    Paged caches scatter the dense row set into dst's OWN pages via the
    table; rows over unallocated pages are dropped. (Page SHARING is a
    host-side table edit, not a device op — see engine/paging.py.)"""
    if is_paged(cache):
        pg = cache["pages"].shape[-3]
        c = cache["ptab"].shape[1] * pg
        cols = jnp.arange(c, dtype=jnp.int32)
        # cols always < C = MP*pg, so the table lookup is in range; the
        # sentinel entries of unallocated pages drop the writes themselves
        page = jnp.take(cache["ptab"][dst], cols // pg)
        off = cols % pg
        out = dict(cache)
        if "scales" in cache:
            out["pages"] = cache["pages"].at[:, page, off].set(
                new_rows["q"], mode="drop")
            out["scales"] = cache["scales"].at[:, page, off].set(
                new_rows["s"], mode="drop")
        else:
            out["pages"] = cache["pages"].at[:, page, off].set(
                new_rows.astype(cache["pages"].dtype), mode="drop")
        return out
    if is_quant(cache):
        return {"q": cache["q"].at[:, dst].set(new_rows["q"]),
                "s": cache["s"].at[:, dst].set(new_rows["s"])}
    return cache.at[:, dst].set(new_rows)


def clone_page(cache: Cache, src_page, dst_page) -> Cache:
    """Copy one physical page (all layers) — the copy-on-write primitive:
    admission clones the FIRST DIVERGENT page of a shared prefix before
    the new request's prefill writes into it."""
    out = dict(cache)
    out["pages"] = cache["pages"].at[:, dst_page].set(cache["pages"][:, src_page])
    if "scales" in cache:
        out["scales"] = cache["scales"].at[:, dst_page].set(
            cache["scales"][:, src_page])
    return out


def gather_pages(cache: Cache, page_ids: jax.Array) -> Cache:
    """Read whole physical pages [L, n, page_size, KV, hd] (+ scales
    [L, n, page_size, KV] when int8) — the device->host OFFLOAD read.
    Dtype-preserving: int8 pages stay quantized, bf16 stays bf16, so the
    host tier stores the exact device representation. page_ids out of
    range clip (callers pad with repeats and slice host-side)."""
    rows = jnp.take(cache["pages"], page_ids, axis=1, mode="clip")
    if "scales" in cache:
        return {"q": rows,
                "s": jnp.take(cache["scales"], page_ids, axis=1,
                              mode="clip")}
    return rows


def scatter_pages(cache: Cache, page_ids: jax.Array, rows: Cache) -> Cache:
    """Write whole pages back into the pool — the host->device RESTORE
    upload, gather_pages' inverse. rows carries the representation
    gather_pages produced; sentinel page_ids (>= n_pages) DROP, so
    callers pad restore batches to a compiled bucket size."""
    out = dict(cache)
    if "scales" in cache:
        out["pages"] = cache["pages"].at[:, page_ids].set(
            rows["q"], mode="drop")
        out["scales"] = cache["scales"].at[:, page_ids].set(
            rows["s"], mode="drop")
    else:
        out["pages"] = cache["pages"].at[:, page_ids].set(
            rows.astype(cache["pages"].dtype), mode="drop")
    return out


def slot_rows(cache: Cache, slot) -> Cache:
    """cache[:, slot] per leaf -> [L, C, KV, hd] (+ scales)."""
    if is_paged(cache):
        pg = cache["pages"].shape[-3]
        idx = _row_index(cache["ptab"][slot], pg)               # [C]
        flat = cache["pages"].reshape(
            (cache["pages"].shape[0], -1) + cache["pages"].shape[-2:])
        rows = jnp.take(flat, idx, axis=1, mode="fill", fill_value=0)
        if "scales" in cache:
            sflat = cache["scales"].reshape(
                cache["scales"].shape[0], -1, cache["scales"].shape[-1])
            return {"q": rows,
                    "s": jnp.take(sflat, idx, axis=1, mode="fill",
                                  fill_value=0)}
        return rows
    if is_quant(cache):
        return {"q": cache["q"][:, slot], "s": cache["s"][:, slot]}
    return cache[:, slot]


def where_rows(mask_c: jax.Array, a: Cache, b: Cache) -> Cache:
    """Select rows along the C axis between two row sets [L, C, KV, hd].

    mask_c: [C] bool (True -> a). Scales select with the same row mask.
    """
    if is_quant(a):
        return {"q": jnp.where(mask_c[None, :, None, None], a["q"], b["q"]),
                "s": jnp.where(mask_c[None, :, None], a["s"], b["s"])}
    return jnp.where(mask_c[None, :, None, None], a, b)


def rows_to_float(rows: Cache, dtype) -> jax.Array:
    """[L, C, KV, hd] row set -> dense float (prompt-cache save path)."""
    if is_quant(rows):
        return dequantize(rows["q"], rows["s"], dtype)
    return rows.astype(dtype)


def rows_from_float(rows: jax.Array, like: Cache) -> Cache:
    """Dense float [L, C, KV, hd] -> the cache's ROW representation
    (what tree_slot_update accepts as new_rows)."""
    if is_quant(like):
        q, s = quantize(rows)
        return {"q": q, "s": s}
    return rows.astype(store_dtype(like))


def cache_sharding(mesh, spec5):
    """NamedShardings for the cache under a 5-dim PartitionSpec; the scale
    leaf ([L, S, C, KV]) drops the trailing head_dim entry."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    full = NamedSharding(mesh, P(*spec5))
    scales = NamedSharding(mesh, P(*spec5[:-1]))
    return full, scales


def paged_sharding(mesh, spec5):
    """Paged layout under the same LOGICAL 5-dim spec: pages
    [L, n_pages, page_size, KV, hd] keep the layer and kv-head entries
    (kv heads on tp); the slot/context entries have no physical analogue
    — any slot's rows may live in any page, so the page axis is
    replicated. The page table is replicated (parallel/sharding.py
    page_table_spec): it is tiny and every shard needs all of it."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    pspec = (spec5[0], None, None, spec5[3], spec5[4])
    return (NamedSharding(mesh, P(*pspec)),
            NamedSharding(mesh, P(*pspec[:-1])),
            NamedSharding(mesh, P(None, None)))


def device_put(cache: Cache, mesh, spec5) -> Cache:
    from jax.sharding import NamedSharding, PartitionSpec as P

    if is_paged(cache):
        pages_sh, scales_sh, ptab_sh = paged_sharding(mesh, spec5)
        out = {"pages": jax.device_put(cache["pages"], pages_sh),
               "ptab": jax.device_put(cache["ptab"], ptab_sh)}
        if "scales" in cache:
            out["scales"] = jax.device_put(cache["scales"], scales_sh)
        return out
    if is_quant(cache):
        full, scales = cache_sharding(mesh, spec5)
        return {"q": jax.device_put(cache["q"], full),
                "s": jax.device_put(cache["s"], scales)}
    return jax.device_put(cache, NamedSharding(mesh, P(*spec5)))
