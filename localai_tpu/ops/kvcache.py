"""Quantized KV-cache representation (int8, per-row-per-head scales).

`kv_cache_dtype: int8` in the model YAML (reference analogue: llama.cpp's
`cache-type-k q8_0`, plumbed via backend.proto ModelOptions and vLLM's
kv_cache_dtype knob, /root/reference/backend/python/vllm/backend.py:92-111)
switches the engine cache from a plain bf16 array to this pytree:

    {"q": int8 [L, S, C, KV, hd], "s": float32 [L, S, C, KV]}

i.e. symmetric int8 with one scale per (layer, slot, position, kv-head),
quantized over head_dim. At hd=128 the scale overhead is 4/128 = 3%, so
the cache shrinks ~1.94x vs bf16 — which is the whole point: decode on
one chip is HBM-bandwidth-bound and slot count is capped by KV size, so
halving the KV doubles the concurrent slots the weight read amortizes
over (VERDICT r4 headline math).

TPU-first numerics: the scales NEVER produce a dequantized cache tensor.
Attention folds them outside the contraction —
    scores[s,kv,g,c] = (q . k_q[c]) * s_k[s,c,kv]         (per-key logit scale)
    out = einsum(probs * s_v[s,c,kv], v_q)                 (scale into probs)
— so the MXU consumes the int8 rows cast in-register (the same fusion the
int8 weight path relies on, models/llama.py:_mat) and HBM reads stay 1
byte/element. See ops/attention.py for the score-side folding.
"""

from __future__ import annotations

from typing import Any, Tuple, Union

import jax
import jax.numpy as jnp

Cache = Union[jax.Array, dict]

_EPS = 1e-8


def wants_quant(dtype) -> bool:
    """True when the configured cache dtype selects the int8 representation."""
    return dtype == jnp.int8


def is_quant(cache: Any) -> bool:
    return isinstance(cache, dict)


def init(shape: Tuple[int, ...], dtype) -> Cache:
    """Zeros cache of the given logical shape; int8 -> quantized pytree."""
    if wants_quant(dtype):
        return {"q": jnp.zeros(shape, jnp.int8),
                "s": jnp.zeros(shape[:-1], jnp.float32)}
    return jnp.zeros(shape, dtype)


def shape(cache: Cache) -> Tuple[int, ...]:
    if is_quant(cache):
        return cache["q"].shape
    return cache.shape


def store_dtype(cache: Cache):
    """The dtype new rows must be cast to before a raw scatter (plain
    caches only; quantized caches go through quantize())."""
    if is_quant(cache):
        return jnp.int8
    return cache.dtype


def quantize(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 over the trailing (head_dim) axis.

    x: [..., hd] -> (q int8 [..., hd], s float32 [...]).
    """
    x32 = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(x32), axis=-1) / 127.0, _EPS)
    q = jnp.clip(jnp.round(x32 / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def dequantize(q: jax.Array, s: jax.Array, dtype) -> jax.Array:
    """Materialize float rows (slot-local ops only: prompt-cache export,
    self-extend re-rotation — never the attention hot path)."""
    return (q.astype(jnp.float32) * s[..., None]).astype(dtype)


def gather_slots(cache: Cache, slot_ids: jax.Array) -> Cache:
    """cache[:, slot_ids] per leaf (continued-prefill row read)."""
    if is_quant(cache):
        return {"q": cache["q"][:, slot_ids], "s": cache["s"][:, slot_ids]}
    return cache[:, slot_ids]


def layer(cache: Cache, li) -> Cache:
    """Select one layer (inside the lax.scan over layers)."""
    if is_quant(cache):
        return {"q": cache["q"][li], "s": cache["s"][li]}
    return cache[li]


def set_layer(cache: Cache, li, lcache: Cache) -> Cache:
    if is_quant(cache):
        return {"q": cache["q"].at[li].set(lcache["q"]),
                "s": cache["s"].at[li].set(lcache["s"])}
    return cache.at[li].set(lcache)


def gather_layer_rows(lcache: Cache, slot_ids: jax.Array) -> Cache:
    """lcache[slot_ids] for a single-layer cache [S, C, KV, hd]."""
    if is_quant(lcache):
        return {"q": lcache["q"][slot_ids], "s": lcache["s"][slot_ids]}
    return lcache[slot_ids]


def scatter_decode(lcache: Cache, slot_idx: jax.Array, lengths: jax.Array,
                   new_kv: jax.Array) -> Cache:
    """Write one token per slot at [slot, lengths[slot]] (mode=drop).

    lcache: single-layer [S, C, KV, hd]; new_kv: [S, KV, hd] float.
    """
    if is_quant(lcache):
        q, s = quantize(new_kv)
        return {"q": lcache["q"].at[slot_idx, lengths].set(q, mode="drop"),
                "s": lcache["s"].at[slot_idx, lengths].set(s, mode="drop")}
    return lcache.at[slot_idx, lengths].set(
        new_kv.astype(lcache.dtype), mode="drop")


def scatter_prefill(cache: Cache, li, rows: jax.Array, cols: jax.Array,
                    new_kv: jax.Array) -> Cache:
    """Batched prompt scatter: cache[li, rows[b,t], cols[b,t]] = new_kv[b,t].

    cache: full [L, S, C, KV, hd]; rows/cols: [B, T]; new_kv: [B, T, KV, hd].
    """
    if is_quant(cache):
        q, s = quantize(new_kv)
        return {"q": cache["q"].at[li, rows, cols].set(q, mode="drop"),
                "s": cache["s"].at[li, rows, cols].set(s, mode="drop")}
    return cache.at[li, rows, cols].set(
        new_kv.astype(cache.dtype), mode="drop")


def tree_slot_update(cache: Cache, dst, new_rows: Cache) -> Cache:
    """cache[:, dst] = new_rows per leaf (fork / restore bodies)."""
    if is_quant(cache):
        return {"q": cache["q"].at[:, dst].set(new_rows["q"]),
                "s": cache["s"].at[:, dst].set(new_rows["s"])}
    return cache.at[:, dst].set(new_rows)


def slot_rows(cache: Cache, slot) -> Cache:
    """cache[:, slot] per leaf -> [L, C, KV, hd] (+ scales)."""
    if is_quant(cache):
        return {"q": cache["q"][:, slot], "s": cache["s"][:, slot]}
    return cache[:, slot]


def where_rows(mask_c: jax.Array, a: Cache, b: Cache) -> Cache:
    """Select rows along the C axis between two row sets [L, C, KV, hd].

    mask_c: [C] bool (True -> a). Scales select with the same row mask.
    """
    if is_quant(a):
        return {"q": jnp.where(mask_c[None, :, None, None], a["q"], b["q"]),
                "s": jnp.where(mask_c[None, :, None], a["s"], b["s"])}
    return jnp.where(mask_c[None, :, None, None], a, b)


def rows_to_float(rows: Cache, dtype) -> jax.Array:
    """[L, C, KV, hd] row set -> dense float (prompt-cache save path)."""
    if is_quant(rows):
        return dequantize(rows["q"], rows["s"], dtype)
    return rows.astype(dtype)


def rows_from_float(rows: jax.Array, like: Cache) -> Cache:
    """Dense float [L, C, KV, hd] -> the cache's representation."""
    if is_quant(like):
        q, s = quantize(rows)
        return {"q": q, "s": s}
    return rows.astype(like.dtype)


def cache_sharding(mesh, spec5):
    """NamedShardings for the cache under a 5-dim PartitionSpec; the scale
    leaf ([L, S, C, KV]) drops the trailing head_dim entry."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    full = NamedSharding(mesh, P(*spec5))
    scales = NamedSharding(mesh, P(*spec5[:-1]))
    return full, scales


def device_put(cache: Cache, mesh, spec5) -> Cache:
    if is_quant(cache):
        full, scales = cache_sharding(mesh, spec5)
        return {"q": jax.device_put(cache["q"], full),
                "s": jax.device_put(cache["s"], scales)}
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.device_put(cache, NamedSharding(mesh, P(*spec5)))
