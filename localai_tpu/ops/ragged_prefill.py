"""RAGGED PACKED PREFILL attention — jnp reference / CPU fallback.

One scheduler tick's worth of prompt tails from MANY slots is packed
into a single [N]-token batch ("Ragged Paged Attention", PAPERS.md
arxiv 2604.15464; TokenWeave, arxiv 2505.11329, motivates collapsing
the per-slot dispatches): segment b occupies the contiguous pack range
[seg_off[b], seg_off[b] + seg_len[b]) and its token at pack index n sits
at absolute cache position seg_start[b] + (n - seg_off[b]) of slot
seg_slots[b]. Each query attends over

  * its slot's COMMITTED cache rows [0, seg_start[b])  (continued
    segments only — prefix reuse, chunked long prompts, context-shift
    re-prefill), and
  * the pack's own keys at indices m <= n with seg_of[m] == seg_of[n]
    (intra-chunk causal attention).

Together that is exactly full causal attention for every packed token —
the same math the per-slot paths (ops/attention.py causal_attention /
mixed_prefill_attention) compute, so greedy output is preserved.

The cache term walks segments with a lax.scan and SELECT-accumulates
per-token online-softmax state (each token belongs to exactly one
segment, so the "online" merge is a select): peak memory stays one
segment's [KV, G, N, C] score block instead of a dense [B, ...] blow-up,
mirroring the page walk the Pallas kernel
(ops/pallas/ragged_prefill.py) does in VMEM. Follows the module rule of
ops/attention.py: cache rows are read BEFORE the caller scatters this
pack's keys, and int8 {"q","s"} rows fold their scales outside the
contraction (scores for K, probs for V) — no dequantized cache
materializes.

Pad conventions (shared with the engine packer and the Pallas kernel):
pad tokens carry seg_of == B_sentinel (>= the real segment count) so
they only ever attend other pads (a pad always sees itself — no NaN
softmax rows); pad SEGMENTS carry seg_len == 0 and a sentinel slot id,
so they select nothing and their (clipped) cache gather is dead weight
the masks discard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from localai_tpu.ops import kvcache

_NEG_INF = -1e30


def _rows_scales(rows):
    """Split a gathered row set into (float rows, scales|None) — the
    int8 fold contract of ops/attention.py::_split_cache."""
    if isinstance(rows, dict):
        return rows["q"], rows["s"]
    return rows, None


def ragged_prefill_attention(q, chunk_k, chunk_v, seg_of, seg_slots,
                             seg_start, lck, lcv, q_per_kv: int,
                             continued: bool = False):
    """Packed ragged prefill attention (see module doc).

    q: [N, H, hd]; chunk_k/chunk_v: [N, KV, hd] (this pack's keys/values,
    NOT yet scattered into the cache); seg_of: [N] int32 (pad sentinel >=
    B); seg_slots/seg_start: [B] int32 (pad slot ids may be any value —
    pad segments match no token); lck/lcv: single-layer cache in any
    layout (paged / contiguous / int8), only read when ``continued``.
    ``continued`` is STATIC: False compiles the pure intra-pack program
    (fresh prompts have no committed rows). Returns [N, H, hd] (q.dtype).
    """
    dtype = q.dtype
    N, H, hd = q.shape
    KV = chunk_k.shape[1]
    G = q_per_kv
    qg = q.reshape(N, KV, G, hd)
    scale = jnp.float32(1.0) / jnp.sqrt(hd).astype(jnp.float32)
    sc_pack = jnp.einsum("nkgd,mkd->kgnm", qg,
                         chunk_k).astype(jnp.float32) * scale
    idx = jnp.arange(N, dtype=jnp.int32)
    mask_pack = (seg_of[:, None] == seg_of[None, :]) \
        & (idx[None, :] <= idx[:, None])                       # [N(q), N(k)]
    sc_pack = jnp.where(mask_pack[None, None], sc_pack, _NEG_INF)
    if not continued:
        probs = jax.nn.softmax(sc_pack, axis=-1).astype(dtype)
        out = jnp.einsum("kgnm,mkd->nkgd", probs, chunk_v)
        return out.reshape(N, H, hd)

    B = seg_slots.shape[0]
    m0 = jnp.full((KV, G, N), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((KV, G, N), jnp.float32)
    a0 = jnp.zeros((KV, G, N, hd), jnp.float32)
    # ONE batched page gather for all segments' committed rows (the
    # per-layer cost the decode fallback already pays per step); the
    # scan then walks the stacked rows — per-iteration work is one
    # [N, C] score block, never a gather
    k_all, sk_all = _rows_scales(kvcache.gather_layer_rows(lck, seg_slots))
    v_all, sv_all = _rows_scales(kvcache.gather_layer_rows(lcv, seg_slots))
    def seg_term(carry, seg):
        m_c, l_c, a_c = carry
        if sk_all is None:
            b, start, k_rows, v_rows = seg
            sk = sv = None
        else:
            b, start, k_rows, sk, v_rows, sv = seg
        C = k_rows.shape[0]
        sc = jnp.einsum("nkgd,ckd->kgnc", qg,
                        k_rows.astype(dtype)).astype(jnp.float32) * scale
        if sk is not None:
            sc = sc * sk.T[:, None, None, :]                 # [KV,1,1,C]
        mask = (seg_of == b)[:, None] \
            & (jnp.arange(C, dtype=jnp.int32)[None, :] < start)  # [N, C]
        sc = jnp.where(mask[None, None], sc, _NEG_INF)
        m_b = jnp.max(sc, axis=-1)                           # [KV, G, N]
        # explicit zero for masked columns: an all-masked row has
        # m_b == _NEG_INF and exp(sc - m_b) would be exp(0) == 1 there
        p = jnp.where(mask[None, None], jnp.exp(sc - m_b[..., None]), 0.0)
        l_b = jnp.sum(p, axis=-1)
        if sv is not None:
            p = p * sv.T[:, None, None, :]
        a_b = jnp.einsum("kgnc,ckd->kgnd", p,
                         v_rows.astype(jnp.float32))
        sel = (seg_of == b)[None, None, :]                   # [1, 1, N]
        return (jnp.where(sel, m_b, m_c), jnp.where(sel, l_b, l_c),
                jnp.where(sel[..., None], a_b, a_c)), None

    bs = jnp.arange(B, dtype=jnp.int32)
    xs = (bs, seg_start, k_all, v_all) if sk_all is None else \
        (bs, seg_start, k_all, sk_all, v_all, sv_all)
    (m_c, l_c, a_c), _ = jax.lax.scan(seg_term, (m0, l0, a0), xs)
    return _combine(qg, chunk_v, sc_pack, mask_pack, m_c, l_c, a_c,
                    N, H, hd, dtype)


def _combine(qg, chunk_v, sc_pack, mask_pack, m_c, l_c, a_c, N, H, hd,
             dtype):
    """Joint softmax over [cache cols, pack cols] via the accumulated
    cache-side stats: every token has at least its own pack key, so
    m_tot is finite and the denominator is positive."""
    m_pack = jnp.max(sc_pack, axis=-1)                       # [KV, G, N]
    m_tot = jnp.maximum(m_c, m_pack)
    p_pack = jnp.where(mask_pack[None, None],
                       jnp.exp(sc_pack - m_tot[..., None]), 0.0)
    alpha = jnp.exp(m_c - m_tot)                             # 0 when no cache
    denom = l_c * alpha + jnp.sum(p_pack, axis=-1)
    out = (a_c * alpha[..., None]
           + jnp.einsum("kgnm,mkd->kgnd", p_pack,
                        chunk_v.astype(jnp.float32))) / denom[..., None]
    return out.transpose(2, 0, 1, 3).reshape(N, H, hd).astype(dtype)
