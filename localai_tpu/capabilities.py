"""Capability orchestration: the glue between HTTP handlers and backends.

Parity with the reference's core/backend package (reference:
core/backend/llm.go ModelInference :35-174 + Finetune :179-227,
embeddings.go, image.go, tts.go, transcript.go, rerank.go, stores.go,
tokenize.go, options.go ModelOptions/gRPCPredictOpts mapping :14,181).
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
from typing import Callable, Iterator, Optional

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.modelmgr.loader import ModelLoader
from localai_tpu.services.errors import wrap_backend_error


def build_model_options(mc: ModelConfig, app: AppConfig) -> pb.ModelOptions:
    """ModelConfig -> proto ModelOptions (reference: options.go:14-178)."""
    return pb.ModelOptions(
        model=mc.model or mc.name,
        model_path=app.models_path,
        context_size=mc.context_size or app.context_size,
        num_slots=mc.num_slots,
        dtype=mc.dtype,
        kv_cache_dtype=mc.kv_cache_dtype,
        quantization=mc.quantization,
        mesh_tp=int(mc.mesh.get("tp", app.mesh_tp) or 0),
        mesh_dp=int(mc.mesh.get("dp", app.mesh_dp) or 1),
        prefill_buckets=[int(b) for b in mc.prefill_buckets],
        tokenizer=mc.tokenizer,
        embeddings=mc.embeddings,
        mmproj=mc.mmproj,
        draft_model=mc.draft_model,
        lora_adapter=mc.lora_adapter,
        lora_base=mc.lora_base,
        lora_scale=mc.lora_scale,
        scheduler=mc.scheduler,
        audio_path=mc.audio_path,
        options=",".join(
            ([f"ga_n={mc.group_attn_n},ga_w={mc.group_attn_w}"]
             if mc.group_attn_n > 1 else [])
            + ([f"controlnet={mc.controlnet}"] if mc.controlnet else [])
            + ([f"decode_burst={mc.decode_burst}"]
               if mc.decode_burst > 0 else [])
            + [str(o) for o in (mc.options or [])]),
    )


def build_predict_options(mc: ModelConfig, prompt: str, overrides: Optional[dict] = None,
                          correlation_id: str = "") -> pb.PredictOptions:
    """Merged sampling config -> proto PredictOptions (reference:
    options.go:181-254 gRPCPredictOpts)."""
    sp = mc.sampling_host(overrides)
    o = overrides or {}
    opts = pb.PredictOptions(
        prompt=prompt,
        max_tokens=int(o.get("max_tokens") or mc.parameters.max_tokens or 256),
        temperature=sp.temperature,
        top_k=sp.top_k,
        top_p=sp.top_p,
        min_p=sp.min_p,
        typical_p=sp.typical_p,
        repeat_penalty=sp.repeat_penalty,
        repeat_last_n=sp.repeat_last_n,
        presence_penalty=sp.presence_penalty,
        frequency_penalty=sp.frequency_penalty,
        mirostat=sp.mirostat,
        mirostat_tau=sp.mirostat_tau,
        mirostat_eta=sp.mirostat_eta,
        seed=sp.seed,
        stop_sequences=list(o.get("stop") or mc.stopwords or []),
        ignore_eos=bool(o.get("ignore_eos", False)),
        echo=bool(o.get("echo", False)),
        grammar=o.get("grammar", ""),
        correlation_id=correlation_id,
        prompt_cache_path=mc.prompt_cache_path,
        prompt_cache_ro=mc.prompt_cache_ro,
        prompt_cache_all=mc.prompt_cache_all,
    )
    for tok, bias in (sp.logit_bias or {}).items():
        opts.logit_bias[int(tok)] = float(bias)
    for img in o.get("images", []) or []:
        opts.images.append(img)
    for aud in o.get("audios", []) or []:
        opts.audios.append(aud)
    for vid in o.get("videos", []) or []:
        opts.videos.append(vid)
    return opts


def predict_metadata(overrides: Optional[dict],
                     correlation_id: str = "") -> Optional[tuple]:
    """gRPC invocation metadata for per-request hints: the compiled
    descriptor cannot grow PredictOptions fields, so the priority class
    rides ``localai-priority`` (ISSUE 10) and the request's trace
    context rides ``localai-trace-id`` (ISSUE 12) — the backend keys
    its RingTracer spans and event-log records by it, so the frontend
    and backend halves of a request share ONE trace id."""
    md = []
    pr = (overrides or {}).get("priority")
    if pr:
        md.append(("localai-priority", str(pr).strip().lower()))
    if correlation_id:
        md.append(("localai-trace-id", str(correlation_id)))
    return tuple(md) or None


def weight_prefetch_enabled(mc: ModelConfig) -> bool:
    """Mirrors the backend's parse of the ``weight_prefetch`` option
    (ISSUE 19) — default OFF: no request log consumers, no warmer
    threads, nothing constructed."""
    for o in mc.options or []:
        s = str(o)
        if s.startswith("weight_prefetch="):
            return s.split("=", 1)[1].strip().lower() in (
                "1", "true", "on", "yes")
    return False


class WeightByteWarmer:
    """Frontend-side half of predictive weight prefetch (ISSUE 19,
    PRESERVE-style): sequentially reads the predicted-next model's
    checkpoint bytes so they sit warm in the host page cache when the
    BACKEND process (a separate process — no parsed-leaf handoff is
    possible across that boundary) mmaps them for its streamed load.
    The in-process parsed-leaf cache lives in
    engine/weights.WeightPrefetcher; this class shares its snapshot
    shape so /metrics exports either identically."""

    _EXTS = (".safetensors", ".gguf", ".bin")

    def __init__(self, max_bytes: int = 8 << 30):
        self.max_bytes = int(max_bytes)
        self._warmed: set = set()
        self._inflight: set = set()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.bytes_total = 0
        self.prefetches = 0

    def note_request(self, model_dir: str):
        """A request for ``model_dir`` arrived: count a hit if its bytes
        were warmed ahead of time (consumes the mark — re-warms happen
        on the next prediction)."""
        with self._lock:
            if model_dir in self._warmed:
                self._warmed.discard(model_dir)
                self.hits += 1
            else:
                self.misses += 1

    def prefetch(self, model_dir: str, wait: bool = False):
        with self._lock:
            if model_dir in self._warmed or model_dir in self._inflight:
                return
            self._inflight.add(model_dir)
        t = threading.Thread(target=self._warm, args=(model_dir,),
                             name="weight-byte-warm", daemon=True)
        t.start()
        if wait:
            t.join()

    def _warm(self, model_dir: str):
        total = 0
        try:
            files = []
            if os.path.isdir(model_dir):
                for fn in sorted(os.listdir(model_dir)):
                    if fn.endswith(self._EXTS):
                        files.append(os.path.join(model_dir, fn))
            elif os.path.isfile(model_dir):
                files = [model_dir]
            for path in files:
                with open(path, "rb", buffering=0) as f:
                    while total < self.max_bytes:
                        chunk = f.read(16 << 20)
                        if not chunk:
                            break
                        total += len(chunk)
            if total:
                with self._lock:
                    self._warmed.add(model_dir)
                    self.bytes_total += total
                    self.prefetches += 1
        except OSError:
            pass
        finally:
            with self._lock:
                self._inflight.discard(model_dir)

    def snapshot(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "bytes_total": self.bytes_total,
                    "prefetches": self.prefetches,
                    "warmed": sorted(self._warmed)}


def trace_enabled(mc: ModelConfig) -> bool:
    """Is request tracing on for this model? Mirrors the backend's
    parse of the ``trace`` option so the frontend's per-request spans
    (HTTP/route/gRPC-hop) go quiet exactly when the backend's do —
    trace=0 is a true no-op on BOTH sides of the boundary."""
    for o in mc.options or []:
        s = str(o)
        if s.startswith("trace="):
            return s.split("=", 1)[1].strip().lower() not in (
                "0", "false", "off", "no")
    return True


def finetune_response(mc: ModelConfig, prediction: str, prompt: str = "",
                      echo: bool = False) -> str:
    """Post-process model output (reference: Finetune, llm.go:179-227)."""
    if echo:
        prediction = prompt + prediction
    for c in mc.cutstrings:
        prediction = re.sub(c, "", prediction)
    for r in mc.extract_regex:
        m = re.search(r, prediction)
        if m:
            prediction = m.group(0)
    for t in mc.trimspace:
        # reference semantics: strip the token as a PREFIX once, then
        # surrounding whitespace (llm.go:219-220) — not replace-all
        prediction = prediction.removeprefix(t).strip()
    for t in mc.trimsuffix:
        prediction = prediction.removesuffix(t).strip()
    return prediction


@dataclasses.dataclass
class TokenChunk:
    text: str
    token_id: int = -1
    finish_reason: str = ""
    completion_tokens: int = 0
    prompt_tokens: int = 0
    # every member token of a burst-coalesced chunk (token_id is the last)
    token_ids: Optional[list] = None
    logprobs: Optional[list] = None


class Capabilities:
    """Per-app singleton bundling loader + configs (reference: the
    (BackendConfigLoader, ModelLoader) pair threaded everywhere)."""

    def __init__(self, app: AppConfig, loader: ModelLoader, configs: dict):
        self.app = app
        self.loader = loader
        self.configs = configs  # name -> ModelConfig
        self._lock = threading.Lock()
        # predictive weight prefetch feed (ISSUE 19): every model load
        # notes its name; the warmer is built lazily on the first model
        # that opts in (weight_prefetch=1), so default-off constructs
        # nothing beyond the log (one dict, no threads)
        from localai_tpu.services.gallery_service import ModelRequestLog

        self.model_requests = ModelRequestLog()
        self.weight_prefetcher: Optional[WeightByteWarmer] = None

    # ---- config resolution ----

    def resolve(self, model_name: str) -> ModelConfig:
        mc = self.configs.get(model_name)
        if mc is None:
            # on-the-fly config for raw model paths (reference behavior:
            # unknown model names get a default config if the file exists)
            mc = ModelConfig(name=model_name)
            mc.model = model_name
        return mc

    def _model_dir(self, mc: ModelConfig) -> str:
        d = mc.model or mc.name
        if not os.path.isabs(d):
            d = os.path.join(self.app.models_path, d)
        return d

    def _note_request(self, mc: ModelConfig):
        """Feed the prediction log and (when this model opted in) warm
        the predicted-NEXT model's checkpoint bytes so a gallery-style
        model switch finds them in the host page cache (ISSUE 19)."""
        self.model_requests.note(mc.name)
        if not weight_prefetch_enabled(mc):
            return
        if self.weight_prefetcher is None:
            with self._lock:
                if self.weight_prefetcher is None:
                    self.weight_prefetcher = WeightByteWarmer()
        self.weight_prefetcher.note_request(self._model_dir(mc))
        nxt = self.model_requests.predict_next(exclude={mc.name})
        if not nxt:
            return
        nmc = self.configs.get(nxt)
        if nmc is not None:
            self.weight_prefetcher.prefetch(self._model_dir(nmc))

    def _load(self, mc: ModelConfig):
        self._note_request(mc)
        opts = build_model_options(mc, self.app)
        if mc.backend:
            return self.loader.backend_loader(mc.backend, mc.name, opts)
        return self.loader.greedy_loader(mc.name, opts)

    # ---- LLM ----

    def inference_stream(self, mc: ModelConfig, prompt: str,
                         overrides: Optional[dict] = None,
                         correlation_id: str = "") -> Iterator[TokenChunk]:
        """Streaming inference (reference: ModelInference llm.go:35-174)."""
        import time as _time

        from localai_tpu.services.tracing import frontend_tracer

        lm = self._load(mc)
        popts = build_predict_options(mc, prompt, overrides, correlation_id)
        md = predict_metadata(overrides, correlation_id)
        tr = frontend_tracer() if trace_enabled(mc) else None
        t_call = _time.monotonic()
        t_first = None
        lm.mark_busy()
        try:
            for reply in lm.client.predict_stream(popts, metadata=md):
                if t_first is None:
                    t_first = _time.monotonic()
                yield TokenChunk(
                    text=reply.message.decode("utf-8", errors="replace"),
                    token_id=reply.token_id,
                    finish_reason=reply.finish_reason,
                    completion_tokens=reply.tokens,
                    prompt_tokens=reply.prompt_tokens,
                    token_ids=list(reply.token_ids) or None,
                    logprobs=list(reply.logprobs) or None,
                )
        except Exception as e:
            # a backend abort (shed/timeout/stall) or a mid-stream crash
            # must reach the client as a typed ServingError with the
            # right HTTP status + Retry-After, never a raw RpcError
            raise wrap_backend_error(e, mc.name) from e
        finally:
            lm.mark_idle()
            if tr is not None and tr.enabled:
                t1 = _time.monotonic()
                if t_first is not None:
                    tr.record("grpc_first_reply", "grpc", t_call, t_first,
                              rid=correlation_id, args={"model": mc.name})
                tr.record("grpc_predict_stream", "grpc", t_call, t1,
                          rid=correlation_id, args={"model": mc.name})

    def inference(self, mc: ModelConfig, prompt: str,
                  overrides: Optional[dict] = None,
                  correlation_id: str = "") -> TokenChunk:
        import time as _time

        from localai_tpu.services.tracing import frontend_tracer

        lm = self._load(mc)
        popts = build_predict_options(mc, prompt, overrides, correlation_id)
        md = predict_metadata(overrides, correlation_id)
        tr = frontend_tracer() if trace_enabled(mc) else None
        t_call = _time.monotonic()
        lm.mark_busy()
        try:
            reply = lm.client.predict(popts, metadata=md)
        except Exception as e:
            raise wrap_backend_error(e, mc.name) from e
        finally:
            lm.mark_idle()
            if tr is not None and tr.enabled:
                tr.record("grpc_predict", "grpc", t_call, _time.monotonic(),
                          rid=correlation_id, args={"model": mc.name})
        text = finetune_response(mc, reply.message.decode("utf-8", errors="replace"))
        return TokenChunk(
            text=text, finish_reason=reply.finish_reason or "stop",
            completion_tokens=reply.tokens, prompt_tokens=reply.prompt_tokens,
        )

    # ---- embeddings ----

    def embeddings(self, mc: ModelConfig, inputs: list) -> list:
        """(reference: ModelEmbedding embeddings.go). All inputs go in ONE
        RPC; the TPU backend pads them into bucketed batches (BASELINE
        config #4: batched embeddings). Backends without batch support
        (fakes, external) fall back to per-input calls."""
        lm = self._load(mc)
        lm.mark_busy()
        try:
            res = lm.client.embedding(pb.PredictOptions(
                prompt=str(inputs[0]) if inputs else "",
                inputs=[str(t) for t in inputs]))
            if res.batch:
                return [list(v.values) for v in res.batch]
            out = [list(res.embeddings)]
            for text in inputs[1:]:
                r = lm.client.embedding(pb.PredictOptions(prompt=str(text)))
                out.append(list(r.embeddings))
            return out
        except Exception as e:
            raise wrap_backend_error(e, mc.name) from e
        finally:
            lm.mark_idle()

    # ---- tokenize ----

    def tokenize(self, mc: ModelConfig, text: str) -> list:
        lm = self._load(mc)
        try:
            res = lm.client.tokenize(pb.PredictOptions(prompt=text))
        except Exception as e:
            raise wrap_backend_error(e, mc.name) from e
        return list(res.tokens)

    # ---- image ----

    def generate_image(self, mc: ModelConfig, positive: str, negative: str,
                       width: int, height: int, steps: int, seed: int,
                       dst: str, src: str = "", mode: str = "",
                       strength: float = None, scheduler: str = "") -> None:
        lm = self._load(mc)
        lm.mark_busy()
        try:
            req = pb.GenerateImageRequest(
                positive_prompt=positive, negative_prompt=negative,
                width=width, height=height, step=steps, seed=seed,
                dst=dst, src=src, mode=mode,
                scheduler=scheduler or mc.scheduler,
            )
            if strength is not None:
                req.strength = float(strength)
            res = lm.client.generate_image(req)
            if not res.success:
                raise RuntimeError(res.message or "image generation failed")
        finally:
            lm.mark_idle()

    # ---- audio ----

    def tts(self, mc: ModelConfig, text: str, voice: str, language: str,
            dst: str) -> None:
        lm = self._load(mc)
        lm.mark_busy()
        try:
            res = lm.client.tts(pb.TTSRequest(
                text=text, model=mc.model or mc.name, dst=dst, voice=voice,
                language=language or None,
            ))
            if not res.success:
                raise RuntimeError(res.message or "tts failed")
        finally:
            lm.mark_idle()

    def sound_generation(self, mc: ModelConfig, text: str, dst: str,
                         duration: Optional[float] = None,
                         temperature: Optional[float] = None) -> None:
        lm = self._load(mc)
        lm.mark_busy()
        try:
            req = pb.SoundGenerationRequest(text=text, model=mc.model or mc.name, dst=dst)
            if duration is not None:
                req.duration = duration
            if temperature is not None:
                req.temperature = temperature
            res = lm.client.sound_generation(req)
            if not res.success:
                raise RuntimeError(res.message or "sound generation failed")
        finally:
            lm.mark_idle()

    def transcribe(self, mc: ModelConfig, audio_path: str, language: str,
                   translate: bool) -> pb.TranscriptResult:
        lm = self._load(mc)
        lm.mark_busy()
        try:
            return lm.client.transcribe(pb.TranscriptRequest(
                dst=audio_path, language=language, translate=translate,
            ))
        finally:
            lm.mark_idle()

    # ---- rerank ----

    def rerank(self, mc: ModelConfig, query: str, documents: list,
               top_n: int) -> pb.RerankResult:
        lm = self._load(mc)
        try:
            return lm.client.rerank(pb.RerankRequest(
                query=query, documents=documents, top_n=top_n,
            ))
        except Exception as e:
            raise wrap_backend_error(e, mc.name) from e

    # ---- stores ----

    def store_client(self, store_name: str = "default"):
        mc = ModelConfig(name=f"store-{store_name}", backend="local-store")
        return self._load(mc).client
