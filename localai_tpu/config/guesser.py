"""Checkpoint-family guesser: default chat templates + stopwords.

Capability parity with the reference's GGUF guesser (reference:
core/config/guesser.go:145-246 — reads the model header, identifies the
chat-template family [LLaMa3/CommandR/Phi3/ChatML/Mistral03/Gemma/
DeepSeek2] and fills in default templates + stopwords when the model YAML
doesn't set them). TPU checkpoints are HF directories, so the signal here
is config.json's model_type plus the tokenizer's chat_template markers
instead of GGUF metadata.
"""

from __future__ import annotations

import json
import logging
import os

log = logging.getLogger(__name__)

# family -> (chat_message template, chat template, stopwords)
FAMILIES = {
    "llama3": (
        "<|start_header_id|>{{ Role }}<|end_header_id|>\n\n{{ Content }}<|eot_id|>",
        "<|begin_of_text|>{{ Input }}<|start_header_id|>assistant<|end_header_id|>\n\n",
        ["<|eot_id|>", "<|end_of_text|>"],
    ),
    "chatml": (
        "<|im_start|>{{ Role }}\n{{ Content }}<|im_end|>",
        "{{ Input }}\n<|im_start|>assistant\n",
        ["<|im_end|>"],
    ),
    "mistral": (
        "{% if Role == 'user' %}[INST] {{ Content }} [/INST]{% else %}{{ Content }}</s>{% endif %}",
        "<s>{{ Input }}",
        ["</s>"],
    ),
    "gemma": (
        "<start_of_turn>{% if Role == 'assistant' %}model{% else %}{{ Role }}{% endif %}\n{{ Content }}<end_of_turn>",
        "{{ Input }}\n<start_of_turn>model\n",
        ["<end_of_turn>"],
    ),
    "phi3": (
        "<|{{ Role }}|>\n{{ Content }}<|end|>",
        "{{ Input }}\n<|assistant|>\n",
        ["<|end|>", "<|endoftext|>"],
    ),
    "deepseek2": (
        "{% if Role == 'user' %}User: {{ Content }}\n{% else %}Assistant: {{ Content }}<|end_of_sentence|>{% endif %}",
        "{{ Input }}Assistant:",
        ["<|end_of_sentence|>"],
    ),
}

_MARKERS = (
    ("<|start_header_id|>", "llama3"),
    ("<|im_start|>", "chatml"),
    ("<start_of_turn>", "gemma"),
    ("<|end_of_sentence|>", "deepseek2"),
    ("<|assistant|>", "phi3"),
    ("[INST]", "mistral"),
)


def identify_family(model_dir: str):
    """Best-effort family id for an HF checkpoint dir (None = unknown)."""
    tok_cfg = {}
    cfg = {}
    for name, target in (("tokenizer_config.json", tok_cfg),
                         ("config.json", cfg)):
        path = os.path.join(model_dir, name)
        if os.path.exists(path):
            try:
                with open(path) as f:
                    target.update(json.load(f))
            except Exception:
                pass
    template = tok_cfg.get("chat_template") or ""
    if isinstance(template, list):  # HF allows named template lists
        template = " ".join(str(t) for t in template)
    for marker, family in _MARKERS:
        if marker in template:
            return family
    mt = (cfg.get("model_type") or "").lower()
    if mt in ("qwen2", "qwen"):  # qwen ships ChatML
        return "chatml"
    if mt == "gemma":
        return "gemma"
    if mt == "phi3":
        return "phi3"
    if mt == "mistral":
        return "mistral"
    if mt == "llama":
        # llama-3 marks itself via vocab size / eos token naming
        eos = str(tok_cfg.get("eos_token", ""))
        if cfg.get("vocab_size", 0) >= 128000 or "eot_id" in eos:
            return "llama3"
    return None


def guess_defaults(mc, models_path: str) -> bool:
    """Fill missing chat templates + stopwords on a ModelConfig from the
    checkpoint family. Returns True if anything was set (reference:
    guessDefaultsFromFile, guesser.go:145-203)."""
    if mc.template.chat and mc.template.chat_message:
        return False
    model_dir = mc.model or mc.name
    if not os.path.isabs(model_dir):
        model_dir = os.path.join(models_path, model_dir)
    if not os.path.isdir(model_dir):
        return False
    family = identify_family(model_dir)
    if family is None:
        return False
    chat_message, chat, stopwords = FAMILIES[family]
    changed = False
    if not mc.template.chat_message:
        mc.template.chat_message = chat_message
        changed = True
    if not mc.template.chat:
        mc.template.chat = chat
        changed = True
    if not mc.stopwords:
        mc.stopwords = list(stopwords)
        changed = True
    if changed:
        log.info("guessed %s chat template for model %s", family, mc.name)
    return changed
