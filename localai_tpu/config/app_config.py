"""Application configuration.

Parity with the reference's ApplicationConfig + env/flag tiers (reference:
core/config/application_config.go, core/cli/run.go:19-74 — every flag has
env aliases, old LOCALAI_* and new names both accepted).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env(*names, default=None, cast=str):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            if cast is bool:
                return v.lower() in ("1", "true", "yes", "on")
            return cast(v)
    return default


@dataclasses.dataclass
class AppConfig:
    models_path: str = "models"
    backend_assets_path: str = ""
    address: str = "127.0.0.1:8080"
    api_keys: list = dataclasses.field(default_factory=list)
    cors: bool = True
    cors_allow_origins: str = "*"
    threads: int = 4
    context_size: int = 2048
    upload_limit_mb: int = 15
    single_active_backend: bool = False
    parallel_requests: bool = True
    preload_models: list = dataclasses.field(default_factory=list)
    galleries: list = dataclasses.field(default_factory=list)
    autoload_galleries: bool = True
    enable_watchdog_idle: bool = False
    enable_watchdog_busy: bool = False
    watchdog_idle_timeout_s: int = 900
    watchdog_busy_timeout_s: int = 300
    disable_metrics_endpoint: bool = False
    disable_webui: bool = False
    log_level: str = "info"
    dynamic_config_dir: str = ""
    uploads_path: str = "uploads"
    config_path: str = "configuration"
    # TPU-native
    mesh_tp: int = 0                  # 0 => all devices
    mesh_dp: int = 1
    load_to_memory: list = dataclasses.field(default_factory=list)  # warmup models

    @staticmethod
    def from_env(**overrides) -> "AppConfig":
        c = AppConfig(
            models_path=_env("LOCALAI_MODELS_PATH", "MODELS_PATH", default="models"),
            address=_env("LOCALAI_ADDRESS", "ADDRESS", default="127.0.0.1:8080"),
            threads=_env("LOCALAI_THREADS", "THREADS", default=4, cast=int),
            context_size=_env("LOCALAI_CONTEXT_SIZE", "CONTEXT_SIZE", default=2048, cast=int),
            upload_limit_mb=_env("LOCALAI_UPLOAD_LIMIT", "UPLOAD_LIMIT", default=15, cast=int),
            single_active_backend=_env("LOCALAI_SINGLE_ACTIVE_BACKEND", "SINGLE_ACTIVE_BACKEND",
                                       default=False, cast=bool),
            parallel_requests=_env("LOCALAI_PARALLEL_REQUESTS", "PARALLEL_REQUESTS",
                                   default=True, cast=bool),
            enable_watchdog_idle=_env("LOCALAI_WATCHDOG_IDLE", "WATCHDOG_IDLE",
                                      default=False, cast=bool),
            enable_watchdog_busy=_env("LOCALAI_WATCHDOG_BUSY", "WATCHDOG_BUSY",
                                      default=False, cast=bool),
            disable_metrics_endpoint=_env("LOCALAI_DISABLE_METRICS", default=False, cast=bool),
            disable_webui=_env("LOCALAI_DISABLE_WEBUI", "DISABLE_WEBUI", default=False, cast=bool),
            log_level=_env("LOCALAI_LOG_LEVEL", default="info"),
            dynamic_config_dir=_env("LOCALAI_CONFIG_DIR", default=""),
            mesh_tp=_env("LOCALAI_MESH_TP", default=0, cast=int),
            mesh_dp=_env("LOCALAI_MESH_DP", default=1, cast=int),
        )
        keys = _env("LOCALAI_API_KEY", "API_KEY", default="")
        if keys:
            c.api_keys = [k.strip() for k in keys.split(",") if k.strip()]
        for k, v in overrides.items():
            if v is not None and hasattr(c, k):
                setattr(c, k, v)
        return c
