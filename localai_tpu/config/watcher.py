"""Dynamic config hot-reload: api_keys.json + external_backends.json.

Capability parity with the reference's config file watcher (reference:
core/startup/config_file_watcher.go:29-43 registers handlers for
api_keys.json [JSON list of keys, appended to the startup keys,
:130-152] and external_backends.json [JSON map name -> backend target,
merged over the startup set, :157-180], re-applied on write/create/
remove). The reference uses fsnotify with a polling fallback; a polling
thread is the portable equivalent here.
"""

from __future__ import annotations

import json
import logging
import os
import threading

log = logging.getLogger(__name__)

WATCHED = ("api_keys.json", "external_backends.json")


class ConfigWatcher:
    """Polls a dynamic-config dir and applies updates in place.

    api_keys: the live list object used by the auth middleware is mutated
    in place (the middleware holds a reference, so updates apply to the
    next request without restarting the server).
    """

    def __init__(self, app_config, loader, interval_s: float = 1.0):
        self.app_config = app_config
        self.loader = loader
        self.interval_s = interval_s
        self._startup_keys = list(app_config.api_keys)
        self._mtimes: dict = {}
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if not self.app_config.dynamic_config_dir:
            return self
        self.poll_once()  # apply any existing files at boot
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="config-watcher")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:
                log.exception("dynamic config poll failed")

    def poll_once(self):
        d = self.app_config.dynamic_config_dir
        for name in WATCHED:
            path = os.path.join(d, name)
            try:
                mtime = os.stat(path).st_mtime_ns
            except OSError:
                mtime = None  # removed -> revert to startup values
            if self._mtimes.get(name, "unset") == mtime:
                continue
            self._mtimes[name] = mtime
            self._apply(name, path if mtime is not None else None)

    def _apply(self, name: str, path):
        content = None
        if path is not None:
            try:
                with open(path) as f:
                    content = json.load(f)
            except Exception:
                log.exception("invalid dynamic config file: %s", name)
                return
        if name == "api_keys.json":
            keys = self._startup_keys + (content or [])
            # in-place: the auth middleware closes over this list object
            self.app_config.api_keys[:] = keys
            log.info("api keys reloaded (%d total)", len(keys))
        elif name == "external_backends.json":
            for backend, target in (content or {}).items():
                self.loader.register_external(backend, target)
            log.info("external backends reloaded (%d)", len(content or {}))
