"""Per-model YAML configuration.

Parity with the reference's BackendConfig (reference:
core/config/backend_config.go:28-548): model name, backend selection,
sampling parameter defaults, prompt templates, context/cache knobs,
function-calling config, and usecase flags used for routing. Knobs that
only make sense for CUDA llama.cpp (NUMA, mmap, tensor_split fractions,
gpu layers) are intentionally absent — the TPU equivalents (mesh plan,
dtype, cache size) replace them.
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Any, Optional

import yaml

# diffusion schedulers implemented by models/sd.py (single source of
# truth for YAML validation, the HTTP route, and the sampler itself —
# importable without pulling in jax)
SCHEDULERS = ("ddim", "euler", "euler_a", "dpmpp_2m")

# Accepted kv_cache_dtype names — the SINGLE source of truth; the backend
# (backend/runner.py) maps these to jnp dtypes and asserts it covers
# exactly this set, so the YAML validator and the runner can't drift.
KV_CACHE_DTYPES = ("bfloat16", "bf16", "float16", "f16", "float32", "f32",
                   "int8", "q8_0")


class Usecase(enum.Flag):
    """Routing flags (reference: backend_config.go:432-548)."""
    NONE = 0
    CHAT = enum.auto()
    COMPLETION = enum.auto()
    EDIT = enum.auto()
    EMBEDDINGS = enum.auto()
    IMAGE = enum.auto()
    TTS = enum.auto()
    TRANSCRIPT = enum.auto()
    RERANK = enum.auto()
    SOUND_GENERATION = enum.auto()
    TOKENIZE = enum.auto()
    VISION = enum.auto()
    ANY = (CHAT | COMPLETION | EDIT | EMBEDDINGS | IMAGE | TTS | TRANSCRIPT
           | RERANK | SOUND_GENERATION | TOKENIZE | VISION)


@dataclasses.dataclass
class PredictionParams:
    """Sampling defaults (reference: core/schema/prediction.go)."""
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    min_p: Optional[float] = None
    typical_p: Optional[float] = None
    max_tokens: Optional[int] = None
    repeat_penalty: Optional[float] = None
    repeat_last_n: Optional[int] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    mirostat: Optional[int] = None
    mirostat_tau: Optional[float] = None
    mirostat_eta: Optional[float] = None
    seed: Optional[int] = None
    echo: bool = False
    n: int = 1
    logit_bias: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class TemplateConfig:
    """Prompt templates (reference: backend_config.go TemplateConfig)."""
    chat: str = ""
    chat_message: str = ""
    completion: str = ""
    edit: str = ""
    function: str = ""
    use_tokenizer_template: bool = False
    join_chat_messages_by_character: Optional[str] = None
    multimodal: str = ""


@dataclasses.dataclass
class FunctionsConfig:
    """Tool-calling behavior (reference: pkg/functions/parse.go:54-90)."""
    disable_no_action: bool = False
    no_action_function_name: str = "answer"
    no_action_description_name: str = ""
    function_name_key: str = "name"
    function_arguments_key: str = "arguments"
    response_regex: list = dataclasses.field(default_factory=list)
    json_regex_match: list = dataclasses.field(default_factory=list)
    replace_function_results: list = dataclasses.field(default_factory=list)
    replace_llm_results: list = dataclasses.field(default_factory=list)
    capture_llm_results: list = dataclasses.field(default_factory=list)
    grammar: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModelConfig:
    name: str = ""
    backend: str = ""                 # "" => greedy autodetect
    description: str = ""
    usage: str = ""
    parameters: PredictionParams = dataclasses.field(default_factory=PredictionParams)
    model: str = ""                   # weights path / HF repo / URL
    tokenizer: str = ""               # defaults to model dir
    context_size: Optional[int] = None
    embeddings: bool = False
    stopwords: list = dataclasses.field(default_factory=list)
    template: TemplateConfig = dataclasses.field(default_factory=TemplateConfig)
    function: FunctionsConfig = dataclasses.field(default_factory=FunctionsConfig)
    system_prompt: str = ""
    # response post-processing (reference: Finetune, core/backend/llm.go:179-227)
    cutstrings: list = dataclasses.field(default_factory=list)
    extract_regex: list = dataclasses.field(default_factory=list)
    trimspace: list = dataclasses.field(default_factory=list)
    trimsuffix: list = dataclasses.field(default_factory=list)
    # TPU-native knobs (replace gpu_layers/tensor_split/low_vram/...)
    dtype: str = "bfloat16"
    kv_cache_dtype: str = "bfloat16"
    # "" | int8 (weight-only per-channel) | int4 (weight-only group-128
    # for layer matmuls, embed/lm_head int8 — llama-family only)
    quantization: str = ""
    num_slots: int = 8                # reference: LLAMACPP_PARALLEL slots
    # free-form "k=v" strings forwarded on the backend options wire
    # (reference: BackendConfig.Options, backend_config.go) — e.g. the
    # video knobs num_frames=14,fps=7,motion=1.0, or the paged-KV knobs
    # kv_layout=paged|contiguous, kv_page_size=N, kv_pool_pages=N,
    # kv_prefix_cache=0|1 (cross-release prefix cache, default on),
    # kv_prefix_cache_min_rows=N (reuse threshold, default 16),
    # kv_offload=0|1 (host-RAM page offload tier, default on),
    # kv_host_pool_mb=N (host tier byte budget), kv_host_store=path
    # (persist offloaded chains across restarts), the long-context
    # window knobs kv_window_pages=N (bounded on-device working set,
    # 0 = off), kv_sink_pages=N (attention-sink head pages pinned on
    # device), kv_window_policy=demote|drop (cold middle pages demote
    # to host or drop) and kv_prefetch_ahead=N (decode-time restore
    # pipeline depth, 0 = off), or the ragged
    # packed-prefill knobs prefill_packed=0|1 (default on; 0 restores
    # per-slot bucketed prefill), prefill_token_budget=N (max packed
    # prompt tokens per scheduler tick, 0 = engine auto) and
    # prefill_packed_fuse=auto|0|1|split (fuse the packed step with the
    # decode burst; 1 = one monolithic program, split = early-emit
    # back-to-back pair, auto = split everywhere) and
    # comm_overlap=auto|0|1 (TokenWeave-style halved-pack overlap of
    # per-layer collectives with compute; auto = meshed backends only,
    # bit-exact either way), or the
    # observability knobs trace=0|1 (request-lifecycle span tracer,
    # default on), trace_ring_size=N (retained spans, default 4096) and
    # slow_request_ms=N (log a span decomposition when TTFT or e2e
    # exceeds N ms; 0 = off), or the system-observability knobs (ISSUE 8)
    # event_log=path|stderr|off (structured JSON-lines event sink for the
    # backend process; the ring at /debug/events works regardless) and
    # peak_tflops=N (override the device peak used for MFU — needed on
    # CPU/unknown device kinds where the built-in table reports 0), or
    # the per-class SLO objectives (ISSUE 12) slo_ttft_ms= / slo_itl_ms=
    # / slo_queue_wait_ms= with value "500" (all classes), "250:1000:5000"
    # (high:normal:low) or "high=250:low=5000" (named subset) and
    # slo_error_budget=F (allowed violation fraction, default 0.01), or
    # the speculative-decoding knobs (ISSUE 13) draft=auto|model|ngram|0
    # (auto = draft model when loaded, else n-gram self-speculation;
    # 0 disables), n_draft=N (proposal depth per round, 0 disables) and
    # spec_ngram=N (lookup n-gram length, default 3), or the replica-pool
    # knob (ISSUE 14) engines=N (N>1 serves the model from N engine
    # replicas behind prefix-affinity routing, sharing ONE host KV tier;
    # requires preempt=1 — pause/resume is the migration primitive.
    # engines=1, the default, builds a plain single Engine bit-for-bit),
    # or the autoscaling knobs (ISSUE 19) autoscale=0|1 (default 0; 1
    # runs the SLO-driven replica autoscaler on the pool housekeeping
    # cadence — requires preempt=1), autoscale_min=N / autoscale_max=N
    # (replica bounds; max 0 = twice the configured engines),
    # autoscale_burn_out=F / autoscale_burn_in=F (short-window SLO burn
    # thresholds for scale-out / scale-in), autoscale_dwell_ms=N /
    # autoscale_cooldown_ms=N (hysteresis brakes) and weight_prefetch=0|1
    # (default 0; 1 streams weight loads leaf-at-a-time and warms the
    # predicted-next gallery model's checkpoint bytes ahead of its first
    # request).
    # The known knobs are value-validated in validate() so a typo fails
    # at config scan instead of silently running the default.
    options: list = dataclasses.field(default_factory=list)
    mesh: dict = dataclasses.field(default_factory=dict)  # {dp: 1, tp: 8, ...}
    prefill_buckets: list = dataclasses.field(default_factory=list)
    # decode tokens per burst dispatch (0 = engine default). Trades
    # per-dispatch overhead against finish-detection latency: smaller
    # bursts admit/release slots sooner (r5 on the serving chip, 8B-int8
    # at 32 slots: burst 8 beat 16 on BOTH throughput and TTFT)
    decode_burst: int = 0
    max_batch_prefill: int = 1
    # capability routing
    known_usecases: Optional[list] = None
    # download source for `model` when it is a URL/hf repo
    download_files: list = dataclasses.field(default_factory=list)
    # multimodal
    mmproj: str = ""
    # diffusion (reference: diffusers backend SchedulerType + img2img,
    # backend.py:169-357): default scheduler for this model
    # (one of SCHEDULERS below; models/sd.py implements them)
    scheduler: str = ""
    # ControlNet dir (diffusers ControlNetModel layout), absolute or
    # relative to the pipeline dir (reference: diffusers backend
    # controlnet attach, backend.py:297-314)
    controlnet: str = ""
    # voice clone: reference audio for tone-color conditioning
    # (reference: ModelOptions.AudioPath, vall-e-x/backend.py:61-68)
    audio_path: str = ""
    # speculative decoding (future)
    draft_model: str = ""
    # LoRA (reference: backend.proto LoraAdapter/LoraBase/LoraScale)
    lora_adapter: str = ""
    lora_base: str = ""
    lora_scale: float = 0.0           # 0 = default 1.0
    # prompt-cache persistence (reference: PromptCachePath/RO/All,
    # options.go:182-191): KV rows + tokens survive restarts on disk
    prompt_cache_path: str = ""
    prompt_cache_ro: bool = False
    prompt_cache_all: bool = False
    # self-extend / group attention (reference: ga_n/ga_w slot state,
    # grpc-server.cpp:209-213): >1 compresses RoPE positions of completed
    # ga_w windows by group_attn_n, extending usable context past the
    # model's training window
    group_attn_n: int = 1
    group_attn_w: int = 512

    def validate(self) -> list:
        problems = []
        if not self.name:
            problems.append("model config missing 'name'")
        if self.context_size is not None and self.context_size <= 0:
            problems.append(f"context_size must be positive, got {self.context_size}")
        if self.num_slots <= 0:
            problems.append(f"num_slots must be positive, got {self.num_slots}")
        if self.scheduler and self.scheduler not in SCHEDULERS:
            problems.append(f"unknown scheduler {self.scheduler!r}")
        if self.kv_cache_dtype.lower() not in KV_CACHE_DTYPES:
            problems.append(
                f"unknown kv_cache_dtype {self.kv_cache_dtype!r}")
        if self.decode_burst < 0:
            problems.append(
                f"decode_burst must be >= 0 (0 = engine default), "
                f"got {self.decode_burst}")
        if self.group_attn_n < 1:
            problems.append(
                f"group_attn_n must be >= 1, got {self.group_attn_n}")
        elif self.group_attn_n > 1:
            if self.group_attn_w <= 0:
                problems.append(
                    f"group_attn_w must be positive, got {self.group_attn_w}")
            elif self.group_attn_w % self.group_attn_n != 0:
                # a non-divisible window makes adjacent compressed blocks
                # share a boundary RoPE position
                problems.append(
                    f"group_attn_w ({self.group_attn_w}) must be divisible "
                    f"by group_attn_n ({self.group_attn_n})")
        bool_vals = ("0", "1", "true", "false", "on", "off", "yes", "no")
        for o in self.options or []:
            s = str(o)
            if "=" not in s:
                continue
            k, v = (p.strip() for p in s.split("=", 1))
            if k == "kv_layout" and v not in ("auto", "paged", "contiguous"):
                problems.append(
                    f"kv_layout must be auto|paged|contiguous, got {v!r}")
            elif k in ("kv_page_size", "kv_pool_pages",
                       "kv_prefix_cache_min_rows",
                       "kv_host_pool_mb",
                       "prefill_token_budget",
                       "trace_ring_size",
                       "slow_request_ms",
                       # fault-tolerant lifecycle knobs (ISSUE 7);
                       # explicit 0 disables the respective bound
                       "max_queued_requests",
                       "max_queue_wait_ms",
                       "request_timeout_ms",
                       "dispatch_stall_ms",
                       # event-log rotation bound (ISSUE 9); 0 disables
                       "event_log_max_mb",
                       # priority scheduler (ISSUE 10); 0 disables the
                       # respective guard (aging / reserve / preemption cap)
                       "max_preemptions",
                       "resume_reserve_pages",
                       "priority_aging_ms",
                       # speculative decoding (ISSUE 13); explicit
                       # n_draft=0 disables speculation
                       "n_draft",
                       # long-context serving tier (ISSUE 16); 0 = window
                       # off / prefetch off, sink defaults to 1 page
                       "kv_window_pages",
                       "kv_sink_pages",
                       "kv_prefetch_ahead",
                       # autoscaling (ISSUE 19); autoscale_max=0 = auto
                       # (twice the configured engines)
                       "autoscale_max",
                       "autoscale_dwell_ms",
                       "autoscale_cooldown_ms",
                       # federated KV stream timing (ISSUE 20, formerly
                       # hardcoded): peer cooldown / negative-cache TTL /
                       # connect timeout, all in ms
                       "kv_stream_cooldown_ms",
                       "kv_stream_negcache_ms",
                       "kv_stream_connect_timeout_ms",
                       # cluster control plane (ISSUE 20): heartbeat
                       # cadence, failure-detector windows, per-op
                       # deadline + retry schedule
                       "cluster_heartbeat_ms",
                       "cluster_suspect_ms",
                       "cluster_dead_ms",
                       "cluster_rpc_timeout_ms",
                       "cluster_rpc_retries",
                       "cluster_rpc_backoff_ms") and not v.isdigit():
                problems.append(
                    f"{k} must be a non-negative integer "
                    f"(0 = engine default), got {v!r}")
            elif k in ("kv_prefix_cache", "kv_offload",
                       "prefill_packed", "trace",
                       # dedicated emission worker (ISSUE 9); 0 restores
                       # the in-loop path
                       "emitter",
                       # preemptive scheduler (ISSUE 10); 0 restores
                       # strict-FIFO admission bit-for-bit
                       "preempt",
                       # SLO-driven autoscaling + predictive weight
                       # prefetch (ISSUE 19); both default off
                       "autoscale",
                       "weight_prefetch") and v.lower() not in bool_vals:
                problems.append(
                    f"{k} must be one of {bool_vals}, got {v!r}")
            elif k == "priority" and v.lower() not in ("high", "normal",
                                                       "low"):
                problems.append(
                    f"priority must be high|normal|low, got {v!r}")
            elif k == "priority_weights":
                try:
                    from localai_tpu.engine.scheduler import (
                        parse_priority_weights)

                    parse_priority_weights(v)
                except ValueError as e:
                    problems.append(str(e))
            elif k == "prefill_packed_fuse" and v not in ("auto", "0", "1",
                                                          "split"):
                problems.append(
                    f"prefill_packed_fuse must be auto|0|1|split, got {v!r}")
            elif k == "comm_overlap" and v not in ("auto", "0", "1"):
                problems.append(
                    f"comm_overlap must be auto|0|1, got {v!r}")
            elif k == "kv_audit" and v not in ("off", "on", "strict"):
                problems.append(
                    f"kv_audit must be off|on|strict, got {v!r}")
            elif k == "kv_window_policy" and v not in ("demote", "drop"):
                problems.append(
                    f"kv_window_policy must be demote|drop, got {v!r}")
            elif k == "draft" and v.lower() not in (
                    "auto", "model", "ngram", "0", "off", "none", "false"):
                problems.append(
                    f"draft must be auto|model|ngram|0, got {v!r}")
            elif k == "spec_ngram" and not (v.isdigit() and int(v) > 0):
                problems.append(
                    f"spec_ngram must be a positive integer, got {v!r}")
            elif k == "engines" and not (v.isdigit() and int(v) > 0):
                problems.append(
                    f"engines must be a positive integer, got {v!r}")
            elif k == "autoscale_min" and not (v.isdigit() and int(v) > 0):
                problems.append(
                    f"autoscale_min must be a positive integer, got {v!r}")
            elif k in ("autoscale_burn_out", "autoscale_burn_in"):
                try:
                    if float(v) <= 0:
                        problems.append(
                            f"{k} must be > 0, got {v!r}")
                except ValueError:
                    problems.append(f"{k} must be a number, got {v!r}")
            elif k == "disagg" and v not in ("both", "prefill", "decode"):
                # prefill/decode disaggregation role (ISSUE 17)
                problems.append(
                    f"disagg must be both|prefill|decode, got {v!r}")
            elif k == "cluster_mode" and v not in ("inproc", "process"):
                # cluster host placement (ISSUE 20)
                problems.append(
                    f"cluster_mode must be inproc|process, got {v!r}")
            elif k == "kv_peers":
                # peer wire addresses, |-separated (the options wire
                # splits on commas): host:port[|host:port...]
                for a in v.split("|"):
                    a = a.strip()
                    h, _, p = a.rpartition(":")
                    if not h or not p.isdigit():
                        problems.append(
                            f"kv_peers entries must be host:port, got {a!r}")
                        break
            elif k == "kv_serve":
                # "1" (ephemeral port) or an explicit bind host:port
                if v.lower() not in ("0", "1", "false", "true", "off",
                                     "on", "no", "yes"):
                    h, _, p = v.rpartition(":")
                    if not h or not p.isdigit():
                        problems.append(
                            f"kv_serve must be 0|1|host:port, got {v!r}")
            elif k == "peak_tflops":
                try:
                    if float(v) < 0:
                        problems.append(
                            f"peak_tflops must be >= 0, got {v!r}")
                except ValueError:
                    problems.append(
                        f"peak_tflops must be a number, got {v!r}")
            elif k in ("slo_ttft_ms", "slo_itl_ms", "slo_queue_wait_ms"):
                # per-class SLO objectives (ISSUE 12): same fail-at-scan
                # contract as priority_weights — the parser IS the
                # validator
                try:
                    from localai_tpu.services.sysobs import parse_slo_classes

                    parse_slo_classes(v)
                except ValueError as e:
                    problems.append(str(e))
            elif k == "slo_error_budget":
                try:
                    if not 0 < float(v) <= 1:
                        problems.append(
                            f"slo_error_budget must be in (0, 1], got {v!r}")
                except ValueError:
                    problems.append(
                        f"slo_error_budget must be a number, got {v!r}")
        # cross-knob: the replica pool migrates via pause/resume, so a
        # pool without the preemptive scheduler could never rebalance or
        # crash-recover — fail at scan, not at model load
        opts = {}
        for o in self.options or []:
            s = str(o)
            if "=" in s:
                k, v = (p.strip() for p in s.split("=", 1))
                opts[k] = v
        if (opts.get("engines", "1").isdigit()
                and int(opts.get("engines", "1")) > 1
                and opts.get("preempt", "1").lower() in
                ("0", "false", "off", "no")):
            problems.append("engines>1 requires preempt=1 (pause/resume "
                            "is the pool's migration primitive)")
        # cross-knob (ISSUE 19): the autoscaler's scale-in drains via
        # the same pause/resume migration path
        if opts.get("autoscale", "0").lower() in ("1", "true", "on",
                                                  "yes"):
            if opts.get("preempt", "1").lower() in ("0", "false", "off",
                                                    "no"):
                problems.append("autoscale=1 requires preempt=1 (scale-in "
                                "drains via pause/resume migration)")
        amin, amax = opts.get("autoscale_min", ""), opts.get(
            "autoscale_max", "")
        if (amin.isdigit() and amax.isdigit() and int(amax) > 0
                and int(amin) > int(amax)):
            problems.append(f"autoscale_min ({amin}) must be <= "
                            f"autoscale_max ({amax})")
        # cross-knob (ISSUE 20): the failure-detector ladder only works
        # if the SUSPECT window opens strictly before the DEAD one — a
        # slow host must be able to sit in SUSPECT without dying
        sus, ded = opts.get("cluster_suspect_ms", ""), opts.get(
            "cluster_dead_ms", "")
        if (sus.isdigit() and ded.isdigit()
                and int(sus) >= int(ded) and int(ded) > 0):
            problems.append(f"cluster_suspect_ms ({sus}) must be < "
                            f"cluster_dead_ms ({ded})")
        # cross-knob (ISSUE 17): a disaggregated role ejects/splices via
        # the same pause/resume primitive, and ships chains through the
        # host tier — both must be armed
        if opts.get("disagg", "both") != "both":
            if opts.get("preempt", "1").lower() in ("0", "false", "off",
                                                    "no"):
                problems.append("disagg=prefill|decode requires preempt=1 "
                                "(pause/resume is the handoff primitive)")
            if opts.get("kv_offload", "1").lower() in ("0", "false", "off",
                                                       "no"):
                problems.append("disagg=prefill|decode requires "
                                "kv_offload=1 (chains ship via the host "
                                "tier)")
        return problems

    def usecases(self) -> Usecase:
        if self.known_usecases:
            u = Usecase.NONE
            for name in self.known_usecases:
                u |= Usecase[name.upper()]
            return u
        # heuristics mirroring reference GuessUsecases (backend_config.go:432)
        u = Usecase.CHAT | Usecase.COMPLETION | Usecase.EDIT | Usecase.TOKENIZE
        if self.embeddings:
            u |= Usecase.EMBEDDINGS
        if self.mmproj:
            u |= Usecase.VISION
        name = (self.backend or "").lower()
        if "diffus" in name or "image" in name:
            u = Usecase.IMAGE
        if "tts" in name or "bark" in name or "coqui" in name:
            u = Usecase.TTS
        if "whisper" in name:
            u = Usecase.TRANSCRIPT
        if "rerank" in name:
            u = Usecase.RERANK
        if self.embeddings and "bert" in name:
            u = Usecase.EMBEDDINGS | Usecase.TOKENIZE
        return u

    def sampling_host(self, request_overrides: Optional[dict] = None):
        """Merge config defaults + request overrides into engine params."""
        from localai_tpu.engine.sampling import SamplingParamsHost

        p = self.parameters
        merged = {
            "temperature": p.temperature if p.temperature is not None else 0.8,
            "top_k": p.top_k if p.top_k is not None else 40,
            "top_p": p.top_p if p.top_p is not None else 0.95,
            "min_p": p.min_p if p.min_p is not None else 0.0,
            "typical_p": p.typical_p if p.typical_p is not None else 1.0,
            "repeat_penalty": p.repeat_penalty if p.repeat_penalty is not None else 1.0,
            "repeat_last_n": p.repeat_last_n if p.repeat_last_n is not None else 64,
            "presence_penalty": p.presence_penalty or 0.0,
            "frequency_penalty": p.frequency_penalty or 0.0,
            "mirostat": p.mirostat or 0,
            "mirostat_tau": p.mirostat_tau if p.mirostat_tau is not None else 5.0,
            "mirostat_eta": p.mirostat_eta if p.mirostat_eta is not None else 0.1,
            "seed": p.seed if p.seed is not None else -1,
            "logit_bias": dict(p.logit_bias or {}),
        }
        for k, v in (request_overrides or {}).items():
            if v is not None and k in merged:
                merged[k] = v
        return SamplingParamsHost(**merged)


def _build(data: dict) -> ModelConfig:
    data = dict(data)
    params = data.pop("parameters", {}) or {}
    # reference keeps model under parameters.model
    model = params.pop("model", "") or data.pop("model", "")
    tmpl = data.pop("template", {}) or {}
    func = data.pop("function", {}) or {}
    known_params = {f.name for f in dataclasses.fields(PredictionParams)}
    known_tmpl = {f.name for f in dataclasses.fields(TemplateConfig)}
    known_func = {f.name for f in dataclasses.fields(FunctionsConfig)}
    known_top = {f.name for f in dataclasses.fields(ModelConfig)}
    mc = ModelConfig(
        parameters=PredictionParams(**{k: v for k, v in params.items() if k in known_params}),
        template=TemplateConfig(**{k: v for k, v in tmpl.items() if k in known_tmpl}),
        function=FunctionsConfig(**{k: v for k, v in func.items() if k in known_func}),
        **{k: v for k, v in data.items() if k in known_top
           and k not in ("parameters", "template", "function")},
    )
    mc.model = model
    return mc


def load_model_config(path: str) -> ModelConfig:
    with open(path) as f:
        data = yaml.safe_load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a mapping")
    mc = _build(data)
    if not mc.name:
        mc.name = os.path.splitext(os.path.basename(path))[0]
    return mc


def load_multi_config(path: str) -> list:
    """Single file with a list of model configs (reference:
    LoadMultipleBackendConfigsSingleFile)."""
    with open(path) as f:
        data = yaml.safe_load(f)
    if not isinstance(data, list):
        raise ValueError(f"{path}: expected a list of model configs")
    return [_build(d) for d in data]


def scan_models_dir(models_path: str) -> dict:
    """Scan for per-model .yaml files (reference: LoadBackendConfigsFromPath)."""
    configs = {}
    if not os.path.isdir(models_path):
        return configs
    for fn in sorted(os.listdir(models_path)):
        if not fn.endswith((".yaml", ".yml")) or fn.startswith("."):
            continue
        try:
            mc = load_model_config(os.path.join(models_path, fn))
            problems = mc.validate()
            if problems:
                raise ValueError("; ".join(problems))
            # fill missing chat templates/stopwords from the checkpoint
            # family (reference: guessDefaultsFromFile, guesser.go:145)
            from localai_tpu.config.guesser import guess_defaults

            guess_defaults(mc, models_path)
            configs[mc.name] = mc
        except Exception as e:  # mirror reference: log and skip broken configs
            import logging
            logging.getLogger(__name__).warning("skipping %s: %s", fn, e)
    return configs
