"""Explorer: a public dashboard over registered federation endpoints.

Capability parity with the reference's explorer (reference:
core/explorer/discovery.go:16-43 + database.go — a JSON-file registry of
network tokens, a background loop that dials each network, counts its
workers, and drops entries that fail repeatedly; served as a dashboard).
The TPU design registers federation-front URLs instead of libp2p tokens
(discovery is explicit — see federation.py) and polls their
/federation/status endpoints.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time

from aiohttp import ClientSession, ClientTimeout, web

log = logging.getLogger("localai_tpu.explorer")

FAILURE_LIMIT = 3  # drop an endpoint after this many consecutive failures
                   # (reference: explorer drops tokens failing 3x,
                   # discovery.go:116-134)


class ExplorerDB:
    """JSON-file registry of federation endpoints."""

    def __init__(self, path: str):
        self.path = path
        self.lock = threading.Lock()
        self.entries: dict = {}   # url -> {"failures": int, "workers": [...],
                                  #         "last_seen": float}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    self.entries = json.load(f)
            except Exception:
                log.exception("invalid explorer db %s", path)

    def save(self):
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "w") as f:
            json.dump(self.entries, f)

    def register(self, url: str):
        with self.lock:
            self.entries.setdefault(url.rstrip("/"), {
                "failures": 0, "workers": [], "last_seen": 0.0})
            self.save()

    def drop(self, url: str):
        with self.lock:
            self.entries.pop(url, None)
            self.save()


class Explorer:
    def __init__(self, db: ExplorerDB, poll_interval_s: float = 30.0):
        self.db = db
        self.poll_interval_s = poll_interval_s

    async def poll_once(self):
        urls = list(self.db.entries)
        async with ClientSession(timeout=ClientTimeout(total=10)) as session:
            for url in urls:
                try:
                    async with session.get(url + "/federation/status") as r:
                        r.raise_for_status()
                        status = await r.json()
                    with self.db.lock:
                        e = self.db.entries.get(url)
                        if e is not None:
                            e["failures"] = 0
                            e["workers"] = status.get("workers", [])
                            e["last_seen"] = time.time()
                            self.db.save()
                except Exception:
                    with self.db.lock:
                        e = self.db.entries.get(url)
                        if e is None:
                            continue
                        e["failures"] += 1
                        dead = e["failures"] >= FAILURE_LIMIT
                    if dead:
                        log.info("dropping failing network %s", url)
                        self.db.drop(url)

    async def _poll_loop(self):
        while True:
            try:
                await self.poll_once()
            except Exception:
                log.exception("explorer poll failed")
            await asyncio.sleep(self.poll_interval_s)

    # ---- http ----

    async def register(self, request):
        body = await request.json()
        url = (body.get("url") or "").strip()
        if not url.startswith(("http://", "https://")):
            raise web.HTTPBadRequest(text="url must be http(s)")
        self.db.register(url)
        await self.poll_once()
        return web.json_response({"registered": url})

    async def networks(self, request):
        with self.db.lock:
            data = [{"url": u,
                     "workers": e.get("workers", []),
                     "online_workers": sum(1 for w in e.get("workers", [])
                                           if w.get("online")),
                     "last_seen": e.get("last_seen", 0.0),
                     "failures": e.get("failures", 0)}
                    for u, e in self.db.entries.items()]
        return web.json_response({"networks": data})

    async def dashboard(self, request):
        html = """<!doctype html><html><head><meta charset="utf-8">
<title>LocalAI TPU explorer</title>
<style>body{font-family:system-ui;margin:24px}td,th{padding:6px 10px;
border-bottom:1px solid #ddd;text-align:left}</style></head><body>
<h1>Federated networks</h1><div id="out">loading…</div>
<script>
fetch('/networks').then(r=>r.json()).then(j=>{
  const t = document.createElement('table');
  t.innerHTML = '<tr><th>network</th><th>workers online</th><th>last seen</th></tr>';
  for(const n of j.networks){
    const tr = document.createElement('tr');
    const a = document.createElement('td'); a.textContent = n.url;
    const b = document.createElement('td');
    b.textContent = n.online_workers + ' / ' + n.workers.length;
    const c = document.createElement('td');
    c.textContent = n.last_seen ? new Date(n.last_seen*1000).toISOString() : 'never';
    tr.append(a,b,c); t.appendChild(tr);
  }
  document.getElementById('out').replaceChildren(t);
});
</script></body></html>"""
        return web.Response(text=html, content_type="text/html")

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/", self.dashboard)
        app.router.add_get("/networks", self.networks)
        app.router.add_post("/register", self.register)
        return app


async def serve(address: str, db_path: str, poll_interval_s: float = 30.0):
    from localai_tpu.api.app import run_app

    ex = Explorer(ExplorerDB(db_path), poll_interval_s)
    await run_app(ex.build_app(), address)
    log.info("explorer listening on %s (db %s)", address, db_path)
    await ex._poll_loop()
