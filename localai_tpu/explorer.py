"""Explorer: a public dashboard over registered federation endpoints.

Capability parity with the reference's explorer (reference:
core/explorer/discovery.go:16-43 + database.go — a JSON-file registry of
network tokens, a background loop that dials each network, counts its
workers, and drops entries that fail repeatedly; served as a dashboard).
The TPU design registers federation-front URLs instead of libp2p tokens
(discovery is explicit — see federation.py) and polls their
/federation/status endpoints.
"""

from __future__ import annotations

import asyncio
import ipaddress
import json
import logging
import os
import socket
import threading
import time
from urllib.parse import urlsplit

from typing import Optional

from aiohttp import ClientSession, ClientTimeout, TCPConnector, web
from aiohttp.abc import AbstractResolver

log = logging.getLogger("localai_tpu.explorer")


def _is_public_ip(text: str) -> bool:
    try:
        addr = ipaddress.ip_address(text)
    except ValueError:
        return False
    return not (addr.is_private or addr.is_loopback or addr.is_link_local
                or addr.is_reserved or addr.is_multicast)


def resolve_public_ip(url: str) -> Optional[str]:
    """Resolve the URL's host ONCE and return a public IP, or None when it
    only resolves to private / loopback / link-local addresses (or not at
    all). The caller must CONNECT TO THE RETURNED IP (pinned) — re-resolving
    at request time reopens the DNS-rebinding window this exists to close."""
    host = urlsplit(url).hostname
    if not host:
        return None
    if _is_public_ip(host):
        return host
    try:
        infos = socket.getaddrinfo(host, None)
    except OSError:
        return None  # unresolvable: don't poll it
    for info in infos:
        if _is_public_ip(info[4][0]):
            return info[4][0]
    return None


def url_resolves_private(url: str) -> bool:
    """True when the URL's host resolves ONLY to private / loopback /
    link-local addresses. Registration makes the explorer issue server-side
    GETs to the URL every poll — an unauthenticated endpoint accepting
    arbitrary targets is an SSRF probe of internal networks and metadata
    services, so private targets are rejected unless explicitly allowed."""
    return resolve_public_ip(url) is None


class _PinnedResolver(AbstractResolver):
    """aiohttp resolver answering from a prevetted host->IP map, so the
    connection goes to the address the guard actually checked."""

    def __init__(self, mapping: dict):
        self.mapping = mapping

    async def resolve(self, host, port=0, family=socket.AF_INET):
        ip = self.mapping.get(host)
        if ip is None:
            raise OSError(f"{host}: not in pinned map")
        return [{"hostname": host, "host": ip, "port": port,
                 "family": socket.AF_INET6 if ":" in ip else socket.AF_INET,
                 "proto": 0, "flags": 0}]

    async def close(self):
        pass

FAILURE_LIMIT = 3  # drop an endpoint after this many consecutive failures
                   # (reference: explorer drops tokens failing 3x,
                   # discovery.go:116-134)


class ExplorerDB:
    """JSON-file registry of federation endpoints."""

    def __init__(self, path: str):
        self.path = path
        self.lock = threading.Lock()
        self.entries: dict = {}   # url -> {"failures": int, "workers": [...],
                                  #         "last_seen": float}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    self.entries = json.load(f)
            except Exception:
                log.exception("invalid explorer db %s", path)

    def save(self):
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "w") as f:
            json.dump(self.entries, f)

    def register(self, url: str):
        with self.lock:
            self.entries.setdefault(url.rstrip("/"), {
                "failures": 0, "workers": [], "last_seen": 0.0})
            self.save()

    def drop(self, url: str):
        with self.lock:
            self.entries.pop(url, None)
            self.save()


class Explorer:
    def __init__(self, db: ExplorerDB, poll_interval_s: float = 30.0,
                 token: str = "", allow_private: bool = False):
        self.db = db
        self.poll_interval_s = poll_interval_s
        # registration guardrails: optional bearer token, and private-range
        # targets rejected by default (see url_resolves_private)
        self.token = token
        self.allow_private = allow_private

    async def poll_once(self):
        urls = list(self.db.entries)
        # resolve every host ONCE (off the event loop) and pin connections
        # to the vetted IPs: checking and then letting aiohttp re-resolve
        # would reopen the DNS-rebinding window (TTL-0 public/private
        # flip-flop between check and connect); redirects are refused for
        # the same reason (a public host 302-ing to metadata endpoints)
        pinned: dict = {}
        if not self.allow_private:
            for url in urls:
                host = urlsplit(url).hostname
                if host:
                    ip = await asyncio.to_thread(resolve_public_ip, url)
                    if ip is not None:
                        pinned[host] = ip
            connector = TCPConnector(resolver=_PinnedResolver(pinned))
        else:
            connector = None
        async with ClientSession(timeout=ClientTimeout(total=10),
                                 connector=connector) as session:
            for url in urls:
                try:
                    if not self.allow_private and \
                            urlsplit(url).hostname not in pinned:
                        raise ValueError("resolves private")
                    async with session.get(url + "/federation/status",
                                           allow_redirects=self.allow_private) as r:
                        r.raise_for_status()
                        status = await r.json()
                    with self.db.lock:
                        e = self.db.entries.get(url)
                        if e is not None:
                            e["failures"] = 0
                            e["workers"] = status.get("workers", [])
                            e["last_seen"] = time.time()
                            self.db.save()
                except Exception:
                    with self.db.lock:
                        e = self.db.entries.get(url)
                        if e is None:
                            continue
                        e["failures"] += 1
                        dead = e["failures"] >= FAILURE_LIMIT
                    if dead:
                        log.info("dropping failing network %s", url)
                        self.db.drop(url)

    async def _poll_loop(self):
        while True:
            try:
                await self.poll_once()
            except Exception:
                log.exception("explorer poll failed")
            await asyncio.sleep(self.poll_interval_s)

    # ---- http ----

    async def register(self, request):
        body = await request.json()
        if self.token:
            auth = request.headers.get("Authorization", "")
            presented = (auth[7:] if auth.startswith("Bearer ")
                         else body.get("token", ""))
            if presented != self.token:
                raise web.HTTPUnauthorized(text="registration token required")
        url = (body.get("url") or "").strip()
        if not url.startswith(("http://", "https://")):
            raise web.HTTPBadRequest(text="url must be http(s)")
        # getaddrinfo can block for seconds on dead resolvers — keep it off
        # the event loop
        if not self.allow_private and await asyncio.to_thread(
                url_resolves_private, url):
            raise web.HTTPForbidden(
                text="url resolves to a private/loopback address")
        self.db.register(url)
        await self.poll_once()
        return web.json_response({"registered": url})

    async def networks(self, request):
        with self.db.lock:
            data = [{"url": u,
                     "workers": e.get("workers", []),
                     "online_workers": sum(1 for w in e.get("workers", [])
                                           if w.get("online")),
                     "last_seen": e.get("last_seen", 0.0),
                     "failures": e.get("failures", 0)}
                    for u, e in self.db.entries.items()]
        return web.json_response({"networks": data})

    async def dashboard(self, request):
        html = """<!doctype html><html><head><meta charset="utf-8">
<title>LocalAI TPU explorer</title>
<style>body{font-family:system-ui;margin:24px}td,th{padding:6px 10px;
border-bottom:1px solid #ddd;text-align:left}</style></head><body>
<h1>Federated networks</h1><div id="out">loading…</div>
<script>
fetch('/networks').then(r=>r.json()).then(j=>{
  const t = document.createElement('table');
  t.innerHTML = '<tr><th>network</th><th>workers online</th><th>last seen</th></tr>';
  for(const n of j.networks){
    const tr = document.createElement('tr');
    const a = document.createElement('td'); a.textContent = n.url;
    const b = document.createElement('td');
    b.textContent = n.online_workers + ' / ' + n.workers.length;
    const c = document.createElement('td');
    c.textContent = n.last_seen ? new Date(n.last_seen*1000).toISOString() : 'never';
    tr.append(a,b,c); t.appendChild(tr);
  }
  document.getElementById('out').replaceChildren(t);
});
</script></body></html>"""
        return web.Response(text=html, content_type="text/html")

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/", self.dashboard)
        app.router.add_get("/networks", self.networks)
        app.router.add_post("/register", self.register)
        return app


async def serve(address: str, db_path: str, poll_interval_s: float = 30.0):
    from localai_tpu.api.app import run_app

    ex = Explorer(
        ExplorerDB(db_path), poll_interval_s,
        token=os.environ.get("LOCALAI_EXPLORER_TOKEN", ""),
        allow_private=os.environ.get(
            "LOCALAI_EXPLORER_ALLOW_PRIVATE", "") == "1")
    await run_app(ex.build_app(), address)
    log.info("explorer listening on %s (db %s)", address, db_path)
    await ex._poll_loop()
