"""Resolve CLI model args into installed models.

Parity with the reference's model preload (reference: pkg/startup/
model_preload.go InstallModels — embedded shortcuts, URLs to YAML configs,
gallery names, raw weight URLs, local paths).
"""

from __future__ import annotations

import logging
import os
import shutil

import yaml

log = logging.getLogger("localai_tpu.gallery.preload")


def install_models(names: list, models_path: str, galleries: list):
    os.makedirs(models_path, exist_ok=True)
    for name in names:
        try:
            _install_one(name, models_path, galleries)
        except Exception:
            log.exception("failed to install %s", name)


def _install_one(name: str, models_path: str, galleries: list):
    from localai_tpu.gallery import downloader as dl
    from localai_tpu.gallery.gallery import find_model, install_model, load_gallery_index

    if os.path.isdir(name):
        # local HF checkpoint dir: write a config pointing at it
        cfg_name = os.path.basename(name.rstrip("/"))
        cfg = {"name": cfg_name, "backend": "tpu-llm",
               "parameters": {"model": os.path.abspath(name)}}
        with open(os.path.join(models_path, f"{cfg_name}.yaml"), "w") as f:
            yaml.safe_dump(cfg, f)
        return
    if os.path.isfile(name) and name.endswith((".yaml", ".yml")):
        shutil.copy(name, models_path)
        return
    if name.startswith(("http://", "https://", "file://", "github:")):
        if name.endswith((".yaml", ".yml")):
            dest = os.path.join(models_path, os.path.basename(name.split("?")[0]))
            dl.download_file(name, dest)
            return
        # raw weights URL: download + minimal config
        fname = os.path.basename(name.split("?")[0])
        dl.download_file(name, os.path.join(models_path, fname))
        base = os.path.splitext(fname)[0]
        with open(os.path.join(models_path, f"{base}.yaml"), "w") as f:
            yaml.safe_dump({"name": base, "parameters": {"model": fname}}, f)
        return
    if name.startswith(("huggingface://", "hf://")):
        fname = name.split("/")[-1]
        dl.download_file(name, os.path.join(models_path, fname))
        base = os.path.splitext(fname)[0]
        with open(os.path.join(models_path, f"{base}.yaml"), "w") as f:
            yaml.safe_dump({"name": base, "parameters": {"model": fname}}, f)
        return
    # gallery name
    index = load_gallery_index(galleries)
    entry = find_model(index, name)
    if entry is None:
        raise ValueError(f"unknown model {name!r} (not a path/URL/gallery entry)")
    install_model(entry, models_path)
