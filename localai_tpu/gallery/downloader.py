"""URI downloader with sha256 verification, resume, and progress.

Parity with the reference downloader (reference: pkg/downloader/uri.go —
scheme prefixes :21-30 huggingface://, github:, oci://, ollama://, file://;
DownloadWithAuthorizationAndCallback :38; partial-file resume naming;
HuggingFace URL mapping huggingface.go:49).
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
from typing import Callable, Optional

import httpx

log = logging.getLogger("localai_tpu.gallery.downloader")

HF_PREFIXES = ("huggingface://", "hf://")
GITHUB_PREFIX = "github:"
FILE_PREFIX = "file://"
OCI_PREFIX = "oci://"
OLLAMA_PREFIX = "ollama://"


def resolve_uri(uri: str) -> str:
    """Map shorthand schemes to concrete URLs (reference: uri.go:34-92)."""
    for p in HF_PREFIXES:
        if uri.startswith(p):
            repo_and_file = uri[len(p):]
            parts = repo_and_file.split("/")
            if len(parts) < 3:
                raise ValueError(f"huggingface uri needs owner/repo/file: {uri}")
            repo = "/".join(parts[:2])
            branch = "main"
            fname = "/".join(parts[2:])
            if "@" in repo:
                repo, branch = repo.split("@", 1)
            return f"https://huggingface.co/{repo}/resolve/{branch}/{fname}"
    if uri.startswith(GITHUB_PREFIX):
        ref = uri[len(GITHUB_PREFIX):]
        parts = ref.split("/")
        owner, repo = parts[0], parts[1]
        branch = "main"
        if "@" in repo:
            repo, branch = repo.split("@", 1)
        path = "/".join(parts[2:])
        return f"https://raw.githubusercontent.com/{owner}/{repo}/{branch}/{path}"
    return uri


def download_file(uri: str, dest: str, sha256: str = "",
                  progress: Optional[Callable] = None,
                  chunk_size: int = 1 << 20) -> str:
    """Download uri to dest (with .partial resume), verify sha256."""
    if uri.startswith(FILE_PREFIX):
        src = uri[len(FILE_PREFIX):]
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        shutil.copyfile(src, dest)
        _verify(dest, sha256)
        return dest
    if uri.startswith((OCI_PREFIX, OLLAMA_PREFIX)):
        raise NotImplementedError(
            "oci/ollama pulls require a registry client; use huggingface:// or https://")

    url = resolve_uri(uri)
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
    partial = dest + ".partial"
    pos = os.path.getsize(partial) if os.path.exists(partial) else 0
    headers = {"Range": f"bytes={pos}-"} if pos else {}
    with httpx.stream("GET", url, headers=headers, timeout=60.0,
                      follow_redirects=True) as resp:
        if resp.status_code == 416:  # already complete
            pass
        else:
            resp.raise_for_status()
            if resp.status_code != 206:
                pos = 0  # server ignored Range; restart
            total = int(resp.headers.get("Content-Length", 0)) + pos
            mode = "ab" if pos else "wb"
            with open(partial, mode) as f:
                done = pos
                for chunk in resp.iter_bytes(chunk_size):
                    f.write(chunk)
                    done += len(chunk)
                    if progress and total:
                        progress(done, total)
    os.replace(partial, dest)
    _verify(dest, sha256)
    return dest


def _verify(path: str, sha256: str):
    if not sha256:
        return
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    if h.hexdigest() != sha256.lower():
        os.unlink(path)
        raise ValueError(f"sha256 mismatch for {path}: got {h.hexdigest()}, want {sha256}")
