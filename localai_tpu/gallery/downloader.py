"""URI downloader with sha256 verification, resume, and progress.

Parity with the reference downloader (reference: pkg/downloader/uri.go —
scheme prefixes :21-30 huggingface://, github:, oci://, ollama://, file://;
DownloadWithAuthorizationAndCallback :38; partial-file resume naming;
HuggingFace URL mapping huggingface.go:49).
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
from typing import Callable, Optional

import httpx

log = logging.getLogger("localai_tpu.gallery.downloader")

HF_PREFIXES = ("huggingface://", "hf://")
GITHUB_PREFIX = "github:"
FILE_PREFIX = "file://"
OCI_PREFIX = "oci://"
OLLAMA_PREFIX = "ollama://"


def resolve_uri(uri: str) -> str:
    """Map shorthand schemes to concrete URLs (reference: uri.go:34-92)."""
    for p in HF_PREFIXES:
        if uri.startswith(p):
            repo_and_file = uri[len(p):]
            parts = repo_and_file.split("/")
            if len(parts) < 3:
                raise ValueError(f"huggingface uri needs owner/repo/file: {uri}")
            repo = "/".join(parts[:2])
            branch = "main"
            fname = "/".join(parts[2:])
            if "@" in repo:
                repo, branch = repo.split("@", 1)
            return f"https://huggingface.co/{repo}/resolve/{branch}/{fname}"
    if uri.startswith(GITHUB_PREFIX):
        ref = uri[len(GITHUB_PREFIX):]
        parts = ref.split("/")
        owner, repo = parts[0], parts[1]
        branch = "main"
        if "@" in repo:
            repo, branch = repo.split("@", 1)
        path = "/".join(parts[2:])
        return f"https://raw.githubusercontent.com/{owner}/{repo}/{branch}/{path}"
    return uri


def download_file(uri: str, dest: str, sha256: str = "",
                  progress: Optional[Callable] = None,
                  chunk_size: int = 1 << 20) -> str:
    """Download uri to dest (with .partial resume), verify sha256."""
    if uri.startswith(FILE_PREFIX):
        src = uri[len(FILE_PREFIX):]
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        shutil.copyfile(src, dest)
        _verify(dest, sha256)
        return dest
    if uri.startswith((OCI_PREFIX, OLLAMA_PREFIX)):
        return _pull_registry_blob(uri, dest, sha256, progress)

    url = resolve_uri(uri)
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
    partial = dest + ".partial"
    pos = os.path.getsize(partial) if os.path.exists(partial) else 0
    headers = {"Range": f"bytes={pos}-"} if pos else {}
    with httpx.stream("GET", url, headers=headers, timeout=60.0,
                      follow_redirects=True) as resp:
        if resp.status_code == 416:  # already complete
            pass
        else:
            resp.raise_for_status()
            if resp.status_code != 206:
                pos = 0  # server ignored Range; restart
            total = int(resp.headers.get("Content-Length", 0)) + pos
            mode = "ab" if pos else "wb"
            with open(partial, mode) as f:
                done = pos
                for chunk in resp.iter_bytes(chunk_size):
                    f.write(chunk)
                    done += len(chunk)
                    if progress and total:
                        progress(done, total)
    os.replace(partial, dest)
    _verify(dest, sha256)
    return dest


OLLAMA_REGISTRY = os.environ.get("LOCALAI_OLLAMA_REGISTRY",
                                 "https://registry.ollama.ai")
OLLAMA_MODEL_MEDIA_TYPE = "application/vnd.ollama.image.model"
MANIFEST_ACCEPT = ", ".join((
    "application/vnd.docker.distribution.manifest.v2+json",
    "application/vnd.oci.image.manifest.v1+json",
))


def parse_image_ref(uri: str):
    """ollama://[registry/]repo[:tag] or oci://registry/repo[:tag]
    -> (registry_base, repository, tag).

    Ollama shorthands mirror the reference (reference: pkg/oci/ollama.go:34-42 —
    bare names map to library/<name> on registry.ollama.ai, default tag
    latest)."""
    if uri.startswith(OLLAMA_PREFIX):
        ref = uri[len(OLLAMA_PREFIX):]
        tag = "latest"
        if ":" in ref.rsplit("/", 1)[-1]:
            ref, tag = ref.rsplit(":", 1)
        if "/" not in ref:
            ref = f"library/{ref}"
        return OLLAMA_REGISTRY, ref, tag
    ref = uri[len(OCI_PREFIX):]
    tag = "latest"
    if ":" in ref.rsplit("/", 1)[-1]:
        ref, tag = ref.rsplit(":", 1)
    host, _, repo = ref.partition("/")
    if not repo:
        raise ValueError(f"oci uri needs registry/repository: {uri}")
    scheme = "http" if host.startswith(("localhost", "127.0.0.1")) else "https"
    return f"{scheme}://{host}", repo, tag


def _pull_registry_blob(uri: str, dest: str, sha256: str,
                        progress: Optional[Callable]) -> str:
    """Pull a model blob via the OCI distribution API (reference:
    pkg/oci/ollama.go — manifest fetch, pick the
    application/vnd.ollama.image.model layer, download its blob; plain OCI
    images take the largest layer)."""
    base, repo, tag = parse_image_ref(uri)
    with httpx.Client(timeout=120.0, follow_redirects=True) as client:
        r = client.get(f"{base}/v2/{repo}/manifests/{tag}",
                       headers={"Accept": MANIFEST_ACCEPT})
        r.raise_for_status()
        manifest = r.json()
        layers = manifest.get("layers") or []
        if not layers:
            raise ValueError(f"no layers in manifest for {uri}")
        model_layers = [l for l in layers
                        if l.get("mediaType") == OLLAMA_MODEL_MEDIA_TYPE]
        layer = (model_layers[0] if model_layers
                 else max(layers, key=lambda l: l.get("size", 0)))
        digest = layer["digest"]
        total = int(layer.get("size", 0))

        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        partial = dest + ".partial"
        with client.stream("GET", f"{base}/v2/{repo}/blobs/{digest}") as resp:
            resp.raise_for_status()
            done = 0
            with open(partial, "wb") as f:
                for chunk in resp.iter_bytes(1 << 20):
                    f.write(chunk)
                    done += len(chunk)
                    if progress and total:
                        progress(done, total)
    os.replace(partial, dest)
    # registries address blobs by digest — verify it even without an
    # explicit sha256 from the gallery entry
    want = sha256 or (digest.split(":", 1)[1] if digest.startswith("sha256:") else "")
    _verify(dest, want)
    return dest


def _verify(path: str, sha256: str):
    if not sha256:
        return
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    if h.hexdigest() != sha256.lower():
        os.unlink(path)
        raise ValueError(f"sha256 mismatch for {path}: got {h.hexdigest()}, want {sha256}")
