"""Model gallery: index fetch, install, delete.

Parity with the reference gallery (reference: core/gallery/gallery.go:19-85
InstallModelFromGallery, models.go:99 InstallModel — download files with
sha256 + progress, write the model config YAML with overrides; `@gallery`
refs; delete removes config + files).
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional

import yaml

from localai_tpu.gallery import downloader

log = logging.getLogger("localai_tpu.gallery")


def load_gallery_index(galleries: list) -> list:
    """galleries: [{name, url}] -> flat list of model entries with _gallery."""
    out = []
    for g in galleries:
        url = g.get("url", "")
        try:
            if url.startswith("file://"):
                with open(url[len("file://"):]) as f:
                    entries = yaml.safe_load(f) or []
            else:
                import httpx

                resp = httpx.get(downloader.resolve_uri(url), timeout=30.0,
                                 follow_redirects=True)
                resp.raise_for_status()
                entries = yaml.safe_load(resp.text) or []
            for e in entries:
                e["_gallery"] = g.get("name", "")
            out.extend(entries)
        except Exception:
            log.exception("failed to load gallery %s", url)
    return out


def find_model(index: list, name: str) -> Optional[dict]:
    """Resolve 'model' or 'gallery@model' refs (reference: gallery.go:44-72)."""
    gallery = ""
    if "@" in name:
        gallery, _, name = name.partition("@")
    for e in index:
        if e.get("name") == name and (not gallery or e.get("_gallery") == gallery):
            return e
    return None


def install_model(entry: dict, models_path: str, overrides: Optional[dict] = None,
                  progress: Optional[Callable] = None, name_override: str = ""):
    """Download the entry's files + write its config YAML."""
    name = name_override or entry.get("name", "model")
    os.makedirs(models_path, exist_ok=True)

    files = entry.get("files", [])
    n = len(files)
    for i, f in enumerate(files):
        dest = os.path.join(models_path, f.get("filename", os.path.basename(f["uri"])))
        def file_progress(done, total, _i=i):
            if progress:
                progress((_i + done / max(total, 1)) / max(n, 1), f"downloading {dest}")
        log.info("downloading %s -> %s", f["uri"], dest)
        downloader.download_file(f["uri"], dest, f.get("sha256", ""), file_progress)

    config = {}
    # inline config or a config_file URL (reference: models.go config handling)
    if entry.get("config_file"):
        cf = entry["config_file"]
        if isinstance(cf, dict):
            config = dict(cf)
        elif isinstance(cf, str) and cf.startswith(("http", "file://", "github:")):
            import tempfile

            with tempfile.NamedTemporaryFile(suffix=".yaml", delete=False) as tmp:
                downloader.download_file(cf, tmp.name)
                with open(tmp.name) as fh:
                    config = yaml.safe_load(fh) or {}
            os.unlink(tmp.name)
        else:
            config = yaml.safe_load(cf) or {}
    if entry.get("url") and not config:
        config = {"name": name}
    config.update(overrides or {})
    config["name"] = name

    cfg_path = os.path.join(models_path, f"{name}.yaml")
    with open(cfg_path, "w") as f:
        yaml.safe_dump(config, f, sort_keys=False)
    if progress:
        progress(1.0, "done")
    return cfg_path


def delete_model(name: str, models_path: str):
    """Remove config + referenced weight files (reference: DeleteModelFromSystem)."""
    cfg_path = os.path.join(models_path, f"{name}.yaml")
    if os.path.exists(cfg_path):
        try:
            with open(cfg_path) as f:
                cfg = yaml.safe_load(f) or {}
            model_file = (cfg.get("parameters") or {}).get("model") or cfg.get("model")
            if model_file:
                p = os.path.join(models_path, model_file)
                if os.path.isfile(p):
                    os.unlink(p)
        except Exception:
            log.exception("failed reading config for delete of %s", name)
        os.unlink(cfg_path)
