"""VITS / MMS-TTS text-to-speech in functional JAX (HF checkpoint layout).

Real-checkpoint TTS (VERDICT r2 #2): loads ``VitsModel`` checkpoints —
facebook/mms-tts-* (1100+ languages) and kakao-enterprise/vits-* — through
their native safetensors layout and runs the full VITS inference stack:

  text encoder (relative-position attention) -> stochastic or
  deterministic duration predictor (rational-quadratic-spline flows) ->
  length regulation -> residual-coupling flow (reverse) -> HiFi-GAN.

Semantics follow the public ``transformers`` implementation
(transformers/models/vits/modeling_vits.py, v4.57) — the r3 test suite
checks NUMERICAL parity against torch ``VitsModel`` on tiny-random
checkpoints. Reference-parity role: the reference serves piper/bark TTS
checkpoints via dedicated backends (reference: backend/go/tts/piper.go,
backend/python/*); this module is the TPU-native published-checkpoint
speech path.

Params are a FLAT dict keyed by the HF tensor names (weight-norm
parametrizations are materialized at load), so the mapping between file
and math is auditable one-to-one.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class VitsConfig:
    vocab_size: int = 38
    hidden_size: int = 192
    num_hidden_layers: int = 6
    num_attention_heads: int = 2
    window_size: int = 4
    ffn_dim: int = 768
    ffn_kernel_size: int = 3
    flow_size: int = 192
    prior_encoder_num_flows: int = 4
    prior_encoder_num_wavenet_layers: int = 4
    wavenet_kernel_size: int = 5
    wavenet_dilation_rate: int = 1
    upsample_initial_channel: int = 512
    upsample_rates: tuple = (8, 8, 2, 2)
    upsample_kernel_sizes: tuple = (16, 16, 4, 4)
    resblock_kernel_sizes: tuple = (3, 7, 11)
    resblock_dilation_sizes: tuple = ((1, 3, 5), (1, 3, 5), (1, 3, 5))
    leaky_relu_slope: float = 0.1
    use_stochastic_duration_prediction: bool = True
    duration_predictor_num_flows: int = 4
    duration_predictor_flow_bins: int = 10
    duration_predictor_tail_bound: float = 5.0
    duration_predictor_kernel_size: int = 3
    duration_predictor_filter_channels: int = 256
    depth_separable_channels: int = 2
    depth_separable_num_layers: int = 3
    num_speakers: int = 1
    speaker_embedding_size: int = 0
    layer_norm_eps: float = 1e-5
    hidden_act: str = "relu"
    noise_scale: float = 0.667
    noise_scale_duration: float = 0.8
    speaking_rate: float = 1.0
    sampling_rate: int = 16000

    @staticmethod
    def from_dict(d: dict) -> "VitsConfig":
        fields = {f.name for f in dataclasses.fields(VitsConfig)}
        kw = {k: (tuple(tuple(x) if isinstance(x, list) else x for x in v)
                  if isinstance(v, list) else v)
              for k, v in d.items() if k in fields}
        return VitsConfig(**kw)

    @staticmethod
    def from_json(path: str) -> "VitsConfig":
        with open(path) as f:
            return VitsConfig.from_dict(json.load(f))


# ---------- primitives (torch layouts: x [B, C, T], w [out, in, k]) ----------

def _conv1d(x, w, b=None, stride=1, dilation=1, padding=0, groups=1):
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=[(padding, padding)],
        rhs_dilation=(dilation,), dimension_numbers=("NCH", "OIH", "NCH"),
        feature_group_count=groups)
    if b is not None:
        out = out + b[None, :, None]
    return out


def _conv_transpose1d(x, w, b=None, stride=1, padding=0):
    """torch ConvTranspose1d: w [in, out, k]."""
    k = w.shape[-1]
    w_t = jnp.flip(w, axis=-1).transpose(1, 0, 2)     # [out, in, k]
    out = jax.lax.conv_general_dilated(
        x, w_t, window_strides=(1,), padding=[(k - 1 - padding,) * 2],
        lhs_dilation=(stride,), dimension_numbers=("NCH", "OIH", "NCH"))
    if b is not None:
        out = out + b[None, :, None]
    return out


def _layer_norm_cl(x, w, b, eps):
    """LayerNorm over the CHANNEL axis of [B, C, T] (torch transposes)."""
    mu = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.var(x, axis=1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w[None, :, None] + b[None, :, None]


def _act(name):
    return {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
            "silu": jax.nn.silu, "swish": jax.nn.silu}[name]


class _P:
    """Flat param accessor with prefix chaining."""

    def __init__(self, params: dict, prefix: str = ""):
        self.d = params
        self.p = prefix

    def __call__(self, name):
        return self.d[self.p + name]

    def has(self, name):
        return (self.p + name) in self.d

    def sub(self, name):
        return _P(self.d, self.p + name)


# ---------- text encoder ----------

def _rel_embeddings(emb, length, window):
    pad = max(length - (window + 1), 0)
    if pad > 0:
        emb = jnp.pad(emb, ((0, 0), (pad, pad), (0, 0)))
    start = max((window + 1) - length, 0)
    return emb[:, start:start + 2 * length - 1]


def _rel_to_abs(x):
    """[BH, L, 2L-1] -> [BH, L, L] (transformers _relative_position_to_absolute_position)."""
    bh, length, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, 1)))
    x = x.reshape(bh, length * 2 * length)
    x = jnp.pad(x, ((0, 0), (0, length - 1)))
    x = x.reshape(bh, length + 1, 2 * length - 1)
    return x[:, :length, length - 1:]


def _abs_to_rel(x):
    """[BH, L, L] -> [BH, L, 2L-1]."""
    bh, length, _ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (0, length - 1)))
    x = x.reshape(bh, length * (2 * length - 1))
    x = jnp.pad(x, ((0, 0), (length, 0)))
    return x.reshape(bh, length, 2 * length)[:, :, 1:]


def _attention(p: _P, cfg: VitsConfig, x):
    """x [B, T, D] -> [B, T, D] (window-relative positional attention)."""
    B, T, D = x.shape
    H = cfg.num_attention_heads
    hd = D // H
    scale = hd ** -0.5

    def lin(n, v):
        return v @ p(n + ".weight").T + p(n + ".bias")

    q = (lin("q_proj", x) * scale).reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    k = lin("k_proj", x).reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    v = lin("v_proj", x).reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    q = q.reshape(B * H, T, hd)
    k = k.reshape(B * H, T, hd)
    v = v.reshape(B * H, T, hd)
    w = q @ k.transpose(0, 2, 1)                               # [BH, T, T]
    if cfg.window_size:
        rel_k = _rel_embeddings(p("emb_rel_k"), T, cfg.window_size)
        w = w + _rel_to_abs(q @ rel_k.transpose(0, 2, 1))
    w = jax.nn.softmax(w, axis=-1)
    out = w @ v                                                # [BH, T, hd]
    if cfg.window_size:
        rel_v = _rel_embeddings(p("emb_rel_v"), T, cfg.window_size)
        out = out + _abs_to_rel(w) @ rel_v
    out = out.reshape(B, H, T, hd).transpose(0, 2, 1, 3).reshape(B, T, D)
    return lin("out_proj", out)


def _feed_forward(p: _P, cfg: VitsConfig, x):
    """x [B, T, D]; convs along T with asymmetric SAME padding."""
    h = x.transpose(0, 2, 1)                                   # [B, D, T]
    k = cfg.ffn_kernel_size
    pl_, pr = (k - 1) // 2, k // 2
    if k > 1:
        h = jnp.pad(h, ((0, 0), (0, 0), (pl_, pr)))
    h = _conv1d(h, p("conv_1.weight"), p("conv_1.bias"))
    h = _act(cfg.hidden_act)(h)
    if k > 1:
        h = jnp.pad(h, ((0, 0), (0, 0), (pl_, pr)))
    h = _conv1d(h, p("conv_2.weight"), p("conv_2.bias"))
    return h.transpose(0, 2, 1)


def _ln(x, w, b, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def text_encoder(p: _P, cfg: VitsConfig, input_ids):
    """input_ids [B, T] -> (hidden [B,T,D], prior_means, prior_log_var)."""
    x = p("embed_tokens.weight")[input_ids] * math.sqrt(cfg.hidden_size)
    for i in range(cfg.num_hidden_layers):
        lp = p.sub(f"encoder.layers.{i}.")
        a = _attention(lp.sub("attention."), cfg, x)
        x = _ln(x + a, lp("layer_norm.weight"), lp("layer_norm.bias"),
                cfg.layer_norm_eps)
        f = _feed_forward(lp.sub("feed_forward."), cfg, x)
        x = _ln(x + f, lp("final_layer_norm.weight"),
                lp("final_layer_norm.bias"), cfg.layer_norm_eps)
    stats = _conv1d(x.transpose(0, 2, 1), p("project.weight"),
                    p("project.bias")).transpose(0, 2, 1)
    m, logs = jnp.split(stats, 2, axis=-1)
    return x, m, logs


# ---------- wavenet + coupling flow ----------

def _wn_weight(p: _P, name):
    """Weight-norm conv weight. load_params materializes these to plain
    ``.weight`` entries once; the on-the-fly path only serves raw
    state_dicts (tests)."""
    if p.has(name + ".weight"):
        return p(name + ".weight")
    g = p(name + ".parametrizations.weight.original0")
    v = p(name + ".parametrizations.weight.original1")
    norm = jnp.sqrt(jnp.sum(v * v, axis=(1, 2), keepdims=True))
    return g * v / norm


def wavenet(p: _P, cfg: VitsConfig, x, num_layers, cond=None):
    """x [B, D, T]; gated dilated conv stack (VitsWaveNet semantics)."""
    D = cfg.hidden_size
    out = jnp.zeros_like(x)
    if cond is not None and p.has("cond_layer.bias"):
        cond = _conv1d(cond, _wn_weight(p, "cond_layer"), p("cond_layer.bias"))
    for i in range(num_layers):
        dil = cfg.wavenet_dilation_rate ** i
        pad = (cfg.wavenet_kernel_size * dil - dil) // 2
        h = _conv1d(x, _wn_weight(p, f"in_layers.{i}"), p(f"in_layers.{i}.bias"),
                    dilation=dil, padding=pad)
        if cond is not None:
            h = h + cond[:, i * 2 * D:(i + 1) * 2 * D]
        acts = jnp.tanh(h[:, :D]) * jax.nn.sigmoid(h[:, D:])
        rs = _conv1d(acts, _wn_weight(p, f"res_skip_layers.{i}"),
                     p(f"res_skip_layers.{i}.bias"))
        if i < num_layers - 1:
            x = x + rs[:, :D]
            out = out + rs[:, D:]
        else:
            out = out + rs
    return out


def flow_reverse(p: _P, cfg: VitsConfig, z, cond=None):
    """Residual-coupling block in reverse: z [B, flow_size, T]."""
    half = cfg.flow_size // 2
    for i in reversed(range(cfg.prior_encoder_num_flows)):
        z = jnp.flip(z, axis=1)
        fp = p.sub(f"flows.{i}.")
        z0, z1 = z[:, :half], z[:, half:]
        h = _conv1d(z0, fp("conv_pre.weight"), fp("conv_pre.bias"))
        h = wavenet(fp.sub("wavenet."), cfg, h,
                    cfg.prior_encoder_num_wavenet_layers, cond)
        m = _conv1d(h, fp("conv_post.weight"), fp("conv_post.bias"))
        z = jnp.concatenate([z0, z1 - m], axis=1)
    return z


# ---------- stochastic duration predictor ----------

def _dds(p: _P, cfg: VitsConfig, x, cond=None):
    """VitsDilatedDepthSeparableConv; x [B, D, T]."""
    if cond is not None:
        x = x + cond
    k = cfg.duration_predictor_kernel_size
    for i in range(cfg.depth_separable_num_layers):
        dil = k ** i
        pad = (k * dil - dil) // 2
        h = _conv1d(x, p(f"convs_dilated.{i}.weight"),
                    p(f"convs_dilated.{i}.bias"), dilation=dil, padding=pad,
                    groups=x.shape[1])
        h = _layer_norm_cl(h, p(f"norms_1.{i}.weight"), p(f"norms_1.{i}.bias"),
                           cfg.layer_norm_eps)
        h = jax.nn.gelu(h, approximate=False)
        h = _conv1d(h, p(f"convs_pointwise.{i}.weight"),
                    p(f"convs_pointwise.{i}.bias"))
        h = _layer_norm_cl(h, p(f"norms_2.{i}.weight"), p(f"norms_2.{i}.bias"),
                           cfg.layer_norm_eps)
        h = jax.nn.gelu(h, approximate=False)
        x = x + h
    return x


def _rq_spline_reverse(inputs, uw, uh, ud, tail_bound):
    """Unconstrained rational-quadratic spline, reverse mode.

    inputs [...]; uw/uh [..., bins]; ud [..., bins-1] (padded to bins+1 with
    the boundary constant). Vectorized counterpart of the transformers
    reference (no boolean indexing)."""
    min_bw = min_bh = min_d = 1e-3
    nbins = uw.shape[-1]
    inside = (inputs >= -tail_bound) & (inputs <= tail_bound)
    x = jnp.where(inside, inputs, 0.0)   # dummy inside-domain value for pads

    const = math.log(math.exp(1 - min_d) - 1)
    ud = jnp.pad(ud, [(0, 0)] * (ud.ndim - 1) + [(1, 1)],
                 constant_values=const)

    widths = jax.nn.softmax(uw, axis=-1)
    widths = min_bw + (1 - min_bw * nbins) * widths
    cumw = jnp.cumsum(widths, axis=-1)
    cumw = jnp.pad(cumw, [(0, 0)] * (cumw.ndim - 1) + [(1, 0)])
    cumw = 2 * tail_bound * cumw - tail_bound
    cumw = cumw.at[..., 0].set(-tail_bound)
    cumw = cumw.at[..., -1].set(tail_bound)
    widths = cumw[..., 1:] - cumw[..., :-1]

    derivs = min_d + jax.nn.softplus(ud)

    heights = jax.nn.softmax(uh, axis=-1)
    heights = min_bh + (1 - min_bh * nbins) * heights
    cumh = jnp.cumsum(heights, axis=-1)
    cumh = jnp.pad(cumh, [(0, 0)] * (cumh.ndim - 1) + [(1, 0)])
    cumh = 2 * tail_bound * cumh - tail_bound
    cumh = cumh.at[..., 0].set(-tail_bound)
    cumh = cumh.at[..., -1].set(tail_bound)
    heights = cumh[..., 1:] - cumh[..., :-1]

    locations = cumh.at[..., -1].add(1e-6)   # reverse: bin by heights
    bin_idx = jnp.sum((x[..., None] >= locations).astype(jnp.int32),
                      axis=-1) - 1
    bin_idx = jnp.clip(bin_idx, 0, nbins - 1)[..., None]

    def take(a):
        return jnp.take_along_axis(a, bin_idx, axis=-1)[..., 0]

    in_cumw = take(cumw)
    in_w = take(widths)
    in_cumh = take(cumh)
    delta = heights / widths
    in_delta = take(delta)
    in_d = take(derivs)
    in_d1 = take(derivs[..., 1:])
    in_h = take(heights)

    i1 = in_d + in_d1 - 2 * in_delta
    i2 = x - in_cumh
    i3 = i2 * i1
    a = in_h * (in_delta - in_d) + i3
    b = in_h * in_d - i3
    c = -in_delta * i2
    disc = b * b - 4 * a * c
    root = (2 * c) / (-b - jnp.sqrt(jnp.maximum(disc, 0.0)))
    out = root * in_w + in_cumw
    return jnp.where(inside, out, inputs)


def _conv_flow_reverse(p: _P, cfg: VitsConfig, z, cond=None):
    half = cfg.depth_separable_channels // 2
    z0, z1 = z[:, :half], z[:, half:]
    h = _conv1d(z0, p("conv_pre.weight"), p("conv_pre.bias"))
    h = _dds(p.sub("conv_dds."), cfg, h, cond)
    h = _conv1d(h, p("conv_proj.weight"), p("conv_proj.bias"))
    B, _, T = z0.shape
    nbins = cfg.duration_predictor_flow_bins
    h = h.reshape(B, half, -1, T).transpose(0, 1, 3, 2)  # [B, half, T, 3b-1]
    scale = math.sqrt(cfg.hidden_size)
    z1 = _rq_spline_reverse(z1, h[..., :nbins] / scale,
                            h[..., nbins:2 * nbins] / scale,
                            h[..., 2 * nbins:],
                            cfg.duration_predictor_tail_bound)
    return jnp.concatenate([z0, z1], axis=1)


def stochastic_duration_reverse(p: _P, cfg: VitsConfig, x, noise,
                                cond=None):
    """x [B, D, T] encoder states; noise [B, 2, T]. Returns log-durations
    [B, 1, T]. (transformers VitsStochasticDurationPredictor, reverse.)"""
    h = _conv1d(x, p("conv_pre.weight"), p("conv_pre.bias"))
    if cond is not None and p.has("cond.bias"):
        h = h + _conv1d(cond, p("cond.weight"), p("cond.bias"))
    h = _dds(p.sub("conv_dds."), cfg, h)
    h = _conv1d(h, p("conv_proj.weight"), p("conv_proj.bias"))

    n = cfg.duration_predictor_num_flows
    # reversed [CF_n .. CF_1, EA] minus the "useless vflow" CF_1
    order = list(range(n, 1, -1)) + [0]
    z = noise
    for idx in order:
        z = jnp.flip(z, axis=1)
        fp = p.sub(f"flows.{idx}.")
        if idx == 0:   # ElementwiseAffine
            z = (z - fp("translate")[None]) * jnp.exp(-fp("log_scale")[None])
        else:
            z = _conv_flow_reverse(fp, cfg, z, cond=h)
    return z[:, :1]


# ---------- HiFi-GAN ----------

def hifigan(p: _P, cfg: VitsConfig, spec, cond=None):
    """spec [B, flow_size, T] -> waveform [B, samples]."""
    slope = cfg.leaky_relu_slope
    x = _conv1d(spec, _wn_weight(p, "conv_pre"), p("conv_pre.bias"), padding=3)
    if cond is not None and p.has("cond.bias"):
        x = x + _conv1d(cond, p("cond.weight"), p("cond.bias"))
    nk = len(cfg.resblock_kernel_sizes)
    for i, (rate, k) in enumerate(zip(cfg.upsample_rates,
                                      cfg.upsample_kernel_sizes)):
        x = jax.nn.leaky_relu(x, slope)
        x = _conv_transpose1d(x, _wn_weight(p, f"upsampler.{i}"),
                              p(f"upsampler.{i}.bias"), stride=rate,
                              padding=(k - rate) // 2)
        acc = None
        for j in range(nk):
            rp = p.sub(f"resblocks.{i * nk + j}.")
            ks = cfg.resblock_kernel_sizes[j]
            dils = cfg.resblock_dilation_sizes[j]
            h = x
            for di, d in enumerate(dils):
                r = h
                h = jax.nn.leaky_relu(h, slope)
                h = _conv1d(h, _wn_weight(rp, f"convs1.{di}"),
                            rp(f"convs1.{di}.bias"), dilation=d,
                            padding=(ks * d - d) // 2)
                h = jax.nn.leaky_relu(h, slope)
                h = _conv1d(h, _wn_weight(rp, f"convs2.{di}"),
                            rp(f"convs2.{di}.bias"), padding=(ks - 1) // 2)
                h = h + r
            acc = h if acc is None else acc + h
        x = acc / nk
    x = jax.nn.leaky_relu(x, 0.01)   # torch default negative_slope
    x = _conv1d(x, _wn_weight(p, "conv_post"), None, padding=3)
    return jnp.tanh(x)[:, 0]


# ---------- full inference ----------

def synthesize(params: dict, cfg: VitsConfig, input_ids: np.ndarray,
               seed: int = 0, speaker_id: Optional[int] = None,
               noise_scale: Optional[float] = None,
               noise_scale_duration: Optional[float] = None,
               speaking_rate: Optional[float] = None,
               frame_pad_to: Optional[int] = None,
               speaker_embedding: Optional[np.ndarray] = None) -> np.ndarray:
    """input_ids [T] -> waveform float32 [samples].

    Host-side orchestration: the duration pass determines the (data-
    dependent) frame count, then the flow+decoder run at that length.
    ``frame_pad_to`` pads frames to a multiple to bound compile variants:
    padded frames enter the flow as ZEROS (masked prior), so the trimmed
    tail can differ from an unpadded run only within the flow/HiFi-GAN
    conv receptive fields (a short end-of-clip fade, not content)."""
    p = _P(params)
    noise_scale = cfg.noise_scale if noise_scale is None else noise_scale
    nsd = (cfg.noise_scale_duration if noise_scale_duration is None
           else noise_scale_duration)
    rate = cfg.speaking_rate if speaking_rate is None else speaking_rate
    rng = np.random.default_rng(seed)

    ids = jnp.asarray(np.asarray(input_ids, np.int32)[None])
    hidden, m_p, logs_p = text_encoder(p.sub("text_encoder."), cfg, ids)
    hidden_ct = hidden.transpose(0, 2, 1)

    cond = None
    if speaker_embedding is not None:
        # voice clone (models/voice_clone.py): a tone-color embedding
        # replaces the speaker-id table lookup on the SAME cond pathway
        cond = jnp.asarray(speaker_embedding, jnp.float32)[None, :, None]
    elif cfg.num_speakers > 1 and speaker_id is not None:
        emb = p("embed_speaker.weight")[speaker_id]
        cond = emb[None, :, None]

    T = ids.shape[1]
    if cfg.use_stochastic_duration_prediction:
        noise = jnp.asarray(
            rng.standard_normal((1, 2, T)).astype(np.float32)) * nsd
        log_dur = stochastic_duration_reverse(
            p.sub("duration_predictor."), cfg, hidden_ct, noise, cond)
    else:
        dp = p.sub("duration_predictor.")
        h = hidden_ct
        if cond is not None and dp.has("cond.bias"):
            h = h + _conv1d(cond, dp("cond.weight"), dp("cond.bias"))
        k = cfg.duration_predictor_kernel_size
        h = _conv1d(h, dp("conv_1.weight"), dp("conv_1.bias"), padding=k // 2)
        h = _layer_norm_cl(jax.nn.relu(h), dp("norm_1.weight"),
                           dp("norm_1.bias"), cfg.layer_norm_eps)
        h = _conv1d(h, dp("conv_2.weight"), dp("conv_2.bias"), padding=k // 2)
        h = _layer_norm_cl(jax.nn.relu(h), dp("norm_2.weight"),
                           dp("norm_2.bias"), cfg.layer_norm_eps)
        log_dur = _conv1d(h, dp("proj.weight"), dp("proj.bias"))

    duration = np.ceil(np.exp(np.asarray(log_dur))[0, 0] / rate)
    frames = int(max(duration.sum(), 1))
    pad_frames = frames
    if frame_pad_to:
        pad_frames = ((frames + frame_pad_to - 1) // frame_pad_to) * frame_pad_to

    # length regulation: frame f attends to the phoneme whose cumulative
    # duration covers it
    cum = np.cumsum(duration)
    frame_idx = np.searchsorted(cum, np.arange(frames) + 1.0)
    frame_idx = np.clip(frame_idx, 0, T - 1)
    attn = np.zeros((pad_frames,), np.int32)
    attn[:frames] = frame_idx

    m_e = jnp.asarray(np.asarray(m_p)[0][attn]).T[None]        # [1, F, flow]->[1, flow, F]
    logs_e = jnp.asarray(np.asarray(logs_p)[0][attn]).T[None]

    z_noise = jnp.asarray(
        rng.standard_normal(m_e.shape).astype(np.float32))
    z_p = m_e + z_noise * jnp.exp(logs_e) * noise_scale
    if pad_frames != frames:
        # padded frames must be ZERO, not phoneme-0 prior + noise — pad
        # content bleeds into the kept tail through conv receptive fields
        fmask = (np.arange(pad_frames) < frames).astype(np.float32)
        z_p = z_p * jnp.asarray(fmask)[None, None, :]
    z = flow_reverse(p.sub("flow."), cfg, z_p, cond)
    wav = hifigan(p.sub("decoder."), cfg, z, cond)
    samples = frames * int(np.prod(cfg.upsample_rates))
    return np.asarray(wav)[0][:samples]


# ---------- weight loading ----------

def materialize_weight_norms(params: dict) -> dict:
    """Fold ``parametrizations.weight.original0/1`` pairs into plain
    ``.weight`` tensors ONCE (g * v / ||v||) so synthesize() never
    recomputes norms per conv per request."""
    out = dict(params)
    for name in list(params):
        if name.endswith(".parametrizations.weight.original0"):
            base = name[: -len(".parametrizations.weight.original0")]
            g = params[name]
            v = params[base + ".parametrizations.weight.original1"]
            norm = jnp.sqrt(jnp.sum(v * v, axis=(1, 2), keepdims=True))
            out[base + ".weight"] = g * v / norm
    return out


def load_params(model_dir: str, cfg: Optional[VitsConfig] = None) -> tuple:
    """(config, flat params dict) from an HF VitsModel checkpoint dir."""
    from safetensors import safe_open

    if cfg is None:
        cfg = VitsConfig.from_json(os.path.join(model_dir, "config.json"))
    path = os.path.join(model_dir, "model.safetensors")
    params: dict = {}
    with safe_open(path, framework="np") as f:
        for name in f.keys():
            params[name] = jnp.asarray(f.get_tensor(name), jnp.float32)
    return cfg, materialize_weight_norms(params)
