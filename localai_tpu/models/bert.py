"""BERT-family encoder for embeddings, functional JAX.

Capability parity with the reference's embedding backends (reference:
backend/go/llm/bert/bert.go bert-embeddings; backend/python/
sentencetransformers/backend.py mean-pooling embeddings). Layers are
stacked for lax.scan like the llama stack; batched inputs with attention
masking; mean-pool + L2 normalize (sentence-transformers semantics).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.ops.norms import layer_norm


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 384
    intermediate_size: int = 1536
    num_layers: int = 6
    num_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: Any = jnp.float32

    @staticmethod
    def from_hf_config(cfg: dict, dtype=jnp.float32) -> "BertConfig":
        return BertConfig(
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            max_position_embeddings=cfg.get("max_position_embeddings", 512),
            type_vocab_size=cfg.get("type_vocab_size", 2),
            layer_norm_eps=cfg.get("layer_norm_eps", 1e-12),
            dtype=dtype,
        )

    @staticmethod
    def from_json(path: str, dtype=jnp.float32) -> "BertConfig":
        with open(path) as f:
            return BertConfig.from_hf_config(json.load(f), dtype=dtype)


def init_params(cfg: BertConfig, key: jax.Array) -> dict:
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    ks = jax.random.split(key, 12)

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan_in)).astype(cfg.dtype)

    return {
        "word_embed": init(ks[0], (cfg.vocab_size, D), D),
        "pos_embed": init(ks[1], (cfg.max_position_embeddings, D), D),
        "type_embed": init(ks[2], (cfg.type_vocab_size, D), D),
        "embed_norm_w": jnp.ones((D,), cfg.dtype),
        "embed_norm_b": jnp.zeros((D,), cfg.dtype),
        "layers": {
            "wq": init(ks[3], (L, D, D), D), "bq": jnp.zeros((L, D), cfg.dtype),
            "wk": init(ks[4], (L, D, D), D), "bk": jnp.zeros((L, D), cfg.dtype),
            "wv": init(ks[5], (L, D, D), D), "bv": jnp.zeros((L, D), cfg.dtype),
            "wo": init(ks[6], (L, D, D), D), "bo": jnp.zeros((L, D), cfg.dtype),
            "attn_norm_w": jnp.ones((L, D), cfg.dtype),
            "attn_norm_b": jnp.zeros((L, D), cfg.dtype),
            "w_in": init(ks[7], (L, D, F), D), "b_in": jnp.zeros((L, F), cfg.dtype),
            "w_out": init(ks[8], (L, F, D), F), "b_out": jnp.zeros((L, D), cfg.dtype),
            "mlp_norm_w": jnp.ones((L, D), cfg.dtype),
            "mlp_norm_b": jnp.zeros((L, D), cfg.dtype),
        },
    }


def encode(params: dict, cfg: BertConfig, tokens: jax.Array, mask: jax.Array,
           type_ids: jax.Array = None):
    """tokens [B, T] int32, mask [B, T] bool -> hidden [B, T, D].

    type_ids [B, T] selects segment embeddings (None = all segment 0);
    cross-encoders mark the document half of a (query, document) pair
    with segment 1."""
    B, T = tokens.shape
    H = cfg.num_heads
    hd = cfg.hidden_size // H
    pos = jnp.arange(T, dtype=jnp.int32)
    if type_ids is None:
        seg = params["type_embed"][None, 0][:, None, :]
    else:
        seg = jnp.take(params["type_embed"], type_ids, axis=0)
    x = (jnp.take(params["word_embed"], tokens, axis=0)
         + params["pos_embed"][None, pos]
         + seg)
    x = layer_norm(x, params["embed_norm_w"], params["embed_norm_b"], cfg.layer_norm_eps)

    neg = jnp.float32(-1e30)

    def layer_fn(x, ly):
        q = (jnp.einsum("btd,de->bte", x, ly["wq"]) + ly["bq"]).reshape(B, T, H, hd)
        k = (jnp.einsum("btd,de->bte", x, ly["wk"]) + ly["bk"]).reshape(B, T, H, hd)
        v = (jnp.einsum("btd,de->bte", x, ly["wv"]) + ly["bv"]).reshape(B, T, H, hd)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(hd)
        scores = jnp.where(mask[:, None, None, :], scores, neg)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, T, -1)
        attn = jnp.einsum("bte,ed->btd", attn, ly["wo"]) + ly["bo"]
        x = layer_norm(x + attn, ly["attn_norm_w"], ly["attn_norm_b"], cfg.layer_norm_eps)
        h = jax.nn.gelu(jnp.einsum("btd,df->btf", x, ly["w_in"]) + ly["b_in"])
        h = jnp.einsum("btf,fd->btd", h, ly["w_out"]) + ly["b_out"]
        x = layer_norm(x + h, ly["mlp_norm_w"], ly["mlp_norm_b"], cfg.layer_norm_eps)
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, params["layers"])
    return x


def embed(params: dict, cfg: BertConfig, tokens: jax.Array, mask: jax.Array,
          normalize: bool = True):
    """Mean-pooled sentence embeddings [B, D] (sentence-transformers style)."""
    hidden = encode(params, cfg, tokens, mask)
    m = mask[:, :, None].astype(hidden.dtype)
    pooled = jnp.sum(hidden * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1e-9)
    if normalize:
        pooled = pooled / jnp.maximum(
            jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)
    return pooled


def cross_score(params: dict, cfg: BertConfig, tokens: jax.Array,
                mask: jax.Array, type_ids: jax.Array):
    """Cross-encoder relevance scores [B] for (query, document) pairs.

    Capability parity with the reference's reranker backend
    (reference: backend/python/rerankers/backend.py:1-123, jina-style
    rerank): BertForSequenceClassification semantics — CLS hidden state
    -> optional tanh pooler -> 1-logit classifier.
    """
    hidden = encode(params, cfg, tokens, mask, type_ids)
    cls = hidden[:, 0, :]
    if "pooler_w" in params:
        cls = jnp.tanh(jnp.einsum("bd,de->be", cls, params["pooler_w"])
                       + params["pooler_b"])
    logit = jnp.einsum("bd,dc->bc", cls, params["classifier_w"]) + params["classifier_b"]
    return logit[:, 0].astype(jnp.float32)


def init_cross_params(cfg: BertConfig, key: jax.Array) -> dict:
    """Random-init encoder + rerank head (for tests/smoke)."""
    k1, k2, k3 = jax.random.split(key, 3)
    params = init_params(cfg, k1)
    D = cfg.hidden_size
    params["pooler_w"] = (jax.random.normal(k2, (D, D), jnp.float32) / np.sqrt(D)).astype(cfg.dtype)
    params["pooler_b"] = jnp.zeros((D,), cfg.dtype)
    params["classifier_w"] = (jax.random.normal(k3, (D, 1), jnp.float32) / np.sqrt(D)).astype(cfg.dtype)
    params["classifier_b"] = jnp.zeros((1,), cfg.dtype)
    return params


def load_hf_cross_params(model_dir: str, cfg: BertConfig) -> dict:
    """Load a HF BertForSequenceClassification reranker (1-label head)."""
    from localai_tpu.engine.weights import _open_shards

    tensors = _open_shards(model_dir)
    params = load_hf_params(model_dir, cfg)

    def maybe(name):
        for prefix in ("", "bert."):
            if prefix + name in tensors:
                h = tensors[prefix + name]
                return np.asarray(h.get_tensor(prefix + name))
        return None

    pw = maybe("pooler.dense.weight")
    if pw is not None:
        params["pooler_w"] = jnp.asarray(pw.T, cfg.dtype)
        params["pooler_b"] = jnp.asarray(maybe("pooler.dense.bias"), cfg.dtype)
    cw = maybe("classifier.weight")
    if cw is None:
        raise KeyError("classifier.weight (not a sequence-classification checkpoint)")
    params["classifier_w"] = jnp.asarray(cw.T, cfg.dtype)
    params["classifier_b"] = jnp.asarray(maybe("classifier.bias"), cfg.dtype)
    return params


def load_hf_params(model_dir: str, cfg: BertConfig) -> dict:
    """Load HF bert-style safetensors into the stacked pytree."""
    from localai_tpu.engine.weights import _open_shards

    tensors = _open_shards(model_dir)

    def get(name):
        for prefix in ("", "bert.", "model."):
            if prefix + name in tensors:
                h = tensors[prefix + name]
                return h.get_tensor(prefix + name)
        raise KeyError(name)

    L = cfg.num_layers
    p = "encoder.layer.{i}."

    def stack(fmt, transpose=False):
        mats = [get(fmt.format(i=i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(np.stack(mats), cfg.dtype)

    return {
        "word_embed": jnp.asarray(get("embeddings.word_embeddings.weight"), cfg.dtype),
        "pos_embed": jnp.asarray(get("embeddings.position_embeddings.weight"), cfg.dtype),
        "type_embed": jnp.asarray(get("embeddings.token_type_embeddings.weight"), cfg.dtype),
        "embed_norm_w": jnp.asarray(get("embeddings.LayerNorm.weight"), cfg.dtype),
        "embed_norm_b": jnp.asarray(get("embeddings.LayerNorm.bias"), cfg.dtype),
        "layers": {
            "wq": stack(p + "attention.self.query.weight", True),
            "bq": stack(p + "attention.self.query.bias"),
            "wk": stack(p + "attention.self.key.weight", True),
            "bk": stack(p + "attention.self.key.bias"),
            "wv": stack(p + "attention.self.value.weight", True),
            "bv": stack(p + "attention.self.value.bias"),
            "wo": stack(p + "attention.output.dense.weight", True),
            "bo": stack(p + "attention.output.dense.bias"),
            "attn_norm_w": stack(p + "attention.output.LayerNorm.weight"),
            "attn_norm_b": stack(p + "attention.output.LayerNorm.bias"),
            "w_in": stack(p + "intermediate.dense.weight", True),
            "b_in": stack(p + "intermediate.dense.bias"),
            "w_out": stack(p + "output.dense.weight", True),
            "b_out": stack(p + "output.dense.bias"),
            "mlp_norm_w": stack(p + "output.LayerNorm.weight"),
            "mlp_norm_b": stack(p + "output.LayerNorm.bias"),
        },
    }
