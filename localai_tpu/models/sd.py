"""Stable-Diffusion-class latent diffusion in functional JAX, consuming
the HF *diffusers* checkpoint layout (VERDICT r2 #2: image generation
must load published checkpoints, not a framework-native toy format).

Components and their file layout (a diffusers pipeline directory):

  text_encoder/model.safetensors   — CLIP text encoder (transformers
                                     CLIPTextModel layout; numerically
                                     verified against torch in tests)
  unet/diffusion_pytorch_model.safetensors — UNet2DConditionModel
                                     (SD-1.x block structure)
  vae/diffusion_pytorch_model.safetensors  — AutoencoderKL
  */config.json                    — per-component configs

Pipeline: prompt -> CLIP hidden states -> classifier-free-guided DDIM
over the UNet in latent space -> VAE decode -> image. Reference parity:
the reference's diffusers backend (reference:
backend/python/diffusers/backend.py:92-217 LoadModel knobs, :360-470
txt2img) drives the same architecture through torch; this is the
TPU-native re-implementation (jit-able denoise steps, static shapes).

Params are FLAT dicts keyed by the checkpoint tensor names, making the
file->math mapping auditable (same stance as models/vits.py).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


class _P:
    def __init__(self, params: dict, prefix: str = ""):
        self.d = params
        self.p = prefix

    def __call__(self, name):
        return self.d[self.p + name]

    def has(self, name):
        return (self.p + name) in self.d

    def sub(self, name):
        return _P(self.d, self.p + name)


def _linear(p: _P, name, x):
    return x @ p(name + ".weight").T + p(name + ".bias")


def _conv2d(x, w, b=None, stride=1, padding=1):
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=[(padding, padding)] * 2,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if b is not None:
        out = out + b[None, :, None, None]
    return out


def _group_norm(x, w, b, groups=32, eps=1e-5):
    N, C, H, W = x.shape
    g = x.reshape(N, groups, C // groups, H, W)
    mu = jnp.mean(g, axis=(2, 3, 4), keepdims=True)
    var = jnp.var(g, axis=(2, 3, 4), keepdims=True)
    g = (g - mu) / jnp.sqrt(var + eps)
    return g.reshape(N, C, H, W) * w[None, :, None, None] + b[None, :, None, None]


def _ln(x, w, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


# ---------------- CLIP text encoder (transformers CLIPTextModel) ----------

@dataclasses.dataclass(frozen=True)
class ClipTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 77
    layer_norm_eps: float = 1e-5
    hidden_act: str = "quick_gelu"

    @staticmethod
    def from_json(path: str) -> "ClipTextConfig":
        with open(path) as f:
            d = json.load(f)
        fields = {f.name for f in dataclasses.fields(ClipTextConfig)}
        return ClipTextConfig(**{k: v for k, v in d.items() if k in fields})


def _clip_act(name):
    if name == "quick_gelu":
        return lambda x: x * jax.nn.sigmoid(1.702 * x)
    return jax.nn.gelu


def clip_text_encode(params: dict, cfg: ClipTextConfig,
                     input_ids: np.ndarray) -> jnp.ndarray:
    """input_ids [B, T] -> last hidden state [B, T, D] (causal CLIP)."""
    p = _P(params, "text_model.")
    ids = jnp.asarray(input_ids)
    B, T = ids.shape
    x = p("embeddings.token_embedding.weight")[ids] \
        + p("embeddings.position_embedding.weight")[:T][None]
    H = cfg.num_attention_heads
    hd = cfg.hidden_size // H
    causal = jnp.triu(jnp.full((T, T), -jnp.inf), k=1)

    for i in range(cfg.num_hidden_layers):
        lp = p.sub(f"encoder.layers.{i}.")
        h = _ln(x, lp("layer_norm1.weight"), lp("layer_norm1.bias"),
                cfg.layer_norm_eps)
        q = _linear(lp, "self_attn.q_proj", h).reshape(B, T, H, hd)
        k = _linear(lp, "self_attn.k_proj", h).reshape(B, T, H, hd)
        v = _linear(lp, "self_attn.v_proj", h).reshape(B, T, H, hd)
        w = jnp.einsum("bthd,bshd->bhts", q, k) / math.sqrt(hd) + causal
        w = jax.nn.softmax(w, axis=-1)
        a = jnp.einsum("bhts,bshd->bthd", w, v).reshape(B, T, -1)
        x = x + _linear(lp, "self_attn.out_proj", a)
        h = _ln(x, lp("layer_norm2.weight"), lp("layer_norm2.bias"),
                cfg.layer_norm_eps)
        h = _clip_act(cfg.hidden_act)(_linear(lp, "mlp.fc1", h))
        x = x + _linear(lp, "mlp.fc2", h)
    return _ln(x, p("final_layer_norm.weight"), p("final_layer_norm.bias"),
               cfg.layer_norm_eps)


# ---------------- UNet2DConditionModel (SD-1.x structure) ----------------

@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: tuple = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    attention_head_dim: Any = 8
    down_block_types: tuple = ("CrossAttnDownBlock2D", "CrossAttnDownBlock2D",
                               "CrossAttnDownBlock2D", "DownBlock2D")
    up_block_types: tuple = ("UpBlock2D", "CrossAttnUpBlock2D",
                             "CrossAttnUpBlock2D", "CrossAttnUpBlock2D")
    norm_num_groups: int = 32

    @staticmethod
    def from_json(path: str) -> "UNetConfig":
        with open(path) as f:
            d = json.load(f)
        fields = {f.name for f in dataclasses.fields(UNetConfig)}
        kw = {k: tuple(v) if isinstance(v, list) else v
              for k, v in d.items() if k in fields}
        return UNetConfig(**kw)

def _timestep_embedding(t, dim):
    """Sinusoidal timestep embedding (diffusers get_timestep_embedding:
    flip_sin_to_cos=True, downscale_freq_shift=0)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _resnet(p: _P, x, temb, groups):
    h = _group_norm(x, p("norm1.weight"), p("norm1.bias"), groups)
    h = _conv2d(jax.nn.silu(h), p("conv1.weight"), p("conv1.bias"))
    t = _linear(p, "time_emb_proj", jax.nn.silu(temb))
    h = h + t[:, :, None, None]
    h = _group_norm(h, p("norm2.weight"), p("norm2.bias"), groups)
    h = _conv2d(jax.nn.silu(h), p("conv2.weight"), p("conv2.bias"))
    if p.has("conv_shortcut.weight"):
        x = _conv2d(x, p("conv_shortcut.weight"), p("conv_shortcut.bias"),
                    padding=0)
    return x + h


def _attn_block(p: _P, x, ctx, heads, groups=32):
    """Transformer2DModel: proj_in -> basic transformer block -> proj_out."""
    B, C, H, W = x.shape
    res = x
    h = _group_norm(x, p("norm.weight"), p("norm.bias"), groups)
    if p("proj_in.weight").ndim == 4:
        h = _conv2d(h, p("proj_in.weight"), p("proj_in.bias"), padding=0)
        h = h.reshape(B, C, H * W).transpose(0, 2, 1)
    else:
        h = h.reshape(B, C, H * W).transpose(0, 2, 1)
        h = h @ p("proj_in.weight").T + p("proj_in.bias")
    tb = p.sub("transformer_blocks.0.")

    def mha(ap: _P, q_in, kv_in):
        hd = q_in.shape[-1] // heads
        q = (q_in @ ap("to_q.weight").T).reshape(B, -1, heads, hd)
        k = (kv_in @ ap("to_k.weight").T).reshape(B, -1, heads, hd)
        v = (kv_in @ ap("to_v.weight").T).reshape(B, -1, heads, hd)
        w = jnp.einsum("bthd,bshd->bhts", q, k) / math.sqrt(hd)
        w = jax.nn.softmax(w, axis=-1)
        o = jnp.einsum("bhts,bshd->bthd", w, v).reshape(B, -1, heads * hd)
        return o @ ap("to_out.0.weight").T + ap("to_out.0.bias")

    h = h + mha(tb.sub("attn1."), _ln(h, tb("norm1.weight"), tb("norm1.bias")),
                _ln(h, tb("norm1.weight"), tb("norm1.bias")))
    n2 = _ln(h, tb("norm2.weight"), tb("norm2.bias"))
    h = h + mha(tb.sub("attn2."), n2, ctx)
    n3 = _ln(h, tb("norm3.weight"), tb("norm3.bias"))
    ff = n3 @ tb("ff.net.0.proj.weight").T + tb("ff.net.0.proj.bias")
    a, gate = jnp.split(ff, 2, axis=-1)
    ff = a * jax.nn.gelu(gate, approximate=False)
    h = h + (ff @ tb("ff.net.2.weight").T + tb("ff.net.2.bias"))
    if p("proj_out.weight").ndim == 4:
        h = h.transpose(0, 2, 1).reshape(B, C, H, W)
        h = _conv2d(h, p("proj_out.weight"), p("proj_out.bias"), padding=0)
    else:
        h = h @ p("proj_out.weight").T + p("proj_out.bias")
        h = h.transpose(0, 2, 1).reshape(B, C, H, W)
    return h + res


def unet_forward(params: dict, cfg: UNetConfig, latents, t, ctx,
                 ctrl_down=None, ctrl_mid=None):
    """latents [B, 4, h, w]; t [B]; ctx [B, T, cross_dim] -> noise pred.

    ctrl_down/ctrl_mid: ControlNet residuals (one per skip sample + one
    mid), added exactly where diffusers UNet2DConditionModel adds its
    down_block_additional_residuals / mid_block_additional_residual."""
    p = _P(params)
    g = cfg.norm_num_groups
    ch0 = cfg.block_out_channels[0]
    temb = _timestep_embedding(t, ch0)
    temb = _linear(p, "time_embedding.linear_1", temb)
    temb = _linear(p, "time_embedding.linear_2", jax.nn.silu(temb))

    def heads(bi):
        ahd = cfg.attention_head_dim
        return ahd[bi] if isinstance(ahd, (tuple, list)) else ahd

    x = _conv2d(latents, p("conv_in.weight"), p("conv_in.bias"))
    skips = [x]
    for bi, btype in enumerate(cfg.down_block_types):
        bp = p.sub(f"down_blocks.{bi}.")
        for li in range(cfg.layers_per_block):
            x = _resnet(bp.sub(f"resnets.{li}."), x, temb, g)
            if btype.startswith("CrossAttn"):
                x = _attn_block(bp.sub(f"attentions.{li}."), x, ctx, heads(bi), g)
            skips.append(x)
        if bp.has("downsamplers.0.conv.weight"):
            x = _conv2d(x, bp("downsamplers.0.conv.weight"),
                        bp("downsamplers.0.conv.bias"), stride=2)
            skips.append(x)

    if ctrl_down is not None:
        assert len(ctrl_down) == len(skips), (len(ctrl_down), len(skips))
        skips = [s + r for s, r in zip(skips, ctrl_down)]

    mp = p.sub("mid_block.")
    x = _resnet(mp.sub("resnets.0."), x, temb, g)
    x = _attn_block(mp.sub("attentions.0."), x, ctx,
                    heads(len(cfg.block_out_channels) - 1), g)
    x = _resnet(mp.sub("resnets.1."), x, temb, g)
    if ctrl_mid is not None:
        x = x + ctrl_mid

    for bi, btype in enumerate(cfg.up_block_types):
        bp = p.sub(f"up_blocks.{bi}.")
        src_bi = len(cfg.block_out_channels) - 1 - bi
        for li in range(cfg.layers_per_block + 1):
            x = jnp.concatenate([x, skips.pop()], axis=1)
            x = _resnet(bp.sub(f"resnets.{li}."), x, temb, g)
            if btype.startswith("CrossAttn"):
                x = _attn_block(bp.sub(f"attentions.{li}."), x, ctx,
                                heads(src_bi), g)
        if bp.has("upsamplers.0.conv.weight"):
            B, C, H, W = x.shape
            x = jax.image.resize(x, (B, C, H * 2, W * 2), "nearest")
            x = _conv2d(x, bp("upsamplers.0.conv.weight"),
                        bp("upsamplers.0.conv.bias"))

    x = _group_norm(x, p("conv_norm_out.weight"), p("conv_norm_out.bias"), g)
    return _conv2d(jax.nn.silu(x), p("conv_out.weight"), p("conv_out.bias"))


# ---------------- ControlNet (diffusers ControlNetModel) ----------------

@dataclasses.dataclass(frozen=True)
class ControlNetConfig:
    in_channels: int = 4
    block_out_channels: tuple = (320, 640, 1280, 1280)
    down_block_types: tuple = ("CrossAttnDownBlock2D",) * 3 + ("DownBlock2D",)
    layers_per_block: int = 2
    attention_head_dim: Any = 8
    cross_attention_dim: int = 768
    norm_num_groups: int = 32
    conditioning_embedding_out_channels: tuple = (16, 32, 96, 256)

    @staticmethod
    def from_json(path: str) -> "ControlNetConfig":
        with open(path) as f:
            d = json.load(f)
        fields = {f.name for f in dataclasses.fields(ControlNetConfig)}
        kw = {k: tuple(v) if isinstance(v, list) else v
              for k, v in d.items() if k in fields}
        return ControlNetConfig(**kw)


def controlnet_forward(params: dict, cfg: ControlNetConfig, latents, t, ctx,
                       cond):
    """ControlNet conditioning pass (reference semantics:
    /root/reference/backend/python/diffusers/backend.py:297-314 attaches a
    diffusers ControlNetModel; this is that model's forward). Structure =
    the UNet's down+mid stack with a conditioning-image embedding added
    after conv_in and zero-conv projections on every skip.

    latents [B, 4, h, w]; cond [B, 3, H, W] full-resolution control image
    in [0, 1] (canny/pose/etc). Returns (down_res list, mid_res)."""
    p = _P(params)
    g = cfg.norm_num_groups
    ch0 = cfg.block_out_channels[0]
    temb = _timestep_embedding(t, ch0)
    temb = _linear(p, "time_embedding.linear_1", temb)
    temb = _linear(p, "time_embedding.linear_2", jax.nn.silu(temb))

    def heads(bi):
        ahd = cfg.attention_head_dim
        return ahd[bi] if isinstance(ahd, (tuple, list)) else ahd

    x = _conv2d(latents, p("conv_in.weight"), p("conv_in.bias"))
    # conditioning embedding: conv_in -> (s1, s2) conv pairs -> conv_out;
    # downsamples the full-res control image to latent resolution
    ce = p.sub("controlnet_cond_embedding.")
    c = jax.nn.silu(_conv2d(cond, ce("conv_in.weight"), ce("conv_in.bias")))
    i = 0
    while ce.has(f"blocks.{i}.weight"):
        c = jax.nn.silu(_conv2d(c, ce(f"blocks.{i}.weight"),
                                ce(f"blocks.{i}.bias"),
                                stride=2 if i % 2 else 1))
        i += 1
    c = _conv2d(c, ce("conv_out.weight"), ce("conv_out.bias"))
    x = x + c

    skips = [x]
    for bi, btype in enumerate(cfg.down_block_types):
        bp = p.sub(f"down_blocks.{bi}.")
        for li in range(cfg.layers_per_block):
            x = _resnet(bp.sub(f"resnets.{li}."), x, temb, g)
            if btype.startswith("CrossAttn"):
                x = _attn_block(bp.sub(f"attentions.{li}."), x, ctx,
                                heads(bi), g)
            skips.append(x)
        if bp.has("downsamplers.0.conv.weight"):
            x = _conv2d(x, bp("downsamplers.0.conv.weight"),
                        bp("downsamplers.0.conv.bias"), stride=2)
            skips.append(x)

    mp = p.sub("mid_block.")
    x = _resnet(mp.sub("resnets.0."), x, temb, g)
    x = _attn_block(mp.sub("attentions.0."), x, ctx,
                    heads(len(cfg.block_out_channels) - 1), g)
    x = _resnet(mp.sub("resnets.1."), x, temb, g)

    down_res = [
        _conv2d(s, p(f"controlnet_down_blocks.{i}.weight"),
                p(f"controlnet_down_blocks.{i}.bias"), padding=0)
        for i, s in enumerate(skips)
    ]
    mid_res = _conv2d(x, p("controlnet_mid_block.weight"),
                      p("controlnet_mid_block.bias"), padding=0)
    return down_res, mid_res


# ---------------- diffusion LoRA (safetensors add-on checkpoints) --------

_KOHYA_FIXUPS = (
    ("down.blocks", "down_blocks"), ("up.blocks", "up_blocks"),
    ("mid.block", "mid_block"), ("transformer.blocks", "transformer_blocks"),
    ("to.q", "to_q"), ("to.k", "to_k"), ("to.v", "to_v"),
    ("to.out", "to_out"), ("proj.in", "proj_in"), ("proj.out", "proj_out"),
    ("conv.in", "conv_in"), ("conv.out", "conv_out"),
    ("conv.shortcut", "conv_shortcut"), ("time.emb.proj", "time_emb_proj"),
    ("ff.net", "ff.net"), ("text.model", "text_model"),
    ("self.attn", "self_attn"), ("q.proj", "q_proj"), ("k.proj", "k_proj"),
    ("v.proj", "v_proj"), ("out.proj", "out_proj"), ("fc.1", "fc1"),
    ("fc.2", "fc2"), ("layer.norm", "layer_norm"),
)


def _kohya_to_module(key: str) -> str:
    """'lora_unet_down_blocks_0_attentions_0_...to_q' (underscore soup) ->
    dotted diffusers module path. The fixup table restores the module
    names that legitimately contain underscores — the same trick
    diffusers' kohya converter uses."""
    name = key.replace("_", ".")
    for a, b in _KOHYA_FIXUPS:
        name = name.replace(a, b)
    return name


def load_sd_lora(path: str):
    """Read a diffusion LoRA safetensors file into
    {(target, module_path): (down [r, in], up [out, r], alpha)} with
    target in {"unet", "text_encoder"}. Supports the two ecosystem
    layouts: kohya ('lora_unet_*.lora_down/up.weight' + '.alpha') and
    peft/diffusers ('unet.*.lora_A/B.weight')."""
    from safetensors import safe_open

    raw = {}
    with safe_open(path, framework="np") as f:
        for k in f.keys():
            raw[k] = np.asarray(f.get_tensor(k), np.float32)

    pairs: dict = {}

    def slot(target, module):
        return pairs.setdefault((target, module), {})

    for k, v in raw.items():
        if k.startswith("lora_unet_") or k.startswith("lora_te_"):
            target = "unet" if k.startswith("lora_unet_") else "text_encoder"
            base = k[len("lora_unet_"):] if target == "unet" \
                else k[len("lora_te_"):]
            if base.endswith(".lora_down.weight"):
                slot(target, _kohya_to_module(
                    base[: -len(".lora_down.weight")]))["down"] = v
            elif base.endswith(".lora_up.weight"):
                slot(target, _kohya_to_module(
                    base[: -len(".lora_up.weight")]))["up"] = v
            elif base.endswith(".alpha"):
                slot(target, _kohya_to_module(
                    base[: -len(".alpha")]))["alpha"] = float(v)
        elif k.startswith(("unet.", "text_encoder.")):
            target, rest = k.split(".", 1)
            for tag, role in ((".lora_A.weight", "down"),
                              (".lora_B.weight", "up"),
                              (".lora.down.weight", "down"),
                              (".lora.up.weight", "up")):
                if rest.endswith(tag):
                    slot(target, rest[: -len(tag)])[role] = v
                    break
    out = {}
    for (target, module), d in pairs.items():
        if "down" in d and "up" in d:
            out[(target, module)] = (d["down"], d["up"], d.get("alpha"))
    if not out:
        raise ValueError(f"no LoRA weight pairs recognized in {path}")
    return out


def apply_sd_lora(unet: dict, clip: dict, path: str, scale: float = 1.0):
    """Fuse a LoRA into the unet/text-encoder weight dicts at load
    (W += scale * (alpha/r) * up @ down — the reference fuses at load
    too, /root/reference/backend/python/diffusers/backend.py:297-314).
    Mutates the dicts in place; returns (n_fused, n_skipped)."""
    pairs = load_sd_lora(path)
    fused = skipped = 0
    for (target, module), (down, up, alpha) in pairs.items():
        params = unet if target == "unet" else clip
        key = module + ".weight"
        if key not in params:
            skipped += 1
            continue
        w = np.asarray(params[key], np.float32)
        r = down.shape[0]
        eff = scale * ((alpha / r) if alpha else 1.0)
        d2, u2 = down.reshape(r, -1), up.reshape(up.shape[0], -1)
        delta = (u2 @ d2).reshape(w.shape) * eff
        params[key] = jnp.asarray(w + delta, jnp.float32)
        fused += 1
    if not fused:
        raise ValueError(f"LoRA {path}: no target module matched the "
                         f"loaded pipeline (skipped {skipped})")
    return fused, skipped


# ---------------- AutoencoderKL ----------------

@dataclasses.dataclass(frozen=True)
class VaeConfig:
    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 4
    block_out_channels: tuple = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_num_groups: int = 32
    scaling_factor: float = 0.18215

    @staticmethod
    def from_json(path: str) -> "VaeConfig":
        with open(path) as f:
            d = json.load(f)
        fields = {f.name for f in dataclasses.fields(VaeConfig)}
        kw = {k: tuple(v) if isinstance(v, list) else v
              for k, v in d.items() if k in fields}
        return VaeConfig(**kw)


def _vae_resnet(p: _P, x, groups):
    h = _group_norm(x, p("norm1.weight"), p("norm1.bias"), groups)
    h = _conv2d(jax.nn.silu(h), p("conv1.weight"), p("conv1.bias"))
    h = _group_norm(h, p("norm2.weight"), p("norm2.bias"), groups)
    h = _conv2d(jax.nn.silu(h), p("conv2.weight"), p("conv2.bias"))
    if p.has("conv_shortcut.weight"):
        x = _conv2d(x, p("conv_shortcut.weight"), p("conv_shortcut.bias"),
                    padding=0)
    return x + h


def _vae_attn(p: _P, x, groups):
    B, C, H, W = x.shape
    h = _group_norm(x, p("group_norm.weight"), p("group_norm.bias"), groups)
    flat = h.reshape(B, C, H * W).transpose(0, 2, 1)
    q = _linear(p, "to_q", flat)
    k = _linear(p, "to_k", flat)
    v = _linear(p, "to_v", flat)
    w = jax.nn.softmax(q @ k.transpose(0, 2, 1) / math.sqrt(C), axis=-1)
    o = _linear(p, "to_out.0", w @ v)
    return x + o.transpose(0, 2, 1).reshape(B, C, H, W)


def vae_decode(params: dict, cfg: VaeConfig, latents):
    """latents [B, 4, h, w] (already divided by scaling_factor) -> image
    [B, 3, 8h, 8w] in [-1, 1]."""
    g = cfg.norm_num_groups
    p = _P(params)
    z = _conv2d(latents, p("post_quant_conv.weight"),
                p("post_quant_conv.bias"), padding=0)
    d = p.sub("decoder.")
    x = _conv2d(z, d("conv_in.weight"), d("conv_in.bias"))
    mp = d.sub("mid_block.")
    x = _vae_resnet(mp.sub("resnets.0."), x, g)
    x = _vae_attn(mp.sub("attentions.0."), x, g)
    x = _vae_resnet(mp.sub("resnets.1."), x, g)
    n_blocks = len(cfg.block_out_channels)
    for bi in range(n_blocks):
        bp = d.sub(f"up_blocks.{bi}.")
        for li in range(cfg.layers_per_block + 1):
            x = _vae_resnet(bp.sub(f"resnets.{li}."), x, g)
        if bp.has("upsamplers.0.conv.weight"):
            B, C, H, W = x.shape
            x = jax.image.resize(x, (B, C, H * 2, W * 2), "nearest")
            x = _conv2d(x, bp("upsamplers.0.conv.weight"),
                        bp("upsamplers.0.conv.bias"))
    x = _group_norm(x, d("conv_norm_out.weight"), d("conv_norm_out.bias"), g)
    return _conv2d(jax.nn.silu(x), d("conv_out.weight"), d("conv_out.bias"))


def vae_encode(params: dict, cfg: VaeConfig, image, noise=None):
    """image [B, 3, H, W] in [-1,1] -> latent sample [B, 4, H/8, W/8]
    (mean when noise is None)."""
    g = cfg.norm_num_groups
    p = _P(params)
    e = p.sub("encoder.")
    x = _conv2d(image, e("conv_in.weight"), e("conv_in.bias"))
    n_blocks = len(cfg.block_out_channels)
    for bi in range(n_blocks):
        bp = e.sub(f"down_blocks.{bi}.")
        for li in range(cfg.layers_per_block):
            x = _vae_resnet(bp.sub(f"resnets.{li}."), x, g)
        if bp.has("downsamplers.0.conv.weight"):
            x = jnp.pad(x, ((0, 0), (0, 0), (0, 1), (0, 1)))
            x = jax.lax.conv_general_dilated(
                x, bp("downsamplers.0.conv.weight"), (2, 2), [(0, 0), (0, 0)],
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
            x = x + bp("downsamplers.0.conv.bias")[None, :, None, None]
    mp = e.sub("mid_block.")
    x = _vae_resnet(mp.sub("resnets.0."), x, g)
    x = _vae_attn(mp.sub("attentions.0."), x, g)
    x = _vae_resnet(mp.sub("resnets.1."), x, g)
    x = _group_norm(x, e("conv_norm_out.weight"), e("conv_norm_out.bias"), g)
    x = _conv2d(jax.nn.silu(x), e("conv_out.weight"), e("conv_out.bias"))
    moments = _conv2d(x, p("quant_conv.weight"), p("quant_conv.bias"),
                      padding=0)
    mean, logvar = jnp.split(moments, 2, axis=1)
    if noise is None:
        return mean
    return mean + jnp.exp(0.5 * jnp.clip(logvar, -30, 20)) * noise


# ---------------- scheduler + pipeline ----------------

def ddim_timesteps_and_alphas(num_train=1000, steps=20, beta_start=0.00085,
                              beta_end=0.012):
    """SD's scaled-linear beta schedule + DDIM timestep subset."""
    steps = max(1, min(int(steps), num_train))
    betas = np.linspace(beta_start ** 0.5, beta_end ** 0.5, num_train) ** 2
    alphas_cum = np.cumprod(1.0 - betas)
    ts = (np.arange(0, steps) * (num_train // steps))[::-1].copy()
    return ts, alphas_cum


from localai_tpu.config.model_config import SCHEDULERS  # noqa: E402


def _sigmas_for(ts, alphas_cum) -> np.ndarray:
    """k-diffusion noise scale per selected timestep: sigma = sqrt((1-a)/a)
    (descending), terminated with sigma = 0."""
    sig = np.sqrt((1.0 - alphas_cum[ts]) / alphas_cum[ts])
    return np.concatenate([sig, [0.0]]).astype(np.float64)


def sample_latents(fwd, lat, ctx2, ts, alphas_cum, cfg_scale, rng,
                   scheduler="ddim", start_index=0):
    """Run the reverse process on latents with the chosen scheduler.

    ``fwd(lat2, t_vec, ctx2) -> eps2`` is the CFG-batched jitted UNet;
    ``lat`` enters at step ``start_index`` (img2img skips the early,
    high-noise steps), already noised appropriately by the caller.

    ddim runs in the variance-preserving (alpha) parameterization; the
    euler / euler-ancestral / DPM++ 2M samplers use the k-diffusion
    sigma-space convention (model input scaled by 1/sqrt(sigma^2+1)),
    matching what the reference's diffusers backend exposes as
    EulerDiscrete / EulerAncestral / DPMSolverMultistep
    (backend/python/diffusers/backend.py:169-357)."""
    if scheduler not in SCHEDULERS:
        raise ValueError(f"unknown scheduler {scheduler!r}; "
                         f"expected one of {SCHEDULERS}")

    # F > 1 = a video's frames denoising as ONE UNet batch (txt2vid /
    # img2vid); the CFG halves are [neg x F, pos x F]
    F = int(np.shape(lat)[0])

    def cfg_eps(lat_in, t):
        lat2 = jnp.concatenate([lat_in, lat_in], axis=0)
        ctxF = ctx2 if F == 1 else jnp.concatenate(
            [jnp.repeat(ctx2[0:1], F, 0), jnp.repeat(ctx2[1:2], F, 0)])
        eps2 = fwd(lat2, jnp.full((2 * F,), int(t), jnp.int32), ctxF)
        eps_u, eps_c = eps2[:F], eps2[F:]
        return eps_u + cfg_scale * (eps_c - eps_u)

    if scheduler == "ddim":
        for i in range(start_index, len(ts)):
            t = ts[i]
            t_prev = ts[i + 1] if i + 1 < len(ts) else -1
            a_t = float(alphas_cum[t])
            a_prev = float(alphas_cum[t_prev]) if t_prev >= 0 else 1.0
            eps = cfg_eps(lat, t)
            x0 = (lat - math.sqrt(1 - a_t) * eps) / math.sqrt(a_t)
            lat = math.sqrt(a_prev) * x0 + math.sqrt(1 - a_prev) * eps
        return lat

    # k-diffusion sigma space: x = lat_vp * sqrt(1 + sigma^2)
    sig = _sigmas_for(ts, alphas_cum)
    x = lat * math.sqrt(1.0 + float(sig[start_index]) ** 2)
    old_denoised = None
    old_h = None
    for i in range(start_index, len(ts)):
        s_i, s_n = float(sig[i]), float(sig[i + 1])
        eps = cfg_eps(x / math.sqrt(s_i ** 2 + 1.0), ts[i])
        denoised = x - s_i * eps
        if scheduler == "euler":
            x = x + eps * (s_n - s_i)
        elif scheduler == "euler_a":
            if s_n > 0:
                s_up = math.sqrt(s_n ** 2 * (s_i ** 2 - s_n ** 2) / s_i ** 2)
                s_down = math.sqrt(s_n ** 2 - s_up ** 2)
            else:
                s_up, s_down = 0.0, 0.0
            x = x + eps * (s_down - s_i)
            if s_up > 0:
                noise = jnp.asarray(rng.standard_normal(
                    np.shape(x)).astype(np.float32))
                x = x + noise * s_up
        else:  # dpmpp_2m (DPM-Solver++(2M), data prediction, 2nd order)
            t_i, t_n = -math.log(max(s_i, 1e-10)), \
                -math.log(max(s_n, 1e-10))
            h = t_n - t_i
            if old_denoised is None or s_n == 0:
                d = denoised
            else:
                r = old_h / h
                d = (1 + 1 / (2 * r)) * denoised - (1 / (2 * r)) * old_denoised
            if s_n == 0:
                x = denoised
            else:
                x = (s_n / s_i) * x - math.expm1(-h) * d
            old_denoised = denoised
            old_h = h
    return x   # sigma ended at 0 -> VP latents


def _slerp(a, b, t: float):
    """Spherical interpolation between two same-shape noise tensors —
    keeps the result on the gaussian shell (plain lerp of gaussians
    shrinks the norm and washes out the denoised frames)."""
    af, bf = np.ravel(a), np.ravel(b)
    omega = np.arccos(np.clip(
        np.dot(af, bf) / max(np.linalg.norm(af) * np.linalg.norm(bf), 1e-12),
        -1.0, 1.0))
    if omega < 1e-6:
        return a + t * (b - a)
    so = np.sin(omega)
    return (np.sin((1 - t) * omega) / so) * a + (np.sin(t * omega) / so) * b


class _VideoMixin:
    """txt2vid / img2vid on the SD stack: the reference serves video via
    diffusers pipelines (StableVideoDiffusionPipeline img2vid,
    VideoDiffusionPipeline txt2vid —
    /root/reference/backend/python/diffusers/backend.py:199-223,440-453).
    The TPU-native equivalent here is a LATENT-WALK video on the loaded
    image pipeline: every frame's initial latent is a spherical
    interpolation along a noise trajectory (img2vid anchors the walk on
    the encoded source image) and ALL frames denoise as one batched UNet
    program — temporal coherence comes from latent-space continuity, and
    the whole video costs one compiled sampling loop. The published 3D
    (spatio-temporal-attention) video checkpoints are not implemented;
    this trades their motion model for zero extra weights on the same
    MXU-batched UNet."""

    def _frame_latents(self, rng, num_frames, shape, motion: float):
        n0 = rng.standard_normal(shape).astype(np.float32)
        n1 = rng.standard_normal(shape).astype(np.float32)
        fr = [_slerp(n0, n1, motion * f / max(num_frames - 1, 1))
              for f in range(num_frames)]
        return jnp.asarray(np.stack(fr))

    def _decode_frames(self, lat) -> np.ndarray:
        # one frame per VAE pass: reuses the single-image compile and
        # caps peak memory at one frame's activations
        return np.stack([self._decode_image(lat[f:f + 1])
                         for f in range(lat.shape[0])])

    def txt2vid(self, prompt: str, negative_prompt: str = "",
                num_frames: int = 14, height: int = 512, width: int = 512,
                steps: int = 20, cfg_scale: float = 7.5, seed: int = 0,
                scheduler: str = "ddim",
                motion: float = 1.0) -> np.ndarray:
        """-> uint8 frames [F, H, W, 3]. ``motion`` scales how far the
        noise trajectory travels across the clip (0 = still image)."""
        ctx2 = self._ctx2(prompt, negative_prompt)
        rng = np.random.default_rng(int(seed) & 0x7FFFFFFF)
        vsf = self._vsf
        height = max(height - height % vsf, vsf)
        width = max(width - width % vsf, vsf)
        shape = (self.unet_cfg.in_channels, height // vsf, width // vsf)
        lat = self._frame_latents(rng, num_frames, shape, motion)
        ts, alphas = ddim_timesteps_and_alphas(steps=steps)
        lat = sample_latents(self._get_fwd(), lat, ctx2, ts, alphas,
                             cfg_scale, rng, scheduler=scheduler)
        return self._decode_frames(lat)

    def img2vid(self, init_image: np.ndarray, prompt: str = "",
                negative_prompt: str = "", num_frames: int = 14,
                strength: float = 0.5, steps: int = 20,
                cfg_scale: float = 7.5, seed: int = 0,
                scheduler: str = "ddim",
                motion: float = 1.0) -> np.ndarray:
        """Animate a source image: every frame starts from the encoded
        image latent noised to the ``strength`` point with a slerp-walked
        noise, so frame 0 stays closest to the source and the clip
        drifts smoothly (reference analogue: img2vid, backend.py:440-447
        — src image in, video out)."""
        strength = min(max(float(strength), 0.05), 1.0)
        ctx2 = self._ctx2(prompt, negative_prompt)
        rng = np.random.default_rng(int(seed) & 0x7FFFFFFF)
        vsf = self._vsf
        H = max(init_image.shape[0] - init_image.shape[0] % vsf, vsf)
        W = max(init_image.shape[1] - init_image.shape[1] % vsf, vsf)
        img = init_image[:H, :W].astype(np.float32) / 255.0 * 2.0 - 1.0
        img = jnp.asarray(img.transpose(2, 0, 1)[None])
        shape = (self.unet_cfg.in_channels, H // vsf, W // vsf)
        noise_enc = jnp.asarray(
            rng.standard_normal((1,) + shape).astype(np.float32))
        lat0 = vae_encode(self.vae, self.vae_cfg, img,
                          noise=noise_enc) * self.vae_cfg.scaling_factor

        ts, alphas = ddim_timesteps_and_alphas(steps=steps)
        start = min(int(round((1.0 - strength) * len(ts))), len(ts) - 1)
        a_start = float(alphas[ts[start]])
        walk = self._frame_latents(rng, num_frames, shape, motion)
        lat = math.sqrt(a_start) * jnp.broadcast_to(
            lat0, (num_frames,) + shape) + math.sqrt(1 - a_start) * walk
        lat = sample_latents(self._get_fwd(), lat, ctx2, ts, alphas,
                             cfg_scale, rng, scheduler=scheduler,
                             start_index=start)
        return self._decode_frames(lat)



@dataclasses.dataclass
class SDPipeline(_VideoMixin):
    """Loaded diffusers-layout pipeline (text encoder + unet + vae,
    optional controlnet subdir, optional fused LoRAs)."""
    clip_cfg: ClipTextConfig
    clip: dict
    unet_cfg: UNetConfig
    unet: dict
    vae_cfg: VaeConfig
    vae: dict
    tokenizer: Any = None
    ctrl_cfg: Any = None     # ControlNetConfig when a controlnet is loaded
    ctrl: Any = None
    _fwd: Any = None    # cached jitted UNet (weights passed as an argument)
    _fwd_ctrl: Any = None

    @staticmethod
    def load(pipe_dir: str, controlnet: str = "",
             lora_paths: tuple = (), lora_scale: float = 1.0) -> "SDPipeline":
        def flat(path):
            from safetensors import safe_open

            out = {}
            with safe_open(path, framework="np") as f:
                for name in f.keys():
                    out[name] = jnp.asarray(f.get_tensor(name), jnp.float32)
            return out

        te = os.path.join(pipe_dir, "text_encoder")
        un = os.path.join(pipe_dir, "unet")
        va = os.path.join(pipe_dir, "vae")
        tok = None
        try:
            from transformers import CLIPTokenizerFast

            tok = CLIPTokenizerFast.from_pretrained(
                os.path.join(pipe_dir, "tokenizer"))
        except Exception:
            pass
        # controlnet: explicit path, or the conventional pipe subdir
        cn = controlnet or os.path.join(pipe_dir, "controlnet")
        if not os.path.isabs(cn) and controlnet:
            cn = os.path.join(pipe_dir, cn)
        ctrl_cfg = ctrl = None
        if os.path.exists(os.path.join(cn, "config.json")):
            ctrl_cfg = ControlNetConfig.from_json(
                os.path.join(cn, "config.json"))
            ctrl = flat(os.path.join(cn, "diffusion_pytorch_model.safetensors"))
        pipe = SDPipeline(
            clip_cfg=ClipTextConfig.from_json(os.path.join(te, "config.json")),
            clip=flat(os.path.join(te, "model.safetensors")),
            unet_cfg=UNetConfig.from_json(os.path.join(un, "config.json")),
            unet=flat(os.path.join(un, "diffusion_pytorch_model.safetensors")),
            vae_cfg=VaeConfig.from_json(os.path.join(va, "config.json")),
            vae=flat(os.path.join(va, "diffusion_pytorch_model.safetensors")),
            tokenizer=tok,
            ctrl_cfg=ctrl_cfg, ctrl=ctrl,
        )
        for lp in lora_paths:
            if not os.path.isabs(lp):
                lp = os.path.join(pipe_dir, lp)
            apply_sd_lora(pipe.unet, pipe.clip, lp, lora_scale)
        return pipe

    def encode_prompt(self, prompt: str) -> jnp.ndarray:
        if self.tokenizer is not None:
            ids = self.tokenizer(prompt, padding="max_length", truncation=True,
                                 max_length=self.clip_cfg.max_position_embeddings,
                                 return_tensors="np")["input_ids"]
        else:
            # hash-chars fallback for tokenizer-less test checkpoints
            T = self.clip_cfg.max_position_embeddings
            ids = np.zeros((1, T), np.int64)
            for i, ch in enumerate(prompt[: T]):
                ids[0, i] = (ord(ch) * 7919) % self.clip_cfg.vocab_size
        return clip_text_encode(self.clip, self.clip_cfg, ids)

    def _get_fwd(self):
        if self._fwd is None:
            # weights enter as an ARGUMENT: a per-call closure would both
            # recompile every request and bake the weights in as constants
            cfg_ = self.unet_cfg
            self._fwd = jax.jit(
                lambda p_, l, t, c: unet_forward(p_, cfg_, l, t, c))
        return lambda l, t, c: self._fwd(self.unet, l, t, c)

    def _get_fwd_controlled(self, cond, ctrl_scale: float):
        """eps function with the ControlNet pass fused in: the cond image
        is fixed per request and CFG-duplicated to the latent batch."""
        if self._fwd_ctrl is None:
            ucfg, ccfg = self.unet_cfg, self.ctrl_cfg

            def f(up, cp, l, t, c, cond_, scale):
                dres, mres = controlnet_forward(cp, ccfg, l, t, c, cond_)
                dres = [d * scale for d in dres]
                return unet_forward(up, ucfg, l, t, c,
                                    ctrl_down=dres, ctrl_mid=mres * scale)

            self._fwd_ctrl = jax.jit(f)
        cond = jnp.asarray(cond, jnp.float32)
        scale = jnp.float32(ctrl_scale)
        return lambda l, t, c: self._fwd_ctrl(
            self.unet, self.ctrl, l, t, c,
            jnp.broadcast_to(cond, (l.shape[0],) + cond.shape[1:]), scale)

    def _ctx2(self, prompt: str, negative_prompt: str):
        ctx = self.encode_prompt(prompt)
        ctx_neg = self.encode_prompt(negative_prompt)
        return jnp.concatenate([ctx_neg, ctx], axis=0)

    @property
    def _vsf(self) -> int:
        # VAE spatial factor: 2 per downsampling block (SD-1.x: 4 -> 8x)
        return 2 ** (len(self.vae_cfg.block_out_channels) - 1)

    def _decode_image(self, lat) -> np.ndarray:
        img = vae_decode(self.vae, self.vae_cfg,
                         lat / self.vae_cfg.scaling_factor)
        img = np.asarray(jnp.clip((img + 1) / 2, 0, 1))[0]
        return (img.transpose(1, 2, 0) * 255).astype(np.uint8)

    def _control_fwd(self, control_image, controlnet_scale, height, width):
        """Pick the eps function: plain UNet, or UNet+ControlNet when a
        control image is given (loudly rejected without a controlnet)."""
        if control_image is None:
            return self._get_fwd()
        if self.ctrl is None:
            raise ValueError(
                "control image given but no controlnet is loaded (put a "
                "diffusers ControlNetModel under <pipe>/controlnet or set "
                "the controlnet option)")
        img01 = control_image.astype(np.float32) / 255.0
        cond = jax.image.resize(
            jnp.asarray(img01.transpose(2, 0, 1)[None]),
            (1, 3, height, width), "bilinear")
        return self._get_fwd_controlled(cond, controlnet_scale)

    def txt2img(self, prompt: str, negative_prompt: str = "",
                height: int = 512, width: int = 512, steps: int = 20,
                cfg_scale: float = 7.5, seed: int = 0,
                scheduler: str = "ddim", control_image: np.ndarray = None,
                controlnet_scale: float = 1.0) -> np.ndarray:
        """-> uint8 image [H, W, 3] (dims rounded DOWN to the VAE's
        spatial factor). CFG + selectable scheduler, SD semantics;
        optional ControlNet conditioning on ``control_image``."""
        ctx2 = self._ctx2(prompt, negative_prompt)
        # proto seed is signed int32; negative means "pick for me"
        rng = np.random.default_rng(int(seed) & 0x7FFFFFFF)
        vsf = self._vsf
        height = max(height - height % vsf, vsf)
        width = max(width - width % vsf, vsf)
        lat = jnp.asarray(rng.standard_normal(
            (1, self.unet_cfg.in_channels, height // vsf, width // vsf)
        ).astype(np.float32))
        ts, alphas = ddim_timesteps_and_alphas(steps=steps)
        fwd = self._control_fwd(control_image, controlnet_scale,
                                height, width)
        lat = sample_latents(fwd, lat, ctx2, ts, alphas,
                             cfg_scale, rng, scheduler=scheduler)
        return self._decode_image(lat)

    def img2img(self, prompt: str, init_image: np.ndarray,
                negative_prompt: str = "", strength: float = 0.75,
                steps: int = 20, cfg_scale: float = 7.5, seed: int = 0,
                scheduler: str = "ddim", control_image: np.ndarray = None,
                controlnet_scale: float = 1.0) -> np.ndarray:
        """init_image uint8 [H, W, 3] -> uint8 image (same VAE-rounded
        dims). Diffusers img2img semantics (reference:
        backend/python/diffusers/backend.py:399-424): the init image is
        VAE-encoded, noised to the schedule point selected by
        ``strength`` (1.0 = ignore the init image, ~0 = keep it), and
        denoised from there."""
        strength = min(max(float(strength), 0.0), 1.0)
        ctx2 = self._ctx2(prompt, negative_prompt)
        rng = np.random.default_rng(int(seed) & 0x7FFFFFFF)
        vsf = self._vsf
        H = max(init_image.shape[0] - init_image.shape[0] % vsf, vsf)
        W = max(init_image.shape[1] - init_image.shape[1] % vsf, vsf)
        img = init_image[:H, :W].astype(np.float32) / 255.0 * 2.0 - 1.0
        img = jnp.asarray(img.transpose(2, 0, 1)[None])
        noise_enc = jnp.asarray(rng.standard_normal(
            (1, self.unet_cfg.in_channels, H // vsf, W // vsf)
        ).astype(np.float32))
        lat0 = vae_encode(self.vae, self.vae_cfg, img,
                          noise=noise_enc) * self.vae_cfg.scaling_factor

        ts, alphas = ddim_timesteps_and_alphas(steps=steps)
        # skip the first (1-strength) of the schedule; start from the
        # init latent noised to that point
        start = min(int(round((1.0 - strength) * len(ts))), len(ts) - 1)
        if strength <= 0.0:
            return self._decode_image(lat0)
        noise = jnp.asarray(rng.standard_normal(
            np.shape(lat0)).astype(np.float32))
        a_start = float(alphas[ts[start]])
        lat = math.sqrt(a_start) * lat0 + math.sqrt(1 - a_start) * noise
        fwd = self._control_fwd(control_image, controlnet_scale, H, W)
        lat = sample_latents(fwd, lat, ctx2, ts, alphas,
                             cfg_scale, rng, scheduler=scheduler,
                             start_index=start)
        return self._decode_image(lat)


def write_video(path: str, frames: np.ndarray, fps: int = 7):
    """frames [F, H, W, 3] uint8 -> file. .mp4/.avi through OpenCV's
    VideoWriter (no ffmpeg binary needed); .gif/.webp/.apng animated
    through PIL. The reference exports mp4 via diffusers export_to_video
    (backend.py:447,453)."""
    ext = os.path.splitext(path)[1].lower()
    if ext in (".gif", ".webp", ".apng", ".png"):
        from PIL import Image

        imgs = [Image.fromarray(f) for f in frames]
        imgs[0].save(path, save_all=True, append_images=imgs[1:],
                     duration=int(1000 / max(fps, 1)), loop=0)
        return
    import cv2

    fourcc = cv2.VideoWriter_fourcc(*("mp4v" if ext == ".mp4" else "MJPG"))
    h, w = frames.shape[1:3]
    vw = cv2.VideoWriter(path, fourcc, float(fps), (w, h))
    if not vw.isOpened():
        raise RuntimeError(f"cannot open video writer for {path}")
    try:
        for f in frames:
            vw.write(cv2.cvtColor(f, cv2.COLOR_RGB2BGR))
    finally:
        vw.release()


# ---------------- tiny-checkpoint generators (tests/export) ----------------

def _rand(rng, *shape):
    return jnp.asarray(rng.standard_normal(shape).astype(np.float32) * 0.05)


def init_clip_params(cfg: ClipTextConfig, seed=0) -> dict:
    rng = np.random.default_rng(seed)
    D, F = cfg.hidden_size, cfg.intermediate_size
    p = {
        "text_model.embeddings.token_embedding.weight": _rand(rng, cfg.vocab_size, D),
        "text_model.embeddings.position_embedding.weight": _rand(
            rng, cfg.max_position_embeddings, D),
        "text_model.final_layer_norm.weight": jnp.ones((D,)),
        "text_model.final_layer_norm.bias": jnp.zeros((D,)),
    }
    for i in range(cfg.num_hidden_layers):
        lp = f"text_model.encoder.layers.{i}."
        for n in ("q_proj", "k_proj", "v_proj", "out_proj"):
            p[lp + f"self_attn.{n}.weight"] = _rand(rng, D, D)
            p[lp + f"self_attn.{n}.bias"] = jnp.zeros((D,))
        p[lp + "mlp.fc1.weight"] = _rand(rng, F, D)
        p[lp + "mlp.fc1.bias"] = jnp.zeros((F,))
        p[lp + "mlp.fc2.weight"] = _rand(rng, D, F)
        p[lp + "mlp.fc2.bias"] = jnp.zeros((D,))
        for n in ("layer_norm1", "layer_norm2"):
            p[lp + n + ".weight"] = jnp.ones((D,))
            p[lp + n + ".bias"] = jnp.zeros((D,))
    return p


def _init_resnet(p, rng, prefix, cin, cout, temb_dim):
    p[prefix + "norm1.weight"] = jnp.ones((cin,))
    p[prefix + "norm1.bias"] = jnp.zeros((cin,))
    p[prefix + "conv1.weight"] = _rand(rng, cout, cin, 3, 3)
    p[prefix + "conv1.bias"] = jnp.zeros((cout,))
    p[prefix + "time_emb_proj.weight"] = _rand(rng, cout, temb_dim)
    p[prefix + "time_emb_proj.bias"] = jnp.zeros((cout,))
    p[prefix + "norm2.weight"] = jnp.ones((cout,))
    p[prefix + "norm2.bias"] = jnp.zeros((cout,))
    p[prefix + "conv2.weight"] = _rand(rng, cout, cout, 3, 3)
    p[prefix + "conv2.bias"] = jnp.zeros((cout,))
    if cin != cout:
        p[prefix + "conv_shortcut.weight"] = _rand(rng, cout, cin, 1, 1)
        p[prefix + "conv_shortcut.bias"] = jnp.zeros((cout,))


def _init_attn(p, rng, prefix, c, cross):
    p[prefix + "norm.weight"] = jnp.ones((c,))
    p[prefix + "norm.bias"] = jnp.zeros((c,))
    p[prefix + "proj_in.weight"] = _rand(rng, c, c)
    p[prefix + "proj_in.bias"] = jnp.zeros((c,))
    tb = prefix + "transformer_blocks.0."
    for n in ("norm1", "norm2", "norm3"):
        p[tb + n + ".weight"] = jnp.ones((c,))
        p[tb + n + ".bias"] = jnp.zeros((c,))
    for ap, kvdim in (("attn1.", c), ("attn2.", cross)):
        p[tb + ap + "to_q.weight"] = _rand(rng, c, c)
        p[tb + ap + "to_k.weight"] = _rand(rng, c, kvdim)
        p[tb + ap + "to_v.weight"] = _rand(rng, c, kvdim)
        p[tb + ap + "to_out.0.weight"] = _rand(rng, c, c)
        p[tb + ap + "to_out.0.bias"] = jnp.zeros((c,))
    p[tb + "ff.net.0.proj.weight"] = _rand(rng, 8 * c, c)
    p[tb + "ff.net.0.proj.bias"] = jnp.zeros((8 * c,))
    p[tb + "ff.net.2.weight"] = _rand(rng, c, 4 * c)
    p[tb + "ff.net.2.bias"] = jnp.zeros((c,))
    p[prefix + "proj_out.weight"] = _rand(rng, c, c)
    p[prefix + "proj_out.bias"] = jnp.zeros((c,))


def init_unet_params(cfg: UNetConfig, seed=0) -> dict:
    """diffusers-named random UNet (mirrors unet_forward's structure)."""
    rng = np.random.default_rng(seed)
    p: dict = {}
    ch = cfg.block_out_channels
    temb = 4 * ch[0]
    p["conv_in.weight"] = _rand(rng, ch[0], cfg.in_channels, 3, 3)
    p["conv_in.bias"] = jnp.zeros((ch[0],))
    p["time_embedding.linear_1.weight"] = _rand(rng, temb, ch[0])
    p["time_embedding.linear_1.bias"] = jnp.zeros((temb,))
    p["time_embedding.linear_2.weight"] = _rand(rng, temb, temb)
    p["time_embedding.linear_2.bias"] = jnp.zeros((temb,))

    skips = [ch[0]]
    cur = ch[0]
    for bi, btype in enumerate(cfg.down_block_types):
        bp = f"down_blocks.{bi}."
        for li in range(cfg.layers_per_block):
            _init_resnet(p, rng, bp + f"resnets.{li}.", cur, ch[bi], temb)
            cur = ch[bi]
            if btype.startswith("CrossAttn"):
                _init_attn(p, rng, bp + f"attentions.{li}.", cur,
                           cfg.cross_attention_dim)
            skips.append(cur)
        if bi < len(ch) - 1:
            p[bp + "downsamplers.0.conv.weight"] = _rand(rng, cur, cur, 3, 3)
            p[bp + "downsamplers.0.conv.bias"] = jnp.zeros((cur,))
            skips.append(cur)

    _init_resnet(p, rng, "mid_block.resnets.0.", cur, cur, temb)
    _init_attn(p, rng, "mid_block.attentions.0.", cur, cfg.cross_attention_dim)
    _init_resnet(p, rng, "mid_block.resnets.1.", cur, cur, temb)

    for bi, btype in enumerate(cfg.up_block_types):
        bp = f"up_blocks.{bi}."
        out_c = ch[len(ch) - 1 - bi]
        for li in range(cfg.layers_per_block + 1):
            skip_c = skips.pop()
            _init_resnet(p, rng, bp + f"resnets.{li}.", cur + skip_c, out_c, temb)
            cur = out_c
            if btype.startswith("CrossAttn"):
                _init_attn(p, rng, bp + f"attentions.{li}.", cur,
                           cfg.cross_attention_dim)
        if bi < len(ch) - 1:
            p[bp + "upsamplers.0.conv.weight"] = _rand(rng, cur, cur, 3, 3)
            p[bp + "upsamplers.0.conv.bias"] = jnp.zeros((cur,))

    p["conv_norm_out.weight"] = jnp.ones((cur,))
    p["conv_norm_out.bias"] = jnp.zeros((cur,))
    p["conv_out.weight"] = _rand(rng, cfg.out_channels, cur, 3, 3)
    p["conv_out.bias"] = jnp.zeros((cfg.out_channels,))
    return p


def init_controlnet_params(cfg: ControlNetConfig, seed=0) -> dict:
    """diffusers-named random ControlNet (mirrors controlnet_forward).
    The zero-convs are RANDOM here (a real checkpoint trains them away
    from zero; zeros would make conditioning a no-op in tests)."""
    rng = np.random.default_rng(seed)
    p: dict = {}
    ch = cfg.block_out_channels
    temb = 4 * ch[0]
    p["conv_in.weight"] = _rand(rng, ch[0], cfg.in_channels, 3, 3)
    p["conv_in.bias"] = jnp.zeros((ch[0],))
    p["time_embedding.linear_1.weight"] = _rand(rng, temb, ch[0])
    p["time_embedding.linear_1.bias"] = jnp.zeros((temb,))
    p["time_embedding.linear_2.weight"] = _rand(rng, temb, temb)
    p["time_embedding.linear_2.bias"] = jnp.zeros((temb,))

    ce = cfg.conditioning_embedding_out_channels
    pre = "controlnet_cond_embedding."
    p[pre + "conv_in.weight"] = _rand(rng, ce[0], 3, 3, 3)
    p[pre + "conv_in.bias"] = jnp.zeros((ce[0],))
    for i in range(len(ce) - 1):
        p[pre + f"blocks.{2 * i}.weight"] = _rand(rng, ce[i], ce[i], 3, 3)
        p[pre + f"blocks.{2 * i}.bias"] = jnp.zeros((ce[i],))
        p[pre + f"blocks.{2 * i + 1}.weight"] = _rand(rng, ce[i + 1], ce[i], 3, 3)
        p[pre + f"blocks.{2 * i + 1}.bias"] = jnp.zeros((ce[i + 1],))
    p[pre + "conv_out.weight"] = _rand(rng, ch[0], ce[-1], 3, 3)
    p[pre + "conv_out.bias"] = jnp.zeros((ch[0],))

    skips = [ch[0]]
    cur = ch[0]
    for bi, btype in enumerate(cfg.down_block_types):
        bp = f"down_blocks.{bi}."
        for li in range(cfg.layers_per_block):
            _init_resnet(p, rng, bp + f"resnets.{li}.", cur, ch[bi], temb)
            cur = ch[bi]
            if btype.startswith("CrossAttn"):
                _init_attn(p, rng, bp + f"attentions.{li}.", cur,
                           cfg.cross_attention_dim)
            skips.append(cur)
        if bi < len(ch) - 1:
            p[bp + "downsamplers.0.conv.weight"] = _rand(rng, cur, cur, 3, 3)
            p[bp + "downsamplers.0.conv.bias"] = jnp.zeros((cur,))
            skips.append(cur)

    _init_resnet(p, rng, "mid_block.resnets.0.", cur, cur, temb)
    _init_attn(p, rng, "mid_block.attentions.0.", cur, cfg.cross_attention_dim)
    _init_resnet(p, rng, "mid_block.resnets.1.", cur, cur, temb)

    for i, c in enumerate(skips):
        p[f"controlnet_down_blocks.{i}.weight"] = _rand(rng, c, c, 1, 1)
        p[f"controlnet_down_blocks.{i}.bias"] = jnp.zeros((c,))
    p["controlnet_mid_block.weight"] = _rand(rng, cur, cur, 1, 1)
    p["controlnet_mid_block.bias"] = jnp.zeros((cur,))
    return p


def init_vae_params(cfg: VaeConfig, seed=0) -> dict:
    rng = np.random.default_rng(seed)
    p: dict = {}
    ch = cfg.block_out_channels
    lc = cfg.latent_channels

    def res(prefix, cin, cout):
        p[prefix + "norm1.weight"] = jnp.ones((cin,))
        p[prefix + "norm1.bias"] = jnp.zeros((cin,))
        p[prefix + "conv1.weight"] = _rand(rng, cout, cin, 3, 3)
        p[prefix + "conv1.bias"] = jnp.zeros((cout,))
        p[prefix + "norm2.weight"] = jnp.ones((cout,))
        p[prefix + "norm2.bias"] = jnp.zeros((cout,))
        p[prefix + "conv2.weight"] = _rand(rng, cout, cout, 3, 3)
        p[prefix + "conv2.bias"] = jnp.zeros((cout,))
        if cin != cout:
            p[prefix + "conv_shortcut.weight"] = _rand(rng, cout, cin, 1, 1)
            p[prefix + "conv_shortcut.bias"] = jnp.zeros((cout,))

    def attn(prefix, c):
        p[prefix + "group_norm.weight"] = jnp.ones((c,))
        p[prefix + "group_norm.bias"] = jnp.zeros((c,))
        for n in ("to_q", "to_k", "to_v", "to_out.0"):
            p[prefix + n + ".weight"] = _rand(rng, c, c)
            p[prefix + n + ".bias"] = jnp.zeros((c,))

    # encoder
    p["encoder.conv_in.weight"] = _rand(rng, ch[0], cfg.in_channels, 3, 3)
    p["encoder.conv_in.bias"] = jnp.zeros((ch[0],))
    cur = ch[0]
    for bi in range(len(ch)):
        bp = f"encoder.down_blocks.{bi}."
        for li in range(cfg.layers_per_block):
            res(bp + f"resnets.{li}.", cur, ch[bi])
            cur = ch[bi]
        if bi < len(ch) - 1:
            p[bp + "downsamplers.0.conv.weight"] = _rand(rng, cur, cur, 3, 3)
            p[bp + "downsamplers.0.conv.bias"] = jnp.zeros((cur,))
    res("encoder.mid_block.resnets.0.", cur, cur)
    attn("encoder.mid_block.attentions.0.", cur)
    res("encoder.mid_block.resnets.1.", cur, cur)
    p["encoder.conv_norm_out.weight"] = jnp.ones((cur,))
    p["encoder.conv_norm_out.bias"] = jnp.zeros((cur,))
    p["encoder.conv_out.weight"] = _rand(rng, 2 * lc, cur, 3, 3)
    p["encoder.conv_out.bias"] = jnp.zeros((2 * lc,))
    p["quant_conv.weight"] = _rand(rng, 2 * lc, 2 * lc, 1, 1)
    p["quant_conv.bias"] = jnp.zeros((2 * lc,))

    # decoder
    p["post_quant_conv.weight"] = _rand(rng, lc, lc, 1, 1)
    p["post_quant_conv.bias"] = jnp.zeros((lc,))
    top = ch[-1]
    p["decoder.conv_in.weight"] = _rand(rng, top, lc, 3, 3)
    p["decoder.conv_in.bias"] = jnp.zeros((top,))
    res("decoder.mid_block.resnets.0.", top, top)
    attn("decoder.mid_block.attentions.0.", top)
    res("decoder.mid_block.resnets.1.", top, top)
    cur = top
    rev = list(reversed(ch))
    for bi in range(len(ch)):
        bp = f"decoder.up_blocks.{bi}."
        for li in range(cfg.layers_per_block + 1):
            res(bp + f"resnets.{li}.", cur, rev[bi])
            cur = rev[bi]
        if bi < len(ch) - 1:
            p[bp + "upsamplers.0.conv.weight"] = _rand(rng, cur, cur, 3, 3)
            p[bp + "upsamplers.0.conv.bias"] = jnp.zeros((cur,))
    p["decoder.conv_norm_out.weight"] = jnp.ones((cur,))
    p["decoder.conv_norm_out.bias"] = jnp.zeros((cur,))
    p["decoder.conv_out.weight"] = _rand(rng, cfg.out_channels, cur, 3, 3)
    p["decoder.conv_out.bias"] = jnp.zeros((cfg.out_channels,))
    return p


def save_tiny_pipeline(pipe_dir: str, clip_cfg: ClipTextConfig,
                       unet_cfg: UNetConfig, vae_cfg: VaeConfig, seed=0,
                       controlnet_cfg: "ControlNetConfig" = None):
    """Write a complete diffusers-LAYOUT pipeline directory (tests)."""
    from safetensors.numpy import save_file

    def dump(sub, cfg_obj, params, fname):
        d = os.path.join(pipe_dir, sub)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "config.json"), "w") as f:
            json.dump({k: (list(v) if isinstance(v, tuple) else v)
                       for k, v in dataclasses.asdict(cfg_obj).items()}, f)
        save_file({k: np.asarray(v) for k, v in params.items()},
                  os.path.join(d, fname))

    dump("text_encoder", clip_cfg, init_clip_params(clip_cfg, seed),
         "model.safetensors")
    dump("unet", unet_cfg, init_unet_params(unet_cfg, seed + 1),
         "diffusion_pytorch_model.safetensors")
    dump("vae", vae_cfg, init_vae_params(vae_cfg, seed + 2),
         "diffusion_pytorch_model.safetensors")
    if controlnet_cfg is not None:
        dump("controlnet", controlnet_cfg,
             init_controlnet_params(controlnet_cfg, seed + 3),
             "diffusion_pytorch_model.safetensors")
