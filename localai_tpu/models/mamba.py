"""Mamba selective-state-space LM — the second LLM family (VERDICT r3 #9).

Replaces the reference's Mamba backend
(backend/python/mamba/backend.py:1-179, mamba_ssm via torch) with a
TPU-native port of the HF `MambaForCausalLM` layout. Mamba is the
TPU-flattering architecture: generation state is FIXED-SIZE per sequence
(a depthwise-conv window plus a [d_inner, d_state] SSM state — no KV
cache growing with context), and the recurrence is scan-native, so the
serving engine's slot model maps onto it directly: the (conv_state,
ssm_state) pair rides the engine's (cache_k, cache_v) lanes.

Implements the engine adapter contract shared with models/llama.py:
  init_cache(cfg, S, C, dtype)  -> (conv_state, ssm_state)
  engine_decode(params, cfg, tokens, lengths, active, ck, cv, pos_offset)
  prefill(params, cfg, tokens, seq_lens, ck, cv, slot_ids, start_pos, ...)
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class MambaConfig:
    vocab_size: int = 50280
    hidden_size: int = 768
    state_size: int = 16
    num_layers: int = 24
    conv_kernel: int = 4
    expand: int = 2
    time_step_rank: int = 48
    layer_norm_epsilon: float = 1e-5
    use_conv_bias: bool = True
    use_bias: bool = False
    tie_word_embeddings: bool = True
    dtype: Any = jnp.float32

    @property
    def d_inner(self) -> int:
        return self.expand * self.hidden_size

    @property
    def max_position_embeddings(self) -> int:
        # no positional encoding: context is bounded only by the engine's
        # token accounting (the runner clamps its default to 4096)
        return 1 << 20

    @staticmethod
    def from_hf_config(c: dict, dtype=jnp.float32) -> "MambaConfig":
        hs = c.get("hidden_size", 768)
        tsr = c.get("time_step_rank", "auto")
        if tsr == "auto" or tsr is None:
            tsr = -(-hs // 16)
        return MambaConfig(
            vocab_size=c.get("vocab_size", 50280),
            hidden_size=hs,
            state_size=c.get("state_size", 16),
            num_layers=c.get("num_hidden_layers", c.get("n_layer", 24)),
            conv_kernel=c.get("conv_kernel", 4),
            expand=c.get("expand", 2),
            time_step_rank=int(tsr),
            layer_norm_epsilon=c.get("layer_norm_epsilon", 1e-5),
            use_conv_bias=c.get("use_conv_bias", True),
            use_bias=c.get("use_bias", False),
            tie_word_embeddings=c.get("tie_word_embeddings", True),
            dtype=dtype,
        )

    @staticmethod
    def from_json(path: str, dtype=jnp.float32) -> "MambaConfig":
        with open(path) as f:
            return MambaConfig.from_hf_config(json.load(f), dtype=dtype)


def _rms(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


# the {q, s} int8 contract is shared by every family — see ops/quant.py
from localai_tpu.ops.quant import mat as _mat  # noqa: E402

QUANT_NAMES = ("in_proj_x", "in_proj_z", "x_proj", "dt_proj_w", "out_proj")


def quantize_params(params: dict) -> dict:
    """Weight-only per-out-channel int8 for the mixer projections (the
    bulk of mamba's weights; conv/norm/A/D stay dense — tiny, and the SSM
    recurrence itself is precision-sensitive)."""
    from localai_tpu.ops.quant import quantize_weight as q

    out = dict(params)
    out["layers"] = {k: (q(v) if k in QUANT_NAMES else v)
                     for k, v in params["layers"].items()}
    return out


def init_params(cfg: MambaConfig, key: jax.Array, dtype=None) -> dict:
    dtype = dtype or cfg.dtype
    L, D, Di = cfg.num_layers, cfg.hidden_size, cfg.d_inner
    N, R, K = cfg.state_size, cfg.time_step_rank, cfg.conv_kernel
    ks = jax.random.split(key, 8)

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                / np.sqrt(fan_in)).astype(dtype)

    A = jnp.broadcast_to(jnp.arange(1, N + 1, dtype=jnp.float32)[None, :],
                         (Di, N))
    params = {
        "embed": init(ks[0], (cfg.vocab_size, D), D),
        "final_norm": jnp.ones((D,), dtype),
        "layers": {
            "norm": jnp.ones((L, D), dtype),
            # HF stores in_proj as one [D, 2*Di] matrix ([x; z] halves);
            # kept SPLIT here so tensor parallelism shards each half's
            # d_inner axis evenly (a contiguous split of the concatenated
            # axis would put all x on some devices and all z on others)
            "in_proj_x": init(ks[1], (L, D, Di), D),
            "in_proj_z": init(ks[7], (L, D, Di), D),
            "conv_w": init(ks[2], (L, Di, K), K),
            "conv_b": jnp.zeros((L, Di), dtype),
            "x_proj": init(ks[3], (L, Di, R + 2 * N), Di),
            "dt_proj_w": init(ks[4], (L, R, Di), R),
            "dt_proj_b": jnp.zeros((L, Di), dtype),
            "A_log": jnp.log(jnp.broadcast_to(A, (L, Di, N))).astype(dtype),
            "D": jnp.ones((L, Di), dtype),
            "out_proj": init(ks[5], (L, Di, D), Di),
        },
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = init(ks[6], (D, cfg.vocab_size), D)
    return params


def load_hf_params(model_dir: str, cfg: MambaConfig, dtype=jnp.float32) -> dict:
    from localai_tpu.engine.weights import _open_shards

    shards = _open_shards(model_dir)

    def get(name):
        for pref in ("", "backbone."):
            if pref + name in shards:
                return np.asarray(shards[pref + name].get_tensor(pref + name))
        raise KeyError(name)

    L = cfg.num_layers

    def stack(fmt, transpose=False):
        mats = [get(fmt.format(i=i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(np.stack(mats), dtype)

    ly = "layers.{i}.mixer."
    in_proj = np.stack([get((ly + "in_proj.weight").format(i=i)).T
                        for i in range(L)])          # [L, D, 2*Di]
    Di = cfg.d_inner
    params = {
        "embed": jnp.asarray(get("embeddings.weight"), dtype),
        "final_norm": jnp.asarray(get("norm_f.weight"), dtype),
        "layers": {
            "norm": stack("layers.{i}.norm.weight"),
            "in_proj_x": jnp.asarray(in_proj[:, :, :Di], dtype),
            "in_proj_z": jnp.asarray(in_proj[:, :, Di:], dtype),
            # conv1d weight [Di, 1, K] -> [Di, K] (depthwise)
            "conv_w": jnp.asarray(np.stack(
                [get((ly + "conv1d.weight").format(i=i))[:, 0, :]
                 for i in range(L)]), dtype),
            "conv_b": stack(ly + "conv1d.bias"),
            "x_proj": stack(ly + "x_proj.weight", True),
            "dt_proj_w": stack(ly + "dt_proj.weight", True),
            "dt_proj_b": stack(ly + "dt_proj.bias"),
            "A_log": stack(ly + "A_log"),
            "D": stack(ly + "D"),
            "out_proj": stack(ly + "out_proj.weight", True),
        },
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight").T, dtype)
    return params


def init_cache(cfg: MambaConfig, num_slots: int, max_len: int, dtype=None):
    """Fixed-size per-slot generation state (max_len only bounds the
    engine's token accounting — the state itself is O(1) in context):
    (conv_state [L, S, Di, K-1], ssm_state [L, S, Di, N]) float32 —
    SSM recurrences are precision-sensitive, states stay fp32."""
    L, Di = cfg.num_layers, cfg.d_inner
    return (jnp.zeros((L, num_slots, Di, cfg.conv_kernel - 1), jnp.float32),
            jnp.zeros((L, num_slots, Di, cfg.state_size), jnp.float32))


def _unembed(x, params, cfg):
    if cfg.tie_word_embeddings:
        return jnp.einsum("...d,vd->...v", x.astype(jnp.float32),
                          params["embed"].astype(jnp.float32))
    return jnp.einsum("...d,dv->...v", x.astype(jnp.float32),
                      params["lm_head"].astype(jnp.float32))


def _mixer_step(h, conv_st, ssm_st, ly, cfg):
    """One token through one mixer. h [B, D]; conv_st [B, Di, K-1];
    ssm_st [B, Di, N]. Returns (out [B, D], conv_st, ssm_st)."""
    R, N = cfg.time_step_rank, cfg.state_size
    dt_ = h.dtype
    x = h @ _mat(ly["in_proj_x"], dt_)           # [B, Di]
    z = h @ _mat(ly["in_proj_z"], dt_)
    window = jnp.concatenate([conv_st, x[:, :, None]], axis=-1)  # [B,Di,K]
    conv_st = window[:, :, 1:]
    x = jnp.sum(window * ly["conv_w"][None], axis=-1) + ly["conv_b"][None]
    x = jax.nn.silu(x)                           # [B, Di]
    proj = x @ _mat(ly["x_proj"], x.dtype)       # [B, R+2N]
    dt = proj[:, :R] @ _mat(ly["dt_proj_w"], proj.dtype) + ly["dt_proj_b"][None]
    dt = jax.nn.softplus(dt)                     # [B, Di]
    Bm = proj[:, R:R + N]                        # [B, N]
    Cm = proj[:, R + N:]
    A = -jnp.exp(ly["A_log"].astype(jnp.float32))          # [Di, N]
    dA = jnp.exp(dt[:, :, None] * A[None])                 # [B, Di, N]
    dB = dt[:, :, None] * Bm[:, None, :]
    ssm_st = ssm_st * dA + dB * x[:, :, None]
    y = jnp.einsum("bdn,bn->bd", ssm_st, Cm) + ly["D"][None] * x
    y = y * jax.nn.silu(z)
    # conv/ssm state stays fp32 (recurrences are precision-sensitive) but
    # the residual path must return to the model dtype — otherwise the
    # fp32 state promotes every later layer's matmuls to f32
    return ((y @ _mat(ly["out_proj"], y.dtype)).astype(cfg.dtype),
            conv_st, ssm_st)


def _layer_scan(params, cfg, h, conv, ssm, active=None):
    """Scan h through all layers; state updates masked where not active.
    Shared by decode and prefill (they must never diverge)."""

    def layer_fn(carry, inp):
        hc = carry
        ly, conv_l, ssm_l = inp
        res = hc
        hn = _rms(hc, ly["norm"], cfg.layer_norm_epsilon)
        out, nconv, nssm = _mixer_step(hn, conv_l, ssm_l, ly, cfg)
        if active is not None:
            nconv = jnp.where(active[:, None, None], nconv, conv_l)
            nssm = jnp.where(active[:, None, None], nssm, ssm_l)
        return res + out, (nconv, nssm)

    return jax.lax.scan(layer_fn, h, (dict(params["layers"]), conv, ssm))


def _forward_token(params, cfg, tokens, conv, ssm, active=None):
    """One step for all rows. tokens [B]; conv/ssm [L, B, ...]."""
    h = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    h, (conv, ssm) = _layer_scan(params, cfg, h, conv, ssm, active)
    h = _rms(h, params["final_norm"], cfg.layer_norm_epsilon)
    return _unembed(h, params, cfg), conv, ssm


def engine_decode(params, cfg, tokens, lengths, active, conv, ssm,
                  pos_offset=None):
    """Engine adapter: one decode step for all slots. Inactive slots'
    states must not advance (the engine computes every slot every step).
    lengths/pos_offset are unused — Mamba has no positional encoding."""
    del lengths, pos_offset
    return _forward_token(params, cfg, tokens, conv, ssm, active=active)


def prefill(params, cfg, tokens, seq_lens, conv, ssm, slot_ids, start_pos,
            continued=False, mm_pos=None, mm_vec=None,
            return_all_logits=False, positions=None):
    """Engine adapter: ingest B prompts into their slots' states.

    Scan-native: the recurrence IS the architecture, so ingestion is a
    lax.scan over positions carrying (conv, ssm) for the B rows. Rows
    with start_pos == 0 start from zero state (a fresh prompt must not
    inherit the slot's previous occupant); continued rows resume the
    slot's existing state. Padding rows (t >= seq_len) don't advance.
    Duplicate slot_ids (batch padding) scatter identical values."""
    assert mm_pos is None and positions is None, \
        "multimodal/positions are llama-family features"
    B, T = tokens.shape
    conv_rows = jnp.take(conv, slot_ids, axis=1)     # [L, B, Di, K-1]
    ssm_rows = jnp.take(ssm, slot_ids, axis=1)
    fresh = (jnp.asarray(start_pos) == 0)[None, :, None, None]
    conv_rows = jnp.where(fresh, 0.0, conv_rows)
    ssm_rows = jnp.where(fresh, 0.0, ssm_rows)

    def step(carry, xs_t):
        conv_r, ssm_r, last_h = carry
        tok, t = xs_t
        act = t < jnp.asarray(seq_lens)
        h = jnp.take(params["embed"], tok, axis=0).astype(cfg.dtype)
        h, (conv_r, ssm_r) = _layer_scan(params, cfg, h, conv_r, ssm_r, act)
        is_last = (t == jnp.asarray(seq_lens) - 1)[:, None]
        last_h = jnp.where(is_last, h, last_h)
        return (conv_r, ssm_r, last_h), (h if return_all_logits else None)

    last0 = jnp.zeros((B, cfg.hidden_size), cfg.dtype)
    (conv_rows, ssm_rows, last_h), hs = jax.lax.scan(
        step, (conv_rows, ssm_rows, last0),
        (jnp.asarray(tokens).T, jnp.arange(T, dtype=jnp.int32)))
    conv = conv.at[:, slot_ids].set(conv_rows)
    ssm = ssm.at[:, slot_ids].set(ssm_rows)
    last_h = _rms(last_h, params["final_norm"], cfg.layer_norm_epsilon)
    if return_all_logits:
        hs = _rms(hs.transpose(1, 0, 2), params["final_norm"],
                  cfg.layer_norm_epsilon)
        return _unembed(hs, params, cfg), conv, ssm
    return _unembed(last_h, params, cfg), conv, ssm
