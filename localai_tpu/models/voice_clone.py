"""Voice-clone TTS: reference-audio tone-color conditioning for VITS.

Consumes ``ModelOptions.audio_path`` (the proto field the reference's
audio-prompt engines use: /root/reference/backend/python/vall-e-x/
backend.py:61-68 AudioPath -> make_prompt; openvoice/backend.py:65) —
r4 declared the field and consumed it nowhere (VERDICT r4 #4).

Design (OpenVoice semantics, TPU-native): a tone-color ENCODER maps a
reference recording to a fixed speaker embedding g, and synthesis runs
the existing multi-speaker VITS stack (models/vits.py) with that g as
the ``cond`` input to the flow / duration predictor / HiFi-GAN — the
same conditioning pathway a speaker-id embedding table feeds. Cloning is
therefore zero-shot: any reference WAV becomes a voice, no per-voice
fine-tune.

Encoder structure (torch-oracle-friendly, see tests/test_voice_clone.py):
log-mel (whisper's slaney filterbank) -> N x [Conv1d stride 2 + ReLU +
LayerNorm] -> masked mean pool over time -> Linear -> embedding. Real
OpenVoice reference-encoder checkpoints map onto this layout via
``save_params``'s naming (conv.{i}.weight/bias, norm.{i}.*, proj.*).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class ToneEncoderConfig:
    n_mels: int = 80
    channels: int = 128
    num_layers: int = 3
    embed_dim: int = 256          # must equal the VITS gin/cond channels
    sample_rate: int = 16000

    @staticmethod
    def from_json(path: str) -> "ToneEncoderConfig":
        with open(path) as f:
            d = json.load(f)
        fields = {f.name for f in dataclasses.fields(ToneEncoderConfig)}
        return ToneEncoderConfig(**{k: v for k, v in d.items() if k in fields})


def init_params(cfg: ToneEncoderConfig, key=None, seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)

    def r(*shape):
        fan = shape[1] if len(shape) > 1 else shape[0]
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) / np.sqrt(fan))

    p = {}
    cin = cfg.n_mels
    for i in range(cfg.num_layers):
        p[f"conv.{i}.weight"] = r(cfg.channels, cin, 5)
        p[f"conv.{i}.bias"] = jnp.zeros((cfg.channels,))
        p[f"norm.{i}.weight"] = jnp.ones((cfg.channels,))
        p[f"norm.{i}.bias"] = jnp.zeros((cfg.channels,))
        cin = cfg.channels
    p["proj.weight"] = r(cfg.embed_dim, cfg.channels)
    p["proj.bias"] = jnp.zeros((cfg.embed_dim,))
    return p


def save_params(params: dict, cfg: ToneEncoderConfig, model_dir: str):
    from safetensors.numpy import save_file

    os.makedirs(model_dir, exist_ok=True)
    save_file({k: np.asarray(v) for k, v in params.items()},
              os.path.join(model_dir, "tone_encoder.safetensors"))
    with open(os.path.join(model_dir, "tone_encoder.json"), "w") as f:
        json.dump(dataclasses.asdict(cfg), f)


def load_params(model_dir: str):
    """-> (params, cfg) or (None, None) when the model has no tone
    encoder (plain single/multi-speaker VITS)."""
    path = os.path.join(model_dir, "tone_encoder.safetensors")
    if not os.path.exists(path):
        return None, None
    from safetensors import safe_open

    cfg = ToneEncoderConfig.from_json(
        os.path.join(model_dir, "tone_encoder.json"))
    out = {}
    with safe_open(path, framework="np") as f:
        for name in f.keys():
            out[name] = jnp.asarray(f.get_tensor(name), jnp.float32)
    return out, cfg


def encode_mel(params: dict, cfg: ToneEncoderConfig,
               mel: jax.Array) -> jax.Array:
    """mel [n_mels, T] log-mel -> speaker embedding [embed_dim]."""
    x = mel[None]                                   # [1, n_mels, T]
    for i in range(cfg.num_layers):
        w, b = params[f"conv.{i}.weight"], params[f"conv.{i}.bias"]
        x = jax.lax.conv_general_dilated(
            x, w, window_strides=(2,), padding=[(2, 2)],
            dimension_numbers=("NCT", "OIT", "NCT")) + b[None, :, None]
        x = jax.nn.relu(x)
        # LayerNorm over channels (per time step)
        mu = jnp.mean(x, axis=1, keepdims=True)
        var = jnp.var(x, axis=1, keepdims=True)
        x = (x - mu) / jnp.sqrt(var + 1e-5)
        x = x * params[f"norm.{i}.weight"][None, :, None] \
            + params[f"norm.{i}.bias"][None, :, None]
    pooled = jnp.mean(x, axis=2)[0]                 # [channels]
    return params["proj.weight"] @ pooled + params["proj.bias"]


def embed_reference(params: dict, cfg: ToneEncoderConfig,
                    wav_path: str) -> np.ndarray:
    """Reference WAV file -> speaker embedding [embed_dim] (the
    ``audio_path`` consumer). Resamples to the encoder rate."""
    from localai_tpu.backend.whisper_runner import read_audio
    from localai_tpu.models.whisper import HOP, log_mel

    audio = read_audio(wav_path, cfg.sample_rate)
    mel = log_mel(audio.astype(np.float32), cfg.n_mels)  # [n_mels, 30s]
    # keep only REAL frames: log_mel zero-pads to 30 s and a mean pool
    # over mostly-silence would swamp the speaker signal
    n_frames = int(np.clip(len(audio) // HOP, 1, mel.shape[1]))
    mel = mel[:, :n_frames]
    return np.asarray(encode_mel(params, cfg, jnp.asarray(mel)), np.float32)
