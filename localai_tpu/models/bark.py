"""Bark text-to-speech — the three-stage GPT pipeline in functional JAX.

Capability parity with the reference's bark backend
(/root/reference/backend/python/bark/backend.py:1-93 — a gRPC wrapper
around the suno-bark package); the architecture/layout spec is the HF
`BarkModel` (public transformers library):

  1. semantic ("text") model: causal GPT over text tokens -> semantic
     tokens (the prompt is text-embeds + voice-history-embeds summed,
     plus an infer token);
  2. coarse acoustics model: causal GPT regressing the first two EnCodec
     codebooks, interleaved per step, over a sliding semantic window;
  3. fine acoustics model: NON-causal GPT with one embedding table per
     codebook and one lm_head per predicted codebook, iteratively
     filling codebooks 2..8 over 1024-position windows;
  4. EnCodec decode (models/encodec.py — shared with MusicGen).

TPU-first shape: each causal stage's generation is ONE jitted
lax.scan over a fixed-size KV cache (prefill + decode fused in a single
device program — no per-token host round-trip); the fine stage is a
host loop over a handful of whole-window forwards. Sampling (greedy or
temperature) happens on-device inside the scan.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class BarkSubConfig:
    input_vocab_size: int = 10_048
    output_vocab_size: int = 10_048
    num_layers: int = 12
    num_heads: int = 12
    hidden_size: int = 768
    block_size: int = 1024
    bias: bool = True
    n_codes_total: int = 8     # fine only
    n_codes_given: int = 1     # fine only

    @staticmethod
    def from_hf(d: dict) -> "BarkSubConfig":
        return BarkSubConfig(
            input_vocab_size=d.get("input_vocab_size", 10_048),
            output_vocab_size=d.get("output_vocab_size", 10_048),
            num_layers=d.get("num_layers", 12),
            num_heads=d.get("num_heads", 12),
            hidden_size=d.get("hidden_size", 768),
            block_size=d.get("block_size", 1024),
            bias=d.get("bias", True),
            n_codes_total=d.get("n_codes_total", 8),
            n_codes_given=d.get("n_codes_given", 1),
        )

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


@dataclasses.dataclass(frozen=True)
class BarkGenConfig:
    """Generation-time constants (HF Bark{Semantic,Coarse,Fine}
    GenerationConfig defaults; overridable for tiny test models)."""
    # semantic
    text_encoding_offset: int = 10_048
    text_pad_token: int = 129_595
    semantic_infer_token: int = 129_599
    semantic_vocab_size: int = 10_000
    semantic_pad_token: int = 10_000        # == eos token
    max_input_semantic_length: int = 256
    semantic_rate_hz: float = 49.9
    semantic_max_new: int = 768
    min_eos_p: Optional[float] = None
    # coarse
    codebook_size: int = 1024
    n_coarse_codebooks: int = 2
    coarse_semantic_pad_token: int = 12_048
    coarse_infer_token: int = 12_050
    max_coarse_input_length: int = 256
    max_coarse_history: int = 630
    sliding_window_len: int = 60
    coarse_rate_hz: float = 75.0
    # fine
    n_fine_codebooks: int = 8
    max_fine_history_length: int = 512
    max_fine_input_length: int = 1024

    @property
    def semantic_to_coarse_ratio(self) -> float:
        return (self.coarse_rate_hz / self.semantic_rate_hz
                * self.n_coarse_codebooks)


def gen_from_hf(d: dict) -> BarkGenConfig:
    """BarkGenConfig from an HF `generation_config.json` dict (the
    BarkGenerationConfig layout real suno/bark checkpoints ship)."""
    s = d.get("semantic_config", {})
    c = d.get("coarse_acoustics_config", {})
    f = d.get("fine_acoustics_config", {})
    base = BarkGenConfig()
    return BarkGenConfig(
        text_encoding_offset=s.get("text_encoding_offset",
                                   base.text_encoding_offset),
        text_pad_token=s.get("text_pad_token", base.text_pad_token),
        semantic_infer_token=s.get("semantic_infer_token",
                                   base.semantic_infer_token),
        semantic_vocab_size=s.get("semantic_vocab_size",
                                  base.semantic_vocab_size),
        semantic_pad_token=s.get("eos_token_id", base.semantic_pad_token),
        max_input_semantic_length=s.get("max_input_semantic_length",
                                        base.max_input_semantic_length),
        semantic_rate_hz=s.get("semantic_rate_hz", base.semantic_rate_hz),
        semantic_max_new=s.get("max_new_tokens", base.semantic_max_new),
        min_eos_p=s.get("min_eos_p", base.min_eos_p),
        codebook_size=d.get("codebook_size", base.codebook_size),
        n_coarse_codebooks=c.get("n_coarse_codebooks",
                                 base.n_coarse_codebooks),
        coarse_semantic_pad_token=c.get("coarse_semantic_pad_token",
                                        base.coarse_semantic_pad_token),
        coarse_infer_token=c.get("coarse_infer_token",
                                 base.coarse_infer_token),
        max_coarse_input_length=c.get("max_coarse_input_length",
                                      base.max_coarse_input_length),
        max_coarse_history=c.get("max_coarse_history",
                                 base.max_coarse_history),
        sliding_window_len=c.get("sliding_window_len",
                                 base.sliding_window_len),
        coarse_rate_hz=c.get("coarse_rate_hz", base.coarse_rate_hz),
        n_fine_codebooks=f.get("n_fine_codebooks", base.n_fine_codebooks),
        max_fine_history_length=f.get("max_fine_history_length",
                                      base.max_fine_history_length),
        max_fine_input_length=f.get("max_fine_input_length",
                                    base.max_fine_input_length),
    )


@dataclasses.dataclass(frozen=True)
class BarkConfig:
    semantic: BarkSubConfig
    coarse: BarkSubConfig
    fine: BarkSubConfig
    gen: BarkGenConfig = dataclasses.field(default_factory=BarkGenConfig)

    @staticmethod
    def from_hf_config(d: dict, gen: Optional[dict] = None) -> "BarkConfig":
        return BarkConfig(
            semantic=BarkSubConfig.from_hf(d.get("semantic_config", {})),
            coarse=BarkSubConfig.from_hf(
                d.get("coarse_acoustics_config", {})),
            fine=BarkSubConfig.from_hf(d.get("fine_acoustics_config", {})),
            gen=gen_from_hf(gen or {}),
        )

    @staticmethod
    def from_dir(model_dir: str) -> "BarkConfig":
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = json.load(f)
        gen = {}
        gpath = os.path.join(model_dir, "generation_config.json")
        if os.path.exists(gpath):
            with open(gpath) as f:
                gen = json.load(f)
        return BarkConfig.from_hf_config(cfg, gen)


# ---------------------------------------------------------------- params

def _ln(t, w, b):
    mu = jnp.mean(t, -1, keepdims=True)
    var = jnp.var(t, -1, keepdims=True)
    out = (t - mu) / jnp.sqrt(var + 1e-5) * w
    return out + b if b is not None else out


def _collect_submodel(get, prefix: str, cfg: BarkSubConfig, fine: bool):
    """Stack one GPT submodel's torch tensors into a scanned pytree."""
    L = cfg.num_layers

    def stack(fmt, transpose=False, optional=False):
        mats = []
        for i in range(L):
            name = fmt.format(i=i)
            t = get(name, optional)
            if t is None:
                return None
            mats.append(t.T if transpose else t)
        return jnp.asarray(np.stack(mats), jnp.float32)

    p = prefix + "layers.{i}."
    params = {
        "pos": jnp.asarray(get(prefix + "position_embeds_layer.weight"),
                           jnp.float32),
        "ln1_w": stack(p + "layernorm_1.weight"),
        "ln1_b": stack(p + "layernorm_1.bias", optional=True),
        "ln2_w": stack(p + "layernorm_2.weight"),
        "ln2_b": stack(p + "layernorm_2.bias", optional=True),
        "qkv_w": stack(p + "attn.att_proj.weight", transpose=True),
        "qkv_b": stack(p + "attn.att_proj.bias", optional=True),
        "wo": stack(p + "attn.out_proj.weight", transpose=True),
        "wo_b": stack(p + "attn.out_proj.bias", optional=True),
        "mlp_in": stack(p + "mlp.in_proj.weight", transpose=True),
        "mlp_in_b": stack(p + "mlp.in_proj.bias", optional=True),
        "mlp_out": stack(p + "mlp.out_proj.weight", transpose=True),
        "mlp_out_b": stack(p + "mlp.out_proj.bias", optional=True),
        "lnf_w": jnp.asarray(get(prefix + "layernorm_final.weight"),
                             jnp.float32),
        "lnf_b": (jnp.asarray(b, jnp.float32) if (b := get(
            prefix + "layernorm_final.bias", True)) is not None else None),
    }
    if fine:
        params["embed"] = jnp.asarray(np.stack(
            [get(f"{prefix}input_embeds_layers.{i}.weight")
             for i in range(cfg.n_codes_total)]), jnp.float32)

        def head(i):
            # tie_word_embeddings (HF default): lm_heads[i] shares
            # input_embeds_layers[i + n_codes_given] and is not saved
            w = get(f"{prefix}lm_heads.{i}.weight", optional=True)
            if w is None:
                w = get(f"{prefix}input_embeds_layers."
                        f"{i + cfg.n_codes_given}.weight")
            return w.T

        params["lm_head"] = jnp.asarray(np.stack(
            [head(i) for i in range(cfg.n_codes_total - cfg.n_codes_given)]),
            jnp.float32)
    else:
        params["embed"] = jnp.asarray(
            get(prefix + "input_embeds_layer.weight"), jnp.float32)
        params["lm_head"] = jnp.asarray(
            get(prefix + "lm_head.weight").T, jnp.float32)
    return params


def load_hf_params(model_dir: str, cfg: BarkConfig):
    """(params, encodec_cfg, encodec_params) from a BarkModel save dir."""
    from localai_tpu.engine.weights import _open_shards
    from localai_tpu.models import encodec as enc

    tensors = _open_shards(model_dir)

    def get(name, optional=False):
        if name not in tensors:
            if optional:
                return None
            raise KeyError(name)
        return tensors[name].get_tensor(name)

    params = {
        "semantic": _collect_submodel(get, "semantic.", cfg.semantic, False),
        "coarse": _collect_submodel(get, "coarse_acoustics.", cfg.coarse,
                                    False),
        "fine": _collect_submodel(get, "fine_acoustics.", cfg.fine, True),
    }
    with open(os.path.join(model_dir, "config.json")) as f:
        codec_cfg = enc.EncodecConfig.from_hf_config(
            json.load(f).get("codec_config", {}))
    codec = enc.load_hf_params(
        {k[len("codec_model."):]: get(k) for k in tensors
         if k.startswith("codec_model.")}, codec_cfg)
    return params, codec_cfg, codec


# --------------------------------------------------------------- forward

def _attn_qkv(h, layer, cfg: BarkSubConfig):
    qkv = jnp.einsum("btd,de->bte", h, layer["qkv_w"])
    if layer["qkv_b"] is not None:
        qkv = qkv + layer["qkv_b"]
    B, T, _ = qkv.shape
    H, hd = cfg.num_heads, cfg.head_dim
    q, k, v = jnp.split(qkv, 3, axis=-1)
    return (q.reshape(B, T, H, hd), k.reshape(B, T, H, hd),
            v.reshape(B, T, H, hd))


def _block(x, layer, cfg: BarkSubConfig, mask):
    """One pre-LN GPT block; mask [B?, 1, Tq, Tk] additive."""
    h = _ln(x, layer["ln1_w"], layer["ln1_b"])
    q, k, v = _attn_qkv(h, layer, cfg)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(cfg.head_dim)
    attn = jax.nn.softmax(scores + mask, axis=-1)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", attn, v)
    ctx = ctx.reshape(x.shape[0], x.shape[1], cfg.hidden_size)
    o = ctx @ layer["wo"]
    if layer["wo_b"] is not None:
        o = o + layer["wo_b"]
    x = x + o
    h = _ln(x, layer["ln2_w"], layer["ln2_b"])
    m = h @ layer["mlp_in"]
    if layer["mlp_in_b"] is not None:
        m = m + layer["mlp_in_b"]
    m = jax.nn.gelu(m, approximate=False) @ layer["mlp_out"]
    if layer["mlp_out_b"] is not None:
        m = m + layer["mlp_out_b"]
    return x + m


def _scan_layers(x, params, cfg: BarkSubConfig, mask):
    def body(x, layer):
        return _block(x, layer, cfg, mask), None

    layers = {k: params[k] for k in
              ("ln1_w", "ln1_b", "ln2_w", "ln2_b", "qkv_w", "qkv_b",
               "wo", "wo_b", "mlp_in", "mlp_in_b", "mlp_out", "mlp_out_b")}
    if layers["ln1_b"] is None:     # bias-less checkpoints: drop None leaves
        layers = {k: v for k, v in layers.items() if v is not None}
        def body(x, layer):  # noqa: F811
            full = dict.fromkeys(
                ("ln1_b", "ln2_b", "qkv_b", "wo_b", "mlp_in_b", "mlp_out_b"))
            full.update(layer)
            return _block(x, full, cfg, mask), None
    x, _ = jax.lax.scan(body, x, layers)
    return x


def causal_logits(params, cfg: BarkSubConfig, embeds, valid=None):
    """Full causal forward over embeds [B, T, D] -> logits [B, T, V].
    ``valid`` [B, T] masks padded positions out of the attended keys."""
    B, T, _ = embeds.shape
    x = embeds + params["pos"][:T][None]
    causal = jnp.tril(jnp.ones((T, T), bool))[None, None]
    if valid is not None:
        causal = causal & valid[:, None, None, :]
    mask = jnp.where(causal, 0.0, -1e9)
    x = _scan_layers(x, params, cfg, mask)
    x = _ln(x, params["lnf_w"], params["lnf_b"])
    return x @ params["lm_head"]


def fine_logits(params, cfg: BarkSubConfig, codes, codebook_idx: int):
    """Non-causal fine forward: codes [B, T, n_codes_total] int32 ->
    logits [B, T, V] for ``codebook_idx`` (embeds = sum of tables
    0..codebook_idx, matching BarkFineModel.forward)."""
    B, T, _ = codes.shape
    emb = params["embed"]                       # [n_codes, V, D]
    x = jnp.zeros((B, T, emb.shape[-1]), jnp.float32)
    for i in range(codebook_idx + 1):
        x = x + jnp.take(emb[i], codes[:, :, i], axis=0)
    x = x + params["pos"][:T][None]
    x = _scan_layers(x, params, cfg, jnp.zeros((1, 1, T, T), jnp.float32))
    x = _ln(x, params["lnf_w"], params["lnf_b"])
    return x @ params["lm_head"][codebook_idx - cfg.n_codes_given]


# ------------------------------------------------------- cached generate

def _prefill_cache(params, cfg: BarkSubConfig, embeds, prefix_len, total):
    """Run the prefix through the blocks, returning per-layer K/V caches
    padded to ``total`` positions plus the last hidden state's logits."""
    B, P, D = embeds.shape
    x = embeds + params["pos"][:P][None]
    pos_idx = jnp.arange(P)
    causal = (pos_idx[None, :] <= pos_idx[:, None])[None, None]
    valid = (jnp.arange(P)[None] < prefix_len[:, None])
    mask = jnp.where(causal & valid[:, None, None, :], 0.0, -1e9)

    ks, vs = [], []
    layers = _layer_list(params, cfg)
    for layer in layers:
        h = _ln(x, layer["ln1_w"], layer["ln1_b"])
        q, k, v = _attn_qkv(h, layer, cfg)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(cfg.head_dim)
        attn = jax.nn.softmax(scores + mask, axis=-1)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", attn, v).reshape(B, P, D)
        o = ctx @ layer["wo"]
        if layer["wo_b"] is not None:
            o = o + layer["wo_b"]
        x = x + o
        h = _ln(x, layer["ln2_w"], layer["ln2_b"])
        m = h @ layer["mlp_in"]
        if layer["mlp_in_b"] is not None:
            m = m + layer["mlp_in_b"]
        m = jax.nn.gelu(m, approximate=False) @ layer["mlp_out"]
        if layer["mlp_out_b"] is not None:
            m = m + layer["mlp_out_b"]
        x = x + m
        ks.append(jnp.pad(k, ((0, 0), (0, total - P), (0, 0), (0, 0))))
        vs.append(jnp.pad(v, ((0, 0), (0, total - P), (0, 0), (0, 0))))
    x = _ln(x, params["lnf_w"], params["lnf_b"])
    # last VALID position's hidden state per batch row
    last = jnp.take_along_axis(
        x, (prefix_len - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    return last @ params["lm_head"], jnp.stack(ks), jnp.stack(vs)


def _layer_list(params, cfg: BarkSubConfig):
    keys = ("ln1_w", "ln1_b", "ln2_w", "ln2_b", "qkv_w", "qkv_b",
            "wo", "wo_b", "mlp_in", "mlp_in_b", "mlp_out", "mlp_out_b")
    out = []
    for i in range(cfg.num_layers):
        out.append({k: (params[k][i] if params[k] is not None else None)
                    for k in keys})
    return out


def _decode_step(params, cfg: BarkSubConfig, tok_embed, pos, ck, cv,
                 prefix_len, step_valid):
    """One cached decode step. tok_embed [B, D]; ck/cv [L, B, total, H, hd];
    writes at position ``pos`` [B]; attends over [0, pos]."""
    B, D = tok_embed.shape
    x = (tok_embed + jnp.take(params["pos"], pos, axis=0))[:, None]
    total = ck.shape[2]
    kpos = jnp.arange(total)
    layers = _layer_list(params, cfg)
    new_ck, new_cv = [], []
    for li, layer in enumerate(layers):
        h = _ln(x, layer["ln1_w"], layer["ln1_b"])
        q, k, v = _attn_qkv(h, layer, cfg)
        lk = ck[li].at[jnp.arange(B), pos].set(k[:, 0])
        lv = cv[li].at[jnp.arange(B), pos].set(v[:, 0])
        # valid keys: prefix rows [0, prefix_len) and generated [P, pos]
        att_ok = (kpos[None] < prefix_len[:, None]) | (
            (kpos[None] <= pos[:, None]) & step_valid[:, None])
        scores = jnp.einsum("bhd,bkhd->bhk", q[:, 0], lk) \
            / np.sqrt(cfg.head_dim)
        attn = jax.nn.softmax(
            jnp.where(att_ok[:, None], scores, -1e9), axis=-1)
        ctx = jnp.einsum("bhk,bkhd->bhd", attn, lv).reshape(B, 1, D)
        o = ctx @ layer["wo"]
        if layer["wo_b"] is not None:
            o = o + layer["wo_b"]
        x = x + o
        h = _ln(x, layer["ln2_w"], layer["ln2_b"])
        m = h @ layer["mlp_in"]
        if layer["mlp_in_b"] is not None:
            m = m + layer["mlp_in_b"]
        m = jax.nn.gelu(m, approximate=False) @ layer["mlp_out"]
        if layer["mlp_out_b"] is not None:
            m = m + layer["mlp_out_b"]
        x = x + m
        new_ck.append(lk)
        new_cv.append(lv)
    x = _ln(x, params["lnf_w"], params["lnf_b"])
    return (x[:, 0] @ params["lm_head"], jnp.stack(new_ck),
            jnp.stack(new_cv))


def _sample(logits, allowed_lo, allowed_hi, temperature, key):
    """Greedy (temperature<=0) or softmax sample restricted to
    [allowed_lo, allowed_hi)."""
    V = logits.shape[-1]
    ids = jnp.arange(V)
    ok = (ids[None] >= allowed_lo[:, None]) & (ids[None] < allowed_hi[:, None])
    masked = jnp.where(ok, logits, -jnp.inf)
    if temperature and temperature > 0:
        return jax.random.categorical(key, masked / temperature, axis=-1)
    return jnp.argmax(masked, axis=-1).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("sub", "g", "temperature", "max_new", "total", "P"))
def _semantic_scan(sem_params, prefix, prefix_len, key, *, sub, g,
                   temperature, max_new, total, P):
    """Prefill + max_new cached decode steps in ONE device program."""
    B = prefix.shape[0]
    eos = jnp.int32(g.semantic_pad_token)
    logits, ck, cv = _prefill_cache(sem_params, sub, prefix, prefix_len,
                                    total)

    def step(carry, key):
        logits, ck, cv, done, n = carry
        lo = jnp.zeros((B,), jnp.int32)
        hi = jnp.full((B,), g.semantic_vocab_size + 1, jnp.int32)
        tok = _sample(logits, lo, hi, temperature, key)
        if g.min_eos_p:
            # the eos probability is taken AFTER vocab suppression (HF
            # applies SuppressTokens before the eos prioritizer): the
            # never-trained out-of-range logits must not absorb mass
            ids = jnp.arange(logits.shape[-1])
            masked = jnp.where(ids[None] <= eos, logits, -jnp.inf)
            p = jax.nn.softmax(masked, axis=-1)[:, g.semantic_pad_token]
            tok = jnp.where(p >= g.min_eos_p, eos, tok)
        tok = jnp.where(done, eos, tok)
        done = done | (tok == eos)
        pos = jnp.minimum(P + n, total - 1)
        emb_t = jnp.take(sem_params["embed"], tok, axis=0)
        logits, ck, cv = _decode_step(
            sem_params, sub, emb_t, jnp.full((B,), pos, jnp.int32),
            ck, cv, prefix_len, ~done)
        return (logits, ck, cv, done, n + 1), tok

    keys = jax.random.split(key, max_new)
    _, toks = jax.lax.scan(
        step, (logits, ck, cv, jnp.zeros((B,), bool), 0), keys)
    return toks.T                                         # [B, max_new]


def generate_semantic(params, cfg: BarkConfig, text_ids, text_len,
                      history: Optional[np.ndarray] = None,
                      temperature: float = 0.0, seed: int = 0,
                      max_new: Optional[int] = None):
    """Text ids [B, <=256] -> semantic tokens [B, max_new] + lengths [B].

    Mirrors BarkSemanticModel.generate: ids get text_encoding_offset,
    pads become text_pad_token, the prompt embedding is
    emb(text)+emb(history) with an infer token appended, and generation
    is restricted to [0, semantic_vocab_size] + eos."""
    g = cfg.gen
    sub = cfg.semantic
    B = text_ids.shape[0]
    ml = g.max_input_semantic_length
    max_new = int(max_new or g.semantic_max_new)

    ids = np.asarray(text_ids, np.int64) + g.text_encoding_offset
    pad_mask = (np.arange(ids.shape[1])[None] >= np.asarray(text_len)[:, None])
    ids[pad_mask] = g.text_pad_token
    ids = np.pad(ids[:, :ml], ((0, 0), (0, max(0, ml - ids.shape[1]))),
                 constant_values=g.text_pad_token)

    if history is not None:
        hist = np.asarray(history, np.int64)[-ml:]
        hist = np.pad(hist, (0, ml - len(hist)),
                      constant_values=g.semantic_pad_token)
    else:
        hist = np.full((ml,), g.semantic_pad_token, np.int64)
    hist = np.broadcast_to(hist, (B, ml))

    emb = params["semantic"]["embed"]
    prefix = (jnp.take(emb, jnp.asarray(ids), axis=0)
              + jnp.take(emb, jnp.asarray(hist), axis=0))
    infer = jnp.broadcast_to(emb[g.semantic_infer_token][None, None],
                             (B, 1, emb.shape[-1]))
    prefix = jnp.concatenate([prefix, infer], axis=1)     # [B, ml+1, D]
    P = ml + 1
    prefix_len = jnp.full((B,), P, jnp.int32)

    # HF cropping semantics: generation stops at the model's block_size.
    # Without the clamp, write positions saturate at block_size-1
    # (jnp.minimum in _semantic_scan) and late steps silently overwrite
    # the last KV row, degrading the audio tail (ADVICE r5, bark.py:833).
    max_new = min(max_new, sub.block_size - P)
    total = min(P + max_new, sub.block_size)

    toks = np.asarray(_semantic_scan(
        params["semantic"], prefix, prefix_len, jax.random.PRNGKey(seed),
        sub=sub, g=g, temperature=float(temperature), max_new=max_new,
        total=total, P=P))
    lengths = []
    for b in range(B):
        nz = np.where(toks[b] == g.semantic_pad_token)[0]
        lengths.append(int(nz[0]) if len(nz) else toks.shape[1])
    return toks, np.asarray(lengths, np.int32)


@functools.partial(
    jax.jit, static_argnames=("sub", "g", "temperature", "P"))
def _coarse_window(co_params, prefix_ids, prefix_len, gen_parity, key,
                   n_new_mask, *, sub, g, temperature, P):
    """One sliding-window pass: prefill the (semantic-chunk + infer +
    coarse-history) prefix, then sliding_window_len alternating-codebook
    decode steps — one device program per window."""
    B = prefix_ids.shape[0]
    emb = co_params["embed"]
    prefix = jnp.take(emb, prefix_ids, axis=0)
    total = P + g.sliding_window_len
    logits, ck, cv = _prefill_cache(co_params, sub, prefix, prefix_len,
                                    total)

    def step(carry, inp):
        logits, ck, cv, n = carry
        key, active = inp
        parity = (gen_parity + n) % 2
        lo = jnp.full((B,), g.semantic_vocab_size, jnp.int32) \
            + parity * g.codebook_size
        hi = lo + g.codebook_size
        tok = _sample(logits, lo, hi, temperature, key)
        pos = jnp.minimum(prefix_len + n, total - 1)
        emb_t = jnp.take(emb, tok, axis=0)
        logits, ck, cv = _decode_step(
            co_params, sub, emb_t, pos, ck, cv, prefix_len,
            jnp.broadcast_to(active, (B,)))
        return (logits, ck, cv, n + 1), tok

    keys = jax.random.split(key, g.sliding_window_len)
    _, toks = jax.lax.scan(step, (logits, ck, cv, 0), (keys, n_new_mask))
    return toks.T


def generate_coarse(params, cfg: BarkConfig, semantic, semantic_len,
                    temperature: float = 0.0, seed: int = 0,
                    history: Optional[dict] = None):
    """Semantic tokens -> interleaved coarse tokens [B, n_steps]
    (codebook 0/1 alternating, ids offset by semantic_vocab_size),
    mirroring BarkCoarseModel.generate's sliding-window loop. A voice
    preset's semantic/coarse prompts condition the windows exactly as
    BarkCoarseModel.preprocess_histories does."""
    g = cfg.gen
    sub = cfg.coarse
    B = semantic.shape[0]
    ratio = g.semantic_to_coarse_ratio
    max_sem_hist = int(np.floor(g.max_coarse_history / ratio))

    sem = np.asarray(semantic, np.int64).copy()
    for b in range(B):
        sem[b, semantic_len[b]:] = g.coarse_semantic_pad_token
    sem[sem == g.semantic_pad_token] = g.coarse_semantic_pad_token

    n_steps = int(np.max(np.round(np.floor(
        np.asarray(semantic_len) * ratio / g.n_coarse_codebooks)
        * g.n_coarse_codebooks)))
    n_windows = int(np.ceil(n_steps / g.sliding_window_len))

    # voice-preset histories (preprocess_histories semantics): the
    # coarse prompt rows get per-codebook offsets, interleave-flatten,
    # and both histories are trimmed to a consistent ratio-aligned tail
    if history is not None and "semantic_prompt" in history \
            and "coarse_prompt" in history:
        sem_hist = np.asarray(history["semantic_prompt"], np.int64).ravel()
        co = np.asarray(history["coarse_prompt"], np.int64).copy()
        for n in range(1, co.shape[0]):
            co[n] += g.codebook_size * n
        co_flat = co.T.reshape(-1) + g.semantic_vocab_size
        n_sem = min(max_sem_hist, len(sem_hist) - len(sem_hist) % 2,
                    int(np.floor(len(co_flat) / ratio)))
        n_co = int(round(n_sem * ratio))
        sem_hist = sem_hist[len(sem_hist) - n_sem:]
        co_hist = co_flat[len(co_flat) - n_co:][:-2] if n_co > 2 else \
            co_flat[:0]
        sem = np.concatenate(
            [np.broadcast_to(sem_hist, (B, len(sem_hist))), sem], axis=1)
        x_coarse = np.broadcast_to(co_hist, (B, len(co_hist))).copy()
        base_sem_idx = len(sem_hist)
    else:
        x_coarse = np.zeros((B, 0), np.int64)
        base_sem_idx = 0
    len_coarse_hist = x_coarse.shape[1]
    total_done = 0
    key = jax.random.PRNGKey(seed)

    # fixed shapes for the jitted window: prefix = 256 + 1 + 630
    P = g.max_coarse_input_length + 1 + g.max_coarse_history

    for _ in range(n_windows):
        sem_idx = base_sem_idx + int(round(total_done / ratio))
        chunk = sem[:, max(0, sem_idx - max_sem_hist):]
        chunk = chunk[:, :g.max_coarse_input_length]
        chunk = np.pad(chunk,
                       ((0, 0),
                        (0, g.max_coarse_input_length - chunk.shape[1])),
                       constant_values=g.coarse_semantic_pad_token)
        hist = x_coarse[:, -g.max_coarse_history:]
        prefix_ids = np.concatenate([
            chunk,
            np.full((B, 1), g.coarse_infer_token, np.int64),
            hist,
            np.zeros((B, P - g.max_coarse_input_length - 1 - hist.shape[1]),
                     np.int64),
        ], axis=1)
        prefix_len = np.full(
            (B,), g.max_coarse_input_length + 1 + hist.shape[1], np.int32)
        n_new = min(g.sliding_window_len, n_steps - total_done)
        key, sub_key = jax.random.split(key)
        mask = np.arange(g.sliding_window_len) < n_new
        toks = np.asarray(_coarse_window(
            params["coarse"], jnp.asarray(prefix_ids),
            jnp.asarray(prefix_len), jnp.int32(total_done % 2), sub_key,
            jnp.asarray(mask), sub=sub, g=g,
            temperature=float(temperature), P=P))
        x_coarse = np.concatenate([x_coarse, toks[:, :n_new]], axis=1)
        total_done += n_new
    return x_coarse[:, len_coarse_hist:]


@functools.partial(
    jax.jit, static_argnames=("sub", "codebook_idx", "cb", "temperature"))
def _fine_refine(fi_params, buf, key, *, sub, codebook_idx, cb, temperature):
    logits = fine_logits(fi_params, sub, buf, codebook_idx)
    rel = logits[:, :, :cb]
    if temperature and temperature > 0:
        return jax.random.categorical(key, rel / temperature, axis=-1)
    return jnp.argmax(rel, axis=-1).astype(jnp.int32)


def generate_fine(params, cfg: BarkConfig, coarse, temperature: float = 0.0,
                  seed: int = 0, history: Optional[dict] = None):
    """Interleaved coarse tokens [B, steps] -> full codebook grid
    [B, n_fine_codebooks, T], mirroring BarkFineModel.generate's
    overlapping-window refinement (a voice preset's fine prompt is
    prepended as already-filled context and trimmed from the output)."""
    g = cfg.gen
    sub = cfg.fine
    B = coarse.shape[0]
    cb = g.codebook_size
    co = np.asarray(coarse, np.int64).reshape(B, -1, g.n_coarse_codebooks)
    co = np.remainder(co - g.semantic_vocab_size, cb)
    T = co.shape[1]

    fine = np.pad(co, ((0, 0), (0, 0),
                       (0, g.n_fine_codebooks - g.n_coarse_codebooks)),
                  constant_values=cb)
    n_history = 0
    if history is not None and "fine_prompt" in history:
        fh = np.asarray(history["fine_prompt"], np.int64).T  # [T, n_fine]
        fh = fh[-g.max_fine_history_length:]
        n_history = fh.shape[0]
        fine = np.concatenate(
            [np.broadcast_to(fh, (B,) + fh.shape), fine], axis=1)
    n_remove = 0
    if fine.shape[1] < g.max_fine_input_length:
        n_remove = g.max_fine_input_length - fine.shape[1]
        fine = np.pad(fine, ((0, 0), (0, n_remove), (0, 0)),
                      constant_values=cb)

    n_loops = max(0, int(np.ceil(
        (T - (g.max_fine_input_length - n_history))
        / g.max_fine_history_length))) + 1

    key = jax.random.PRNGKey(seed)
    for n_outer in range(n_loops):
        start = min(n_outer * g.max_fine_history_length,
                    fine.shape[1] - g.max_fine_input_length)
        fill = min(n_history + n_outer * g.max_fine_history_length,
                   fine.shape[1] - g.max_fine_history_length)
        rel_fill = fill - start
        buf = fine[:, start: start + g.max_fine_input_length]
        for ci in range(g.n_coarse_codebooks, g.n_fine_codebooks):
            key, sk = jax.random.split(key)
            preds = np.asarray(_fine_refine(
                params["fine"], jnp.asarray(buf), sk, sub=sub,
                codebook_idx=ci, cb=cb, temperature=float(temperature)))
            buf[:, rel_fill:, ci] = preds[:, rel_fill:]
        fine[:, fill: fill + g.max_fine_input_length - rel_fill] = \
            buf[:, rel_fill:]
    fine = np.transpose(fine, (0, 2, 1))[:, :, n_history:]
    if n_remove:
        fine = fine[:, :, :-n_remove]
    return fine


def generate_speech(params, cfg: BarkConfig, codec_cfg, codec_params,
                    text_ids, text_len, temperature: float = 0.0,
                    seed: int = 0, max_semantic: Optional[int] = None,
                    history: Optional[dict] = None):
    """Full pipeline: text ids -> waveform [B, T_audio] float32."""
    from localai_tpu.models import encodec as enc

    sem_hist = history.get("semantic_prompt") if history else None
    semantic, sem_len = generate_semantic(
        params, cfg, text_ids, text_len, history=sem_hist,
        temperature=temperature, seed=seed, max_new=max_semantic)
    coarse = generate_coarse(params, cfg, semantic, sem_len,
                             temperature=temperature, seed=seed + 1,
                             history=history)
    fine = generate_fine(params, cfg, coarse, temperature=temperature,
                         seed=seed + 2, history=history)
    codes = jnp.transpose(jnp.asarray(fine), (1, 0, 2))   # [K, B, T]
    audio = enc.decode(codec_params, codec_cfg, codes)    # [B, ch, samples]
    return np.asarray(audio)[:, 0]
