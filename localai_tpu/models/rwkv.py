"""RWKV v4 LM — the third LLM family through the unchanged serving engine.

Replaces the reference's RWKV backend
(/root/reference/backend/go/llm/rwkv/rwkv.go:1-95 — a cgo wrapper over
rwkv.cpp) with a TPU-native port of the HF ``RwkvForCausalLM`` layout.
Like Mamba, RWKV is TPU-flattering: generation state is FIXED-SIZE per
sequence (per layer: a token-shift vector for each of the two mixers plus
the wkv numerator/denominator/max accumulators — no KV cache growing
with context), so it rides the engine's (cache_k, cache_v) lanes via the
same family-adapter contract as models/mamba.py:

  init_cache(cfg, S, C, dtype)  -> (att_state [L,S,4,D], ffn_state [L,S,1,D])
  engine_decode(params, cfg, tokens, lengths, active, ck, cv, pos_offset)
  prefill(params, cfg, tokens, seq_lens, ck, cv, slot_ids, start_pos, ...)

att_state lanes: [prev_x, wkv_num, wkv_den, wkv_max]; a FRESH sequence
starts from zeros except wkv_max = -1e38 (the HF init), handled by the
fresh-row masking in prefill. The wkv recurrence uses the max-state
stabilized form (exactly HF modeling_rwkv.rwkv_linear_attention_cpu) so
torch parity is bit-for-bit testable.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

_MAX_INIT = -1e38


@dataclasses.dataclass(frozen=True)
class RwkvConfig:
    vocab_size: int = 50277
    hidden_size: int = 768
    num_layers: int = 12
    attention_hidden_size: int = 768   # == hidden_size for v4
    intermediate_size: int = 3072      # 4 * hidden_size default
    layer_norm_epsilon: float = 1e-5
    rescale_every: int = 6
    tie_word_embeddings: bool = False
    dtype: Any = jnp.float32

    @property
    def max_position_embeddings(self) -> int:
        # no positional encoding; context bounded by engine accounting
        return 1 << 20

    @property
    def d_inner(self) -> int:
        # sharding-axis analogue used by generic family plumbing
        return self.attention_hidden_size

    @staticmethod
    def from_hf_config(c: dict, dtype=jnp.float32) -> "RwkvConfig":
        hs = c.get("hidden_size", 768)
        return RwkvConfig(
            vocab_size=c.get("vocab_size", 50277),
            hidden_size=hs,
            num_layers=c.get("num_hidden_layers", 12),
            attention_hidden_size=c.get("attention_hidden_size", hs) or hs,
            intermediate_size=c.get("intermediate_size", 4 * hs) or 4 * hs,
            layer_norm_epsilon=c.get("layer_norm_epsilon", 1e-5),
            rescale_every=c.get("rescale_every", 6),
            tie_word_embeddings=c.get("tie_word_embeddings", False),
            dtype=dtype,
        )

    @staticmethod
    def from_json(path: str, dtype=jnp.float32) -> "RwkvConfig":
        with open(path) as f:
            return RwkvConfig.from_hf_config(json.load(f), dtype=dtype)


def _ln(x, w, b, eps):
    mu = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
    var = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
    return (((x.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps))
            .astype(x.dtype) * w + b)


# the {q, s} int8 contract is shared by every family — see ops/quant.py
from localai_tpu.ops.quant import mat as _mat  # noqa: E402

QUANT_NAMES = ("att_key", "att_value", "att_receptance", "att_output",
               "ffn_key", "ffn_receptance", "ffn_value")


def quantize_params(params: dict) -> dict:
    """Weight-only per-out-channel int8 for the mixer Linears."""
    from localai_tpu.ops.quant import quantize_weight as q

    out = dict(params)
    out["layers"] = {k: (q(v) if k in QUANT_NAMES else v)
                     for k, v in params["layers"].items()}
    return out


def init_params(cfg: RwkvConfig, key: jax.Array, dtype=None) -> dict:
    dtype = dtype or cfg.dtype
    L, D, A, F = (cfg.num_layers, cfg.hidden_size,
                  cfg.attention_hidden_size, cfg.intermediate_size)
    ks = jax.random.split(key, 12)

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                / np.sqrt(fan_in)).astype(dtype)

    params = {
        "embed": init(ks[0], (cfg.vocab_size, D), D),
        "pre_ln_w": jnp.ones((D,), dtype), "pre_ln_b": jnp.zeros((D,), dtype),
        "out_ln_w": jnp.ones((D,), dtype), "out_ln_b": jnp.zeros((D,), dtype),
        "head": init(ks[1], (D, cfg.vocab_size), D),
        "layers": {
            "ln1_w": jnp.ones((L, D), dtype), "ln1_b": jnp.zeros((L, D), dtype),
            "ln2_w": jnp.ones((L, D), dtype), "ln2_b": jnp.zeros((L, D), dtype),
            "time_decay": jnp.zeros((L, A), jnp.float32) - 1.0,
            "time_first": jnp.zeros((L, A), jnp.float32),
            "mix_k": jnp.full((L, D), 0.5, dtype),
            "mix_v": jnp.full((L, D), 0.5, dtype),
            "mix_r": jnp.full((L, D), 0.5, dtype),
            "att_key": init(ks[2], (L, D, A), D),
            "att_value": init(ks[3], (L, D, A), D),
            "att_receptance": init(ks[4], (L, D, A), D),
            "att_output": init(ks[5], (L, A, D), A),
            "ffn_mix_k": jnp.full((L, D), 0.5, dtype),
            "ffn_mix_r": jnp.full((L, D), 0.5, dtype),
            "ffn_key": init(ks[6], (L, D, F), D),
            "ffn_receptance": init(ks[7], (L, D, D), D),
            "ffn_value": init(ks[8], (L, F, D), F),
        },
    }
    return params


def load_hf_params(model_dir: str, cfg: RwkvConfig, dtype=jnp.float32) -> dict:
    """HF ``RwkvForCausalLM`` safetensors layout.

    HF's ``rescale_every`` machinery (output projections divided by
    2^(i//rescale) AND hidden states halved periodically) is a balanced
    fp16-overflow trick whose net function is identity — this port runs
    the plain arithmetic in fp32/bf16, which is exactly equivalent."""
    from localai_tpu.engine.weights import _open_shards

    shards = _open_shards(model_dir)

    def get(name):
        for pref in ("", "rwkv."):
            if pref + name in shards:
                return np.asarray(shards[pref + name].get_tensor(pref + name))
        raise KeyError(name)

    L = cfg.num_layers
    bl = "blocks.{i}."
    at = bl + "attention."
    ff = bl + "feed_forward."

    def stack(fmt, transpose=False, squeeze=False):
        mats = []
        for i in range(L):
            m = get(fmt.format(i=i))
            if squeeze:
                m = m.reshape(-1)
            if transpose:
                m = m.T
            mats.append(m)
        return jnp.asarray(np.stack(mats), dtype)

    params = {
        "embed": jnp.asarray(get("embeddings.weight"), dtype),
        "pre_ln_w": jnp.asarray(get("blocks.0.pre_ln.weight"), dtype),
        "pre_ln_b": jnp.asarray(get("blocks.0.pre_ln.bias"), dtype),
        "out_ln_w": jnp.asarray(get("ln_out.weight"), dtype),
        "out_ln_b": jnp.asarray(get("ln_out.bias"), dtype),
        "head": jnp.asarray(get("head.weight").T, dtype),
        "layers": {
            "ln1_w": stack(bl + "ln1.weight"),
            "ln1_b": stack(bl + "ln1.bias"),
            "ln2_w": stack(bl + "ln2.weight"),
            "ln2_b": stack(bl + "ln2.bias"),
            "time_decay": jnp.asarray(np.stack(
                [get((at + "time_decay").format(i=i)).reshape(-1)
                 for i in range(L)]), jnp.float32),
            "time_first": jnp.asarray(np.stack(
                [get((at + "time_first").format(i=i)).reshape(-1)
                 for i in range(L)]), jnp.float32),
            "mix_k": stack(at + "time_mix_key", squeeze=True),
            "mix_v": stack(at + "time_mix_value", squeeze=True),
            "mix_r": stack(at + "time_mix_receptance", squeeze=True),
            "att_key": stack(at + "key.weight", transpose=True),
            "att_value": stack(at + "value.weight", transpose=True),
            "att_receptance": stack(at + "receptance.weight", transpose=True),
            "att_output": stack(at + "output.weight", transpose=True),
            "ffn_mix_k": stack(ff + "time_mix_key", squeeze=True),
            "ffn_mix_r": stack(ff + "time_mix_receptance", squeeze=True),
            "ffn_key": stack(ff + "key.weight", transpose=True),
            "ffn_receptance": stack(ff + "receptance.weight", transpose=True),
            "ffn_value": stack(ff + "value.weight", transpose=True),
        },
    }
    return params


def init_cache(cfg: RwkvConfig, num_slots: int, max_len: int, dtype=None):
    """Fixed-size per-slot state (fp32 — the wkv accumulators are
    precision-sensitive): att lanes [L, S, 4, D] = [prev_x, num, den, max]
    (max initialized to -1e38, the HF fresh-state value); ffn lane
    [L, S, 1, D] = [prev_x]."""
    L, D = cfg.num_layers, cfg.hidden_size
    att = jnp.zeros((L, num_slots, 4, D), jnp.float32)
    att = att.at[:, :, 3].set(_MAX_INIT)
    ffn = jnp.zeros((L, num_slots, 1, D), jnp.float32)
    return att, ffn


def _fresh_att_state(shape_like):
    fresh = jnp.zeros_like(shape_like)
    return fresh.at[..., 3, :].set(_MAX_INIT)


def _time_mixing(x, st, ly, cfg):
    """x [B, D]; st [B, 4, D] = [prev_x, num, den, max]. Returns
    (out [B, D], st). Exactly HF rwkv_linear_attention_cpu."""
    dt = x.dtype
    prev_x, num, den, mx = (st[:, 0].astype(dt),
                            st[:, 1].astype(jnp.float32),
                            st[:, 2].astype(jnp.float32),
                            st[:, 3].astype(jnp.float32))
    xk = x * ly["mix_k"] + prev_x * (1 - ly["mix_k"])
    xv = x * ly["mix_v"] + prev_x * (1 - ly["mix_v"])
    xr = x * ly["mix_r"] + prev_x * (1 - ly["mix_r"])
    r = jax.nn.sigmoid(xr @ _mat(ly["att_receptance"], dt))
    k = (xk @ _mat(ly["att_key"], dt)).astype(jnp.float32)
    v = (xv @ _mat(ly["att_value"], dt)).astype(jnp.float32)
    u = ly["time_first"].astype(jnp.float32)
    w = -jnp.exp(ly["time_decay"].astype(jnp.float32))
    # output: stabilized (num + e^{u+k} v) / (den + e^{u+k})
    max_out = jnp.maximum(mx, u + k)
    e1 = jnp.exp(mx - max_out)
    e2 = jnp.exp(u + k - max_out)
    wkv = (e1 * num + e2 * v) / (e1 * den + e2)
    # state advance: decay by e^w, absorb current k/v
    max_st = jnp.maximum(mx + w, k)
    e1s = jnp.exp(mx + w - max_st)
    e2s = jnp.exp(k - max_st)
    num = e1s * num + e2s * v
    den = e1s * den + e2s
    out = (r * wkv.astype(dt)) @ _mat(ly["att_output"], dt)
    st = jnp.stack([x.astype(jnp.float32), num, den, max_st], axis=1)
    return out, st


def _channel_mixing(x, st, ly, cfg):
    """x [B, D]; st [B, 1, D] = [prev_x]."""
    dt = x.dtype
    prev_x = st[:, 0].astype(dt)
    xk = x * ly["ffn_mix_k"] + prev_x * (1 - ly["ffn_mix_k"])
    xr = x * ly["ffn_mix_r"] + prev_x * (1 - ly["ffn_mix_r"])
    r = jax.nn.sigmoid(xr @ _mat(ly["ffn_receptance"], dt))
    k = jnp.square(jax.nn.relu(xk @ _mat(ly["ffn_key"], dt)))
    out = r * (k @ _mat(ly["ffn_value"], dt))
    return out, x.astype(jnp.float32)[:, None, :]


def _layer_scan(params, cfg, h, att, ffn, active=None):
    """h [B, D] through all layers; state updates masked where inactive."""

    def layer_fn(carry, inp):
        hc = carry
        ly, att_l, ffn_l = inp
        xa = _ln(hc, ly["ln1_w"], ly["ln1_b"], cfg.layer_norm_epsilon)
        out_a, natt = _time_mixing(xa, att_l, ly, cfg)
        hc = hc + out_a
        xf = _ln(hc, ly["ln2_w"], ly["ln2_b"], cfg.layer_norm_epsilon)
        out_f, nffn = _channel_mixing(xf, ffn_l, ly, cfg)
        hc = hc + out_f
        if active is not None:
            natt = jnp.where(active[:, None, None], natt, att_l)
            nffn = jnp.where(active[:, None, None], nffn, ffn_l)
        return hc, (natt, nffn)

    return jax.lax.scan(layer_fn, h, (dict(params["layers"]), att, ffn))


def _forward_token(params, cfg, tokens, att, ffn, active=None):
    h = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    h = _ln(h, params["pre_ln_w"], params["pre_ln_b"],
            cfg.layer_norm_epsilon)
    h, (att, ffn) = _layer_scan(params, cfg, h, att, ffn, active)
    h = _ln(h, params["out_ln_w"], params["out_ln_b"],
            cfg.layer_norm_epsilon)
    logits = (h.astype(jnp.float32)
              @ _mat(params["head"], jnp.float32).astype(jnp.float32))
    return logits, att, ffn


def engine_decode(params, cfg, tokens, lengths, active, att, ffn,
                  pos_offset=None):
    """Engine adapter: one decode step for all slots (state frozen where
    inactive). lengths/pos_offset unused — no positional encoding."""
    del lengths, pos_offset
    return _forward_token(params, cfg, tokens, att, ffn, active=active)


def prefill(params, cfg, tokens, seq_lens, att, ffn, slot_ids, start_pos,
            continued=False, mm_pos=None, mm_vec=None,
            return_all_logits=False, positions=None):
    """Engine adapter: ingest B prompts. Fresh rows (start_pos == 0)
    reset to the INIT state (zeros + wkv_max = -1e38); continued rows
    resume. Mirrors models/mamba.py:prefill."""
    assert mm_pos is None and positions is None, \
        "multimodal/positions are llama-family features"
    B, T = tokens.shape
    att_rows = jnp.take(att, slot_ids, axis=1)   # [L, B, 4, D]
    ffn_rows = jnp.take(ffn, slot_ids, axis=1)   # [L, B, 1, D]
    fresh = (jnp.asarray(start_pos) == 0)[None, :, None, None]
    att_rows = jnp.where(fresh, _fresh_att_state(att_rows), att_rows)
    ffn_rows = jnp.where(fresh, 0.0, ffn_rows)

    def step(carry, xs_t):
        att_r, ffn_r, last_h = carry
        tok, t = xs_t
        act = t < jnp.asarray(seq_lens)
        h = jnp.take(params["embed"], tok, axis=0).astype(cfg.dtype)
        h = _ln(h, params["pre_ln_w"], params["pre_ln_b"],
                cfg.layer_norm_epsilon)
        h, (att_r, ffn_r) = _layer_scan(params, cfg, h, att_r, ffn_r, act)
        is_last = (t == jnp.asarray(seq_lens) - 1)[:, None]
        last_h = jnp.where(is_last, h, last_h)
        return (att_r, ffn_r, last_h), (h if return_all_logits else None)

    last0 = jnp.zeros((B, cfg.hidden_size), cfg.dtype)
    (att_rows, ffn_rows, last_h), hs = jax.lax.scan(
        step, (att_rows, ffn_rows, last0),
        (jnp.asarray(tokens).T, jnp.arange(T, dtype=jnp.int32)))
    att = att.at[:, slot_ids].set(att_rows)
    ffn = ffn.at[:, slot_ids].set(ffn_rows)

    def head(h):
        h = _ln(h, params["out_ln_w"], params["out_ln_b"],
                cfg.layer_norm_epsilon)
        return (h.astype(jnp.float32)
                @ _mat(params["head"], jnp.float32).astype(jnp.float32))

    if return_all_logits:
        return head(hs.transpose(1, 0, 2)), att, ffn
    return head(last_h), att, ffn
