"""Latent-free image diffusion: conditional UNet + DDIM sampler, functional JAX.

Capability parity with the reference's image generation backends
(reference: backend/python/diffusers/backend.py:1-510 — GenerateImage RPC
with prompt/negative prompt, steps, seed, cfg scale, size; also the NCNN
stable-diffusion Go wrappers). Architecture is framework-native: a small
pixel-space UNet (two down/up stages with skips), sinusoidal timestep
embedding, and byte-level text conditioning (mean-pooled prompt embedding
added to the time embedding) with classifier-free guidance.

Checkpoints use this framework's safetensors layout (save_params /
load_params, same walker as models/tts.py); random init produces
structured noise fields, keeping the full RPC -> sampler -> PNG path real
in offline environments.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    image_size: int = 64
    channels: int = 3
    base_width: int = 64
    time_dim: int = 128
    text_vocab: int = 256
    num_steps_train: int = 1000
    dtype: Any = jnp.float32

    @staticmethod
    def from_json(path: str, dtype=jnp.float32) -> "DiffusionConfig":
        with open(path) as f:
            cfg = json.load(f)
        return DiffusionConfig(
            image_size=cfg.get("image_size", 64),
            channels=cfg.get("channels", 3),
            base_width=cfg.get("base_width", 64),
            time_dim=cfg.get("time_dim", 128),
            text_vocab=cfg.get("text_vocab", 256),
            num_steps_train=cfg.get("num_steps_train", 1000),
            dtype=dtype,
        )


def _conv_init(key, out_c, in_c, k=3):
    fan = in_c * k * k
    return (jax.random.normal(key, (out_c, in_c, k, k), jnp.float32)
            / np.sqrt(fan)).astype(jnp.float32)


def init_params(cfg: DiffusionConfig, key: jax.Array) -> dict:
    W = cfg.base_width
    ks = iter(jax.random.split(key, 32))

    def conv(out_c, in_c, k=3):
        return {"w": _conv_init(next(ks), out_c, in_c, k),
                "b": jnp.zeros((out_c,), jnp.float32)}

    def dense(out_d, in_d):
        return {"w": (jax.random.normal(next(ks), (in_d, out_d), jnp.float32)
                      / np.sqrt(in_d)),
                "b": jnp.zeros((out_d,), jnp.float32)}

    return {
        "text_embed": (jax.random.normal(next(ks), (cfg.text_vocab, cfg.time_dim),
                                         jnp.float32) / np.sqrt(cfg.time_dim)),
        "time_mlp1": dense(cfg.time_dim, cfg.time_dim),
        "time_mlp2": dense(cfg.time_dim, cfg.time_dim),
        "in_conv": conv(W, cfg.channels),
        "d1a": conv(W, W), "d1b": conv(W, W), "d1t": dense(W, cfg.time_dim),
        "down1": conv(W * 2, W),            # stride 2
        "d2a": conv(W * 2, W * 2), "d2b": conv(W * 2, W * 2),
        "d2t": dense(W * 2, cfg.time_dim),
        "down2": conv(W * 4, W * 2),        # stride 2
        "mid_a": conv(W * 4, W * 4), "mid_b": conv(W * 4, W * 4),
        "mid_t": dense(W * 4, cfg.time_dim),
        "up2": conv(W * 2, W * 4, k=3),     # after 2x resize
        "u2a": conv(W * 2, W * 4), "u2b": conv(W * 2, W * 2),
        "u2t": dense(W * 2, cfg.time_dim),
        "up1": conv(W, W * 2, k=3),
        "u1a": conv(W, W * 2), "u1b": conv(W, W),
        "u1t": dense(W, cfg.time_dim),
        "out_conv": conv(cfg.channels, W),
    }


def _conv2d(x, p, stride=1):
    return jax.lax.conv_general_dilated(
        x, p["w"], (stride, stride), "SAME",
        dimension_numbers=("NCHW", "OIHW", "NCHW")) + p["b"][None, :, None, None]


def _dense(x, p):
    return x @ p["w"] + p["b"]


def _time_embedding(t, dim):
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) * jnp.arange(half) / max(half - 1, 1))
    ang = t[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)


def _resblock(x, pa, pb, pt, temb):
    h = jax.nn.silu(_conv2d(x, pa))
    h = h + _dense(temb, pt)[:, :, None, None]
    h = jax.nn.silu(_conv2d(h, pb))
    return x + h if x.shape == h.shape else h


def unet(params: dict, cfg: DiffusionConfig, x: jax.Array, t: jax.Array,
         text_emb: jax.Array) -> jax.Array:
    """Predict noise eps. x [B,C,H,W]; t [B] float; text_emb [B, time_dim]."""
    temb = _time_embedding(t, cfg.time_dim) + text_emb
    temb = _dense(jax.nn.silu(_dense(temb, params["time_mlp1"])), params["time_mlp2"])

    h0 = _conv2d(x, params["in_conv"])
    h1 = _resblock(h0, params["d1a"], params["d1b"], params["d1t"], temb)
    d1 = jax.nn.silu(_conv2d(h1, params["down1"], stride=2))
    h2 = _resblock(d1, params["d2a"], params["d2b"], params["d2t"], temb)
    d2 = jax.nn.silu(_conv2d(h2, params["down2"], stride=2))
    m = _resblock(d2, params["mid_a"], params["mid_b"], params["mid_t"], temb)

    u2 = jax.image.resize(m, (m.shape[0], m.shape[1],
                              m.shape[2] * 2, m.shape[3] * 2), "nearest")
    u2 = jax.nn.silu(_conv2d(u2, params["up2"]))
    u2 = _resblock(jnp.concatenate([u2, h2], axis=1),
                   params["u2a"], params["u2b"], params["u2t"], temb)
    u1 = jax.image.resize(u2, (u2.shape[0], u2.shape[1],
                               u2.shape[2] * 2, u2.shape[3] * 2), "nearest")
    u1 = jax.nn.silu(_conv2d(u1, params["up1"]))
    u1 = _resblock(jnp.concatenate([u1, h1], axis=1),
                   params["u1a"], params["u1b"], params["u1t"], temb)
    return _conv2d(u1, params["out_conv"])


def text_embedding(params: dict, prompt: str, dim: int) -> jax.Array:
    """[1, time_dim] mean-pooled byte embedding (empty prompt = zeros,
    which doubles as the classifier-free-guidance unconditional branch)."""
    ids = list(prompt.encode("utf-8", errors="replace"))[:512]
    if not ids:
        return jnp.zeros((1, dim), jnp.float32)
    emb = jnp.take(params["text_embed"], jnp.asarray(ids, jnp.int32), axis=0)
    return jnp.mean(emb, axis=0, keepdims=True)


def _alphas(cfg: DiffusionConfig):
    betas = np.linspace(1e-4, 0.02, cfg.num_steps_train, dtype=np.float64)
    return np.cumprod(1.0 - betas)


@functools.lru_cache(maxsize=4)
def _jit_eps(cfg: DiffusionConfig):
    return jax.jit(lambda p, x, t, c, u, g: (
        unet(p, cfg, x, t, u) + g * (unet(p, cfg, x, t, c) - unet(p, cfg, x, t, u))))


def ddim_sample(params: dict, cfg: DiffusionConfig, prompt: str,
                negative_prompt: str = "", steps: int = 20, seed: int = 0,
                guidance: float = 7.5) -> np.ndarray:
    """DDIM (eta=0) sampling with classifier-free guidance.
    Returns uint8 [H, W, C]."""
    H = W = cfg.image_size
    key = jax.random.PRNGKey(seed & 0x7FFFFFFF)
    x = jax.random.normal(key, (1, cfg.channels, H, W), jnp.float32)
    abar = _alphas(cfg)
    ts = np.linspace(cfg.num_steps_train - 1, 0, max(steps, 1)).astype(np.int64)
    cond = text_embedding(params, prompt, cfg.time_dim)
    if negative_prompt:
        uncond = text_embedding(params, negative_prompt, cfg.time_dim)
    else:
        uncond = jnp.zeros_like(cond)
    eps_fn = _jit_eps(cfg)
    g = jnp.float32(guidance)

    for i, t in enumerate(ts):
        a_t = abar[t]
        a_prev = abar[ts[i + 1]] if i + 1 < len(ts) else 1.0
        eps = eps_fn(params, x, jnp.full((1,), float(t), jnp.float32), cond,
                     uncond, g)
        x0 = (x - np.sqrt(1 - a_t) * eps) / np.sqrt(a_t)
        x0 = jnp.clip(x0, -1.5, 1.5)
        x = np.sqrt(a_prev) * x0 + np.sqrt(1 - a_prev) * eps
    img = np.asarray(jnp.clip((x[0] + 1.0) * 127.5, 0, 255)).astype(np.uint8)
    return img.transpose(1, 2, 0)


def save_params(params: dict, cfg: DiffusionConfig, model_dir: str):
    from safetensors.numpy import save_file

    os.makedirs(model_dir, exist_ok=True)
    flat = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}{k}.", v)
        else:
            flat[prefix[:-1]] = np.asarray(node)

    walk("", params)
    save_file(flat, os.path.join(model_dir, "model.safetensors"))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump({
            "model_type": "localai_tpu_diffusion",
            "image_size": cfg.image_size, "channels": cfg.channels,
            "base_width": cfg.base_width, "time_dim": cfg.time_dim,
            "text_vocab": cfg.text_vocab,
            "num_steps_train": cfg.num_steps_train,
        }, f)


def load_params(model_dir: str, cfg: DiffusionConfig) -> dict:
    from safetensors.numpy import load_file

    flat = load_file(os.path.join(model_dir, "model.safetensors"))
    params: dict = {}
    for name, arr in flat.items():
        parts = name.split(".")
        node = params
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(arr, jnp.float32)
    return params
