"""MusicGen text-to-music in JAX (real SoundGeneration, VERDICT r3 #6).

Replaces the reference's transformers-musicgen backend
(backend/python/transformers-musicgen/backend.py:1-176 — MusicGen via
torch, duration + prompted generation) with a TPU-native port of the HF
`MusicgenForConditionalGeneration` layout:

  text prompt --T5 encoder--> states --MusicGen decoder (cross-attn,
  num_codebooks delay pattern)--> EnCodec codes --models/encodec.py-->
  waveform

The decoder runs as a jitted cached step (cross K/V precomputed, self
K/V cache carried), with classifier-free guidance as a batch-of-2.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.models import encodec as codec


# ---------------------------------------------------------------- configs

@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 768
    d_kv: int = 64
    d_ff: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"

    @staticmethod
    def from_hf_config(c: dict) -> "T5Config":
        return T5Config(
            vocab_size=c.get("vocab_size", 32128),
            d_model=c.get("d_model", 768),
            d_kv=c.get("d_kv", 64),
            d_ff=c.get("d_ff", 3072),
            num_layers=c.get("num_layers", 12),
            num_heads=c.get("num_heads", 12),
            relative_attention_num_buckets=c.get(
                "relative_attention_num_buckets", 32),
            relative_attention_max_distance=c.get(
                "relative_attention_max_distance", 128),
            layer_norm_epsilon=c.get("layer_norm_epsilon", 1e-6),
            feed_forward_proj=c.get("feed_forward_proj", "relu"),
        )


@dataclasses.dataclass(frozen=True)
class MusicgenConfig:
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    ffn_dim: int = 4096
    vocab_size: int = 2048          # EnCodec codebook size
    num_codebooks: int = 4
    max_position_embeddings: int = 2048
    activation: str = "gelu"
    audio_channels: int = 1
    t5: T5Config = dataclasses.field(default_factory=T5Config)
    enc: codec.EncodecConfig = dataclasses.field(
        default_factory=codec.EncodecConfig)
    frame_rate: int = 50

    @property
    def pad_token_id(self) -> int:   # the delay-pattern BOS/pad code
        return self.vocab_size

    @staticmethod
    def from_hf_config(c: dict) -> "MusicgenConfig":
        d = c.get("decoder", c)
        ec = c.get("audio_encoder", {})
        up = ec.get("upsampling_ratios", (8, 5, 4, 2))
        sr = ec.get("sampling_rate", 32000)
        return MusicgenConfig(
            hidden_size=d.get("hidden_size", 1024),
            num_layers=d.get("num_hidden_layers", 24),
            num_heads=d.get("num_attention_heads", 16),
            ffn_dim=d.get("ffn_dim", 4096),
            vocab_size=d.get("vocab_size", 2048),
            num_codebooks=d.get("num_codebooks", 4),
            max_position_embeddings=d.get("max_position_embeddings", 2048),
            activation=d.get("activation_function", "gelu"),
            audio_channels=d.get("audio_channels", 1),
            t5=T5Config.from_hf_config(c.get("text_encoder", {})),
            enc=codec.EncodecConfig.from_hf_config(ec),
            frame_rate=ec.get("frame_rate",
                              int(round(sr / float(np.prod(up))))),
        )

    @staticmethod
    def from_json(path: str) -> "MusicgenConfig":
        with open(path) as f:
            return MusicgenConfig.from_hf_config(json.load(f))


# ---------------------------------------------------------------- T5 encoder

def _t5_ln(x, w, eps):
    """T5LayerNorm: rms-style, no mean subtraction, no bias."""
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps) * w).astype(x.dtype)


def _rel_bucket(rel, num_buckets, max_distance):
    """HF T5 _relative_position_bucket (bidirectional)."""
    nb = num_buckets // 2
    buckets = jnp.where(rel > 0, nb, 0)
    n = jnp.abs(rel)
    max_exact = nb // 2
    is_small = n < max_exact
    large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-9)
        / math.log(max_distance / max_exact) * (nb - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, nb - 1)
    return buckets + jnp.where(is_small, n, large)


def t5_encode(params: dict, cfg: T5Config, tokens, mask) -> jax.Array:
    """tokens [B, T] int32, mask [B, T] -> encoder states [B, T, D]."""
    B, T = tokens.shape
    H, dkv = cfg.num_heads, cfg.d_kv
    x = jnp.take(params["embed"], tokens, axis=0)

    pos = jnp.arange(T, dtype=jnp.int32)
    rel = pos[None, :] - pos[:, None]            # memory - query
    bucket = _rel_bucket(rel, cfg.relative_attention_num_buckets,
                         cfg.relative_attention_max_distance)
    # bias table only exists in block 0 and is shared by all blocks
    bias = jnp.take(params["rel_bias"], bucket, axis=0)      # [T, T, H]
    bias = bias.transpose(2, 0, 1)[None]                     # [1, H, T, T]
    neg = (1.0 - mask[:, None, None, :].astype(jnp.float32)) * -1e9
    bias = bias + neg

    def layer(x, ly):
        h = _t5_ln(x, ly["attn_norm"], cfg.layer_norm_epsilon)
        q = (h @ ly["wq"]).reshape(B, T, H, dkv)
        k = (h @ ly["wk"]).reshape(B, T, H, dkv)
        v = (h @ ly["wv"]).reshape(B, T, H, dkv)
        # T5 attention has NO 1/sqrt(d) scaling (folded into init)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) + bias
        p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        a = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, T, H * dkv)
        x = x + a @ ly["wo"]
        h = _t5_ln(x, ly["mlp_norm"], cfg.layer_norm_epsilon)
        if "wi_1" in ly:   # gated act (flan-style)
            h = jax.nn.gelu(h @ ly["wi_0"], approximate=False) * (h @ ly["wi_1"])
        else:
            h = jax.nn.relu(h @ ly["wi"])
        x = x + h @ ly["wo_ff"]
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    return _t5_ln(x, params["final_norm"], cfg.layer_norm_epsilon)


# ------------------------------------------------------------- decoder LM

def sinusoidal_positions(n: int, dim: int) -> np.ndarray:
    """Musicgen sinusoids: [cos | sin] concatenation (tensor2tensor)."""
    half = dim // 2
    freq = np.exp(np.arange(half, dtype=np.float64)
                  * -(math.log(10000.0) / (half - 1)))
    ang = np.arange(n, dtype=np.float64)[:, None] * freq[None, :]
    emb = np.concatenate([np.cos(ang), np.sin(ang)], axis=1)
    if dim % 2 == 1:
        emb = np.concatenate([emb, np.zeros((n, 1))], axis=1)
    return emb.astype(np.float32)


def _attn(q, k, v, H, mask=None):
    """q [B,Tq,D], k/v [B,Tk,D] -> [B,Tq,D]; scaled dot-product."""
    B, Tq, D = q.shape
    hd = D // H
    q = q.reshape(B, Tq, H, hd) * (hd ** -0.5)
    k = k.reshape(B, -1, H, hd)
    v = v.reshape(B, -1, H, hd)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    if mask is not None:
        s = s + mask
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, Tq, D)


def _ln(x, w, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * w + b


def cross_kv(params: dict, cfg: MusicgenConfig, enc_states):
    """Precompute per-layer cross-attention K/V: ([L,B,Tk,D], [L,B,Tk,D])."""
    ls = params["layers"]
    return jax.lax.map(
        lambda wkv: (enc_states @ wkv[0], enc_states @ wkv[1]),
        (ls["xwk"], ls["xwv"]))


def decode_step(params: dict, cfg: MusicgenConfig, codes, pos, xk, xv,
                enc_mask, cache_k, cache_v):
    """One decoder step.

    codes [B, nq] int32 (previous frame's token per codebook, delay
    pattern already applied; pad_token_id = BOS row of the embeddings);
    pos [] int32; xk/xv [L, B, Tk, D]; enc_mask [B, Tk];
    cache_k/v [L, B, Tmax, D]. Returns (logits [B, nq, V], ck, cv).
    """
    B = codes.shape[0]
    D = cfg.hidden_size
    H = cfg.num_heads
    # sum of per-codebook embeddings (each table has vocab+1 rows; row
    # vocab == the delay-pattern pad/BOS token)
    x = 0.0
    emb = params["embed"]                      # [nq, V+1, D]
    for k in range(cfg.num_codebooks):
        x = x + jnp.take(emb[k], codes[:, k], axis=0)
    x = x[:, None, :] + params["pos_table"][pos][None, None, :]

    Tmax = cache_k.shape[2]
    neg_enc = (1.0 - enc_mask[:, None, None, :].astype(jnp.float32)) * -1e9

    def layer_fn(x, inp):
        ly, ck_l, cv_l, li = inp
        h = _ln(x, ly["norm1_w"], ly["norm1_b"])
        q = h @ ly["wq"]
        k = h @ ly["wk"]
        v = h @ ly["wv"]
        ck_l = jax.lax.dynamic_update_slice(ck_l, k, (0, pos, 0))
        cv_l = jax.lax.dynamic_update_slice(cv_l, v, (0, pos, 0))
        valid = (jnp.arange(Tmax) <= pos)[None, None, None, :]
        mask = jnp.where(valid, 0.0, -1e9)
        a = _attn(q, ck_l, cv_l, H, mask)
        x = x + a @ ly["wo"]
        h = _ln(x, ly["norm2_w"], ly["norm2_b"])
        a = _attn(h @ ly["xwq"], xk[li], xv[li], H, neg_enc)
        x = x + a @ ly["xwo"]
        h = _ln(x, ly["norm3_w"], ly["norm3_b"])
        h = jax.nn.gelu(h @ ly["fc1"], approximate=False)
        x = x + h @ ly["fc2"]
        return x, (ck_l, cv_l)

    layers = dict(params["layers"])
    li = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    x, (cache_k, cache_v) = jax.lax.scan(
        layer_fn, x, (layers, cache_k, cache_v, li))
    x = _ln(x, params["final_norm_w"], params["final_norm_b"])
    # lm_heads [nq, V, D]; x [B, 1, D]
    logits = jnp.einsum("bd,nvd->bnv", x[:, 0, :], params["lm_heads"])
    return logits, cache_k, cache_v


# ---------------------------------------------------------------- loading

def load_hf_params(model_dir: str, cfg: MusicgenConfig) -> dict:
    from localai_tpu.engine.weights import _open_shards

    shards = _open_shards(model_dir)
    tensors = {n: np.asarray(h.get_tensor(n)) for n, h in shards.items()}
    return params_from_tensors(tensors, cfg)


def params_from_tensors(tensors: dict, cfg: MusicgenConfig) -> dict:
    f32 = lambda a: jnp.asarray(a, jnp.float32)  # noqa: E731

    def get(name):
        return tensors[name]

    # ---- T5 text encoder ----
    t5 = cfg.t5
    tl = {"attn_norm": [], "wq": [], "wk": [], "wv": [], "wo": [],
          "mlp_norm": [], "wo_ff": []}
    gated = "gated" in t5.feed_forward_proj
    if gated:
        tl["wi_0"], tl["wi_1"] = [], []
    else:
        tl["wi"] = []
    for i in range(t5.num_layers):
        b = f"text_encoder.encoder.block.{i}.layer"
        tl["attn_norm"].append(get(f"{b}.0.layer_norm.weight"))
        tl["wq"].append(get(f"{b}.0.SelfAttention.q.weight").T)
        tl["wk"].append(get(f"{b}.0.SelfAttention.k.weight").T)
        tl["wv"].append(get(f"{b}.0.SelfAttention.v.weight").T)
        tl["wo"].append(get(f"{b}.0.SelfAttention.o.weight").T)
        tl["mlp_norm"].append(get(f"{b}.1.layer_norm.weight"))
        if gated:
            tl["wi_0"].append(get(f"{b}.1.DenseReluDense.wi_0.weight").T)
            tl["wi_1"].append(get(f"{b}.1.DenseReluDense.wi_1.weight").T)
        else:
            tl["wi"].append(get(f"{b}.1.DenseReluDense.wi.weight").T)
        tl["wo_ff"].append(get(f"{b}.1.DenseReluDense.wo.weight").T)
    t5_params = {
        "embed": f32(get("text_encoder.shared.weight")),
        "rel_bias": f32(get(
            "text_encoder.encoder.block.0.layer.0.SelfAttention"
            ".relative_attention_bias.weight")),
        "final_norm": f32(get("text_encoder.encoder.final_layer_norm.weight")),
        "layers": {k: f32(np.stack(v)) for k, v in tl.items()},
    }

    # ---- MusicGen decoder ----
    nq, V, D = cfg.num_codebooks, cfg.vocab_size, cfg.hidden_size
    embed = np.stack([get(f"decoder.model.decoder.embed_tokens.{k}.weight")
                      for k in range(nq)])
    heads = np.stack([get(f"decoder.lm_heads.{k}.weight")
                      for k in range(nq)])                  # [nq, V, D]
    dl = {}
    names = {
        "norm1_w": "self_attn_layer_norm.weight",
        "norm1_b": "self_attn_layer_norm.bias",
        "norm2_w": "encoder_attn_layer_norm.weight",
        "norm2_b": "encoder_attn_layer_norm.bias",
        "norm3_w": "final_layer_norm.weight",
        "norm3_b": "final_layer_norm.bias",
    }
    mats = {
        "wq": "self_attn.q_proj.weight", "wk": "self_attn.k_proj.weight",
        "wv": "self_attn.v_proj.weight", "wo": "self_attn.out_proj.weight",
        "xwq": "encoder_attn.q_proj.weight",
        "xwk": "encoder_attn.k_proj.weight",
        "xwv": "encoder_attn.v_proj.weight",
        "xwo": "encoder_attn.out_proj.weight",
        "fc1": "fc1.weight", "fc2": "fc2.weight",
    }
    for out, nm in names.items():
        dl[out] = f32(np.stack(
            [get(f"decoder.model.decoder.layers.{i}.{nm}")
             for i in range(cfg.num_layers)]))
    for out, nm in mats.items():
        dl[out] = f32(np.stack(
            [get(f"decoder.model.decoder.layers.{i}.{nm}").T
             for i in range(cfg.num_layers)]))
    dec_params = {
        "embed": f32(embed),
        "lm_heads": f32(heads),
        "pos_table": f32(sinusoidal_positions(
            cfg.max_position_embeddings, D)),
        "final_norm_w": f32(get("decoder.model.decoder.layer_norm.weight")),
        "final_norm_b": f32(get("decoder.model.decoder.layer_norm.bias")),
        "layers": dl,
    }

    enc_params = codec.load_hf_params(tensors, cfg.enc,
                                      prefix="audio_encoder.")
    return {"t5": t5_params, "decoder": dec_params, "encodec": enc_params}


# -------------------------------------------------------------- generation

def generate(params: dict, cfg: MusicgenConfig, text_tokens, text_mask,
             frames: int, temperature: float = 1.0, top_k: int = 250,
             guidance_scale: float = 3.0, seed: int = 0):
    """Text-conditioned generation -> waveform [samples] float32.

    Mirrors the reference backend's semantics (duration -> frames at the
    codec frame rate; sampled with top-k, classifier-free guidance).
    """
    nq = cfg.num_codebooks
    B = 1
    enc = t5_encode(params["t5"], cfg.t5, text_tokens, text_mask)
    if guidance_scale and guidance_scale != 1.0:
        # CFG: row 0 conditioned, row 1 "unconditioned" (text fully
        # masked — HF zeroes the attention mask for the null branch)
        enc = jnp.concatenate([enc, enc], axis=0)
        mask2 = jnp.concatenate([text_mask,
                                 jnp.zeros_like(text_mask)], axis=0)
        B = 2
    else:
        mask2 = text_mask
    xk, xv = cross_kv(params["decoder"], cfg, enc)

    L, D = cfg.num_layers, cfg.hidden_size
    total = frames + nq            # BOS column + delayed tail
    ck = jnp.zeros((L, B, total, D), jnp.float32)
    cv = jnp.zeros((L, B, total, D), jnp.float32)

    step_fn = jax.jit(
        lambda codes, pos, ck, cv: decode_step(
            params["decoder"], cfg, codes, pos, xk, xv, mask2, ck, cv))

    pad = cfg.pad_token_id
    seq = np.full((nq, total), pad, np.int32)
    key = jax.random.PRNGKey(seed)
    cur = np.full((B, nq), pad, np.int32)
    for t in range(total - 1):
        logits, ck, cv = step_fn(jnp.asarray(cur), jnp.int32(t), ck, cv)
        lg = np.asarray(logits, np.float32)      # [B, nq, V]
        if B == 2:
            lg = lg[1] + guidance_scale * (lg[0] - lg[1])  # [nq, V]
        else:
            lg = lg[0]
        key, sub = jax.random.split(key)
        nxt = _sample_row(lg, temperature, top_k, sub)
        # delay pattern: codebook k only emits real tokens for
        # t+1 in [k+1, k+1+frames); otherwise the pad/BOS token
        for k in range(nq):
            tt = t + 1
            if k + 1 <= tt < k + 1 + frames:
                seq[k, tt] = nxt[k]
            else:
                seq[k, tt] = pad
        cur = np.broadcast_to(seq[:, t + 1], (B, nq)).copy()
    # revert the delay: codes[k, f] = seq[k, f + k + 1]
    codes = np.stack([seq[k, k + 1:k + 1 + frames] for k in range(nq)])
    wav = codec.decode(params["encodec"], cfg.enc, codes[:, None, :])
    return np.asarray(wav[0, 0], np.float32)


def _sample_row(logits, temperature, top_k, key):
    """logits [nq, V] -> [nq] sampled ids (top-k + temperature)."""
    if temperature <= 0:
        return np.asarray(jnp.argmax(logits, axis=-1), np.int32)
    lg = logits / max(temperature, 1e-6)
    if top_k and top_k < lg.shape[-1]:
        kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
        lg = jnp.where(lg < kth, -1e9, lg)
    return np.asarray(jax.random.categorical(key, lg, axis=-1), np.int32)
