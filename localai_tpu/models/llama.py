"""Llama-family decoder (Llama 2/3/3.1, Mistral, Qwen2-style) in functional JAX.

TPU-first design notes:
  * Parameters are a plain pytree with all transformer layers STACKED on a
    leading axis so the forward pass is a single ``lax.scan`` — one trace,
    one compile, O(1) HLO size in depth.
  * All shapes are static; prefill uses bucketed sequence lengths and decode
    is a fixed [num_slots] batch so XLA compiles each bucket exactly once.
  * Sharding is expressed with ``jax.sharding.PartitionSpec`` per leaf (see
    localai_tpu/parallel/sharding.py); attention heads and MLP intermediate
    are split on the "tp" mesh axis, batch/slots on "dp".
  * GQA (num_kv_heads < num_heads) native; KV cache layout is
    [layers, slots, max_len, kv_heads, head_dim] which keeps the decode
    attention contraction MXU-friendly and the per-slot cache rows
    contiguous in HBM.

Capability parity target: the reference's main LLM engine is llama.cpp
behind a gRPC server (reference: backend/cpp/llama/grpc-server.cpp); this
module plays the role of llama.cpp's forward pass (llama_decode) for the
TPU engine in localai_tpu/engine/.
"""

from __future__ import annotations

import dataclasses
import json
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from localai_tpu.ops.rope import apply_rope, rope_frequencies
from localai_tpu.ops.norms import rms_norm
from localai_tpu.ops import kvcache
from localai_tpu.ops.attention import (
    causal_attention,
    decode_attention,
    decode_attention_append,
    mixed_prefill_attention,
)


def _decode_attn_mode() -> str:
    """LOCALAI_DECODE_ATTN: scatter (default, fastest measured on the
    serving chip) | append | pallas."""
    import os

    return os.environ.get("LOCALAI_DECODE_ATTN", "scatter")


def _pallas_decode() -> bool:
    """Use the Pallas decode-attention kernel on real TPU backends (the
    jnp path suffers XLA relayout copies there — see ops/pallas/
    decode_attention.py). CPU (tests, virtual meshes) uses the jnp
    reference implementation."""
    import os

    if os.environ.get("LOCALAI_NO_PALLAS", "") == "1":
        return False
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:  # pragma: no cover
        return False


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    rope_theta: float = 10000.0
    rope_scaling_type: str = "none"  # none | linear | yarn | llama3
    rope_scaling_factor: float = 1.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_position: int = 8192
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    attention_bias: bool = False
    dtype: Any = jnp.bfloat16

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    @staticmethod
    def from_hf_config(cfg: dict, dtype=jnp.bfloat16) -> "LlamaConfig":
        """Build from a HuggingFace ``config.json`` dict (llama/mistral/qwen2)."""
        rope_scaling = cfg.get("rope_scaling") or {}
        rs_type = rope_scaling.get("rope_type", rope_scaling.get("type", "none")) or "none"
        return LlamaConfig(
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            num_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
            head_dim=cfg.get("head_dim"),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rope_scaling_type=rs_type,
            rope_scaling_factor=rope_scaling.get("factor", 1.0),
            rope_low_freq_factor=rope_scaling.get("low_freq_factor", 1.0),
            rope_high_freq_factor=rope_scaling.get("high_freq_factor", 4.0),
            rope_original_max_position=rope_scaling.get(
                "original_max_position_embeddings", cfg.get("max_position_embeddings", 8192)
            ),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
            max_position_embeddings=cfg.get("max_position_embeddings", 4096),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            attention_bias=cfg.get("attention_bias", False),
            dtype=dtype,
        )

    @staticmethod
    def from_json(path: str, dtype=jnp.bfloat16) -> "LlamaConfig":
        with open(path) as f:
            return LlamaConfig.from_hf_config(json.load(f), dtype=dtype)


def init_params(cfg: LlamaConfig, key: jax.Array, dtype=None) -> dict:
    """Random-init parameter pytree (layers stacked on axis 0)."""
    dtype = dtype or cfg.dtype
    hd = cfg.head_dim_
    L, D, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    H, KV = cfg.num_heads, cfg.num_kv_heads
    keys = jax.random.split(key, 10)

    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan_in)).astype(dtype)

    params = {
        "embed": init(keys[0], (cfg.vocab_size, D), D),
        "layers": {
            "attn_norm": jnp.ones((L, D), dtype),
            "wq": init(keys[1], (L, D, H * hd), D),
            "wk": init(keys[2], (L, D, KV * hd), D),
            "wv": init(keys[3], (L, D, KV * hd), D),
            "wo": init(keys[4], (L, H * hd, D), H * hd),
            "mlp_norm": jnp.ones((L, D), dtype),
            "w_gate": init(keys[5], (L, D, F), D),
            "w_up": init(keys[6], (L, D, F), D),
            "w_down": init(keys[7], (L, F, D), F),
        },
        "final_norm": jnp.ones((D,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = init(keys[8], (D, cfg.vocab_size), D)
    return params


# the {q, s} int8 contract is shared by every family — see ops/quant.py
from localai_tpu.ops.quant import mat as _mat  # noqa: E402


def _embed_rows(embed, tokens, dtype):
    """Token-embedding lookup; int8 tables dequantize AFTER the gather."""
    if isinstance(embed, dict):
        rows = jnp.take(embed["q"], tokens, axis=0).astype(jnp.float32)
        return (rows * embed["s"]).astype(dtype)
    return jnp.take(embed, tokens, axis=0).astype(dtype)


def quantize_params(params: dict, bits: int = 8, group: int = 128) -> dict:
    """Weight-only quantization for every matmul weight (norms stay
    as-is). Capability parity: the reference serves quantized GGUF
    (Q4/Q8) by default; these are the TPU-native analogues — the MXU
    consumes dequantized tiles while HBM traffic halves (int8) or
    quarters (int4) vs bf16.

    bits=8: per-out-channel symmetric int8 everywhere.
    bits=4: group-128 symmetric int4 for the LAYER matmuls (~85% of an
    8B's weight bytes) while embed/lm_head stay int8 — the embedding
    gather dequantizes row-wise (grouped scales don't compose with it)
    and the unembed is the quality-critical matmul."""
    import functools

    from localai_tpu.ops.quant import quantize_weight, quantize_weight_int4

    quant_names = {"embed", "lm_head", "wq", "wk", "wv", "wo",
                   "w_gate", "w_up", "w_down"}
    q = (functools.partial(quantize_weight_int4, group=group)
         if bits == 4 else quantize_weight)

    out = {}
    for name, leaf in params.items():
        if name == "layers":
            out[name] = {k: (q(v) if k in quant_names else v)
                         for k, v in leaf.items()}
        elif name in quant_names:
            out[name] = quantize_weight(leaf) if bits == 4 else q(leaf)
        else:
            out[name] = leaf
    return out


def dequantize_params(params: dict, dtype=jnp.bfloat16) -> dict:
    """Inverse of quantize_params: int8 {q, s} leaves back to dense float
    (used by the train step — gradients need float leaves)."""
    def dq(leaf):
        if isinstance(leaf, dict) and set(leaf) == {"q", "s"}:
            return _mat(leaf, dtype)
        return leaf

    out = {}
    for name, leaf in params.items():
        if name == "layers":
            out[name] = {k: dq(v) for k, v in leaf.items()}
        else:
            out[name] = dq(leaf)
    return out


def _project_qkv(x, layer, cfg: LlamaConfig):
    """x: [B, T, D] -> q [B,T,H,hd], k/v [B,T,KV,hd]."""
    B, T, _ = x.shape
    hd = cfg.head_dim_
    dt = x.dtype
    q = jnp.einsum("btd,dh->bth", x, _mat(layer["wq"], dt)).reshape(B, T, cfg.num_heads, hd)
    k = jnp.einsum("btd,dh->bth", x, _mat(layer["wk"], dt)).reshape(B, T, cfg.num_kv_heads, hd)
    v = jnp.einsum("btd,dh->bth", x, _mat(layer["wv"], dt)).reshape(B, T, cfg.num_kv_heads, hd)
    return q, k, v


def _mlp(x, layer):
    dt = x.dtype
    gate = jnp.einsum("btd,df->btf", x, _mat(layer["w_gate"], dt))
    up = jnp.einsum("btd,df->btf", x, _mat(layer["w_up"], dt))
    return jnp.einsum("btf,fd->btd", jax.nn.silu(gate) * up,
                      _mat(layer["w_down"], dt))


def _unembed(x, params, cfg: LlamaConfig):
    if cfg.tie_word_embeddings:
        w = _mat(params["embed"], x.dtype).T
    else:
        w = _mat(params["lm_head"], x.dtype)
    return jnp.einsum("btd,dv->btv", x, w).astype(jnp.float32)


def prefill(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,      # [B, T] int32, right-padded
    seq_lens: jax.Array,    # [B] int32 true lengths
    cache_k: jax.Array,     # [L, S, C, KV, hd]
    cache_v: jax.Array,
    slot_ids: jax.Array,    # [B] int32 cache slots to fill
    start_pos: jax.Array,   # [B] int32 position offset (nonzero = continued prefix)
    continued: bool = False,  # STATIC: True when any start_pos may be nonzero
    mm_pos: Optional[jax.Array] = None,   # [B, P] chunk-relative positions
    mm_vec: Optional[jax.Array] = None,   # [B, P, D] injected embeddings
    return_all_logits: bool = False,      # STATIC: logits for every position
    positions: Optional[jax.Array] = None,  # [B, T] RoPE position override
):
    """Process full prompts, write KV into the cache slots, return last-token logits.

    ``continued`` selects the attention path at trace time: fresh prompts
    attend chunk-locally (cheap); continued chunks attend through the cache
    rows with absolute-position masking. Returns (logits [B, V] at position
    seq_lens-1, cache_k, cache_v).

    mm_pos/mm_vec implement LLaVA-style multimodal injection (reference:
    grpc-server.cpp:1157-1180,1425-1440): projected image-patch embeddings
    replace the token embeddings at the given chunk-relative positions.
    Inactive entries must use a LARGE positive sentinel (>= T) so the
    scatter's mode="drop" discards them — negative indices would WRAP.

    INVARIANT (enforced by the engine scheduler, not checkable in-jit):
    start_pos + T <= cache capacity C. Out-of-range rows are dropped by
    the KV scatter (mode="drop"), i.e. silently lost, not clamped.
    """
    B, T = tokens.shape
    if positions is None:
        positions = start_pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    sin, cos = rope_frequencies(cfg, positions)
    x = _embed_rows(params["embed"], tokens, cfg.dtype)
    if mm_pos is not None:
        bidx = jnp.arange(B, dtype=jnp.int32)[:, None] * jnp.ones_like(mm_pos)
        x = x.at[bidx, mm_pos].set(mm_vec.astype(cfg.dtype), mode="drop")
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < seq_lens[:, None]  # [B, T]

    def layer_fn(carry, layer):
        x, ck, cv = carry
        li = layer.pop("_idx")
        h = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _project_qkv(h, layer, cfg)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        if continued:
            # continued prefix: committed keys live in the cache. Rows are
            # read BEFORE this chunk's scatter (attention combines them
            # with the in-register chunk keys) — reading the same-step
            # scattered rows forces XLA to materialize a full layer copy
            # (measured +8 ms/step at decode; same hazard here). int8
            # caches pass the {"q","s"} rows straight through — the
            # attention op folds scales without a dequantized copy.
            k_rows = kvcache.gather_layer_rows(kvcache.layer(ck, li), slot_ids)
            v_rows = kvcache.gather_layer_rows(kvcache.layer(cv, li), slot_ids)
            if not kvcache.is_quant(k_rows):
                k_rows = k_rows.astype(cfg.dtype)
                v_rows = v_rows.astype(cfg.dtype)
            attn = mixed_prefill_attention(q, k, v, k_rows, v_rows,
                                           start_pos, seq_lens, cfg.q_per_kv)
        else:
            attn = causal_attention(q, k, v, valid, cfg.q_per_kv)
        # write this layer's K/V for all B prompts into their slots with ONE
        # batched scatter (ck[li, slot_ids[b], start_pos[b]+t] = k[b, t]) —
        # a python loop of per-prompt dynamic_update_slices serializes B*2
        # updates per layer and dominated batched-prefill time. Duplicate
        # slot entries (engine batch padding) write identical rows.
        rows = slot_ids[:, None] * jnp.ones((1, T), jnp.int32)              # [B, T]
        cols = start_pos[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B, T]
        ck = kvcache.scatter_prefill(ck, li, rows, cols, k)
        cv = kvcache.scatter_prefill(cv, li, rows, cols, v)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1), _mat(layer["wo"], x.dtype))
        h = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(h, layer)
        return (x, ck, cv), None

    layers = dict(params["layers"])
    layers["_idx"] = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    (x, cache_k, cache_v), _ = jax.lax.scan(layer_fn, (x, cache_k, cache_v), layers)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    # gather hidden state at the last valid position of each prompt
    if return_all_logits:
        # [B, T, V] — used by speculative verification (every draft
        # position needs the target's next-token distribution)
        return _unembed(x, params, cfg), cache_k, cache_v
    last = jnp.take_along_axis(x, (seq_lens - 1)[:, None, None].astype(jnp.int32), axis=1)
    logits = _unembed(last, params, cfg)[:, 0, :]
    return logits, cache_k, cache_v


def _ragged_pallas_ok(lck, N: int, cfg: LlamaConfig) -> bool:
    """Use the Pallas ragged-prefill kernel for this pack? Real TPU
    backend, plain-float PAGED cache, and ragged_kernel_plan finds a
    (qb, pkb) blocking. The kernel blocks queries per segment, so its
    scratch is per-q-block — pack LENGTH no longer disqualifies a pack
    (the old whole-pack scratch gate bailed above ~1k tokens at 8B head
    shapes)."""
    from localai_tpu.ops.pallas.ragged_prefill import ragged_kernel_plan

    if not (_pallas_decode() and kvcache.is_paged(lck)
            and not kvcache.is_quant(lck)):
        return False
    return ragged_kernel_plan(N, cfg.num_kv_heads, cfg.q_per_kv,
                              cfg.head_dim_) is not None


def ragged_kernel_shape_fallback(cache_k, N: int, cfg: LlamaConfig) -> bool:
    """Would a continued [N]-token pack leave the Pallas kernel path for
    SHAPE reasons? The engine counts these per packed dispatch
    (metrics()["packed_prefill"]["kernel_fallback"]) so a regression of
    the long-pack cliff is observable. Deliberately platform- and
    dtype-independent: int8 scales and contiguous layouts are static
    config choices routed to the jnp path by design, not a
    length-dependent cliff — counting them would bury the signal (and
    make the CPU-CI zero-fallback gate meaningless)."""
    from localai_tpu.ops.pallas.ragged_prefill import ragged_kernel_plan

    lck = kvcache.layer(cache_k, 0)
    if not kvcache.is_paged(lck) or kvcache.is_quant(lck):
        return False
    return ragged_kernel_plan(N, cfg.num_kv_heads, cfg.q_per_kv,
                              cfg.head_dim_) is None


def ragged_prefill(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,      # [N] int32 packed prompt tokens (pads 0)
    positions: jax.Array,   # [N] int32 absolute cache position (pads: C)
    seg_of: jax.Array,      # [N] int32 segment per token (pads: sentinel)
    seg_slots: jax.Array,   # [B] int32 slot per segment (pads: sentinel)
    seg_start: jax.Array,   # [B] int32 committed rows per segment
    seg_off: jax.Array,     # [B] int32 pack offset of each segment
    seg_len: jax.Array,     # [B] int32 tokens in each segment (pads: 0)
    cache_k: jax.Array,
    cache_v: jax.Array,
    continued: bool = False,  # STATIC: True when any seg_start may be > 0
    rope_positions: Optional[jax.Array] = None,  # [N] RoPE override
    comm_overlap: bool = False,  # STATIC: TokenWeave halved-pack overlap
):
    """RAGGED PACKED PREFILL: process the prompt tails of up to B slots
    as ONE [N]-token batch — per-segment causal self-attention plus
    (``continued`` only) attention over each slot's committed cache
    rows, with the new KV rows written through every token's own slot's
    page table in one ragged scatter (ops/kvcache.py::scatter_ragged).

    ``rope_positions`` decouples rotation from placement for
    self-extend segments: the cache position (``positions``) drives the
    KV scatter while compressed group-attention positions drive RoPE —
    committed rows were already re-rotated in place by the engine, so
    attention itself stays position-table-free. ``comm_overlap``
    (STATIC) splits the pack in two around each layer's out-projection
    and MLP so their contraction-sharded matmuls become independent
    matmul + all-reduce chains XLA can interleave on a tp mesh
    (parallel/sharding.py::overlap_halves; bit-exact, so greedy output
    is byte-identical either way).

    This is the reference's llama_batch packing (engine.py module doc:
    grpc-server.cpp:1671+ packs prompt chunks of all slots into one
    batch) expressed TPU-natively: the pack pads only to a small set of
    TOTAL-token buckets, so a tick's worth of ragged prompt tails costs
    one dispatch and near-zero pad compute instead of one padded
    per-slot bucket each (see engine.py packed-prefill scheduling).

    Returns (logits [B, V] at each segment's last packed token,
    cache_k, cache_v). Pad segments (seg_len == 0) produce garbage
    logits rows the caller must gate on; their tokens write nothing
    (position sentinel C drops the scatter) and their state is never
    sampled (slot sentinel drops the engine's key/mu writes).
    """
    from localai_tpu.ops.ragged_prefill import ragged_prefill_attention
    from localai_tpu.parallel.sharding import overlap_halves

    N = tokens.shape[0]
    B = seg_slots.shape[0]
    rp = positions if rope_positions is None else rope_positions
    sin, cos = rope_frequencies(cfg, rp[None, :])
    x = _embed_rows(params["embed"], tokens, cfg.dtype)[None]   # [1, N, D]
    # per-token target slot for the ragged KV scatter (pads ride the
    # clipped lookup; their position sentinel drops the write)
    slot_of = jnp.take(seg_slots, jnp.minimum(seg_of, B - 1))

    def layer_fn(carry, layer):
        x, ck, cv = carry
        li = layer.pop("_idx")
        h = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _project_qkv(h, layer, cfg)     # [1, N, {H|KV}, hd]
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        lck, lcv = kvcache.layer(ck, li), kvcache.layer(cv, li)
        # committed rows are read BEFORE this pack's scatter (the same
        # no-read-after-write rule as every other attention path here)
        if continued and _ragged_pallas_ok(lck, N, cfg):
            from localai_tpu.ops.pallas.ragged_prefill import (
                ragged_kernel_plan, ragged_prefill_attention_pallas)

            qb, pkb = ragged_kernel_plan(N, cfg.num_kv_heads, cfg.q_per_kv,
                                         cfg.head_dim_)
            attn = ragged_prefill_attention_pallas(
                q[0], k[0], v[0], lck["pages"], lcv["pages"], lck["ptab"],
                seg_slots, seg_start, seg_off, seg_len, cfg.q_per_kv,
                pkb=pkb, qb=qb)
        else:
            attn = ragged_prefill_attention(
                q[0], k[0], v[0], seg_of, seg_slots, seg_start, lck, lcv,
                cfg.q_per_kv, continued=continued)
        ck = kvcache.scatter_ragged(ck, li, slot_of, positions, k[0])
        cv = kvcache.scatter_ragged(cv, li, slot_of, positions, v[0])
        attn_r = attn[None].reshape(1, N, -1)

        def out_proj(t):
            return jnp.einsum("bth,hd->btd", t, _mat(layer["wo"], x.dtype))

        def mlp_half(t):
            return _mlp(rms_norm(t, layer["mlp_norm"], cfg.rms_norm_eps),
                        layer)

        if comm_overlap:
            x = x + overlap_halves(out_proj, attn_r, axis=1)
            x = x + overlap_halves(mlp_half, x, axis=1)
        else:
            x = x + out_proj(attn_r)
            x = x + mlp_half(x)
        return (x, ck, cv), None

    layers = dict(params["layers"])
    layers["_idx"] = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    (x, cache_k, cache_v), _ = jax.lax.scan(layer_fn, (x, cache_k, cache_v),
                                            layers)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    # hidden state at each segment's LAST packed token (pads clamp to 0)
    last = jnp.maximum(seg_off + seg_len - 1, 0)
    hs = jnp.take(x[0], last, axis=0)                           # [B, D]
    logits = _unembed(hs[None], params, cfg)[0]
    return logits, cache_k, cache_v


def _decode_attend_write(q1, k1, v1, lck, lcv, lengths, cfg: LlamaConfig):
    """One decode token per slot: attend + scatter the new K/V row.

    q1 [S, H, hd]; k1/v1 [S, KV, hd]; returns (attn [S, H, hd], lk, lv).

    Decode-attention path selection (r3 benchmark campaign,
    scripts/profile_decode*.py on the serving chip):
      * post-scatter einsum (this default): 11.4 ms/step model-only on
        the 1B bench config — the best measured composition despite
        XLA materializing relayouted layer copies around the dot;
      * append-attention (pre-scatter read, jnp or the Pallas kernel
        in ops/pallas/decode_attention.py): semantically identical,
        measured 12.9-14.6 ms/step here — the relayout moves rather
        than disappears. Kept selectable (LOCALAI_DECODE_ATTN=append
        | pallas) because the balance may flip off the axon tunnel."""
    S = q1.shape[0]
    slot_idx = jnp.arange(S, dtype=jnp.int32)
    mode = _decode_attn_mode()
    if kvcache.is_paged(lck):
        # PAGED layout: the ragged paged kernels on real TPU backends
        # (pages consumed in place, page table scalar-prefetched into the
        # block pipeline; int8 caches use the {q, scales} kernel variant
        # so pages stay quantized in HBM); pure-jnp page gather +
        # append-attention everywhere else (JAX_PLATFORMS=cpu tests —
        # the gathered {"q","s"} rows fold scales exactly like the
        # contiguous path)
        if _pallas_decode() and kvcache.is_quant(lck):
            # int8 pages stay quantized in HBM: the {q, scales} kernel
            # variant folds the scales in VMEM (ROADMAP PR-1 follow-up —
            # previously int8 paged decode fell back to the dense jnp
            # gather even where the pallas kernel ran)
            from localai_tpu.ops.pallas.paged_attention import (
                paged_decode_attention_append_quant)

            attn = paged_decode_attention_append_quant(
                q1, k1, v1, lck["pages"], lck["scales"], lcv["pages"],
                lcv["scales"], lck["ptab"], lengths, cfg.q_per_kv)
        elif _pallas_decode():
            from localai_tpu.ops.pallas.paged_attention import (
                paged_decode_attention_append)

            attn = paged_decode_attention_append(
                q1, k1, v1, lck["pages"], lcv["pages"], lck["ptab"],
                lengths, cfg.q_per_kv)
        else:
            attn = decode_attention_append(
                q1, k1, v1, kvcache.gather_all_rows(lck),
                kvcache.gather_all_rows(lcv), lengths, cfg.q_per_kv)
        lk = kvcache.scatter_decode(lck, slot_idx, lengths, k1)
        lv = kvcache.scatter_decode(lcv, slot_idx, lengths, v1)
        return attn, lk, lv
    if mode == "pallas" and _pallas_decode() and not kvcache.is_quant(lck):
        from localai_tpu.ops.pallas.decode_attention import (
            decode_attention_append_pallas)

        attn = decode_attention_append_pallas(
            q1, k1, v1, lck, lcv, lengths, cfg.q_per_kv)
        lk = kvcache.scatter_decode(lck, slot_idx, lengths, k1)
        lv = kvcache.scatter_decode(lcv, slot_idx, lengths, v1)
    elif mode == "append" or (mode == "pallas" and kvcache.is_quant(lck)):
        attn = decode_attention_append(q1, k1, v1, lck, lcv, lengths,
                                       cfg.q_per_kv)
        lk = kvcache.scatter_decode(lck, slot_idx, lengths, k1)
        lv = kvcache.scatter_decode(lcv, slot_idx, lengths, v1)
    else:
        # scatter new k/v at [slot, lengths[slot]], then attend over the
        # updated rows ([0, lengths]); out-of-range positions
        # (lengths==C) are dropped, preserving the capacity invariant
        lk = kvcache.scatter_decode(lck, slot_idx, lengths, k1)
        lv = kvcache.scatter_decode(lcv, slot_idx, lengths, v1)
        attn = decode_attention(q1, lk, lv, lengths + 1, cfg.q_per_kv)
    return attn, lk, lv


def decode_step(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,     # [S] int32 — one token per slot
    lengths: jax.Array,    # [S] int32 — current context length per slot (position of new token)
    cache_k: jax.Array,    # [L, S, C, KV, hd]
    cache_v: jax.Array,
    pos_offset: jax.Array = None,  # [S] int32 — self-extend position offset
):
    """One decode step for ALL slots (inactive slots are masked by caller).

    Returns (logits [S, V], cache_k, cache_v). The new token for slot s is
    written at cache position lengths[s]; attention spans [0, lengths[s]].
    With self-extend (group attention) active, its RoPE position is
    lengths[s] - pos_offset[s]: cache ROWS keep raw token order (attention
    masking is row-based) while positions are compressed.

    INVARIANT (enforced by the engine scheduler): lengths[s] < C for active
    slots. At lengths[s] == C the one_hot write row is all-zero and the new
    token's K/V would be silently dropped — the scheduler must context-shift
    or finish the request before the cache fills.
    """
    S = tokens.shape[0]
    positions = lengths[:, None]  # [S, 1]
    if pos_offset is not None:
        positions = positions - pos_offset[:, None]
    sin, cos = rope_frequencies(cfg, positions)
    x = _embed_rows(params["embed"], tokens, cfg.dtype)[:, None, :]  # [S,1,D]
    C = kvcache.shape(cache_k)[2]

    def layer_fn(carry, layer):
        x, ck, cv = carry
        li = layer.pop("_idx")
        h = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _project_qkv(h, layer, cfg)  # q [S,1,H,hd], k/v [S,1,KV,hd]
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        lck, lcv = kvcache.layer(ck, li), kvcache.layer(cv, li)
        attn, lk, lv = _decode_attend_write(q[:, 0], k[:, 0], v[:, 0],
                                            lck, lcv, lengths, cfg)
        ck = kvcache.set_layer(ck, li, lk)
        cv = kvcache.set_layer(cv, li, lv)
        x = x + jnp.einsum("sh,hd->sd", attn.reshape(S, -1), _mat(layer["wo"], x.dtype))[:, None, :]
        h = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(h, layer)
        return (x, ck, cv), None

    layers = dict(params["layers"])
    layers["_idx"] = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    (x, cache_k, cache_v), _ = jax.lax.scan(layer_fn, (x, cache_k, cache_v), layers)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    logits = _unembed(x, params, cfg)[:, 0, :]
    return logits, cache_k, cache_v


def engine_decode(params, cfg, tokens, lengths, active, cache_k, cache_v,
                  pos_offset=None):
    """Engine adapter (shared contract with models/mamba.py): one decode
    step for all slots; inactive slots must not write KV — their write
    position is forced to C so the scatter's mode=\"drop\" discards it."""
    C = kvcache.shape(cache_k)[2]
    write_lengths = jnp.where(active, lengths, C)
    return decode_step(params, cfg, tokens, write_lengths, cache_k, cache_v,
                       pos_offset=pos_offset)


def fused_prefill_decode(
    params: dict,
    cfg: LlamaConfig,
    tokens: jax.Array,      # [S] int32 — pending decode token per slot
    lengths: jax.Array,     # [S] int32 — context length per slot
    active: jax.Array,      # [S] bool — slots advancing this step
    cache_k: jax.Array,
    cache_v: jax.Array,
    pr_tokens: jax.Array,   # [B, T] int32 fresh prompts, right-padded
    pr_seq: jax.Array,      # [B] int32 true lengths
    pr_slots: jax.Array,    # [B] int32 target slots (disjoint from active)
    pr_start: jax.Array,    # [B] int32 position offset
    pos_offset: jax.Array = None,   # [S] self-extend offset for decode
):
    """One decode step for all active slots AND a fresh-prompt prefill
    batch, in a SINGLE forward whose activations are concatenated along
    the token axis — so the two workloads share every weight read.

    Packing prompt tokens and decode tokens into one batch is the
    reference's llama_batch design (grpc-server.cpp:1671+); the TPU form
    is a static-shape concat feeding shared matmuls, with per-segment
    RoPE/attention after the projections.

    MEASURED NEGATIVE RESULT on the current serving stack (r5, 8B-int8 +
    int8 KV, 32 slots, axon tunnel): this fused forward costs ~68 ms
    over a plain decode step, vs ~14 ms for the sequential
    prefill-then-decode composition it replaces — the concat/slice
    layout copies around every projection outweigh the shared weight
    reads, so the engine keeps the sequential form (engine.py
    _fused_body). Kept, parity-tested, because the balance is a property
    of the interconnect: on a directly-attached chip the shared-read
    saving should dominate.

    Semantics match engine_decode(active-masked) followed by
    prefill(continued=False) on disjoint slots. Returns
    (dec_logits [S, V], pr_logits [B, V], cache_k, cache_v)."""
    S = tokens.shape[0]
    B, T = pr_tokens.shape
    D = cfg.hidden_size
    hd = cfg.head_dim_
    C = kvcache.shape(cache_k)[2]
    write_lengths = jnp.where(active, lengths, C)   # inactive writes drop

    dpos = write_lengths[:, None]                   # [S, 1]
    if pos_offset is not None:
        dpos = dpos - pos_offset[:, None]
    ppos = pr_start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    pos_all = jnp.concatenate([dpos.reshape(1, S), ppos.reshape(1, B * T)],
                              axis=1)               # [1, S+B*T]
    sin, cos = rope_frequencies(cfg, pos_all)
    xd = _embed_rows(params["embed"], tokens, cfg.dtype)        # [S, D]
    xp = _embed_rows(params["embed"], pr_tokens, cfg.dtype)     # [B, T, D]
    x = jnp.concatenate([xd, xp.reshape(B * T, D)], axis=0)[None]  # [1,N,D]
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < pr_seq[:, None]
    rows = pr_slots[:, None] * jnp.ones((1, T), jnp.int32)
    cols = ppos

    def layer_fn(carry, layer):
        x, ck, cv = carry
        li = layer.pop("_idx")
        h = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _project_qkv(h, layer, cfg)       # ONE weight read each
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        qd, qp = q[0, :S], q[0, S:].reshape(B, T, cfg.num_heads, hd)
        kd, kp = k[0, :S], k[0, S:].reshape(B, T, cfg.num_kv_heads, hd)
        vd, vp = v[0, :S], v[0, S:].reshape(B, T, cfg.num_kv_heads, hd)
        lck, lcv = kvcache.layer(ck, li), kvcache.layer(cv, li)
        attn_d, lk, lv = _decode_attend_write(qd, kd, vd, lck, lcv,
                                              write_lengths, cfg)
        ck = kvcache.set_layer(ck, li, lk)
        cv = kvcache.set_layer(cv, li, lv)
        attn_p = causal_attention(qp, kp, vp, valid, cfg.q_per_kv)
        ck = kvcache.scatter_prefill(ck, li, rows, cols, kp)
        cv = kvcache.scatter_prefill(cv, li, rows, cols, vp)
        attn = jnp.concatenate([attn_d.reshape(S, -1),
                                attn_p.reshape(B * T, -1)], axis=0)[None]
        x = x + jnp.einsum("bth,hd->btd", attn, _mat(layer["wo"], x.dtype))
        h = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        x = x + _mlp(h, layer)
        return (x, ck, cv), None

    layers = dict(params["layers"])
    layers["_idx"] = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    (x, cache_k, cache_v), _ = jax.lax.scan(layer_fn, (x, cache_k, cache_v),
                                            layers)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    xd = x[0, :S]                                   # [S, D]
    xp = x[0, S:].reshape(B, T, D)
    last = jnp.take_along_axis(
        xp, (pr_seq - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    both = jnp.concatenate([xd, last], axis=0)[None]   # [1, S+B, D]
    logits = _unembed(both, params, cfg)[0]
    return logits[:S], logits[S:], cache_k, cache_v


def shift_cache_positions(cache_k: jax.Array, cfg: LlamaConfig,
                          slot: jax.Array, deltas: jax.Array) -> jax.Array:
    """Re-rotate ONE slot's cached keys by per-row position deltas [C].

    The recomputeless self-extend primitive: grouped attention compresses
    the positions of past blocks (reference KV surgery:
    grpc-server.cpp:1904-1927); since RoPE rotations compose, rotating the
    cached (already-rotated) keys by (new_pos - old_pos) is EXACT. Values
    carry no positional encoding and stay untouched. Rows with delta 0
    are rotated by the identity."""
    from localai_tpu.ops.rope import rope_delta_terms, rotate_by_delta

    sin, cos = rope_delta_terms(cfg, deltas)            # [C, hd]
    rows = kvcache.slot_rows(cache_k, slot)             # [L, C, KV, hd]
    if kvcache.is_quant(rows):
        # dequant -> rotate -> requant for the ONE slot being compressed
        # (slot-local, off the hot path; one extra quantization rounding)
        dense = kvcache.dequantize(rows["q"], rows["s"], cfg.dtype)
        out = rotate_by_delta(dense, sin[None, :, None, :],
                              cos[None, :, None, :])
        return kvcache.tree_slot_update(cache_k, slot,
                                        kvcache.rows_from_float(out, cache_k))
    out = rotate_by_delta(rows, sin[None, :, None, :], cos[None, :, None, :])
    if kvcache.is_paged(cache_k):
        # scatter the rotated rows back through the page table (the slot
        # owns its pages exclusively here: cross-slot page sharing is
        # disabled under self-extend — see engine admission gates)
        return kvcache.tree_slot_update(cache_k, slot, out)
    return cache_k.at[:, slot].set(out)


def init_cache(cfg: LlamaConfig, num_slots: int, max_len: int, dtype=None,
               page_size: int = 0, num_pages: int = 0):
    """KV cache: ([L, S, C, KV, hd], [L, S, C, KV, hd]); ``dtype=int8``
    selects the quantized {"q","s"} pytree (ops/kvcache.py).

    ``page_size > 0`` selects the PAGED layout instead: a shared page
    pool (num_pages physical pages, default the full S * C/page_size —
    i.e. never more HBM than the contiguous reservation) plus a per-slot
    page table, same logical shape."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, num_slots, max_len, cfg.num_kv_heads, cfg.head_dim_)
    if page_size:
        return (kvcache.init_paged(shape, dtype, page_size, num_pages),
                kvcache.init_paged(shape, dtype, page_size, num_pages))
    return kvcache.init(shape, dtype), kvcache.init(shape, dtype)
