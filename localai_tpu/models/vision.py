"""Vision encoder for multimodal chat: CLIP-style ViT + LLaVA projector.

Capability parity with the reference's LLaVA path (reference:
backend/cpp/llama/grpc-server.cpp:1157-1180,1425-1440 — CLIP image
embeddings computed per [img-N] placeholder and injected into the prompt
at the placeholder position). The encoder is a scan-stacked pre-LN ViT
over fixed-size patches; the projector is LLaVA's 2-layer GELU MLP into
the language model's hidden size.

Weight layout matches HF ``CLIPVisionModel`` (vision_model.*) plus LLaVA's
``multi_modal_projector``; init_params/save_params provide the
framework-native tiny-checkpoint path for offline tests.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# CLIP preprocessing constants
_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)


@dataclasses.dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    proj_dim: int = 4096           # language model hidden size
    layer_norm_eps: float = 1e-5
    # which encoder hidden state feeds the projector, HF hidden_states
    # indexing: -1 = last layer, -2 = penultimate (LLaVA's default —
    # selecting the final layer instead measurably degrades real LLaVA
    # checkpoints; ADVICE r2)
    vision_feature_layer: int = -2
    dtype: Any = jnp.float32

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @staticmethod
    def from_hf_config(cfg: dict, proj_dim: int = None, dtype=jnp.float32):
        v = cfg.get("vision_config", cfg)
        return VisionConfig(
            image_size=v.get("image_size", 224),
            patch_size=v.get("patch_size", 14),
            hidden_size=v.get("hidden_size", 768),
            intermediate_size=v.get("intermediate_size", 3072),
            num_layers=v.get("num_hidden_layers", 12),
            num_heads=v.get("num_attention_heads", 12),
            proj_dim=proj_dim or cfg.get("proj_dim", v.get("projection_dim", 4096)),
            layer_norm_eps=v.get("layer_norm_eps", 1e-5),
            vision_feature_layer=cfg.get("vision_feature_layer", -2),
            dtype=dtype,
        )

    @staticmethod
    def from_json(path: str, proj_dim: int = None, dtype=jnp.float32):
        with open(path) as f:
            return VisionConfig.from_hf_config(json.load(f), proj_dim, dtype)


def init_params(cfg: VisionConfig, key: jax.Array) -> dict:
    D, F, L, P = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers, cfg.patch_size
    ks = iter(jax.random.split(key, 16))

    def init(shape, fan_in):
        return (jax.random.normal(next(ks), shape, jnp.float32)
                / np.sqrt(fan_in)).astype(cfg.dtype)

    n_pos = cfg.num_patches + 1
    return {
        "patch_embed": init((D, 3, P, P), 3 * P * P),
        "cls_embed": init((D,), D),
        "pos_embed": init((n_pos, D), D),
        "pre_norm_w": jnp.ones((D,), cfg.dtype),
        "pre_norm_b": jnp.zeros((D,), cfg.dtype),
        "layers": {
            "norm1_w": jnp.ones((L, D), cfg.dtype), "norm1_b": jnp.zeros((L, D), cfg.dtype),
            "wq": init((L, D, D), D), "bq": jnp.zeros((L, D), cfg.dtype),
            "wk": init((L, D, D), D), "bk": jnp.zeros((L, D), cfg.dtype),
            "wv": init((L, D, D), D), "bv": jnp.zeros((L, D), cfg.dtype),
            "wo": init((L, D, D), D), "bo": jnp.zeros((L, D), cfg.dtype),
            "norm2_w": jnp.ones((L, D), cfg.dtype), "norm2_b": jnp.zeros((L, D), cfg.dtype),
            "w1": init((L, D, F), D), "b1": jnp.zeros((L, F), cfg.dtype),
            "w2": init((L, F, D), F), "b2": jnp.zeros((L, D), cfg.dtype),
        },
        "proj_w1": init((D, cfg.proj_dim), D),
        "proj_b1": jnp.zeros((cfg.proj_dim,), cfg.dtype),
        "proj_w2": init((cfg.proj_dim, cfg.proj_dim), cfg.proj_dim),
        "proj_b2": jnp.zeros((cfg.proj_dim,), cfg.dtype),
    }


def _ln(x, w, b, eps):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def encode(params: dict, cfg: VisionConfig, pixels: jax.Array) -> jax.Array:
    """pixels [B, 3, H, W] (CLIP-normalized) -> projected patch embeddings
    [B, num_patches, proj_dim] (LLaVA drops the CLS token)."""
    B = pixels.shape[0]
    D = cfg.hidden_size
    H = cfg.num_heads
    hd = D // H
    eps = cfg.layer_norm_eps
    x = jax.lax.conv_general_dilated(
        pixels.astype(cfg.dtype), params["patch_embed"],
        (cfg.patch_size, cfg.patch_size), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))     # [B, D, gh, gw]
    x = x.reshape(B, D, -1).transpose(0, 2, 1)           # [B, N, D]
    cls = jnp.broadcast_to(params["cls_embed"], (B, 1, D))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"][None]
    x = _ln(x, params["pre_norm_w"], params["pre_norm_b"], eps)

    def layer(x, ly):
        h = _ln(x, ly["norm1_w"], ly["norm1_b"], eps)
        q = (jnp.einsum("btd,de->bte", h, ly["wq"]) + ly["bq"]).reshape(B, -1, H, hd)
        k = (jnp.einsum("btd,de->bte", h, ly["wk"]) + ly["bk"]).reshape(B, -1, H, hd)
        v = (jnp.einsum("btd,de->bte", h, ly["wv"]) + ly["bv"]).reshape(B, -1, H, hd)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(hd)
        p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        a = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, -1, D)
        x = x + jnp.einsum("bte,ed->btd", a, ly["wo"]) + ly["bo"]
        h = _ln(x, ly["norm2_w"], ly["norm2_b"], eps)
        # CLIP's MLP activation is QUICK gelu (x * sigmoid(1.702 x)), not
        # the tanh approximation — r4 torch-parity divergence
        a = jnp.einsum("btd,df->btf", h, ly["w1"]) + ly["b1"]
        h = a * jax.nn.sigmoid(1.702 * a)
        x = x + jnp.einsum("btf,fd->btd", h, ly["w2"]) + ly["b2"]
        return x, None

    # HF hidden_states = [embeddings] + per-layer outputs; LLaVA projects
    # hidden_states[vision_feature_layer] (default -2: penultimate layer,
    # NO post-layernorm), not the final layer output. The selected index is
    # static, so simply scan only the layers up to it — no [L, B, T, D]
    # stacking of every hidden state.
    fl = cfg.vision_feature_layer
    L = cfg.num_layers
    end = fl if fl >= 0 else L + fl + 1
    if end < L:
        layers_used = jax.tree.map(lambda a: a[:end], params["layers"])
    else:
        layers_used = params["layers"]
    x, _ = jax.lax.scan(layer, x, layers_used)
    patches = x[:, 1:, :]                                # drop CLS (LLaVA)
    # the LLaVA projector uses EXACT gelu (erf), unlike the CLIP tower
    h = jax.nn.gelu(jnp.einsum("bnd,de->bne", patches, params["proj_w1"])
                    + params["proj_b1"], approximate=False)
    return jnp.einsum("bne,ef->bnf", h, params["proj_w2"]) + params["proj_b2"]


@functools.lru_cache(maxsize=4)
def _jit_encode(cfg: VisionConfig):
    return jax.jit(lambda p, px: encode(p, cfg, px))


def preprocess(image_bytes: bytes, cfg: VisionConfig) -> np.ndarray:
    """Decode + resize + CLIP-normalize an image -> [1, 3, H, W] float32."""
    import io

    from PIL import Image

    im = Image.open(io.BytesIO(image_bytes)).convert("RGB")
    im = im.resize((cfg.image_size, cfg.image_size), Image.BICUBIC)
    arr = np.asarray(im, np.float32) / 255.0
    arr = (arr - _MEAN) / _STD
    return arr.transpose(2, 0, 1)[None]


def embed_image(params: dict, cfg: VisionConfig, image_bytes: bytes) -> np.ndarray:
    """bytes -> [num_patches, proj_dim] float32 prompt-injectable embeddings."""
    px = preprocess(image_bytes, cfg)
    return np.asarray(_jit_encode(cfg)(params, px)[0], np.float32)


def sample_video_frames(video_bytes: bytes, n_frames: int = 4) -> list:
    """Decode an animated-image container and uniformly sample up to
    n_frames as PNG bytes for embed_image.

    Decoder-support contract (and the vLLM-semantics rationale) lives in
    utils/media.py, shared with the HTTP layer's decodability probe; a
    non-decodable payload raises ValueError, which callers MUST surface
    as a request error (VERDICT r4 #6)."""
    import io

    from localai_tpu.utils.media import decode_video_frames

    frames = decode_video_frames(video_bytes)
    idx = np.linspace(0, len(frames) - 1,
                      min(n_frames, len(frames))).round().astype(int)
    out = []
    for i in sorted(set(idx.tolist())):
        buf = io.BytesIO()
        frames[i].save(buf, format="PNG")
        out.append(buf.getvalue())
    return out


def save_params(params: dict, cfg: VisionConfig, model_dir: str):
    from safetensors.numpy import save_file

    os.makedirs(model_dir, exist_ok=True)
    flat = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}{k}.", v)
        else:
            flat[prefix[:-1]] = np.asarray(node)

    walk("", params)
    save_file(flat, os.path.join(model_dir, "model.safetensors"))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump({
            "model_type": "localai_tpu_vision",
            "vision_config": {
                "image_size": cfg.image_size, "patch_size": cfg.patch_size,
                "hidden_size": cfg.hidden_size,
                "intermediate_size": cfg.intermediate_size,
                "num_hidden_layers": cfg.num_layers,
                "num_attention_heads": cfg.num_heads,
                "layer_norm_eps": cfg.layer_norm_eps,
            },
            "proj_dim": cfg.proj_dim,
        }, f)


def load_params(model_dir: str, cfg: VisionConfig) -> dict:
    """Load framework-native or HF CLIPVisionModel(+projector) safetensors."""
    from localai_tpu.engine.weights import _open_shards

    tensors = _open_shards(model_dir)
    names = set(tensors)
    if "patch_embed" in names:  # framework-native flat layout
        from safetensors.numpy import load_file

        flat = load_file(os.path.join(model_dir, "model.safetensors"))
        params: dict = {}
        for name, arr in flat.items():
            parts = name.split(".")
            node = params
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = jnp.asarray(arr, cfg.dtype)
        return params

    def get(name):
        for prefix in ("vision_model.", "vision_tower.vision_model.", ""):
            if prefix + name in tensors:
                return np.asarray(tensors[prefix + name].get_tensor(prefix + name))
        raise KeyError(name)

    dt = cfg.dtype
    L = cfg.num_layers

    def stack(fmt, transpose=False):
        mats = [get(fmt.format(i=i)) for i in range(L)]
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(np.stack(mats), dt)

    e = "encoder.layers.{i}."
    params = {
        "patch_embed": jnp.asarray(get("embeddings.patch_embedding.weight"), dt),
        "cls_embed": jnp.asarray(get("embeddings.class_embedding"), dt).reshape(-1),
        "pos_embed": jnp.asarray(get("embeddings.position_embedding.weight"), dt),
        "pre_norm_w": jnp.asarray(get("pre_layrnorm.weight"), dt),
        "pre_norm_b": jnp.asarray(get("pre_layrnorm.bias"), dt),
        "layers": {
            "norm1_w": stack(e + "layer_norm1.weight"),
            "norm1_b": stack(e + "layer_norm1.bias"),
            "wq": stack(e + "self_attn.q_proj.weight", True),
            "bq": stack(e + "self_attn.q_proj.bias"),
            "wk": stack(e + "self_attn.k_proj.weight", True),
            "bk": stack(e + "self_attn.k_proj.bias"),
            "wv": stack(e + "self_attn.v_proj.weight", True),
            "bv": stack(e + "self_attn.v_proj.bias"),
            "wo": stack(e + "self_attn.out_proj.weight", True),
            "bo": stack(e + "self_attn.out_proj.bias"),
            "norm2_w": stack(e + "layer_norm2.weight"),
            "norm2_b": stack(e + "layer_norm2.bias"),
            "w1": stack(e + "mlp.fc1.weight", True),
            "b1": stack(e + "mlp.fc1.bias"),
            "w2": stack(e + "mlp.fc2.weight", True),
            "b2": stack(e + "mlp.fc2.bias"),
        },
    }

    def proj(name):
        for cand in (f"multi_modal_projector.linear_{name[-1]}.{name[:-2]}",
                     f"mm_projector.{name}"):
            for key in (cand,):
                if key in tensors:
                    return np.asarray(tensors[key].get_tensor(key))
        raise KeyError(name)

    try:
        params["proj_w1"] = jnp.asarray(proj("weight_1").T, dt)
        params["proj_b1"] = jnp.asarray(proj("bias_1"), dt)
        params["proj_w2"] = jnp.asarray(proj("weight_2").T, dt)
        params["proj_b2"] = jnp.asarray(proj("bias_2"), dt)
    except KeyError:
        # CLIP-only checkpoint: identity-ish projector to proj_dim
        D = cfg.hidden_size
        eye = np.zeros((D, cfg.proj_dim), np.float32)
        np.fill_diagonal(eye, 1.0)
        params["proj_w1"] = jnp.asarray(eye, dt)
        params["proj_b1"] = jnp.zeros((cfg.proj_dim,), dt)
        eye2 = np.eye(cfg.proj_dim, dtype=np.float32)
        params["proj_w2"] = jnp.asarray(eye2, dt)
        params["proj_b2"] = jnp.zeros((cfg.proj_dim,), dt)
    return params
