"""Text-to-speech: byte-conditioned transformer acoustic model with a
conv-transpose neural vocoder, functional JAX.

Capability parity with the reference's TTS backends (reference:
backend/go/tts/piper.go:1-49 — text in, WAV file out, optional voice;
plus the python TTS family backend/python/{bark,coqui,parler-tts}/).
Architecture is framework-native (piper's ONNX VITS graphs don't map to
this stack): byte embedding -> scan-stacked transformer encoder ->
conv-transpose upsampling pyramid (4*4*4*4 = 256 samples/char at 16 kHz,
~matching speech pacing) -> tanh waveform head.

Checkpoints use this framework's own safetensors layout (save_params /
load_params); random init synthesizes structured-but-alien audio, which
keeps the full RPC/file path real in offline environments.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

SAMPLE_RATE = 16000
SAMPLES_PER_TOKEN = 256


@dataclasses.dataclass(frozen=True)
class TTSConfig:
    vocab_size: int = 256          # raw bytes
    d_model: int = 256
    num_layers: int = 4
    num_heads: int = 4
    max_tokens: int = 512
    upsample: tuple = (4, 4, 4, 4)  # product == SAMPLES_PER_TOKEN
    dtype: Any = jnp.float32

    @staticmethod
    def from_json(path: str, dtype=jnp.float32) -> "TTSConfig":
        with open(path) as f:
            cfg = json.load(f)
        return TTSConfig(
            vocab_size=cfg.get("vocab_size", 256),
            d_model=cfg.get("d_model", 256),
            num_layers=cfg.get("num_layers", 4),
            num_heads=cfg.get("num_heads", 4),
            max_tokens=cfg.get("max_tokens", 512),
            upsample=tuple(cfg.get("upsample", (4, 4, 4, 4))),
            dtype=dtype,
        )


def init_params(cfg: TTSConfig, key: jax.Array) -> dict:
    D, L = cfg.d_model, cfg.num_layers
    F = 4 * D
    ks = iter(jax.random.split(key, 16))

    def init(shape, fan_in):
        return (jax.random.normal(next(ks), shape, jnp.float32)
                / np.sqrt(fan_in)).astype(cfg.dtype)

    # vocoder: conv-transpose pyramid halving channels per stage
    widths = [D]
    for _ in cfg.upsample:
        widths.append(max(widths[-1] // 2, 8))
    voc = []
    for i, r in enumerate(cfg.upsample):
        voc.append({
            "w": init((widths[i + 1], widths[i], 2 * r), widths[i] * 2 * r),
            "b": jnp.zeros((widths[i + 1],), cfg.dtype),
        })
    return {
        "embed": init((cfg.vocab_size, D), D),
        "pos": init((cfg.max_tokens, D), D),
        "layers": {
            "norm_w": jnp.ones((L, D), cfg.dtype),
            "norm_b": jnp.zeros((L, D), cfg.dtype),
            "wq": init((L, D, D), D), "wk": init((L, D, D), D),
            "wv": init((L, D, D), D), "wo": init((L, D, D), D),
            "mlp_norm_w": jnp.ones((L, D), cfg.dtype),
            "mlp_norm_b": jnp.zeros((L, D), cfg.dtype),
            "w1": init((L, D, F), D), "w2": init((L, F, D), F),
        },
        "voc": {str(i): v for i, v in enumerate(voc)},
        "head_w": init((1, widths[-1], 3), widths[-1] * 3),
        "head_b": jnp.zeros((1,), cfg.dtype),
    }


def _ln(x, w, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def synthesize_jit(params: dict, cfg: TTSConfig, tokens: jax.Array,
                   mask: jax.Array) -> jax.Array:
    """tokens [B, T] int32 bytes, mask [B, T] -> waveform [B, T*256] f32."""
    B, T = tokens.shape
    H = cfg.num_heads
    hd = cfg.d_model // H
    x = jnp.take(params["embed"], tokens, axis=0) + params["pos"][None, :T]

    def layer(x, ly):
        h = _ln(x, ly["norm_w"], ly["norm_b"])
        q = jnp.einsum("btd,de->bte", h, ly["wq"]).reshape(B, T, H, hd)
        k = jnp.einsum("btd,de->bte", h, ly["wk"]).reshape(B, T, H, hd)
        v = jnp.einsum("btd,de->bte", h, ly["wv"]).reshape(B, T, H, hd)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(hd)
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        a = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, T, -1)
        x = x + jnp.einsum("bte,ed->btd", a, ly["wo"])
        h = _ln(x, ly["mlp_norm_w"], ly["mlp_norm_b"])
        x = x + jnp.einsum("btf,fd->btd",
                           jax.nn.gelu(jnp.einsum("btd,df->btf", h, ly["w1"])),
                           ly["w2"])
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    y = x.transpose(0, 2, 1)                               # [B, D, T]
    for i, r in enumerate(cfg.upsample):
        v = params["voc"][str(i)]
        y = jax.lax.conv_transpose(
            y, v["w"], (r,), "SAME",
            dimension_numbers=("NCH", "OIH", "NCH"))
        y = jax.nn.leaky_relu(y + v["b"][None, :, None], 0.1)
    wave = jax.lax.conv_general_dilated(
        y, params["head_w"], (1,), [(1, 1)],
        dimension_numbers=("NCH", "OIH", "NCH")) + params["head_b"][None, :, None]
    wave = jnp.tanh(wave[:, 0, :])
    # zero out samples past the text length
    smask = jnp.repeat(mask, SAMPLES_PER_TOKEN, axis=1)
    return wave * smask


@functools.lru_cache(maxsize=8)
def _jit_synth(cfg: TTSConfig):
    return jax.jit(lambda p, t, m: synthesize_jit(p, cfg, t, m))


def synthesize(params: dict, cfg: TTSConfig, text: str) -> np.ndarray:
    """Text -> float32 waveform at SAMPLE_RATE (bucketed static shapes)."""
    ids = list(text.encode("utf-8", errors="replace"))[: cfg.max_tokens]
    ids = ids or [32]
    bucket = 32
    while bucket < len(ids):
        bucket *= 2
    bucket = min(bucket, cfg.max_tokens)
    tokens = np.zeros((1, bucket), np.int32)
    tokens[0, : len(ids)] = ids
    mask = np.zeros((1, bucket), bool)
    mask[0, : len(ids)] = True
    wave = np.asarray(_jit_synth(cfg)(params, tokens, mask))[0]
    return wave[: len(ids) * SAMPLES_PER_TOKEN]


def save_params(params: dict, cfg: TTSConfig, model_dir: str):
    from safetensors.numpy import save_file

    os.makedirs(model_dir, exist_ok=True)
    flat = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}{k}.", v)
        else:
            flat[prefix[:-1]] = np.asarray(node)

    walk("", params)
    save_file(flat, os.path.join(model_dir, "model.safetensors"))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump({
            "model_type": "localai_tpu_tts",
            "vocab_size": cfg.vocab_size, "d_model": cfg.d_model,
            "num_layers": cfg.num_layers, "num_heads": cfg.num_heads,
            "max_tokens": cfg.max_tokens, "upsample": list(cfg.upsample),
        }, f)


def load_params(model_dir: str, cfg: TTSConfig) -> dict:
    from safetensors.numpy import load_file

    flat = load_file(os.path.join(model_dir, "model.safetensors"))
    params: dict = {}
    for name, arr in flat.items():
        parts = name.split(".")
        node = params
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(arr, cfg.dtype)
    return params


def write_wav(path: str, wave_f32: np.ndarray, sample_rate: int = SAMPLE_RATE):
    import wave as wavelib

    pcm = (np.clip(wave_f32, -1.0, 1.0) * 32767).astype("<i2")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with wavelib.open(path, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sample_rate)
        w.writeframes(pcm.tobytes())
