"""Minimal training/fine-tuning step (next-token cross-entropy).

The reference has no training at all (SURVEY.md section 5.4); this exists so
the framework's sharding story covers the full dp/tp mesh for gradients too
(and to seed a future fine-tuning surface). Optimizer state and update are
deliberately simple (SGD); optax slots in trivially later.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from localai_tpu.models import llama
from localai_tpu.ops.norms import rms_norm
from localai_tpu.ops.rope import apply_rope, rope_frequencies
from localai_tpu.ops.attention import causal_attention


def forward_all_logits(params, cfg, tokens, seq_lens):
    """Teacher-forced forward returning logits at every position [B, T, V]."""
    B, T = tokens.shape
    positions = jnp.arange(T, dtype=jnp.int32)[None, :].repeat(B, axis=0)
    sin, cos = rope_frequencies(cfg, positions)
    x = llama._embed_rows(params["embed"], tokens, cfg.dtype)
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < seq_lens[:, None]

    def layer_fn(x, layer):
        layer.pop("_idx", None)
        h = rms_norm(x, layer["attn_norm"], cfg.rms_norm_eps)
        q, k, v = llama._project_qkv(h, layer, cfg)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        attn = causal_attention(q, k, v, valid, cfg.q_per_kv)
        x = x + jnp.einsum("bth,hd->btd", attn.reshape(B, T, -1),
                           llama._mat(layer["wo"], x.dtype))
        h = rms_norm(x, layer["mlp_norm"], cfg.rms_norm_eps)
        x = x + llama._mlp(h, layer)
        return x, None

    x, _ = jax.lax.scan(layer_fn, x, dict(params["layers"]))
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return llama._unembed(x, params, cfg)


def loss_fn(params, cfg, tokens, seq_lens):
    """Mean next-token cross-entropy over valid positions."""
    logits = forward_all_logits(params, cfg, tokens, seq_lens)  # [B, T, V]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    T = tokens.shape[1]
    valid = jnp.arange(T - 1, dtype=jnp.int32)[None, :] < (seq_lens - 1)[:, None]
    return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1)


def train_step(params, cfg, tokens, seq_lens, lr: float = 1e-4):
    """One SGD step; gradients follow the params' sharding (dp-psum by GSPMD).

    int8-quantized pytrees (dict {q, s} leaves) are dequantized first —
    value_and_grad needs float leaves, and training updates quantized
    weights as their dense float equivalents."""
    quantized = isinstance(params.get("embed"), dict) or any(
        isinstance(l, dict) for l in params.get("layers", {}).values())
    if quantized:
        params = llama.dequantize_params(params, cfg.dtype)
    loss, grads = jax.value_and_grad(loss_fn)(params, cfg, tokens, seq_lens)
    new_params = jax.tree.map(lambda p, g: (p - lr * g.astype(jnp.float32)).astype(p.dtype), params, grads)
    return loss, new_params
