"""Whisper-family speech-to-text (encoder-decoder transformer), functional JAX.

Capability parity with the reference's STT backend (reference:
backend/go/transcribe/whisper/whisper.go:1-105 — whisper.cpp behind the
AudioTranscription RPC, producing per-segment text with start/end times).

TPU-first design: the mel frontend is jnp FFT (one fused kernel per 30s
window), the encoder is a scan-stacked transformer over a static
[B, 1500, D] sequence, and decoding is a jitted single-token step with a
static-shape self-attention KV cache plus precomputed cross-attention K/V —
the same compile-once pattern as the llama engine. Audio is processed in
30-second windows (whisper's native chunking); each window yields one
transcript segment with window-aligned timestamps.

Weight layout matches HF ``WhisperForConditionalGeneration`` safetensors.
"""

from __future__ import annotations

import dataclasses
import functools
import json
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

SAMPLE_RATE = 16000
N_FFT = 400
HOP = 160
CHUNK_S = 30
CHUNK_SAMPLES = SAMPLE_RATE * CHUNK_S          # 480_000
CHUNK_FRAMES = CHUNK_SAMPLES // HOP            # 3000


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    vocab_size: int = 51865
    n_mels: int = 80
    d_model: int = 384
    encoder_layers: int = 4
    decoder_layers: int = 4
    num_heads: int = 6
    max_source_positions: int = 1500
    max_target_positions: int = 448
    decoder_start_token_id: int = 50258
    eos_token_id: int = 50257
    dtype: Any = jnp.float32

    @staticmethod
    def from_hf_config(cfg: dict, dtype=jnp.float32) -> "WhisperConfig":
        return WhisperConfig(
            vocab_size=cfg["vocab_size"],
            n_mels=cfg.get("num_mel_bins", 80),
            d_model=cfg["d_model"],
            encoder_layers=cfg["encoder_layers"],
            decoder_layers=cfg["decoder_layers"],
            num_heads=cfg["encoder_attention_heads"],
            max_source_positions=cfg.get("max_source_positions", 1500),
            max_target_positions=cfg.get("max_target_positions", 448),
            decoder_start_token_id=cfg.get("decoder_start_token_id", 50258),
            eos_token_id=cfg.get("eos_token_id", 50257),
            dtype=dtype,
        )

    @staticmethod
    def from_json(path: str, dtype=jnp.float32) -> "WhisperConfig":
        with open(path) as f:
            return WhisperConfig.from_hf_config(json.load(f), dtype=dtype)

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


# ---------- mel frontend ----------

def _mel_filterbank(n_mels: int) -> np.ndarray:
    """[n_mels, n_fft//2+1] triangular mel filters (SLANEY mel scale).

    Whisper's filterbank (and the HF WhisperFeatureExtractor oracle) uses
    the slaney scale — linear below 1 kHz, logarithmic above — not HTK;
    r4's torch-parity test caught the HTK version diverging by up to
    0.23 in log-mel units (a real transcription-quality bug)."""
    f_sp = 200.0 / 3.0
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / f_sp
    logstep = np.log(6.4) / 27.0

    def hz_to_mel(f):
        f = np.asarray(f, np.float64)
        mel = f / f_sp
        return np.where(f >= min_log_hz,
                        min_log_mel + np.log(np.maximum(f, 1e-9) / min_log_hz)
                        / logstep, mel)

    def mel_to_hz(m):
        m = np.asarray(m, np.float64)
        hz = m * f_sp
        return np.where(m >= min_log_mel,
                        min_log_hz * np.exp(logstep * (m - min_log_mel)), hz)

    fmax = SAMPLE_RATE / 2
    mels = np.linspace(hz_to_mel(0.0), hz_to_mel(fmax), n_mels + 2)
    freqs = mel_to_hz(mels)
    bins = np.fft.rfftfreq(N_FFT, d=1.0 / SAMPLE_RATE)
    fb = np.zeros((n_mels, len(bins)), np.float32)
    for i in range(n_mels):
        lo, ctr, hi = freqs[i], freqs[i + 1], freqs[i + 2]
        up = (bins - lo) / max(ctr - lo, 1e-9)
        down = (hi - bins) / max(hi - ctr, 1e-9)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
    # slaney-style energy normalization
    enorm = 2.0 / (freqs[2:] - freqs[:-2])
    fb *= enorm[:, None]
    return fb


def log_mel(audio: np.ndarray, n_mels: int) -> np.ndarray:
    """Float32 mono audio (16 kHz) -> [n_mels, CHUNK_FRAMES] log-mel.

    Whisper normalization: log10 clamped, ceiling-relative floor at -8,
    scaled to roughly [-1, 1]. Input is padded/trimmed to 30 s.
    """
    a = np.zeros((CHUNK_SAMPLES,), np.float32)
    a[: min(len(audio), CHUNK_SAMPLES)] = audio[:CHUNK_SAMPLES]
    window = np.hanning(N_FFT + 1)[:-1].astype(np.float32)
    pad = N_FFT // 2
    a = np.pad(a, (pad, pad), mode="reflect")
    frames = np.lib.stride_tricks.sliding_window_view(a, N_FFT)[::HOP][:CHUNK_FRAMES]
    spec = np.fft.rfft(frames * window, axis=-1)
    power = (np.abs(spec) ** 2).astype(np.float32)
    mel = _mel_filterbank(n_mels) @ power.T                 # [n_mels, frames]
    logmel = np.log10(np.maximum(mel, 1e-10))
    logmel = np.maximum(logmel, logmel.max() - 8.0)
    return ((logmel + 4.0) / 4.0).astype(np.float32)


def _sinusoids(length: int, channels: int) -> np.ndarray:
    """Whisper's fixed sinusoidal encoder positions."""
    log_timescale = np.log(10000.0) / (channels // 2 - 1)
    inv = np.exp(-log_timescale * np.arange(channels // 2))
    scaled = np.arange(length)[:, None] * inv[None, :]
    return np.concatenate([np.sin(scaled), np.cos(scaled)], axis=1).astype(np.float32)


# ---------- parameters ----------

def _attn_block(ks, L, D, dtype, cross=False):
    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan_in)).astype(dtype)
    p = "x" if cross else ""
    return {
        p + "attn_norm_w": jnp.ones((L, D), dtype),
        p + "attn_norm_b": jnp.zeros((L, D), dtype),
        p + "wq": init(ks[0], (L, D, D), D), p + "bq": jnp.zeros((L, D), dtype),
        p + "wk": init(ks[1], (L, D, D), D),  # whisper: no k bias
        p + "wv": init(ks[2], (L, D, D), D), p + "bv": jnp.zeros((L, D), dtype),
        p + "wo": init(ks[3], (L, D, D), D), p + "bo": jnp.zeros((L, D), dtype),
    }


def _mlp_block(ks, L, D, F, dtype):
    def init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan_in)).astype(dtype)
    return {
        "mlp_norm_w": jnp.ones((L, D), dtype), "mlp_norm_b": jnp.zeros((L, D), dtype),
        "w1": init(ks[0], (L, D, F), D), "b1": jnp.zeros((L, F), dtype),
        "w2": init(ks[1], (L, F, D), F), "b2": jnp.zeros((L, D), dtype),
    }


def init_params(cfg: WhisperConfig, key: jax.Array) -> dict:
    D, M = cfg.d_model, cfg.n_mels
    F = 4 * D
    dtype = cfg.dtype
    ks = iter(jax.random.split(key, 24))

    def init(shape, fan_in):
        return (jax.random.normal(next(ks), shape, jnp.float32)
                / np.sqrt(fan_in)).astype(dtype)

    enc_layers = {}
    enc_layers.update(_attn_block([next(ks) for _ in range(4)], cfg.encoder_layers, D, dtype))
    enc_layers.update(_mlp_block([next(ks) for _ in range(2)], cfg.encoder_layers, D, F, dtype))
    dec_layers = {}
    dec_layers.update(_attn_block([next(ks) for _ in range(4)], cfg.decoder_layers, D, dtype))
    dec_layers.update(_attn_block([next(ks) for _ in range(4)], cfg.decoder_layers, D, dtype, cross=True))
    dec_layers.update(_mlp_block([next(ks) for _ in range(2)], cfg.decoder_layers, D, F, dtype))
    return {
        "conv1_w": init((D, M, 3), M * 3), "conv1_b": jnp.zeros((D,), dtype),
        "conv2_w": init((D, D, 3), D * 3), "conv2_b": jnp.zeros((D,), dtype),
        "enc_pos": jnp.asarray(_sinusoids(cfg.max_source_positions, D), dtype),
        "enc_layers": enc_layers,
        "enc_norm_w": jnp.ones((D,), dtype), "enc_norm_b": jnp.zeros((D,), dtype),
        "tok_embed": init((cfg.vocab_size, D), D),
        "dec_pos": init((cfg.max_target_positions, D), D),
        "dec_layers": dec_layers,
        "dec_norm_w": jnp.ones((D,), dtype), "dec_norm_b": jnp.zeros((D,), dtype),
    }


# ---------- forward ----------

def _ln(x, w, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def _mha(q, k, v, H, mask=None):
    """q [B,Tq,D], k/v [B,Tk,D] -> [B,Tq,D]."""
    B, Tq, D = q.shape
    hd = D // H
    q = q.reshape(B, Tq, H, hd)
    k = k.reshape(B, -1, H, hd)
    v = v.reshape(B, -1, H, hd)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) / np.sqrt(hd)
    if mask is not None:
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, Tq, D)


def encode(params: dict, cfg: WhisperConfig, mel: jax.Array) -> jax.Array:
    """mel [B, n_mels, 3000] -> encoder states [B, 1500, D]."""
    x = jax.lax.conv_general_dilated(
        mel.astype(cfg.dtype), params["conv1_w"], (1,), [(1, 1)],
        dimension_numbers=("NCH", "OIH", "NCH"))
    x = jax.nn.gelu(x + params["conv1_b"][None, :, None])
    x = jax.lax.conv_general_dilated(
        x, params["conv2_w"], (2,), [(1, 1)],
        dimension_numbers=("NCH", "OIH", "NCH"))
    x = jax.nn.gelu(x + params["conv2_b"][None, :, None])
    x = x.transpose(0, 2, 1)                               # [B, 1500, D]
    x = x + params["enc_pos"][None, : x.shape[1]]
    H = cfg.num_heads

    def layer(x, ly):
        h = _ln(x, ly["attn_norm_w"], ly["attn_norm_b"])
        q = jnp.einsum("btd,de->bte", h, ly["wq"]) + ly["bq"]
        k = jnp.einsum("btd,de->bte", h, ly["wk"])
        v = jnp.einsum("btd,de->bte", h, ly["wv"]) + ly["bv"]
        x = x + jnp.einsum("bte,ed->btd", _mha(q, k, v, H), ly["wo"]) + ly["bo"]
        h = _ln(x, ly["mlp_norm_w"], ly["mlp_norm_b"])
        h = jax.nn.gelu(jnp.einsum("btd,df->btf", h, ly["w1"]) + ly["b1"])
        x = x + jnp.einsum("btf,fd->btd", h, ly["w2"]) + ly["b2"]
        return x, None

    x, _ = jax.lax.scan(layer, x, params["enc_layers"])
    return _ln(x, params["enc_norm_w"], params["enc_norm_b"])


def cross_kv(params: dict, cfg: WhisperConfig, enc: jax.Array):
    """Precompute per-layer cross-attention K/V: ([L,B,Tk,D], [L,B,Tk,D])."""
    def one(ly):
        k = jnp.einsum("btd,de->bte", enc, ly["xwk"])
        v = jnp.einsum("btd,de->bte", enc, ly["xwv"]) + ly["xbv"]
        return k, v

    ks, vs = jax.lax.map(
        lambda ly: one(ly),
        {k: v for k, v in params["dec_layers"].items() if k.startswith("x")})
    return ks, vs


def decode_step(params: dict, cfg: WhisperConfig, token: jax.Array, pos: jax.Array,
                xk: jax.Array, xv: jax.Array, cache_k: jax.Array, cache_v: jax.Array):
    """One greedy decoder step.

    token [B] int32; pos [] int32; xk/xv [L, B, Tk, D] cross K/V;
    cache_k/v [L, B, Tmax, D] self-attention cache.
    Returns (logits [B, V], cache_k, cache_v).
    """
    B = token.shape[0]
    H = cfg.num_heads
    Tmax = cache_k.shape[2]
    x = jnp.take(params["tok_embed"], token, axis=0)[:, None, :]  # [B,1,D]
    x = x + params["dec_pos"][pos][None, None, :]

    def layer(carry, ly):
        x, li = carry
        # self-attention over cached positions [0, pos]
        h = _ln(x, ly["attn_norm_w"], ly["attn_norm_b"])
        q = jnp.einsum("btd,de->bte", h, ly["wq"]) + ly["bq"]
        k_new = jnp.einsum("btd,de->bte", h, ly["wk"])[:, 0]
        v_new = (jnp.einsum("btd,de->bte", h, ly["wv"]) + ly["bv"])[:, 0]
        ck = jax.lax.dynamic_update_slice(cache_k[li], k_new[:, None], (0, pos, 0))
        cv = jax.lax.dynamic_update_slice(cache_v[li], v_new[:, None], (0, pos, 0))
        valid = (jnp.arange(Tmax) <= pos)[None, None, None, :]
        x = x + jnp.einsum("bte,ed->btd",
                           _mha(q, ck, cv, H, valid), ly["wo"]) + ly["bo"]
        # cross-attention over encoder states
        h = _ln(x, ly["xattn_norm_w"], ly["xattn_norm_b"])
        q = jnp.einsum("btd,de->bte", h, ly["xwq"]) + ly["xbq"]
        x = x + jnp.einsum("bte,ed->btd",
                           _mha(q, xk[li], xv[li], H), ly["xwo"]) + ly["xbo"]
        h = _ln(x, ly["mlp_norm_w"], ly["mlp_norm_b"])
        h = jax.nn.gelu(jnp.einsum("btd,df->btf", h, ly["w1"]) + ly["b1"])
        x = x + jnp.einsum("btf,fd->btd", h, ly["w2"]) + ly["b2"]
        return (x, li + 1), (ck, cv)

    (x, _), (new_k, new_v) = jax.lax.scan(
        layer, (x, jnp.int32(0)), params["dec_layers"])
    x = _ln(x, params["dec_norm_w"], params["dec_norm_b"])
    logits = jnp.einsum("btd,vd->btv", x, params["tok_embed"]).astype(jnp.float32)
    return logits[:, 0], new_k, new_v


@functools.lru_cache(maxsize=8)
def _jit_encode(cfg: WhisperConfig):
    return jax.jit(lambda p, mel: cross_kv(p, cfg, encode(p, cfg, mel)))


@functools.lru_cache(maxsize=8)
def _jit_step(cfg: WhisperConfig):
    # params passed as an argument — a closure would bake the weights into
    # the executable as constants (slow compiles, re-upload per compile)
    return jax.jit(
        lambda p, tok, pos, xk, xv, ck, cv: decode_step(p, cfg, tok, pos,
                                                        xk, xv, ck, cv),
        donate_argnums=(5, 6))


def transcribe_window(params: dict, cfg: WhisperConfig, mel: np.ndarray,
                      max_new: int = 224, forced_tokens=None) -> list:
    """Greedy-decode one 30s window. Returns generated token ids."""
    xk, xv = _jit_encode(cfg)(params, jnp.asarray(mel)[None])
    Tmax = min(cfg.max_target_positions, 232)  # one compiled cache shape
    max_new = min(max_new, Tmax - 8)
    L = cfg.decoder_layers
    cache_k = jnp.zeros((L, 1, Tmax, cfg.d_model), cfg.dtype)
    cache_v = jnp.zeros_like(cache_k)

    step = _jit_step(cfg)

    forced = list(forced_tokens or [cfg.decoder_start_token_id])
    out = []
    token = jnp.asarray([forced[0]], jnp.int32)
    for pos in range(min(Tmax - 1, max_new + len(forced) - 1)):
        logits, cache_k, cache_v = step(params, token, jnp.int32(pos), xk, xv,
                                        cache_k, cache_v)
        if pos + 1 < len(forced):
            nxt = forced[pos + 1]
        else:
            nxt = int(np.asarray(jnp.argmax(logits[0])))
            if nxt == cfg.eos_token_id:
                break
            out.append(nxt)
        token = jnp.asarray([nxt], jnp.int32)
    return out


# ---------- HF weight loading ----------

def save_hf_params(params: dict, cfg: WhisperConfig, model_dir: str):
    """Write the pytree as HF WhisperForConditionalGeneration safetensors
    (inverse of load_hf_params; used for export and test fixtures)."""
    import os

    from safetensors.numpy import save_file

    os.makedirs(model_dir, exist_ok=True)
    out = {}

    def unstack(side, fmt, arr, transpose=False):
        for i in range(arr.shape[0]):
            m = np.asarray(arr[i])
            out[f"model.{side}.layers.{i}.{fmt}"] = m.T if transpose else m

    def attn(side, layers, cross=False):
        a = "encoder_attn" if cross else "self_attn"
        p = "x" if cross else ""
        unstack(side, a + "_layer_norm.weight", layers[p + "attn_norm_w"])
        unstack(side, a + "_layer_norm.bias", layers[p + "attn_norm_b"])
        unstack(side, a + ".q_proj.weight", layers[p + "wq"], True)
        unstack(side, a + ".q_proj.bias", layers[p + "bq"])
        unstack(side, a + ".k_proj.weight", layers[p + "wk"], True)
        unstack(side, a + ".v_proj.weight", layers[p + "wv"], True)
        unstack(side, a + ".v_proj.bias", layers[p + "bv"])
        unstack(side, a + ".out_proj.weight", layers[p + "wo"], True)
        unstack(side, a + ".out_proj.bias", layers[p + "bo"])

    def mlp(side, layers):
        unstack(side, "final_layer_norm.weight", layers["mlp_norm_w"])
        unstack(side, "final_layer_norm.bias", layers["mlp_norm_b"])
        unstack(side, "fc1.weight", layers["w1"], True)
        unstack(side, "fc1.bias", layers["b1"])
        unstack(side, "fc2.weight", layers["w2"], True)
        unstack(side, "fc2.bias", layers["b2"])

    attn("encoder", params["enc_layers"])
    mlp("encoder", params["enc_layers"])
    attn("decoder", params["dec_layers"])
    attn("decoder", params["dec_layers"], cross=True)
    mlp("decoder", params["dec_layers"])
    for hf, ours in (
        ("model.encoder.conv1.weight", "conv1_w"),
        ("model.encoder.conv1.bias", "conv1_b"),
        ("model.encoder.conv2.weight", "conv2_w"),
        ("model.encoder.conv2.bias", "conv2_b"),
        ("model.encoder.embed_positions.weight", "enc_pos"),
        ("model.encoder.layer_norm.weight", "enc_norm_w"),
        ("model.encoder.layer_norm.bias", "enc_norm_b"),
        ("model.decoder.embed_tokens.weight", "tok_embed"),
        ("model.decoder.embed_positions.weight", "dec_pos"),
        ("model.decoder.layer_norm.weight", "dec_norm_w"),
        ("model.decoder.layer_norm.bias", "dec_norm_b"),
    ):
        out[hf] = np.asarray(params[ours])
    save_file(out, os.path.join(model_dir, "model.safetensors"))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump({
            "model_type": "whisper",
            "vocab_size": cfg.vocab_size,
            "num_mel_bins": cfg.n_mels,
            "d_model": cfg.d_model,
            "encoder_layers": cfg.encoder_layers,
            "decoder_layers": cfg.decoder_layers,
            "encoder_attention_heads": cfg.num_heads,
            "decoder_attention_heads": cfg.num_heads,
            "max_source_positions": cfg.max_source_positions,
            "max_target_positions": cfg.max_target_positions,
            "decoder_start_token_id": cfg.decoder_start_token_id,
            "eos_token_id": cfg.eos_token_id,
        }, f)


def load_hf_params(model_dir: str, cfg: WhisperConfig) -> dict:
    from localai_tpu.engine.weights import _open_shards

    tensors = _open_shards(model_dir)

    def get(name):
        for prefix in ("model.", ""):
            if prefix + name in tensors:
                return np.asarray(tensors[prefix + name].get_tensor(prefix + name))
        raise KeyError(name)

    dt = cfg.dtype

    def stack(fmt, n, transpose=False, optional=False):
        mats = []
        for i in range(n):
            try:
                m = get(fmt.format(i=i))
            except KeyError:
                if optional:
                    m = None
                else:
                    raise
            mats.append(m)
        if mats[0] is None:
            return None
        if transpose:
            mats = [m.T for m in mats]
        return jnp.asarray(np.stack(mats), dt)

    def attn(side, n, cross=False):
        a = "encoder_attn" if cross else "self_attn"
        base = side + ".layers.{i}." + a
        p = "x" if cross else ""
        out = {
            p + "attn_norm_w": stack(side + ".layers.{i}." + a + "_layer_norm.weight", n),
            p + "attn_norm_b": stack(side + ".layers.{i}." + a + "_layer_norm.bias", n),
            p + "wq": stack(base + ".q_proj.weight", n, True),
            p + "bq": stack(base + ".q_proj.bias", n),
            p + "wk": stack(base + ".k_proj.weight", n, True),
            p + "wv": stack(base + ".v_proj.weight", n, True),
            p + "bv": stack(base + ".v_proj.bias", n),
            p + "wo": stack(base + ".out_proj.weight", n, True),
            p + "bo": stack(base + ".out_proj.bias", n),
        }
        return out

    def mlp(side, n):
        return {
            "mlp_norm_w": stack(side + ".layers.{i}.final_layer_norm.weight", n),
            "mlp_norm_b": stack(side + ".layers.{i}.final_layer_norm.bias", n),
            "w1": stack(side + ".layers.{i}.fc1.weight", n, True),
            "b1": stack(side + ".layers.{i}.fc1.bias", n),
            "w2": stack(side + ".layers.{i}.fc2.weight", n, True),
            "b2": stack(side + ".layers.{i}.fc2.bias", n),
        }

    enc_layers = attn("encoder", cfg.encoder_layers)
    enc_layers.update(mlp("encoder", cfg.encoder_layers))
    dec_layers = attn("decoder", cfg.decoder_layers)
    dec_layers.update(attn("decoder", cfg.decoder_layers, cross=True))
    dec_layers.update(mlp("decoder", cfg.decoder_layers))
    return {
        "conv1_w": jnp.asarray(get("encoder.conv1.weight"), dt),
        "conv1_b": jnp.asarray(get("encoder.conv1.bias"), dt),
        "conv2_w": jnp.asarray(get("encoder.conv2.weight"), dt),
        "conv2_b": jnp.asarray(get("encoder.conv2.bias"), dt),
        "enc_pos": jnp.asarray(get("encoder.embed_positions.weight"), dt),
        "enc_layers": enc_layers,
        "enc_norm_w": jnp.asarray(get("encoder.layer_norm.weight"), dt),
        "enc_norm_b": jnp.asarray(get("encoder.layer_norm.bias"), dt),
        "tok_embed": jnp.asarray(get("decoder.embed_tokens.weight"), dt),
        "dec_pos": jnp.asarray(get("decoder.embed_positions.weight"), dt),
        "dec_layers": dec_layers,
        "dec_norm_w": jnp.asarray(get("decoder.layer_norm.weight"), dt),
        "dec_norm_b": jnp.asarray(get("decoder.layer_norm.bias"), dt),
    }
