"""EnCodec neural audio codec — the DECODE path (codes -> waveform).

TPU-native port of the codec MusicGen/Bark-class models emit audio
through (reference consumes it inside the transformers-musicgen backend:
backend/python/transformers-musicgen/backend.py:1-176). Implements the
HF `EncodecModel` layout: residual-vector-quantizer codebook lookup and
the SEANet decoder (conv -> 2-layer residual LSTM -> per-ratio
[ELU, transposed conv, resnet blocks] -> ELU -> conv), with
weight-norm parametrizations folded into plain kernels at load time.

Convolution padding mirrors EncodecConv1d/EncodecConvTranspose1d
exactly: causal (left) or asymmetric padding for strided convs, and
fixed-padding trim after transposed convs.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class EncodecConfig:
    audio_channels: int = 1
    hidden_size: int = 128
    num_filters: int = 32
    num_residual_layers: int = 1
    upsampling_ratios: tuple = (8, 5, 4, 2)
    kernel_size: int = 7
    last_kernel_size: int = 7
    residual_kernel_size: int = 3
    dilation_growth_rate: int = 2
    compress: int = 2
    num_lstm_layers: int = 2
    codebook_size: int = 1024
    codebook_dim: int = 128
    use_causal_conv: bool = True
    pad_mode: str = "reflect"
    trim_right_ratio: float = 1.0
    use_conv_shortcut: bool = True
    sampling_rate: int = 24000

    @staticmethod
    def from_hf_config(cfg: dict) -> "EncodecConfig":
        return EncodecConfig(
            audio_channels=cfg.get("audio_channels", 1),
            hidden_size=cfg.get("hidden_size", 128),
            num_filters=cfg.get("num_filters", 32),
            num_residual_layers=cfg.get("num_residual_layers", 1),
            upsampling_ratios=tuple(cfg.get("upsampling_ratios", (8, 5, 4, 2))),
            kernel_size=cfg.get("kernel_size", 7),
            last_kernel_size=cfg.get("last_kernel_size", 7),
            residual_kernel_size=cfg.get("residual_kernel_size", 3),
            dilation_growth_rate=cfg.get("dilation_growth_rate", 2),
            compress=cfg.get("compress", 2),
            num_lstm_layers=cfg.get("num_lstm_layers", 2),
            codebook_size=cfg.get("codebook_size", 1024),
            codebook_dim=cfg.get("codebook_dim",
                                 cfg.get("hidden_size", 128)),
            use_causal_conv=cfg.get("use_causal_conv", True),
            pad_mode=cfg.get("pad_mode", "reflect"),
            trim_right_ratio=cfg.get("trim_right_ratio", 1.0),
            use_conv_shortcut=cfg.get("use_conv_shortcut", True),
            sampling_rate=cfg.get("sampling_rate", 24000),
        )


def _pad1d(x, left: int, right: int, mode: str):
    """x [B, C, T]; reflect with the small-input zero-extension trick
    EncodecConv1d._pad1d uses."""
    if mode != "reflect":
        return jnp.pad(x, ((0, 0), (0, 0), (left, right)))
    T = x.shape[-1]
    max_pad = max(left, right)
    extra = 0
    if T <= max_pad:
        extra = max_pad - T + 1
        x = jnp.pad(x, ((0, 0), (0, 0), (0, extra)))
    out = jnp.pad(x, ((0, 0), (0, 0), (left, right)), mode="reflect")
    if extra:
        out = out[..., : out.shape[-1] - extra]
    return out


def conv1d(x, w, b, cfg: EncodecConfig, stride: int = 1, dilation: int = 1):
    """EncodecConv1d: x [B, C, T], w [out, in, k] (weight-norm folded)."""
    k = (w.shape[-1] - 1) * dilation + 1
    pad_total = k - stride
    T = x.shape[-1]
    n_frames = (T - k + pad_total) / stride + 1
    ideal = (math.ceil(n_frames) - 1) * stride + k - pad_total
    extra = ideal - T
    if cfg.use_causal_conv:
        x = _pad1d(x, pad_total, extra, cfg.pad_mode)
    else:
        right = pad_total // 2
        x = _pad1d(x, pad_total - right, right + extra, cfg.pad_mode)
    out = jax.lax.conv_general_dilated(
        x, w, (stride,), [(0, 0)], rhs_dilation=(dilation,),
        dimension_numbers=("NCH", "OIH", "NCH"))
    return out + b[None, :, None]


def conv_transpose1d(x, w, b, cfg: EncodecConfig, stride: int):
    """EncodecConvTranspose1d: w [in, out, k] (torch layout, folded)."""
    k = w.shape[-1]
    # torch ConvTranspose1d weight is [in, out, k]; with
    # transpose_kernel=True jax treats the kernel as the FORWARD conv's
    # (spatially flipped, I/O swapped), so the torch layout maps to "OIH"
    out = jax.lax.conv_transpose(
        x, w, (stride,), "VALID",
        dimension_numbers=("NCH", "OIH", "NCH"), transpose_kernel=True)
    out = out + b[None, :, None]
    pad_total = k - stride
    if cfg.use_causal_conv:
        right = math.ceil(pad_total * cfg.trim_right_ratio)
    else:
        right = pad_total // 2
    left = pad_total - right
    end = out.shape[-1] - right
    return out[..., left:end]


def _lstm_layer(x, wi, wh, bi, bh):
    """One LSTM layer over x [T, B, D] (torch gate order i, f, g, o)."""
    D = wh.shape[-1]

    def step(carry, xt):
        h, c = carry
        gates = xt @ wi.T + bi + h @ wh.T + bh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h

    B = x.shape[1]
    h0 = jnp.zeros((B, D), x.dtype)
    _, ys = jax.lax.scan(step, (h0, h0), x)
    return ys


def lstm_residual(x, layers):
    """EncodecLSTM: x [B, C, T] -> lstm stack + residual."""
    h = x.transpose(2, 0, 1)          # [T, B, C]
    y = h
    for wi, wh, bi, bh in layers:
        y = _lstm_layer(y, wi, wh, bi, bh)
    return (y + h).transpose(1, 2, 0)


def rvq_decode(codes, codebooks):
    """codes [nq, B, T] int32; codebooks [nq][codebook_size, dim] ->
    [B, dim, T] (sum of per-quantizer codebook rows)."""
    out = 0.0
    for i, cb in enumerate(codebooks):
        out = out + jnp.take(cb, codes[i], axis=0)   # [B, T, dim]
    return out.transpose(0, 2, 1)


def decode(params: dict, cfg: EncodecConfig, codes) -> jax.Array:
    """codes [nq, B, T] -> waveform [B, audio_channels, samples]."""
    x = rvq_decode(jnp.asarray(codes, jnp.int32), params["codebooks"])
    x = x.astype(jnp.float32)
    layers = params["decoder"]
    x = conv1d(x, *layers["conv_in"], cfg)
    x = lstm_residual(x, layers["lstm"])
    for up in layers["ups"]:
        x = jax.nn.elu(x)
        x = conv_transpose1d(x, up["convt_w"], up["convt_b"], cfg,
                             stride=up["stride"])
        for rb in up["resblocks"]:
            res = x
            h = jax.nn.elu(x)
            h = conv1d(h, rb["w1"], rb["b1"], cfg, dilation=rb["dilation"])
            h = jax.nn.elu(h)
            h = conv1d(h, rb["w2"], rb["b2"], cfg)
            if "ws" in rb:
                res = conv1d(res, rb["ws"], rb["bs"], cfg)
            x = res + h
    x = jax.nn.elu(x)
    x = conv1d(x, *layers["conv_out"], cfg)
    return x


def load_hf_params(tensors: dict, cfg: EncodecConfig, prefix: str = "") -> dict:
    """Build the decode-path pytree from a {name: np.ndarray} mapping
    (e.g. a loaded safetensors file). ``prefix`` selects a sub-model
    (\"audio_encoder.\" inside a MusicGen checkpoint)."""

    def get(name):
        return np.asarray(tensors[prefix + name])

    def fold(name, transpose_dim0=False):
        """Fold a weight-norm parametrized conv kernel: w = g * v/||v||.
        torch weight_norm uses dim=0 for BOTH Conv1d and ConvTranspose1d
        (g has shape [dim0, 1, 1]; norm over the remaining dims)."""
        base = name + ".parametrizations.weight"
        g = get(base + ".original0")
        v = get(base + ".original1")
        norm = np.sqrt((v ** 2).sum(axis=(1, 2), keepdims=True))
        w = g * v / np.maximum(norm, 1e-12)
        b = get(name + ".bias")
        return jnp.asarray(w, jnp.float32), jnp.asarray(b, jnp.float32)

    # layer indices in EncodecDecoder.layers (see module doc): 0 conv_in,
    # 1 lstm, then per ratio [ELU, convT, resblock x n], finally ELU, conv
    idx = 0
    dec = {}
    dec["conv_in"] = fold(f"decoder.layers.{idx}.conv")
    idx += 1
    lstm = []
    for li in range(cfg.num_lstm_layers):
        lstm.append(tuple(jnp.asarray(get(
            f"decoder.layers.{idx}.lstm.{nm}_l{li}"), jnp.float32)
            for nm in ("weight_ih", "weight_hh", "bias_ih", "bias_hh")))
    dec["lstm"] = lstm
    idx += 1
    ups = []
    for ratio in cfg.upsampling_ratios:
        idx += 1  # ELU
        wt, bt = fold(f"decoder.layers.{idx}.conv", transpose_dim0=True)
        up = {"convt_w": wt, "convt_b": bt, "stride": ratio, "resblocks": []}
        idx += 1
        for j in range(cfg.num_residual_layers):
            rb = {}
            base = f"decoder.layers.{idx}.block"
            rb["w1"], rb["b1"] = fold(base + ".1.conv")
            rb["w2"], rb["b2"] = fold(base + ".3.conv")
            rb["dilation"] = cfg.dilation_growth_rate ** j
            if cfg.use_conv_shortcut:
                rb["ws"], rb["bs"] = fold(
                    f"decoder.layers.{idx}.shortcut.conv")
            up["resblocks"].append(rb)
            idx += 1
        ups.append(up)
    dec["ups"] = ups
    idx += 1  # ELU
    dec["conv_out"] = fold(f"decoder.layers.{idx}.conv")

    codebooks = []
    i = 0
    while prefix + f"quantizer.layers.{i}.codebook.embed" in tensors:
        codebooks.append(jnp.asarray(
            get(f"quantizer.layers.{i}.codebook.embed"), jnp.float32))
        i += 1
    return {"decoder": dec, "codebooks": codebooks}
