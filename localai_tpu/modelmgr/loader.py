"""Model lifecycle: load/route/shutdown backends per model.

Parity with the reference's ModelLoader (reference: pkg/model/loader.go:22-28
model map keyed by modelID; initializers.go:457 BackendLoader, :502
GreedyLoader ordered autodetect, :402-423 health-check poll loop,
loader.go:143-168 busy-aware shutdown, loader.go:170-206 CheckIsLoaded
zombie cleanup; external backends initializers.go:336-360).

TPU re-design: backends are Python modules spawned as gRPC subprocesses
(or in-process servers for tests/embedded use). Capability probing is not
CPU-flag selection (AVX/CUDA variants) but device platform: one engine
binary serves any TPU/CPU host because XLA owns code generation.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendClient, BackendServicer, make_server
from localai_tpu.modelmgr.process import BackendProcess, free_port, spawn_python_backend

log = logging.getLogger("localai_tpu.modelmgr.loader")

# ordered by priority, mirroring the reference's autoload order
# (initializers.go:33-57): the main engine first, specialized after.
KNOWN_BACKENDS: dict = {
    "tpu-llm": "localai_tpu.backend.runner",
    "tpu-embeddings": "localai_tpu.backend.embed_runner",
    "tpu-rerank": "localai_tpu.backend.rerank_runner",
    "tpu-diffusion": "localai_tpu.backend.diffusion_runner",
    "tpu-whisper": "localai_tpu.backend.whisper_runner",
    "tpu-tts": "localai_tpu.backend.tts_runner",
    "local-store": "localai_tpu.backend.store_backend",
    "fake": "localai_tpu.backend.fake",
    # remote HF Inference API passthrough (reference:
    # backend/go/llm/langchain — lowest greedy priority)
    "langchain-huggingface": "localai_tpu.backend.remote_runner",
}
GREEDY_ORDER = ["tpu-llm", "langchain-huggingface"]


class LoadedModel:
    def __init__(self, model_id: str, backend_name: str, client: BackendClient,
                 process: Optional[BackendProcess] = None, server=None):
        self.model_id = model_id
        self.backend_name = backend_name
        self.client = client
        self.process = process
        self.server = server  # in-process grpc server (embedded backends)
        self.last_used = time.monotonic()
        self.busy = 0
        self.health_fails = 0     # consecutive failed idle health probes
        self.first_fail_t = 0.0   # when the current failure streak began
        self.watchdog = None  # set by ModelLoader when a watchdog is attached
        self._lock = threading.Lock()

    def mark_busy(self):
        with self._lock:
            self.busy += 1
            self.last_used = time.monotonic()
        if self.watchdog is not None:
            self.watchdog.mark(self.model_id, True)

    def mark_idle(self):
        with self._lock:
            self.busy = max(0, self.busy - 1)
            idle = self.busy == 0
            self.last_used = time.monotonic()
            # a completed request is the strongest health signal there is
            self.health_fails = 0
        if idle and self.watchdog is not None:
            self.watchdog.mark(self.model_id, False)

    def close(self):
        try:
            self.client.close()
        except Exception:
            pass
        if self.server is not None:
            self.server.stop(grace=1)
        if self.process is not None:
            self.process.stop()


class ModelLoader:
    def __init__(self, health_attempts: int = 600, health_interval_s: float = 0.5,
                 single_active: bool = False):
        self.models: dict[str, LoadedModel] = {}
        self._lock = threading.Lock()           # guards the dicts only
        self._load_locks: dict[str, threading.Lock] = {}  # serialize per-model loads
        self.health_attempts = health_attempts
        self.health_interval_s = health_interval_s
        self.single_active = single_active
        self.external_backends: dict[str, str] = {}   # name -> module or host:port
        self.embedded: dict[str, Callable[[], BackendServicer]] = {}
        self.watchdog = None

    # ---- registration ----

    def register_external(self, name: str, target: str):
        """target: python module path or 'host:port' (reference:
        EXTERNAL_GRPC_BACKENDS semantics, initializers.go:336-360)."""
        self.external_backends[name] = target

    def register_embedded(self, name: str, factory: Callable[[], BackendServicer]):
        """In-process backend (reference: pkg/grpc/embed.go Provide)."""
        self.embedded[name] = factory

    # ---- loading ----

    def backend_loader(self, backend_name: str, model_id: str,
                       model_opts: pb.ModelOptions) -> LoadedModel:
        # per-model serialization; the global lock is only held for dict ops
        # so a multi-minute weight load never blocks other models' lookups
        with self._lock:
            load_lock = self._load_locks.setdefault(model_id, threading.Lock())
        with load_lock:
            with self._lock:
                lm = self.models.get(model_id)
            if lm is not None:
                # a BUSY backend is alive by definition (requests are
                # streaming through it) — probing it with a short-timeout
                # health RPC under load is how r4's bench watched the
                # loader KILL a healthy, saturated backend mid-serving
                # (the gRPC thread can answer slowly when the host core
                # is contended). Idle backends are probed, but a single
                # failed/timed-out probe must NOT kill a live process
                # either (same failure mode, observed in a busy==0 gap):
                # respawn only when the process is actually dead or three
                # consecutive probes failed. A truly wedged-but-alive
                # backend is the watchdog's job (busy-too-long kills).
                dead = lm.process is not None and not lm.process.alive()
                now = time.monotonic()
                if not dead and lm.busy > 0:
                    lm.last_used = now
                    return lm
                if not dead and self._healthy(lm):
                    lm.health_fails = 0
                    lm.last_used = now
                    return lm
                if lm.health_fails == 0:
                    lm.first_fail_t = now
                lm.health_fails += 1
                # back-to-back probes inside one transient stall must not
                # exhaust the strikes: require >= 3 failures SPREAD over
                # >= 30s before replacing a live process
                if not dead and (lm.health_fails < 3
                                 or now - lm.first_fail_t < 30.0):
                    log.warning("model %s health probe failed (%d); "
                                "keeping the live backend", model_id,
                                lm.health_fails)
                    lm.last_used = now
                    return lm
                log.warning("model %s backend %s; respawning", model_id,
                            "process died" if dead else
                            "unhealthy repeatedly")
                with self._lock:
                    self._drop(model_id)
            if self.single_active:
                with self._lock:
                    idle_others = [m for m, o in self.models.items()
                                   if m != model_id and o.busy == 0]
                    for other_id in idle_others:
                        self._drop(other_id)
            lm = self._spawn_and_load(backend_name, model_id, model_opts)
            with self._lock:
                self.models[model_id] = lm
            return lm

    def greedy_loader(self, model_id: str, model_opts: pb.ModelOptions,
                      order: Optional[list] = None) -> LoadedModel:
        """Try backends in priority order (reference: GreedyLoader
        initializers.go:502)."""
        errors = []
        for name in order or GREEDY_ORDER:
            try:
                return self.backend_loader(name, model_id, model_opts)
            except Exception as e:
                errors.append(f"{name}: {e}")
        raise RuntimeError("could not load model with any backend: " + "; ".join(errors))

    def _spawn_and_load(self, backend_name: str, model_id: str,
                        model_opts: pb.ModelOptions) -> LoadedModel:
        client, process, server = self._connect_backend(backend_name)
        try:
            self._wait_healthy(client, process)
            res = client.load_model(model_opts)
            if not res.success:
                raise RuntimeError(f"LoadModel failed: {res.message}")
        except Exception:
            client.close()
            if server is not None:
                server.stop(grace=0)
            if process is not None:
                process.stop()
            raise
        lm = LoadedModel(model_id, backend_name, client, process, server)
        lm.watchdog = self.watchdog
        if self.watchdog is not None:
            self.watchdog.add(model_id, lm)
        return lm

    def _connect_backend(self, backend_name: str):
        """Returns (client, process|None, inproc_server|None)."""
        if backend_name in self.embedded:
            addr = f"127.0.0.1:{free_port()}"
            server = make_server(self.embedded[backend_name](), addr)
            server.start()
            return BackendClient(addr), None, server
        target = self.external_backends.get(backend_name)
        if target and _looks_like_addr(target):
            return BackendClient(target), None, None
        module = target or KNOWN_BACKENDS.get(backend_name)
        if module is None:
            raise ValueError(f"unknown backend: {backend_name}")
        process = spawn_python_backend(module, name=backend_name)
        return BackendClient(process.addr), process, None

    def _wait_healthy(self, client: BackendClient, process: Optional[BackendProcess]):
        for _ in range(self.health_attempts):
            if process is not None and not process.alive():
                raise RuntimeError("backend process died during startup")
            if client.health(timeout=1.0):
                return
            time.sleep(self.health_interval_s)
        raise TimeoutError("backend did not become healthy")

    def _healthy(self, lm: LoadedModel) -> bool:
        if lm.process is not None and not lm.process.alive():
            return False
        return lm.client.health(timeout=5.0)

    # ---- queries ----

    def get(self, model_id: str) -> Optional[LoadedModel]:
        with self._lock:
            return self.models.get(model_id)

    def list_loaded(self) -> list:
        with self._lock:
            return list(self.models.keys())

    # ---- shutdown ----

    def shutdown_model(self, model_id: str, force: bool = False,
                       max_wait_s: float = 120.0):
        """Busy-aware shutdown (reference: loader.go:143-168)."""
        deadline = time.monotonic() + max_wait_s
        wait = 2.0
        while True:
            with self._lock:
                lm = self.models.get(model_id)
                if lm is None:
                    return
                if lm.busy == 0 or force or time.monotonic() > deadline:
                    self._drop(model_id)
                    return
            time.sleep(min(wait, 5.0))
            wait *= 1.5

    def _drop(self, model_id: str):
        lm = self.models.pop(model_id, None)
        if lm is not None:
            if self.watchdog is not None:
                self.watchdog.remove(model_id)
            lm.close()

    def stop_all(self):
        with self._lock:
            for model_id in list(self.models):
                self._drop(model_id)


def _looks_like_addr(target: str) -> bool:
    host, _, port = target.rpartition(":")
    return bool(host) and port.isdigit()
