"""Model lifecycle: load/route/shutdown backends per model.

Parity with the reference's ModelLoader (reference: pkg/model/loader.go:22-28
model map keyed by modelID; initializers.go:457 BackendLoader, :502
GreedyLoader ordered autodetect, :402-423 health-check poll loop,
loader.go:143-168 busy-aware shutdown, loader.go:170-206 CheckIsLoaded
zombie cleanup; external backends initializers.go:336-360).

TPU re-design: backends are Python modules spawned as gRPC subprocesses
(or in-process servers for tests/embedded use). Capability probing is not
CPU-flag selection (AVX/CUDA variants) but device platform: one engine
binary serves any TPU/CPU host because XLA owns code generation.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Optional

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.service import BackendClient, BackendServicer, make_server
from localai_tpu.modelmgr.process import BackendProcess, free_port, spawn_python_backend
from localai_tpu.services.errors import CircuitOpenError
from localai_tpu.services.eventlog import EVENTS

log = logging.getLogger("localai_tpu.modelmgr.loader")


class CircuitBreaker:
    """Per-model load circuit breaker (ISSUE 7 crash recovery): after
    ``threshold`` CONSECUTIVE spawn/LoadModel failures the breaker opens
    and load attempts fail fast with CircuitOpenError (HTTP 503 with the
    breaker state in the body) for ``cooldown_s`` — a crash-looping
    checkpoint must not burn a spawn + multi-second weight load per
    request. After the cooldown one probe attempt is let through
    (half-open); its outcome closes or re-opens the breaker."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 name: str = ""):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.name = name            # model id, for event-log records
        self.failures = 0
        self.state = "closed"       # closed | open | half-open
        self.opened_t = 0.0
        self._lock = threading.Lock()

    def check(self, model_id: str):
        """Raise CircuitOpenError if open; transition to half-open when
        the cooldown has elapsed (that caller becomes the probe)."""
        with self._lock:
            if self.state != "open":
                return
            remaining = self.cooldown_s - (time.monotonic() - self.opened_t)
            if remaining <= 0:
                self.state = "half-open"
                EVENTS.emit("circuit_half_open", model=self.name or model_id)
                return
            # breaker-state dict built inline: snapshot() takes this same
            # non-reentrant lock
            raise CircuitOpenError(
                f"circuit open for model {model_id}: {self.failures} "
                f"consecutive load failures; retry in {remaining:.1f}s",
                retry_after_s=max(1.0, remaining),
                detail={"breaker": {
                    "state": "open", "failures": self.failures,
                    "cooldown_s": self.cooldown_s,
                    "retry_after_s": round(remaining, 1)}})

    def record_failure(self):
        opened = False
        with self._lock:
            self.failures += 1
            if self.state == "half-open" or self.failures >= self.threshold:
                opened = self.state != "open"
                self.state = "open"
                self.opened_t = time.monotonic()
            n = self.failures
        if opened:
            EVENTS.emit("circuit_open", model=self.name, failures=n,
                        cooldown_s=self.cooldown_s)

    def record_success(self):
        with self._lock:
            closed = self.state != "closed"
            self.failures = 0
            self.state = "closed"
        if closed:
            EVENTS.emit("circuit_close", model=self.name)

    def snapshot(self) -> dict:
        with self._lock:
            remaining = 0.0
            if self.state == "open":
                remaining = max(0.0, self.cooldown_s
                                - (time.monotonic() - self.opened_t))
            return {"state": self.state, "failures": self.failures,
                    "cooldown_s": self.cooldown_s,
                    "retry_after_s": round(remaining, 1)}

# ordered by priority, mirroring the reference's autoload order
# (initializers.go:33-57): the main engine first, specialized after.
KNOWN_BACKENDS: dict = {
    "tpu-llm": "localai_tpu.backend.runner",
    "tpu-embeddings": "localai_tpu.backend.embed_runner",
    "tpu-rerank": "localai_tpu.backend.rerank_runner",
    "tpu-diffusion": "localai_tpu.backend.diffusion_runner",
    "tpu-whisper": "localai_tpu.backend.whisper_runner",
    "tpu-tts": "localai_tpu.backend.tts_runner",
    "local-store": "localai_tpu.backend.store_backend",
    "fake": "localai_tpu.backend.fake",
    # remote HF Inference API passthrough (reference:
    # backend/go/llm/langchain — lowest greedy priority)
    "langchain-huggingface": "localai_tpu.backend.remote_runner",
}
GREEDY_ORDER = ["tpu-llm", "langchain-huggingface"]


class LoadedModel:
    def __init__(self, model_id: str, backend_name: str, client: BackendClient,
                 process: Optional[BackendProcess] = None, server=None):
        self.model_id = model_id
        self.backend_name = backend_name
        self.client = client
        self.process = process
        self.server = server  # in-process grpc server (embedded backends)
        self.last_used = time.monotonic()
        self.busy = 0
        self.health_fails = 0     # consecutive failed idle health probes
        self.first_fail_t = 0.0   # when the current failure streak began
        self.watchdog = None  # set by ModelLoader when a watchdog is attached
        # set before close() so the supervisor thread can tell an
        # operator-requested shutdown from a crash it must respawn
        self.intentional_stop = False
        self.supervisor: Optional[threading.Thread] = None
        # cross-process clock handshake (ISSUE 12): offset/rtt measured
        # around LoadModel, used to shift backend trace timestamps onto
        # the frontend timeline. {} when the backend sent no handshake
        # (e.g. FakeServicer's plain "loaded") — merge then falls back
        # to raw epochs. Re-measured automatically on respawn because
        # every spawn goes through _spawn_and_load.
        self.clock: dict = {}
        self._lock = threading.Lock()

    def mark_busy(self):
        with self._lock:
            self.busy += 1
            self.last_used = time.monotonic()
        if self.watchdog is not None:
            self.watchdog.mark(self.model_id, True)

    def mark_idle(self):
        with self._lock:
            self.busy = max(0, self.busy - 1)
            idle = self.busy == 0
            self.last_used = time.monotonic()
            # a completed request is the strongest health signal there is
            self.health_fails = 0
        if idle and self.watchdog is not None:
            self.watchdog.mark(self.model_id, False)

    def close(self):
        self.intentional_stop = True
        try:
            self.client.close()
        except Exception:
            pass
        if self.server is not None:
            self.server.stop(grace=1)
        if self.process is not None:
            self.process.stop()


class ModelLoader:
    def __init__(self, health_attempts: int = 600, health_interval_s: float = 0.5,
                 single_active: bool = False,
                 breaker_threshold: int = 3, breaker_cooldown_s: float = 30.0,
                 respawn_backoff_base_s: float = 0.5,
                 respawn_backoff_cap_s: float = 15.0,
                 respawn_max_attempts: int = 5):
        self.models: dict[str, LoadedModel] = {}
        self._lock = threading.Lock()           # guards the dicts only
        self._load_locks: dict[str, threading.Lock] = {}  # serialize per-model loads
        self.health_attempts = health_attempts
        self.health_interval_s = health_interval_s
        self.single_active = single_active
        self.external_backends: dict[str, str] = {}   # name -> module or host:port
        self.embedded: dict[str, Callable[[], BackendServicer]] = {}
        self.watchdog = None
        # crash recovery (ISSUE 7): per-model circuit breakers, supervisor
        # respawn backoff, and respawn telemetry for /metrics
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        self.respawn_backoff_base_s = respawn_backoff_base_s
        self.respawn_backoff_cap_s = respawn_backoff_cap_s
        self.respawn_max_attempts = respawn_max_attempts
        self._breakers: dict[str, CircuitBreaker] = {}
        self.respawns: dict[str, int] = {}
        self._closed = False

    # ---- registration ----

    def register_external(self, name: str, target: str):
        """target: python module path or 'host:port' (reference:
        EXTERNAL_GRPC_BACKENDS semantics, initializers.go:336-360)."""
        self.external_backends[name] = target

    def register_embedded(self, name: str, factory: Callable[[], BackendServicer]):
        """In-process backend (reference: pkg/grpc/embed.go Provide)."""
        self.embedded[name] = factory

    # ---- loading ----

    def backend_loader(self, backend_name: str, model_id: str,
                       model_opts: pb.ModelOptions) -> LoadedModel:
        # per-model serialization; the global lock is only held for dict ops
        # so a multi-minute weight load never blocks other models' lookups
        with self._lock:
            load_lock = self._load_locks.setdefault(model_id, threading.Lock())
        with load_lock:
            with self._lock:
                lm = self.models.get(model_id)
            if lm is not None:
                # a BUSY backend is alive by definition (requests are
                # streaming through it) — probing it with a short-timeout
                # health RPC under load is how r4's bench watched the
                # loader KILL a healthy, saturated backend mid-serving
                # (the gRPC thread can answer slowly when the host core
                # is contended). Idle backends are probed, but a single
                # failed/timed-out probe must NOT kill a live process
                # either (same failure mode, observed in a busy==0 gap):
                # respawn only when the process is actually dead or three
                # consecutive probes failed. A truly wedged-but-alive
                # backend is the watchdog's job (busy-too-long kills).
                dead = lm.process is not None and not lm.process.alive()
                now = time.monotonic()
                if not dead and lm.busy > 0:
                    lm.last_used = now
                    return lm
                if not dead and self._healthy(lm):
                    lm.health_fails = 0
                    lm.last_used = now
                    return lm
                if lm.health_fails == 0:
                    lm.first_fail_t = now
                lm.health_fails += 1
                # back-to-back probes inside one transient stall must not
                # exhaust the strikes: require >= 3 failures SPREAD over
                # >= 30s before replacing a live process
                if not dead and (lm.health_fails < 3
                                 or now - lm.first_fail_t < 30.0):
                    log.warning("model %s health probe failed (%d); "
                                "keeping the live backend", model_id,
                                lm.health_fails)
                    lm.last_used = now
                    return lm
                log.warning("model %s backend %s; respawning", model_id,
                            "process died" if dead else
                            "unhealthy repeatedly")
                self._drop(model_id)
            if self.single_active:
                # pop victims under the lock, close OUTSIDE it: close()
                # can block up to 10 s in the process-stop grace, and
                # holding the global lock through it stalls every other
                # loader operation (ISSUE 7 satellite)
                with self._lock:
                    victims = [self._pop_locked(m)
                               for m, o in list(self.models.items())
                               if m != model_id and o.busy == 0]
                for v in victims:
                    self._close_lm(v)
            # circuit breaker: a crash-looping model fails fast here with
            # the breaker state instead of burning another spawn + load
            breaker = self._breaker(model_id)
            breaker.check(model_id)
            try:
                lm = self._spawn_and_load(backend_name, model_id, model_opts)
            except Exception:
                breaker.record_failure()
                raise
            breaker.record_success()
            with self._lock:
                self.models[model_id] = lm
            self._start_supervisor(lm, backend_name, model_opts)
            return lm

    def _breaker(self, model_id: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(model_id)
            if b is None:
                b = self._breakers[model_id] = CircuitBreaker(
                    self.breaker_threshold, self.breaker_cooldown_s,
                    name=model_id)
            return b

    # ---- crash recovery (ISSUE 7) ----

    def _start_supervisor(self, lm: LoadedModel, backend_name: str,
                          model_opts: pb.ModelOptions):
        """Waiter thread on the backend process: detects death the moment
        the kernel reaps it (no polling interval) and respawns with
        exponential backoff + jitter. In-flight streams fail immediately
        at the gRPC layer (UNAVAILABLE -> structured retryable error via
        services/errors.py); this thread restores capacity for the NEXT
        request."""
        if lm.process is None:
            return
        t = threading.Thread(
            target=self._supervise, args=(lm, backend_name, model_opts),
            name=f"supervise-{lm.model_id}", daemon=True)
        lm.supervisor = t
        t.start()

    def _supervise(self, lm: LoadedModel, backend_name: str,
                   model_opts: pb.ModelOptions):
        rc = lm.process.proc.wait()
        if lm.intentional_stop or self._closed:
            return
        with self._lock:
            if self.models.get(lm.model_id) is not lm:
                return  # already replaced/dropped by another path
            self.respawns[lm.model_id] = self.respawns.get(lm.model_id, 0) + 1
            n_respawns = self.respawns[lm.model_id]
        log.warning(
            "backend for model %s died unexpectedly (exit %s); "
            "respawning with backoff", lm.model_id, rc)
        EVENTS.emit("respawn", model=lm.model_id, exit_code=rc,
                    respawns=n_respawns)
        base = self.respawn_backoff_base_s
        for attempt in range(self.respawn_max_attempts):
            # full jitter: crash-looping fleets must not thunder in sync
            delay = min(self.respawn_backoff_cap_s,
                        base * (2 ** attempt)) * (0.5 + random.random())
            time.sleep(delay)
            if self._closed or lm.intentional_stop:
                return
            try:
                # backend_loader sees the dead process and replaces it;
                # the breaker counts consecutive failures for us
                self.backend_loader(backend_name, lm.model_id, model_opts)
                return
            except CircuitOpenError:
                return  # breaker open: stop burning spawns; loads re-probe
            except Exception as e:
                log.warning("respawn attempt %d/%d for model %s failed: %s",
                            attempt + 1, self.respawn_max_attempts,
                            lm.model_id, e)
        log.error("model %s: giving up after %d respawn attempts",
                  lm.model_id, self.respawn_max_attempts)

    def stats(self) -> dict:
        """Per-model recovery telemetry for /readyz and /metrics:
        {model: {respawns, breaker, circuit_state}} with circuit_state
        encoded 0=closed 1=open 2=half-open (Prometheus gauge)."""
        with self._lock:
            names = set(self.models) | set(self._breakers) | set(self.respawns)
            breakers = dict(self._breakers)
            respawns = dict(self.respawns)
        out = {}
        code = {"closed": 0, "open": 1, "half-open": 2}
        for name in names:
            b = breakers.get(name)
            snap = b.snapshot() if b is not None else {
                "state": "closed", "failures": 0,
                "cooldown_s": self.breaker_cooldown_s, "retry_after_s": 0.0}
            out[name] = {"respawns": respawns.get(name, 0),
                         "breaker": snap,
                         "circuit_state": code.get(snap["state"], 0)}
        return out

    def greedy_loader(self, model_id: str, model_opts: pb.ModelOptions,
                      order: Optional[list] = None) -> LoadedModel:
        """Try backends in priority order (reference: GreedyLoader
        initializers.go:502)."""
        errors = []
        for name in order or GREEDY_ORDER:
            try:
                return self.backend_loader(name, model_id, model_opts)
            except CircuitOpenError:
                # breaker open is per-MODEL, not per-backend: trying the
                # next backend would re-raise from the same breaker; the
                # whole point is a fast 503 with the breaker state
                raise
            except Exception as e:
                errors.append(f"{name}: {e}")
        raise RuntimeError("could not load model with any backend: " + "; ".join(errors))

    def _spawn_and_load(self, backend_name: str, model_id: str,
                        model_opts: pb.ModelOptions) -> LoadedModel:
        client, process, server = self._connect_backend(backend_name)
        try:
            self._wait_healthy(client, process)
            t_send = time.time()
            res = client.load_model(model_opts)
            t_recv = time.time()
            if not res.success:
                raise RuntimeError(f"LoadModel failed: {res.message}")
        except Exception:
            client.close()
            if server is not None:
                server.stop(grace=0)
            if process is not None:
                process.stop()
            raise
        lm = LoadedModel(model_id, backend_name, client, process, server)
        lm.clock = _parse_handshake(res.message, t_send, t_recv)
        lm.watchdog = self.watchdog
        if self.watchdog is not None:
            self.watchdog.add(model_id, lm)
        return lm

    def _connect_backend(self, backend_name: str):
        """Returns (client, process|None, inproc_server|None)."""
        if backend_name in self.embedded:
            addr = f"127.0.0.1:{free_port()}"
            server = make_server(self.embedded[backend_name](), addr)
            server.start()
            return BackendClient(addr), None, server
        target = self.external_backends.get(backend_name)
        if target and _looks_like_addr(target):
            return BackendClient(target), None, None
        module = target or KNOWN_BACKENDS.get(backend_name)
        if module is None:
            raise ValueError(f"unknown backend: {backend_name}")
        process = spawn_python_backend(module, name=backend_name)
        return BackendClient(process.addr), process, None

    def _wait_healthy(self, client: BackendClient, process: Optional[BackendProcess]):
        for _ in range(self.health_attempts):
            if process is not None and not process.alive():
                raise RuntimeError("backend process died during startup")
            if client.health(timeout=1.0):
                return
            time.sleep(self.health_interval_s)
        raise TimeoutError("backend did not become healthy")

    def _healthy(self, lm: LoadedModel) -> bool:
        if lm.process is not None and not lm.process.alive():
            return False
        return lm.client.health(timeout=5.0)

    # ---- queries ----

    def get(self, model_id: str) -> Optional[LoadedModel]:
        with self._lock:
            return self.models.get(model_id)

    def list_loaded(self) -> list:
        with self._lock:
            return list(self.models.keys())

    # ---- shutdown ----

    def shutdown_model(self, model_id: str, force: bool = False,
                       max_wait_s: float = 120.0):
        """Busy-aware shutdown (reference: loader.go:143-168)."""
        deadline = time.monotonic() + max_wait_s
        wait = 2.0
        while True:
            with self._lock:
                lm = self.models.get(model_id)
                if lm is None:
                    return
                if lm.busy == 0 or force or time.monotonic() > deadline:
                    lm = self._pop_locked(model_id)
                else:
                    lm = None
            if lm is not None:
                # close OUTSIDE the lock: process.stop can block up to
                # its 10 s grace, and holding the global lock through it
                # stalls every other loader operation (ISSUE 7 satellite)
                self._close_lm(lm)
                return
            time.sleep(min(wait, 5.0))
            wait *= 1.5

    def _pop_locked(self, model_id: str) -> Optional[LoadedModel]:
        """Unregister a model; caller holds self._lock. The (possibly
        slow) close is the caller's job, outside the lock."""
        lm = self.models.pop(model_id, None)
        if lm is not None and self.watchdog is not None:
            self.watchdog.remove(model_id)
        return lm

    @staticmethod
    def _close_lm(lm: Optional[LoadedModel]):
        if lm is None:
            return
        lm.intentional_stop = True   # before close: park the supervisor
        try:
            lm.close()
        except Exception:
            log.exception("backend close failed for model %s", lm.model_id)

    def _drop(self, model_id: str):
        with self._lock:
            lm = self._pop_locked(model_id)
        self._close_lm(lm)

    def stop_all(self):
        self._closed = True
        with self._lock:
            victims = [self._pop_locked(m) for m in list(self.models)]
        for lm in victims:
            self._close_lm(lm)


def _parse_handshake(message: str, t_send: float, t_recv: float) -> dict:
    """Clock-offset handshake from a LoadModel reply (ISSUE 12).

    The backend stamps its wall clock inside the Result.message JSON;
    the midpoint of the RPC round-trip is the best single-sample
    estimate of WHEN that stamp was taken on the frontend's clock, so

        offset_s = backend_wall - (t_send + t_recv) / 2

    with the full round-trip as the honest uncertainty bound (the true
    offset lies within ±rtt/2 of the estimate). Backends that reply
    with a plain string (FakeServicer's "loaded", older runners) yield
    {} — merged traces then fall back to raw epoch alignment."""
    try:
        doc = __import__("json").loads(message)
        hs = doc.get("handshake") or {}
        bw = float(hs["wall"])
    except (ValueError, TypeError, KeyError, AttributeError):
        return {}
    return {
        "offset_s": bw - (t_send + t_recv) / 2.0,
        "rtt_s": max(0.0, t_recv - t_send),
        "backend_wall": bw,
        "backend_pid": int(hs.get("pid", 0) or 0),
        "trace_epoch": float(hs.get("trace_epoch", 0.0) or 0.0),
        "measured_at": t_recv,
    }


def _looks_like_addr(target: str) -> bool:
    host, _, port = target.rpartition(":")
    return bool(host) and port.isdigit()
