"""Backend process management: spawn, log-tail, terminate.

Parity with the reference's process manager (reference: pkg/model/
process.go:73-137 — chmod+exec with --addr, stdout/stderr tailed into the
core logs, SIGTERM cleanup), re-based on subprocess + threads.
"""

from __future__ import annotations

import logging
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Optional

log = logging.getLogger("localai_tpu.modelmgr.process")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class BackendProcess:
    """A spawned backend speaking the contract on 127.0.0.1:port."""

    def __init__(self, command: list, addr: str, env: Optional[dict] = None,
                 name: str = ""):
        self.command = command
        self.addr = addr
        self.name = name or os.path.basename(command[0])
        self.proc: Optional[subprocess.Popen] = None
        self._env = env
        self._tail_threads: list = []
        # readiness/failure markers observed in the log tail: the spawn
        # retry uses bind_failed to detect losing the free_port() -> bind
        # race ("address already in use", raised by make_server)
        self.started = threading.Event()
        self.bind_failed = threading.Event()

    def start(self):
        env = dict(os.environ)
        if self._env:
            env.update(self._env)
        log.info("starting backend %s: %s (addr %s)", self.name,
                 shlex.join(self.command), self.addr)
        self.proc = subprocess.Popen(
            self.command,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
            start_new_session=True,  # own process group for clean kill
        )
        for stream, level in ((self.proc.stdout, logging.DEBUG),
                              (self.proc.stderr, logging.DEBUG)):
            t = threading.Thread(target=self._tail, args=(stream, level), daemon=True)
            t.start()
            self._tail_threads.append(t)

    def _tail(self, stream, level):
        try:
            for line in iter(stream.readline, b""):
                text = line.decode(errors="replace").rstrip()
                if "gRPC Server listening at" in text:
                    self.started.set()
                elif "address already in use" in text.lower():
                    self.bind_failed.set()
                log.log(level, "[%s] %s", self.name, text)
        except ValueError:
            pass  # stream closed

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def stop(self, grace_s: float = 10.0):
        if not self.proc:
            return
        if self.proc.poll() is None:
            try:
                os.killpg(self.proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            deadline = time.monotonic() + grace_s
            while time.monotonic() < deadline and self.proc.poll() is None:
                time.sleep(0.1)
            if self.proc.poll() is None:
                try:
                    os.killpg(self.proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        # drain the tails before closing the pipes (ISSUE 7 satellite):
        # the readers see EOF once the process is dead, so this is
        # bounded — closing first silently dropped the final log lines
        for t in self._tail_threads:
            t.join(timeout=5.0)
        self._tail_threads = []
        for s in (self.proc.stdout, self.proc.stderr):
            try:
                s.close()
            except Exception:
                pass


def spawn_python_backend(module: str, extra_args: Optional[list] = None,
                         env: Optional[dict] = None, name: str = "",
                         bind_race_wait_s: float = 2.0) -> BackendProcess:
    """Spawn `python -m <module> --addr 127.0.0.1:<freeport>`.

    free_port() closes its probe socket before the backend binds, so
    another process can steal the port in between (ISSUE 7 satellite):
    if the child dies with "address already in use" in its tail, retry
    ONCE with a fresh port. Deliberately one retry — a second loss in a
    row means something is systematically wrong with the port space.
    """
    for attempt in (0, 1):
        port = free_port()
        addr = f"127.0.0.1:{port}"
        cmd = [sys.executable, "-m", module, "--addr", addr] + (extra_args or [])
        bp = BackendProcess(cmd, addr, env=env, name=name or module)
        bp.start()
        if attempt == 1:
            return bp
        # watch briefly for the bind race losing; a slow import simply
        # exhausts the window and proceeds to the caller's health poll
        deadline = time.monotonic() + bind_race_wait_s
        while time.monotonic() < deadline:
            if bp.started.is_set() or bp.bind_failed.is_set() \
                    or not bp.alive():
                break
            time.sleep(0.02)
        if not bp.alive():
            # the tail may stamp bind_failed slightly after poll() flips:
            # give the reader threads a moment to drain the death message
            for t in bp._tail_threads:
                t.join(timeout=1.0)
        if not bp.bind_failed.is_set():
            return bp
        log.warning("backend %s lost the %s bind race; retrying on a "
                    "fresh port", bp.name, addr)
        bp.stop(grace_s=0.0)
    return bp  # unreachable; satisfies the type checker
