"""Backend process management: spawn, log-tail, terminate.

Parity with the reference's process manager (reference: pkg/model/
process.go:73-137 — chmod+exec with --addr, stdout/stderr tailed into the
core logs, SIGTERM cleanup), re-based on subprocess + threads.
"""

from __future__ import annotations

import logging
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Optional

log = logging.getLogger("localai_tpu.modelmgr.process")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class BackendProcess:
    """A spawned backend speaking the contract on 127.0.0.1:port."""

    def __init__(self, command: list, addr: str, env: Optional[dict] = None,
                 name: str = ""):
        self.command = command
        self.addr = addr
        self.name = name or os.path.basename(command[0])
        self.proc: Optional[subprocess.Popen] = None
        self._env = env
        self._tail_threads: list = []

    def start(self):
        env = dict(os.environ)
        if self._env:
            env.update(self._env)
        log.info("starting backend %s: %s (addr %s)", self.name,
                 shlex.join(self.command), self.addr)
        self.proc = subprocess.Popen(
            self.command,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
            start_new_session=True,  # own process group for clean kill
        )
        for stream, level in ((self.proc.stdout, logging.DEBUG),
                              (self.proc.stderr, logging.DEBUG)):
            t = threading.Thread(target=self._tail, args=(stream, level), daemon=True)
            t.start()
            self._tail_threads.append(t)

    def _tail(self, stream, level):
        try:
            for line in iter(stream.readline, b""):
                log.log(level, "[%s] %s", self.name, line.decode(errors="replace").rstrip())
        except ValueError:
            pass  # stream closed

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def stop(self, grace_s: float = 10.0):
        if not self.proc:
            return
        if self.proc.poll() is None:
            try:
                os.killpg(self.proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            deadline = time.monotonic() + grace_s
            while time.monotonic() < deadline and self.proc.poll() is None:
                time.sleep(0.1)
            if self.proc.poll() is None:
                try:
                    os.killpg(self.proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        for s in (self.proc.stdout, self.proc.stderr):
            try:
                s.close()
            except Exception:
                pass


def spawn_python_backend(module: str, extra_args: Optional[list] = None,
                         env: Optional[dict] = None, name: str = "") -> BackendProcess:
    """Spawn `python -m <module> --addr 127.0.0.1:<freeport>`."""
    port = free_port()
    addr = f"127.0.0.1:{port}"
    cmd = [sys.executable, "-m", module, "--addr", addr] + (extra_args or [])
    bp = BackendProcess(cmd, addr, env=env, name=name or module)
    bp.start()
    return bp
