"""WatchDog: kill backends that are busy too long or idle too long.

Parity with the reference (reference: pkg/model/watchdog.go:19-156 —
busy/idle marks per backend, 30s sweep, kills over-threshold backends).
"""

from __future__ import annotations

import logging
import threading
import time

from localai_tpu.services.eventlog import EVENTS

log = logging.getLogger("localai_tpu.modelmgr.watchdog")


class WatchDog:
    def __init__(self, loader, busy_timeout_s: float = 300.0,
                 idle_timeout_s: float = 900.0, check_busy: bool = False,
                 check_idle: bool = False, sweep_interval_s: float = 30.0):
        self.loader = loader
        self.busy_timeout_s = busy_timeout_s
        self.idle_timeout_s = idle_timeout_s
        self.check_busy = check_busy
        self.check_idle = check_idle
        self.sweep_interval_s = sweep_interval_s
        self._busy_since: dict[str, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, name="watchdog", daemon=True)
        self._thread.start()

    def shutdown(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def add(self, model_id: str, lm):
        pass  # tracking happens through mark()/loader state

    def remove(self, model_id: str):
        with self._lock:
            self._busy_since.pop(model_id, None)

    def mark(self, model_id: str, busy: bool):
        with self._lock:
            if busy:
                self._busy_since.setdefault(model_id, time.monotonic())
            else:
                self._busy_since.pop(model_id, None)

    def _run(self):
        while not self._stop.wait(self.sweep_interval_s):
            try:
                now = time.monotonic()
                if self.check_busy:
                    with self._lock:
                        stuck = [m for m, t in self._busy_since.items()
                                 if now - t > self.busy_timeout_s]
                    for m in stuck:
                        log.warning("watchdog: %s busy > %.0fs, killing", m, self.busy_timeout_s)
                        EVENTS.emit("watchdog_kill", model=m, reason="busy",
                                    timeout_s=self.busy_timeout_s)
                        self.loader.shutdown_model(m, force=True)
                if self.check_idle:
                    for m in self.loader.list_loaded():
                        lm = self.loader.get(m)
                        if lm and lm.busy == 0 and now - lm.last_used > self.idle_timeout_s:
                            log.info("watchdog: %s idle > %.0fs, releasing", m, self.idle_timeout_s)
                            EVENTS.emit("watchdog_kill", model=m,
                                        reason="idle",
                                        timeout_s=self.idle_timeout_s)
                            self.loader.shutdown_model(m, force=True)
            except Exception:
                log.exception("watchdog sweep failed")
