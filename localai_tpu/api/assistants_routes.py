"""OpenAI Assistants + Files APIs with JSON-blob persistence.

Capability parity with the reference (reference:
core/http/endpoints/openai/assistant.go:1-522 — assistant CRUD + modify +
assistant-file attach/list/get/delete persisted to assistants.json /
assistantsFile.json; core/http/endpoints/openai/files.go:1-194 — multipart
upload, purpose filter, content download, persisted to uploadedFiles.json;
blobs reloaded at boot, core/http/app.go:154-156).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

from aiohttp import web

from localai_tpu.api.app import get_state

ASSISTANTS_FILE = "assistants.json"
ASSISTANT_FILES_FILE = "assistantsFile.json"
UPLOADED_FILES_FILE = "uploadedFiles.json"


class AssistantStore:
    """File-backed store for assistants, assistant-file links, and uploads."""

    def __init__(self, upload_dir: str):
        self.dir = upload_dir
        self.lock = threading.Lock()
        os.makedirs(upload_dir, exist_ok=True)
        self.assistants: list = self._load(ASSISTANTS_FILE)
        self.assistant_files: list = self._load(ASSISTANT_FILES_FILE)
        self.files: list = self._load(UPLOADED_FILES_FILE)

    def _load(self, name):
        path = os.path.join(self.dir, name)
        if os.path.exists(path):
            try:
                with open(path) as f:
                    return json.load(f)
            except Exception:
                return []
        return []

    def save(self):
        for name, data in ((ASSISTANTS_FILE, self.assistants),
                           (ASSISTANT_FILES_FILE, self.assistant_files),
                           (UPLOADED_FILES_FILE, self.files)):
            with open(os.path.join(self.dir, name), "w") as f:
                json.dump(data, f)

    def file_path(self, file_id: str) -> str:
        return os.path.join(self.dir, file_id)


def _store(request) -> AssistantStore:
    state = get_state(request)
    store = getattr(state, "assistant_store", None)
    if store is None:
        base = state.config.uploads_path
        if not os.path.isabs(base):
            base = os.path.join(state.config.models_path, base)
        store = AssistantStore(base)
        state.assistant_store = store
    return store


def _json(data, status=200):
    return web.json_response(data, status=status)


# ---------- assistants ----------

async def create_assistant(request):
    store = _store(request)
    body = await request.json()
    if not body.get("model"):
        raise web.HTTPBadRequest(text="model is required")
    with store.lock:
        a = {
            "id": f"asst_{uuid.uuid4().hex[:24]}",
            "object": "assistant",
            "created": int(time.time()),
            "model": body["model"],
            "name": body.get("name", ""),
            "description": body.get("description", ""),
            "instructions": body.get("instructions", ""),
            "tools": body.get("tools", []),
            "file_ids": body.get("file_ids", []),
            "metadata": body.get("metadata", {}),
        }
        store.assistants.append(a)
        store.save()
    return _json(a)


async def list_assistants(request):
    store = _store(request)
    limit = int(request.query.get("limit", "20"))
    order = request.query.get("order", "desc")
    after = request.query.get("after")
    before = request.query.get("before")
    with store.lock:
        items = sorted(store.assistants, key=lambda a: a["id"],
                       reverse=(order != "asc"))
        if after:
            ids = [a["id"] for a in items]
            if after in ids:
                items = items[ids.index(after) + 1:]
        if before:
            ids = [a["id"] for a in items]
            if before in ids:
                items = items[: ids.index(before)]
        return _json(items[:limit])


def _find(items, key, value):
    for x in items:
        if x[key] == value:
            return x
    return None


async def get_assistant(request):
    store = _store(request)
    a = _find(store.assistants, "id", request.match_info["assistant_id"])
    if a is None:
        raise web.HTTPNotFound(text="assistant not found")
    return _json(a)


async def modify_assistant(request):
    store = _store(request)
    body = await request.json()
    with store.lock:
        a = _find(store.assistants, "id", request.match_info["assistant_id"])
        if a is None:
            raise web.HTTPNotFound(text="assistant not found")
        for k in ("model", "name", "description", "instructions", "tools",
                  "file_ids", "metadata"):
            if k in body:
                a[k] = body[k]
        store.save()
    return _json(a)


async def delete_assistant(request):
    store = _store(request)
    aid = request.match_info["assistant_id"]
    with store.lock:
        before = len(store.assistants)
        store.assistants = [a for a in store.assistants if a["id"] != aid]
        deleted = len(store.assistants) != before
        if deleted:
            store.assistant_files = [
                f for f in store.assistant_files if f["assistant_id"] != aid]
            store.save()
    return _json({"id": aid, "object": "assistant.deleted", "deleted": deleted},
                 status=200 if deleted else 404)


# ---------- assistant files ----------

async def create_assistant_file(request):
    store = _store(request)
    body = await request.json()
    aid = request.match_info["assistant_id"]
    with store.lock:
        a = _find(store.assistants, "id", aid)
        if a is None:
            raise web.HTTPNotFound(text="assistant not found")
        if _find(store.files, "id", body.get("file_id")) is None:
            raise web.HTTPNotFound(text="file not found")
        af = {
            "id": f"af_{uuid.uuid4().hex[:24]}",
            "object": "assistant.file",
            "created_at": int(time.time()),
            "assistant_id": aid,
            "file_id": body["file_id"],
        }
        store.assistant_files.append(af)
        if body["file_id"] not in a["file_ids"]:
            a["file_ids"].append(body["file_id"])
        store.save()
    return _json(af)


async def list_assistant_files(request):
    store = _store(request)
    aid = request.match_info["assistant_id"]
    items = [f for f in store.assistant_files if f["assistant_id"] == aid]
    return _json({"object": "list", "data": items})


async def get_assistant_file(request):
    store = _store(request)
    af = _find(store.assistant_files, "id", request.match_info["file_id"])
    if af is None or af["assistant_id"] != request.match_info["assistant_id"]:
        raise web.HTTPNotFound(text="assistant file not found")
    return _json(af)


async def delete_assistant_file(request):
    store = _store(request)
    aid = request.match_info["assistant_id"]
    fid = request.match_info["file_id"]
    with store.lock:
        before = len(store.assistant_files)
        store.assistant_files = [
            f for f in store.assistant_files
            if not (f["assistant_id"] == aid
                    and (f["id"] == fid or f["file_id"] == fid))]
        deleted = len(store.assistant_files) != before
        a = _find(store.assistants, "id", aid)
        if a and fid in a.get("file_ids", []):
            a["file_ids"].remove(fid)
        if deleted:
            store.save()
    if not deleted:
        raise web.HTTPNotFound(
            text=json.dumps({"error": {"message": f"file {fid} not attached",
                                       "type": "invalid_request_error"}}),
            content_type="application/json")
    return _json({"id": fid, "object": "assistant.file.deleted",
                  "deleted": deleted})


# ---------- files ----------

async def upload_file(request):
    store = _store(request)
    reader = await request.multipart()
    purpose = ""
    filename = ""
    content = b""
    while True:
        part = await reader.next()
        if part is None:
            break
        if part.name == "purpose":
            purpose = (await part.read()).decode()
        elif part.name == "file":
            filename = part.filename or "upload"
            content = await part.read()
    if not purpose:
        raise web.HTTPBadRequest(text="purpose is required")
    if not content:
        raise web.HTTPBadRequest(text="file is required")
    with store.lock:
        f = {
            "id": f"file-{uuid.uuid4().hex[:24]}",
            "object": "file",
            "bytes": len(content),
            "created_at": int(time.time()),
            "filename": filename,
            "purpose": purpose,
        }
        with open(store.file_path(f["id"]), "wb") as fh:
            fh.write(content)
        store.files.append(f)
        store.save()
    return _json(f)


async def list_files(request):
    store = _store(request)
    purpose = request.query.get("purpose")
    items = (store.files if not purpose
             else [f for f in store.files if f["purpose"] == purpose])
    return _json({"object": "list", "data": items})


async def get_file(request):
    store = _store(request)
    f = _find(store.files, "id", request.match_info["file_id"])
    if f is None:
        raise web.HTTPNotFound(text="file not found")
    return _json(f)


async def get_file_content(request):
    store = _store(request)
    f = _find(store.files, "id", request.match_info["file_id"])
    if f is None:
        raise web.HTTPNotFound(text="file not found")
    path = store.file_path(f["id"])
    if not os.path.exists(path):
        raise web.HTTPNotFound(text="file content missing")
    return web.FileResponse(path)


async def delete_file(request):
    store = _store(request)
    fid = request.match_info["file_id"]
    with store.lock:
        f = _find(store.files, "id", fid)
        if f is None:
            raise web.HTTPNotFound(text="file not found")
        store.files.remove(f)
        store.assistant_files = [
            af for af in store.assistant_files if af["file_id"] != fid]
        for a in store.assistants:
            if fid in a.get("file_ids", []):
                a["file_ids"].remove(fid)
        try:
            os.remove(store.file_path(fid))
        except OSError:
            pass
        store.save()
    return _json({"id": fid, "object": "file", "deleted": True})


def register(app: web.Application):
    r = app.router
    for prefix in ("/v1", ""):
        r.add_get(f"{prefix}/assistants", list_assistants)
        r.add_post(f"{prefix}/assistants", create_assistant)
        r.add_get(f"{prefix}/assistants/{{assistant_id}}", get_assistant)
        r.add_post(f"{prefix}/assistants/{{assistant_id}}", modify_assistant)
        r.add_delete(f"{prefix}/assistants/{{assistant_id}}", delete_assistant)
        r.add_get(f"{prefix}/assistants/{{assistant_id}}/files",
                  list_assistant_files)
        r.add_post(f"{prefix}/assistants/{{assistant_id}}/files",
                   create_assistant_file)
        r.add_get(f"{prefix}/assistants/{{assistant_id}}/files/{{file_id}}",
                  get_assistant_file)
        r.add_delete(f"{prefix}/assistants/{{assistant_id}}/files/{{file_id}}",
                     delete_assistant_file)
        r.add_post(f"{prefix}/files", upload_file)
        r.add_get(f"{prefix}/files", list_files)
        r.add_get(f"{prefix}/files/{{file_id}}", get_file)
        r.add_get(f"{prefix}/files/{{file_id}}/content", get_file_content)
        r.add_delete(f"{prefix}/files/{{file_id}}", delete_file)
