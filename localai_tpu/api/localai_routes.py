"""LocalAI-specific + 3rd-party-compat endpoints.

Parity with the reference route tables (reference: core/http/routes/
localai.go:14-71 — gallery ops, TTS, sound generation, tokenize, stores,
/metrics, backend monitor/shutdown, /system, /version, p2p, tokenMetrics;
routes/health.go — /healthz /readyz; routes/elevenlabs.go; routes/jina.go).
"""

from __future__ import annotations

import os
import secrets
import tempfile
import time

from aiohttp import web

from localai_tpu import __version__
from localai_tpu.api.app import api_error, get_state
from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.services.eventlog import EVENTS
from localai_tpu.services.metrics import CONTENT_TYPE, METRICS, label_str


def register(app: web.Application):
    r = app.router
    # health (reference: routes/health.go). /healthz is pure liveness;
    # /readyz is distinct (ISSUE 7): it consults the loader's circuit
    # breakers so an orchestrator stops routing to a crash-looping node
    r.add_get("/healthz", healthz)
    r.add_get("/readyz", readyz)
    # tts + sound generation
    r.add_post("/tts", tts)
    r.add_post("/sound-generation", sound_generation)
    # elevenlabs compat (reference: routes/elevenlabs.go)
    r.add_post("/v1/text-to-speech/{voice_id}", elevenlabs_tts)
    r.add_post("/v1/sound-generation", sound_generation)
    # jina compat (reference: routes/jina.go)
    r.add_post("/v1/rerank", rerank)
    # tokenize
    r.add_post("/v1/tokenize", tokenize)
    # stores (reference: routes/localai.go:49-53)
    r.add_post("/stores/set", stores_set)
    r.add_post("/stores/delete", stores_delete)
    r.add_post("/stores/get", stores_get)
    r.add_post("/stores/find", stores_find)
    # observability
    r.add_get("/metrics", metrics)
    r.add_get("/backend/monitor", backend_monitor)
    r.add_post("/backend/monitor", backend_monitor)
    r.add_post("/backend/shutdown", backend_shutdown)
    r.add_get("/system", system_info)
    r.add_get("/version", version)
    r.add_get("/v1/tokenMetrics", token_metrics)
    r.add_get("/debug/trace", debug_trace)
    r.add_get("/debug/profile", debug_profile)
    # system observability (ISSUE 8): live engine-state snapshot +
    # merged structured event log
    r.add_get("/debug/state", debug_state)
    r.add_get("/debug/events", debug_events)
    r.add_get("/debug/kv", debug_kv)
    # gallery (reference: routes/localai.go:14-44)
    r.add_post("/models/apply", models_apply)
    r.add_post("/models/delete/{name}", models_delete)
    r.add_get("/models/available", models_available)
    r.add_get("/models/jobs/{uuid}", models_job_status)
    r.add_get("/models/jobs", models_all_jobs)
    r.add_post("/models/galleries", add_gallery)
    r.add_delete("/models/galleries", remove_gallery)
    # p2p parity surface (topology is static on TPU; report the mesh)
    r.add_get("/api/p2p", p2p_nodes)
    r.add_get("/api/p2p/token", p2p_token)


async def healthz(request):
    return web.Response(text="OK")


def _readyz_load(state) -> dict:
    """Per-model queue depth + slots-in-flight off the (cheap, native)
    GetMetrics fields, short-timeout and failure-tolerant: readiness
    must answer even when a backend is wedged."""
    import json

    out = {}
    for name in state.caps.loader.list_loaded():
        lm = state.caps.loader.get(name)
        if lm is None:
            continue
        try:
            m = lm.client.get_metrics(timeout=1.0)
            out[name] = {"queue_depth": int(m.queued),
                         "slots_in_flight": int(m.slots_active),
                         "slots_total": int(m.slots_total)}
            # target-vs-actual replicas + last scaling decision (ISSUE
            # 19): parsed tolerantly from the stats JSON — absent on
            # unpooled models and non-JSON backends
            try:
                stats = json.loads(m.prompt_json_for_slot or "{}")
            except (ValueError, TypeError):
                stats = {}
            if "engine_replicas" in stats:
                pool = stats.get("pool") or {}
                out[name]["replicas_alive"] = pool.get(
                    "replicas_alive", stats["engine_replicas"])
                out[name]["replicas_target"] = stats.get(
                    "engine_replicas_target",
                    pool.get("replicas_target"))
                auto = pool.get("autoscale")
                if auto:
                    out[name]["last_scale_decision"] = auto.get(
                        "last_decision")
        except Exception:
            out[name] = {"queue_depth": None, "slots_in_flight": None}
    return out


async def readyz(request):
    """Readiness distinct from liveness: 503 (with Retry-After) while any
    model's load circuit breaker is open — the process is alive, but a
    load balancer should prefer other replicas until the breaker cools.
    The body carries the full breaker map plus per-model queue depth and
    slots-in-flight (ISSUE 8 satellite, closes the PR-7 follow-up) so an
    external LB can weight replicas, not just drop them."""
    state = get_state(request)
    try:
        stats = state.caps.loader.stats()
    except Exception:
        stats = {}
    breakers = {name: s["breaker"] for name, s in stats.items()}
    open_breakers = {name: b for name, b in breakers.items()
                     if b["state"] == "open"}
    load = await state.run_blocking(_readyz_load, state)
    if open_breakers:
        retry_after = max(1, int(max(
            b.get("retry_after_s", 0.0) for b in open_breakers.values())))
        return web.json_response(
            {"status": "unready", "circuit_open": open_breakers,
             "breakers": breakers, "load": load},
            status=503, headers={"Retry-After": str(retry_after)})
    return web.json_response(
        {"status": "ready",
         "models_loaded": len(state.caps.loader.list_loaded()),
         "breakers": breakers, "load": load})


async def run_audio_capability(request, call) -> web.Response:
    """Run a sync capability ``call(dst)`` that writes a wav to dst; return
    the audio as the response body. The temp file is always cleaned up."""
    state = get_state(request)
    dst = os.path.join(tempfile.gettempdir(), f"localai-audio-{secrets.token_hex(8)}.wav")
    try:
        await state.run_blocking(call, dst)
        with open(dst, "rb") as f:
            return web.Response(body=f.read(), content_type="audio/wav")
    finally:
        if os.path.exists(dst):
            os.unlink(dst)


async def version(request):
    return web.json_response({"version": __version__})


_POOL_GAUGES = ("kv_pages_total", "kv_pages_free", "kv_pages_retained",
                "kv_pages_active", "kv_pages_offloaded")
_PCACHE_COUNTERS = ("hits", "misses", "evicted_pages", "inserted_pages",
                    "hit_rows")
# host-tier transfer totals (engine/kv_offload.py stats key -> metric):
# localai_kv_offload_{pages,bytes,restores,hits,misses}_total
_OFFLOAD_COUNTERS = (("offloaded_pages", "pages"),
                     ("offloaded_bytes", "bytes"),
                     ("restores", "restores"),
                     ("hits", "hits"),
                     ("misses", "misses"),
                     ("evicted_pages", "evicted_pages"),
                     ("restored_pages", "restored_pages"))
# prefetch-ahead pipeline totals (ISSUE 16; engine/kv_offload.py stats
# key -> localai_kv_prefetch_<metric>_total): pages restored ahead of
# need, pages the admission claimed (hits), sync restores the pipeline
# predicted but lost (late), and expired/raided speculation (wasted)
_PREFETCH_COUNTERS = (("prefetch_issued", "issued"),
                      ("prefetch_hits", "hits"),
                      ("prefetch_late", "late"),
                      ("prefetch_wasted", "wasted"))
# per-request TTFT decomposition (engine.py _ttft_decomp rolling window,
# p50 over the last 512 finished requests) — loaded-TTFT regressions
# show up here without running bench: queue_wait (admission backlog),
# admit_to_first (prefill scheduling + other slots' work), and the pure
# prefill dispatch time. stats key -> localai_ttft_<metric>_p50_ms
_TTFT_GAUGES = (("queue_wait", "queue_wait"),
                ("admit_to_first", "admit_to_first"),
                ("prefill_dispatch", "prefill_dispatch"))
# packed-prefill scheduling totals (engine.py metrics()["packed_prefill"])
_PACKED_COUNTERS = ("dispatches", "tokens", "segments", "pad_tokens",
                    "kernel_fallback")
# engine-owned latency histograms (engine.py metrics()["histograms"]):
# re-exposed verbatim with proper _bucket/_sum/_count exposition
_LATENCY_HISTOGRAMS = ("ttft_seconds", "itl_seconds",
                       "decode_burst_seconds", "prefill_dispatch_seconds")
# fault-tolerant lifecycle counters (engine.py metrics()["lifecycle"],
# ISSUE 7): stats key -> localai_<metric> per model
_LIFECYCLE_COUNTERS = (("requests_shed", "requests_shed_total"),
                       ("requests_timed_out", "requests_timed_out_total"),
                       ("stalls", "engine_stalls_total"),
                       ("stall_dumps", "stall_dumps_total"))
# preemptive priority scheduler (ISSUE 10): preempt/resume totals +
# per-class depth gauges, from engine metrics()["scheduler"]
_SCHED_COUNTERS = (("preemptions", "preemptions_total"),
                   ("resumes", "resume_restore_total"),
                   ("resume_reprefills", "resume_restore_reprefills_total"),
                   ("resume_restore_rows", "resume_restore_rows_total"),
                   ("aged_promotions", "priority_aged_promotions_total"))
# system observability (ISSUE 8): XLA compile tracking + memory
# watermarks + goodput/MFU, from engine metrics()["sysobs"]
_SYSOBS_COUNTERS = ("xla_compiles_total", "xla_compiles_after_warmup_total",
                    "goodput_tokens_total")
_SYSOBS_GAUGES = ("xla_compile_seconds", "mfu", "goodput_tok_s",
                  "mem_weight_bytes", "mem_pool_frag_holes",
                  "mem_pool_frag_ratio")
# watermark keys are prefixed mem_ on export; the known set is cleared
# explicitly so unloads don't leave stale per-model peaks behind
_SYSOBS_WATERMARKS = ("peak_queued", "peak_slots_active",
                      "peak_tokens_total", "peak_pool_active_pages",
                      "peak_pool_retained_pages", "peak_pool_pages_in_use",
                      "peak_host_offloaded_pages", "peak_host_bytes",
                      "peak_device_bytes_in_use")
# device allocator stats (ISSUE 12 satellite): engine sysobs.device_mem
# key -> localai_mem_device_<metric>; absent on CPU backends
_DEVICE_MEM_GAUGES = (("bytes_in_use", "bytes_in_use"),
                      ("peak_bytes_in_use", "peak_bytes_in_use"),
                      ("bytes_limit", "bytes_limit"))
# per-class SLO engine (ISSUE 12): burn-rate gauges per
# (model, priority, metric, window) + violation totals, from engine
# metrics()["slo"]; flight-recorder dump counters ride along
_SLO_WINDOWS = (("burn_5m", "5m"), ("burn_1h", "1h"))
# speculative decoding (ISSUE 13): per-round totals + the acceptance
# rate, from engine metrics()["spec"]; since ISSUE 18 each series is
# additionally split by acceptance mode — mode="greedy" (accept_greedy)
# vs mode="sampled" (rejection-sampling acceptance) — from
# metrics()["spec"]["by_mode"], alongside the unlabeled aggregate
_SPEC_COUNTERS = (("rounds", "spec_rounds_total"),
                  ("proposed", "spec_proposed_total"),
                  ("accepted", "spec_accepted_total"))
# KV lifecycle auditor (ISSUE 15): scan/violation/leak/ledger totals,
# from engine metrics()["kv_audit"] (pool-aggregated for engines>1)
_KV_AUDIT_COUNTERS = ("checks", "violations", "leaked_pages",
                      "ledger_events")
# cross-host KV streaming transport (ISSUE 17): the federated tier's
# fetch totals, from engine metrics()["kv_stream"] (stats key ->
# localai_kv_stream_<metric>_total)
_KV_STREAM_COUNTERS = (("fetches", "fetches"), ("hits", "hits"),
                       ("misses", "misses"), ("pages", "pages"),
                       ("bytes", "bytes"), ("pushes", "pushes"),
                       ("pushed_pages", "pushed_pages"),
                       ("corrupt_rejected", "corrupt_rejected"))


def _refresh_engine_metrics(state):
    """Pull each loaded LLM backend's engine stats (the JSON side-channel
    on GetMetrics — see backend/runner.py) into the Prometheus registry:
    kv pool occupancy gauges + prefix-cache counters, labeled by model.
    Runs synchronously right before every /metrics render, Prometheus
    pull style; backends without GetMetrics (tts, diffusion, ...) are
    skipped."""
    import json as _json

    for g in ("kv_pool_pages", "kv_pool_oversubscription",
              "prefix_cache_entries", "kv_offload_host_bytes",
              "ttft_samples", "queue_depth", "slots_in_flight",
              *_LATENCY_HISTOGRAMS,
              *(f"ttft_{m}_p50_ms" for _k, m in _TTFT_GAUGES),
              *(f"prefill_packed_{k}_total" for k in _PACKED_COUNTERS),
              "prefill_kernel_fallback_total",
              *(f"prefix_cache_{k}_total" for k in _PCACHE_COUNTERS),
              *(f"kv_offload_{m}_total" for _k, m in _OFFLOAD_COUNTERS),
              *(f"kv_prefetch_{m}_total" for _k, m in _PREFETCH_COUNTERS),
              "kv_prefetch_inflight",
              *(m for _k, m in _LIFECYCLE_COUNTERS),
              *(m for _k, m in _SCHED_COUNTERS),
              "queue_depth_class", "resume_queue_depth",
              *_SYSOBS_COUNTERS, *_SYSOBS_GAUGES,
              *(f"mem_{k}" for k in _SYSOBS_WATERMARKS),
              *(f"mem_device_{m}" for _k, m in _DEVICE_MEM_GAUGES),
              "slo_burn_rate", "slo_objective_ms", "slo_violations_total",
              "slo_error_budget", "flight_dumps_total",
              "flight_dumps_suppressed_total",
              *(m for _k, m in _SPEC_COUNTERS),
              "spec_acceptance_rate",
              *(f"kv_audit_{k}_total" for k in _KV_AUDIT_COUNTERS),
              *(f"kv_stream_{m}_total" for _k, m in _KV_STREAM_COUNTERS),
              "kv_stream_inflight", "kv_stream_peers_online",
              "cluster_hosts", "disagg_handoffs_total",
              "engine_queue_limit", "cluster_host_state",
              "cluster_heartbeat_rtt_ms", "cluster_rpc_retries_total",
              "cluster_rpc_timeouts_total",
              "engine_replicas", "replica_queue_depth",
              "replica_slots_in_flight", "replica_migrations_total",
              "pool_affinity_hits_total", "pool_affinity_misses_total",
              "resume_reserve_pages",
              "engine_replicas_target", "autoscale_decisions_total",
              "autoscale_flaps_suppressed_total",
              "weight_prefetch_hits_total", "weight_prefetch_bytes_total",
              "backend_respawns_total", "circuit_state"):
        METRICS.clear_instrument(g)
    # loader-owned recovery telemetry (ISSUE 7): respawn counts + breaker
    # state come from the core's loader, not the backend — a model whose
    # backend is DEAD right now is exactly the one that must still export
    try:
        for name, s in state.caps.loader.stats().items():
            METRICS.set_counter("backend_respawns_total", s["respawns"],
                                label_str(model=name))
            METRICS.set_gauge("circuit_state", s["circuit_state"],
                              label_str(model=name))
    except Exception:
        pass
    for name in state.caps.loader.list_loaded():
        lm = state.caps.loader.get(name)
        if lm is None:
            continue
        try:
            m = lm.client.get_metrics(timeout=2.0)
            stats = _json.loads(m.prompt_json_for_slot or "{}")
        except Exception:
            continue
        # TTFT decomposition + packed-prefill scheduling: any engine
        # layout (the gauges exist for contiguous caches too)
        td = stats.get("ttft_decomp_p50_ms")
        if td:
            for skey, mkey in _TTFT_GAUGES:
                METRICS.set_gauge(f"ttft_{mkey}_p50_ms",
                                  td.get(skey, 0.0), label_str(model=name))
            METRICS.set_gauge("ttft_samples", td.get("n", 0),
                              label_str(model=name))
        # scheduler load gauges + latency histograms (any layout)
        METRICS.set_gauge("queue_depth", stats.get("queued", 0),
                          label_str(model=name))
        METRICS.set_gauge("slots_in_flight", stats.get("slots_active", 0),
                          label_str(model=name))
        for hname, h in (stats.get("histograms") or {}).items():
            if hname in _LATENCY_HISTOGRAMS:
                METRICS.set_histogram(hname, label_str(model=name),
                                      h.get("le", ()), h.get("counts", ()),
                                      h.get("sum", 0.0), h.get("count", 0))
        pp = stats.get("packed_prefill")
        if pp and stats.get("prefill_packed"):
            for key in _PACKED_COUNTERS:
                METRICS.set_counter(f"prefill_packed_{key}_total",
                                    pp.get(key, 0), label_str(model=name))
            # headline alias (ISSUE 11): a pack that left the Pallas
            # kernel path for the jnp reference is a silent throughput
            # cliff — exported under its own name so dashboards can
            # alert on it without knowing the packed_prefill family
            METRICS.set_counter("prefill_kernel_fallback_total",
                                pp.get("kernel_fallback", 0),
                                label_str(model=name))
        lc = stats.get("lifecycle")
        if lc:
            for skey, mkey in _LIFECYCLE_COUNTERS:
                METRICS.set_counter(mkey, lc.get(skey, 0),
                                    label_str(model=name))
        # preemptive priority scheduler (ISSUE 10): preempt/resume
        # totals + per-class queue depth (queued + parked-for-resume)
        sch = stats.get("scheduler")
        if sch and sch.get("preempt"):
            for skey, mkey in _SCHED_COUNTERS:
                METRICS.set_counter(mkey, sch.get(skey, 0),
                                    label_str(model=name))
            METRICS.set_gauge("resume_queue_depth",
                              sch.get("resume_depth", 0),
                              label_str(model=name))
            for cls, n in (sch.get("queued_by_class") or {}).items():
                METRICS.set_gauge("queue_depth_class", n,
                                  label_str(model=name, priority=cls))
            # resume-reserve autosize (ISSUE 14 satellite): the
            # EFFECTIVE reserve — explicit knob, or the preemption-rate
            # EWMA-derived value when the knob is 0
            METRICS.set_gauge("resume_reserve_pages",
                              sch.get("resume_reserve_pages", 0),
                              label_str(model=name))
        # engine replica pool (ISSUE 14): pool width, per-replica load,
        # migration totals by reason. engines=1 exports width 1 and no
        # per-replica/pool series (plain Engine stats carry no "pool")
        METRICS.set_gauge("engine_replicas",
                          stats.get("engine_replicas", 1),
                          label_str(model=name))
        for r in (stats.get("replicas") or []):
            rl = label_str(model=name, replica=str(r.get("replica", 0)))
            METRICS.set_gauge("replica_queue_depth", r.get("queued", 0), rl)
            METRICS.set_gauge("replica_slots_in_flight",
                              r.get("slots_in_flight", 0), rl)
        pool = stats.get("pool")
        if pool:
            for reason, n in (pool.get("migrations") or {}).items():
                METRICS.set_counter("replica_migrations_total", n,
                                    label_str(model=name, reason=reason))
            METRICS.set_counter("pool_affinity_hits_total",
                                pool.get("affinity_hits", 0),
                                label_str(model=name))
            METRICS.set_counter("pool_affinity_misses_total",
                                pool.get("affinity_misses", 0),
                                label_str(model=name))
            # SLO-driven autoscaling (ISSUE 19): target width + decision/
            # suppressed-flap counters by direction. Absent unless
            # autoscale=1 built a policy.
            METRICS.set_gauge("engine_replicas_target",
                              pool.get("replicas_target",
                                       stats.get("engine_replicas", 1)),
                              label_str(model=name))
            auto = pool.get("autoscale")
            if auto:
                for d, n in (auto.get("decisions") or {}).items():
                    METRICS.set_counter("autoscale_decisions_total", n,
                                        label_str(model=name, direction=d))
                for d, n in (auto.get("flaps_suppressed") or {}).items():
                    METRICS.set_counter(
                        "autoscale_flaps_suppressed_total", n,
                        label_str(model=name, direction=d))
        # streamed weight-load + in-backend prefetch stats (ISSUE 19)
        ws = stats.get("weight_stream")
        if ws:
            METRICS.set_counter("weight_prefetch_hits_total",
                                1 if ws.get("prefetch_hit") else 0,
                                label_str(model=name, source="backend"))
            METRICS.set_counter("weight_prefetch_bytes_total",
                                ws.get("bytes", 0),
                                label_str(model=name, source="backend"))
        # speculative decoding (ISSUE 13): per-round proposal/acceptance
        # totals + the derived acceptance rate, skipped when the engine
        # resolved speculation off (non-llama, lockstep, draft=0)
        spec = stats.get("spec")
        if spec and spec.get("mode") not in (None, "off"):
            for skey, mkey in _SPEC_COUNTERS:
                METRICS.set_counter(mkey, spec.get(skey, 0),
                                    label_str(model=name))
            METRICS.set_gauge("spec_acceptance_rate",
                              spec.get("acceptance_rate", 0.0),
                              label_str(model=name))
            # ISSUE 18: per-acceptance-mode split (greedy vs sampled)
            for mode, c in (spec.get("by_mode") or {}).items():
                for skey, mkey in _SPEC_COUNTERS:
                    METRICS.set_counter(
                        mkey, c.get(skey, 0),
                        label_str(model=name, mode=mode))
                METRICS.set_gauge("spec_acceptance_rate",
                                  c.get("acceptance_rate", 0.0),
                                  label_str(model=name, mode=mode))
        # system observability (ISSUE 8): compile counters, memory
        # watermarks, goodput/MFU
        so = stats.get("sysobs")
        if so:
            comp = so.get("compiles") or {}
            METRICS.set_counter("xla_compiles_total",
                                comp.get("compiles_total", 0),
                                label_str(model=name))
            METRICS.set_counter("xla_compiles_after_warmup_total",
                                comp.get("compiles_after_warmup", 0),
                                label_str(model=name))
            # float seconds: exposed as a gauge (set_counter truncates)
            METRICS.set_gauge("xla_compile_seconds",
                              comp.get("compile_seconds_total", 0.0),
                              label_str(model=name))
            gp = so.get("goodput") or {}
            METRICS.set_counter("goodput_tokens_total",
                                gp.get("goodput_tokens_total", 0),
                                label_str(model=name))
            METRICS.set_gauge("goodput_tok_s", gp.get("goodput_tok_s", 0.0),
                              label_str(model=name))
            METRICS.set_gauge("mfu", gp.get("mfu", 0.0),
                              label_str(model=name))
            for k, v in (so.get("watermarks") or {}).items():
                METRICS.set_gauge(f"mem_{k}", v, label_str(model=name))
            METRICS.set_gauge("mem_weight_bytes",
                              so.get("weight_bytes", 0),
                              label_str(model=name))
            frag = so.get("fragmentation")
            if frag:
                METRICS.set_gauge("mem_pool_frag_holes",
                                  frag.get("hole_pages", 0),
                                  label_str(model=name))
                METRICS.set_gauge("mem_pool_frag_ratio",
                                  frag.get("ratio", 0.0),
                                  label_str(model=name))
            # device allocator stats (ISSUE 12 satellite): real HBM
            # numbers when the backend platform exposes memory_stats()
            dm = so.get("device_mem")
            if dm:
                for skey, mkey in _DEVICE_MEM_GAUGES:
                    if skey in dm:
                        METRICS.set_gauge(f"mem_device_{mkey}", dm[skey],
                                          label_str(model=name))
        # per-class SLO engine (ISSUE 12): burn-rate gauges + violation
        # counters per (priority class, metric); the flight recorder's
        # dump/suppression totals ride the same pull
        slo = stats.get("slo")
        if slo:
            METRICS.set_gauge("slo_error_budget",
                              slo.get("error_budget", 0.0),
                              label_str(model=name))
            for cls, metrics_d in (slo.get("classes") or {}).items():
                for metric, s in (metrics_d or {}).items():
                    labels = label_str(model=name, priority=cls,
                                       slo_metric=metric)
                    METRICS.set_gauge("slo_objective_ms",
                                      s.get("objective_ms", 0.0), labels)
                    METRICS.set_counter("slo_violations_total",
                                        s.get("violations", 0), labels)
                    for skey, window in _SLO_WINDOWS:
                        METRICS.set_gauge(
                            "slo_burn_rate", s.get(skey, 0.0),
                            label_str(model=name, priority=cls,
                                      slo_metric=metric, window=window))
        fr = stats.get("flight_recorder")
        if fr:
            METRICS.set_counter("flight_dumps_total", fr.get("dumps", 0),
                                label_str(model=name))
            METRICS.set_counter("flight_dumps_suppressed_total",
                                fr.get("suppressed", 0),
                                label_str(model=name))
        # per-span exemplars (ISSUE 8 satellite, closes the PR-6
        # follow-up): worst-since-last-pull observation per histogram,
        # tagged with its request correlation id
        for hname, ex in (stats.get("hist_exemplars") or {}).items():
            if hname in _LATENCY_HISTOGRAMS:
                METRICS.set_exemplar(hname, label_str(model=name),
                                     ex.get("value", 0.0),
                                     ex.get("trace_id", ""),
                                     ex.get("ts", 0.0))
        if stats.get("kv_layout") != "paged":
            continue
        for key in _POOL_GAUGES:
            if key in stats:
                state_name = key[len("kv_pages_"):]
                METRICS.set_gauge(
                    "kv_pool_pages",
                    stats[key],
                    label_str(model=name, state=state_name))
        if "kv_pool_oversubscription" in stats:
            METRICS.set_gauge("kv_pool_oversubscription",
                              stats["kv_pool_oversubscription"],
                              label_str(model=name))
        pc = stats.get("prefix_cache")
        if pc:
            METRICS.set_gauge("prefix_cache_entries", pc.get("entries", 0),
                              label_str(model=name))
            for key in _PCACHE_COUNTERS:
                METRICS.set_counter(f"prefix_cache_{key}_total",
                                    pc.get(key, 0), label_str(model=name))
        off = stats.get("kv_offload")
        if off:
            METRICS.set_gauge("kv_offload_host_bytes", off.get("bytes", 0),
                              label_str(model=name))
            for skey, mkey in _OFFLOAD_COUNTERS:
                METRICS.set_counter(f"kv_offload_{mkey}_total",
                                    off.get(skey, 0), label_str(model=name))
            for skey, mkey in _PREFETCH_COUNTERS:
                METRICS.set_counter(f"kv_prefetch_{mkey}_total",
                                    off.get(skey, 0), label_str(model=name))
            METRICS.set_gauge("kv_prefetch_inflight",
                              off.get("prefetch_inflight", 0),
                              label_str(model=name))
        ka = stats.get("kv_audit")
        if ka:
            for key in _KV_AUDIT_COUNTERS:
                METRICS.set_counter(f"kv_audit_{key}_total",
                                    ka.get(key, 0), label_str(model=name))
        # cross-host KV federation (ISSUE 17): the peer tier's transfer
        # totals; absent unless kv_peers= armed a federated tier
        ks = stats.get("kv_stream")
        if ks:
            for skey, mkey in _KV_STREAM_COUNTERS:
                METRICS.set_counter(f"kv_stream_{mkey}_total",
                                    ks.get(skey, 0), label_str(model=name))
            METRICS.set_gauge("kv_stream_inflight", ks.get("inflight", 0),
                              label_str(model=name))
            METRICS.set_gauge("kv_stream_peers_online",
                              ks.get("peers_online", 0),
                              label_str(model=name))
        # admission capacity after autoscale co-scaling (ISSUE 20): the
        # effective queue limit tracks live width, so shed behavior is
        # observable next to queue_depth
        if "queue_limit" in stats:
            METRICS.set_gauge("engine_queue_limit",
                              stats.get("queue_limit", 0),
                              label_str(model=name))
        # cluster width + prefill/decode disaggregation handoffs
        cl = stats.get("cluster")
        if cl:
            METRICS.set_gauge("cluster_hosts", cl.get("hosts_alive", 0),
                              label_str(model=name))
            # process-mode control plane (ISSUE 20): failure-detector
            # states, heartbeat RTT, and the RPC retry/timeout ledger
            for st in ("alive", "suspect", "dead"):
                METRICS.set_gauge(
                    "cluster_host_state",
                    sum(1 for v in (cl.get("host_states") or {}).values()
                        if v == st),
                    label_str(model=name, state=st))
            for hid, hb in (cl.get("heartbeat") or {}).items():
                METRICS.set_gauge("cluster_heartbeat_rtt_ms",
                                  hb.get("rtt_ms", 0.0),
                                  label_str(model=name, host=hid))
            rpc = cl.get("rpc") or {}
            for op, n in (rpc.get("retries") or {}).items():
                METRICS.set_counter("cluster_rpc_retries_total", n,
                                    label_str(model=name, op=op))
            for op, n in (rpc.get("timeouts") or {}).items():
                METRICS.set_counter("cluster_rpc_timeouts_total", n,
                                    label_str(model=name, op=op))
        dg = stats.get("disagg")
        if dg:
            METRICS.set_counter("disagg_handoffs_total",
                                dg.get("handoffs", 0),
                                label_str(model=name,
                                          role=dg.get("role", "both")))
    # frontend weight byte-warmer (ISSUE 19): OS-page-cache warm totals
    # for predicted-next gallery models. Process-level (the warmer spans
    # models), so labeled by source rather than model — the backend's
    # in-process stream stats export the source="backend" twin above
    wp = getattr(state.caps, "weight_prefetcher", None)
    if wp is not None:
        ws = wp.snapshot()
        METRICS.set_counter("weight_prefetch_hits_total",
                            ws.get("hits", 0),
                            label_str(source="frontend"))
        METRICS.set_counter("weight_prefetch_bytes_total",
                            ws.get("bytes_total", 0),
                            label_str(source="frontend"))


async def metrics(request):
    state = get_state(request)
    if state.config.disable_metrics_endpoint:
        return api_error("metrics disabled", 404)
    await state.run_blocking(_refresh_engine_metrics, state)
    # full Content-Type set via headers: aiohttp's content_type= kwarg
    # rejects parameters (";"), and the exposition version IS part of
    # the Prometheus scrape contract (ISSUE 8 satellite)
    return web.Response(text=METRICS.render(),
                        headers={"Content-Type": CONTENT_TYPE})


def _collect_traces(state) -> dict:
    """Merge the HTTP process's span ring AND every loaded model's ring
    into ONE clock-aligned Chrome trace JSON (ISSUE 12 tentpole): the
    frontend is pid 0 ("localai-http"), each backend its own pid with
    its slot/scheduler tracks under it. Backend timestamps are relative
    to THAT process's trace epoch, so each event is shifted by

        (backend_t0_epoch - offset_s - frontend_t0_epoch) µs

    where offset_s is the LoadModel clock-handshake estimate of the
    backend-vs-frontend wall-clock skew (loader.LoadedModel.clock; the
    residual error is bounded by that handshake's rtt_s). Backends
    without GetTrace or without the epoch block (old fakes) and RPC
    failures are skipped/unshifted — a debug surface must never 500
    because one backend is old."""
    import json as _json

    from localai_tpu.services.tracing import chrome_trace, frontend_tracer

    front = chrome_trace(frontend_tracer(), pid=0,
                         process_name="localai-http")
    f_epoch = front["localai"]["t0_epoch"]
    events: list = list(front["traceEvents"])
    clocks: dict = {}
    pid = 0
    for name in state.caps.loader.list_loaded():
        lm = state.caps.loader.get(name)
        if lm is None:
            continue
        try:
            r = lm.client.get_trace(timeout=5.0)
            trace = _json.loads(bytes(r.message).decode("utf-8"))
        except Exception:
            continue
        pid += 1
        clock = getattr(lm, "clock", None) or {}
        b_epoch = float((trace.get("localai") or {}).get("t0_epoch", 0.0)
                        or 0.0)
        shift_us = ((b_epoch - clock.get("offset_s", 0.0) - f_epoch) * 1e6
                    if b_epoch else 0.0)
        clocks[name] = {"offset_s": clock.get("offset_s", 0.0),
                        "rtt_s": clock.get("rtt_s", 0.0),
                        "t0_epoch": b_epoch, "shift_us": round(shift_us, 1)}
        for ev in trace.get("traceEvents", []):
            ev["pid"] = pid
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                ev["args"] = {"name": f"localai-engine:{name}"}
            elif shift_us and "ts" in ev:
                ev["ts"] = round(ev["ts"] + shift_us, 1)
            events.append(ev)
    return {"displayTimeUnit": "ms", "traceEvents": events,
            "localai": {"t0_epoch": f_epoch, "clocks": clocks}}


async def debug_trace(request):
    """Chrome trace-event JSON of every loaded engine's span ring —
    load the response body at https://ui.perfetto.dev."""
    state = get_state(request)
    trace = await state.run_blocking(_collect_traces, state)
    return web.json_response(trace)


def _backend_state_payloads(state) -> dict:
    """Pull each loaded backend's GetState JSON (engine snapshot + event
    ring). Backends without GetState (tts, diffusion, old fakes) answer
    UNIMPLEMENTED and are skipped — debug surfaces never 500 because one
    backend can't answer."""
    import json as _json

    out = {}
    for name in state.caps.loader.list_loaded():
        lm = state.caps.loader.get(name)
        if lm is None:
            continue
        try:
            r = lm.client.get_state(timeout=5.0)
            out[name] = _json.loads(bytes(r.message).decode("utf-8"))
        except Exception:
            continue
    return out


def _collect_state(state) -> dict:
    """One live-JSON snapshot of the whole serving system (ISSUE 8):
    core uptime + loader recovery stats + per-engine slots/queues/pool
    map/compile history, plus the core process's own event-log ring."""
    try:
        loader_stats = state.caps.loader.stats()
    except Exception:
        loader_stats = {}
    payloads = _backend_state_payloads(state)
    out = {
        "uptime_s": round(time.time() - state.started_at, 1),
        "version": __version__,
        "loader": loader_stats,
        "models": {name: p.get("state") for name, p in payloads.items()},
        "eventlog": EVENTS.snapshot(),
    }
    # predictive weight prefetch (ISSUE 19): the frontend byte-warmer's
    # counters + the request-log scores it predicts from. Absent unless
    # some model armed weight_prefetch=1 (the warmer is built lazily)
    wp = getattr(state.caps, "weight_prefetcher", None)
    if wp is not None:
        out["weight_prefetch"] = {
            "warmer": wp.snapshot(),
            "requests": state.caps.model_requests.snapshot(),
        }
    return out


async def debug_state(request):
    """Live JSON of engine internals: slots in flight, queue depths, kv
    pool map, breaker state, last N compiles (ISSUE 8 tentpole)."""
    state = get_state(request)
    snap = await state.run_blocking(_collect_state, state)
    return web.json_response(snap)


def _collect_events(state, last: int = 0) -> list:
    """Merge the core process's event ring with every backend's (pulled
    over GetState), tag each record's origin, and return them in time
    order — one correlation-id'd stream across process boundaries."""
    merged = [dict(ev, proc="core") for ev in EVENTS.events()]
    for name, p in _backend_state_payloads(state).items():
        lm = state.caps.loader.get(name)
        # clock-handshake correction (ISSUE 12): backend events carry
        # the BACKEND's wall clock; subtracting the measured offset puts
        # them on the frontend timeline so the sort below is honest
        off = (getattr(lm, "clock", None) or {}).get("offset_s", 0.0) \
            if lm is not None else 0.0
        for ev in p.get("events") or []:
            ev = dict(ev, proc=f"backend:{name}", model=name)
            if off and "ts" in ev:
                ev["ts"] = ev["ts"] - off
            merged.append(ev)
    merged.sort(key=lambda ev: ev.get("ts", 0.0))
    if last > 0:
        merged = merged[-last:]
    return merged


async def debug_events(request):
    """Merged structured event log (admissions, sheds, timeouts,
    respawns, circuit transitions, compile storms, pool pressure) from
    the core and every backend: GET /debug/events[?last=N]."""
    state = get_state(request)
    try:
        last = int(request.query.get("last", 0))
    except ValueError:
        return api_error("last must be an integer", 400)
    events = await state.run_blocking(_collect_events, state, last)
    return web.json_response({"events": events, "count": len(events)})


async def debug_kv(request):
    """KV lifecycle view per loaded model (ISSUE 15): tier map,
    per-chain genealogy, fragmentation layout, audit counters + last
    violations and the ledger tail. Rides the "kv" key of each
    backend's GetState; models with kv_audit=off (or no pages) answer
    the {"mode": "off"} shape, and an EnginePool answers the merged
    multi-replica view."""
    state = get_state(request)
    payloads = await state.run_blocking(_backend_state_payloads, state)
    return web.json_response(
        {"models": {name: p.get("kv") or {"mode": "off"}
                    for name, p in payloads.items()}})


async def debug_profile(request):
    """Capture a jax.profiler device trace on a loaded backend:
    GET /debug/profile?seconds=N[&model=name]. Returns the backend-local
    directory holding the TensorBoard/perfetto capture."""
    state = get_state(request)
    try:
        seconds = float(request.query.get("seconds", 3))
    except ValueError:
        return api_error("seconds must be a number", 400)
    model = request.query.get("model", "")
    loaded = state.caps.loader.list_loaded()
    if model and model not in loaded:
        return api_error(f"model {model} is not loaded", 404)
    names = [model] if model else list(loaded)
    for name in names:
        lm = state.caps.loader.get(name)
        if lm is None:
            continue
        try:
            r = await state.run_blocking(
                lm.client.profile, seconds, max(30.0, seconds + 30.0))
        except Exception as e:
            return api_error(f"profile RPC failed: {e}", 502)
        return web.json_response({
            "model": name,
            "success": bool(r.success),
            "capture_dir": r.message,
            "seconds": seconds,
        }, status=200 if r.success else 500)
    return api_error("no profilable model loaded", 404)


# --------------- tts / sound ---------------

async def tts(request):
    state = get_state(request)
    body = await request.json()
    model = body.get("model") or body.get("backend") or ""
    if not model:
        return api_error("model is required", 400, "invalid_request_error")
    mc = state.caps.resolve(model)
    return await run_audio_capability(
        request, lambda dst: state.caps.tts(
            mc, body.get("input", ""), body.get("voice", ""),
            body.get("language", ""), dst))


async def elevenlabs_tts(request):
    state = get_state(request)
    body = await request.json()
    voice_id = request.match_info["voice_id"]
    model = body.get("model_id") or ""
    if not model:
        return api_error("model_id is required", 400, "invalid_request_error")
    mc = state.caps.resolve(model)
    return await run_audio_capability(
        request, lambda dst: state.caps.tts(
            mc, body.get("text", ""), voice_id, body.get("language_code", ""), dst))


async def sound_generation(request):
    state = get_state(request)
    body = await request.json()
    model = body.get("model_id") or body.get("model") or ""
    if not model:
        return api_error("model is required", 400, "invalid_request_error")
    mc = state.caps.resolve(model)
    return await run_audio_capability(
        request, lambda dst: state.caps.sound_generation(
            mc, body.get("text", ""), dst,
            body.get("duration_seconds"), body.get("temperature")))


# --------------- rerank ---------------

async def rerank(request):
    state = get_state(request)
    body = await request.json()
    model = body.get("model") or ""
    if not model:
        return api_error("model is required", 400, "invalid_request_error")
    mc = state.caps.resolve(model)
    res = await state.run_blocking(
        state.caps.rerank, mc, body.get("query", ""),
        list(body.get("documents", [])), int(body.get("top_n") or 0))
    return web.json_response({
        "model": model,
        "usage": {"total_tokens": res.usage.total_tokens,
                  "prompt_tokens": res.usage.prompt_tokens},
        "results": [
            {"index": r.index, "relevance_score": r.relevance_score,
             "document": {"text": r.text}}
            for r in res.results
        ],
    })


# --------------- tokenize ---------------

async def tokenize(request):
    state = get_state(request)
    body = await request.json()
    model = body.get("model") or ""
    if not model:
        return api_error("model is required", 400, "invalid_request_error")
    mc = state.caps.resolve(model)
    tokens = await state.run_blocking(state.caps.tokenize, mc, body.get("content", ""))
    return web.json_response({"tokens": tokens})


# --------------- stores ---------------

def _store_client(request):
    return get_state(request).caps.store_client()


async def stores_set(request):
    state = get_state(request)
    body = await request.json()
    keys = body.get("keys", [])
    values = body.get("values", [])
    if len(keys) != len(values):
        return api_error("keys and values must have equal length", 400)
    client = await state.run_blocking(_store_client, request)
    await state.run_blocking(client.stores_set, pb.StoresSetOptions(
        keys=[pb.StoresKey(floats=k) for k in keys],
        values=[pb.StoresValue(bytes=str(v).encode()) for v in values],
    ))
    return web.json_response({})


async def stores_delete(request):
    state = get_state(request)
    body = await request.json()
    client = await state.run_blocking(_store_client, request)
    await state.run_blocking(client.stores_delete, pb.StoresDeleteOptions(
        keys=[pb.StoresKey(floats=k) for k in body.get("keys", [])]))
    return web.json_response({})


async def stores_get(request):
    state = get_state(request)
    body = await request.json()
    client = await state.run_blocking(_store_client, request)
    res = await state.run_blocking(client.stores_get, pb.StoresGetOptions(
        keys=[pb.StoresKey(floats=k) for k in body.get("keys", [])]))
    return web.json_response({
        "keys": [list(k.floats) for k in res.keys],
        "values": [v.bytes.decode() for v in res.values],
    })


async def stores_find(request):
    state = get_state(request)
    body = await request.json()
    client = await state.run_blocking(_store_client, request)
    res = await state.run_blocking(client.stores_find, pb.StoresFindOptions(
        key=pb.StoresKey(floats=body.get("key", [])),
        top_k=int(body.get("topk") or body.get("top_k") or 10)))
    return web.json_response({
        "keys": [list(k.floats) for k in res.keys],
        "values": [v.bytes.decode() for v in res.values],
        "similarities": list(res.similarities),
    })


# --------------- backend monitor / system ---------------

async def backend_monitor(request):
    """(reference: core/services/backend_monitor.go + endpoint)"""
    state = get_state(request)
    if request.method == "POST":
        body = await request.json()
        model = body.get("model", "")
    else:
        model = request.query.get("model", "")
    if not model:
        return api_error("model is required", 400, "invalid_request_error")
    lm = state.caps.loader.get(model)
    if lm is None:
        return api_error(f"model {model} is not loaded", 404)
    status = await state.run_blocking(lm.client.status)
    return web.json_response({
        "memory_info": {"total": status.memory.total,
                        "breakdown": dict(status.memory.breakdown)},
        "state": pb.StatusResponse.State.Name(status.state),
    })


async def backend_shutdown(request):
    state = get_state(request)
    body = await request.json()
    model = body.get("model", "")
    if not model:
        return api_error("model is required", 400, "invalid_request_error")
    await state.run_blocking(state.caps.loader.shutdown_model, model)
    return web.json_response({})


async def system_info(request):
    """(reference: routes/localai.go:60-66 /system)"""
    import jax

    state = get_state(request)
    try:
        devices = [{"id": d.id, "platform": d.platform,
                    "kind": getattr(d, "device_kind", "")} for d in jax.devices()]
    except Exception:
        devices = []
    return web.json_response({
        "backends": sorted(state.caps.loader.list_loaded()),
        "devices": devices,
        "loaded_models": sorted(state.caps.loader.list_loaded()),
        "version": __version__,
    })


async def token_metrics(request):
    """(reference: core/http/endpoints/localai/get_token_metrics.go)"""
    state = get_state(request)
    body = {}
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:
            body = {}
    model = body.get("model") or request.query.get("model", "")
    if not model:
        return api_error("model is required", 400, "invalid_request_error")
    lm = state.caps.loader.get(model)
    if lm is None:
        return api_error(f"model {model} is not loaded", 404)
    m = await state.run_blocking(lm.client.get_metrics)
    try:
        import json as _json

        engine_stats = _json.loads(m.prompt_json_for_slot or "{}")
    except Exception:
        engine_stats = {}
    return web.json_response({
        "model": model,
        "tokens_per_second": m.tokens_per_second,
        "tokens_generated": m.tokens_generated,
        "slots_active": m.slots_active,
        "slots_total": m.slots_total,
        "queued": m.queued,
        "uptime_s": m.uptime_s,
        # full engine stats dict (kv pool occupancy, prefix-cache
        # hit/miss/evict, TTFT decomposition) — see Engine.metrics()
        "engine": engine_stats,
    })


# --------------- gallery ---------------

async def models_apply(request):
    state = get_state(request)
    if state.gallery_service is None:
        return api_error("gallery service not available", 503)
    body = await request.json()
    job_id = state.gallery_service.submit_apply(body)
    return web.json_response({
        "uuid": job_id,
        "status": str(request.url.with_path(f"/models/jobs/{job_id}")),
    })


async def models_delete(request):
    state = get_state(request)
    if state.gallery_service is None:
        return api_error("gallery service not available", 503)
    name = request.match_info["name"]
    job_id = state.gallery_service.submit_delete(name)
    return web.json_response({
        "uuid": job_id,
        "status": str(request.url.with_path(f"/models/jobs/{job_id}")),
    })


async def models_available(request):
    state = get_state(request)
    if state.gallery_service is None:
        return api_error("gallery service not available", 503)
    models = await state.run_blocking(state.gallery_service.list_available)
    return web.json_response(models)


async def models_job_status(request):
    state = get_state(request)
    if state.gallery_service is None:
        return api_error("gallery service not available", 503)
    status = state.gallery_service.job_status(request.match_info["uuid"])
    if status is None:
        return api_error("job not found", 404)
    return web.json_response(status)


async def models_all_jobs(request):
    state = get_state(request)
    if state.gallery_service is None:
        return api_error("gallery service not available", 503)
    return web.json_response(state.gallery_service.all_jobs())


async def add_gallery(request):
    state = get_state(request)
    body = await request.json()
    state.config.galleries.append({"name": body.get("name"), "url": body.get("url")})
    return web.json_response({"name": body.get("name")})


async def remove_gallery(request):
    state = get_state(request)
    body = await request.json()
    state.config.galleries = [
        g for g in state.config.galleries if g.get("name") != body.get("name")
    ]
    return web.json_response({})


# --------------- p2p parity ---------------

async def p2p_nodes(request):
    """On TPU the 'swarm' is the static device mesh — report it in the
    same shape the reference reports federated nodes (reference:
    core/http/endpoints/localai/p2p.go)."""
    import jax

    try:
        nodes = [
            {"name": f"device-{d.id}", "id": str(d.id), "online": True,
             "platform": d.platform}
            for d in jax.devices()
        ]
    except Exception:
        nodes = []
    return web.json_response({"nodes": nodes, "federated_nodes": []})


async def p2p_token(request):
    return web.json_response({"token": ""})
