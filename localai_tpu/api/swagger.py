"""OpenAPI surface at /swagger (VERDICT r2 #10).

Parity with the reference, which serves a generated swagger spec at
/swagger/* (reference: swagger/docs.go registered in
core/http/routes/localai.go:20). Instead of a build-time generator, the
spec is derived from the LIVE aiohttp route table at request time, so it
can never drift from what is actually registered; summaries come from
handler docstrings.

Endpoints:
  /swagger/index.json  — OpenAPI 3.0 document listing every route
  /swagger (+ /swagger/index.html) — minimal HTML viewer
"""

from __future__ import annotations

import html as _html

from aiohttp import web


def _spec(app: web.Application) -> dict:
    paths: dict = {}
    for route in app.router.routes():
        resource = route.resource
        if resource is None:
            continue
        path = resource.canonical
        method = route.method.lower()
        if method in ("head", "options", "*"):
            continue
        if path.startswith("/swagger"):
            continue
        doc = (route.handler.__doc__ or "").strip().splitlines()
        summary = doc[0] if doc else ""
        entry = paths.setdefault(path, {})
        op = {
            "summary": summary,
            "operationId": f"{method}_{path.strip('/').replace('/', '_').replace('{', '').replace('}', '') or 'root'}",
            "responses": {"200": {"description": "OK"}},
        }
        params = [p[1:-1] for p in path.split("/") if p.startswith("{")]
        if params:
            op["parameters"] = [
                {"name": p, "in": "path", "required": True,
                 "schema": {"type": "string"}} for p in params
            ]
        entry[method] = op
    return {
        "openapi": "3.0.3",
        "info": {
            "title": "LocalAI TPU API",
            "description": "OpenAI-compatible + LocalAI-compatible API "
                           "served by the TPU-native framework.",
            "version": "2.0.0",
        },
        "paths": dict(sorted(paths.items())),
    }


async def index_json(request: web.Request) -> web.Response:
    """OpenAPI 3.0 spec generated from the live route table."""
    return web.json_response(_spec(request.app))


async def index_html(request: web.Request) -> web.Response:
    """Minimal HTML API browser over /swagger/index.json."""
    spec = _spec(request.app)
    rows = []
    for path, methods in spec["paths"].items():
        for method, op in methods.items():
            rows.append(
                f"<tr><td><code>{method.upper()}</code></td>"
                f"<td><code>{_html.escape(path)}</code></td>"
                f"<td>{_html.escape(op.get('summary', ''))}</td></tr>")
    body = f"""<!doctype html><html><head><meta charset="utf-8">
<title>LocalAI TPU API</title>
<style>body{{font-family:system-ui;margin:24px}}td,th{{padding:4px 10px;
border-bottom:1px solid #ddd;text-align:left;font-size:14px}}</style>
</head><body><h1>LocalAI TPU API</h1>
<p>{len(rows)} operations — <a href="/swagger/index.json">index.json</a></p>
<table><tr><th>method</th><th>path</th><th>summary</th></tr>
{''.join(rows)}</table></body></html>"""
    return web.Response(text=body, content_type="text/html")


def register(app: web.Application):
    app.router.add_get("/swagger", index_html)
    app.router.add_get("/swagger/index.html", index_html)
    app.router.add_get("/swagger/index.json", index_json)
