"""Chat request -> prompt assembly.

Parity with the reference's chat pipeline (reference: core/http/endpoints/
openai/chat.go:296-441 — per-message template evaluation, join, outer chat
template; multimodal content parts request.go:150-217 -> base64 +
[img-N]/[audio-N]/[vid-N] placeholders).
"""

from __future__ import annotations

import base64
from typing import Optional

import httpx

from localai_tpu.config.model_config import ModelConfig
from localai_tpu.templates import prompts as T


def _fetch_media(url: str) -> str:
    """data: URIs and http(s) URLs -> base64 payload (reference:
    pkg/utils/base64.go GetImageURLAsBase64)."""
    if url.startswith("data:"):
        _, _, payload = url.partition("base64,")
        if not payload:
            raise ValueError("unsupported data URI (expected base64)")
        return payload
    if url.startswith(("http://", "https://")):
        resp = httpx.get(url, timeout=30.0, follow_redirects=True)
        resp.raise_for_status()
        return base64.b64encode(resp.content).decode()
    raise ValueError(f"unsupported media URL scheme: {url[:32]}")


def flatten_content(message: dict) -> tuple:
    """OpenAI content parts -> (text, images[], audios[], videos[]) base64.

    (reference: request.go:150-217 'CONTENT' interface handling)
    """
    content = message.get("content")
    if content is None:
        return "", [], [], []
    if isinstance(content, str):
        return content, [], [], []
    texts, images, audios, videos = [], [], [], []
    for part in content:
        ptype = part.get("type", "text")
        if ptype == "text":
            texts.append(part.get("text", ""))
        elif ptype == "image_url":
            images.append(_fetch_media(part["image_url"]["url"]))
        elif ptype in ("audio_url", "input_audio"):
            url = part.get("audio_url", {}).get("url") or part.get("input_audio", {}).get("data", "")
            audios.append(_fetch_media(url) if url.startswith(("data:", "http")) else url)
        elif ptype == "video_url":
            videos.append(_fetch_media(part["video_url"]["url"]))
    return "\n".join(texts), images, audios, videos


def build_chat_prompt(mc: ModelConfig, messages: list, tokenizer=None,
                      functions: Optional[list] = None) -> tuple:
    """Returns (prompt_text, images, audios, videos)."""
    all_images, all_audios, all_videos = [], [], []
    norm_msgs = []
    for i, m in enumerate(messages):
        text, imgs, auds, vids = flatten_content(m)
        if imgs or auds or vids:
            text = T.multimodal_placeholders(
                mc.template.multimodal, text,
                n_images=len(imgs), n_audios=len(auds), n_videos=len(vids),
                img_offset=len(all_images), audio_offset=len(all_audios),
                vid_offset=len(all_videos),
            )
        all_images += imgs
        all_audios += auds
        all_videos += vids
        norm_msgs.append({"role": m.get("role", "user"), "content": text,
                          "tool_calls": m.get("tool_calls"),
                          "name": m.get("name")})

    if mc.template.use_tokenizer_template and tokenizer is not None:
        prompt = T.apply_tokenizer_template(tokenizer, norm_msgs, tools=functions)
        return prompt, all_images, all_audios, all_videos

    system_prompt = mc.system_prompt
    rendered = []
    msg_tpl = mc.template.chat_message or T.DEFAULT_CHAT_MESSAGE
    for i, m in enumerate(norm_msgs):
        data = T.ChatMessageData(
            system_prompt=system_prompt,
            role=m["role"], role_name=m["role"], content=m["content"] or "",
            function_call=m.get("tool_calls"),
            last_message=(i == len(norm_msgs) - 1),
            index=i,
        )
        s = T.render_chat_message(msg_tpl, data)
        if s:
            rendered.append(s)
    joiner = mc.template.join_chat_messages_by_character
    joined = (joiner if joiner is not None else "\n").join(rendered)

    if mc.template.chat:
        prompt = T.render_chat_prompt(mc.template.chat, joined, system_prompt,
                                      functions=functions)
    else:
        prompt = joined
    return prompt, all_images, all_audios, all_videos
