"""HTTP application assembly.

Parity with the reference's fiber app (reference: core/http/app.go:52-188 —
error handler, request logging, metrics middleware, bearer key-auth on
everything with GET exemptions, CORS, route registration), re-based on
aiohttp. Blocking capability calls run on a thread pool; token streams
bridge into asyncio via a queue.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import math
import re
import secrets
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

from aiohttp import web

from localai_tpu.services.errors import ServingError
from localai_tpu.services.metrics import METRICS

log = logging.getLogger("localai_tpu.api")

# GET paths reachable without an API key (reference: auth.go exemption list)
AUTH_EXEMPT = [
    re.compile(r"^/$"),
    re.compile(r"^/healthz$"),
    re.compile(r"^/readyz$"),
    re.compile(r"^/metrics$"),
    re.compile(r"^/static/"),
    re.compile(r"^/swagger"),
]


@web.middleware
async def error_middleware(request, handler):
    try:
        return await handler(request)
    except web.HTTPException:
        raise
    except ServingError as e:
        # structured lifecycle failures (shed / backend down / circuit
        # open / deadline): the right status + Retry-After, one WARNING —
        # a full traceback for an expected overload would drown the logs
        log.warning("serving error: %s %s -> %d %s: %s", request.method,
                    request.path, e.status, e.etype, e)
        return error_response(e)
    except Exception as e:
        log.exception("handler error: %s %s", request.method, request.path)
        return api_error(str(e), 500)


def api_error(message: str, status: int = 500, etype: str = "server_error"):
    """OpenAI-style error envelope (reference: schema.ErrorResponse)."""
    return web.json_response(
        {"error": {"message": message, "type": etype, "param": None, "code": status}},
        status=status,
    )


def error_response(e: ServingError) -> web.Response:
    """ServingError -> OpenAI-style envelope with its HTTP status, the
    breaker/retryability detail merged into the error object, and a
    Retry-After header when the engine provided a hint."""
    body = {"message": str(e), "type": e.etype, "param": None,
            "code": e.status}
    body.update(e.body_extra())
    headers = {}
    if e.retry_after_s:
        headers["Retry-After"] = str(math.ceil(e.retry_after_s))
    if e.status == 429:
        METRICS.inc("http_requests_shed_total")
    return web.json_response({"error": body}, status=e.status,
                             headers=headers)


# observability surfaces excluded from per-request http spans: scrapes
# and debug pulls would otherwise fill the frontend ring with their own
# reads of it
_TRACE_SKIP = re.compile(r"^/(metrics|debug/|healthz|readyz|static/)")


def make_metrics_middleware():
    import uuid

    from localai_tpu.services.tracing import frontend_tracer

    @web.middleware
    async def metrics_middleware(request, handler):
        t0 = time.perf_counter()
        # ONE trace context per request (ISSUE 12): minted here (or taken
        # from X-Correlation-ID), read by every route via
        # request["correlation_id"], propagated to the backend over
        # localai-trace-id invocation metadata — both processes' spans
        # share this id on the merged /debug/trace timeline.
        rid = request.headers.get("X-Correlation-ID") or uuid.uuid4().hex
        request["correlation_id"] = rid
        t_mono = time.monotonic()
        status = [0]
        try:
            resp = await handler(request)
            status[0] = resp.status
            return resp
        finally:
            # label by the matched route PATTERN, not the raw path —
            # raw paths (job uuids, 404 probes) are unbounded-cardinality
            resource = request.match_info.route.resource
            path = resource.canonical if resource else "unmatched"
            METRICS.observe_api_call(request.method, path,
                                     time.perf_counter() - t0)
            tr = frontend_tracer()
            if tr.enabled and not _TRACE_SKIP.match(request.path):
                tr.record("http", "http", t_mono, time.monotonic(),
                          rid=rid, args={"method": request.method,
                                         "path": path,
                                         "status": status[0] or 500})
    return metrics_middleware


def make_auth_middleware(api_keys: list):
    @web.middleware
    async def auth_middleware(request, handler):
        if not api_keys:
            return await handler(request)
        if request.method in ("GET", "OPTIONS") and any(
            p.match(request.path) for p in AUTH_EXEMPT
        ):
            return await handler(request)
        auth = request.headers.get("Authorization", "")
        key = auth.removeprefix("Bearer ").strip()
        if key and any(secrets.compare_digest(key, k) for k in api_keys):
            return await handler(request)
        return api_error("invalid api key", 401, "invalid_request_error")
    return auth_middleware


def make_cors_middleware(allow_origins: str = "*"):
    @web.middleware
    async def cors_middleware(request, handler):
        if request.method == "OPTIONS":
            resp = web.Response(status=204)
        else:
            resp = await handler(request)
        resp.headers["Access-Control-Allow-Origin"] = allow_origins
        resp.headers["Access-Control-Allow-Headers"] = "Authorization, Content-Type"
        resp.headers["Access-Control-Allow-Methods"] = "GET, POST, DELETE, OPTIONS"
        return resp
    return cors_middleware


class AppState:
    """Shared server state hung off the aiohttp app."""

    def __init__(self, caps, app_config, gallery_service=None):
        self.caps = caps
        self.config = app_config
        self.gallery_service = gallery_service
        self.executor = ThreadPoolExecutor(max_workers=64, thread_name_prefix="cap")
        self.started_at = time.time()

    async def run_blocking(self, fn, *args, **kwargs):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self.executor, lambda: fn(*args, **kwargs))

    async def iter_blocking(self, gen_factory) -> "asyncio.Queue":
        """Run a sync generator on the pool; yield items via an async queue.

        Never blocks the pump thread (unbounded queue + put_nowait), so a
        client disconnect cannot wedge an executor worker; the consumer sets
        q.cancel_event to stop the generator early (GeneratorExit runs its
        finally blocks, releasing busy marks / backend streams).
        """
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        SENTINEL = object()
        cancel = threading.Event()

        def pump():
            gen = gen_factory()
            try:
                for item in gen:
                    if cancel.is_set():
                        break
                    loop.call_soon_threadsafe(q.put_nowait, item)
            except Exception as e:
                loop.call_soon_threadsafe(q.put_nowait, e)
            finally:
                try:
                    gen.close()
                except Exception:
                    log.exception("stream generator close failed")
                loop.call_soon_threadsafe(q.put_nowait, SENTINEL)

        self.executor.submit(pump)
        q.sentinel = SENTINEL  # type: ignore[attr-defined]
        q.cancel_event = cancel  # type: ignore[attr-defined]
        return q


def get_state(request) -> AppState:
    return request.app["state"]


async def sse_response(request, chunks: "asyncio.Queue"):
    """Drain an async queue of dicts into an SSE stream, ending with [DONE]
    (reference: chat.go:463-508 fasthttp StreamWriter)."""
    # peek the FIRST item before committing to a 200 + event-stream: a
    # request shed by admission control or refused by an open circuit
    # fails before any token is produced, and the client deserves a real
    # 429/503 with Retry-After — not a 200 stream containing an error
    first = await chunks.get()
    if isinstance(first, ServingError):
        if hasattr(chunks, "cancel_event"):
            chunks.cancel_event.set()
        log.warning("stream refused: %s %s -> %d %s: %s", request.method,
                    request.path, first.status, first.etype, first)
        return error_response(first)
    resp = web.StreamResponse(headers={
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
        "Connection": "keep-alive",
        "X-Accel-Buffering": "no",
    })
    await resp.prepare(request)
    seed: list = [first]
    try:
        done = False
        while not done:
            # greedy drain: one socket write per batch of queued chunks.
            # A decode burst delivers many tokens at once, and per-token
            # write+flush is the dominant host cost of the SSE path on a
            # 1-core rig (VERDICT r4 #2)
            batch = seed or [await chunks.get()]
            seed = []
            while True:
                try:
                    batch.append(chunks.get_nowait())
                except asyncio.QueueEmpty:
                    break
            out = bytearray()
            for item in batch:
                if item is chunks.sentinel:
                    done = True
                    break
                if isinstance(item, Exception):
                    # mid-stream failure: the 200 is already on the wire,
                    # so the typed error rides the stream body instead
                    err = {"message": str(item), "type": "server_error"}
                    if isinstance(item, ServingError):
                        err["type"] = item.etype
                        err.update(item.body_extra())
                    out += f"data: {json.dumps({'error': err})}\n\n".encode()
                    done = True
                    break
                if isinstance(item, (bytes, bytearray)):
                    out += item   # pre-framed by the route (already "data: ...\n\n")
                else:
                    out += f"data: {json.dumps(item, ensure_ascii=False)}\n\n".encode()
            if out:
                await resp.write(bytes(out))
        await resp.write(b"data: [DONE]\n\n")
    except (ConnectionResetError, asyncio.CancelledError):
        raise
    finally:
        if hasattr(chunks, "cancel_event"):
            chunks.cancel_event.set()
        with contextlib.suppress(OSError, ConnectionResetError):
            await resp.write_eof()
    return resp


def build_app(caps, app_config, gallery_service=None) -> web.Application:
    from localai_tpu.api import localai_routes, openai_routes

    state = AppState(caps, app_config, gallery_service)
    middlewares = [error_middleware, make_metrics_middleware()]
    if app_config.cors:
        middlewares.append(make_cors_middleware(app_config.cors_allow_origins))
    middlewares.append(make_auth_middleware(app_config.api_keys))
    app = web.Application(
        middlewares=middlewares,
        client_max_size=app_config.upload_limit_mb * 1024 * 1024,
    )
    app["state"] = state
    openai_routes.register(app)
    localai_routes.register(app)

    from localai_tpu.api import assistants_routes

    assistants_routes.register(app)
    if not app_config.disable_webui:
        from localai_tpu.api import webui

        webui.register(app)

    from localai_tpu.api import swagger

    swagger.register(app)
    return app


async def run_app(app, address: str):
    host, _, port = address.rpartition(":")
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host or "0.0.0.0", int(port))
    await site.start()
    return runner
