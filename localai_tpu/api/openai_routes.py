"""OpenAI-compatible endpoints.

Parity with the reference (reference: core/http/endpoints/openai/ — chat.go,
completion.go, edit.go, embeddings.go, image.go, transcription.go, list.go;
route table core/http/routes/openai.go:11-85 registers each under /v1/* and
/* aliases).
"""

from __future__ import annotations

import base64
import json
import os
import secrets
import tempfile
import time
import uuid
from typing import Optional

from aiohttp import web

from localai_tpu.api.app import api_error, get_state, sse_response
from localai_tpu.api.chatflow import build_chat_prompt
from localai_tpu.capabilities import finetune_response
from localai_tpu.templates import prompts as T


def register(app: web.Application):
    r = app.router
    for prefix in ("/v1", ""):
        r.add_post(f"{prefix}/chat/completions", chat_completions)
        r.add_post(f"{prefix}/completions", completions)
        r.add_post(f"{prefix}/edits", edits)
        r.add_post(f"{prefix}/embeddings", embeddings)
        r.add_post(f"{prefix}/images/generations", images_generations)
        r.add_post(f"{prefix}/audio/transcriptions", audio_transcriptions)
        r.add_post(f"{prefix}/audio/speech", audio_speech)
        r.add_get(f"{prefix}/models", list_models)
        r.add_get(f"{prefix}/models/{{model}}", get_model)


async def _read_json(request) -> dict:
    try:
        return await request.json()
    except Exception:
        raise web.HTTPBadRequest(text="invalid JSON body")


def _model_from(request, body: dict) -> str:
    # path override > body > header (reference: fiber.go ModelFromContext)
    m = body.get("model") or request.headers.get("X-Model") or ""
    if not m:
        state = get_state(request)
        if len(state.caps.configs) == 1:
            m = next(iter(state.caps.configs))
    if not m:
        raise web.HTTPBadRequest(text="model is required")
    return m


def _overrides_from(body: dict) -> dict:
    o = {}
    for k in ("temperature", "top_k", "top_p", "min_p", "typical_p", "seed",
              "presence_penalty", "frequency_penalty", "repeat_penalty",
              "logit_bias", "ignore_eos", "echo", "grammar",
              # scheduling class (ISSUE 10): high|normal|low; unknown
              # values degrade to the model default at the engine
              "priority"):
        if k in body and body[k] is not None:
            o[k] = body[k]
    if body.get("max_tokens") or body.get("max_completion_tokens"):
        o["max_tokens"] = body.get("max_tokens") or body.get("max_completion_tokens")
    stop = body.get("stop")
    if stop:
        o["stop"] = [stop] if isinstance(stop, str) else list(stop)
    return o


def _usage(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


# --------------- chat ---------------

async def chat_completions(request):
    state = get_state(request)
    body = await _read_json(request)
    model = _model_from(request, body)
    mc = state.caps.resolve(model)
    messages = body.get("messages") or []
    if not messages:
        return api_error("messages is required", 400, "invalid_request_error")

    # minted (or taken from X-Correlation-ID) by the metrics middleware:
    # one trace context per request, shared with the backend (ISSUE 12)
    correlation_id = request.get("correlation_id") or uuid.uuid4().hex
    overrides = _overrides_from(body)

    tools = body.get("tools") or []
    functions = body.get("functions") or [
        t["function"] for t in tools if t.get("type") == "function"
    ]
    tool_choice = body.get("tool_choice") or body.get("function_call")
    if tool_choice == "none":
        functions = []  # OpenAI semantics: tools declared but must not be called
    grammar = ""
    if functions and not body.get("grammar"):
        from localai_tpu.functions.grammars import json_schema

        force_name = None
        if isinstance(tool_choice, dict):
            force_name = ((tool_choice.get("function") or {}).get("name")
                          or tool_choice.get("name"))
        grammar = json_schema.grammar_for_functions(
            functions, force_name=force_name,
            parallel_calls=bool(body.get("parallel_tool_calls", False)),
            name_key=mc.function.function_name_key,
            arguments_key=mc.function.function_arguments_key,
        )
        if grammar:
            overrides["grammar"] = grammar

    t_route = time.monotonic()
    prompt, images, audios, videos = await state.run_blocking(
        build_chat_prompt, mc, messages, None, functions or None
    )
    from localai_tpu.capabilities import trace_enabled
    from localai_tpu.services.tracing import frontend_tracer

    _tr = frontend_tracer()
    if _tr.enabled and trace_enabled(mc):
        _tr.record("build_prompt", "route", t_route, time.monotonic(),
                   rid=correlation_id, args={"model": model})
    # media parts the loaded model cannot consume are a 400, never a
    # silent drop (VERDICT r4 #6 — r4 fetched audio/video then discarded
    # them, answering confidently about media the model never saw)
    if audios:
        return api_error(
            "audio content parts are not supported on chat completions; "
            "use /v1/audio/transcriptions for speech input", 400,
            "invalid_request_error")
    if (images or videos) and not mc.mmproj:
        return api_error(
            "this model has no vision projector (mmproj); image/video "
            "content parts cannot be used", 400, "invalid_request_error")
    if videos:
        # decodability probe — the same contract the backend's frame
        # sampler enforces (utils/media.py), so route 400s and backend
        # rejections can never drift apart
        from localai_tpu.utils.media import probe_video_b64

        for v in videos:
            try:
                await state.run_blocking(probe_video_b64, v)
            except ValueError as e:
                return api_error(str(e), 400, "invalid_request_error")
        overrides["videos"] = videos
    if images:
        overrides["images"] = images

    created = int(time.time())
    cmpl_id = f"chatcmpl-{secrets.token_hex(12)}"

    if body.get("stream"):
        def gen():
            role = {"id": cmpl_id, "object": "chat.completion.chunk",
                    "created": created, "model": model,
                    "choices": [{"index": 0, "delta": {"role": "assistant",
                                                       "content": ""},
                                 "finish_reason": None}]}
            # the role delta is deferred until the backend produced its
            # first chunk: a request refused at admission (shed, circuit
            # open, backend down) must fail the HTTP exchange with a real
            # 429/503 + Retry-After, not a 200 stream opened by an eager
            # skeleton (sse_response peeks the first item for exactly this)
            sent_role = False
            usage = [0, 0]
            finish = "stop"
            # content deltas are the per-token hot path: pre-serialize the
            # invariant chunk skeleton once and splice only the token text
            # (sse_response passes pre-framed bytes through untouched)
            head = (f'data: {{"id":"{cmpl_id}",'
                    '"object":"chat.completion.chunk",'
                    f'"created":{created},"model":{json.dumps(model)},'
                    '"choices":[{"index":0,"delta":{"content":').encode()
            tail = b'},"finish_reason":null}]}\n\n'
            # under a forced tool grammar the whole output IS the call JSON:
            # buffer it and emit a tool_calls delta instead of content
            buffer_tools = bool(functions and grammar)
            collected = []
            for chunk in state.caps.inference_stream(mc, prompt, overrides,
                                                     correlation_id):
                if not sent_role:
                    yield role
                    sent_role = True
                usage = [chunk.prompt_tokens, chunk.completion_tokens]
                if chunk.finish_reason:
                    finish = chunk.finish_reason
                if chunk.text:
                    if buffer_tools:
                        collected.append(chunk.text)
                    else:
                        yield (head + json.dumps(
                            chunk.text, ensure_ascii=False).encode() + tail)
            if buffer_tools:
                from localai_tpu.functions import parse as fparse

                calls = fparse.parse_function_calls("".join(collected), mc.function)
                if calls:
                    finish = "tool_calls"
                    yield {"id": cmpl_id, "object": "chat.completion.chunk",
                           "created": created, "model": model,
                           "choices": [{"index": 0, "delta": {"tool_calls": [
                               {"index": i, "id": f"call_{secrets.token_hex(8)}",
                                "type": "function",
                                "function": {"name": c.name,
                                             "arguments": c.arguments}}
                               for i, c in enumerate(calls)]},
                               "finish_reason": None}]}
                elif collected:
                    yield {"id": cmpl_id, "object": "chat.completion.chunk",
                           "created": created, "model": model,
                           "choices": [{"index": 0,
                                        "delta": {"content": "".join(collected)},
                                        "finish_reason": None}]}
            if not sent_role:
                yield role      # empty generation: still a valid stream
            final = {"id": cmpl_id, "object": "chat.completion.chunk",
                     "created": created, "model": model,
                     "choices": [{"index": 0, "delta": {},
                                  "finish_reason": finish}],
                     "usage": _usage(*usage)}
            yield final

        q = await state.iter_blocking(gen)
        return await sse_response(request, q)

    # non-stream: n choices (reference: ComputeChoices inference.go:11-63).
    # Fanned out CONCURRENTLY: each choice occupies its own engine slot and
    # the continuous-batching engine decodes them together; identical
    # prompts submitted together prefill ONCE and fork KV rows to the
    # sibling slots (engine._admit in-flight dedup). Each choice gets a
    # DISTINCT seed (explicit seed: seed+i; default: per-choice correlation
    # id feeds the engine's fallback-seed hash) — n identical samples was
    # ADVICE r2's finding.
    import asyncio

    n = int(body.get("n") or 1)

    def _choice_overrides(i):
        if not i:
            return overrides
        o = dict(overrides or {})
        if o.get("seed") is not None:
            o["seed"] = int(o["seed"]) + i
        return o

    chunks = await asyncio.gather(*[
        state.run_blocking(state.caps.inference, mc, prompt,
                           _choice_overrides(i),
                           f"{correlation_id}-c{i}" if i and correlation_id
                           else correlation_id)
        for i in range(n)
    ])
    choices = []
    usage_pt, usage_ct = 0, 0
    for i, chunk in enumerate(chunks):
        usage_pt = chunk.prompt_tokens
        usage_ct += chunk.completion_tokens
        text = chunk.text
        message = {"role": "assistant", "content": text}
        finish = chunk.finish_reason or "stop"
        if functions:
            from localai_tpu.functions import parse as fparse

            calls = fparse.parse_function_calls(text, mc.function)
            if calls:
                message = {
                    "role": "assistant", "content": None,
                    "tool_calls": [
                        {"id": f"call_{secrets.token_hex(8)}", "type": "function",
                         "function": {"name": c.name, "arguments": c.arguments}}
                        for c in calls
                    ],
                }
                finish = "tool_calls"
        choices.append({"index": i, "message": message, "finish_reason": finish})
    return web.json_response({
        "id": cmpl_id, "object": "chat.completion", "created": created,
        "model": model, "choices": choices, "usage": _usage(usage_pt, usage_ct),
    })


# --------------- completions ---------------

async def completions(request):
    state = get_state(request)
    body = await _read_json(request)
    model = _model_from(request, body)
    mc = state.caps.resolve(model)
    overrides = _overrides_from(body)
    correlation_id = request.get("correlation_id") or uuid.uuid4().hex
    prompts = body.get("prompt", "")
    if isinstance(prompts, str):
        prompts = [prompts]

    created = int(time.time())
    cmpl_id = f"cmpl-{secrets.token_hex(12)}"

    def render(p):
        if mc.template.completion:
            return T.render_completion(mc.template.completion, p, mc.system_prompt)
        return p

    if body.get("stream"):
        prompt = render(prompts[0])

        def gen():
            usage = [0, 0]
            finish = "stop"
            # pre-serialized skeleton, as in the chat stream hot path
            head = (f'data: {{"id":"{cmpl_id}","object":"text_completion",'
                    f'"created":{created},"model":{json.dumps(model)},'
                    '"choices":[{"index":0,"text":').encode()
            tail = b',"finish_reason":null}]}\n\n'
            for chunk in state.caps.inference_stream(mc, prompt, overrides,
                                                     correlation_id):
                usage = [chunk.prompt_tokens, chunk.completion_tokens]
                if chunk.finish_reason:
                    finish = chunk.finish_reason
                if chunk.text:
                    yield (head + json.dumps(
                        chunk.text, ensure_ascii=False).encode() + tail)
            yield {"id": cmpl_id, "object": "text_completion", "created": created,
                   "model": model,
                   "choices": [{"index": 0, "text": "", "finish_reason": finish}],
                   "usage": _usage(*usage)}

        q = await state.iter_blocking(gen)
        return await sse_response(request, q)

    # multi-prompt batches fan out concurrently across engine slots
    import asyncio

    chunks = await asyncio.gather(*[
        state.run_blocking(state.caps.inference, mc, render(p), overrides,
                           f"{correlation_id}-p{i}" if i else correlation_id)
        for i, p in enumerate(prompts)
    ])
    choices = []
    usage_pt, usage_ct = 0, 0
    for i, chunk in enumerate(chunks):
        usage_pt += chunk.prompt_tokens
        usage_ct += chunk.completion_tokens
        choices.append({"index": i, "text": chunk.text,
                        "finish_reason": chunk.finish_reason or "stop"})
    return web.json_response({
        "id": cmpl_id, "object": "text_completion", "created": created,
        "model": model, "choices": choices, "usage": _usage(usage_pt, usage_ct),
    })


# --------------- edits ---------------

async def edits(request):
    state = get_state(request)
    body = await _read_json(request)
    model = _model_from(request, body)
    mc = state.caps.resolve(model)
    instruction = body.get("instruction", "")
    inp = body.get("input", "")
    if mc.template.edit:
        prompt = T.render_edit(mc.template.edit, instruction, inp)
    else:
        prompt = f"{instruction}\n\n{inp}"
    overrides = _overrides_from(body)
    chunk = await state.run_blocking(state.caps.inference, mc, prompt, overrides)
    return web.json_response({
        "object": "edit", "created": int(time.time()), "model": model,
        "choices": [{"index": 0, "text": chunk.text}],
        "usage": _usage(chunk.prompt_tokens, chunk.completion_tokens),
    })


# --------------- embeddings ---------------

async def embeddings(request):
    state = get_state(request)
    body = await _read_json(request)
    model = _model_from(request, body)
    mc = state.caps.resolve(model)
    inputs = body.get("input", "")
    if isinstance(inputs, (str, int)):
        inputs = [inputs]
    vecs = await state.run_blocking(state.caps.embeddings, mc, inputs)
    data = [
        {"object": "embedding", "index": i, "embedding": v}
        for i, v in enumerate(vecs)
    ]
    return web.json_response({
        "object": "list", "model": model, "data": data,
        "usage": _usage(0, 0),
    })


# --------------- images ---------------

async def images_generations(request):
    state = get_state(request)
    body = await _read_json(request)
    model = body.get("model") or "stablediffusion"
    mc = state.caps.resolve(model)
    size = body.get("size", "512x512")
    try:
        width, height = (int(x) for x in size.split("x"))
    except ValueError:
        return api_error(f"invalid size {size}", 400, "invalid_request_error")
    prompt = body.get("prompt", "")
    positive, _, negative = prompt.partition("|")
    try:
        n = int(body.get("n") or 1)
        step = int(body.get("step", 25))
        base_seed = int(body.get("seed", 0))
    except (TypeError, ValueError):
        return api_error("n, step and seed must be integers", 400,
                         "invalid_request_error")
    # img2img (reference: OpenAIRequest.File -> request.src,
    # endpoints/openai/image.go): base64 init image (optionally a data
    # URL) + "strength"; scheduler override rides the same body
    from localai_tpu.config.model_config import SCHEDULERS

    scheduler = str(body.get("scheduler", "") or "")
    if scheduler and scheduler not in SCHEDULERS:
        return api_error(f"unknown scheduler {scheduler!r}", 400,
                         "invalid_request_error")
    strength = body.get("strength")
    if strength is not None:
        import math as _math

        try:
            strength = float(strength)
        except (TypeError, ValueError):
            strength = None
        if strength is None or not _math.isfinite(strength):
            return api_error("strength must be a finite number", 400,
                             "invalid_request_error")
    src = ""
    if body.get("file"):
        data = body["file"]
        if isinstance(data, str) and data.startswith("data:"):
            # same contract as chatflow._fetch_media: only base64 data URIs
            head, sep, payload = data.partition("base64,")
            if not sep:
                return api_error("unsupported data URI (base64 only)", 400,
                                 "invalid_request_error")
            data = payload
        try:
            raw = base64.b64decode(data)
        except Exception:
            return api_error("file must be base64", 400,
                             "invalid_request_error")
        fd, src = tempfile.mkstemp(suffix=".png", prefix="localai-img2img-")
        with os.fdopen(fd, "wb") as f:
            f.write(raw)
    out = []
    mode = str(body.get("mode", "") or "")
    # video modes write a video container at dst (reference: diffusers
    # backend export_to_video); "format" picks gif/webp over mp4
    ext = ".png"
    if mode in ("txt2vid", "img2vid"):
        fmt = str(body.get("format", "") or "mp4").lstrip(".").lower()
        if fmt not in ("mp4", "gif", "webp", "avi"):
            return api_error(f"unsupported video format {fmt!r}", 400,
                             "invalid_request_error")
        ext = "." + fmt
    try:
        for i in range(n):
            dst = os.path.join(tempfile.gettempdir(),
                               f"localai-img-{secrets.token_hex(8)}{ext}")
            # n > 1 must produce n DIFFERENT samples: offset the seed
            # per image (a fixed seed otherwise reseeds the sampler
            # identically n times). Offsets wrap inside int32 (the proto
            # field); negative = "pick for me" -> fresh entropy per image.
            if base_seed >= 0:
                seed_i = (base_seed + i) % 0x7FFFFFFF
            else:
                seed_i = secrets.randbits(31)
            await state.run_blocking(
                state.caps.generate_image, mc, positive.strip(),
                negative.strip(), width, height, step,
                seed_i, dst, src, mode,
                strength, scheduler)
            if body.get("response_format") == "b64_json":
                with open(dst, "rb") as f:
                    out.append({"b64_json":
                                base64.b64encode(f.read()).decode()})
                os.unlink(dst)
            else:
                out.append({"url": f"file://{dst}"})
    finally:
        if src:
            try:
                os.unlink(src)
            except OSError:
                pass
    return web.json_response({"created": int(time.time()), "data": out})


# --------------- audio ---------------

async def audio_transcriptions(request):
    state = get_state(request)
    reader = await request.multipart()
    model, language, translate, audio_path = "", "", False, None
    async for part in reader:
        if part.name == "model":
            model = (await part.read()).decode()
        elif part.name == "language":
            language = (await part.read()).decode()
        elif part.name == "translate":
            translate = (await part.read()).decode().lower() in ("1", "true")
        elif part.name == "file":
            suffix = os.path.splitext(part.filename or "audio.wav")[1]
            fd, audio_path = tempfile.mkstemp(suffix=suffix, prefix="localai-stt-")
            with os.fdopen(fd, "wb") as f:
                f.write(await part.read())
    if not audio_path:
        return api_error("file is required", 400, "invalid_request_error")
    mc = state.caps.resolve(model or "whisper")
    try:
        res = await state.run_blocking(
            state.caps.transcribe, mc, audio_path, language, translate)
    finally:
        os.unlink(audio_path)
    return web.json_response({
        "text": res.text,
        "segments": [
            {"id": s.id, "start": s.start / 1e9, "end": s.end / 1e9,
             "text": s.text, "tokens": list(s.tokens)}
            for s in res.segments
        ],
    })


async def audio_speech(request):
    """OpenAI TTS endpoint (reference: localai/tts.go handles /tts;
    /v1/audio/speech maps here too per routes/openai.go)."""
    from localai_tpu.api.localai_routes import run_audio_capability

    state = get_state(request)
    body = await _read_json(request)
    model = _model_from(request, body)
    mc = state.caps.resolve(model)
    return await run_audio_capability(
        request, lambda dst: state.caps.tts(
            mc, body.get("input", ""), body.get("voice", ""),
            body.get("language", ""), dst))


# --------------- models ---------------

async def list_models(request):
    state = get_state(request)
    loaded = set(state.caps.loader.list_loaded())
    data = [
        {"id": name, "object": "model", "created": int(state.started_at),
         "owned_by": "localai-tpu", "ready": name in loaded}
        for name, mc in sorted(state.caps.configs.items())
    ]
    return web.json_response({"object": "list", "data": data})


async def get_model(request):
    state = get_state(request)
    name = request.match_info["model"]
    if name not in state.caps.configs:
        return api_error(f"model {name} not found", 404, "invalid_request_error")
    return web.json_response({"id": name, "object": "model",
                              "owned_by": "localai-tpu"})
