"""WebUI: server-rendered dashboard over the existing JSON APIs.

Capability parity with the reference's HTMX dashboard (reference:
core/http/routes/ui.go:88-413 + core/http/views/ — model browse/install
with live progress, chat, text-to-image, TTS, and p2p/swarm pages).
Re-designed as dependency-free server-rendered pages with small inline
scripts that drive the SAME public endpoints a programmatic client uses
(/v1/models, /models/apply, /models/jobs/:uid, /v1/chat/completions SSE,
/v1/images/generations, /tts, /api/p2p) — no template engine, no asset
pipeline, nothing the JSON API can't do.
"""

from __future__ import annotations

import html

from aiohttp import web

from localai_tpu.api.app import get_state

_STYLE = """
body{font-family:system-ui,sans-serif;margin:0;background:#f6f7f9;color:#1c2430}
header{background:#1c2430;color:#fff;padding:10px 24px;display:flex;gap:18px;align-items:baseline}
header a{color:#9fc1ff;text-decoration:none;margin-right:10px}
header .brand{font-weight:700;font-size:18px;color:#fff}
main{max-width:960px;margin:24px auto;padding:0 16px}
.card{background:#fff;border:1px solid #e2e6ec;border-radius:8px;padding:16px;margin-bottom:16px}
table{width:100%;border-collapse:collapse}
td,th{text-align:left;padding:6px 8px;border-bottom:1px solid #eef1f5;font-size:14px}
button{background:#2a62d9;color:#fff;border:0;border-radius:6px;padding:6px 12px;cursor:pointer}
button:disabled{background:#9fb3d9}
input,textarea,select{width:100%;box-sizing:border-box;padding:8px;border:1px solid #cdd5e0;border-radius:6px;font:inherit}
pre{white-space:pre-wrap;background:#0f1420;color:#d7e3f4;padding:12px;border-radius:6px;min-height:80px}
.status{font-size:13px;color:#5a6678}
"""


def _page(title: str, body: str) -> web.Response:
    doc = f"""<!doctype html><html><head><meta charset="utf-8">
<title>{html.escape(title)} — LocalAI TPU</title><style>{_STYLE}</style></head>
<body><header><span class="brand">LocalAI&nbsp;TPU</span>
<nav><a href="/">Models</a><a href="/browse">Browse</a><a href="/chat">Chat</a>
<a href="/text2image">Image</a><a href="/tts-ui">TTS</a><a href="/p2p-ui">Mesh</a></nav>
</header><main>{body}</main></body></html>"""
    return web.Response(text=doc, content_type="text/html")


async def index(request):
    state = get_state(request)
    rows = []
    for name, mc in sorted(state.caps.configs.items()):
        loaded = state.caps.loader.is_loaded(name) if hasattr(
            state.caps.loader, "is_loaded") else False
        rows.append(
            f"<tr><td>{html.escape(name)}</td>"
            f"<td>{html.escape(mc.backend or 'auto')}</td>"
            f"<td>{'loaded' if loaded else 'on disk'}</td>"
            f"<td><button class=\"del\" data-name=\"{html.escape(name, quote=True)}\">"
            f"delete</button></td></tr>")
    body = f"""
<div class="card"><h2>Installed models</h2>
<table><tr><th>name</th><th>backend</th><th>state</th><th></th></tr>
{''.join(rows) or '<tr><td colspan=4>no models installed — try Browse</td></tr>'}
</table></div>
<script>
for(const b of document.querySelectorAll('button.del')){{
  b.addEventListener('click', async () => {{
    const name = b.dataset.name;  // entity-decoded by the parser, not JS
    if(!confirm('Delete '+name+'?'))return;
    await fetch('/models/delete/'+encodeURIComponent(name),{{method:'POST'}});
    location.reload();
  }});
}}
</script>"""
    return _page("Models", body)


async def browse(request):
    body = """
<div class="card"><h2>Model gallery</h2>
<p class="status">Models from configured galleries; installs stream progress from /models/jobs.</p>
<div id="list">loading…</div></div>
<script>
async function load(){
  const r = await fetch('/models/available');
  const items = await r.json();
  const div = document.getElementById('list');
  if(!Array.isArray(items)||!items.length){div.textContent='no gallery models available';return}
  // DOM construction with textContent: gallery manifests are REMOTE
  // content — names must never reach innerHTML or JS-string context
  const table = document.createElement('table');
  table.innerHTML = '<tr><th>name</th><th>gallery</th><th></th></tr>';
  for(const m of items){
    const tr = document.createElement('tr');
    const td1 = document.createElement('td'); td1.textContent = m.name;
    const td2 = document.createElement('td'); td2.textContent = m.gallery||'';
    const td3 = document.createElement('td');
    const btn = document.createElement('button');
    btn.textContent = 'install';
    const id = (m.gallery ? m.gallery + '@' : '') + m.name;
    btn.addEventListener('click', () => install(id, btn));
    td3.appendChild(btn);
    tr.append(td1, td2, td3);
    table.appendChild(tr);
  }
  div.replaceChildren(table);
}
async function install(id, btn){
  btn.disabled = true;
  const r = await fetch('/models/apply',{method:'POST',headers:{'Content-Type':'application/json'},
    body:JSON.stringify({id})});
  const {uuid} = await r.json();
  const tick = setInterval(async ()=>{
    const s = await (await fetch('/models/jobs/'+uuid)).json();
    btn.textContent = s.processed ? (s.error?'failed':'installed')
                                  : `${Math.round((s.progress||0))}%`;
    if(s.processed){clearInterval(tick);}
  }, 700);
}
load();
</script>"""
    return _page("Browse", body)


async def chat(request):
    state = get_state(request)
    options = "".join(f"<option>{html.escape(n)}</option>"
                      for n in sorted(state.caps.configs))
    body = f"""
<div class="card"><h2>Chat</h2>
<select id="model">{options}</select>
<pre id="out"></pre>
<textarea id="msg" rows="3" placeholder="Say something…"></textarea>
<p><button id="send">Send</button> <span class="status" id="st"></span></p></div>
<script>
const hist = [];
send.onclick = async () => {{
  const text = msg.value.trim(); if(!text) return;
  hist.push({{role:'user', content:text}});
  out.textContent += 'you: ' + text + '\\n'; msg.value=''; st.textContent='…';
  const r = await fetch('/v1/chat/completions', {{method:'POST',
    headers:{{'Content-Type':'application/json'}},
    body: JSON.stringify({{model:model.value, messages:hist, stream:true}})}});
  out.textContent += 'assistant: ';
  const reader = r.body.getReader(); const dec = new TextDecoder();
  let reply = '', buf='';
  while(true){{
    const {{done, value}} = await reader.read(); if(done) break;
    buf += dec.decode(value, {{stream:true}});
    for(const line of buf.split('\\n')){{
      if(!line.startsWith('data: ')) continue;
      const payload = line.slice(6);
      if(payload === '[DONE]') continue;
      try {{
        const d = JSON.parse(payload).choices?.[0]?.delta?.content;
        if(d) {{ reply += d; }}
      }} catch(e) {{}}
    }}
    buf = buf.slice(buf.lastIndexOf('\\n')+1);
    out.textContent = out.textContent.replace(/assistant: [^]*$/, () => 'assistant: '+reply);
  }}
  out.textContent += '\\n'; hist.push({{role:'assistant', content:reply}});
  st.textContent='';
}};
</script>"""
    return _page("Chat", body)


async def text2image(request):
    state = get_state(request)
    options = "".join(f"<option>{html.escape(n)}</option>"
                      for n in sorted(state.caps.configs))
    body = f"""
<div class="card"><h2>Text to image</h2>
<select id="model">{options}</select>
<input id="prompt" placeholder="a pelican riding a bicycle">
<p><button id="go">Generate</button> <span class="status" id="st"></span></p>
<img id="img" style="max-width:100%"></div>
<script>
go.onclick = async () => {{
  st.textContent='generating…'; go.disabled=true;
  const r = await fetch('/v1/images/generations', {{method:'POST',
    headers:{{'Content-Type':'application/json'}},
    body: JSON.stringify({{model:model.value, prompt:prompt.value, size:'256x256',
                           response_format:'b64_json'}})}});
  const j = await r.json(); go.disabled=false;
  if(j.data && j.data[0]){{
    img.src = j.data[0].b64_json ? 'data:image/png;base64,'+j.data[0].b64_json : j.data[0].url;
    st.textContent='';
  }} else st.textContent = JSON.stringify(j);
}};
</script>"""
    return _page("Image", body)


async def tts_ui(request):
    state = get_state(request)
    options = "".join(f"<option>{html.escape(n)}</option>"
                      for n in sorted(state.caps.configs))
    body = f"""
<div class="card"><h2>Text to speech</h2>
<select id="model">{options}</select>
<input id="text" placeholder="Hello from the TPU">
<p><button id="go">Speak</button> <span class="status" id="st"></span></p>
<audio id="audio" controls style="width:100%"></audio></div>
<script>
go.onclick = async () => {{
  st.textContent='synthesizing…'; go.disabled=true;
  const r = await fetch('/tts', {{method:'POST',
    headers:{{'Content-Type':'application/json'}},
    body: JSON.stringify({{model:model.value, input:text.value}})}});
  go.disabled=false;
  if(!r.ok){{ st.textContent = await r.text(); return; }}
  audio.src = URL.createObjectURL(await r.blob()); audio.play(); st.textContent='';
}};
</script>"""
    return _page("TTS", body)


async def p2p_ui(request):
    body = """
<div class="card"><h2>Device mesh</h2><pre id="out">loading…</pre></div>
<script>
fetch('/api/p2p').then(r=>r.json()).then(j=>{
  out.textContent = JSON.stringify(j, null, 2);
}).catch(e=>{ out.textContent = String(e); });
</script>"""
    return _page("Mesh", body)


def register(app: web.Application):
    r = app.router
    r.add_get("/", index)
    r.add_get("/browse", browse)
    r.add_get("/chat", chat)
    r.add_get("/text2image", text2image)
    r.add_get("/tts-ui", tts_ui)
    r.add_get("/p2p-ui", p2p_ui)
