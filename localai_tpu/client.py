"""Python client SDK for a running server (reference parity:
core/clients/store.go + pkg/store/client.go — the Go vector-store client
SDK, extended with the obvious chat/embedding helpers).

Synchronous, httpx-based, dependency-light:

    from localai_tpu.client import Client
    c = Client("http://localhost:8080", api_key="sk-...")
    c.stores_set(keys=[[0.1, 0.2]], values=["hello"], store="default")
    hits = c.stores_find(key=[0.1, 0.2], topk=3)
    text = c.chat("tiny", [{"role": "user", "content": "hi"}])
"""

from __future__ import annotations

from typing import Iterator, Optional

import httpx


class Client:
    def __init__(self, base_url: str, api_key: str = "",
                 timeout: float = 120.0):
        self.base_url = base_url.rstrip("/")
        headers = {}
        if api_key:
            headers["Authorization"] = f"Bearer {api_key}"
        self._http = httpx.Client(base_url=self.base_url, headers=headers,
                                  timeout=timeout)

    def close(self):
        self._http.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _post(self, path: str, body: dict) -> dict:
        r = self._http.post(path, json=body)
        r.raise_for_status()
        return r.json() if r.content else {}

    # ---- vector store (reference: core/clients/store.go:1-155) ----

    def stores_set(self, keys: list, values: list, store: str = "") -> None:
        self._post("/stores/set",
                   {"keys": keys, "values": values, "store": store})

    def stores_get(self, keys: list, store: str = "") -> tuple:
        r = self._post("/stores/get", {"keys": keys, "store": store})
        return r.get("keys", []), r.get("values", [])

    def stores_delete(self, keys: list, store: str = "") -> None:
        self._post("/stores/delete", {"keys": keys, "store": store})

    def stores_find(self, key: list, topk: int = 5, store: str = "") -> tuple:
        r = self._post("/stores/find",
                       {"key": key, "topk": topk, "store": store})
        return (r.get("keys", []), r.get("values", []),
                r.get("similarities", []))

    # ---- convenience wrappers over the OpenAI surface ----

    def chat(self, model: str, messages: list, **kw) -> str:
        r = self._post("/v1/chat/completions",
                       {"model": model, "messages": messages, **kw})
        return r["choices"][0]["message"]["content"]

    def chat_stream(self, model: str, messages: list, **kw) -> Iterator[str]:
        import json as _json

        with self._http.stream("POST", "/v1/chat/completions", json={
                "model": model, "messages": messages, "stream": True, **kw
        }) as r:
            r.raise_for_status()
            for line in r.iter_lines():
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data == "[DONE]":
                    return
                delta = (_json.loads(data)["choices"] or [{}])[0].get(
                    "delta", {})
                if delta.get("content"):
                    yield delta["content"]

    def embeddings(self, model: str, inputs) -> list:
        r = self._post("/v1/embeddings", {"model": model, "input": inputs})
        return [d["embedding"] for d in r["data"]]

    def models(self) -> list:
        r = self._http.get("/v1/models")
        r.raise_for_status()
        return [m["id"] for m in r.json().get("data", [])]

    def health(self) -> bool:
        try:
            return self._http.get("/readyz").status_code == 200
        except httpx.HTTPError:
            return False
