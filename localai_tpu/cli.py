"""CLI entrypoint: python -m localai_tpu <command>.

Parity with the reference CLI (reference: core/cli/cli.go:8-20 —
run|models|tts|sound-generation|transcript|worker|util subcommands; flags
with env aliases via core/cli/run.go struct tags).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys


def _add_run(sub):
    p = sub.add_parser("run", help="start the API server")
    p.add_argument("models", nargs="*", help="models to preload (path/URL/gallery name)")
    p.add_argument("--models-path", default=os.environ.get("LOCALAI_MODELS_PATH", "models"))
    p.add_argument("--address", default=os.environ.get("LOCALAI_ADDRESS", "127.0.0.1:8080"))
    p.add_argument("--context-size", type=int, default=None)
    p.add_argument("--api-keys", default=None, help="comma-separated bearer keys")
    p.add_argument("--single-active-backend", action="store_true")
    p.add_argument("--enable-watchdog-idle", action="store_true")
    p.add_argument("--enable-watchdog-busy", action="store_true")
    p.add_argument("--mesh-tp", type=int, default=None)
    p.add_argument("--mesh-dp", type=int, default=None)
    p.add_argument("--load-to-memory", action="append", default=[])
    p.add_argument("--log-level", default=os.environ.get("LOCALAI_LOG_LEVEL", "info"))
    p.add_argument("--disable-webui", action="store_true")


def _add_simple(sub):
    m = sub.add_parser("models", help="list/install models offline")
    msub = m.add_subparsers(dest="models_cmd", required=True)
    mi = msub.add_parser("install")
    mi.add_argument("names", nargs="+")
    mi.add_argument("--models-path", default="models")
    ml = msub.add_parser("list")
    ml.add_argument("--models-path", default="models")

    t = sub.add_parser("tts", help="one-shot TTS")
    t.add_argument("text")
    t.add_argument("--model", required=True)
    t.add_argument("--voice", default="")
    t.add_argument("--output", default="out.wav")
    t.add_argument("--models-path", default="models")

    tr = sub.add_parser("transcript", help="one-shot transcription")
    tr.add_argument("file")
    tr.add_argument("--model", required=True)
    tr.add_argument("--language", default="")
    tr.add_argument("--models-path", default="models")

    w = sub.add_parser("worker", help="start a multi-host worker process")
    w.add_argument("--coordinator", required=True, help="host:port of process 0")
    w.add_argument("--num-processes", type=int, required=True)
    w.add_argument("--process-id", type=int, required=True)

    u = sub.add_parser("util", help="utilities")
    usub = u.add_subparsers(dest="util_cmd", required=True)
    ui = usub.add_parser("model-info")
    ui.add_argument("path")

    sg = sub.add_parser("sound-generation", help="one-shot sound generation")
    sg.add_argument("text")
    sg.add_argument("--model", required=True)
    sg.add_argument("--duration", type=float, default=None)
    sg.add_argument("--output", default="out.wav")
    sg.add_argument("--models-path", default="models")

    f = sub.add_parser("federated",
                       help="request-level load balancer over N instances")
    f.add_argument("--address", default="127.0.0.1:8080")
    f.add_argument("--workers", required=True,
                   help="comma-separated base URLs (http://host:port)")
    f.add_argument("--load-balancing-strategy", default="random",
                   choices=["random", "least_number_of_requests"])

    x = sub.add_parser("explorer",
                       help="dashboard over registered federation endpoints")
    x.add_argument("--address", default="127.0.0.1:8080")
    x.add_argument("--db-path", default="explorer.json")
    x.add_argument("--poll-interval", type=float, default=30.0)


def main(argv=None):
    parser = argparse.ArgumentParser(prog="localai-tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)
    _add_run(sub)
    _add_simple(sub)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=getattr(logging, getattr(args, "log_level", "info").upper(), logging.INFO),
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )

    if args.cmd == "run":
        from localai_tpu.config.app_config import AppConfig
        from localai_tpu.startup import serve

        cfg = AppConfig.from_env(
            models_path=args.models_path,
            address=args.address,
            context_size=args.context_size,
            single_active_backend=args.single_active_backend or None,
            enable_watchdog_idle=args.enable_watchdog_idle or None,
            enable_watchdog_busy=args.enable_watchdog_busy or None,
            mesh_tp=args.mesh_tp,
            mesh_dp=args.mesh_dp,
            disable_webui=args.disable_webui or None,
        )
        if args.api_keys:
            cfg.api_keys = [k.strip() for k in args.api_keys.split(",")]
        cfg.preload_models = list(args.models)
        cfg.load_to_memory = list(args.load_to_memory)
        try:
            asyncio.run(serve(cfg))
        except KeyboardInterrupt:
            pass
        return 0

    if args.cmd == "models":
        from localai_tpu.config.model_config import scan_models_dir

        if args.models_cmd == "list":
            for name in sorted(scan_models_dir(args.models_path)):
                print(name)
        elif args.models_cmd == "install":
            from localai_tpu.gallery.preload import install_models

            install_models(args.names, args.models_path, [])
        return 0

    if args.cmd == "tts":
        from localai_tpu.capabilities import Capabilities
        from localai_tpu.config.app_config import AppConfig
        from localai_tpu.config.model_config import scan_models_dir
        from localai_tpu.modelmgr.loader import ModelLoader

        app = AppConfig.from_env(models_path=args.models_path)
        loader = ModelLoader()
        caps = Capabilities(app, loader, scan_models_dir(args.models_path))
        try:
            caps.tts(caps.resolve(args.model), args.text, args.voice, "", args.output)
            print(args.output)
        finally:
            loader.stop_all()
        return 0

    if args.cmd == "transcript":
        from localai_tpu.capabilities import Capabilities
        from localai_tpu.config.app_config import AppConfig
        from localai_tpu.config.model_config import scan_models_dir
        from localai_tpu.modelmgr.loader import ModelLoader

        app = AppConfig.from_env(models_path=args.models_path)
        loader = ModelLoader()
        caps = Capabilities(app, loader, scan_models_dir(args.models_path))
        try:
            res = caps.transcribe(caps.resolve(args.model), args.file, args.language, False)
            print(res.text)
        finally:
            loader.stop_all()
        return 0

    if args.cmd == "worker":
        # multi-host: join the jax distributed service and block; the
        # coordinator (process 0) owns the HTTP port (replaces the
        # reference's p2p rpc-server worker mode, core/cli/worker/)
        import jax

        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
        print(f"worker {args.process_id}/{args.num_processes} joined "
              f"{args.coordinator}; devices: {jax.local_device_count()} local")
        import time

        while True:
            time.sleep(60)

    if args.cmd == "sound-generation":
        from localai_tpu.capabilities import Capabilities
        from localai_tpu.config.app_config import AppConfig
        from localai_tpu.config.model_config import scan_models_dir
        from localai_tpu.modelmgr.loader import ModelLoader

        app = AppConfig.from_env(models_path=args.models_path)
        loader = ModelLoader()
        caps = Capabilities(app, loader, scan_models_dir(args.models_path))
        try:
            caps.sound_generation(caps.resolve(args.model), args.text,
                                  args.output, duration=args.duration)
            print(args.output)
        finally:
            loader.stop_all()
        return 0

    if args.cmd == "federated":
        from localai_tpu.federation import serve as fed_serve

        workers = [w.strip() for w in args.workers.split(",") if w.strip()]
        try:
            asyncio.run(fed_serve(workers, args.address,
                                  args.load_balancing_strategy))
        except KeyboardInterrupt:
            pass
        return 0

    if args.cmd == "explorer":
        from localai_tpu.explorer import serve as ex_serve

        try:
            asyncio.run(ex_serve(args.address, args.db_path,
                                 args.poll_interval))
        except KeyboardInterrupt:
            pass
        return 0

    if args.cmd == "util":
        if args.util_cmd == "model-info":
            import json

            from localai_tpu.models.llama import LlamaConfig

            cfg_path = os.path.join(args.path, "config.json")
            cfg = LlamaConfig.from_json(cfg_path)
            print(json.dumps(cfg.__dict__, default=str, indent=2))
        return 0

    return 1


if __name__ == "__main__":
    sys.exit(main())
