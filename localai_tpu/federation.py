"""Federation: request-level load balancing across full serving instances.

Capability parity with the reference's federated server (reference:
core/p2p/federated_server.go:36-105 + federated.go:39-99 — a thin proxy
in front of N LocalAI instances choosing a worker per request, randomly
or by least in-flight load, skipping offline workers). The reference
discovers workers over its libp2p VPN; the TPU design replaces discovery
with an explicit worker list (pod addresses are static and declarative —
SURVEY §2.4: "front-door LB over N model servers / pods (DCN)").
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import random
import time

from aiohttp import ClientSession, ClientTimeout, web

log = logging.getLogger("localai_tpu.federation")

HOP_HEADERS = {"host", "content-length", "transfer-encoding", "connection",
               "keep-alive", "te", "upgrade"}


class Worker:
    def __init__(self, base: str):
        self.base = base.rstrip("/")
        self.inflight = 0
        self.failed_at = 0.0

    def online(self, cooldown_s: float = 10.0) -> bool:
        return (time.monotonic() - self.failed_at) > cooldown_s


class FederatedServer:
    """Reverse proxy with random / least-used worker selection."""

    def __init__(self, workers: list, strategy: str = "random",
                 timeout_s: float = 600.0):
        if not workers:
            raise ValueError("federation needs at least one worker")
        self.workers = [Worker(w) for w in workers]
        self.strategy = strategy
        self.timeout_s = timeout_s
        self._session = None   # shared, created lazily on the serving loop

    def _get_session(self):
        """One shared ClientSession (connection pool) for all proxied
        requests — a fresh session per request paid TCP(+TLS) setup on the
        hot path (r2 review). Lazy: must be created on the running loop."""
        if self._session is None or self._session.closed:
            self._session = ClientSession(
                timeout=ClientTimeout(total=self.timeout_s))
        return self._session

    def pick(self):
        candidates = [w for w in self.workers if w.online()] or self.workers
        if self.strategy in ("least_number_of_requests", "least_used"):
            return min(candidates, key=lambda w: w.inflight)
        return random.choice(candidates)

    async def proxy(self, request: web.Request) -> web.StreamResponse:
        """Stream one request through a worker. Failure attribution
        matters (ISSUE 17 satellite): only UPSTREAM faults — refused
        connect, timeout, a mid-stream read error — stamp ``failed_at``
        and bench the worker. A CLIENT that disconnects mid-stream (the
        common case for abandoned SSE token streams) must NOT count
        against the worker, and must still decrement ``inflight`` so
        least-used routing never sees phantom load."""
        worker = self.pick()
        url = f"{worker.base}{request.path_qs}"
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in HOP_HEADERS}
        body = await request.read()
        worker.inflight += 1
        try:
            session = self._get_session()
            try:
                upstream = await session.request(
                    request.method, url, data=body, headers=headers)
            except asyncio.CancelledError:
                raise            # client gone before connect: not a fault
            except Exception as e:
                # connect refused / DNS / timeout: the worker is at
                # fault, and nothing is on the wire yet — clean 502
                worker.failed_at = time.monotonic()
                log.warning("worker %s failed: %s", worker.base, e)
                raise web.HTTPBadGateway(
                    text=f"worker {worker.base} failed: {e}")
            try:
                resp = web.StreamResponse(status=upstream.status)
                for k, v in upstream.headers.items():
                    if k.lower() not in HOP_HEADERS:
                        resp.headers[k] = v
                await resp.prepare(request)
                # stream chunks through (SSE token streams stay live)
                while True:
                    try:
                        chunk = await upstream.content.readany()
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        # mid-stream UPSTREAM failure: worker at fault;
                        # headers already sent, so terminate the stream
                        # (a second response would corrupt the wire)
                        worker.failed_at = time.monotonic()
                        log.warning("worker %s failed mid-stream: %s",
                                    worker.base, e)
                        with contextlib.suppress(Exception):
                            await resp.write_eof()
                        return resp
                    if not chunk:
                        break
                    try:
                        await resp.write(chunk)
                    except asyncio.CancelledError:
                        raise
                    except (ConnectionError, RuntimeError) as e:
                        # CLIENT dropped mid-stream: the worker did
                        # nothing wrong — stays online, no failed_at
                        log.debug("client dropped mid-stream (%s); "
                                  "worker %s stays online", e, worker.base)
                        return resp
                upstream.release()   # fully drained: pool the connection
                await resp.write_eof()
                return resp
            finally:
                upstream.close()     # no-op after release(); otherwise
                                     # drops the half-read connection
        finally:
            # every exit — success, 502, upstream fault, client
            # disconnect, cancellation — releases the in-flight slot
            worker.inflight -= 1

    async def status(self, request: web.Request) -> web.Response:
        return web.json_response({
            "strategy": self.strategy,
            "workers": [{"base": w.base, "inflight": w.inflight,
                         "online": w.online()} for w in self.workers],
        })

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/federation/status", self.status)
        app.router.add_route("*", "/{path:.*}", self.proxy)

        async def _close_session(_app):
            if self._session is not None and not self._session.closed:
                await self._session.close()

        app.on_cleanup.append(_close_session)
        return app


async def serve(workers: list, address: str, strategy: str = "random"):
    from localai_tpu.api.app import run_app

    server = FederatedServer(workers, strategy)
    await run_app(server.build_app(), address)
    log.info("federated front listening on %s -> %d workers",
             address, len(workers))
    import asyncio

    while True:
        await asyncio.sleep(3600)
