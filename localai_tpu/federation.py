"""Federation: request-level load balancing across full serving instances.

Capability parity with the reference's federated server (reference:
core/p2p/federated_server.go:36-105 + federated.go:39-99 — a thin proxy
in front of N LocalAI instances choosing a worker per request, randomly
or by least in-flight load, skipping offline workers). The reference
discovers workers over its libp2p VPN; the TPU design replaces discovery
with an explicit worker list (pod addresses are static and declarative —
SURVEY §2.4: "front-door LB over N model servers / pods (DCN)").
"""

from __future__ import annotations

import logging
import random
import time

from aiohttp import ClientSession, ClientTimeout, web

log = logging.getLogger("localai_tpu.federation")

HOP_HEADERS = {"host", "content-length", "transfer-encoding", "connection",
               "keep-alive", "te", "upgrade"}


class Worker:
    def __init__(self, base: str):
        self.base = base.rstrip("/")
        self.inflight = 0
        self.failed_at = 0.0

    def online(self, cooldown_s: float = 10.0) -> bool:
        return (time.monotonic() - self.failed_at) > cooldown_s


class FederatedServer:
    """Reverse proxy with random / least-used worker selection."""

    def __init__(self, workers: list, strategy: str = "random",
                 timeout_s: float = 600.0):
        if not workers:
            raise ValueError("federation needs at least one worker")
        self.workers = [Worker(w) for w in workers]
        self.strategy = strategy
        self.timeout_s = timeout_s
        self._session = None   # shared, created lazily on the serving loop

    def _get_session(self):
        """One shared ClientSession (connection pool) for all proxied
        requests — a fresh session per request paid TCP(+TLS) setup on the
        hot path (r2 review). Lazy: must be created on the running loop."""
        if self._session is None or self._session.closed:
            self._session = ClientSession(
                timeout=ClientTimeout(total=self.timeout_s))
        return self._session

    def pick(self):
        candidates = [w for w in self.workers if w.online()] or self.workers
        if self.strategy in ("least_number_of_requests", "least_used"):
            return min(candidates, key=lambda w: w.inflight)
        return random.choice(candidates)

    async def proxy(self, request: web.Request) -> web.StreamResponse:
        worker = self.pick()
        url = f"{worker.base}{request.path_qs}"
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in HOP_HEADERS}
        body = await request.read()
        worker.inflight += 1
        resp = None
        try:
            session = self._get_session()
            async with session.request(request.method, url, data=body,
                                       headers=headers) as upstream:
                resp = web.StreamResponse(status=upstream.status)
                for k, v in upstream.headers.items():
                    if k.lower() not in HOP_HEADERS:
                        resp.headers[k] = v
                await resp.prepare(request)
                # stream chunks through (SSE token streams stay live)
                async for chunk in upstream.content.iter_any():
                    await resp.write(chunk)
                await resp.write_eof()
                return resp
        except Exception as e:
            worker.failed_at = time.monotonic()
            log.warning("worker %s failed: %s", worker.base, e)
            if resp is None or not resp.prepared:
                # nothing on the wire yet: a clean 502 is still possible
                raise web.HTTPBadGateway(
                    text=f"worker {worker.base} failed: {e}")
            # headers/partial body already sent: terminate the stream
            # instead of raising (a second response would corrupt the wire)
            import contextlib

            with contextlib.suppress(Exception):
                await resp.write_eof()
            return resp
        finally:
            worker.inflight -= 1

    async def status(self, request: web.Request) -> web.Response:
        return web.json_response({
            "strategy": self.strategy,
            "workers": [{"base": w.base, "inflight": w.inflight,
                         "online": w.online()} for w in self.workers],
        })

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/federation/status", self.status)
        app.router.add_route("*", "/{path:.*}", self.proxy)

        async def _close_session(_app):
            if self._session is not None and not self._session.closed:
                await self._session.close()

        app.on_cleanup.append(_close_session)
        return app


async def serve(workers: list, address: str, strategy: str = "random"):
    from localai_tpu.api.app import run_app

    server = FederatedServer(workers, strategy)
    await run_app(server.build_app(), address)
    log.info("federated front listening on %s -> %d workers",
             address, len(workers))
    import asyncio

    while True:
        await asyncio.sleep(3600)
