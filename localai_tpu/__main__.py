from localai_tpu.cli import main

raise SystemExit(main())
