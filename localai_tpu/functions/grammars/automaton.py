"""Pushdown grammar matcher + token-level logit-mask builder.

This is the piece the reference gets from llama.cpp's grammar sampling
(reference: backend/cpp/llama/grpc-server.cpp:688 grammar into slot
sampling params, common_sampler_sample at :1977): during decode, only
tokens whose text the grammar can accept from its current state are
allowed; everything else is masked to -inf before sampling.

TPU re-design: the grammar runs as a host-side pushdown automaton
(characters), while enforcement happens on-device via a per-slot additive
penalty row folded into the existing [S, V] bias matrix of the compiled
sampling step — so constrained decoding costs one masked-row upload per
token, not a host round-trip inside sampling.

Key structures:
  * state = frozenset of stacks; stack = tuple of frames (rule, alt, idx)
    with the TOP at the end. Stacks are expanded so every top frame points
    at a char element; an EMPTY stack in the set means the grammar can
    terminate here (EOS allowed).
  * TokenMaskBuilder walks a trie over the tokenizer's vocabulary strings
    while advancing the automaton, memoizing state -> vocab mask; typical
    JSON grammars revisit a handful of states so steady-state masking is a
    dict hit.
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

from localai_tpu.functions.grammars.gbnf import parse_gbnf


class Grammar:
    """Compiled grammar with memoized state transitions."""

    def __init__(self, rules, root_id: int):
        self.rules = rules
        self.root_id = root_id
        self._expand_memo: dict = {}

    @staticmethod
    def from_text(text: str) -> "Grammar":
        rules, root = parse_gbnf(text)
        return Grammar(rules, root)

    # -- state machinery --

    def initial_state(self) -> frozenset:
        out: set = set()
        for alt_id in range(len(self.rules[self.root_id])):
            out |= self._expand(((self.root_id, alt_id, 0),))
        return frozenset(out)

    def _expand(self, stack: tuple) -> set:
        """Expand until the top frame is a char element (or stack empty)."""
        memo = self._expand_memo.get(stack)
        if memo is not None:
            return memo
        self._expand_memo[stack] = set()  # cycle guard (left recursion)
        result: set = set()
        if not stack:
            result.add(stack)
        else:
            r, a, i = stack[-1]
            alt = self.rules[r][a]
            if i >= len(alt):
                result |= self._expand(stack[:-1])
            else:
                elem = alt[i]
                if elem[0] == "c":
                    result.add(stack)
                else:  # rule ref
                    rid = elem[1]
                    cont = stack[:-1] + ((r, a, i + 1),)
                    for alt_id in range(len(self.rules[rid])):
                        result |= self._expand(cont + ((rid, alt_id, 0),))
        self._expand_memo[stack] = result
        return result

    @staticmethod
    def _char_matches(elem, cp: int) -> bool:
        _, ranges, negated = elem
        hit = any(lo <= cp <= hi for lo, hi in ranges)
        return hit != negated

    def advance_char(self, state: frozenset, ch: str) -> Optional[frozenset]:
        """One character; None if the grammar rejects it."""
        cp = ord(ch)
        out: set = set()
        for stack in state:
            if not stack:
                continue  # completed grammar accepts no more chars
            r, a, i = stack[-1]
            elem = self.rules[r][a][i]
            if self._char_matches(elem, cp):
                out |= self._expand(stack[:-1] + ((r, a, i + 1),))
        return frozenset(out) if out else None

    def advance_string(self, state: frozenset, s: str) -> Optional[frozenset]:
        for ch in s:
            state = self.advance_char(state, ch)
            if state is None:
                return None
        return state

    @staticmethod
    def is_accepting(state: frozenset) -> bool:
        return () in state

    def accepts(self, text: str) -> bool:
        """Whole-string acceptance (test/debug helper)."""
        st = self.advance_string(self.initial_state(), text)
        return st is not None and self.is_accepting(st)


class GrammarMatcher:
    """Per-request wrapper: grammar + current state."""

    def __init__(self, grammar: Grammar):
        self.grammar = grammar
        self.state = grammar.initial_state()

    def accept(self, s: str) -> bool:
        nxt = self.grammar.advance_string(self.state, s)
        if nxt is None:
            return False
        self.state = nxt
        return True

    @property
    def accepting(self) -> bool:
        return Grammar.is_accepting(self.state)


class _TrieNode:
    __slots__ = ("children", "token_ids")

    def __init__(self):
        self.children: dict = {}
        self.token_ids: list = []


def token_strings(tokenizer) -> list:
    """Per-token surface strings; None for tokens that must never be emitted
    under a grammar (specials). Index = token id."""
    specials = set(getattr(tokenizer, "all_special_ids", None) or [])
    if hasattr(tokenizer, "get_vocab"):
        vocab = tokenizer.get_vocab()
        size = max(vocab.values()) + 1
        out: list = [None] * size
        for tok, tid in vocab.items():
            if tid in specials:
                continue
            try:
                s = tokenizer.convert_tokens_to_string([tok])
            except Exception:
                s = None
            out[tid] = s if s else None
        return out
    # minimal tokenizers (tests): decode each id individually
    size = tokenizer.get_vocab_size()
    out = []
    for tid in range(size):
        if tid in specials:
            out.append(None)
            continue
        try:
            s = tokenizer.decode([tid])
        except Exception:
            s = None
        out.append(s if s else None)
    return out


class TokenMaskBuilder:
    """vocab trie + (grammar state -> allowed-token mask) memo."""

    def __init__(self, token_strs: list, eos_ids: Iterable[int], vocab_size: int):
        self.vocab_size = vocab_size
        self.eos_ids = [e for e in eos_ids if 0 <= e < vocab_size]
        self.root = _TrieNode()
        for tid, s in enumerate(token_strs[:vocab_size]):
            if not s:
                continue
            node = self.root
            for ch in s:
                nxt = node.children.get(ch)
                if nxt is None:
                    nxt = node.children[ch] = _TrieNode()
                node = nxt
            node.token_ids.append(tid)
        self._memo: dict = {}
        self._penalty_memo: dict = {}

    MAX_MEMO = 8192

    def allowed(self, grammar: Grammar, state: frozenset) -> np.ndarray:
        """Bool [V]: True where the token may be sampled from this state.

        Memoized per (grammar, state); the grammar object itself is the key
        (a strong ref — id() reuse after GC must not alias masks), with a
        size cap so a server seeing many distinct tool schemas cannot grow
        the memo unboundedly."""
        key = (grammar, state)
        cached = self._memo.get(key)
        if cached is not None:
            return cached
        if len(self._memo) >= self.MAX_MEMO:
            self._memo.clear()
            self._penalty_memo.clear()
        mask = np.zeros((self.vocab_size,), np.bool_)

        def visit(node: _TrieNode, st: frozenset):
            for tid in node.token_ids:
                mask[tid] = True
            for ch, child in node.children.items():
                nxt = grammar.advance_char(st, ch)
                if nxt is not None:
                    visit(child, nxt)

        visit(self.root, state)
        if Grammar.is_accepting(state) or not mask.any():
            # EOS when the grammar can terminate — or as a pressure valve
            # when the grammar is stuck (mirrors llama.cpp resetting to EOS
            # rather than sampling garbage)
            for e in self.eos_ids:
                mask[e] = True
        self._memo[key] = mask
        return mask

    def penalty_row(self, grammar: Grammar, state: frozenset) -> np.ndarray:
        """f32 [V] additive row: 0 where allowed, -1e9 where masked. Memoized
        alongside the mask so the decode hot path is a dict hit."""
        key = (grammar, state)
        row = self._penalty_memo.get(key)
        if row is None:
            allowed = self.allowed(grammar, state)
            row = np.where(allowed, 0.0, -1e9).astype(np.float32)
            self._penalty_memo[key] = row
        return row
