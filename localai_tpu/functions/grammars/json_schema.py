"""JSON-schema -> GBNF grammar for constrained decoding.

Parity with the reference's grammar compiler (reference: pkg/functions/
grammars/json_schema.go JSONSchemaConverter + bnf.go primitives), written
fresh: a recursive schema walker emitting llama.cpp-style GBNF. The engine
consumes this via the grammar automaton (functions/grammars/automaton.py)
to mask logits during sampling.
"""

from __future__ import annotations

import json
import re
from typing import Optional

SPACE_RULE = '" "?'

PRIMITIVES = {
    "boolean": '("true" | "false") space',
    "number": '("-"? ([0-9] | [1-9] [0-9]*)) ("." [0-9]+)? ([eE] [-+]? [0-9]+)? space',
    "integer": '("-"? ([0-9] | [1-9] [0-9]*)) space',
    "string": r'"\"" ( [^"\\] | "\\" (["\\/bfnrt] | "u" [0-9a-fA-F] [0-9a-fA-F] [0-9a-fA-F] [0-9a-fA-F]) )* "\"" space',
    "null": '"null" space',
}

_INVALID_RULE_CHARS = re.compile(r"[^a-zA-Z0-9-]+")


class JSONSchemaConverter:
    def __init__(self):
        self.rules: dict[str, str] = {"space": SPACE_RULE}

    def _add_rule(self, name: str, rule: str) -> str:
        esc = _INVALID_RULE_CHARS.sub("-", name) or "rule"
        key = esc
        i = 0
        while key in self.rules and self.rules[key] != rule:
            i += 1
            key = f"{esc}{i}"
        self.rules[key] = rule
        return key

    def _format_literal(self, literal) -> str:
        s = json.dumps(literal)
        escaped = s.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{escaped}"'

    def visit(self, schema: dict, name: str = "root") -> str:
        stype = schema.get("type")
        if "oneOf" in schema or "anyOf" in schema:
            alts = schema.get("oneOf") or schema.get("anyOf")
            rule = " | ".join(self.visit(a, f"{name}-{i}") for i, a in enumerate(alts))
            return self._add_rule(name, rule)
        if "const" in schema:
            return self._add_rule(name, self._format_literal(schema["const"]) + " space")
        if "enum" in schema:
            rule = " | ".join(self._format_literal(v) for v in schema["enum"])
            return self._add_rule(name, f"({rule}) space")
        if stype == "object" or "properties" in schema:
            props = schema.get("properties", {})
            required = schema.get("required", list(props.keys()))
            req_pieces, opt_pieces = [], []
            for key, sub in props.items():
                sub_name = self.visit(sub, f"{name}-{key}")
                piece = f'{self._format_literal(key)} space ":" space {sub_name}'
                (req_pieces if key in required else opt_pieces).append(piece)
            body = ' "," space '.join(req_pieces)
            if opt_pieces:
                # any subset of optionals, in order, comma-separated: chain
                # of rest-rules so separators are always correct
                rest = None
                for i in range(len(opt_pieces) - 1, -1, -1):
                    rule = opt_pieces[i]
                    if rest is not None:
                        rule = f'{opt_pieces[i]} ("," space {rest})? | {rest}'
                    rest = self._add_rule(f"{name}-opt{i}", rule)
                if body:
                    body += f' ("," space {rest})?'
                else:
                    body = f"({rest})?"
            return self._add_rule(name, f'"{{" space {body} "}}" space'
                                  if body else '"{" space "}" space')
        if stype == "array" or "items" in schema:
            item = self.visit(schema.get("items", {}), f"{name}-item")
            rule = f'"[" space ({item} ("," space {item})*)? "]" space'
            return self._add_rule(name, rule)
        if stype in PRIMITIVES:
            return self._add_rule(stype, PRIMITIVES[stype])
        # untyped: any JSON value
        self._ensure_value_rule()
        return "value"

    def _ensure_value_rule(self):
        if "value" in self.rules:
            return
        self.rules["string"] = PRIMITIVES["string"]
        self.rules["number"] = PRIMITIVES["number"]
        self.rules["boolean"] = PRIMITIVES["boolean"]
        self.rules["null"] = PRIMITIVES["null"]
        self.rules["value"] = ("object | array | string | number | boolean | null")
        self.rules["object"] = (
            '"{" space (string ":" space value ("," space string ":" space value)*)? "}" space'
        )
        self.rules["array"] = '"[" space (value ("," space value)*)? "]" space'

    def format_grammar(self, root_rule: str = "root") -> str:
        lines = []
        if root_rule != "root":
            lines.append(f"root ::= {root_rule}")
        for name, rule in self.rules.items():
            lines.append(f"{name} ::= {rule}")
        return "\n".join(lines)


def schema_to_grammar(schema: dict) -> str:
    conv = JSONSchemaConverter()
    root = conv.visit(schema, "root")
    return conv.format_grammar(root)


def grammar_for_functions(functions: list,
                          force_name: Optional[str] = None,
                          parallel_calls: bool = False,
                          name_key: str = "name",
                          arguments_key: str = "arguments") -> str:
    """OpenAI tools -> grammar constraining output to function-call JSON
    (reference: functionsToJSONSchema + grammar options, parse.go:92-150).

    ``force_name`` narrows the grammar to one named tool (OpenAI
    tool_choice={"type":"function","function":{"name":...}} semantics).
    """
    if force_name:
        functions = [f for f in functions if f.get("name") == force_name]
    alts = []
    for fn in functions:
        alts.append({
            "type": "object",
            "properties": {
                name_key: {"const": fn["name"]},
                arguments_key: fn.get("parameters", {"type": "object"}),
            },
            "required": [name_key, arguments_key],
        })
    if not alts:
        return ""
    one_call: dict = {"oneOf": alts} if len(alts) > 1 else alts[0]
    schema = {
        "type": "array", "items": one_call, "minItems": 1,
    } if parallel_calls else one_call
    return schema_to_grammar(schema)
