"""ctypes bindings for the native grammar runtime (runtime/grammar.cc).

The C++ automaton is the production path for constrained decoding at real
vocab sizes (a cold [32k]-vocab mask walk in pure Python costs hundreds
of ms; the C++ walk is ~ms) — role parity with llama.cpp's in-C++ grammar
sampler (reference: grpc-server.cpp:688,1977). The shared library is
compiled on demand with g++ into a user cache dir and loaded via ctypes
(no pybind11 in this environment); automaton.py remains the semantic
reference and the fallback when no compiler is available.

Interface parity with automaton.py: NativeGrammar states are opaque ints
(instead of frozensets) and NativeMaskBuilder.penalty_row memoizes rows
per state so the engine's identity-compare fast path keeps working.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import struct
import subprocess
import threading
from typing import Iterable, Optional

import numpy as np

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "..", "runtime",
                    "grammar.cc")
_lib = None
_lib_lock = threading.Lock()
_lib_failed = False


def _build_and_load():
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        raise FileNotFoundError(src)
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache = os.environ.get("LOCALAI_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "localai_tpu", "native")
    os.makedirs(cache, exist_ok=True)
    so = os.path.join(cache, f"libgrammar-{digest}.so")
    if not os.path.exists(so):
        tmp = so + ".tmp"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp],
            check=True, capture_output=True)
        os.replace(tmp, so)
        log.info("built native grammar runtime: %s", so)
    lib = ctypes.CDLL(so)
    lib.ga_grammar_new.restype = ctypes.c_void_p
    lib.ga_grammar_new.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.ga_grammar_free.argtypes = [ctypes.c_void_p]
    lib.ga_initial.restype = ctypes.c_int
    lib.ga_initial.argtypes = [ctypes.c_void_p]
    lib.ga_advance.restype = ctypes.c_int
    lib.ga_advance.argtypes = [ctypes.c_void_p, ctypes.c_int,
                               ctypes.c_char_p, ctypes.c_size_t]
    lib.ga_accepting.restype = ctypes.c_int
    lib.ga_accepting.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ga_mask_builder_new.restype = ctypes.c_void_p
    lib.ga_mask_builder_new.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_size_t, ctypes.c_int32]
    lib.ga_mask_builder_free.argtypes = [ctypes.c_void_p]
    lib.ga_penalty_row.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_float)]
    return lib


def get_lib():
    """The loaded native library, or None (no compiler / disabled)."""
    global _lib, _lib_failed
    if os.environ.get("LOCALAI_NATIVE_GRAMMAR", "1") == "0":
        return None
    with _lib_lock:
        if _lib is None and not _lib_failed:
            try:
                _lib = _build_and_load()
            except Exception:
                _lib_failed = True
                log.warning("native grammar runtime unavailable; using the "
                            "python automaton", exc_info=True)
        return _lib


def serialize_rules(rules, root_id: int) -> bytes:
    """Pack parse_gbnf output into the grammar.cc binary layout."""
    out = [struct.pack("<II", len(rules), root_id)]
    for rule in rules:
        out.append(struct.pack("<I", len(rule)))
        for alt in rule:
            out.append(struct.pack("<I", len(alt)))
            for elem in alt:
                if elem[0] == "c":
                    _, ranges, negated = elem
                    out.append(struct.pack("<BBI", 0, 1 if negated else 0,
                                           len(ranges)))
                    for lo, hi in ranges:
                        out.append(struct.pack("<II", lo, hi))
                else:
                    out.append(struct.pack("<BI", 1, elem[1]))
    return b"".join(out)


class NativeGrammar:
    """Opaque-state counterpart of automaton.Grammar."""

    def __init__(self, rules, root_id: int, lib):
        self._lib = lib
        blob = serialize_rules(rules, root_id)
        self._handle = lib.ga_grammar_new(blob, len(blob))

    @staticmethod
    def from_text(text: str) -> "NativeGrammar":
        from localai_tpu.functions.grammars.gbnf import parse_gbnf

        lib = get_lib()
        if lib is None:
            raise RuntimeError("native grammar runtime unavailable")
        rules, root = parse_gbnf(text)
        return NativeGrammar(rules, root, lib)

    def __del__(self):
        lib, handle = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and handle:
            lib.ga_grammar_free(handle)

    def initial_state(self) -> int:
        return self._lib.ga_initial(self._handle)

    def advance_string(self, state: int, s: str) -> Optional[int]:
        b = s.encode("utf-8")
        nxt = self._lib.ga_advance(self._handle, state, b, len(b))
        return None if nxt < 0 else nxt

    def is_accepting(self, state: int) -> bool:
        return bool(self._lib.ga_accepting(self._handle, state))

    def accepts(self, text: str) -> bool:
        st = self.advance_string(self.initial_state(), text)
        return st is not None and self.is_accepting(st)


class NativeMaskBuilder:
    """Counterpart of automaton.TokenMaskBuilder over the native trie."""

    def __init__(self, token_strs: list, eos_ids: Iterable[int], vocab_size: int):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native grammar runtime unavailable")
        self._lib = lib
        self.vocab_size = vocab_size
        parts = []
        for tid, s in enumerate(token_strs[:vocab_size]):
            if not s:
                continue
            b = s.encode("utf-8")
            parts.append(struct.pack("<ii", tid, len(b)) + b)
        blob = b"".join(parts)
        eos = [e for e in eos_ids if 0 <= e < vocab_size]
        arr = (ctypes.c_int32 * len(eos))(*eos)
        self._handle = lib.ga_mask_builder_new(blob, len(blob), arr, len(eos),
                                               vocab_size)
        self._penalty_memo: dict = {}

    def __del__(self):
        lib, handle = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and handle:
            lib.ga_mask_builder_free(handle)

    MAX_MEMO = 8192

    def penalty_row(self, grammar: NativeGrammar, state: int) -> np.ndarray:
        key = (grammar, state)
        row = self._penalty_memo.get(key)
        if row is None:
            if len(self._penalty_memo) >= self.MAX_MEMO:
                self._penalty_memo.clear()
            row = np.empty((self.vocab_size,), np.float32)
            self._lib.ga_penalty_row(
                self._handle, grammar._handle, state,
                row.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            self._penalty_memo[key] = row
        return row
