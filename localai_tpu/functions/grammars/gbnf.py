"""GBNF grammar text -> compiled rule table.

Parses the llama.cpp GBNF dialect that our JSON-schema compiler emits
(and that users hand-write in model configs): rule definitions
``name ::= body`` with literals, char classes, groups, alternation and
postfix repetition operators.

Semantics parity target: llama.cpp's grammar-parser (driven by the
reference at backend/cpp/llama/grpc-server.cpp:688 where the grammar
string enters slot sampling params). The implementation is original:
postfix operators are expanded into auxiliary recursive rules, and the
compiled form is a tuple-of-tuples rule table consumed by
functions/grammars/automaton.py.

Compiled form:
  rules: list indexed by rule id; rules[r] = tuple of alternates;
  alternate = tuple of elements; element =
    ("c", ranges, negated)  -- char set; ranges = ((lo, hi), ...) codepoints
    ("r", rule_id)          -- rule reference
"""

from __future__ import annotations

import re
from typing import Optional

_RULE_DEF = re.compile(r"^([a-zA-Z][a-zA-Z0-9_-]*)\s*::=\s*(.*)$")

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\",
            "[": "[", "]": "]", "/": "/", "b": "\b", "f": "\f",
            "'": "'", "-": "-", "^": "^"}


class GrammarError(ValueError):
    pass


def _strip_comments(text: str) -> str:
    out = []
    for line in text.splitlines():
        # '#' starts a comment unless inside a literal/class — a cheap scan
        res, in_str, in_cls, esc = [], False, False, False
        for ch in line:
            if esc:
                res.append(ch)
                esc = False
                continue
            if ch == "\\":
                res.append(ch)
                esc = True
                continue
            if ch == '"' and not in_cls:
                in_str = not in_str
            elif ch == "[" and not in_str:
                in_cls = True
            elif ch == "]" and not in_str:
                in_cls = False
            elif ch == "#" and not in_str and not in_cls:
                break
            res.append(ch)
        out.append("".join(res))
    return "\n".join(out)


def _join_rule_lines(text: str) -> list:
    """Group physical lines into one logical line per rule definition."""
    logical: list[str] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if _RULE_DEF.match(line.strip()):
            logical.append(line.strip())
        elif logical:
            logical[-1] += " " + line.strip()
        else:
            raise GrammarError(f"grammar text before first rule: {line!r}")
    return logical


class _Parser:
    """Recursive-descent parser for one rule body."""

    def __init__(self, body: str, rule_name: str, aux_rules: dict):
        self.s = body
        self.i = 0
        self.rule_name = rule_name
        self.aux_rules = aux_rules  # name -> list of alternates (shared)
        self.n_aux = 0

    # -- low-level --

    def _ws(self):
        while self.i < len(self.s) and self.s[self.i] in " \t\n":
            self.i += 1

    def _peek(self) -> str:
        return self.s[self.i] if self.i < len(self.s) else ""

    def _take(self) -> str:
        ch = self._peek()
        self.i += 1
        return ch

    def _escape(self) -> str:
        ch = self._take()
        if ch == "x":
            code = self.s[self.i:self.i + 2]
            self.i += 2
            return chr(int(code, 16))
        if ch == "u":
            code = self.s[self.i:self.i + 4]
            self.i += 4
            return chr(int(code, 16))
        if ch == "U":
            code = self.s[self.i:self.i + 8]
            self.i += 8
            return chr(int(code, 16))
        if ch in _ESCAPES:
            return _ESCAPES[ch]
        raise GrammarError(f"bad escape \\{ch} in rule {self.rule_name}")

    # -- aux rule helpers --

    def _new_aux(self, alternates: list) -> str:
        name = f"{self.rule_name}${self.n_aux}"
        self.n_aux += 1
        self.aux_rules[name] = alternates
        return name

    # -- grammar pieces --

    def parse_alternates(self, in_group: bool = False) -> list:
        alts = [self.parse_sequence(in_group)]
        self._ws()
        while self._peek() == "|":
            self._take()
            alts.append(self.parse_sequence(in_group))
            self._ws()
        return alts

    def parse_sequence(self, in_group: bool) -> list:
        elems: list = []
        sym_start = 0  # start index in elems of the last parsed symbol
        while True:
            self._ws()
            ch = self._peek()
            if not ch or ch == "|" or (in_group and ch == ")"):
                return elems
            sym_start = len(elems)
            if ch == '"':
                self._take()
                while self._peek() != '"':
                    if not self._peek():
                        raise GrammarError(f"unterminated literal in {self.rule_name}")
                    c = self._take()
                    if c == "\\":
                        c = self._escape()
                    elems.append(("c", ((ord(c), ord(c)),), False))
                self._take()
            elif ch == "[":
                elems.append(self._parse_class())
            elif ch == "(":
                self._take()
                inner = self.parse_alternates(in_group=True)
                self._ws()
                if self._take() != ")":
                    raise GrammarError(f"missing ')' in {self.rule_name}")
                name = self._new_aux(inner)
                elems.append(("ref", name))
            elif ch.isalnum() or ch == "_":
                name = self._parse_name()
                elems.append(("ref", name))
            else:
                raise GrammarError(
                    f"unexpected {ch!r} at {self.i} in rule {self.rule_name}")
            # postfix operators apply to the whole preceding symbol
            self._ws()
            op = self._peek()
            if op and op in "*+?":
                self._take()
                elems = self._apply_repeat(elems, sym_start, op)
            elif op == "{":
                self._take()
                spec = ""
                while self._peek() != "}":
                    if not self._peek():
                        raise GrammarError(f"unterminated {{...}} in {self.rule_name}")
                    spec += self._take()
                self._take()
                elems = self._apply_braces(elems, sym_start, spec)

    def _parse_name(self) -> str:
        start = self.i
        while True:
            ch = self._peek()
            if not ch or not (ch.isalnum() or ch in "_-$"):
                break
            self.i += 1
        return self.s[start:self.i]

    def _parse_class(self):
        self._take()  # '['
        negated = self._peek() == "^"
        if negated:
            self._take()
        ranges = []
        while self._peek() != "]":
            if not self._peek():
                raise GrammarError(f"unterminated char class in {self.rule_name}")
            c = self._take()
            if c == "\\":
                c = self._escape()
            lo = ord(c)
            hi = lo
            if self._peek() == "-" and self.s[self.i + 1:self.i + 2] != "]":
                self._take()
                c2 = self._take()
                if c2 == "\\":
                    c2 = self._escape()
                hi = ord(c2)
            ranges.append((lo, hi))
        self._take()
        return ("c", tuple(ranges), negated)

    def _apply_repeat(self, elems: list, sym_start: int, op: str) -> list:
        symbol = elems[sym_start:]
        name = f"{self.rule_name}${self.n_aux}"
        self.n_aux += 1
        ref = ("ref", name)
        if op == "*":
            self.aux_rules[name] = [symbol + [ref], []]
        elif op == "+":
            self.aux_rules[name] = [symbol + [ref], list(symbol)]
        else:  # '?'
            self.aux_rules[name] = [list(symbol), []]
        return elems[:sym_start] + [ref]

    def _apply_braces(self, elems: list, sym_start: int, spec: str) -> list:
        symbol = elems[sym_start:]
        parts = spec.split(",")
        try:
            m = int(parts[0]) if parts[0].strip() else 0
            if len(parts) == 1:
                n: Optional[int] = m
            else:
                n = int(parts[1]) if parts[1].strip() else None
        except ValueError:
            raise GrammarError(f"bad repetition {{{spec}}} in {self.rule_name}")
        out = elems[:sym_start]
        for _ in range(m):
            out += symbol
        if n is None:  # {m,} -> star tail
            out = self._apply_repeat(out + symbol, len(out), "*")
        else:
            for _ in range(n - m):
                out = self._apply_repeat(out + symbol, len(out), "?")
        return out


def parse_gbnf(text: str) -> tuple:
    """Parse GBNF text. Returns (rules, root_id); see module docstring."""
    named: dict[str, list] = {}
    aux: dict[str, list] = {}
    for logical in _join_rule_lines(_strip_comments(text)):
        m = _RULE_DEF.match(logical)
        if not m:
            raise GrammarError(f"not a rule definition: {logical!r}")
        name, body = m.group(1), m.group(2)
        p = _Parser(body, name, aux)
        alts = p.parse_alternates()
        p._ws()
        if p.i != len(p.s):
            raise GrammarError(f"trailing junk in rule {name}: {p.s[p.i:]!r}")
        if name in named:
            raise GrammarError(f"duplicate rule {name}")
        named[name] = alts
    named.update(aux)
    if "root" not in named:
        raise GrammarError("grammar has no 'root' rule")

    ids = {name: i for i, name in enumerate(named)}
    rules = []
    for name, alts in named.items():
        compiled_alts = []
        for alt in alts:
            compiled = []
            for elem in alt:
                if elem[0] == "ref":
                    target = elem[1]
                    if target not in ids:
                        raise GrammarError(f"undefined rule {target!r} (used in {name})")
                    compiled.append(("r", ids[target]))
                else:
                    compiled.append(elem)
            compiled_alts.append(tuple(compiled))
        rules.append(tuple(compiled_alts))
    return rules, ids["root"]
