"""Parse function/tool calls out of model output.

Parity with the reference's response parsing (reference: pkg/functions/
parse.go ParseFunctionCall :150+ — JSON regex match, response regex with
named groups, replace rules, multiple-call arrays, llama3.1 <function=...>
style via grammars/llama31_schema.go).
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Optional

from localai_tpu.config.model_config import FunctionsConfig


@dataclasses.dataclass
class FuncCall:
    name: str
    arguments: str  # JSON string (OpenAI wire format)


_LLAMA31 = re.compile(r"<function=(\w+)>(.*?)</function>", re.DOTALL)


def _try_json(text: str) -> Optional[object]:
    text = text.strip()
    # strip common markdown fences
    if text.startswith("```"):
        text = re.sub(r"^```[a-zA-Z]*\n?", "", text)
        text = re.sub(r"\n?```$", "", text)
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        return None


def _find_json_objects(text: str) -> list:
    """Scan for balanced top-level {...} or [...] spans."""
    out = []
    depth = 0
    start = None
    in_str = False
    esc = False
    for i, ch in enumerate(text):
        if esc:
            esc = False
            continue
        if ch == "\\" and in_str:
            esc = True
            continue
        if ch == '"':
            in_str = not in_str
            continue
        if in_str:
            continue
        if ch in "{[":
            if depth == 0:
                start = i
            depth += 1
        elif ch in "}]":
            depth -= 1
            if depth == 0 and start is not None:
                obj = _try_json(text[start : i + 1])
                if obj is not None:
                    out.append(obj)
                start = None
            depth = max(depth, 0)
    return out


def _to_calls(obj, cfg: FunctionsConfig) -> list:
    name_key = cfg.function_name_key or "name"
    args_key = cfg.function_arguments_key or "arguments"
    items = obj if isinstance(obj, list) else [obj]
    calls = []
    for it in items:
        if not isinstance(it, dict) or name_key not in it:
            continue
        args = it.get(args_key, {})
        if not isinstance(args, str):
            args = json.dumps(args)
        calls.append(FuncCall(name=str(it[name_key]), arguments=args))
    return calls


def parse_function_calls(text: str, cfg: Optional[FunctionsConfig] = None) -> list:
    cfg = cfg or FunctionsConfig()

    for pattern, repl in _pairs(cfg.replace_llm_results):
        text = re.sub(pattern, repl, text)

    # llama3.1-style <function=name>{args}</function>
    m31 = _LLAMA31.findall(text)
    if m31:
        return [FuncCall(name=n, arguments=a.strip() or "{}") for n, a in m31]

    # response_regex with named groups (reference: parse.go responseRegex)
    for pattern in cfg.response_regex:
        m = re.search(pattern, text, re.DOTALL)
        if m:
            groups = m.groupdict()
            if "name" in groups:
                args = groups.get("arguments", "{}")
                return [FuncCall(name=groups["name"], arguments=args)]

    # json_regex_match: extract the JSON payload first
    candidates = []
    for pattern in cfg.json_regex_match:
        m = re.search(pattern, text, re.DOTALL)
        if m:
            candidates.append(m.group(1) if m.groups() else m.group(0))
    if not candidates:
        candidates = [text]

    for cand in candidates:
        obj = _try_json(cand)
        if obj is None:
            objs = _find_json_objects(cand)
        else:
            objs = [obj]
        for o in objs:
            calls = _to_calls(o, cfg)
            if calls:
                for c in calls:
                    for pattern, repl in _pairs(cfg.replace_function_results):
                        c.arguments = re.sub(pattern, repl, c.arguments)
                if cfg.disable_no_action:
                    calls = [c for c in calls if c.name != cfg.no_action_function_name]
                return calls
    return []


def _pairs(rules: list) -> list:
    out = []
    for r in rules:
        if isinstance(r, dict):
            out.append((r.get("key", r.get("pattern", "")), r.get("value", r.get("replace", ""))))
        elif isinstance(r, (list, tuple)) and len(r) == 2:
            out.append((r[0], r[1]))
    return out


def text_content(text: str, cfg: Optional[FunctionsConfig] = None) -> str:
    """Non-call text when using mixed text+JSON mode (reference:
    ParseTextFromResults + capture_llm_results)."""
    cfg = cfg or FunctionsConfig()
    for pattern in cfg.capture_llm_results:
        m = re.search(pattern, text, re.DOTALL)
        if m:
            return m.group(1) if m.groups() else m.group(0)
    return text
