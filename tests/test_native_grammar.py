"""Native grammar runtime (runtime/grammar.cc) vs the python automaton.

The python automaton is the semantic reference; the C++ runtime must be
bit-identical on states, acceptance, and vocab masks.
"""

import json

import numpy as np
import pytest

from localai_tpu.functions.grammars import native
from localai_tpu.functions.grammars.automaton import (
    Grammar, TokenMaskBuilder, token_strings)
from localai_tpu.functions.grammars.json_schema import schema_to_grammar

pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="no native grammar runtime (g++?)")


def _json_grammar():
    return schema_to_grammar({"type": "object", "properties": {
        "name": {"type": "string"}, "count": {"type": "integer"}},
        "required": ["name"]})


class _ByteTok:
    def __init__(self):
        self.all_special_ids = [256]

    def get_vocab_size(self):
        return 257

    def decode(self, ids, **kw):
        return bytes(i for i in ids if i < 256).decode("latin1")


def test_acceptance_equivalence():
    text = _json_grammar()
    py = Grammar.from_text(text)
    nat = native.NativeGrammar.from_text(text)
    cases = [
        ('{"name": "x"}', True),
        ('{"name": "x", "count": 42}', True),
        ('{"count": 1}', False),          # name required first
        ('{"name": 5}', False),
        ('{"name": "x"', False),
        ("[]", False),
    ]
    for s, _ in cases:
        assert py.accepts(s) == nat.accepts(s), s
    # spot-check expected values too
    assert nat.accepts('{"name": "ok"}')
    assert not nat.accepts("nope")


def test_incremental_advance_equivalence():
    text = _json_grammar()
    py = Grammar.from_text(text)
    nat = native.NativeGrammar.from_text(text)
    ps, ns = py.initial_state(), nat.initial_state()
    for piece in ['{"', "name", '": ', '"ab', 'c"', "}"]:
        ps = py.advance_string(ps, piece)
        ns = nat.advance_string(ns, piece)
        assert (ps is None) == (ns is None), piece
    assert py.is_accepting(ps) and nat.is_accepting(ns)
    # rejection agrees
    assert py.advance_string(py.initial_state(), "x") is None
    assert nat.advance_string(nat.initial_state(), "x") is None


def test_mask_rows_identical():
    text = _json_grammar()
    tok = _ByteTok()
    strs = token_strings(tok)
    py_b = TokenMaskBuilder(strs, [256], 257)
    na_b = native.NativeMaskBuilder(strs, [256], 257)
    py_g = Grammar.from_text(text)
    na_g = native.NativeGrammar.from_text(text)

    ps, ns = py_g.initial_state(), na_g.initial_state()
    for step in range(24):
        pr = py_b.penalty_row(py_g, ps)
        nr = na_b.penalty_row(na_g, ns)
        assert np.array_equal(pr, nr), f"row mismatch at step {step}"
        # identity memoization (engine fast path)
        assert na_b.penalty_row(na_g, ns) is nr
        # walk the first allowed byte forward in both automata
        allowed = np.nonzero(pr == 0.0)[0]
        if len(allowed) == 0 or allowed[0] == 256:
            break
        ch = chr(int(allowed[0]))
        ps = py_g.advance_string(ps, ch)
        ns = na_g.advance_string(ns, ch)
        assert (ps is None) == (ns is None)
        if ps is None:
            break
