"""Stable-Diffusion-class stack: CLIP text parity vs torch transformers,
diffusers-layout UNet/VAE structural load, end-to-end txt2img."""

import numpy as np
import pytest

from localai_tpu.models import sd


def test_clip_text_parity_vs_transformers():
    torch = pytest.importorskip("torch")
    from transformers import CLIPTextConfig, CLIPTextModel

    torch.manual_seed(0)
    tcfg = CLIPTextConfig(
        vocab_size=99, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, hidden_act="quick_gelu")
    model = CLIPTextModel(tcfg).eval()

    import jax.numpy as jnp

    params = {k: jnp.asarray(v.detach().numpy())
              for k, v in model.state_dict().items()}
    jcfg = sd.ClipTextConfig(
        vocab_size=99, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, hidden_act="quick_gelu")

    ids = np.array([[5, 9, 2, 77, 31, 8, 1, 0]], np.int64)
    with torch.no_grad():
        want = model(input_ids=torch.tensor(ids)).last_hidden_state.numpy()
    got = np.asarray(sd.clip_text_encode(params, jcfg, ids))
    np.testing.assert_allclose(got, want, atol=3e-5)


def _tiny_cfgs():
    clip = sd.ClipTextConfig(vocab_size=64, hidden_size=16,
                             intermediate_size=32, num_hidden_layers=1,
                             num_attention_heads=2, max_position_embeddings=8)
    unet = sd.UNetConfig(
        block_out_channels=(16, 32), layers_per_block=1,
        cross_attention_dim=16, attention_head_dim=2,
        down_block_types=("CrossAttnDownBlock2D", "DownBlock2D"),
        up_block_types=("UpBlock2D", "CrossAttnUpBlock2D"),
        norm_num_groups=8)
    vae = sd.VaeConfig(block_out_channels=(16, 32), layers_per_block=1,
                       norm_num_groups=8)
    return clip, unet, vae


def test_unet_and_vae_shapes():
    import jax.numpy as jnp

    _, ucfg, vcfg = _tiny_cfgs()
    up = sd.init_unet_params(ucfg)
    lat = jnp.zeros((2, 4, 8, 8))
    ctx = jnp.zeros((2, 8, ucfg.cross_attention_dim))
    out = sd.unet_forward(up, ucfg, lat, jnp.array([500, 10]), ctx)
    assert out.shape == (2, 4, 8, 8)

    vp = sd.init_vae_params(vcfg)
    img = sd.vae_decode(vp, vcfg, jnp.zeros((1, 4, 8, 8)))
    assert img.shape == (1, 3, 16, 16)  # 2 blocks -> one 2x upsample
    enc = sd.vae_encode(vp, vcfg, img)
    assert enc.shape == (1, 4, 8, 8)


def test_pipeline_from_diffusers_layout_dir(tmp_path):
    """save -> SDPipeline.load -> txt2img produces a deterministic image;
    CFG scale and prompt change the output."""
    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)

    pipe = sd.SDPipeline.load(pipe_dir)
    img = pipe.txt2img("a red square", height=32, width=32, steps=3,
                       cfg_scale=4.0, seed=7)
    assert img.shape == (32, 32, 3) and img.dtype == np.uint8
    img2 = pipe.txt2img("a red square", height=32, width=32, steps=3,
                        cfg_scale=4.0, seed=7)
    np.testing.assert_array_equal(img, img2)  # seeded determinism
    img3 = pipe.txt2img("a blue circle", height=32, width=32, steps=3,
                        cfg_scale=4.0, seed=7)
    assert np.abs(img.astype(int) - img3.astype(int)).max() > 0


def test_diffusion_servicer_routes_diffusers_dirs(tmp_path):
    """The image backend serves a diffusers-layout dir through the SD
    pipeline and writes a PNG."""
    from localai_tpu.backend import contract_pb2 as pb
    from localai_tpu.backend.diffusion_runner import DiffusionServicer

    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)

    s = DiffusionServicer()
    r = s.LoadModel(pb.ModelOptions(model=pipe_dir), None)
    assert r.success, r.message
    assert s.sd_pipe is not None
    dst = str(tmp_path / "out.png")
    r = s.GenerateImage(pb.GenerateImageRequest(
        positive_prompt="a pelican", width=32, height=32, step=2,
        seed=3, dst=dst), None)
    assert r.success, r.message
    from PIL import Image

    im = Image.open(dst)
    assert im.size == (32, 32)
