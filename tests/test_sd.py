"""Stable-Diffusion-class stack: CLIP text parity vs torch transformers,
diffusers-layout UNet/VAE structural load, end-to-end txt2img."""

import jax.numpy as jnp
import numpy as np
import pytest

from localai_tpu.models import sd


def test_clip_text_parity_vs_transformers():
    torch = pytest.importorskip("torch")
    from transformers import CLIPTextConfig, CLIPTextModel

    torch.manual_seed(0)
    tcfg = CLIPTextConfig(
        vocab_size=99, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, hidden_act="quick_gelu")
    model = CLIPTextModel(tcfg).eval()

    import jax.numpy as jnp

    params = {k: jnp.asarray(v.detach().numpy())
              for k, v in model.state_dict().items()}
    jcfg = sd.ClipTextConfig(
        vocab_size=99, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, hidden_act="quick_gelu")

    ids = np.array([[5, 9, 2, 77, 31, 8, 1, 0]], np.int64)
    with torch.no_grad():
        want = model(input_ids=torch.tensor(ids)).last_hidden_state.numpy()
    got = np.asarray(sd.clip_text_encode(params, jcfg, ids))
    np.testing.assert_allclose(got, want, atol=3e-5)


def _tiny_cfgs():
    clip = sd.ClipTextConfig(vocab_size=64, hidden_size=16,
                             intermediate_size=32, num_hidden_layers=1,
                             num_attention_heads=2, max_position_embeddings=8)
    unet = sd.UNetConfig(
        block_out_channels=(16, 32), layers_per_block=1,
        cross_attention_dim=16, attention_head_dim=2,
        down_block_types=("CrossAttnDownBlock2D", "DownBlock2D"),
        up_block_types=("UpBlock2D", "CrossAttnUpBlock2D"),
        norm_num_groups=8)
    vae = sd.VaeConfig(block_out_channels=(16, 32), layers_per_block=1,
                       norm_num_groups=8)
    return clip, unet, vae


def test_unet_and_vae_shapes():
    import jax.numpy as jnp

    _, ucfg, vcfg = _tiny_cfgs()
    up = sd.init_unet_params(ucfg)
    lat = jnp.zeros((2, 4, 8, 8))
    ctx = jnp.zeros((2, 8, ucfg.cross_attention_dim))
    out = sd.unet_forward(up, ucfg, lat, jnp.array([500, 10]), ctx)
    assert out.shape == (2, 4, 8, 8)

    vp = sd.init_vae_params(vcfg)
    img = sd.vae_decode(vp, vcfg, jnp.zeros((1, 4, 8, 8)))
    assert img.shape == (1, 3, 16, 16)  # 2 blocks -> one 2x upsample
    enc = sd.vae_encode(vp, vcfg, img)
    assert enc.shape == (1, 4, 8, 8)


def test_pipeline_from_diffusers_layout_dir(tmp_path):
    """save -> SDPipeline.load -> txt2img produces a deterministic image;
    CFG scale and prompt change the output."""
    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)

    pipe = sd.SDPipeline.load(pipe_dir)
    img = pipe.txt2img("a red square", height=32, width=32, steps=3,
                       cfg_scale=4.0, seed=7)
    assert img.shape == (32, 32, 3) and img.dtype == np.uint8
    img2 = pipe.txt2img("a red square", height=32, width=32, steps=3,
                        cfg_scale=4.0, seed=7)
    np.testing.assert_array_equal(img, img2)  # seeded determinism
    img3 = pipe.txt2img("a blue circle", height=32, width=32, steps=3,
                        cfg_scale=4.0, seed=7)
    assert np.abs(img.astype(int) - img3.astype(int)).max() > 0


def test_diffusion_servicer_routes_diffusers_dirs(tmp_path):
    """The image backend serves a diffusers-layout dir through the SD
    pipeline and writes a PNG."""
    from localai_tpu.backend import contract_pb2 as pb
    from localai_tpu.backend.diffusion_runner import DiffusionServicer

    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)

    s = DiffusionServicer()
    r = s.LoadModel(pb.ModelOptions(model=pipe_dir), None)
    assert r.success, r.message
    assert s.sd_pipe is not None
    dst = str(tmp_path / "out.png")
    r = s.GenerateImage(pb.GenerateImageRequest(
        positive_prompt="a pelican", width=32, height=32, step=2,
        seed=3, dst=dst), None)
    assert r.success, r.message
    from PIL import Image

    im = Image.open(dst)
    assert im.size == (32, 32)


# ---------------- r4: torch block cross-checks (VERDICT #7) ----------------
# diffusers is not installed here, so the oracles are HAND-BUILT torch
# modules implementing the documented SD block semantics (ResnetBlock2D,
# Transformer2DModel with GEGLU, VAE attention) over the SAME weights.

def _np_weights(shapes, seed=0):
    rng = np.random.default_rng(seed)
    return {k: rng.standard_normal(s).astype(np.float32) * 0.1
            for k, s in shapes.items()}


def test_unet_resnet_block_torch_parity():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    cin, cout, temb_dim, groups = 16, 32, 24, 8
    w = _np_weights({
        "norm1.weight": (cin,), "norm1.bias": (cin,),
        "conv1.weight": (cout, cin, 3, 3), "conv1.bias": (cout,),
        "time_emb_proj.weight": (cout, temb_dim),
        "time_emb_proj.bias": (cout,),
        "norm2.weight": (cout,), "norm2.bias": (cout,),
        "conv2.weight": (cout, cout, 3, 3), "conv2.bias": (cout,),
        "conv_shortcut.weight": (cout, cin, 1, 1), "conv_shortcut.bias": (cout,),
    }, seed=1)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((2, cin, 8, 8)).astype(np.float32)
    temb = rng.standard_normal((2, temb_dim)).astype(np.float32)

    got = np.asarray(sd._resnet(sd._P({k: jnp.asarray(v)
                                       for k, v in w.items()}),
                                jnp.asarray(x), jnp.asarray(temb), groups))

    with torch.no_grad():
        tx = torch.tensor(x)
        h = F.group_norm(tx, groups, torch.tensor(w["norm1.weight"]),
                         torch.tensor(w["norm1.bias"]), eps=1e-5)
        h = F.conv2d(F.silu(h), torch.tensor(w["conv1.weight"]),
                     torch.tensor(w["conv1.bias"]), padding=1)
        t = F.linear(F.silu(torch.tensor(temb)),
                     torch.tensor(w["time_emb_proj.weight"]),
                     torch.tensor(w["time_emb_proj.bias"]))
        h = h + t[:, :, None, None]
        h = F.group_norm(h, groups, torch.tensor(w["norm2.weight"]),
                         torch.tensor(w["norm2.bias"]), eps=1e-5)
        h = F.conv2d(F.silu(h), torch.tensor(w["conv2.weight"]),
                     torch.tensor(w["conv2.bias"]), padding=1)
        sc = F.conv2d(tx, torch.tensor(w["conv_shortcut.weight"]),
                      torch.tensor(w["conv_shortcut.bias"]))
        want = (sc + h).numpy()
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_unet_attn_block_torch_parity():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    C, heads, groups, ctx_dim, ff = 16, 2, 8, 12, 32
    names = {
        "norm.weight": (C,), "norm.bias": (C,),
        "proj_in.weight": (C, C), "proj_in.bias": (C,),
        "proj_out.weight": (C, C), "proj_out.bias": (C,),
    }
    tb = "transformer_blocks.0."
    for n in ("norm1", "norm2", "norm3"):
        names[tb + n + ".weight"] = (C,)
        names[tb + n + ".bias"] = (C,)
    for a, kvdim in (("attn1", C), ("attn2", ctx_dim)):
        names[tb + a + ".to_q.weight"] = (C, C)
        names[tb + a + ".to_k.weight"] = (C, kvdim)
        names[tb + a + ".to_v.weight"] = (C, kvdim)
        names[tb + a + ".to_out.0.weight"] = (C, C)
        names[tb + a + ".to_out.0.bias"] = (C,)
    names[tb + "ff.net.0.proj.weight"] = (2 * ff, C)
    names[tb + "ff.net.0.proj.bias"] = (2 * ff,)
    names[tb + "ff.net.2.weight"] = (C, ff)
    names[tb + "ff.net.2.bias"] = (C,)
    w = _np_weights(names, seed=3)
    rng = np.random.default_rng(4)
    x = rng.standard_normal((1, C, 4, 4)).astype(np.float32)
    ctx = rng.standard_normal((1, 5, ctx_dim)).astype(np.float32)

    got = np.asarray(sd._attn_block(
        sd._P({k: jnp.asarray(v) for k, v in w.items()}),
        jnp.asarray(x), jnp.asarray(ctx), heads, groups))

    def t(name):
        return torch.tensor(w[name])

    with torch.no_grad():
        tx = torch.tensor(x)
        h = F.group_norm(tx, groups, t("norm.weight"), t("norm.bias"),
                         eps=1e-5)
        h = h.reshape(1, C, 16).permute(0, 2, 1)
        h = F.linear(h, t("proj_in.weight"), t("proj_in.bias"))

        def mha(pre, q_in, kv_in):
            hd = C // heads
            q = F.linear(q_in, t(tb + pre + ".to_q.weight")).reshape(
                1, -1, heads, hd)
            k = F.linear(kv_in, t(tb + pre + ".to_k.weight")).reshape(
                1, -1, heads, hd)
            v = F.linear(kv_in, t(tb + pre + ".to_v.weight")).reshape(
                1, -1, heads, hd)
            wts = torch.softmax(
                torch.einsum("bthd,bshd->bhts", q, k) / hd ** 0.5, dim=-1)
            o = torch.einsum("bhts,bshd->bthd", wts, v).reshape(1, -1, C)
            return F.linear(o, t(tb + pre + ".to_out.0.weight"),
                            t(tb + pre + ".to_out.0.bias"))

        n1 = F.layer_norm(h, (C,), t(tb + "norm1.weight"),
                          t(tb + "norm1.bias"))
        h = h + mha("attn1", n1, n1)
        n2 = F.layer_norm(h, (C,), t(tb + "norm2.weight"),
                          t(tb + "norm2.bias"))
        h = h + mha("attn2", n2, torch.tensor(ctx))
        n3 = F.layer_norm(h, (C,), t(tb + "norm3.weight"),
                          t(tb + "norm3.bias"))
        proj = F.linear(n3, t(tb + "ff.net.0.proj.weight"),
                        t(tb + "ff.net.0.proj.bias"))
        a, gate = proj.chunk(2, dim=-1)
        ffo = a * F.gelu(gate)
        h = h + F.linear(ffo, t(tb + "ff.net.2.weight"),
                         t(tb + "ff.net.2.bias"))
        h = F.linear(h, t("proj_out.weight"), t("proj_out.bias"))
        want = (h.permute(0, 2, 1).reshape(1, C, 4, 4) + tx).numpy()
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_vae_attn_torch_parity():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F

    C, groups = 16, 8
    w = _np_weights({
        "group_norm.weight": (C,), "group_norm.bias": (C,),
        "to_q.weight": (C, C), "to_q.bias": (C,),
        "to_k.weight": (C, C), "to_k.bias": (C,),
        "to_v.weight": (C, C), "to_v.bias": (C,),
        "to_out.0.weight": (C, C), "to_out.0.bias": (C,),
    }, seed=5)
    rng = np.random.default_rng(6)
    x = rng.standard_normal((1, C, 4, 4)).astype(np.float32)
    got = np.asarray(sd._vae_attn(
        sd._P({k: jnp.asarray(v) for k, v in w.items()}),
        jnp.asarray(x), groups))

    def t(name):
        return torch.tensor(w[name])

    with torch.no_grad():
        tx = torch.tensor(x)
        h = F.group_norm(tx, groups, t("group_norm.weight"),
                         t("group_norm.bias"), eps=1e-5)
        flat = h.reshape(1, C, 16).permute(0, 2, 1)
        q = F.linear(flat, t("to_q.weight"), t("to_q.bias"))
        k = F.linear(flat, t("to_k.weight"), t("to_k.bias"))
        v = F.linear(flat, t("to_v.weight"), t("to_v.bias"))
        wts = torch.softmax(q @ k.permute(0, 2, 1) / C ** 0.5, dim=-1)
        o = F.linear(wts @ v, t("to_out.0.weight"), t("to_out.0.bias"))
        want = (tx + o.permute(0, 2, 1).reshape(1, C, 4, 4)).numpy()
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)


def test_timestep_embedding_formula():
    """flip_sin_to_cos=True, downscale_freq_shift=0 (SD UNet settings)."""
    import math as m

    t = np.array([0, 7, 500], np.int64)
    dim = 32
    half = dim // 2
    freqs = np.exp(-m.log(10000) * np.arange(half) / half)
    args = t[:, None].astype(np.float64) * freqs[None]
    want = np.concatenate([np.cos(args), np.sin(args)], axis=-1)
    got = np.asarray(sd._timestep_embedding(jnp.asarray(t), dim))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


# ---------------- r4: schedulers + img2img ----------------

def test_schedulers_produce_distinct_deterministic_images(tmp_path):
    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)
    pipe = sd.SDPipeline.load(pipe_dir)
    imgs = {}
    for sched in sd.SCHEDULERS:
        a = pipe.txt2img("a fox", height=32, width=32, steps=4,
                         cfg_scale=3.0, seed=11, scheduler=sched)
        b = pipe.txt2img("a fox", height=32, width=32, steps=4,
                         cfg_scale=3.0, seed=11, scheduler=sched)
        np.testing.assert_array_equal(a, b)
        imgs[sched] = a
    # the samplers genuinely differ
    assert any(np.abs(imgs["ddim"].astype(int)
                      - imgs[s].astype(int)).max() > 0
               for s in ("euler", "euler_a", "dpmpp_2m"))
    with pytest.raises(ValueError):
        pipe.txt2img("x", height=32, width=32, steps=2, scheduler="plms")


def test_img2img_strength_semantics(tmp_path):
    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)
    pipe = sd.SDPipeline.load(pipe_dir)
    rng = np.random.default_rng(0)
    init = rng.integers(0, 255, size=(32, 32, 3)).astype(np.uint8)

    recon = pipe.img2img("a fox", init, strength=0.0, steps=4, seed=5)
    low = pipe.img2img("a fox", init, strength=0.3, steps=4, seed=5)
    high = pipe.img2img("a fox", init, strength=1.0, steps=4, seed=5)
    assert recon.shape == (32, 32, 3)

    def d(a, b):
        return float(np.mean((a.astype(float) - b.astype(float)) ** 2))

    # low strength stays closer to the strength-0 reconstruction than a
    # full-strength resample does
    assert d(low, recon) < d(high, recon)
    # determinism
    np.testing.assert_array_equal(
        low, pipe.img2img("a fox", init, strength=0.3, steps=4, seed=5))


def test_diffusion_servicer_img2img_and_scheduler(tmp_path):
    from PIL import Image

    from localai_tpu.backend import contract_pb2 as pb
    from localai_tpu.backend.diffusion_runner import DiffusionServicer

    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)

    s = DiffusionServicer()
    r = s.LoadModel(pb.ModelOptions(model=pipe_dir, scheduler="euler"), None)
    assert r.success, r.message
    assert s.scheduler == "euler"

    rng = np.random.default_rng(1)
    src = str(tmp_path / "init.png")
    Image.fromarray(rng.integers(0, 255, size=(32, 32, 3))
                    .astype(np.uint8)).save(src)
    dst = str(tmp_path / "out.png")
    r = s.GenerateImage(pb.GenerateImageRequest(
        positive_prompt="a pelican", step=3, seed=3, dst=dst, src=src,
        strength=0.5, scheduler="dpmpp_2m"), None)
    assert r.success, r.message
    assert Image.open(dst).size == (32, 32)


# ---------------- r5: ControlNet + diffusion LoRA (VERDICT r4 #5) --------

def _ctrl_cfg():
    return sd.ControlNetConfig(
        block_out_channels=(16, 32), layers_per_block=1,
        cross_attention_dim=16, attention_head_dim=2,
        down_block_types=("CrossAttnDownBlock2D", "DownBlock2D"),
        conditioning_embedding_out_channels=(8, 16), norm_num_groups=8)


def test_controlnet_conditioning_changes_generation(tmp_path):
    """txt2img with a control image differs from unconditioned txt2img,
    is deterministic, and responds to the control image content; without
    a controlnet loaded a control image is a loud error."""
    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae,
                          controlnet_cfg=_ctrl_cfg())
    pipe = sd.SDPipeline.load(pipe_dir)
    assert pipe.ctrl is not None

    rng = np.random.default_rng(0)
    ctrl_a = (rng.random((32, 32, 3)) * 255).astype(np.uint8)
    ctrl_b = np.zeros((32, 32, 3), np.uint8)
    base = pipe.txt2img("a house", height=32, width=32, steps=2,
                        cfg_scale=4.0, seed=7)
    ca1 = pipe.txt2img("a house", height=32, width=32, steps=2,
                       cfg_scale=4.0, seed=7, control_image=ctrl_a)
    ca2 = pipe.txt2img("a house", height=32, width=32, steps=2,
                       cfg_scale=4.0, seed=7, control_image=ctrl_a)
    cb = pipe.txt2img("a house", height=32, width=32, steps=2,
                      cfg_scale=4.0, seed=7, control_image=ctrl_b)
    np.testing.assert_array_equal(ca1, ca2)        # deterministic
    assert np.abs(base.astype(int) - ca1.astype(int)).max() > 0
    assert np.abs(ca1.astype(int) - cb.astype(int)).max() > 0

    # no controlnet -> loud rejection, not a silent drop
    plain_dir = str(tmp_path / "plain")
    sd.save_tiny_pipeline(plain_dir, clip, unet, vae)
    plain = sd.SDPipeline.load(plain_dir)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="controlnet"):
        plain.txt2img("x", height=32, width=32, steps=1,
                      control_image=ctrl_a)


def test_controlnet_through_servicer(tmp_path):
    """mode=controlnet routes src as the control image end-to-end."""
    from PIL import Image

    from localai_tpu.backend import contract_pb2 as pb
    from localai_tpu.backend.diffusion_runner import DiffusionServicer

    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae,
                          controlnet_cfg=_ctrl_cfg())
    src = str(tmp_path / "ctrl.png")
    Image.fromarray((np.random.default_rng(1).random((32, 32, 3)) * 255)
                    .astype(np.uint8)).save(src)
    s = DiffusionServicer()
    r = s.LoadModel(pb.ModelOptions(model=pipe_dir), None)
    assert r.success, r.message
    dst = str(tmp_path / "out.png")
    r = s.GenerateImage(pb.GenerateImageRequest(
        positive_prompt="a bridge", width=32, height=32, step=2,
        seed=3, dst=dst, src=src, mode="controlnet"), None)
    assert r.success, r.message
    assert Image.open(dst).size == (32, 32)


def _write_tiny_lora(path, unet_params, scale_keys, rank=2, seed=5):
    """kohya-style LoRA safetensors targeting the given unet modules."""
    from safetensors.numpy import save_file

    rng = np.random.default_rng(seed)
    t = {}
    for mod in scale_keys:
        w = np.asarray(unet_params[mod + ".weight"])
        out_d, in_d = w.shape[0], int(np.prod(w.shape[1:]))
        kname = "lora_unet_" + mod.replace(".", "_")
        t[kname + ".lora_down.weight"] = \
            rng.standard_normal((rank, in_d)).astype(np.float32) * 0.05
        t[kname + ".lora_up.weight"] = \
            rng.standard_normal((out_d, rank)).astype(np.float32) * 0.05
        t[kname + ".alpha"] = np.full((), rank, np.float32)
    save_file(t, path)
    return t


def test_sd_lora_fuses_exactly_and_changes_output(tmp_path):
    """W' == W + scale*(alpha/r)*up@down for every targeted module, and
    the LoRA'd pipeline generates a different image."""
    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)
    base = sd.SDPipeline.load(pipe_dir)
    img_base = base.txt2img("a fox", height=32, width=32, steps=2,
                            cfg_scale=4.0, seed=11)

    targets = [
        "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_q",
        "down_blocks.0.attentions.0.transformer_blocks.0.attn2.to_k",
        "mid_block.attentions.0.transformer_blocks.0.attn1.to_v",
    ]
    lora_path = str(tmp_path / "add_detail.safetensors")
    tensors = _write_tiny_lora(lora_path, base.unet, targets)

    lora = sd.SDPipeline.load(pipe_dir, lora_paths=(lora_path,),
                              lora_scale=0.8)
    for mod in targets:
        w0 = np.asarray(base.unet[mod + ".weight"])
        kname = "lora_unet_" + mod.replace(".", "_")
        down = tensors[kname + ".lora_down.weight"]
        up = tensors[kname + ".lora_up.weight"]
        want = w0 + 0.8 * (up @ down)   # alpha == rank -> factor 1
        np.testing.assert_allclose(np.asarray(lora.unet[mod + ".weight"]),
                                   want, atol=1e-6)
    img_lora = lora.txt2img("a fox", height=32, width=32, steps=2,
                            cfg_scale=4.0, seed=11)
    assert np.abs(img_base.astype(int) - img_lora.astype(int)).max() > 0


def test_sd_lora_unmatched_is_loud(tmp_path):
    from safetensors.numpy import save_file

    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)
    bogus = str(tmp_path / "bogus.safetensors")
    save_file({
        "lora_unet_nonexistent_module.lora_down.weight":
            np.zeros((2, 4), np.float32),
        "lora_unet_nonexistent_module.lora_up.weight":
            np.zeros((4, 2), np.float32),
    }, bogus)
    import pytest as _pytest
    with _pytest.raises(ValueError, match="no target module matched"):
        sd.SDPipeline.load(pipe_dir, lora_paths=(bogus,))


def test_txt2vid_latent_walk(tmp_path):
    """txt2vid: F frames, deterministic, temporally coherent (adjacent
    frames closer than the clip's endpoints), motion=0 = still clip."""
    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)
    pipe = sd.SDPipeline.load(pipe_dir)

    frames = pipe.txt2vid("a drifting cloud", num_frames=5, height=32,
                          width=32, steps=3, cfg_scale=4.0, seed=7)
    assert frames.shape == (5, 32, 32, 3) and frames.dtype == np.uint8
    again = pipe.txt2vid("a drifting cloud", num_frames=5, height=32,
                         width=32, steps=3, cfg_scale=4.0, seed=7)
    np.testing.assert_array_equal(frames, again)

    d = lambda a, b: float(np.mean(np.abs(a.astype(int) - b.astype(int))))
    adjacent = np.mean([d(frames[i], frames[i + 1]) for i in range(4)])
    assert adjacent < d(frames[0], frames[-1]) + 1e-9
    assert d(frames[0], frames[-1]) > 0          # it actually moves

    still = pipe.txt2vid("a drifting cloud", num_frames=3, height=32,
                         width=32, steps=3, cfg_scale=4.0, seed=7,
                         motion=0.0)
    np.testing.assert_array_equal(still[0], still[1])


def test_img2vid_anchors_on_source(tmp_path):
    """img2vid frames stay near the source at low strength, and the
    source image actually conditions the clip."""
    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)
    pipe = sd.SDPipeline.load(pipe_dir)

    rng = np.random.default_rng(0)
    src_a = rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
    src_b = rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
    fa = pipe.img2vid(src_a, prompt="x", num_frames=3, strength=0.4,
                      steps=4, seed=3)
    fb = pipe.img2vid(src_b, prompt="x", num_frames=3, strength=0.4,
                      steps=4, seed=3)
    assert fa.shape == (3, 32, 32, 3)
    assert np.abs(fa.astype(int) - fb.astype(int)).max() > 0


def test_write_video_mp4_and_gif(tmp_path):
    """write_video produces a REAL readable container: mp4 via OpenCV
    round-trips the frame count; gif via PIL round-trips frames."""
    rng = np.random.default_rng(1)
    frames = rng.integers(0, 255, (6, 32, 32, 3)).astype(np.uint8)

    mp4 = str(tmp_path / "clip.mp4")
    sd.write_video(mp4, frames, fps=4)
    import cv2

    cap = cv2.VideoCapture(mp4)
    assert cap.isOpened()
    n = 0
    while cap.read()[0]:
        n += 1
    cap.release()
    assert n == 6

    gif = str(tmp_path / "clip.gif")
    sd.write_video(gif, frames, fps=4)
    from PIL import Image

    im = Image.open(gif)
    assert getattr(im, "n_frames", 1) == 6


def test_diffusion_servicer_video_modes(tmp_path):
    """GenerateImage mode=txt2vid/img2vid writes a video at dst; img2vid
    without a src is a loud failure."""
    from localai_tpu.backend import contract_pb2 as pb
    from localai_tpu.backend.diffusion_runner import DiffusionServicer

    clip, unet, vae = _tiny_cfgs()
    pipe_dir = str(tmp_path / "pipe")
    sd.save_tiny_pipeline(pipe_dir, clip, unet, vae)

    s = DiffusionServicer()
    r = s.LoadModel(pb.ModelOptions(model=pipe_dir,
                                    options="num_frames=3,fps=4"), None)
    assert r.success, r.message

    dst = str(tmp_path / "clip.mp4")
    r = s.GenerateImage(pb.GenerateImageRequest(
        positive_prompt="a wave", width=32, height=32, step=3, seed=1,
        dst=dst, mode="txt2vid"), None)
    assert r.success, r.message
    import cv2

    cap = cv2.VideoCapture(dst)
    assert cap.isOpened() and cap.read()[0]
    cap.release()

    r = s.GenerateImage(pb.GenerateImageRequest(
        positive_prompt="x", dst=str(tmp_path / "v2.mp4"),
        mode="img2vid"), None)
    assert not r.success
    assert "src" in r.message

    from PIL import Image

    srcp = str(tmp_path / "src.png")
    Image.fromarray(np.full((32, 32, 3), 128, np.uint8)).save(srcp)
    dst2 = str(tmp_path / "clip2.gif")
    r = s.GenerateImage(pb.GenerateImageRequest(
        positive_prompt="a wave", step=3, seed=1, src=srcp, dst=dst2,
        mode="img2vid"), None)
    assert r.success, r.message
    im = Image.open(dst2)
    assert getattr(im, "n_frames", 1) == 3
