"""System observability (ISSUE 8): XLA compile tracking (zero after
warmup, storm detection on a cold program), memory watermarks vs pool
accounting, MFU/goodput arithmetic, event-log ring semantics, the
/debug/state + /debug/events + /readyz surfaces, and exemplar
exposition."""

import asyncio
import json
import threading

import jax
import jax.numpy as jnp
import httpx
import pytest

from localai_tpu.engine import engine as eng
from localai_tpu.engine import sampling
from localai_tpu.engine.paging import PagePool
from localai_tpu.models import llama
from localai_tpu.services import sysobs
from localai_tpu.services.eventlog import EVENTS, EventLog
from localai_tpu.services.metrics import (Metrics, escape_label_value,
                                          label_str)


# -------------------------------------------------------- compile tracking

@pytest.fixture(scope="module")
def warm_engine(byte_tokenizer):
    """Tiny PRECOMPILED paged engine: the warm boundary is marked, so
    any further compile is a storm by contract."""
    cfg = llama.LlamaConfig(
        vocab_size=258, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2,
        max_position_embeddings=256,
    )
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ecfg = eng.EngineConfig(num_slots=2, max_context=64,
                            prefill_buckets=(16,), prefill_chunk=16,
                            decode_burst=2, kv_layout="paged",
                            kv_page_size=16)
    e = eng.Engine(cfg, params, byte_tokenizer, ecfg)
    e.start(precompile=True)
    yield e
    e.shutdown()


def _gen(engine, tok, prompt="hello sysobs", n=6):
    req = eng.GenRequest(
        prompt_ids=tok.encode(prompt),
        params=sampling.SamplingParamsHost(temperature=0.0),
        max_new_tokens=n, ignore_eos=True,
    )
    return engine.generate_text(req)


def test_precompile_marks_warm_and_counts_compiles(warm_engine):
    snap = warm_engine._cobs.snapshot()
    assert snap["warm"] is True
    # precompile() compiled the serving variants with the tracker bound
    assert snap["compiles_total"] > 0
    assert snap["compile_seconds_total"] > 0
    # attribution: the fn-getter notes name the compiled programs
    programs = {c["program"] for c in warm_engine._cobs.last_compiles()}
    assert any(p.startswith("decode_burst") for p in programs)
    assert any(p.startswith("prefill") for p in programs)


def test_repeated_waves_compile_nothing_after_warmup(warm_engine,
                                                     byte_tokenizer):
    """The acceptance contract: a repeated wave of identical-shape
    traffic on a precompiled engine causes ZERO recompiles."""
    before = warm_engine._cobs.snapshot()
    for _ in range(2):
        _gen(warm_engine, byte_tokenizer)
    after = warm_engine._cobs.snapshot()
    assert after["compiles_after_warmup"] == before["compiles_after_warmup"]
    assert after["compiles_after_warmup"] == 0


def test_cold_program_after_warmup_is_a_storm(warm_engine):
    """A compile on a warm engine increments the storm counter and
    emits a structured compile_storm event through the engine's
    eventlog write-through."""
    # built OUTSIDE the activated block: the ones-fill is itself a tiny
    # compile and must not consume the program note
    x = jnp.ones((4,), jnp.float32)
    before = warm_engine._cobs.snapshot()
    with sysobs.activated(warm_engine._cobs):
        warm_engine._cobs.note_program("test_cold_bucket", 99)
        # a fresh lambda is a fresh jit cache entry -> one real compile
        jax.jit(lambda y: y * 2 + 1)(x)
    after = warm_engine._cobs.snapshot()
    assert (after["compiles_after_warmup"]
            == before["compiles_after_warmup"] + 1)
    storms = [ev for ev in EVENTS.events()
              if ev.get("event") == "compile_storm"
              and ev.get("program") == "test_cold_bucket:99"]
    assert storms, "compile_storm event missing from the process ring"
    assert storms[-1]["after_warmup"] is True


def test_tracker_thread_isolation():
    """Two engines compiling on different threads must not cross-count:
    dispatch is by thread-local registration."""
    x = jnp.ones((2,), jnp.float32)
    a, b = sysobs.CompileTracker(model="a"), sysobs.CompileTracker(model="b")
    with sysobs.activated(a):
        jax.jit(lambda y: y - 3)(x)
    assert a.snapshot()["compiles_total"] >= 1
    assert b.snapshot()["compiles_total"] == 0


# ------------------------------------------------------------- watermarks

def test_watermarks_max_fold():
    wm = sysobs.Watermarks()
    wm.sample(pool=3, host=0)
    wm.sample(pool=7, host=None)   # None samples are skipped
    wm.sample(pool=2, host=5)
    assert wm.peak("pool") == 7
    assert wm.snapshot() == {"peak_host": 5, "peak_pool": 7}


def test_engine_watermarks_match_pool_accounting(warm_engine,
                                                 byte_tokenizer):
    _gen(warm_engine, byte_tokenizer)
    m = warm_engine.metrics()
    so = m["sysobs"]
    wm = so["watermarks"]
    pool = warm_engine._pool
    # a served request must have left a high-water mark, and no peak can
    # exceed the physical pool
    assert wm["peak_pool_pages_in_use"] >= 1
    assert wm["peak_pool_pages_in_use"] <= pool.num_pages
    assert wm["peak_slots_active"] >= 1
    assert wm["peak_tokens_total"] >= 1
    # weight bytes: computed from the actual param tree, so > 0
    assert so["weight_bytes"] > 0
    frag = so["fragmentation"]
    assert frag["free_pages"] == pool.free_pages
    assert frag["hole_pages"] + frag["tail_pages"] == frag["free_pages"]


def test_pagepool_fragmentation_holes_vs_tail():
    pool = PagePool(num_slots=2, max_context=64, page_size=16)  # 8 pages
    assert pool.fragmentation() == {"free_pages": 8, "tail_pages": 8,
                                    "hole_pages": 0, "ratio": 0.0}
    # pages pop from the free-list head (0,1,2): freeing page 1 leaves a
    # HOLE below the in-use region while 3..7 remain the contiguous tail
    pages = [pool.alloc_detached() for _ in range(3)]
    assert pages == [0, 1, 2]
    pool.unref_detached(1)
    frag = pool.fragmentation()
    assert frag["free_pages"] == 6
    assert frag["tail_pages"] == 5   # 3..7
    assert frag["hole_pages"] == 1   # page 1
    assert frag["ratio"] == pytest.approx(1 / 6, abs=1e-4)


# ------------------------------------------------------------ goodput/MFU

def test_flops_per_token_hand_computed():
    cfg = llama.LlamaConfig(
        vocab_size=100, hidden_size=8, intermediate_size=16,
        num_layers=2, num_heads=2, num_kv_heads=1,
        max_position_embeddings=64,
    )
    # head_dim = 8/2 = 4; q = 2*4 = 8 cols; kv = 1*4 = 4 cols
    per_layer = (8 * 8          # q proj
                 + 2 * 8 * 4    # k,v proj
                 + 8 * 8        # o proj
                 + 3 * 8 * 16)  # gate/up/down
    expect = 2.0 * (2 * per_layer + 8 * 100)
    assert sysobs.flops_per_token(cfg) == expect
    # attention term: 4 * layers * ctx * hidden
    assert (sysobs.flops_per_token(cfg, ctx=10)
            == expect + 4.0 * 2 * 10 * 8)


def test_goodput_meter_and_mfu():
    m = sysobs.GoodputMeter(flops_per_tok=1e9, peak_flops=1e12)
    m.add(100)
    m.add(50)
    snap = m.snapshot()
    assert snap["goodput_tokens_total"] == 150
    assert snap["goodput_requests_total"] == 2
    # at an explicit 100 tok/s: 100 * 1e9 / 1e12 = 0.1 MFU
    assert m.mfu(tok_s=100.0) == pytest.approx(0.1)


def test_mfu_honest_zero_without_peak():
    m = sysobs.GoodputMeter(flops_per_tok=1e9, peak_flops=0.0)
    m.add(1000)
    assert m.mfu(tok_s=1e6) == 0.0


def test_peak_device_flops_env_override(monkeypatch):
    monkeypatch.setenv("LOCALAI_PEAK_TFLOPS", "2.5")
    assert sysobs.peak_device_flops() == pytest.approx(2.5e12)
    monkeypatch.setenv("LOCALAI_PEAK_TFLOPS", "garbage")
    # bad override falls through to the table (CPU -> 0.0)
    assert sysobs.peak_device_flops() == 0.0


def test_engine_goodput_counts_only_completions(warm_engine,
                                                byte_tokenizer):
    before = warm_engine.metrics()["sysobs"]["goodput"]
    _gen(warm_engine, byte_tokenizer, n=5)
    after = warm_engine.metrics()["sysobs"]["goodput"]
    assert (after["goodput_tokens_total"]
            == before["goodput_tokens_total"] + 5)
    assert (after["goodput_requests_total"]
            == before["goodput_requests_total"] + 1)


# --------------------------------------------------------------- eventlog

def test_eventlog_ring_bounded_and_ordered():
    ev = EventLog(sink="off", ring_size=16)
    for i in range(100):
        ev.emit("tick", n=i)
    evs = ev.events()
    assert len(evs) == 16
    assert [e["n"] for e in evs] == list(range(84, 100))
    assert evs[-1]["seq"] == 100
    assert ev.events(last=3) == evs[-3:]
    assert ev.snapshot()["ring_size"] == 16


def test_eventlog_file_sink_write_through(tmp_path):
    path = tmp_path / "events.jsonl"
    ev = EventLog(sink=str(path), ring_size=8)
    ev.emit("admit", rid="r1", queued=2)
    ev.emit("shed", rid="r2", reason="queue_full")
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [ln["event"] for ln in lines] == ["admit", "shed"]
    assert lines[0]["rid"] == "r1"
    assert lines[1]["reason"] == "queue_full"


def test_eventlog_bad_sink_never_raises():
    ev = EventLog(sink="/nonexistent-dir-xyz/events.jsonl", ring_size=4)
    ev.emit("still_works")   # ring-only fallback
    assert ev.sink == "off"
    assert ev.events()[-1]["event"] == "still_works"


def test_engine_lifecycle_events_have_correlation_ids(warm_engine,
                                                      byte_tokenizer):
    _gen(warm_engine, byte_tokenizer)
    evs = EVENTS.events()
    admits = [e for e in evs if e["event"] == "admit"]
    completes = [e for e in evs if e["event"] == "complete"]
    assert admits and completes
    # the completion's rid pivots back to its admission
    assert completes[-1]["rid"] in {e["rid"] for e in admits}
    assert completes[-1]["completion_tokens"] >= 1


# ------------------------------------------------- state snapshot (engine)

def test_engine_state_snapshot_shape(warm_engine, byte_tokenizer):
    _gen(warm_engine, byte_tokenizer)
    s = warm_engine.state_snapshot()
    assert s["warm"] is True
    assert len(s["slots"]) == 2
    assert s["queued"] == 0
    assert s["compiles"]["compiles_total"] > 0
    assert s["weight_bytes"] > 0
    pool = s["pool"]
    assert pool["pages_total"] == warm_engine._pool.num_pages
    assert len(pool["pages_per_slot"]) == 2
    assert "fragmentation" in pool
    json.dumps(s)   # the snapshot must be JSON-serializable as-is


# ------------------------------------------------------- HTTP debug surface

@pytest.fixture(scope="module")
def server():
    from localai_tpu.api.app import build_app, run_app
    from localai_tpu.backend.fake import FakeServicer
    from localai_tpu.capabilities import Capabilities
    from localai_tpu.config.app_config import AppConfig
    from localai_tpu.config.model_config import ModelConfig
    from localai_tpu.modelmgr.loader import ModelLoader
    from localai_tpu.modelmgr.process import free_port

    port = free_port()
    app_config = AppConfig(models_path="/tmp/localai-test-models",
                           address=f"127.0.0.1:{port}")
    loader = ModelLoader(health_attempts=100, health_interval_s=0.1)
    loader.register_embedded("fake", FakeServicer)
    configs = {"tiny": ModelConfig(name="tiny", backend="fake",
                                   model="tiny"),
               # /debug/kv shape variants (ISSUE 15): audited-off and
               # merged multi-replica views, loaded on demand by the
               # kv endpoint tests
               "tinyoff": ModelConfig(name="tinyoff", backend="fake",
                                      model="tiny",
                                      options=["kv_audit=off"]),
               "tinypool": ModelConfig(name="tinypool", backend="fake",
                                       model="tiny",
                                       options=["engines=2"])}
    caps = Capabilities(app_config, loader, configs)
    app = build_app(caps, app_config)

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            await run_app(app, app_config.address)
            started.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(10)

    class H:
        base = f"http://127.0.0.1:{port}"

    # load "tiny" so the debug surfaces have a backend to pull from
    r = httpx.post(f"{H.base}/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello world"}],
    }, timeout=60)
    assert r.status_code == 200, r.text
    yield H
    loop.call_soon_threadsafe(loop.stop)
    loader.stop_all()


def test_metrics_content_type_and_escaping(server):
    r = httpx.get(f"{server.base}/metrics")
    assert r.status_code == 200
    assert r.headers["content-type"].startswith(
        "text/plain; version=0.0.4")
    assert "localai_api_call_bucket" in r.text


def test_readyz_body_has_breakers_and_load(server):
    r = httpx.get(f"{server.base}/readyz")
    assert r.status_code == 200
    body = r.json()
    assert body["status"] == "ready"
    assert body["breakers"]["tiny"]["state"] == "closed"
    load = body["load"]["tiny"]
    assert load["queue_depth"] == 0
    assert load["slots_total"] == 1


def test_debug_state_endpoint(server):
    r = httpx.get(f"{server.base}/debug/state")
    assert r.status_code == 200
    body = r.json()
    assert body["uptime_s"] >= 0
    assert "tiny" in body["loader"]
    st = body["models"]["tiny"]
    assert st["warm"] is True
    assert st["compiles"]["compiles_total"] == 0
    assert "eventlog" in body


def test_debug_events_endpoint_merges_and_tags(server):
    EVENTS.emit("core_marker", detail="from-core")
    r = httpx.get(f"{server.base}/debug/events")
    assert r.status_code == 200
    evs = r.json()["events"]
    procs = {e["proc"] for e in evs}
    assert "core" in procs
    assert "backend:tiny" in procs   # the fake's ring rode GetState
    assert any(e["event"] == "core_marker" for e in evs)
    # time-ordered merge
    ts = [e.get("ts", 0.0) for e in evs]
    assert ts == sorted(ts)
    # ?last trims to the most recent N
    r2 = httpx.get(f"{server.base}/debug/events", params={"last": 1})
    assert len(r2.json()["events"]) == 1


def test_debug_kv_endpoint(server):
    r = httpx.get(f"{server.base}/debug/kv")
    assert r.status_code == 200
    kv = r.json()["models"]["tiny"]
    assert kv["mode"] == "on"
    assert kv["pool"]["pages_total"] == 8
    assert kv["pool"]["free"] + kv["pool"]["active"] + kv["pool"][
        "retained"] == kv["pool"]["pages_total"]
    aud = kv["audit"]
    assert aud["violations"] == 0 and aud["last_violations"] == []
    assert aud["ledger"]["counts"]["alloc"] >= 1
    assert kv["ledger_tail"][0]["op"] == "alloc"
    assert kv["chains"][0]["depth"] == 0
    assert "host" in kv


def test_debug_kv_endpoint_off_and_multi_replica_shapes(server):
    for name in ("tinyoff", "tinypool"):
        r = httpx.post(f"{server.base}/v1/chat/completions", json={
            "model": name,
            "messages": [{"role": "user", "content": "hello"}],
        }, timeout=60)
        assert r.status_code == 200, r.text
    models = httpx.get(f"{server.base}/debug/kv").json()["models"]
    # kv_audit=off: no auditor, no ledger — just the mode marker
    off = models["tinyoff"]
    assert off["mode"] == "off" and "ledger_tail" not in off
    # engines=2: the pool's merged view, one entry per replica
    tp = models["tinypool"]
    assert tp["engine_replicas"] == 2
    assert [r["replica"] for r in tp["replicas"]] == [0, 1]
    assert all(r["audit"]["violations"] == 0 for r in tp["replicas"])
    assert "shared_host" in tp and "pool_index_keys" in tp


# -------------------------------------------------------------- exemplars

def _parse_prom(text):
    out = {}
    for ln in text.splitlines():
        if ln.startswith("#"):
            continue
        out.setdefault(ln.split("{")[0].split(" ")[0], []).append(ln)
    return out


def test_exemplar_rides_matching_bucket():
    m = Metrics()
    m.set_histogram("ttft_seconds", label_str(model="m1"),
                    [0.1, 1.0, 10.0], [2, 3, 1, 0], 4.2, 6)
    m.set_exemplar("ttft_seconds", label_str(model="m1"),
                   0.5, "req-worst", ts=1234.5)
    lines = _parse_prom(m.render())["localai_ttft_seconds_bucket"]
    tagged = [ln for ln in lines if "# {" in ln]
    assert len(tagged) == 1
    # 0.5 falls in the le="1.0" bucket
    assert 'le="1.0"' in tagged[0]
    assert 'trace_id="req-worst"' in tagged[0]
    assert tagged[0].rstrip().endswith("0.5 1234.500")


def test_exemplar_over_top_bucket_lands_on_inf():
    m = Metrics()
    m.set_histogram("itl_seconds", label_str(model="m1"),
                    [0.1, 1.0], [1, 1, 1], 20.0, 3)
    m.set_exemplar("itl_seconds", label_str(model="m1"), 15.0, "slowest")
    lines = _parse_prom(m.render())["localai_itl_seconds_bucket"]
    tagged = [ln for ln in lines if "# {" in ln]
    assert len(tagged) == 1
    assert 'le="+Inf"' in tagged[0]


def test_label_value_escaping():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    assert label_str(model='we"ird') == 'model="we\\"ird"'
    # sorted for stable exposition
    assert label_str(b="2", a="1") == 'a="1",b="2"'


def test_clear_instrument_drops_exemplars():
    m = Metrics()
    m.set_histogram("h", label_str(model="x"), [1.0], [1, 0], 0.5, 1)
    m.set_exemplar("h", label_str(model="x"), 0.5, "t")
    m.clear_instrument("h")
    assert "# {" not in m.render()
