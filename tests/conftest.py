"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax initializes.

Mirrors the reference's test stance (SURVEY.md section 4) but adds what it
lacks: hermetic multi-device sharding tests without real hardware.

NOTE: the axon TPU plugin ignores the JAX_PLATFORMS env var, so the switch
must go through jax.config before any backend initialization.
"""

import os

os.environ.setdefault("JAX_ENABLE_X64", "0")
# Older jax releases (< 0.4.x with jax_num_cpu_devices) spell the virtual
# device count as an XLA flag; it is read at backend init, which has not
# happened yet here.
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # pre-jax_num_cpu_devices release: XLA_FLAGS above covers it

# Persistent compilation cache: the suite builds dozens of Engine
# instances over the same tiny-llama shapes; deserializing repeat
# programs instead of recompiling keeps the whole tier-1 run inside
# its wall-clock budget (same helper the serving path uses).
from localai_tpu.utils.jaxtools import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "e2e: full-stack tests spawning real backend subprocesses")
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 "
                   "verify budget (ROADMAP runs -m 'not slow')")


@pytest.fixture(scope="session")
def tiny_llama():
    """A tiny randomly-initialized llama for engine/API tests."""
    from localai_tpu.models import llama

    cfg = llama.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        max_position_embeddings=128,
    )
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


class ByteTokenizer:
    """Minimal tokenizer for hermetic tests: bytes <-> ids, id 0 = EOS."""

    eos_token_id = 0
    bos_token_id = 1

    def encode(self, text: str):
        return [2 + b for b in text.encode("utf-8")]

    def decode(self, ids, skip_special_tokens=True):
        data = bytes(i - 2 for i in ids if i >= 2)
        return data.decode("utf-8", errors="replace")

    def get_vocab_size(self):
        return 258


@pytest.fixture(scope="session")
def byte_tokenizer():
    return ByteTokenizer()
