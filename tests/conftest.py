"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax initializes.

Mirrors the reference's test stance (SURVEY.md section 4) but adds what it
lacks: hermetic multi-device sharding tests without real hardware.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_llama():
    """A tiny randomly-initialized llama for engine/API tests."""
    import jax
    from localai_tpu.models import llama

    cfg = llama.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        max_position_embeddings=128,
    )
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params
