"""Torch-parity oracles for the flagship model families (VERDICT r3 #3).

Each test builds a tiny-random HF checkpoint with the installed torch
``transformers`` (the numerical oracle), saves it in the real published
layout, loads it through this framework's own config+weight loaders, and
compares outputs at fp32 — end-to-end through the actual serving entry
points (prefill/decode_step/encode), not reimplementations.

Reference backends being mirrored:
  llama   -> backend/cpp/llama/grpc-server.cpp (the main LLM engine)
  whisper -> backend/go/transcribe/whisper (AudioTranscription)
  bert    -> backend/go/llm/bert (embeddings), backend/python/rerankers
  CLIP    -> grpc-server.cpp LLaVA vision tower (:1157-1180)

Tolerances: fp32 compute on both sides; 2e-4 absolute / 2e-3 relative
catches real math divergences (RoPE layout, GQA grouping, gelu variant,
mel filterbank) while riding out accumulation-order noise.
"""

import json
import os

import numpy as np
import pytest

# oracle parity is thorough but slow; keep tier-1 (-m 'not slow') fast
pytestmark = pytest.mark.slow

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from localai_tpu.engine import weights  # noqa: E402
from localai_tpu.models import bert as jbert  # noqa: E402
from localai_tpu.models import llama as jllama  # noqa: E402
from localai_tpu.models import vision as jvision  # noqa: E402
from localai_tpu.models import whisper as jwhisper  # noqa: E402


def _close(ours, ref, atol=2e-4, rtol=2e-3, what=""):
    np.testing.assert_allclose(np.asarray(ours, np.float32),
                               np.asarray(ref, np.float32),
                               atol=atol, rtol=rtol, err_msg=what)


# ---------------------------------------------------------------- llama

def _tiny_torch_llama(tmp, rope_scaling=None, theta=10000.0):
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    tcfg = LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=theta,
        tie_word_embeddings=False, rope_scaling=rope_scaling,
        attention_bias=False,
    )
    model = LlamaForCausalLM(tcfg).eval()
    d = os.path.join(tmp, "llama")
    model.save_pretrained(d, safe_serialization=True)
    return d, model


def _load_ours_llama(d):
    cfg = jllama.LlamaConfig.from_json(os.path.join(d, "config.json"),
                                       dtype=jnp.float32)
    params = weights.load_llama_params(d, cfg, dtype=jnp.float32)
    return cfg, params


def _llama_parity(d, model, n_prompt=9, n_decode=6):
    cfg, params = _load_ours_llama(d)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 96, size=n_prompt).astype(np.int32)

    with torch.no_grad():
        ref = model(torch.tensor(ids[None].astype(np.int64))).logits[0].numpy()

    ck, cv = jllama.init_cache(cfg, 1, 64, jnp.float32)
    ours, ck, cv = jllama.prefill(
        params, cfg, ids[None], np.array([n_prompt], np.int32), ck, cv,
        np.array([0], np.int32), np.array([0], np.int32),
        return_all_logits=True)
    _close(ours[0, :n_prompt], ref, what="prefill logits (all positions)")

    # greedy decode continuation through the cached decode_step path
    cur = np.array([int(np.argmax(ref[-1]))], np.int32)
    tids = list(ids) + [int(cur[0])]
    lengths = np.array([n_prompt], np.int32)
    for step in range(n_decode):
        logits, ck, cv = jllama.decode_step(params, cfg, cur, lengths, ck, cv)
        with torch.no_grad():
            tref = model(torch.tensor(np.asarray(tids)[None].astype(np.int64))
                         ).logits[0, -1].numpy()
        _close(logits[0], tref, what=f"decode_step logits @ step {step}")
        cur = np.array([int(np.argmax(tref))], np.int32)
        tids.append(int(cur[0]))
        lengths = lengths + 1


def test_llama_logits_parity(tmp_path):
    d, model = _tiny_torch_llama(str(tmp_path))
    _llama_parity(d, model)


def test_llama_rope_linear_scaling_parity(tmp_path):
    d, model = _tiny_torch_llama(
        str(tmp_path),
        rope_scaling={"rope_type": "linear", "factor": 2.0})
    _llama_parity(d, model, n_prompt=12)


def test_llama_rope_llama3_parity(tmp_path):
    d, model = _tiny_torch_llama(
        str(tmp_path),
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32},
        theta=50000.0)
    _llama_parity(d, model, n_prompt=12)


# --------------------------------------------------------------- whisper

def _tiny_torch_whisper(tmp):
    from transformers import WhisperConfig, WhisperForConditionalGeneration

    torch.manual_seed(0)
    tcfg = WhisperConfig(
        vocab_size=120, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, num_mel_bins=16,
        max_source_positions=1500, max_target_positions=64,
        decoder_start_token_id=1, pad_token_id=0, bos_token_id=1,
        eos_token_id=2,
    )
    model = WhisperForConditionalGeneration(tcfg).eval()
    d = os.path.join(tmp, "whisper")
    model.save_pretrained(d, safe_serialization=True)
    return d, model


def test_whisper_encoder_decoder_parity(tmp_path):
    d, model = _tiny_torch_whisper(str(tmp_path))
    cfg = jwhisper.WhisperConfig.from_json(os.path.join(d, "config.json"),
                                           dtype=jnp.float32)
    params = jwhisper.load_hf_params(d, cfg)

    rng = np.random.default_rng(1)
    mel = rng.normal(size=(1, 16, 3000)).astype(np.float32)
    with torch.no_grad():
        tenc = model.model.encoder(torch.tensor(mel)).last_hidden_state.numpy()
    enc = np.asarray(jwhisper.encode(params, cfg, mel))
    _close(enc, tenc, what="whisper encoder states")

    # decoder: step-by-step with self-attn cache vs torch full forward
    dec_ids = np.array([1, 7, 23, 50], np.int64)
    with torch.no_grad():
        tlogits = model(input_features=torch.tensor(mel),
                        decoder_input_ids=torch.tensor(dec_ids[None])
                        ).logits[0].numpy()
    xk, xv = jwhisper.cross_kv(params, cfg, jnp.asarray(tenc))
    L, D = cfg.decoder_layers, cfg.d_model
    ckd = jnp.zeros((L, 1, 64, D), jnp.float32)
    cvd = jnp.zeros((L, 1, 64, D), jnp.float32)
    for t, tok in enumerate(dec_ids):
        logits, ckd, cvd = jwhisper.decode_step(
            params, cfg, np.array([tok], np.int32), np.int32(t), xk, xv,
            ckd, cvd)
        _close(logits[0], tlogits[t], what=f"whisper decoder logits @ {t}")


def test_whisper_log_mel_matches_feature_extractor():
    from transformers import WhisperFeatureExtractor

    rng = np.random.default_rng(2)
    audio = (rng.normal(size=16000 * 3) * 0.1).astype(np.float32)
    fe = WhisperFeatureExtractor(feature_size=16)
    ref = fe(audio, sampling_rate=16000, return_tensors="np",
             padding="max_length")["input_features"][0]
    ours = jwhisper.log_mel(audio, 16)
    _close(ours, ref, atol=2e-3, rtol=2e-2, what="log-mel features")


# ------------------------------------------------------------------ bert

def _tiny_torch_bert_cfg():
    from transformers import BertConfig

    return BertConfig(
        vocab_size=60, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
    )


def test_bert_hidden_state_parity(tmp_path):
    from transformers import BertModel

    torch.manual_seed(0)
    model = BertModel(_tiny_torch_bert_cfg()).eval()
    d = os.path.join(str(tmp_path), "bert")
    model.save_pretrained(d, safe_serialization=True)

    cfg = jbert.BertConfig.from_json(os.path.join(d, "config.json"),
                                     dtype=jnp.float32)
    params = jbert.load_hf_params(d, cfg)

    tokens = np.array([[2, 11, 35, 7, 0, 0], [5, 9, 0, 0, 0, 0]], np.int32)
    mask = (tokens > 0).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.tensor(tokens.astype(np.int64)),
                    attention_mask=torch.tensor(mask.astype(np.int64))
                    ).last_hidden_state.numpy()
    ours = np.asarray(jbert.encode(params, cfg, tokens, mask))
    # only non-padding positions are meaningful
    for b in range(2):
        n = int(mask[b].sum())
        _close(ours[b, :n], ref[b, :n], what=f"bert hidden states row {b}")


def test_bert_cross_encoder_parity(tmp_path):
    from transformers import BertForSequenceClassification

    torch.manual_seed(0)
    model = BertForSequenceClassification(
        _tiny_torch_bert_cfg(), ).eval()
    model.config.num_labels = 1
    # rebuild with 1 label head
    cfg_t = _tiny_torch_bert_cfg()
    cfg_t.num_labels = 1
    model = BertForSequenceClassification(cfg_t).eval()
    d = os.path.join(str(tmp_path), "rerank")
    model.save_pretrained(d, safe_serialization=True)

    cfg = jbert.BertConfig.from_json(os.path.join(d, "config.json"),
                                     dtype=jnp.float32)
    params = jbert.load_hf_cross_params(d, cfg)
    tokens = np.array([[2, 11, 35, 7, 9, 3]], np.int32)
    mask = np.ones_like(tokens)
    type_ids = np.array([[0, 0, 0, 1, 1, 1]], np.int32)
    with torch.no_grad():
        ref = model(torch.tensor(tokens.astype(np.int64)),
                    attention_mask=torch.tensor(mask.astype(np.int64)),
                    token_type_ids=torch.tensor(type_ids.astype(np.int64))
                    ).logits[0, 0].item()
    ours = float(np.asarray(jbert.cross_score(params, cfg, tokens, mask,
                                              type_ids))[0])
    assert abs(ours - ref) < 2e-4, (ours, ref)


# -------------------------------------------------------------- CLIP ViT

def test_clip_vit_llava_features_parity(tmp_path):
    from safetensors.torch import save_file
    from transformers import CLIPVisionConfig, CLIPVisionModel

    torch.manual_seed(0)
    tcfg = CLIPVisionConfig(
        image_size=28, patch_size=14, hidden_size=16, intermediate_size=32,
        num_hidden_layers=3, num_attention_heads=2, projection_dim=24,
    )
    model = CLIPVisionModel(tcfg).eval()

    # LLaVA-style projector (2-layer gelu MLP) on top of the penultimate
    # layer's patch features
    torch.manual_seed(1)
    lin1 = torch.nn.Linear(16, 24)
    lin2 = torch.nn.Linear(24, 24)

    d = os.path.join(str(tmp_path), "clip")
    os.makedirs(d)
    sd = {f"vision_model.{k}": v for k, v in model.vision_model.state_dict().items()}
    sd["multi_modal_projector.linear_1.weight"] = lin1.weight.detach()
    sd["multi_modal_projector.linear_1.bias"] = lin1.bias.detach()
    sd["multi_modal_projector.linear_2.weight"] = lin2.weight.detach()
    sd["multi_modal_projector.linear_2.bias"] = lin2.bias.detach()
    save_file(sd, os.path.join(d, "model.safetensors"))
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"vision_config": tcfg.to_dict(), "proj_dim": 24}, f)

    cfg = jvision.VisionConfig.from_json(os.path.join(d, "config.json"),
                                         dtype=jnp.float32)
    params = jvision.load_params(d, cfg)

    rng = np.random.default_rng(3)
    pixels = rng.normal(size=(1, 3, 28, 28)).astype(np.float32)
    with torch.no_grad():
        hs = model(torch.tensor(pixels), output_hidden_states=True
                   ).hidden_states
        feats = hs[-2][:, 1:, :]           # penultimate layer, CLS dropped
        ref = lin2(torch.nn.functional.gelu(lin1(feats))).numpy()
    ours = np.asarray(jvision.encode(params, cfg, pixels))
    _close(ours, ref, what="LLaVA projected patch features")
