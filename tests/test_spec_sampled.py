"""Stochastic speculative sampling (ISSUE 18): rejection-sampling
acceptance so temperature>0 slots ride the fused spec tick.

Coverage layers:

* `accept_sampled` units — hand-checked acceptance probabilities
  (explicit draft distribution AND the deterministic one-hot
  degeneration), residual renormalization with the draft token zeroed,
  all-reject => exactly one fresh sample, full-accept => drafts + bonus,
  inactive-slot key/emission neutrality;
* `verify_dist` — per-position distribution identity with the plain
  sampler's filter_window (the distribution-preservation mechanism);
* engine-level distribution preservation — chi-square goodness-of-fit
  of spec-sampled vs plain-sampled token frequencies over a fixed seed
  ladder (two deterministic runs; the acceptance contract is
  distribution-identity, not byte-identity);
* the PR-10 re-admission contract for a preempted SAMPLED spec slot —
  the resumed continuation is bit-for-bit a fresh re-admission of
  (prompt + emitted) with the same seed on an identical spec-on engine;
* eligibility exclusions that must hold by TEST, not comment: grammar-
  constrained slots and lockstep engines never enter spec rounds.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from localai_tpu.engine import engine as eng
from localai_tpu.engine import sampling, speculative
from localai_tpu.models import llama
from localai_tpu.services.eventlog import EVENTS

from .conftest import ByteTokenizer


# ---------- accept_sampled units ----------


def _keys(S, base=0):
    return jnp.stack([
        jax.random.key_data(jax.random.PRNGKey(base + i)) for i in range(S)])


def _dist(rows, V):
    """[len(rows)] probability rows -> [W, V] array."""
    out = np.zeros((len(rows), V), np.float32)
    for j, row in enumerate(rows):
        for tok, p in row.items():
            out[j, tok] = p
    return out


def test_accept_sampled_full_accept_emits_drafts_plus_bonus():
    V, D = 8, 3
    drafts = jnp.asarray([[3, 5, 2]], jnp.int32)
    tp = jnp.asarray(_dist([{3: 1.0}, {5: 1.0}, {2: 1.0}, {7: 1.0}], V))[None]
    out, n_out, k, new_keys = speculative.accept_sampled(
        drafts, tp, None, _keys(1), jnp.asarray([True]))
    assert int(k[0]) == 3 and int(n_out[0]) == 4
    assert np.asarray(out[0]).tolist() == [3, 5, 2, 7]
    assert not np.array_equal(np.asarray(new_keys), np.asarray(_keys(1)))


def test_accept_sampled_all_reject_exactly_one_fresh_sample():
    # p(draft) == 0 at position 0: u < 0 never accepts, and the residual
    # (p with the draft token zeroed) IS p — the single emitted token
    # comes from the target's position-0 law
    V, D = 8, 3
    drafts = jnp.asarray([[3, 3, 3]], jnp.int32)
    tp = jnp.asarray(_dist(
        [{6: 1.0}, {1: 1.0}, {1: 1.0}, {1: 1.0}], V))[None]
    out, n_out, k, _ = speculative.accept_sampled(
        drafts, tp, None, _keys(1), jnp.asarray([True]))
    assert int(k[0]) == 0 and int(n_out[0]) == 1
    assert int(out[0, 0]) == 6


def test_accept_sampled_acceptance_probability_and_residual():
    # p0 = {a:.5, b:.3, c:.2}, draft = a (one-hot q): acceptance is
    # exactly u < 0.5; rejected slots resample from the residual
    # norm(p0 with a zeroed) = {b:.6, c:.4} — never a
    V, S = 8, 4000
    a, b, c = 3, 4, 5
    drafts = jnp.full((S, 1), a, jnp.int32)
    tp = jnp.broadcast_to(jnp.asarray(
        _dist([{a: 0.5, b: 0.3, c: 0.2}, {1: 1.0}], V))[None], (S, 2, V))
    out, n_out, k, _ = speculative.accept_sampled(
        drafts, tp, None, _keys(S), jnp.ones((S,), bool))
    k = np.asarray(k)
    first = np.asarray(out[:, 0])
    acc_rate = float((k == 1).mean())
    assert abs(acc_rate - 0.5) < 0.04          # +-5 sigma at S=4000
    rej = first[k == 0]
    assert rej.size > 0 and not np.any(rej == a)
    frac_b = float((rej == b).mean())
    assert abs(frac_b - 0.6) < 0.06
    assert np.array_equal(np.asarray(n_out), k + 1)


def test_accept_sampled_explicit_draft_probs_ratio():
    # non-one-hot q: p = {x:.2, y:.5, z:.3}, q = {x:.4, y:.6}, draft = x
    # => accept with min(1, .2/.4) = 0.5; the residual clip(p - q, 0)
    # has mass ONLY on z — rejection always emits z (hand-checked)
    V, S = 8, 4000
    x, y, z = 2, 3, 4
    drafts = jnp.full((S, 1), x, jnp.int32)
    tp = jnp.broadcast_to(jnp.asarray(
        _dist([{x: 0.2, y: 0.5, z: 0.3}, {1: 1.0}], V))[None], (S, 2, V))
    qp = jnp.broadcast_to(jnp.asarray(
        _dist([{x: 0.4, y: 0.6}], V))[None], (S, 1, V))
    out, _n, k, _ = speculative.accept_sampled(
        drafts, tp, qp, _keys(S, base=100), jnp.ones((S,), bool))
    k = np.asarray(k)
    first = np.asarray(out[:, 0])
    assert abs(float((k == 1).mean()) - 0.5) < 0.04
    assert np.all(first[k == 0] == z)


def test_accept_sampled_one_hot_degeneration_bit_exact():
    # draft_probs=None must equal an explicit one-hot q bit-for-bit:
    # same keys => same uniforms => same acceptances and resamples
    V, S, D = 16, 64, 3
    rng = np.random.default_rng(0)
    drafts = jnp.asarray(rng.integers(0, V, size=(S, D)), jnp.int32)
    raw = rng.random((S, D + 1, V)).astype(np.float32)
    tp = jnp.asarray(raw / raw.sum(-1, keepdims=True))
    onehot = jnp.asarray(
        np.eye(V, dtype=np.float32)[np.asarray(drafts)])        # [S, D, V]
    act = jnp.ones((S,), bool)
    o1, n1, k1, nk1 = speculative.accept_sampled(
        drafts, tp, None, _keys(S), act)
    o2, n2, k2, nk2 = speculative.accept_sampled(
        drafts, tp, onehot, _keys(S), act)
    assert np.array_equal(np.asarray(o1), np.asarray(o2))
    assert np.array_equal(np.asarray(n1), np.asarray(n2))
    assert np.array_equal(np.asarray(k1), np.asarray(k2))
    assert np.array_equal(np.asarray(nk1), np.asarray(nk2))


def test_accept_sampled_inactive_slot_untouched():
    V = 8
    drafts = jnp.asarray([[3], [3]], jnp.int32)
    tp = jnp.broadcast_to(jnp.asarray(
        _dist([{3: 1.0}, {5: 1.0}], V))[None], (2, 2, V))
    keys = _keys(2)
    out, n_out, _k, new_keys = speculative.accept_sampled(
        drafts, tp, None, keys, jnp.asarray([True, False]))
    assert int(n_out[0]) == 2 and int(n_out[1]) == 0
    assert np.array_equal(np.asarray(new_keys[1]), np.asarray(keys[1]))
    assert not np.array_equal(np.asarray(new_keys[0]), np.asarray(keys[0]))


# ---------- verify_dist: the distribution-identity mechanism ----------


def test_verify_dist_matches_plain_filter_window():
    """Each verify position's (idx, probs) must equal what filter_window
    produces for that position's logits under the slot's params — the
    same code path plain `sample` draws its categorical from."""
    S, W, V = 2, 3, 64
    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.normal(size=(S, W, V)).astype(np.float32))
    sp = sampling.make_slot_params(S)
    sp["temperature"][:] = [0.7, 1.3]
    sp["top_k"][:] = [8, 0]
    sp["top_p"][:] = [0.9, 0.95]
    sp["greedy"][:] = False
    spj = {k: jnp.asarray(v) for k, v in sp.items()}
    vidx, vprobs = sampling.verify_dist(logits, spj, use_typical=False)
    zb = jnp.zeros((1, 1), jnp.float32)
    for s in range(S):
        row = {k: jnp.asarray(v[s:s + 1]) for k, v in sp.items()}
        for w in range(W):
            idx, masked, _ = sampling.filter_window(
                logits[s, w][None], row, None, None, zb, mu=None,
                use_penalties=False, use_typical=False, use_mirostat=False)
            probs = jax.nn.softmax(masked, axis=-1)
            assert np.array_equal(np.asarray(vidx[s, w]), np.asarray(idx[0]))
            np.testing.assert_allclose(np.asarray(vprobs[s, w]),
                                       np.asarray(probs[0]), rtol=1e-6)
    # rank-0 of the window is the greedy argmax (byte-stability anchor)
    assert np.array_equal(np.asarray(vidx[:, :, 0]),
                          np.asarray(jnp.argmax(logits, axis=-1)))


def test_two_sample_chi2_helper():
    rng = np.random.default_rng(3)
    p = np.asarray([0.5, 0.3, 0.15, 0.05])
    a = np.bincount(rng.choice(4, size=2000, p=p), minlength=4)
    b = np.bincount(rng.choice(4, size=2000, p=p), minlength=4)
    _stat, dof, pv = speculative.two_sample_chi2(a, b)
    assert dof >= 1 and pv > 0.01             # same law: not rejected
    c = np.bincount(rng.choice(4, size=2000, p=p[::-1]), minlength=4)
    _stat, _dof, pv_bad = speculative.two_sample_chi2(a, c)
    assert pv_bad < 1e-6                      # different law: rejected


# ---------- engine-level: sampled slots ride the spec tick ----------


def _cfg():
    return llama.LlamaConfig(
        vocab_size=258, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, max_position_embeddings=256,
        dtype=jnp.float32)


def _engine(params, draft_mode="ngram", **kw):
    e = eng.Engine(
        _cfg(), params, ByteTokenizer(),
        eng.EngineConfig(num_slots=2, max_context=128,
                         prefill_buckets=(16, 32, 64), prefill_chunk=64,
                         cache_dtype=jnp.float32, draft=draft_mode, **kw))
    e.start()
    return e


def _sampled_req(prompt: str, seed: int, n: int = 40, **pkw):
    return eng.GenRequest(
        prompt_ids=ByteTokenizer().encode(prompt),
        params=sampling.SamplingParamsHost(temperature=0.8, seed=seed, **pkw),
        max_new_tokens=n, ignore_eos=True)


PROMPT = "the cat sat on the mat. the cat sat on the mat. the cat sat"


def test_sampled_slot_joins_spec_and_splits_mode_counters():
    params = llama.init_params(_cfg(), jax.random.PRNGKey(0),
                               dtype=jnp.float32)
    e = _engine(params, decode_burst=8)
    try:
        assert e._spec_mode == "ngram"
        _, evs = e.generate_text(_sampled_req(PROMPT, seed=5))
        assert len(eng.event_ids(evs)) == 40
        st = e._spec_stats
        bm = st["by_mode"]["sampled"]
        assert st["dispatches"] > 0
        assert bm["rounds"] > 0                  # it actually speculated
        assert bm["tokens"] >= bm["rounds"]      # >= 1 token per round
        assert st["by_mode"]["greedy"]["rounds"] == 0
        sp = e.metrics()["spec"]
        assert sp["by_mode"]["sampled"]["rounds"] == bm["rounds"]
        assert sp["by_mode"]["sampled"]["accept_per_dispatch"] >= 1.0
        assert 0.0 <= sp["by_mode"]["sampled"]["acceptance_rate"] <= 1.0
        snap = e.state_snapshot()
        assert snap["spec"]["by_mode"]["sampled"]["rounds"] == bm["rounds"]
    finally:
        e.shutdown()


def test_spec_sampled_chi_square_distribution_parity():
    """THE distribution-preservation contract: over a fixed seed ladder,
    spec-on sampled token frequencies are chi-square-indistinguishable
    from plain (spec-off) sampling. Both runs are fully deterministic
    (fixed seeds), so this does not flake — it fails only if the
    acceptance/residual math biases the law."""
    params = llama.init_params(_cfg(), jax.random.PRNGKey(0),
                               dtype=jnp.float32)
    seeds = range(10)
    V = _cfg().vocab_size

    def run(draft_mode):
        e = _engine(params, draft_mode=draft_mode, decode_burst=8)
        counts = np.zeros((V,), np.int64)
        try:
            for s in seeds:
                _, evs = e.generate_text(
                    _sampled_req(PROMPT, seed=s, top_k=16))
                ids = eng.event_ids(evs)
                assert len(ids) == 40
                counts += np.bincount(ids, minlength=V)[:V]
            return counts, dict(e._spec_stats["by_mode"]["sampled"])
        finally:
            e.shutdown()

    on, bm = run("ngram")
    off, _ = run("0")
    assert bm["rounds"] > 0                      # spec path actually ran
    assert int(on.sum()) == int(off.sum()) == 10 * 40
    stat, dof, p = speculative.two_sample_chi2(on, off)
    assert dof >= 1
    assert p > 0.01, f"distribution drift: chi2={stat:.2f} dof={dof} p={p:.4f}"


def test_sampled_spec_preempt_resume_readmission_contract(
        tiny_llama, byte_tokenizer):
    """PR-10 resume contract for a SAMPLED spec slot: the resumed
    continuation is bit-for-bit what a fresh re-admission of
    (prompt + emitted-before-pause) computes on an identical spec-on
    engine with the same seed — the RNG key re-seeds from params.seed at
    (re-)admission and the per-round spec RNG schedule is deterministic,
    so resume-as-readmission stays exact even though sampled spec is
    only distribution-identical to spec-OFF decoding."""
    cfg, params = tiny_llama
    kw = dict(num_slots=1, max_context=96, prefill_buckets=(16, 64),
              decode_burst=4, kv_prefix_cache=False, kv_offload=False,
              cache_dtype=jnp.float32)

    def req(prompt_ids, n, priority="", seed=11):
        return eng.GenRequest(
            prompt_ids=list(prompt_ids),
            params=sampling.SamplingParamsHost(temperature=0.8, seed=seed),
            max_new_tokens=n, ignore_eos=True, priority=priority)

    prompt = byte_tokenizer.encode("resume me resume me resume me")
    e = eng.Engine(cfg, params, byte_tokenizer,
                   eng.EngineConfig(draft="ngram", **kw))
    e.start()
    try:
        assert e._spec_mode == "ngram"
        EVENTS.clear()
        req_low = req(prompt, 48, priority="low")
        out_low = e.submit(req_low)
        first = out_low.get(timeout=60.0)
        assert first.error is None
        out_high = e.submit(eng.GenRequest(
            prompt_ids=byte_tokenizer.encode("urgent"),
            params=sampling.SamplingParamsHost(temperature=0.0),
            max_new_tokens=8, ignore_eos=True, priority="high"))
        high_evs = []
        while True:
            ev = out_high.get(timeout=60.0)
            if ev is None:
                break
            high_evs.append(ev)
        low_evs = [first]
        while True:
            ev = out_low.get(timeout=60.0)
            if ev is None:
                break
            low_evs.append(ev)
        assert all(ev.error is None for ev in high_evs + low_evs)
        pre = [ev for ev in EVENTS.events()
               if ev["event"] == "preempt" and ev["rid"] == req_low.request_id]
        assert pre, "the high arrival should preempt the sampled spec slot"
        k = pre[0]["n_decoded"]
        low_ids = eng.event_ids(low_evs)
        assert len(low_ids) == 48 and 0 < k < 48
        assert e._spec_stats["by_mode"]["sampled"]["rounds"] > 0
        stats = e.metrics()["scheduler"]
        assert stats["preemptions"] >= 1 and stats["resumes"] >= 1
    finally:
        e.shutdown()

    # fresh spec-ON engine, re-admission of the identical token history
    ref_engine = eng.Engine(cfg, params, byte_tokenizer,
                            eng.EngineConfig(draft="ngram", **kw))
    ref_engine.start()
    try:
        ref = eng.event_ids(list(ref_engine.generate(
            req(prompt + low_ids[:k], 48 - k, priority="low"))))
    finally:
        ref_engine.shutdown()
    assert low_ids[k:] == ref


# ---------- exclusions that must hold by test ----------


def test_grammar_constrained_slot_never_enters_spec_rounds():
    from localai_tpu.functions.grammars import json_schema

    params = llama.init_params(_cfg(), jax.random.PRNGKey(0),
                               dtype=jnp.float32)
    grammar = json_schema.schema_to_grammar(
        {"type": "object", "properties": {"city": {"enum": ["sf", "nyc"]}},
         "required": ["city"]})
    e = _engine(params, decode_burst=8)
    try:
        assert e._spec_mode == "ngram"
        req = eng.GenRequest(
            prompt_ids=ByteTokenizer().encode("call: call: call:"),
            params=sampling.SamplingParamsHost(temperature=0.8, seed=5),
            max_new_tokens=32, grammar=grammar)
        _, evs = e.generate_text(req)
        assert eng.event_ids(evs)
        # the grammared slot was the ONLY traffic: no spec tick may run
        assert e._spec_stats["dispatches"] == 0
        assert e._spec_stats["rounds"] == 0
    finally:
        e.shutdown()


def test_lockstep_engine_resolves_spec_off():
    import types

    params = llama.init_params(_cfg(), jax.random.PRNGKey(0),
                               dtype=jnp.float32)
    bus = types.SimpleNamespace(send=lambda *a, **k: None,
                                close=lambda: None)
    e = eng.Engine(_cfg(), params, ByteTokenizer(),
                   eng.EngineConfig(num_slots=2, max_context=128,
                                    prefill_buckets=(16, 32, 64),
                                    cache_dtype=jnp.float32, draft="ngram"),
                   bus=bus)
    e.start()
    try:
        # lockstep dispatches are not in the follower descriptor set:
        # the mode resolver forces spec OFF even with draft requested
        assert e._spec_mode == "off"
        _, evs = e.generate_text(_sampled_req(PROMPT, seed=5, n=16))
        assert len(eng.event_ids(evs)) == 16
        assert e._spec_stats["dispatches"] == 0
    finally:
        e.shutdown()
