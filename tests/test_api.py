"""API-level integration tests: full HTTP app + fake backend over a socket.

Mirrors the reference's app_test.go (boots startup + HTTP on a port per
suite, drives it with a real client) but hermetic via the fake backend.
"""

import asyncio
import json
import threading
import time

import httpx
import pytest

from localai_tpu.api.app import build_app, run_app
from localai_tpu.backend.fake import FakeServicer
from localai_tpu.capabilities import Capabilities
from localai_tpu.config.app_config import AppConfig
from localai_tpu.config.model_config import ModelConfig
from localai_tpu.modelmgr.loader import ModelLoader
from localai_tpu.modelmgr.process import free_port


class ServerHandle:
    def __init__(self, port, loader, base):
        self.port = port
        self.loader = loader
        self.base = base


@pytest.fixture(scope="module")
def server():
    port = free_port()
    app_config = AppConfig(models_path="/tmp/localai-test-models",
                           address=f"127.0.0.1:{port}")
    loader = ModelLoader(health_attempts=100, health_interval_s=0.1)
    loader.register_embedded("fake", FakeServicer)
    loader.register_embedded("local-store", FakeServicer)
    configs = {
        "tiny": ModelConfig(name="tiny", backend="fake", model="tiny"),
        "embedder": ModelConfig(name="embedder", backend="fake", model="emb",
                                embeddings=True),
    }
    caps = Capabilities(app_config, loader, configs)
    app = build_app(caps, app_config)

    loop = asyncio.new_event_loop()
    started = threading.Event()
    runner_box = {}

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            runner_box["runner"] = await run_app(app, app_config.address)
            started.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(10)
    handle = ServerHandle(port, loader, f"http://127.0.0.1:{port}")
    yield handle
    loop.call_soon_threadsafe(loop.stop)
    loader.stop_all()


def test_healthz_and_version(server):
    assert httpx.get(f"{server.base}/healthz").status_code == 200
    v = httpx.get(f"{server.base}/version").json()
    assert "version" in v


def test_list_models(server):
    r = httpx.get(f"{server.base}/v1/models").json()
    names = {m["id"] for m in r["data"]}
    assert {"tiny", "embedder"} <= names


def test_chat_completion_nonstream(server):
    r = httpx.post(f"{server.base}/v1/chat/completions", json={
        "model": "tiny",
        "messages": [{"role": "user", "content": "hello there general"}],
    }, timeout=60)
    assert r.status_code == 200, r.text
    body = r.json()
    assert body["object"] == "chat.completion"
    content = body["choices"][0]["message"]["content"]
    assert "hello" in content  # fake echoes the prompt words
    assert body["usage"]["total_tokens"] > 0


def test_media_parts_rejected_loudly(server):
    """r5 (VERDICT r4 #6): audio parts and image/video parts on a model
    without a vision projector return 400 — never a silent drop."""
    base = f"{server.base}/v1/chat/completions"
    r = httpx.post(base, json={
        "model": "tiny",
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "what does this say"},
            {"type": "input_audio", "input_audio": {"data": "aGk="}}]}],
    }, timeout=60)
    assert r.status_code == 400, r.text
    assert "audio" in r.json()["error"]["message"]
    tiny_png = ("iVBORw0KGgoAAAANSUhEUgAAAAEAAAABCAYAAAAfFcSJAAAADUlEQVR4"
                "2mP8z8BQDwAEhQGAhKmMIQAAAABJRU5ErkJggg==")
    r2 = httpx.post(base, json={
        "model": "tiny",
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "describe"},
            {"type": "image_url",
             "image_url": {"url": f"data:image/png;base64,{tiny_png}"}}]}],
    }, timeout=60)
    assert r2.status_code == 400, r2.text
    assert "mmproj" in r2.json()["error"]["message"]
    r3 = httpx.post(base, json={
        "model": "tiny",
        "messages": [{"role": "user", "content": [
            {"type": "text", "text": "describe"},
            {"type": "video_url",
             "video_url": {"url": f"data:video/mp4;base64,{tiny_png}"}}]}],
    }, timeout=60)
    assert r3.status_code == 400, r3.text


def test_chat_completion_stream_sse(server):
    with httpx.stream("POST", f"{server.base}/v1/chat/completions", json={
        "model": "tiny", "stream": True,
        "messages": [{"role": "user", "content": "one two three"}],
    }, timeout=60) as r:
        assert r.status_code == 200
        assert r.headers["content-type"].startswith("text/event-stream")
        events = []
        for line in r.iter_lines():
            if line.startswith("data: "):
                events.append(line[len("data: "):])
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
    text = "".join(c["choices"][0]["delta"].get("content", "") for c in chunks)
    assert "one" in text and "three" in text
    assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
    assert "usage" in chunks[-1]


def test_completions_endpoint(server):
    r = httpx.post(f"{server.base}/v1/completions", json={
        "model": "tiny", "prompt": "alpha beta gamma",
    }, timeout=60)
    assert r.status_code == 200
    assert "alpha" in r.json()["choices"][0]["text"]


def test_completions_multiple_prompts(server):
    r = httpx.post(f"{server.base}/v1/completions", json={
        "model": "tiny", "prompt": ["a b", "c d"],
    }, timeout=60)
    ch = r.json()["choices"]
    assert len(ch) == 2
    assert ch[0]["index"] == 0 and ch[1]["index"] == 1


def test_edits_endpoint(server):
    r = httpx.post(f"{server.base}/v1/edits", json={
        "model": "tiny", "instruction": "fix", "input": "teh cat",
    }, timeout=60)
    assert r.status_code == 200
    assert r.json()["object"] == "edit"


def test_embeddings_endpoint(server):
    r = httpx.post(f"{server.base}/v1/embeddings", json={
        "model": "embedder", "input": ["hello", "world"],
    }, timeout=60)
    data = r.json()["data"]
    assert len(data) == 2
    assert len(data[0]["embedding"]) == 16
    assert data[0]["embedding"] != data[1]["embedding"]


def test_tokenize_endpoint(server):
    r = httpx.post(f"{server.base}/v1/tokenize", json={
        "model": "tiny", "content": "a b c d",
    }, timeout=60)
    assert len(r.json()["tokens"]) == 4


def test_rerank_endpoint(server):
    r = httpx.post(f"{server.base}/v1/rerank", json={
        "model": "tiny", "query": "apple pie",
        "documents": ["banana bread", "apple pie recipe", "car manual"],
        "top_n": 2,
    }, timeout=60)
    results = r.json()["results"]
    assert len(results) == 2
    assert results[0]["index"] == 1  # best match


def test_tts_endpoint(server):
    r = httpx.post(f"{server.base}/tts", json={
        "model": "tiny", "input": "hello",
    }, timeout=60)
    assert r.status_code == 200
    assert r.headers["content-type"] == "audio/wav"
    assert r.content[:4] == b"RIFF"


def test_stores_roundtrip(server):
    httpx.post(f"{server.base}/stores/set", json={
        "keys": [[1.0, 0.0], [0.0, 1.0]], "values": ["a", "b"],
    }, timeout=60)
    found = httpx.post(f"{server.base}/stores/find", json={
        "key": [0.9, 0.1], "topk": 1,
    }, timeout=60).json()
    assert found["values"] == ["a"]


def test_metrics_endpoint(server):
    r = httpx.get(f"{server.base}/metrics")
    assert "localai_api_call" in r.text


def test_debug_trace_endpoint(server):
    """/debug/trace merges per-model chrome traces (fake backend returns
    a minimal one) into a single perfetto-loadable document."""
    # force-load a model: traces come only from loaded backends
    httpx.post(f"{server.base}/v1/completions", json={
        "model": "tiny", "prompt": "warm up", "max_tokens": 2,
    }, timeout=60)
    r = httpx.get(f"{server.base}/debug/trace", timeout=30)
    assert r.status_code == 200
    doc = r.json()
    assert doc["displayTimeUnit"] == "ms"
    ev = doc["traceEvents"]
    # process_name metadata rewritten to localai-engine:<model>, one pid
    # per loaded model
    procs = {e["args"]["name"]: e["pid"] for e in ev
             if e.get("name") == "process_name"}
    assert any(n.startswith("localai-engine:") for n in procs)
    assert len(set(procs.values())) == len(procs)
    xs = [e for e in ev if e.get("ph") == "X"]
    assert xs and all("ts" in e and "dur" in e for e in xs)


def test_client_sdk(server):
    """The Python client SDK (reference parity: core/clients/store.go)."""
    from localai_tpu.client import Client

    with Client(server.base) as c:
        assert c.health()
        assert "tiny" in c.models()
        c.stores_set(keys=[[1.0, 0.0], [0.0, 1.0]], values=["a", "b"])
        keys, values, sims = c.stores_find(key=[0.95, 0.05], topk=1)
        assert values == ["a"] and len(sims) == 1
        got_k, got_v = c.stores_get(keys=[[0.0, 1.0]])
        assert got_v == ["b"]
        c.stores_delete(keys=[[0.0, 1.0]])
        _, got_v = c.stores_get(keys=[[0.0, 1.0]])
        assert got_v == []
        out = c.chat("tiny", [{"role": "user", "content": "hello"}],
                     max_tokens=8)
        assert isinstance(out, str) and out
        stream = "".join(c.chat_stream(
            "tiny", [{"role": "user", "content": "hello"}], max_tokens=8))
        assert stream
        embs = c.embeddings("embedder", ["x", "y"])
        assert len(embs) == 2


def test_system_endpoint(server):
    r = httpx.get(f"{server.base}/system").json()
    assert "devices" in r


def test_backend_monitor_and_shutdown(server):
    r = httpx.post(f"{server.base}/backend/monitor", json={"model": "tiny"}, timeout=60)
    assert r.status_code == 200
    assert r.json()["state"] == "READY"
    r = httpx.post(f"{server.base}/backend/shutdown", json={"model": "tiny"}, timeout=60)
    assert r.status_code == 200
    assert "tiny" not in server.loader.list_loaded()


def test_unknown_model_404s_cleanly(server):
    r = httpx.post(f"{server.base}/v1/chat/completions", json={
        "model": "definitely-not-a-model",
        "messages": [{"role": "user", "content": "x"}],
    }, timeout=120)
    # the backend aborts the load UNAVAILABLE (model fetch failed), which
    # the lifecycle error taxonomy renders as a retryable 503 envelope;
    # a backend without that mapping still 500s — either way a clean
    # JSON error, never a raw traceback
    assert r.status_code in (500, 503)
    assert "error" in r.json()


def test_bad_json_400(server):
    r = httpx.post(f"{server.base}/v1/chat/completions",
                   content=b"{not json", headers={"Content-Type": "application/json"})
    assert r.status_code == 400


def test_missing_messages_400(server):
    r = httpx.post(f"{server.base}/v1/chat/completions", json={"model": "tiny"})
    assert r.status_code == 400


def test_elevenlabs_tts_compat(server):
    r = httpx.post(f"{server.base}/v1/text-to-speech/voice123", json={
        "model_id": "tiny", "text": "hello",
    }, timeout=60)
    assert r.status_code == 200
    assert r.content[:4] == b"RIFF"


def test_swagger_lists_every_route(server):
    """/swagger serves an OpenAPI doc derived from the LIVE route table
    (reference: swagger/docs.go at /swagger/*)."""
    client = httpx.Client(base_url=server.base, timeout=30)
    r = client.get("/swagger/index.json")
    assert r.status_code == 200
    spec = r.json()
    assert spec["openapi"].startswith("3.")
    paths = spec["paths"]
    for must in ("/v1/chat/completions", "/v1/models", "/v1/embeddings",
                 "/tts", "/v1/files", "/metrics"):
        assert must in paths, must
    assert "post" in paths["/v1/chat/completions"]
    # HTML browser works and is auth-exempt
    r = client.get("/swagger")
    assert r.status_code == 200 and "LocalAI TPU API" in r.text
