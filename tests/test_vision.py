"""Multimodal (LLaVA-style) vision path: encoder, injection, chat e2e.

Reference semantics: CLIP embeddings injected at [img-N] placeholder
positions during prefill (grpc-server.cpp:1157-1180,1425-1440).
"""

import base64
import io
import os

import jax
import numpy as np
import pytest

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.models import vision


def _png_bytes(color):
    from PIL import Image

    im = Image.new("RGB", (20, 20), color)
    buf = io.BytesIO()
    im.save(buf, format="PNG")
    return buf.getvalue()


TINY_VCFG = vision.VisionConfig(
    image_size=16, patch_size=4, hidden_size=32, intermediate_size=64,
    num_layers=1, num_heads=2, proj_dim=64)


def test_vision_encoder_shapes_and_sensitivity():
    params = vision.init_params(TINY_VCFG, jax.random.PRNGKey(0))
    red = vision.embed_image(params, TINY_VCFG, _png_bytes("red"))
    blue = vision.embed_image(params, TINY_VCFG, _png_bytes("blue"))
    assert red.shape == (TINY_VCFG.num_patches, 64)
    assert np.all(np.isfinite(red))
    assert not np.allclose(red, blue)  # different images -> different embeds


def test_vision_save_load_roundtrip(tmp_path):
    params = vision.init_params(TINY_VCFG, jax.random.PRNGKey(1))
    vdir = str(tmp_path / "vis")
    vision.save_params(params, TINY_VCFG, vdir)
    cfg2 = vision.VisionConfig.from_json(os.path.join(vdir, "config.json"),
                                         proj_dim=64)
    params2 = vision.load_params(vdir, cfg2)
    a = vision.embed_image(params, TINY_VCFG, _png_bytes("green"))
    b = vision.embed_image(params2, cfg2, _png_bytes("green"))
    assert np.allclose(a, b, atol=1e-5)


def test_multimodal_chat_through_engine(tmp_path):
    """image_url-style chat: [img-0] placeholder + base64 image through the
    real runner/engine; the image content must influence generation."""
    os.environ["LOCALAI_PRECOMPILE"] = "0"
    import localai_tpu.backend.runner as runner
    from tests.tinymodel import write_tiny_checkpoint, write_tiny_tokenizer

    mdir = str(tmp_path / "llm")
    os.makedirs(mdir)
    write_tiny_checkpoint(mdir)
    write_tiny_tokenizer(mdir)
    vdir = str(tmp_path / "vis")
    vision.save_params(vision.init_params(TINY_VCFG, jax.random.PRNGKey(0)),
                       TINY_VCFG, vdir)

    sv = runner.EngineServicer()
    res = sv.LoadModel(pb.ModelOptions(
        model=mdir, mmproj=vdir, num_slots=2, context_size=128,
        prefill_buckets=[16, 64], mesh_tp=1, mesh_dp=1), None)
    assert res.success, res.message
    try:
        def ask(images, prompt):
            return sv.Predict(pb.PredictOptions(
                prompt=prompt, images=images, max_tokens=6, ignore_eos=True,
                temperature=0.0), None)

        b64_red = base64.b64encode(_png_bytes("red")).decode()
        b64_blue = base64.b64encode(_png_bytes("blue")).decode()
        r1 = ask([b64_red], "[img-0]\ndescribe")
        r2 = ask([b64_red], "[img-0]\ndescribe")
        r3 = ask([b64_blue], "[img-0]\ndescribe")
        assert r1.tokens == 6
        assert r1.message == r2.message          # deterministic greedy
        assert r1.message != r3.message          # image content matters
        # prompt accounting includes the image patch positions
        assert r1.prompt_tokens >= TINY_VCFG.num_patches
        # plain text still works with the vision tower loaded
        r4 = sv.Predict(pb.PredictOptions(
            prompt="hello", max_tokens=4, ignore_eos=True, temperature=0.0), None)
        assert r4.tokens == 4

        # r5 (VERDICT r4 #6): VIDEO parts are consumed — a GIF's frames
        # ride the same tower; different videos -> different generations
        def gif_b64(colors):
            from PIL import Image

            frames = [Image.new("RGB", (20, 20), c) for c in colors]
            buf = io.BytesIO()
            frames[0].save(buf, format="GIF", save_all=True,
                           append_images=frames[1:], duration=100)
            return base64.b64encode(buf.getvalue()).decode()

        def ask_vid(vid, prompt):
            return sv.Predict(pb.PredictOptions(
                prompt=prompt, videos=[vid], max_tokens=6, ignore_eos=True,
                temperature=0.0), None)

        v1 = ask_vid(gif_b64(["red", "green", "blue"]), "[vid-0]\nwhat")
        v2 = ask_vid(gif_b64(["red", "green", "blue"]), "[vid-0]\nwhat")
        v3 = ask_vid(gif_b64(["black", "white"]), "[vid-0]\nwhat")
        assert v1.tokens == 6
        assert v1.message == v2.message
        assert v1.message != v3.message  # video content matters
        # each sampled frame injects num_patches rows
        assert v1.prompt_tokens >= 3 * TINY_VCFG.num_patches
    finally:
        sv.engine.shutdown()


def test_media_parts_rejected_loudly(tmp_path):
    """The forbidden outcome is a silent drop: audio parts and media on a
    vision-less model must error at the backend boundary (the HTTP layer
    400s first; this is the gRPC backstop)."""
    os.environ["LOCALAI_PRECOMPILE"] = "0"
    import localai_tpu.backend.runner as runner
    from tests.tinymodel import write_tiny_checkpoint

    mdir = str(tmp_path / "llm")
    os.makedirs(mdir)
    write_tiny_checkpoint(mdir)
    sv = runner.EngineServicer()
    res = sv.LoadModel(pb.ModelOptions(
        model=mdir, num_slots=2, context_size=64,
        prefill_buckets=[16], mesh_tp=1, mesh_dp=1), None)
    assert res.success, res.message
    try:
        with pytest.raises(ValueError, match="audio content parts"):
            sv._build_request(pb.PredictOptions(
                prompt="x", audios=["aGk="], max_tokens=2))
        with pytest.raises(ValueError, match="vision-capable"):
            sv._build_request(pb.PredictOptions(
                prompt="x", images=["aGk="], max_tokens=2))
        with pytest.raises(ValueError, match="vision-capable"):
            sv._build_request(pb.PredictOptions(
                prompt="x", videos=["aGk="], max_tokens=2))
    finally:
        sv.engine.shutdown()


def test_undecodable_video_raises():
    with pytest.raises(ValueError, match="undecodable video"):
        vision.sample_video_frames(b"\x00\x00\x00\x18ftypmp42 not a real mp4")


def test_video_frame_sampling():
    from PIL import Image

    frames = [Image.new("RGB", (8, 8), (i * 20, 0, 0)) for i in range(10)]
    buf = io.BytesIO()
    frames[0].save(buf, format="GIF", save_all=True, append_images=frames[1:],
                   duration=50)
    out = vision.sample_video_frames(buf.getvalue(), n_frames=4)
    assert len(out) == 4
    # uniform coverage: first and last frames always included
    first = Image.open(io.BytesIO(out[0])).convert("RGB")
    assert first.getpixel((0, 0))[0] <= 30
