"""Model lifecycle tests against the fake backend (spawned + embedded)."""

import pytest

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.fake import FakeServicer
from localai_tpu.modelmgr.loader import ModelLoader


@pytest.fixture()
def loader():
    ml = ModelLoader(health_attempts=60, health_interval_s=0.2)
    yield ml
    ml.stop_all()


def test_embedded_backend_load_and_predict(loader):
    loader.register_embedded("fake", FakeServicer)
    lm = loader.backend_loader("fake", "m1", pb.ModelOptions(model="whatever"))
    assert lm.client.health()
    r = lm.client.predict(pb.PredictOptions(prompt="hello world"))
    assert r.message == b"hello world"
    assert r.finish_reason == "stop"


def test_spawned_backend_process(loader):
    lm = loader.backend_loader("fake", "m2", pb.ModelOptions(model="x"))
    assert lm.process is not None and lm.process.alive()
    chunks = list(lm.client.predict_stream(pb.PredictOptions(prompt="a b c")))
    assert b"".join(c.message for c in chunks) == b"a b c"
    assert chunks[-1].finish_reason == "stop"
    loader.shutdown_model("m2")
    assert loader.get("m2") is None


def test_load_failure_surfaces(loader):
    loader.register_embedded("fake", FakeServicer)
    with pytest.raises(RuntimeError, match="fake load failure"):
        loader.backend_loader("fake", "bad", pb.ModelOptions(model="fail-this"))


def test_model_reuse_same_client(loader):
    loader.register_embedded("fake", FakeServicer)
    a = loader.backend_loader("fake", "m3", pb.ModelOptions(model="x"))
    b = loader.backend_loader("fake", "m3", pb.ModelOptions(model="x"))
    assert a is b


def test_respawn_after_process_death(loader):
    lm = loader.backend_loader("fake", "m4", pb.ModelOptions(model="x"))
    lm.process.stop()
    lm2 = loader.backend_loader("fake", "m4", pb.ModelOptions(model="x"))
    assert lm2 is not lm
    assert lm2.client.health()


def test_greedy_loader_falls_through(loader):
    calls = []

    class Failing(FakeServicer):
        def LoadModel(self, request, context):
            calls.append("failing")
            return pb.Result(success=False, message="nope")

    loader.register_embedded("bad", Failing)
    loader.register_embedded("good", FakeServicer)
    lm = loader.greedy_loader("m5", pb.ModelOptions(model="x"), order=["bad", "good"])
    assert lm.backend_name == "good"
    assert calls == ["failing"]


def test_stores_roundtrip_via_contract(loader):
    loader.register_embedded("fake", FakeServicer)
    lm = loader.backend_loader("fake", "st", pb.ModelOptions(model="x"))
    lm.client.stores_set(pb.StoresSetOptions(
        keys=[pb.StoresKey(floats=[1.0, 0.0]), pb.StoresKey(floats=[0.0, 1.0])],
        values=[pb.StoresValue(bytes=b"a"), pb.StoresValue(bytes=b"b")],
    ))
    found = lm.client.stores_find(pb.StoresFindOptions(
        key=pb.StoresKey(floats=[1.0, 0.1]), top_k=1))
    assert found.values[0].bytes == b"a"
    assert found.similarities[0] > 0.9
