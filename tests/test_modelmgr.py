"""Model lifecycle tests against the fake backend (spawned + embedded)."""

import os
import signal
import socket
import time

import grpc
import pytest

from localai_tpu.backend import contract_pb2 as pb
from localai_tpu.backend.fake import FakeServicer
from localai_tpu.modelmgr import process as process_mod
from localai_tpu.modelmgr.loader import ModelLoader
from localai_tpu.modelmgr.watchdog import WatchDog
from localai_tpu.services.errors import CircuitOpenError
from localai_tpu.services.faults import FAULTS


@pytest.fixture()
def loader():
    ml = ModelLoader(health_attempts=60, health_interval_s=0.2)
    yield ml
    ml.stop_all()


def test_embedded_backend_load_and_predict(loader):
    loader.register_embedded("fake", FakeServicer)
    lm = loader.backend_loader("fake", "m1", pb.ModelOptions(model="whatever"))
    assert lm.client.health()
    r = lm.client.predict(pb.PredictOptions(prompt="hello world"))
    assert r.message == b"hello world"
    assert r.finish_reason == "stop"


def test_spawned_backend_process(loader):
    lm = loader.backend_loader("fake", "m2", pb.ModelOptions(model="x"))
    assert lm.process is not None and lm.process.alive()
    chunks = list(lm.client.predict_stream(pb.PredictOptions(prompt="a b c")))
    assert b"".join(c.message for c in chunks) == b"a b c"
    assert chunks[-1].finish_reason == "stop"
    loader.shutdown_model("m2")
    assert loader.get("m2") is None


def test_load_failure_surfaces(loader):
    loader.register_embedded("fake", FakeServicer)
    with pytest.raises(RuntimeError, match="fake load failure"):
        loader.backend_loader("fake", "bad", pb.ModelOptions(model="fail-this"))


def test_model_reuse_same_client(loader):
    loader.register_embedded("fake", FakeServicer)
    a = loader.backend_loader("fake", "m3", pb.ModelOptions(model="x"))
    b = loader.backend_loader("fake", "m3", pb.ModelOptions(model="x"))
    assert a is b


def test_respawn_after_process_death(loader):
    lm = loader.backend_loader("fake", "m4", pb.ModelOptions(model="x"))
    lm.process.stop()
    lm2 = loader.backend_loader("fake", "m4", pb.ModelOptions(model="x"))
    assert lm2 is not lm
    assert lm2.client.health()


def test_greedy_loader_falls_through(loader):
    calls = []

    class Failing(FakeServicer):
        def LoadModel(self, request, context):
            calls.append("failing")
            return pb.Result(success=False, message="nope")

    loader.register_embedded("bad", Failing)
    loader.register_embedded("good", FakeServicer)
    lm = loader.greedy_loader("m5", pb.ModelOptions(model="x"), order=["bad", "good"])
    assert lm.backend_name == "good"
    assert calls == ["failing"]


def test_stores_roundtrip_via_contract(loader):
    loader.register_embedded("fake", FakeServicer)
    lm = loader.backend_loader("fake", "st", pb.ModelOptions(model="x"))
    lm.client.stores_set(pb.StoresSetOptions(
        keys=[pb.StoresKey(floats=[1.0, 0.0]), pb.StoresKey(floats=[0.0, 1.0])],
        values=[pb.StoresValue(bytes=b"a"), pb.StoresValue(bytes=b"b")],
    ))
    found = lm.client.stores_find(pb.StoresFindOptions(
        key=pb.StoresKey(floats=[1.0, 0.1]), top_k=1))
    assert found.values[0].bytes == b"a"
    assert found.similarities[0] > 0.9


# ---- fault-tolerant lifecycle (ISSUE 7) ----


def _poll(predicate, timeout_s=10.0, step_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(step_s)
    return predicate()


def test_watchdog_kills_busy_too_long(loader):
    loader.register_embedded("fake", FakeServicer)
    wd = WatchDog(loader, busy_timeout_s=0.05, check_busy=True,
                  sweep_interval_s=0.05)
    loader.watchdog = wd
    wd.start()
    try:
        lm = loader.backend_loader("fake", "wd1", pb.ModelOptions(model="x"))
        lm.mark_busy()  # never marked idle: a wedged request
        assert _poll(lambda: loader.get("wd1") is None)
    finally:
        wd.shutdown()


def test_watchdog_releases_idle(loader):
    loader.register_embedded("fake", FakeServicer)
    wd = WatchDog(loader, idle_timeout_s=0.05, check_idle=True,
                  sweep_interval_s=0.05)
    loader.watchdog = wd
    wd.start()
    try:
        loader.backend_loader("fake", "wd2", pb.ModelOptions(model="x"))
        assert _poll(lambda: loader.get("wd2") is None)
    finally:
        wd.shutdown()


def test_health_probe_grace_keeps_live_backend(loader):
    """A transiently failing probe must NOT kill a live backend: 3
    strikes spread over 30 s are required before a respawn."""

    class Flaky(FakeServicer):
        fail = False

        def Health(self, request, context):
            if Flaky.fail:
                context.abort(grpc.StatusCode.UNAVAILABLE, "probe fail")
            return super().Health(request, context)

    Flaky.fail = False
    loader.register_embedded("flaky", Flaky)
    lm = loader.backend_loader("flaky", "m6", pb.ModelOptions(model="x"))
    Flaky.fail = True
    a = loader.backend_loader("flaky", "m6", pb.ModelOptions(model="x"))
    b = loader.backend_loader("flaky", "m6", pb.ModelOptions(model="x"))
    assert a is lm and b is lm
    assert lm.health_fails >= 2
    Flaky.fail = False
    c = loader.backend_loader("flaky", "m6", pb.ModelOptions(model="x"))
    assert c is lm and lm.health_fails == 0


def test_supervisor_respawns_killed_backend():
    ml = ModelLoader(health_attempts=60, health_interval_s=0.2,
                     respawn_backoff_base_s=0.05,
                     respawn_backoff_cap_s=0.2)
    try:
        lm = ml.backend_loader("fake", "sup1", pb.ModelOptions(model="x"))
        assert lm.process is not None and lm.process.alive()
        os.kill(lm.process.proc.pid, signal.SIGKILL)

        def replaced():
            cur = ml.get("sup1")
            return (cur is not None and cur is not lm
                    and cur.client.health(timeout=1.0))

        assert _poll(replaced, timeout_s=30.0, step_s=0.05)
        assert ml.stats()["sup1"]["respawns"] >= 1
        assert ml.stats()["sup1"]["breaker"]["state"] == "closed"
    finally:
        ml.stop_all()


def test_circuit_breaker_opens_then_recovers():
    ml = ModelLoader(breaker_threshold=2, breaker_cooldown_s=0.3)
    ml.register_embedded("fake", FakeServicer)
    try:
        for _ in range(2):
            with pytest.raises(RuntimeError, match="fake load failure"):
                ml.backend_loader("fake", "cb1",
                                  pb.ModelOptions(model="fail-this"))
        t0 = time.monotonic()
        with pytest.raises(CircuitOpenError) as ei:
            ml.backend_loader("fake", "cb1",
                              pb.ModelOptions(model="fail-this"))
        assert time.monotonic() - t0 < 0.1  # fast-fail: no spawn attempt
        assert ei.value.status == 503
        assert ei.value.retryable
        assert ei.value.detail["breaker"]["state"] == "open"
        assert ei.value.retry_after_s >= 1.0
        assert ml.stats()["cb1"]["circuit_state"] == 1
        time.sleep(0.35)
        # half-open probe with a now-working config closes the breaker
        lm = ml.backend_loader("fake", "cb1", pb.ModelOptions(model="ok"))
        assert lm.client.health()
        assert ml.stats()["cb1"]["breaker"]["state"] == "closed"
    finally:
        ml.stop_all()


def test_spawn_retries_lost_bind_race(monkeypatch):
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    stolen = blocker.getsockname()[1]
    real_free_port = process_mod.free_port
    ports = [stolen]

    def rigged_free_port():
        return ports.pop(0) if ports else real_free_port()

    monkeypatch.setattr(process_mod, "free_port", rigged_free_port)
    bp = process_mod.spawn_python_backend(
        "localai_tpu.backend.fake", name="race", bind_race_wait_s=15.0)
    try:
        assert bp.addr != f"127.0.0.1:{stolen}"
        assert _poll(bp.started.is_set, timeout_s=20.0, step_s=0.05)
    finally:
        bp.stop(grace_s=0.0)
        blocker.close()


def test_unary_retry_absorbs_injected_unavailable(loader):
    loader.register_embedded("fake", FakeServicer)
    lm = loader.backend_loader("fake", "rt1", pb.ModelOptions(model="x"))
    FAULTS.arm("rpc_unavailable", "Embedding", count=2)
    try:
        res = lm.client.embedding(pb.PredictOptions(prompt="hi"))
        assert list(res.embeddings)
        assert FAULTS.fired.get("rpc_unavailable") == 2
    finally:
        FAULTS.reset()
